"""LRU cache of baked MPI scenes with a byte budget.

Serving splits the render pipeline the way FastNeRF splits cache from
sample: *baking* a scene — producing its MPI and placing it on device — is
expensive and per-scene cacheable, while *serving* a pose is cheap and
batches well. This module holds the baked side: device-resident
``BakedScene``s keyed by scene id, least-recently-used eviction once the
byte budget is exceeded, and hit/miss/eviction counters that feed
``serve/metrics.py`` (cache hit rate is a first-class serving metric — a
thrashing scene cache turns every request into a bake).
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class BakedScene:
  """One servable scene (or tile crop), resident on device.

  ``tgt_intrinsics``/``out_hw`` are the tile-cropped-source fields
  (serve/tiles.py): the MPI may be a crop of the scene with the crop
  correction folded into ``intrinsics``, while the rendered frame keeps
  the original camera (``tgt_intrinsics``) and full dims (``out_hw``).
  ``None`` (every whole-scene bake) keeps the engine's historical call
  shape bit-exactly.
  """

  scene_id: str
  rgba_layers: jnp.ndarray  # [H, W, P, 4], planes back-to-front
  depths: jnp.ndarray       # [P], descending (see camera.inv_depths)
  intrinsics: jnp.ndarray   # [3, 3]
  nbytes: int
  tgt_intrinsics: jnp.ndarray | None = None
  out_hw: tuple | None = None


def bake_scene(scene_id, rgba_layers, depths, intrinsics,
               device=None) -> BakedScene:
  """Place host arrays on device as one servable scene (f32).

  Blocks until the transfer lands so the bake cost is paid here, inside
  the cache-miss accounting, not silently inside the first render.
  ``device`` pins the placement (the degraded-mode CPU fallback bakes
  onto its own devices, not the defaulted primary); None keeps JAX's
  default placement.
  """
  rgba = np.asarray(rgba_layers, np.float32)
  d = np.asarray(depths, np.float32)
  k = np.asarray(intrinsics, np.float32)
  if rgba.ndim != 4 or rgba.shape[-1] != 4:
    raise ValueError(f"rgba_layers must be [H, W, P, 4], got {rgba.shape}")
  if d.shape != (rgba.shape[2],):
    raise ValueError(
        f"depths {d.shape} must be [P] matching rgba planes {rgba.shape[2]}")
  if k.shape != (3, 3):
    raise ValueError(f"intrinsics must be [3, 3], got {k.shape}")
  if device is not None:
    # Straight host -> target transfer. Routing through jnp.asarray first
    # would stage the arrays on the DEFAULT backend — the device whose
    # outage is the very reason a fallback bake is happening.
    rgba, d, k = (jax.device_put(a, device) for a in (rgba, d, k))
  else:
    rgba, d, k = jnp.asarray(rgba), jnp.asarray(d), jnp.asarray(k)
  jax.block_until_ready(rgba)
  nbytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
               for a in (rgba, d, k))
  return BakedScene(str(scene_id), rgba, d, k, nbytes)


class SceneCache:
  """Thread-safe LRU over ``BakedScene`` with byte-budget eviction.

  Eviction keeps at least the most recent scene even when it alone
  exceeds the budget — a cache that refuses every scene cannot serve.
  """

  def __init__(self, byte_budget: int = 2 << 30):
    if byte_budget <= 0:
      raise ValueError(f"byte_budget must be positive, got {byte_budget}")
    self.byte_budget = int(byte_budget)
    self._scenes: OrderedDict[str, BakedScene] = OrderedDict()
    self._bytes = 0
    self._lock = threading.Lock()
    self.hits = 0
    self.misses = 0
    self.evictions = 0
    self.invalidations = 0

  def get(self, scene_id: str) -> BakedScene | None:
    with self._lock:
      scene = self._scenes.get(scene_id)
      if scene is None:
        self.misses += 1
        return None
      self._scenes.move_to_end(scene_id)
      self.hits += 1
      return scene

  def put(self, scene: BakedScene) -> None:
    with self._lock:
      old = self._scenes.pop(scene.scene_id, None)
      if old is not None:
        self._bytes -= old.nbytes
      self._scenes[scene.scene_id] = scene
      self._bytes += scene.nbytes
      self._evict_locked()

  def get_or_bake(self, scene_id: str, bake) -> BakedScene:
    """Cached scene, or ``bake()``'s result inserted (miss accounted)."""
    scene = self.get(scene_id)
    if scene is not None:
      return scene
    scene = bake()
    self.put(scene)
    return scene

  def invalidate(self, scene_id: str) -> bool:
    """Drop one baked scene (live checkpoint reload: the scene's host
    data changed, so the next request must re-bake). Requests already
    holding the old ``BakedScene`` finish on it — device buffers free
    once the last reference drops. Returns whether the id was resident."""
    with self._lock:
      scene = self._scenes.pop(scene_id, None)
      if scene is None:
        return False
      self._bytes -= scene.nbytes
      self.invalidations += 1
      return True

  def invalidate_prefix(self, prefix: str) -> int:
    """Drop every entry whose key starts with ``prefix`` (a tiled
    scene's whole tile set — grid-changing reloads retire every tile id
    the old grid minted). Returns the number of entries dropped."""
    with self._lock:
      keys = [k for k in self._scenes if k.startswith(prefix)]
      for key in keys:
        self._bytes -= self._scenes.pop(key).nbytes
      self.invalidations += len(keys)
      return len(keys)

  def _evict_locked(self) -> None:
    while self._bytes > self.byte_budget and len(self._scenes) > 1:
      _, evicted = self._scenes.popitem(last=False)
      self._bytes -= evicted.nbytes
      self.evictions += 1

  def __contains__(self, scene_id: str) -> bool:
    with self._lock:
      return scene_id in self._scenes

  def __len__(self) -> int:
    with self._lock:
      return len(self._scenes)

  def stats(self) -> dict:
    with self._lock:
      lookups = self.hits + self.misses
      return {
          "scenes": len(self._scenes),
          "bytes": self._bytes,
          "byte_budget": self.byte_budget,
          "hits": self.hits,
          "misses": self.misses,
          "evictions": self.evictions,
          "invalidations": self.invalidations,
          "hit_rate": (self.hits / lookups) if lookups else None,
      }
