"""Serving front ends: in-process service + stdlib HTTP server.

``RenderService`` wires cache + engine + scheduler + metrics into one
object with a pure-Python API — tests and ``bench/serve_load.py`` drive it
directly, no sockets. ``make_http_server`` wraps a service in a threaded
stdlib ``http.server`` front end:

  GET  /healthz -> {"status": "ok", "devices", "scenes", ...}
  GET  /stats   -> the metrics snapshot (latency percentiles, throughput,
                   batch-size histogram, queue depth, cache hit rate)
  GET  /metrics -> Prometheus text exposition of the same counters
                   (obs/prom.py; scrape with a stock Prometheus)
  GET  /debug/traces  -> recent + slowest-N finished request traces
                   (?id=<trace_id> returns just that id's records)
  GET  /debug/events  -> the bounded structured lifecycle event log
                   (breaker transitions, scene swaps, SLO alert edges;
                   ?kind= filters, ?recent=N bounds)
  GET  /debug/tsdb -> windowed history from the on-box time-series ring
                   (?family= selects one metric family, ?recent=S bounds
                   the window, ?points=N caps points per series; no
                   family lists the resident families; 503 unless built
                   with a tsdb config)
  GET  /debug/profile?seconds=N -> capture a device profile of live
                   traffic (409 while one is in flight; 503 unless the
                   service was built with a profile dir); a configured
                   profile hook receives the finished capture dir
  GET  /debug/attrib -> the resource-attribution ledger: per
                   (scene x class x brownout-level) cell device
                   phase-seconds, queue wait, bytes out, edge serves,
                   plus the conservation reconciliation (?top=K bounds
                   the cell list; 503 unless built with attrib)
  GET  /debug/incidents -> the incident-bundle ring index (?id=
                   fetches one full bundle; 503 unless built with an
                   incident dir)
  GET  /scenes  -> {"scenes": [...]} — the asset tier's discovery
                   endpoint (what a SceneFetcher sweeps)
  GET  /scene/{id}/manifest -> versioned JSON manifest (tile grid,
                   per-tile sha256 digests, depths, intrinsics); ETag =
                   scene digest, Cache-Control: no-cache (tiled only)
  GET  /scene/{id}/asset/{digest} -> immutable content-addressed bytes
                   (zlib'd raw-f32 tile or per-plane PNG); strong ETag,
                   Cache-Control: public, max-age=31536000, immutable
  GET  /scene/{id}/viewer -> the CSS-3D layer viewer HTML templated
                   against asset URLs (layers stream through the CDN
                   path, not inlined base64)
  POST /render  -> body {"scene_id": str, "pose": [[...4x4...]]} ->
                   {"scene_id", "shape", "dtype", "image_b64"} — raw
                   little-endian f32 pixels, base64 (shape [H, W, 3]).
                   Every response (success or error) carries an
                   ``X-Trace-Id`` header; with tracing enabled the id
                   resolves to a span tree at ``/debug/traces``. A valid
                   inbound W3C ``traceparent`` header's trace-id is
                   honored as the id, so a fronting proxy can stitch
                   distributed traces.
  POST /session -> pose-in / frame-out streaming session (503 unless
                   built with ``session=``): JSON hello body
                   {"scene_id": str} opens the session, then the same
                   socket switches to length-prefixed binary frames —
                   poses in, rendered frames out (serve/session/). The
                   response streams with no Content-Length; 503 +
                   Retry-After when the session bound is reached.

Scenes register host-side (``add_scene``) and bake lazily through the
LRU cache on first request, so cache hit/miss accounting reflects real
traffic. 404 for unknown scenes, 400 for malformed requests, 503 when
the scheduler sheds load (queue at ``max_queue``) or the circuit breaker
is open (with a Retry-After header); handler threads block on the
scheduler future, so HTTP concurrency turns into micro-batch coalescing
on the device. ``Accept: application/octet-stream`` on ``/render``
returns the raw little-endian f32 pixels with shape/dtype response
headers (half the payload of the default base64 JSON).

``/healthz`` is a three-state health machine, not a liveness ping:
``ok`` (breaker closed, dispatcher running), ``degraded`` (breaker
open/half-open — requests fast-fail or ride the CPU fallback; the
``reason`` field says which), ``unhealthy`` (service closed or the
dispatcher thread died).
"""

from __future__ import annotations

import base64
import dataclasses
import functools
import hashlib
import json
import math
import re
import socket
import threading
import time
import urllib.parse
import zlib
from collections import OrderedDict as _OrderedDict
from concurrent.futures import TimeoutError as FuturesTimeoutError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp
import numpy as np

from mpi_vision_tpu.core import camera
from mpi_vision_tpu.core.camera import inv_depths
from mpi_vision_tpu.core.sampling import Convention  # noqa: F401 - API re-export
from mpi_vision_tpu.obs import attrib as attrib_mod
from mpi_vision_tpu.obs import incident as incident_mod
from mpi_vision_tpu.obs import prom
from mpi_vision_tpu.obs import ship as ship_mod
from mpi_vision_tpu.obs import tsdb as tsdb_mod
from mpi_vision_tpu.obs.events import EventLog
from mpi_vision_tpu.obs.profile import DeviceProfiler, ProfileBusyError
from mpi_vision_tpu.obs.slo import SloConfig, SloTracker
from mpi_vision_tpu.obs.trace import (
    NULL_TRACE,
    NULL_TRACER,
    Tracer,
    new_trace_id,
)
from mpi_vision_tpu.serve import brownout as brownout_mod
from mpi_vision_tpu.serve import cache as cache_mod
from mpi_vision_tpu.serve import tiles as tiles_mod
from mpi_vision_tpu.serve.assets import store as assets_mod
from mpi_vision_tpu.serve.edge import EdgeConfig, EdgeFrameCache, warp_frame
from mpi_vision_tpu.serve.edge.lattice import pose_error
from mpi_vision_tpu.serve.engine import RenderEngine, upsample_nearest
from mpi_vision_tpu.serve.metrics import ServeMetrics
from mpi_vision_tpu.serve.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    ResilienceConfig,
    ResilientExecutor,
    TransientDeviceError,
)
from mpi_vision_tpu.serve.scheduler import MicroBatcher, QueueFullError
from mpi_vision_tpu.serve.session import manager as session_mod
from mpi_vision_tpu.serve.session import protocol as session_protocol


def synthetic_scene(scene_id: str, height: int = 256, width: int = 256,
                    planes: int = 16, seed: int = 0):
  """A procedural (rgba_layers, depths, intrinsics) scene for demos/load.

  Smooth per-plane color gradients with sparse alpha, seeded by
  ``(seed, scene_id)`` so distinct ids render distinctly even at one
  seed — enough structure that renders differ across poses and scenes,
  hermetic enough for CI.
  """
  rng = np.random.default_rng([seed, zlib.crc32(str(scene_id).encode())])
  yy, xx = np.meshgrid(np.linspace(0, 1, height, dtype=np.float32),
                       np.linspace(0, 1, width, dtype=np.float32),
                       indexing="ij")
  layers = np.empty((height, width, planes, 4), np.float32)
  for p in range(planes):
    phase = rng.uniform(0, 2 * np.pi, 3)
    freq = rng.uniform(1.0, 4.0, 3)
    for c in range(3):
      layers[..., p, c] = 0.5 + 0.5 * np.sin(
          freq[c] * (xx + yy) * np.pi + phase[c])
    alpha = 0.5 + 0.5 * np.sin(freq[0] * xx * 7 + phase[0] + p)
    layers[..., p, 3] = np.clip(alpha - 0.3, 0.0, 1.0)
  depths = np.asarray(inv_depths(1.0, 100.0, planes), np.float32)
  fx = 0.5 * width
  k = np.asarray(camera.intrinsics_matrix(fx, fx, width / 2.0, height / 2.0),
                 np.float32)
  return layers, depths, k


def synthetic_tiled_scene(scene_id: str, height: int = 512,
                          width: int = 512, planes: int = 32,
                          regions: int = 3, band: int | None = None,
                          seed: int = 0):
  """A depth-stratified procedural scene — the tiled-serving workload.

  ``synthetic_scene`` content, but each of ``regions x regions`` spatial
  blocks keeps alpha only on a contiguous band of ``band`` planes — the
  structure Tiled MPI exploits: real scenes put each image region's
  content in a narrow depth range, so a frustum touching few tiles
  needs few planes. The band is a left-to-right depth STAIRCASE (column
  0 holds the nearest slab, the last column the farthest — a room wall
  receding to one side), so a pan that excludes some columns excludes
  their depth slabs too. Plane RGB is left intact everywhere (the
  farthest plane composites unconditionally); only alpha is masked,
  which is exactly the property the plane cull keys on.
  """
  layers, depths, k = synthetic_scene(scene_id, height, width, planes,
                                      seed=seed)
  if band is None:
    band = max(planes // max(regions, 1), 1)
  ry = -(-height // regions)
  rx = -(-width // regions)
  span = max(planes - band, 0)
  for i in range(regions):
    for j in range(regions):
      lo = round(j * span / max(regions - 1, 1))
      keep = set(range(lo, min(lo + band, planes)))
      drop = [p for p in range(planes) if p not in keep]
      layers[i * ry:(i + 1) * ry, j * rx:(j + 1) * rx][..., drop, 3] = 0.0
  return layers, depths, k


class RenderService:
  """The in-process serving API (the HTTP layer is a thin shell on this).

  Args:
    cache_bytes: scene-cache byte budget.
    max_batch / max_wait_ms: micro-batching knobs (scheduler.py).
    max_inflight: streaming-pipeline window (scheduler.py): concurrent
      flights whose h2d/compute/readback overlap and whose futures
      complete out of dispatch order. 1 = the legacy blocking dispatch
      (the A/B baseline in ``bench/serve_load.py``). The string
      ``"auto"`` turns on adaptive sizing: the window starts at 2 and
      grows while growing keeps improving the dispatch-gap metric,
      capped at ``max_inflight_cap``.
    max_inflight_cap: hard ceiling for ``max_inflight="auto"``.
    tile: tile edge in pixels (``serve/tiles.py``). None (default)
      serves monolithic scenes exactly as before. An int splits every
      registered scene into a fixed tile grid: requests render only the
      frustum-touched crop with content-free planes culled (bit-exact
      to the monolithic render when the frustum covers every tile), the
      baked cache holds/evicts/invalidates per tile, live reloads swap
      only tiles whose digests changed, and the edge frame cache drops
      only frames that depended on a changed tile.
    convention: coordinate convention for the engine (None keeps the
      engine default, the reference's REF_HOMOGRAPHY). Non-square tiled
      scenes (room-scale panoramas) should pass ``Convention.EXACT`` —
      the reference convention's axis swap is only benign on square
      frames, and the tile planner faithfully reproduces whichever
      convention the engine renders with.
    edge: the pose-quantized edge frame cache (``serve/edge/``): None
      (default) serves every request through the scheduler as before;
      an ``EdgeConfig`` caches finished frames per view cell, serves
      exact cell hits directly, warps near-misses off the nearest
      cached frame, and gives the HTTP layer strong ETags /
      ``If-None-Match`` -> 304 / ``Cache-Control`` (``render_edge``).
    method / use_mesh: renderer routing knobs (engine.py).
    resilience: retry/breaker/watchdog knobs (resilience.py); None turns
      the whole resilience layer off (raw PR-1 behavior).
    cpu_fallback: degraded-mode routing while the breaker is open —
      "auto" builds a CPU fallback engine exactly when the primary is
      not already CPU (the serving analogue of ``bench.py --allow-cpu``),
      "off" fast-fails instead; "on" forces one.
    fallback_engine: explicit fallback engine override (tests).
    tracer: request tracing (obs/trace.py). None — the default — is the
      no-op tracer: requests run untraced at zero overhead. Pass a
      ``Tracer()`` to record span trees (``/debug/traces``, X-Trace-Id).
    profile_dir: enables ``/debug/profile`` captures into this directory
      (``obs.profile.DeviceProfiler`` over ``jax.profiler``).
    profiler: explicit profiler override (tests inject fake trace
      contexts); wins over ``profile_dir``.
    profile_hook: optional callable invoked with each finished capture's
      directory (``serve --profile-hook CMD`` wraps a user command) —
      the artifact-upload seam. Hook failures are counted
      (``profile_hook_failures``) and reported in the capture response,
      never raised: an upload problem must not fail the capture.
    slo: SLO tracking (``obs.slo``). The default ``SloConfig()`` tracks
      99% availability + 95%-under-1s latency with multi-window
      burn-rate alerting; pass a custom ``SloConfig``, a pre-built
      ``SloTracker`` (tests inject fake clocks), or None to disable.
      Surfaced as the ``slo`` block in ``/stats``, ``mpi_slo_*``
      families in ``/metrics``, and folded into ``/healthz`` (a firing
      alert reports ``degraded`` with the reason).
    alert_hook: optional callable invoked with each ``slo_alert``
      event's record dict on every alert FIRE and CLEAR edge (``serve
      --alert-hook CMD`` wraps a user command) — the alert *delivery*
      seam, the serving twin of ``profile_hook``. Edges are delivered
      IN ORDER by one daemon worker thread (alert edges fire inside
      the request path; a pager webhook must not stall a render, and a
      slow fire delivery must not be overtaken by its clear); failures
      are counted
      (``alert_hook_failures``, surfaced in ``/stats``) and never
      raised: a dead pager must not fail the service it pages about.
    events: the lifecycle event log (``obs.events.EventLog``; a private
      one is made if omitted) serving ``/debug/events`` — breaker
      transitions, watchdog trips, scene swaps, SLO alert edges.
    tsdb: the on-box time-series ring (``obs.tsdb``): pass a
      ``TsdbConfig`` to sample every ``/metrics`` family on its cadence
      (the recorder thread starts here and stops in ``close``) and
      serve windowed history at ``GET /debug/tsdb``; pass a pre-built
      ``TsdbRecorder`` to adopt it un-started (tests drive ``sample()``
      with fake clocks); None disables the endpoint (503).
    ship: off-host telemetry shipping (``obs.ship``): pass a
      ``ShipConfig`` to batch rotated event-log segments, SLO alert
      edges, and incremental tsdb snapshots to its HTTP sink on a
      daemon thread (retry + disk spool; counted, never fatal, never on
      the request path); pass a pre-built ``TelemetryShipper`` to adopt
      it un-started (tests drive ``tick()``); None disables shipping.
    attrib: resource attribution (``obs.attrib``): pass an
      ``AttribConfig`` to account every completed request's device
      phase-seconds, queue wait, bytes, and edge serves into bounded
      ``(scene x class x brownout-level)`` cells served at
      ``GET /debug/attrib`` (+ an ``attrib`` block in ``/stats`` and
      additive ``mpi_serve_attrib_*`` families the cluster router's
      pool merge sums into a fleet ledger); pass a pre-built
      ``AttribLedger`` to adopt it; None disables the endpoint (503).
    incidents: the SLO-triggered incident recorder (``obs.incident``):
      pass an ``IncidentConfig`` to capture a self-contained bundle
      (alert + burn numbers, slowest traces, tsdb window, events,
      brownout state, top attribution cells) on every alert FIRE edge
      — deduplicated until the clear — into a bounded on-disk ring
      served at ``GET /debug/incidents`` and shipped off-host through
      the telemetry shipper's spool; pass a pre-built
      ``IncidentRecorder`` to adopt it un-started (tests drive
      ``drain()``); None disables the endpoint (503). Requires SLO
      tracking (the alert edges are the trigger).
    metrics_ttl_s: ``/metrics`` exposition-string cache TTL
      (``obs.prom.ExpositionCache``) — scrape storms on the aggregated
      cluster endpoint cost one snapshot render per window instead of
      one per scrape; <= 0 renders fresh every scrape.
    clock: injectable monotonic clock for the exposition cache (the
      serve/-wide rule; scheduler/metrics/tracer carry their own).
  """

  def __init__(self, cache_bytes: int = 2 << 30, max_batch: int = 8,
               max_wait_ms: float = 2.0, max_inflight: "int | str" = 4,
               max_inflight_cap: int = 16,
               method: str = "fused", tile: "int | str | None" = None,
               asset_cache_bytes: int = 256 << 20,
               convention: "Convention | None" = None,
               use_mesh: bool | None = None, max_queue: int = 1024,
               engine: RenderEngine | None = None,
               resilience: ResilienceConfig | None = ResilienceConfig(),
               cpu_fallback: str = "auto", fallback_engine=None,
               edge: EdgeConfig | None = None,
               tracer: Tracer | None = None, profile_dir: str | None = None,
               profiler: DeviceProfiler | None = None,
               profile_hook=None, alert_hook=None,
               slo: "SloConfig | SloTracker | None" = SloConfig(),
               brownout: "brownout_mod.BrownoutConfig | None" = None,
               events: EventLog | None = None,
               tsdb: "tsdb_mod.TsdbConfig | tsdb_mod.TsdbRecorder | None" = None,
               ship: "ship_mod.ShipConfig | ship_mod.TelemetryShipper | None" = None,
               attrib: "attrib_mod.AttribConfig | attrib_mod.AttribLedger | None" = None,
               incidents: "incident_mod.IncidentConfig | incident_mod.IncidentRecorder | None" = None,
               session: "session_mod.SessionConfig | None" = None,
               metrics_ttl_s: float = 0.25, clock=time.monotonic):
    if cpu_fallback not in ("auto", "on", "off"):
      raise ValueError(
          f"cpu_fallback must be auto/on/off, got {cpu_fallback!r}")
    if cpu_fallback == "on" and resilience is None and fallback_engine is None:
      # The fallback only engages through the resilience layer's breaker;
      # accepting the combination silently would drop an explicit knob.
      raise ValueError("cpu_fallback='on' requires resilience enabled")
    adaptive_inflight = max_inflight == "auto"
    if adaptive_inflight:
      if max_inflight_cap < 2:
        raise ValueError(
            f"max_inflight_cap must be >= 2 for auto, got {max_inflight_cap}")
      max_inflight = 2  # the adaptive starting window
    elif isinstance(max_inflight, str):
      raise ValueError(
          f"max_inflight must be an int or 'auto', got {max_inflight!r}")
    elif max_inflight < 1:
      raise ValueError(f"max_inflight must be >= 1, got {max_inflight}")
    if isinstance(tile, str) and tile != "auto":
      raise ValueError(f"tile must be an int, 'auto', or None, got {tile!r}")
    if tile is not None and tile != "auto" and tile < 8:
      # Below 8 px the crop-correction affines degenerate (1-px crops
      # divide by zero under the reference conventions) and the per-tile
      # bookkeeping dwarfs the pixels it manages.
      raise ValueError(f"tile must be >= 8 pixels, got {tile}")
    if tile is not None and method == "fused_pallas":
      # render_mpi rejects tgt_intrinsics/out_hw for the Pallas kernel,
      # so every CULLED render would 500 while full-coverage warmup
      # succeeds — fail the misconfiguration at construction instead.
      raise ValueError(
          "tile-granular serving requires an XLA method "
          "('fused'/'scan'/'assoc'); method='fused_pallas' cannot "
          "render cropped sources")
    if brownout is not None:
      if slo is None:
        # The ladder is DRIVEN by the SLO fast-window burn; without the
        # tracker it would only ever see queue depth and silently lose
        # half its trigger — fail the misconfiguration at construction.
        raise ValueError("brownout requires SLO tracking (slo=None "
                         "disables the burn signal that drives the "
                         "ladder)")
      if method == "fused_pallas":
        # L2's half-resolution renders ride the same tgt_intrinsics/
        # out_hw path as tile crops, which the Pallas kernel rejects.
        raise ValueError(
            "brownout degraded rendering requires an XLA method "
            "('fused'/'scan'/'assoc'); method='fused_pallas' cannot "
            "render reduced-resolution targets")
    if incidents is not None and slo is None:
      # The recorder only ever triggers on SLO alert edges; without the
      # tracker it would sit armed forever and never capture — fail the
      # misconfiguration at construction (the brownout precedent).
      raise ValueError("incidents require SLO tracking (slo=None "
                       "disables the alert edges that trigger capture)")
    # "auto" derives a per-scene size from its dims at publish
    # (tiles_mod.auto_tile); every `self.tile is not None` gate below
    # treats it exactly like an explicit size.
    self.tile = tile if tile == "auto" else (
        int(tile) if tile is not None else None)
    self._clock = clock
    # The engine's own window must not be the bottleneck under retries
    # (an abandoned attempt can briefly hold a slot next to its retry's)
    # nor under adaptive growth (size it for the cap, not the start).
    engine_window = max_inflight_cap if adaptive_inflight else max_inflight
    engine_kw = {} if convention is None else {"convention": convention}
    self.engine = engine if engine is not None else RenderEngine(
        method=method, use_mesh=use_mesh,
        max_inflight=max(8, 2 * engine_window), **engine_kw)
    self.cache = cache_mod.SceneCache(byte_budget=cache_bytes)
    self.metrics = ServeMetrics()
    # Resource-attribution ledger (obs/attrib.py): installed ON the
    # metrics object so the one record_request recording point feeds
    # both sides of the conservation invariant.
    if isinstance(attrib, attrib_mod.AttribLedger):
      self.attrib = attrib
    elif attrib is not None:
      self.attrib = attrib_mod.AttribLedger(attrib)
    else:
      self.attrib = None
    self.metrics.attrib = self.attrib
    self.events = events if events is not None else EventLog()
    # SLO judgment layer: alert edges land in the event log, request
    # outcomes feed the tracker via ServeMetrics (one recording point).
    if isinstance(slo, SloTracker):
      self.slo = slo
    elif slo is not None:
      self.slo = SloTracker(slo, clock=clock)
    else:
      self.slo = None
    if self.slo is not None:
      if self.slo.on_alert is None:
        self.slo.on_alert = self._on_slo_alert
      self.metrics.slo = self.slo
    self.tracer = tracer if tracer is not None else NULL_TRACER
    if profiler is not None:
      self.profiler = profiler
    else:
      self.profiler = (DeviceProfiler(profile_dir) if profile_dir else None)
    self.profile_hook = profile_hook
    self.profile_hook_failures = 0
    self.alert_hook = alert_hook
    self._alert_hook_lock = threading.Lock()
    self._alert_hook_queue = None  # lazy: only alerting services pay it
    self.alert_hook_runs = 0
    self.alert_hook_failures = 0
    self.resilient = None if resilience is None else ResilientExecutor(
        resilience, metrics=self.metrics, events=self.events)
    self.fallback_engine = fallback_engine
    if (self.fallback_engine is None and self.resilient is not None
        and (cpu_fallback == "on"
             or (cpu_fallback == "auto"
                 and self.engine.platform != "cpu"))):
      self.fallback_engine = self.engine.cpu_fallback()
    self._fallback_cache = (
        cache_mod.SceneCache(byte_budget=cache_bytes)
        if self.fallback_engine is not None else None)
    self._scene_data: dict[str, tuple] = {}
    self._scene_lock = threading.Lock()
    # Tile-granular serving state (serve/tiles.py): per-scene tiling
    # metadata (digests, plane masks, grid — all guarded by
    # _scene_lock), a per-TILE baked LRU (its own cache so tile bytes /
    # evictions are first-class accounting, and so a live reload can
    # invalidate exactly the changed tiles), and a small bounded memo of
    # assembled crops so the steady-state hit path pays one dict lookup
    # instead of K device concats per request.
    self._tile_meta: dict[str, tiles_mod.TileMeta] = {}
    self._tile_cache = (cache_mod.SceneCache(byte_budget=cache_bytes)
                        if self.tile is not None else None)
    self._fallback_tile_cache = (
        cache_mod.SceneCache(byte_budget=cache_bytes)
        if self.tile is not None and self.fallback_engine is not None
        else None)
    self._crop_memo: "OrderedDict[str, cache_mod.BakedScene]" = \
        _OrderedDict()
    self._crop_memo_bytes = 0
    # A quarter of the baked-cache allowance: each memo entry duplicates
    # its crop's device bytes ON TOP of the tiles it was concatenated
    # from, so the memo gets a bounded supplement, not a second full
    # budget (total tiled residency <= 1.25x --cache-mb).
    self._crop_memo_budget = max(int(cache_bytes) // 4, 1)
    self._crop_lock = threading.Lock()
    # Content-addressed asset tier (serve/assets/): rides the tile
    # digests, so it exists exactly when tiling does. The store holds
    # ENCODED bytes (zlib tiles, PNG layers) under its own byte budget;
    # evicted assets re-encode from live scene data on demand.
    self.assets = (assets_mod.AssetStore(byte_budget=asset_cache_bytes)
                   if self.tile is not None else None)
    # The edge frame cache (serve/edge/): per-scene generation counters
    # make the params digest change on every add_scene/swap_scenes, so a
    # live reload orphans every cached cell of the old pixels; the base
    # digest folds in the render-affecting engine identity so two
    # differently-configured services never share frame identities.
    self.edge = None if edge is None else EdgeFrameCache(edge,
                                                         clock=self._clock)
    self._scene_gen: dict[str, int] = {}
    desc = self.engine.describe()
    self._edge_base = hashlib.sha1(repr(tuple(
        (k, desc.get(k))
        for k in ("platform", "method", "sharded", "devices")
    )).encode()).hexdigest()[:8]
    if self.edge is not None:
      self.events.emit(
          "edge_cache_enabled",
          trans_cell=self.edge.config.trans_cell,
          rot_bucket_deg=self.edge.config.rot_bucket_deg,
          warp_max_trans=self.edge.config.warp_max_trans,
          warp_max_rot_deg=self.edge.config.warp_max_rot_deg,
          byte_budget=self.edge.config.byte_budget)
    self.scheduler = MicroBatcher(
        self.engine, self._get_scene, metrics=self.metrics,
        max_batch=max_batch, max_wait_ms=max_wait_ms,
        # The keyer carries the brownout degrade tier into batch keys,
        # so untiled-but-brownout services need it installed too (its
        # untiled arm is the identity key at degrade 0).
        batch_keyer=(self._tile_batch_key
                     if self.tile is not None or brownout is not None
                     else None),
        max_queue=max_queue, max_inflight=max_inflight,
        adaptive_inflight=adaptive_inflight,
        max_inflight_cap=max_inflight_cap if adaptive_inflight else None,
        resilient=self.resilient,
        fallback_engine=self.fallback_engine,
        fallback_scene_provider=(
            self._get_scene_fallback
            if self.fallback_engine is not None else None)).start()
    # Brownout ladder (serve/brownout.py): built after the scheduler so
    # its queue-occupancy signal reads the live queue; the burn signal is
    # the SLO tracker's fast window (validated non-None above).
    self.brownout = None if brownout is None else \
        brownout_mod.BrownoutController(
            brownout, burn_fn=self.slo.fast_burn,
            queue_fn=self.scheduler.queue_fraction,
            on_transition=self._on_brownout_transition, clock=clock)
    # Session tier (serve/session/): built after the brownout controller
    # because the prefetcher reads its level (L3+ mutes the predictor)
    # and after the scheduler because session frames ride render_request
    # straight into it.
    self.sessions = None if session is None else session_mod.SessionManager(
        session, service=self, clock=clock)
    self._metrics_cache = prom.ExpositionCache(
        self._render_metrics_text, ttl_s=metrics_ttl_s, clock=clock)
    # Flight-recorder legs (obs/tsdb.py, obs/ship.py): configs build and
    # START the daemon threads; pre-built objects are adopted un-started
    # (tests drive sample()/tick() against fake clocks/sinks). The tsdb
    # recorder samples _render_metrics_text directly — history must be
    # fresh samples, not the exposition cache's memoized string.
    if isinstance(tsdb, tsdb_mod.TsdbRecorder):
      self.tsdb = tsdb
    elif tsdb is not None:
      self.tsdb = tsdb_mod.TsdbRecorder(
          self._render_metrics_text, tsdb).start()
    else:
      self.tsdb = None
    if isinstance(ship, ship_mod.TelemetryShipper):
      self.shipper = ship
      if self.shipper.tsdb is None:
        self.shipper.tsdb = self.tsdb
    elif ship is not None:
      self.shipper = ship_mod.TelemetryShipper(ship, tsdb=self.tsdb).start()
    else:
      self.shipper = None
    # Incident recorder (obs/incident.py): built last — its collector
    # freezes every surface wired above (slo, tracer, tsdb, events,
    # brownout, attrib, profiler). Configs build + START the worker;
    # pre-built recorders are adopted un-started (tests drive drain())
    # with the service's collector/shipper wired in if absent (the
    # shipper.tsdb adoption precedent).
    if isinstance(incidents, incident_mod.IncidentRecorder):
      self.incidents = incidents
      if self.incidents.collect is None:
        self.incidents.collect = self._incident_context
      if self.incidents.on_bundle is None and self.shipper is not None:
        self.incidents.on_bundle = self.shipper.note_incident
    elif incidents is not None:
      self.incidents = incident_mod.IncidentRecorder(
          incidents, collect=self._incident_context,
          on_bundle=(self.shipper.note_incident
                     if self.shipper is not None else None),
          events=self.events, clock=clock).start()
    else:
      self.incidents = None
    self._closed = False

  def _on_slo_alert(self, name: str, firing: bool, details: dict) -> None:
    record = self.events.emit("slo_alert", slo=name, firing=firing,
                              **details)
    # NULL_EVENTS returns None; the shipper/hook still need the facts.
    if record is None:
      record = {"kind": "slo_alert", "slo": name, "firing": firing,
                **details}
    shipper = getattr(self, "shipper", None)
    if shipper is not None:
      # O(1) queue append — the off-host delivery happens on the
      # shipper's own thread, never inside the alert (request) path.
      shipper.note_alert(record)
    incidents = getattr(self, "incidents", None)
    if incidents is not None:
      # Same contract: O(1) edge note here, bundle capture on the
      # recorder's own worker thread. Fire edges queue one capture
      # (deduplicated until the clear edge releases the latch).
      incidents.note_alert(name, firing, details)
    if self.alert_hook is None:
      return
    # Off the request path: alert edges fire inside SloTracker.check()
    # under a live render, and a slow pager webhook must not add its
    # latency to the very requests it is paging about. ONE worker
    # draining a queue, not a thread per edge: a slow FIRE delivery must
    # not be overtaken by its own CLEAR (a pager that hears CLEAR then
    # FIRE is left permanently firing).
    if self._closed:
      return  # a post-close scrape must not page about a dead service
    with self._alert_hook_lock:
      if self._alert_hook_queue is None:
        import queue

        self._alert_hook_queue = queue.SimpleQueue()
        threading.Thread(target=self._alert_hook_worker,
                         name="mpi-serve-alert-hook", daemon=True).start()
    self._alert_hook_queue.put(dict(record))

  def _alert_hook_worker(self) -> None:
    while True:
      record = self._alert_hook_queue.get()
      if record is None:  # close() sentinel: drain done, exit
        return
      try:
        self.alert_hook(record)
        with self._alert_hook_lock:
          self.alert_hook_runs += 1
      except Exception as e:  # noqa: BLE001 - a dead pager must not kill serving
        with self._alert_hook_lock:
          self.alert_hook_runs += 1
          self.alert_hook_failures += 1
        self.events.emit("alert_hook_failed", slo=record.get("slo"),
                         firing=record.get("firing"), error=repr(e))

  def _on_brownout_transition(self, old: int, new: int,
                              reason: str) -> None:
    self.events.emit("brownout_level", old=old, new=new, reason=reason)

  def _incident_context(self, alert: dict) -> dict:
    """One incident bundle's context (the recorder's ``collect`` hook):
    every surface an operator would hand-stitch after a page — the SLO
    burn numbers, the slowest traces, the tsdb window over the spike,
    the recent events, the brownout ladder state, the hottest
    attribution cells — frozen at the fire edge, plus optionally a
    device-profile capture. Runs on the recorder's worker thread, never
    the request path. Absent subsystems contribute nothing rather than
    fail the capture (and the recorder survives this raising anyway)."""
    del alert  # the recorder already embeds the alert record itself
    cfg = self.incidents.config
    out: dict = {}
    if self.slo is not None:
      out["slo"] = self.slo.snapshot()
    if self.tracer is not NULL_TRACER:
      out["traces"] = self.tracer.snapshot(recent=cfg.traces_recent)
    if self.tsdb is not None:
      out["tsdb_window"] = {
          "window_s": cfg.tsdb_window_s,
          "families": self.tsdb.snapshot_since(
              self.tsdb.now() - cfg.tsdb_window_s)}
    out["events"] = self.events.snapshot(recent=cfg.events_recent)
    if self.brownout is not None:
      out["brownout"] = self.brownout.snapshot()
    if self.attrib is not None:
      out["attrib_top"] = self.attrib.top_cells(cfg.top_k_cells)
    if cfg.profile_seconds > 0 and self.profiler is not None:
      try:
        out["profile"] = self.profile(cfg.profile_seconds)
      except Exception as e:  # noqa: BLE001 - a busy/failing profiler
        # must not cost the bundle its other slices.
        out["profile"] = {"error": repr(e)}
    return out

  # -- scenes -------------------------------------------------------------

  def add_scene(self, scene_id: str, rgba_layers, depths,
                intrinsics) -> None:
    """Register a scene (host arrays); it bakes lazily on first request.

    With tiling on, the scene is split into its tile grid here (per-tile
    digests + plane masks) and a re-registration invalidates ONLY the
    tiles whose bytes changed — the same diff live reloads use.
    """
    entry = (np.asarray(rgba_layers, np.float32),
             np.asarray(depths, np.float32),
             np.asarray(intrinsics, np.float32))
    sid = str(scene_id)
    if tiles_mod.KEY_SEP in sid:
      # The tile/crop batch- and cache-key separator: a scene id
      # carrying it would alias tile keys (the HTTP layer rejects all
      # control characters for the same reason).
      raise ValueError("scene_id must not contain '\\x1f'")
    if self.tile is not None:
      self._publish_tiled(sid, entry)
      return
    with self._scene_lock:
      self._scene_data[sid] = entry
      # New content under this id: a fresh generation makes every edge
      # frame digest of the old pixels unreachable.
      self._scene_gen[sid] = self._scene_gen.get(sid, 0) + 1
    if self.edge is not None:
      self.edge.invalidate_scene(sid)

  def _publish_tiled(self, sid: str, entry: tuple) -> list[tuple[int, int]]:
    """Publish (or re-publish) one scene into the tiled registry and
    invalidate exactly the tiles whose bytes changed. Returns the
    changed tile ids (every tile for a first publish or a grid/geometry
    change)."""
    tile_px = (self.tile if isinstance(self.tile, int)
               else tiles_mod.auto_tile(entry[0].shape[0],
                                        entry[0].shape[1]))
    meta = tiles_mod.TileMeta.build(entry[0], entry[1], entry[2],
                                    tile_px)
    with self._scene_lock:
      old = self._tile_meta.get(sid)
      self._scene_data[sid] = entry
      self._tile_meta[sid] = meta
    if old is None:
      changed = [(i, j) for i in range(meta.grid.rows)
                 for j in range(meta.grid.cols)]
      # First publish under this id: nothing valid can be cached, but a
      # stale same-id residue from a pre-tiling registration must go.
      self._tile_cache.invalidate_prefix(sid + tiles_mod.KEY_SEP)
      if self._fallback_tile_cache is not None:
        self._fallback_tile_cache.invalidate_prefix(sid + tiles_mod.KEY_SEP)
      self.cache.invalidate(sid)
      self._purge_crop_memo(sid)
      if self.edge is not None:
        self.edge.invalidate_scene(sid)
      self._publish_assets(sid, meta, changed)
      return changed
    changed = old.changed_tiles(meta)
    all_changed = len(changed) == len(meta.grid) or old.grid != meta.grid
    for (i, j) in (changed if not all_changed else []):
      key = tiles_mod.tile_cache_key(sid, i, j)
      self._tile_cache.invalidate(key)
      if self._fallback_tile_cache is not None:
        self._fallback_tile_cache.invalidate(key)
    if all_changed:
      # Grid or geometry changed: every old tile id is dead, and even
      # frames that touched no tile may depend on the camera — sweep
      # everything under this scene.
      self._tile_cache.invalidate_prefix(sid + tiles_mod.KEY_SEP)
      if self._fallback_tile_cache is not None:
        self._fallback_tile_cache.invalidate_prefix(sid + tiles_mod.KEY_SEP)
    if changed:
      self._purge_crop_memo(sid)
    if self.edge is not None and changed:
      if all_changed:
        self.edge.invalidate_scene(sid)
      else:
        self.edge.invalidate_tiles(sid, changed)
    self._publish_assets(sid, meta, changed)
    return changed

  def _publish_assets(self, sid: str, meta, changed) -> None:
    """Register the new generation's tile digests with the asset store
    and announce the manifest. Unchanged tiles keep their digests, so
    their asset URLs/ETags survive the publish byte-identical — the
    asset-tier mirror of the tile-granular cache invalidation above."""
    if self.assets is None:
      return
    grid = meta.grid
    planes = int(meta.depths.shape[0])
    index = {}
    for i in range(grid.rows):
      for j in range(grid.cols):
        y0, y1, x0, x1 = grid.rect(i, j)
        index[meta.digests[i][j]] = {
            "kind": "tile", "scene_id": sid, "row": i, "col": j,
            "shape": (y1 - y0, x1 - x0, planes, 4)}
    self.assets.publish_scene(sid, index)
    self.events.emit("manifest_publish", scene_id=sid,
                     scene_digest=meta.scene_digest, tiles=len(grid),
                     tiles_changed=len(changed))

  def _purge_crop_memo(self, sid: str) -> None:
    with self._crop_lock:
      for key in [k for k in self._crop_memo
                  if k.startswith(sid + tiles_mod.KEY_SEP)]:
        self._crop_memo_bytes -= self._crop_memo.pop(key).nbytes

  def add_synthetic_scenes(self, n: int, height: int = 256, width: int = 256,
                           planes: int = 16, seed: int = 0) -> list[str]:
    ids = []
    for i in range(n):
      sid = f"scene_{i:03d}"
      self.add_scene(sid, *synthetic_scene(sid, height, width, planes,
                                           seed=seed + i))
      ids.append(sid)
    return ids

  def scene_ids(self) -> list[str]:
    with self._scene_lock:
      return sorted(self._scene_data)

  def tile_meta(self, scene_id: str):
    """The current ``TileMeta`` of a tiled scene (None if unknown or
    the service is untiled) — the ``SceneFetcher`` diff's local side."""
    with self._scene_lock:
      return self._tile_meta.get(str(scene_id))

  def scene_entry(self, scene_id: str):
    """The registered host arrays ``(rgba, depths, intrinsics)`` of a
    scene, or None. Shared read-only by convention — callers that
    mutate must copy."""
    with self._scene_lock:
      return self._scene_data.get(str(scene_id))

  # -- content-addressed asset tier (serve/assets/) -----------------------

  def _require_assets(self) -> None:
    if self.assets is None:
      raise RuntimeError(
          "the asset tier rides the tile digests: construct "
          "RenderService with tile= (serve --tiled)")

  def scene_manifest(self, scene_id: str) -> dict:
    """The versioned scene manifest (``GET /scene/{id}/manifest``).

    Built lazily per generation and cached by scene digest; the first
    build also bakes the per-plane layer PNGs the viewer composites.
    Raises KeyError for unknown scenes.
    """
    self._require_assets()
    sid = str(scene_id)
    meta = self.tile_meta(sid)
    if meta is None:
      raise KeyError(f"unknown scene {sid!r}")
    cached = self.assets.manifest(sid, meta.scene_digest)
    if cached is not None:
      return cached
    entry = self.scene_entry(sid)
    layers = self._publish_layer_assets(sid, meta, entry)
    manifest = assets_mod.build_manifest(
        sid, meta, params_digest=f"{self._edge_base}:tiled",
        layers=layers)
    # Cache only if this generation is still current (a concurrent swap
    # may have republished mid-build; the next request rebuilds).
    if self.tile_meta(sid) is meta:
      self.assets.cache_manifest(sid, meta.scene_digest, manifest)
    return manifest

  def _publish_layer_assets(self, sid: str, meta, entry) -> list[str]:
    """Encode each MPI plane as a PNG asset (the viewer's sources),
    addressed by the sha256 of the PNG bytes. Returns the digests,
    index 0 farthest (the template's compositing order)."""
    from mpi_vision_tpu.viewer import export as viewer_export

    rgba = entry[0]
    digests, index = [], {}
    for plane in range(rgba.shape[2]):
      png = viewer_export.layer_to_png_bytes(rgba[:, :, plane])
      digest = assets_mod.digest_of(png)
      self.metrics.record_asset_encode()
      self.assets.put(digest, png, png,
                      {"kind": "layer", "content_type":
                       assets_mod.LAYER_CONTENT_TYPE,
                       "encoding": assets_mod.LAYER_ENCODING})
      index[digest] = {"kind": "layer", "scene_id": sid, "plane": plane}
      digests.append(digest)
    self.assets.register_assets(sid, index)
    return digests

  def scene_asset(self, scene_id: str, digest: str) -> tuple[bytes, dict]:
    """Encoded bytes + serving metadata of one content-addressed asset.

    Resident bytes serve straight from the LRU; an evicted-but-live
    digest re-encodes from scene data (digest-verified — a scene that
    changed under a stale descriptor can never serve wrong bytes).
    Raises KeyError when the digest is neither resident nor live: 404.
    The scene id in the URL only scopes routing; the digest alone names
    the bytes.
    """
    self._require_assets()
    hit = self.assets.get(digest)
    if hit is not None:
      return hit
    desc = self.assets.source(digest)
    if desc is None:
      raise KeyError(f"unknown asset digest {digest[:12]}…")
    tr = self.tracer.start_trace("asset_encode",
                                 scene_id=desc["scene_id"],
                                 digest=digest[:12])
    try:
      out = self._encode_asset(desc, digest)
    except Exception as e:
      tr.finish(error=repr(e))
      raise
    tr.finish()
    return out

  def _encode_asset(self, desc: dict, digest: str) -> tuple[bytes, dict]:
    sid = desc["scene_id"]
    entry = self.scene_entry(sid)
    meta = self.tile_meta(sid)
    if entry is None or meta is None:
      raise KeyError(f"asset {digest[:12]}… lost its scene {sid!r}")
    self.metrics.record_asset_encode()
    if desc["kind"] == "tile":
      y0, y1, x0, x1 = meta.grid.rect(desc["row"], desc["col"])
      raw = np.ascontiguousarray(entry[0][y0:y1, x0:x1]).tobytes()
      encoded = assets_mod.encode_tile(raw)
      serve_meta = {"kind": "tile",
                    "content_type": assets_mod.TILE_CONTENT_TYPE,
                    "encoding": assets_mod.TILE_ENCODING}
    else:
      from mpi_vision_tpu.viewer import export as viewer_export

      raw = encoded = viewer_export.layer_to_png_bytes(
          entry[0][:, :, desc["plane"]])
      serve_meta = {"kind": "layer",
                    "content_type": assets_mod.LAYER_CONTENT_TYPE,
                    "encoding": assets_mod.LAYER_ENCODING}
    try:
      self.assets.put(digest, raw, encoded, serve_meta)
    except assets_mod.AssetIntegrityError:
      # The scene changed between descriptor registration and this
      # encode (or the bake is corrupt): the digest no longer names
      # producible bytes. Refuse to serve — immutability means wrong
      # bytes under a digest would be cached forever downstream.
      self.metrics.record_asset_publish_reject()
      raise
    return encoded, serve_meta

  def scene_viewer_html(self, scene_id: str) -> tuple[str, str]:
    """The browser viewer for one scene, templated against asset URLs
    (no inlined base64 — layers ride the immutable asset path).
    Returns ``(html, scene_digest)``; the digest is the ETag token.
    """
    from mpi_vision_tpu.viewer import export as viewer_export

    sid = str(scene_id)
    man = self.scene_manifest(sid)
    quoted = urllib.parse.quote(sid, safe="")
    sources = [f"/scene/{quoted}/asset/{d}" for d in man["layers"]]
    depths = man["depths"]
    grid = man["grid"]
    fx = float(man["intrinsics"][0][0])
    fov_deg = math.degrees(2.0 * math.atan2(grid["width"] / 2.0,
                                            max(fx, 1e-6)))
    html = viewer_export.render_viewer_html(
        sources, grid["width"], grid["height"],
        near=min(depths), far=max(depths), fov_deg=fov_deg)
    return html, man["scene_digest"]

  def _tile_batch_key(self, scene_id: str, pose,
                      degrade: int = 0) -> tuple[str, dict | None]:
    """The scheduler's batch-key hook for tiled services: frustum-cull
    the request into a ``TileSignature`` so it batches only with
    requests sharing its exact render plan. Untiled scenes (an
    ``--mpi-dir`` scene living next to tiled ones) pass through on the
    plain scene id.

    Under brownout the admitted level arrives as ``degrade``: L1 thins
    the signature's plane set (the key changes with it, so degraded and
    full-quality requests can never coalesce into one batch), and L2
    additionally appends the half-res marker field — a distinct key AND
    a distinct scene-provider plan, keeping the degraded render out of
    every full-quality compile bucket and crop memo."""
    with self._scene_lock:
      meta = self._tile_meta.get(scene_id)
    if meta is None:
      if degrade >= 2:
        return brownout_mod.half_res_key(scene_id), None
      return scene_id, None
    sig = meta.plan(np.asarray(pose, np.float32)[None],
                    self.engine.convention)
    if degrade >= 1 and self.brownout is not None:
      sig = dataclasses.replace(sig, planes=tiles_mod.thin_planes(
          sig.planes, self.brownout.config.plane_keep))
    key = scene_id + tiles_mod.KEY_SEP + sig.token()
    if degrade >= 2:
      key = brownout_mod.half_res_key(key)
    # No metrics here: the scheduler records the attrs only for
    # requests it actually ENQUEUES, so breaker fast-fails and
    # queue-full rejections never skew the cull ratios.
    return (key, {
        "tiles_touched": sig.tiles_touched,
        "tiles_rendered": sig.tiles_rendered,
        "tiles_culled": sig.tiles_total - sig.tiles_rendered,
        "tiles_total": sig.tiles_total,
        "planes": len(sig.planes),
    })

  def _get_scene(self, scene_id: str) -> cache_mod.BakedScene:
    base, half_res = brownout_mod.split_degrade_key(scene_id)
    sid, _, token = base.partition(tiles_mod.KEY_SEP)
    if self.tile is not None:
      with self._scene_lock:
        meta = self._tile_meta.get(sid)
      if meta is not None:
        return self._assemble_crop(sid, meta, token, fallback=False,
                                   half_res=half_res)

    def bake():
      with self._scene_lock:
        entry = self._scene_data.get(base)
      if entry is None:
        raise KeyError(f"unknown scene {base!r}")
      # Bake-fault hook (FaultyEngine.check_bake): inside the cache-miss
      # path so injected bake failures fire exactly where a dead device
      # would fail a real bake — never on cache hits.
      check_bake = getattr(self.engine, "check_bake", None)
      if check_bake is not None:
        check_bake(base)
      return cache_mod.bake_scene(base, *entry)

    scene = self.cache.get_or_bake(base, bake)
    if half_res:
      # L2 half-res view of the full bake: shares the device layers
      # (nothing extra resident), overrides only the render target —
      # half-scaled intrinsics and a halved raster. Built per call, not
      # cached: the wrapper is two tiny arrays.
      scene = self._half_res_view(scene)
    return scene

  def _half_res_view(self,
                     scene: cache_mod.BakedScene) -> cache_mod.BakedScene:
    """Derive the L2 target override from a full-quality bake: target
    intrinsics scaled by 1/2 in the first two rows, output raster
    halved. The source layers are shared by reference."""
    base_k = (scene.tgt_intrinsics if scene.tgt_intrinsics is not None
              else scene.intrinsics)
    h, w = (scene.out_hw if scene.out_hw is not None
            else (int(scene.rgba_layers.shape[0]),
                  int(scene.rgba_layers.shape[1])))
    tgt_k = jnp.asarray(base_k) * jnp.asarray(
        [[0.5], [0.5], [1.0]], jnp.float32)
    return dataclasses.replace(
        scene, scene_id=brownout_mod.half_res_key(scene.scene_id),
        tgt_intrinsics=tgt_k,
        out_hw=(max(int(h) // 2, 1), max(int(w) // 2, 1)))

  def _assemble_crop(self, sid: str, meta: tiles_mod.TileMeta,
                     token: str, fallback: bool,
                     half_res: bool = False) -> cache_mod.BakedScene:
    """The tiled scene provider: per-tile get-or-bake, then one device
    concat of the signature's crop with its culled plane set and
    crop-corrected source intrinsics. A bounded memo makes the repeat
    path one dict lookup; a full-coverage all-planes signature returns a
    plain whole-scene ``BakedScene`` (no target override), sharing the
    monolithic path's compile and its bit-exactness. ``half_res`` (the
    L2 brownout tier) wraps the assembled crop in a half-res target
    view on the way out — the memo keeps only full-quality entries, so
    a brownout episode can never pollute the full-quality repeat
    path."""
    grid = meta.grid
    sig = None
    if token:
      # The token was minted by the batch keyer against the meta CURRENT
      # at submit time; a reload that changed the grid or plane count
      # while the request sat queued makes it stale. Validate against
      # THIS meta and fall back to full coverage of the current scene —
      # a correct fresh frame beats a clamped-gather misrender or a 500.
      try:
        parsed = tiles_mod.TileSignature.parse(token, grid)
        y0, y1, x0, x1 = parsed.crop
        if (0 <= y0 < y1 <= grid.height and 0 <= x0 < x1 <= grid.width
            and parsed.planes
            and all(0 <= p < meta.planes for p in parsed.planes)):
          sig = parsed
      except ValueError:
        pass
    if sig is None:
      # Plain scene-id lookups (warmup, prebake) assemble full coverage.
      sig = meta.signature(np.ones((grid.rows, grid.cols), bool))
    memo_key = sid + tiles_mod.KEY_SEP + sig.token() + \
        (tiles_mod.KEY_SEP + "fb" if fallback else "")
    with self._crop_lock:
      memo = self._crop_memo.get(memo_key)
      if memo is not None:
        self._crop_memo.move_to_end(memo_key)
        return self._half_res_view(memo) if half_res else memo
    cache = self._fallback_tile_cache if fallback else self._tile_cache
    device = (self.fallback_engine.devices[0] if fallback else None)
    rows, cols = meta.crop_tiles(sig.crop)

    def bake_tile(i, j):
      def bake():
        with self._scene_lock:
          entry = self._scene_data.get(sid)
        if entry is None:
          raise KeyError(f"unknown scene {sid!r}")
        if not fallback:
          check_bake = getattr(self.engine, "check_bake", None)
          if check_bake is not None:
            check_bake(sid)
        y0, y1, x0, x1 = grid.rect(i, j)
        return cache_mod.bake_scene(
            tiles_mod.tile_cache_key(sid, i, j),
            entry[0][y0:y1, x0:x1], entry[1], entry[2], device=device)
      return cache.get_or_bake(tiles_mod.tile_cache_key(sid, i, j), bake)

    idx = np.asarray(sig.planes, np.int32)
    tile_rows = []
    depths = intrinsics = None
    for i in rows:
      row = [bake_tile(i, j) for j in cols]
      depths, intrinsics = row[0].depths, row[0].intrinsics
      tile_rows.append(row[0].rgba_layers[:, :, idx, :] if len(row) == 1
                       else jnp.concatenate(
                           [t.rgba_layers[:, :, idx, :] for t in row],
                           axis=1))
    rgba = tile_rows[0] if len(tile_rows) == 1 else jnp.concatenate(
        tile_rows, axis=0)
    full = (sig.crop == (0, grid.height, 0, grid.width)
            and len(sig.planes) == meta.planes)
    if full:
      k_src, tgt_k, out_hw = intrinsics, None, None
      depths_sel = depths
    else:
      k_src = jnp.asarray(
          meta.crop_src_intrinsics(sig.crop, self.engine.convention))
      tgt_k = jnp.asarray(meta.intrinsics)
      out_hw = (grid.height, grid.width)
      depths_sel = depths[idx]
      if device is not None:
        k_src, tgt_k, depths_sel = (jax.device_put(a, device)
                                    for a in (k_src, tgt_k, depths_sel))
    jax.block_until_ready(rgba)
    nbytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                 for a in (rgba, depths_sel, k_src))
    scene = cache_mod.BakedScene(memo_key, rgba, depths_sel, k_src,
                                 nbytes, tgt_intrinsics=tgt_k,
                                 out_hw=out_hw)
    # Memoize ONLY if no publish/swap raced this assembly — verified and
    # inserted under the scene lock (the _edge_put pattern), so a swap's
    # registry update either happens-before this check (stale branch
    # below) or happens-after, in which case its invalidation sweep +
    # memo purge run after this insert and clean it up.
    with self._scene_lock:
      if self._tile_meta.get(sid) is meta:
        with self._crop_lock:
          old = self._crop_memo.pop(memo_key, None)
          if old is not None:  # a concurrent same-key assembly won
            self._crop_memo_bytes -= old.nbytes
          self._crop_memo[memo_key] = scene
          self._crop_memo_bytes += scene.nbytes
          # Bounded by entries AND bytes (each entry duplicates its
          # crop on device; the byte budget keeps the memo inside the
          # same allowance the tile cache answers to).
          while self._crop_memo and (
              len(self._crop_memo) > _CROP_MEMO_CAP
              or self._crop_memo_bytes > self._crop_memo_budget):
            _, evicted = self._crop_memo.popitem(last=False)
            self._crop_memo_bytes -= evicted.nbytes
        return self._half_res_view(scene) if half_res else scene
    # Stale: the tiles baked above may hold pre-swap bytes inserted
    # AFTER the swap's invalidation sweep. Drop them (unchanged tiles
    # re-bake to identical bytes, changed ones to the new bytes) and
    # serve this result uncached — the same one-stale-response-max
    # contract as the untiled swap.
    for i in rows:
      for j in cols:
        cache.invalidate(tiles_mod.tile_cache_key(sid, i, j))
    return self._half_res_view(scene) if half_res else scene

  def _get_scene_fallback(self, scene_id: str) -> cache_mod.BakedScene:
    """Scene provider for the degraded-mode engine: same host arrays,
    baked onto the fallback's (CPU) devices, cached separately so an
    outage does not evict the primary's residency."""
    # The fallback ignores the L2 half-res marker: it is already the
    # degraded-capacity path, and serving full resolution there is safe
    # (the readback upsample is a no-op on matching shapes).
    base, _ = brownout_mod.split_degrade_key(scene_id)
    sid, _, token = base.partition(tiles_mod.KEY_SEP)
    if self.tile is not None:
      with self._scene_lock:
        meta = self._tile_meta.get(sid)
      if meta is not None:
        return self._assemble_crop(sid, meta, token, fallback=True)

    def bake():
      with self._scene_lock:
        entry = self._scene_data.get(base)
      if entry is None:
        raise KeyError(f"unknown scene {base!r}")
      return cache_mod.bake_scene(
          base, *entry, device=self.fallback_engine.devices[0])

    return self._fallback_cache.get_or_bake(base, bake)

  def swap_scenes(self, scenes: dict, prebake: bool = False) -> list[str]:
    """Atomically publish new host data for ``scenes`` (live ckpt reload).

    ``scenes`` maps scene id -> ``(rgba_layers, depths, intrinsics)``.
    The registry updates first, then the baked caches (primary AND
    fallback) invalidate the changed ids — so a request that raced the
    swap serves either the old bake or the new one, never a mix, and no
    in-flight request is dropped: futures already holding a
    ``BakedScene`` render it to completion, and the old device buffers
    free when the last reference drops. ``prebake=True`` re-bakes the
    swapped scenes immediately so the first post-swap request does not
    pay the bake either. Returns the swapped ids.
    """
    entries = {
        str(sid): (np.asarray(rgba, np.float32),
                   np.asarray(depths, np.float32),
                   np.asarray(k, np.float32))
        for sid, (rgba, depths, k) in scenes.items()}
    swapped = sorted(entries)
    if self.tile is not None:
      # Tile-granular reload: diff each scene's tile digests and swap
      # ONLY the changed tiles — untouched tiles keep their baked cache
      # entries, and edge frames that never sampled a changed tile keep
      # their bytes AND their strong ETags (pinned in test_tiles.py).
      tiles_changed = {sid: len(self._publish_tiled(sid, entry))
                       for sid, entry in entries.items()}
      if prebake:
        for sid in entries:
          if tiles_changed[sid]:
            self._get_scene(sid)
      self.events.emit("scene_swap", scenes=swapped, prebake=bool(prebake),
                       tiles_changed=tiles_changed)
      return swapped
    with self._scene_lock:
      self._scene_data.update(entries)
      for sid in entries:
        self._scene_gen[sid] = self._scene_gen.get(sid, 0) + 1
    for sid in entries:
      self.cache.invalidate(sid)
      if self._fallback_cache is not None:
        self._fallback_cache.invalidate(sid)
    if self.edge is not None:
      # The edge cache invalidates exactly like the baked caches: a
      # request racing the swap serves old pixels under the OLD etag or
      # new pixels under a NEW one, never stale bytes under a fresh tag
      # (the generation bump above already orphaned the old digests;
      # the sweep frees their bytes).
      dropped = sum(self.edge.invalidate_scene(sid) for sid in swapped)
      self.events.emit("edge_cache_invalidated", scenes=swapped,
                       frames=dropped)
    if prebake:
      for sid in entries:
        self._get_scene(sid)
    self.events.emit("scene_swap", scenes=swapped, prebake=bool(prebake))
    return swapped

  def prebake_fallback(self, k: int | None = None,
                       scene_ids=None) -> list[str]:
    """Pre-bake the hottest-K scenes onto the degraded-mode CPU engine.

    Without this, the FIRST breaker-open render of each scene pays a
    cold CPU bake on top of an already-degraded request (ROADMAP
    resilience follow-on). "Hottest" defaults to registration order
    (startup has no traffic stats yet); pass ``scene_ids`` to override.
    No-op (returns []) when there is no fallback engine.
    """
    if self.fallback_engine is None:
      return []
    ids = list(scene_ids) if scene_ids is not None else self.scene_ids()
    if k is not None:
      ids = ids[:max(int(k), 0)]
    for sid in ids:
      self._get_scene_fallback(sid)
    return ids

  def warmup(self, scene_ids=None) -> None:
    """Bake scenes (default: all registered) and compile every batch
    bucket up to the scheduler's ``max_batch`` for the first scene's
    geometry, so steady-state traffic never pays an XLA compile. With
    the brownout controller armed, the half-res (L2+) buckets compile
    too — a browned-out service's steady state includes its degraded
    tiers, and paying those compiles mid-overload would make the cure
    slower than the disease."""
    ids = list(scene_ids) if scene_ids is not None else self.scene_ids()
    if not ids:
      return
    scenes = [self._get_scene(sid) for sid in ids]
    eye = np.eye(4, dtype=np.float32)
    buckets = sorted({self.engine.batch_bucket(v)
                      for v in range(1, self.scheduler.max_batch + 1)})
    variants = [scenes[0]]
    if self.brownout is not None:
      variants.append(self._half_res_view(scenes[0]))
    for scene in variants:
      for b in buckets:
        self.engine.render_batch(scene, np.broadcast_to(eye, (b, 4, 4)))
    if self.edge is not None:
      # The warp tier jits per frame shape too; without this, the first
      # near-miss of each resolution pays its compile mid-stream — under
      # a fused session flush that one slow frame stalls the whole
      # flight behind it.
      warmed: set[tuple[int, int]] = set()
      for sid in ids:
        try:
          hw = self._full_hw(sid)
          if hw in warmed:
            continue
          _, intrinsics, plane_depth, _ = self._edge_meta(sid)
        except KeyError:
          continue
        warmed.add(hw)
        frame = np.zeros((hw[0], hw[1], 3), np.float32)
        warp_frame(frame, eye, eye, intrinsics, plane_depth)

  # -- request path -------------------------------------------------------

  def render(self, scene_id: str, pose, timeout: float = 60.0,
             trace=NULL_TRACE) -> np.ndarray:
    """Blocking render of one ``[4, 4]`` pose -> ``[H, W, 3]`` f32."""
    return self.scheduler.render(scene_id, pose, timeout=timeout,
                                 trace=trace)

  def _full_hw(self, scene_id: str) -> tuple[int, int]:
    """The scene's full output raster ``(H, W)`` — the shape contract a
    degraded (half-res) render is upsampled back to at readback."""
    sid = str(scene_id)
    with self._scene_lock:
      meta = self._tile_meta.get(sid)
      entry = self._scene_data.get(sid)
    if meta is not None:
      return meta.grid.height, meta.grid.width
    if entry is None:
      raise KeyError(f"unknown scene {sid!r}")
    return int(entry[0].shape[0]), int(entry[0].shape[1])

  def _attrib_kwargs(self, attrib: "tuple | None",
                     edge: str | None) -> dict:
    """``record_request``'s attribution context for an edge-served
    request — kwargs form, so with the ledger off nothing is passed and
    drop-in metrics stubs predating the kwarg keep working."""
    if self.attrib is None:
      return {}
    cls, level = attrib if attrib is not None else (None, 0)
    return {"attrib": {"class": cls, "level": level, "edge": edge}}

  def _attrib_bytes(self, scene_id, attrib: "tuple | None",
                    nbytes) -> None:
    """Account response payload bytes to the request's attribution cell
    (no-op with the ledger off). Recorded at the serving front doors
    (``render_edge``/``render_request``); raw ``render()`` callers get
    no bytes attribution — they never serialized a response."""
    if self.attrib is None:
      return
    cls, level = attrib if attrib is not None else (None, 0)
    self.attrib.record_bytes(scene_id, cls, level, nbytes=int(nbytes))

  def _render_scheduled(self, scene_id: str, pose, timeout: float,
                        trace, degrade: int,
                        attrib: "tuple | None" = None) -> np.ndarray:
    """Scheduler render at the admitted degrade tier. L2+ renders at
    half resolution on-device (a quarter of the compositing FLOPs) and
    nearest-upsamples back to the full raster host-side at readback, so
    every response keeps the scene's shape contract."""
    # degrade/attrib are passed only when engaged: drop-in
    # scheduler.render replacements (fault stubs, tests) predating the
    # kwargs keep working for the paths they were written against.
    kwargs = {"degrade": min(degrade, 2)} if degrade else {}
    if self.attrib is not None and attrib is not None:
      kwargs["attrib"] = attrib
    img = self.scheduler.render(scene_id, pose, timeout=timeout,
                                trace=trace, **kwargs)
    if degrade >= 2:
      img = upsample_nearest(img, self._full_hw(scene_id))
    return img

  def render_traced(self, scene_id: str, pose, timeout: float = 60.0):
    """``render`` plus a trace: returns ``(image, trace_id)``.

    The trace id is "" when tracing is disabled (the HTTP layer still
    stamps ``X-Trace-Id`` by generating its own in that case).
    """
    tr = self.tracer.start_trace("render", scene_id=str(scene_id))
    return (self.scheduler.render(scene_id, pose, timeout=timeout,
                                  trace=tr), tr.trace_id)

  def render_async(self, scene_id: str, pose):
    """Non-blocking render; returns a ``concurrent.futures.Future``."""
    return self.scheduler.submit(scene_id, pose)

  # -- edge frame cache ---------------------------------------------------

  def _edge_meta(self, scene_id: str) -> tuple[str, np.ndarray, float,
                                               str | None]:
    """``(params_digest, intrinsics, plane_depth, content_token)``.

    The digest is the edge cache-key component. Untiled scenes fold in
    the scene's generation, so any content change retires every cached
    cell (token None — stale puts key an unreachable digest and need no
    guard). TILED scenes keep a STABLE digest — correctness comes from
    tile-addressed invalidation instead, which is what lets frames that
    never sampled a changed tile survive a reload with their ETags —
    and the token (the tile-digest hash) guards ``_edge_put`` against a
    render that raced a swap. Raises ``KeyError`` for unknown scenes
    (the same 404 contract as the scheduler path).
    """
    sid = str(scene_id)
    with self._scene_lock:
      entry = self._scene_data.get(sid)
      if entry is None:
        raise KeyError(f"unknown scene {sid!r}")
      gen = self._scene_gen.get(sid, 0)
      meta = self._tile_meta.get(sid)
      depths, intrinsics = entry[1], entry[2]
    # Representative warp depth: the geometric mean of the scene's depth
    # range — the single plane that splits typical MPI content evenly.
    d_near, d_far = float(depths.min()), float(depths.max())
    plane_depth = math.sqrt(max(d_near, 1e-6) * max(d_far, 1e-6))
    if meta is not None:
      return (f"{self._edge_base}:tiled", intrinsics, plane_depth,
              meta.scene_digest)
    return f"{self._edge_base}:g{gen}", intrinsics, plane_depth, None

  def _touched_tiles(self, scene_id: str, pose) -> frozenset | None:
    """The tile ids this pose's frustum can sample (None for untiled
    scenes) — recorded on edge entries for tile-addressed invalidation."""
    with self._scene_lock:
      meta = self._tile_meta.get(str(scene_id))
    if meta is None:
      return None
    return meta.touched_tile_ids(
        meta.touched(np.asarray(pose, np.float32)[None],
                     self.engine.convention))

  def _edge_put(self, sid: str, digest: str, cell, pose, img, intrinsics,
                plane_depth: float, token: str | None, tiles):
    """Populate the edge cell, guarded against a swap that raced the
    render: a tiled scene's digest is stable across reloads, so a stale
    put must be REFUSED (checked and inserted under the scene lock —
    either the put lands before the swap's registry update and the
    swap's tile sweep drops it, or it sees the new tile digests and
    skips). Untiled scenes need no guard: their digest carries the
    generation, so a stale put keys an unreachable digest."""
    if token is None:
      return self.edge.put(sid, digest, cell, pose, img, intrinsics,
                           plane_depth)
    with self._scene_lock:
      meta = self._tile_meta.get(sid)
      if meta is None or meta.scene_digest != token:
        return None  # scene changed mid-render: serve it, don't cache it
      return self.edge.put(sid, digest, cell, pose, img, intrinsics,
                           plane_depth, tiles=tiles)

  def render_edge(self, scene_id: str, pose, timeout: float = 60.0,
                  trace=NULL_TRACE, degrade: int = 0,
                  attrib: "tuple | None" = None) -> tuple[np.ndarray,
                                                          dict]:
    """Render through the edge frame cache -> ``(image, info)``.

    ``info``: ``{"edge": "off" | "hit" | "warp" | "miss", "etag":
    str | None, "max_age_s": int | None, "degraded": bool}``. Exact
    cell hits return the stored frame (READ-ONLY — it is shared with
    every other hit) with its strong ETag; near-misses return a fresh
    single-homography warp of the nearest cached frame (pose-specific,
    so no ETag); misses render through the scheduler and populate the
    cell. Hit and warp latencies are recorded into the same request
    metrics/SLO stream as rendered ones — the p50 drop IS the feature,
    it must be visible in ``/stats``. With the edge cache disabled this
    is exactly ``render`` (plus the ``"off"`` info), so callers can
    wire one path.

    ``degrade`` is the admitted brownout tier. It reshapes this path,
    never the cache: L3 widens the warp-tolerance (stale-while-
    overloaded — cached full-quality frames absorb traffic the device
    cannot), and a degraded MISS renders thinned/half-res and is served
    WITHOUT an ETag and WITHOUT populating the cell. The edge cache
    holds only full-quality frames, ever — a degraded frame must not
    poison the bit-exact ETag contract.
    """
    if self.edge is None:
      img = self._render_scheduled(str(scene_id), pose, timeout, trace,
                                   degrade, attrib)
      self._attrib_bytes(scene_id, attrib, img.nbytes)
      return (img, {"edge": "off", "etag": None, "max_age_s": None,
                    "degraded": degrade > 0})
    t0 = self._clock()
    try:
      # Everything before the scheduler hand-off owns the trace's error
      # edge: a 404 (unknown scene) or a failing warp happens entirely
      # up here, and the handler's promise that every X-Trace-Id
      # resolves in /debug/traces must hold for those too. Past the
      # hand-off the flight finishes the trace (finish is idempotent).
      pose = np.asarray(pose, np.float32)
      digest, intrinsics, plane_depth, token = self._edge_meta(scene_id)
      max_age = self.edge.config.max_age_s
      warp_scale = (self.brownout.config.l3_warp_scale
                    if degrade >= 3 and self.brownout is not None else 1.0)
      kind, entry, cell = self.edge.lookup(scene_id, digest, pose,
                                           warp_scale=warp_scale)
      if kind == "hit":
        span = trace.start_span("edge_hit", cell=list(cell))
        trace.end_span(span)
        self.metrics.record_request(self._clock() - t0, scene_id=scene_id,
                                    trace_id=trace.trace_id or None,
                                    **self._attrib_kwargs(attrib, "hit"))
        self._attrib_bytes(scene_id, attrib, entry.frame.nbytes)
        trace.finish()
        # An exact hit is the stored full-quality frame whatever the
        # brownout level — it keeps its strong ETag and is NOT degraded.
        return entry.frame, {"edge": "hit", "etag": entry.etag,
                             "max_age_s": max_age, "degraded": False}
      if kind == "warp":
        span = trace.start_span("edge_warp", cell=list(cell),
                                from_cell=list(entry.cell))
        img = warp_frame(entry.frame, entry.pose, pose, entry.intrinsics,
                         entry.plane_depth)
        trace.end_span(span)
        # Warp-quality telemetry (ROADMAP satellite): how far the served
        # frame's render pose was from the request. Drift here shows in
        # mpi_serve_edge_warp_pose_error BEFORE users see smeared
        # pixels, and the exemplar links the tail to a recorded trace.
        warp_trans, warp_rot_deg = pose_error(pose, entry.pose)
        self.metrics.record_warp_pose_error(
            warp_trans, warp_rot_deg, trace_id=trace.trace_id or None)
        self.metrics.record_request(self._clock() - t0, scene_id=scene_id,
                                    trace_id=trace.trace_id or None,
                                    **self._attrib_kwargs(attrib, "warp"))
        self._attrib_bytes(scene_id, attrib, img.nbytes)
        trace.finish()
        # A warp served only because L3 widened the tolerance is
        # labelled degraded; one within the base tolerance is ordinary
        # quality whatever the level.
        cfg = self.edge.config
        stale = (warp_trans > cfg.warp_max_trans
                 or warp_rot_deg > cfg.warp_max_rot_deg)
        return img, {"edge": "warp", "etag": None, "max_age_s": max_age,
                     "degraded": stale}
    except Exception as e:
      trace.finish(error=repr(e))
      raise
    # Negative cache: this view cell was shed queue-full moments ago and
    # its negative TTL has not lapsed — fail fast with the remaining TTL
    # as Retry-After instead of re-entering the saturated queue. This
    # shed costs a dict probe, not a queue slot.
    shed_remaining_s = self.edge.negative_lookup(scene_id, digest, pose)
    if shed_remaining_s is not None:
      err = QueueFullError(
          "request queue full (negative-cached view cell)")
      err.retry_after_s = shed_remaining_s
      trace.finish(error=repr(err))
      raise err
    # Miss: a real render (latency recorded by the scheduler as usual),
    # then populate the cell. First writer wins — serving the RESIDENT
    # entry's frame keeps every response consistent with the cell's one
    # strong ETag even when two misses race. Tiled scenes record the
    # frustum's tile set (captured BEFORE the render, consistent with
    # the token) so a tile-granular reload drops only dependent frames.
    tiles = self._touched_tiles(scene_id, pose) if token is not None \
        else None
    try:
      img = self._render_scheduled(str(scene_id), pose, timeout, trace,
                                   degrade, attrib)
    except QueueFullError as e:
      # Shed for real: plant the negative entry so the NEXT request for
      # this cell (and everyone piling behind it) skips the queue.
      ttl = self.edge.negative_put(scene_id, digest, pose)
      if ttl is not None and e.retry_after_s is None:
        e.retry_after_s = ttl
      raise
    self._attrib_bytes(scene_id, attrib, img.nbytes)
    if self.attrib is not None and tiles:
      # Tile-tier demand: the source tiles this miss's frustum could
      # sample (hits/warps reuse pixels — no new tile reads).
      cls, level = attrib if attrib is not None else (None, 0)
      self.attrib.record_tiles(scene_id, cls, level, tiles=len(tiles))
    if degrade > 0:
      # Degraded render: labelled, un-ETag'd, and NEVER cached — the
      # cell stays empty until a full-quality render fills it.
      return img, {"edge": "miss", "etag": None, "max_age_s": max_age,
                   "degraded": True}
    entry = self._edge_put(str(scene_id), digest, cell, pose, img,
                           intrinsics, plane_depth, token, tiles)
    if entry is None:  # a swap raced the render: correct, just uncached
      return img, {"edge": "miss", "etag": None, "max_age_s": max_age,
                   "degraded": False}
    return entry.frame, {"edge": "miss", "etag": entry.etag,
                         "max_age_s": max_age, "degraded": False}

  def render_request(self, scene_id: str, pose, request_class=None,
                     timeout: float = 60.0,
                     trace=NULL_TRACE) -> tuple[np.ndarray, dict]:
    """The brownout-aware front door: priority admission, then a render
    at the admitted degrade tier. ``info`` is ``render_edge``'s dict
    plus ``"level"`` (the brownout level this response was served
    under). With no brownout controller this is exactly ``render_edge``
    (level 0, never degraded).

    Sheds raise ``BrownoutShedError`` (a ``QueueFullError``, so the
    HTTP 503 + Retry-After arm already handles it). Brownout sheds and
    degraded serves are counted in their own metric families and NEVER
    fed to the SLO tracker as bad — shedding is the mechanism that
    brings the burn rate DOWN; counting it as failure would wedge the
    ladder at max level.
    """
    # The front door is where the request class is known — normalize it
    # here (brownout or not) so the attribution ledger's class dimension
    # reflects admission classes, not raw header strings.
    cls = brownout_mod.normalize_class(request_class)
    if self.brownout is None:
      img, info = self.render_edge(scene_id, pose, timeout=timeout,
                                   trace=trace, attrib=(cls, 0))
      info.setdefault("degraded", False)
      info["level"] = 0
      return img, info
    try:
      level = self.brownout.admit(cls)
    except brownout_mod.BrownoutShedError as e:
      self.metrics.record_brownout_shed(cls)
      trace.finish(error=repr(e))
      raise
    degrade = min(level, 3)
    img, info = self.render_edge(scene_id, pose, timeout=timeout,
                                 trace=trace, degrade=degrade,
                                 attrib=(cls, level))
    info["level"] = level
    if info.get("degraded"):
      self.metrics.record_degraded(level)
    return img, info

  def edge_cell_resident(self, scene_id: str, pose) -> tuple:
    """``(view_cell, resident?)`` for a pose — the session prefetcher's
    planning probe. Uses the edge cache's non-counting ``resident`` so
    planning reads never pollute hit/miss telemetry. ``(None, True)``
    when there is nothing to prefetch into (edge off, scene unknown)."""
    if self.edge is None:
      return None, True
    try:
      digest, _, _, _ = self._edge_meta(scene_id)
    except KeyError:
      return None, True
    pose = np.asarray(pose, dtype=np.float32)
    cell = self.edge.cell_of(pose)
    return cell, self.edge.resident(str(scene_id), digest, cell)

  def edge_revalidate(self, scene_id: str, pose,
                      if_none_match: str | None) -> str | None:
    """The matching strong ETag when ``if_none_match`` still identifies
    the request's view cell (HTTP 304: skip the render AND the body),
    else None. Unknown scenes return None — the render path owns 404."""
    if self.edge is None or not if_none_match:
      return None
    try:
      digest, _, _, _ = self._edge_meta(scene_id)
    except KeyError:
      return None
    return self.edge.revalidate(scene_id, digest, np.asarray(pose, np.float32),
                                if_none_match)

  # -- observability ------------------------------------------------------

  def attrib_snapshot(self, top: int | None = None) -> dict:
    """The ``/debug/attrib`` payload: the ledger snapshot plus the
    conservation reconciliation against the metrics layer's own
    (unrounded) request/phase totals. Raises ``RuntimeError`` when the
    service was built without attribution (handlers map it to 503)."""
    if self.attrib is None:
      raise RuntimeError(
          "attribution disabled: construct RenderService with attrib "
          "(serve --attrib)")
    return self.attrib.snapshot(top=top,
                                reference=self.metrics.attrib_reference())

  def _render_metrics_text(self) -> str:
    text = prom.render_serve_metrics(self.stats(),
                                     self.metrics.latency_histogram())
    if self.slo is not None:
      text += self.slo.metrics_text()
    # Flight-recorder families ride every exposition (zeros while the
    # knobs are off — the always-exposed convention).
    tsdb = getattr(self, "tsdb", None)
    text += tsdb_mod.registry(
        tsdb.stats() if tsdb is not None else None).render()
    shipper = getattr(self, "shipper", None)
    text += ship_mod.registry(
        shipper.stats() if shipper is not None else None).render()
    ledger = getattr(self, "attrib", None)
    text += attrib_mod.registry(
        ledger.snapshot() if ledger is not None else None).render()
    incidents = getattr(self, "incidents", None)
    text += incident_mod.registry(
        incidents.stats() if incidents is not None else None).render()
    return text

  def metrics_text(self) -> str:
    """The ``/metrics`` body: Prometheus text exposition of ``stats()``,
    memoized for ``metrics_ttl_s`` (scrape storms cost one render)."""
    return self._metrics_cache.get()

  def profile(self, seconds: float) -> dict:
    """Capture a device profile of live traffic (``/debug/profile``).

    With a ``profile_hook``, the finished capture's directory is handed
    to it (artifact upload); a failing hook is counted and reported in
    the response — never fatal, the capture on disk is still good.
    """
    if self.profiler is None:
      raise RuntimeError(
          "profiling disabled: construct RenderService with profile_dir "
          "(serve --profile-dir)")
    result = self.profiler.capture(seconds)
    if self.profile_hook is not None:
      try:
        self.profile_hook(result["logdir"])
        result["hook"] = "ok"
      except Exception as e:  # noqa: BLE001 - upload failure is not capture failure
        self.profile_hook_failures += 1
        result["hook"] = f"failed: {e}"
        self.events.emit("profile_hook_failed", logdir=result["logdir"],
                         error=repr(e))
    return result

  def stats(self) -> dict:
    out = self.metrics.snapshot(cache_stats=self.cache.stats())
    out.setdefault("pipeline", {})["max_inflight"] = \
        self.scheduler.max_inflight
    adaptive = self.scheduler.adaptive_snapshot()
    if adaptive is not None:
      out["pipeline"]["adaptive"] = adaptive
    if self.edge is not None:
      out["edge"] = self.edge.stats()
    if self.tile is not None:
      out["tiles"]["tile"] = self.tile
      with self._scene_lock:
        out["tiles"]["scenes_tiled"] = len(self._tile_meta)
      with self._crop_lock:
        out["tiles"]["crop_memo"] = {"entries": len(self._crop_memo),
                                     "cap": _CROP_MEMO_CAP,
                                     "bytes": self._crop_memo_bytes,
                                     "byte_budget":
                                         self._crop_memo_budget}
      out["tile_cache"] = self._tile_cache.stats()
    if self.assets is not None:
      out["assets"]["cache"] = self.assets.stats()
    out["engine"] = self.engine.describe()
    if self.resilient is not None:
      out["breaker"] = self.resilient.breaker.snapshot()
    if self.slo is not None:
      out["slo"] = self.slo.snapshot()
    if self.brownout is not None:
      # Overlay the controller's live state onto the metrics block (the
      # snapshot's counters stay — they are the shed/degrade history).
      out["brownout"].update(self.brownout.snapshot())
    if self.sessions is not None:
      # Same overlay contract as brownout: live state from the manager,
      # lifecycle/prefetch counters stay from the metrics snapshot.
      out["session"].update(self.sessions.snapshot())
    out["events"] = {"emitted": self.events.emitted,
                     "dropped": self.events.dropped,
                     "sink_errors": self.events.sink_errors}
    if self.tsdb is not None:
      out["tsdb"] = self.tsdb.stats()
    if self.shipper is not None:
      out["ship"] = self.shipper.stats()
    if self.attrib is not None:
      out["attrib"] = self.attrib_snapshot()
    if self.incidents is not None:
      out["incidents"] = self.incidents.stats()
    if self.profiler is not None:
      out["profile"] = {"captures": self.profiler.captures,
                        "hook_failures": self.profile_hook_failures}
    if self.alert_hook is not None:
      with self._alert_hook_lock:
        out["alert_hook"] = {"runs": self.alert_hook_runs,
                             "failures": self.alert_hook_failures}
    return out

  def events_snapshot(self, recent: int = 128,
                      kind: str | None = None) -> dict:
    """The ``/debug/events`` payload, with the retention story closed:
    the ring's snapshot (plus the sink's rotation accounting) and — with
    a shipper attached — how many rotated segments made it off-host vs.
    are still waiting on disk."""
    out = self.events.snapshot(recent=recent, kind=kind)
    if self.shipper is not None:
      ship_stats = self.shipper.stats()
      out.setdefault("retention", {})["shipped"] = {
          "segments_shipped": ship_stats["segments_shipped"],
          "segments_pending": self.shipper.pending_segments(),
          "segment_errors": ship_stats["segment_errors"],
      }
    return out

  def healthz(self) -> dict:
    """The health state machine: ok / degraded / unhealthy + reason.

    ``degraded`` means the service still answers but not at full
    fidelity: the breaker has given up on the primary device and
    requests either ride the CPU fallback or fast-fail 503 — or an SLO
    burn-rate alert is firing (the service answers, but it is failing
    its objectives fast enough to page; the ``reason`` says which
    objective and how hot the burn). A wedged or dead dispatcher is
    ``unhealthy`` — before the watchdog existed, exactly that state kept
    reporting ``ok`` forever.
    """
    out = {
        "devices": len(self.engine.devices),
        "platform": self.engine.platform,
        "scenes": len(self.scene_ids()),
    }
    breaker = self.resilient.breaker if self.resilient is not None else None
    snap = breaker.snapshot() if breaker is not None else None
    slo_firing = self.slo.alerts_firing() if self.slo is not None else []
    slo_reason = None
    if slo_firing:
      snap_slo = self.slo.snapshot()
      parts = []
      for name in slo_firing:
        if ":" in name:
          # Per-scene quantile alert ("latency_p99:scene_007"): the
          # windowed quantile lives in the per_scene block.
          base, _, scene = name.partition(":")
          entry = (snap_slo.get("per_scene") or {}).get(scene)
          thr_ms = snap_slo["objectives"].get(base, {}).get("threshold_ms")
          q_ms = entry["fast"]["quantile_ms"] if entry is not None else None
          if q_ms is not None and thr_ms is not None:
            parts.append(f"{name} at {q_ms:g}ms (> {thr_ms:g}ms)")
          else:
            parts.append(name)
          continue
        obj = snap_slo["objectives"][name]
        if "quantile" in obj:
          q_ms = obj["fast"]["quantile_ms"]
          parts.append(
              f"{name} at {q_ms:g}ms (> {obj['threshold_ms']:g}ms "
              "threshold)" if q_ms is not None else name)
        else:
          parts.append(f"{name} burning at {obj['fast']['burn_rate']:g}x "
                       f"(>= {snap_slo['config']['burn_threshold']:g}x "
                       f"of a {obj['target']:g} target)")
      slo_reason = "SLO alert firing: " + "; ".join(parts)
    if self._closed:
      status, reason = "unhealthy", "service closed"
    elif not self.scheduler.dispatcher_alive():
      status, reason = "unhealthy", "dispatcher thread is not running"
    elif snap is not None and snap["state"] != CircuitBreaker.CLOSED:
      status = "degraded"
      reason = (f"circuit {snap['state']} after "
                f"{snap['consecutive_failures']} consecutive device "
                f"failures; ")
      reason += ("rendering on CPU fallback"
                 if self.fallback_engine is not None
                 else "fast-failing renders (503)")
      if slo_reason is not None:
        reason += "; " + slo_reason
    elif slo_firing:
      # A firing burn-rate alert is degraded, not unhealthy: the service
      # still answers (killing it over a latency regression would turn a
      # partial failure into a total one), but probes and the cluster
      # router must see that objectives are being missed.
      status, reason = "degraded", slo_reason
    else:
      status, reason = "ok", None
    out["status"] = status
    if reason is not None:
      out["reason"] = reason
    if self.slo is not None:
      out["slo_alerts_firing"] = slo_firing
    if snap is not None:
      out["breaker"] = snap
      out["fallback_active"] = (
          self.fallback_engine is not None
          and snap["state"] != CircuitBreaker.CLOSED)
    return out

  def close(self) -> None:
    if not self._closed:
      self._closed = True
      if self.tsdb is not None:
        self.tsdb.stop()
      # Incidents stop BEFORE the shipper: the stop sentinel lands
      # behind queued fire edges, so a capture racing close still
      # reaches disk AND still hands its bundle to a live shipper.
      if self.incidents is not None:
        self.incidents.stop()
      if self.shipper is not None:
        self.shipper.stop()
      # Sessions stop before the scheduler: their drain loops submit
      # into it, and closing them first lets in-flight frames finish.
      if self.sessions is not None:
        self.sessions.close_all()
      self.scheduler.stop()
      with self._alert_hook_lock:
        hook_queue = self._alert_hook_queue
      if hook_queue is not None:
        hook_queue.put(None)  # let the alert-hook worker exit

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


# A /render body is a scene id + 4x4 pose (< 1 KB); anything near this cap
# is malformed or hostile, and the handler must not buffer it.
_MAX_BODY_BYTES = 1 << 20

# Assembled-crop memo entries retained per service (serve/tiles.py): the
# steady-state signatures of live traffic are few (view cells cluster),
# and each entry duplicates its crop's bytes on device — keep it small.
_CROP_MEMO_CAP = 32

# W3C traceparent: version, 32-hex trace-id, 16-hex parent span id,
# 2-hex flags (https://www.w3.org/TR/trace-context/). Spec requires
# lowercase hex; all-zero trace-id / parent-id are invalid. Versions above
# "00" may append dash-separated fields after the flags — receivers must
# still parse the version-00 prefix — while version "00" itself is exactly
# four fields.
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})(-.+)?$")


def _inbound_trace_id(headers) -> str | None:
  """The trace-id of a valid inbound ``traceparent`` header, else None.

  Honoring it means a fronting proxy/mesh sees ITS trace-id echoed in
  ``X-Trace-Id`` and recorded at ``/debug/traces`` — distributed traces
  stitch without translation (ROADMAP observability follow-on). Invalid
  headers are ignored (fresh id), never rejected: tracing must not be
  able to fail a render."""
  value = headers.get("traceparent")
  if value is None:
    return None
  m = _TRACEPARENT_RE.match(value.strip())
  if m is None or m.group(1) == "ff":
    return None
  if m.group(5) is not None and m.group(1) == "00":
    return None  # version 00 forbids trailing fields
  trace_id, parent_id = m.group(2), m.group(3)
  if trace_id == "0" * 32 or parent_id == "0" * 16:
    return None
  return trace_id


# Asset-tier routes (serve/assets/): the digest is 64 lowercase sha256
# hex — anything else is a 404, never a lookup.
_ASSET_PATH_RE = re.compile(r"^/scene/([^/]+)/asset/([0-9a-f]{64})$")
_SCENE_PATH_RE = re.compile(r"^/scene/([^/]+)/(manifest|viewer)$")


class _Handler(BaseHTTPRequestHandler):
  """One request per thread (ThreadingHTTPServer); blocking on the
  scheduler future is what feeds concurrent HTTP load into one batch."""

  service: RenderService  # bound via functools.partial in make_http_server

  def __init__(self, service: RenderService, *args, **kwargs):
    self.service = service
    super().__init__(*args, **kwargs)

  def log_message(self, fmt, *args):  # noqa: A003 - stdlib signature
    pass  # request logging is the metrics layer's job, not stderr's

  def _send_bytes(self, body: bytes, status: int = 200,
                  content_type: str = "application/json",
                  extra_headers: dict | None = None) -> None:
    # A client that hangs up mid-response (routine under load-shed: it
    # timed out first) must cost a counter, not a stderr traceback from
    # the handler thread.
    try:
      self.send_response(status)
      self.send_header("Content-Type", content_type)
      self.send_header("Content-Length", str(len(body)))
      for key, value in (extra_headers or {}).items():
        self.send_header(key, value)
      self.end_headers()
      self.wfile.write(body)
    except (BrokenPipeError, ConnectionResetError):
      self.service.metrics.record_client_disconnect()
      self.close_connection = True

  def _send_json(self, payload: dict, status: int = 200,
                 extra_headers: dict | None = None) -> None:
    self._send_bytes(json.dumps(payload).encode(), status=status,
                     extra_headers=extra_headers)

  def do_GET(self):  # noqa: N802 - stdlib name
    parsed = urllib.parse.urlsplit(self.path)
    if parsed.path == "/healthz":
      health = self.service.healthz()
      # Status-code probes (k8s httpGet, LB health checks) never read the
      # body: unhealthy must be non-2xx. Degraded stays 200 — the service
      # is still answering (fallback or fast-fail), don't get it killed.
      self._send_json(health,
                      status=503 if health["status"] == "unhealthy" else 200)
    elif parsed.path == "/stats":
      self._send_json(self.service.stats())
    elif parsed.path == "/metrics":
      # Default: classic text format, exemplars STRIPPED — a `#` after
      # the value is a parse error that fails a vanilla Prometheus
      # scrape wholesale. ?exemplars=1 (the cluster router's scrape,
      # OpenMetrics-aware collectors) serves them inline.
      text = self.service.metrics_text()
      query = urllib.parse.parse_qs(parsed.query)
      if query.get("exemplars", ["0"])[0] not in ("1", "true"):
        text = prom.strip_exemplars(text)
      self._send_bytes(
          text.encode(),
          content_type="text/plain; version=0.0.4; charset=utf-8")
    elif parsed.path == "/debug/traces":
      # ?id=<trace_id> searches the retained traces for one id (ring +
      # slowest exemplars) — the single-trace view the cluster router
      # fans out to stitch cross-process trees.
      query = urllib.parse.parse_qs(parsed.query)
      tid = query.get("id", [None])[0]
      if tid:
        self._send_json({"trace_id": tid,
                         "traces": self.service.tracer.find(tid)})
      else:
        self._send_json(self.service.tracer.snapshot())
    elif parsed.path == "/debug/events":
      query = urllib.parse.parse_qs(parsed.query)
      kind = query.get("kind", [None])[0]
      try:
        recent = int(query.get("recent", ["128"])[0])
      except ValueError:
        self._send_json({"error": "recent must be an integer"}, status=400)
        return
      self._send_json(self.service.events_snapshot(recent=recent,
                                                   kind=kind))
    elif parsed.path == "/debug/tsdb":
      self._do_tsdb(parsed.query)
    elif parsed.path == "/debug/attrib":
      self._do_attrib(parsed.query)
    elif parsed.path == "/debug/incidents":
      self._do_incidents(parsed.query)
    elif parsed.path == "/debug/profile":
      self._do_profile(parsed.query)
    elif parsed.path == "/scenes":
      # The asset tier's discovery endpoint: what a SceneFetcher sweeps.
      self._send_json({"scenes": self.service.scene_ids()})
    elif parsed.path.startswith("/scene/"):
      self._do_scene(parsed.path)
    else:
      self._send_json({"error": f"unknown path {self.path}"}, status=404)

  def _if_none_match(self, etag: str) -> bool:
    header = self.headers.get("If-None-Match", "")
    return etag in (tok.strip() for tok in header.split(","))

  def _do_scene(self, path: str) -> None:
    """Asset-tier GETs: ``/scene/{id}/manifest`` (revalidatable JSON),
    ``/scene/{id}/asset/{digest}`` (immutable content-addressed bytes),
    ``/scene/{id}/viewer`` (the layer-compositing browser viewer)."""
    svc = self.service
    asset = _ASSET_PATH_RE.match(path)
    scene = _SCENE_PATH_RE.match(path)
    if (asset is None and scene is None) or svc.assets is None:
      self._send_json({"error": f"unknown path {self.path}"}, status=404)
      return
    if asset is not None:
      sid = urllib.parse.unquote(asset.group(1))
      digest = asset.group(2)
      etag = assets_mod.asset_etag(digest)
      headers = {"ETag": etag,
                 "Cache-Control": assets_mod.ASSET_CACHE_CONTROL,
                 "X-Scene-Id": sid}
      if self._if_none_match(etag):
        # Immutable means ANY cached copy is current: revalidations
        # match on the digest alone, no scene lookup at all.
        svc.metrics.record_asset_request("asset", "not_modified")
        self._send_bytes(b"", status=304, extra_headers=headers)
        return
      try:
        body, meta = svc.scene_asset(sid, digest)
      except (KeyError, assets_mod.AssetIntegrityError):
        svc.metrics.record_asset_request("asset", "not_found")
        self._send_json({"error": f"unknown asset {digest[:12]}"},
                        status=404)
        return
      svc.metrics.record_asset_request("asset", "ok", nbytes=len(body))
      headers["X-Asset-Encoding"] = meta["encoding"]
      self._send_bytes(body, content_type=meta["content_type"],
                       extra_headers=headers)
      return
    sid = urllib.parse.unquote(scene.group(1))
    kind = scene.group(2)
    try:
      if kind == "manifest":
        man = svc.scene_manifest(sid)
        body = svc.assets.manifest_bytes(man)
        token, ctype = man["scene_digest"], "application/json"
      else:
        html, token = svc.scene_viewer_html(sid)
        body, ctype = html.encode(), "text/html; charset=utf-8"
    except KeyError:
      svc.metrics.record_asset_request("manifest", "not_found")
      self._send_json({"error": f"unknown scene {sid!r}"}, status=404)
      return
    etag = assets_mod.manifest_etag(token)
    # The manifest names the CURRENT generation: always revalidate
    # (no-cache), always cheap (304 against the scene digest).
    headers = {"ETag": etag, "Cache-Control": "no-cache",
               "X-Scene-Id": sid}
    if self._if_none_match(etag):
      svc.metrics.record_asset_request("manifest", "not_modified")
      self._send_bytes(b"", status=304, extra_headers=headers)
      return
    svc.metrics.record_asset_request("manifest", "ok", nbytes=len(body))
    self._send_bytes(body, content_type=ctype, extra_headers=headers)

  def _do_tsdb(self, query: str) -> None:
    """``/debug/tsdb?family=&recent=&points=``: windowed history from
    the on-box time-series ring. Without ``family``, the index: resident
    family names + recorder stats."""
    if self.service.tsdb is None:
      self._send_json(
          {"error": "tsdb disabled: construct RenderService with tsdb "
                    "(serve --tsdb-interval-s)"}, status=503)
      return
    try:
      family, recent, points = tsdb_mod.parse_query(
          urllib.parse.parse_qs(query))
    except ValueError:
      self._send_json({"error": "recent must be a number and points an "
                                "integer"}, status=400)
      return
    if family:
      self._send_json(self.service.tsdb.query(family, recent_s=recent,
                                              points=points))
    else:
      self._send_json({"families": self.service.tsdb.families(),
                       "stats": self.service.tsdb.stats()})

  def _do_attrib(self, query: str) -> None:
    """``/debug/attrib?top=K``: the resource-attribution ledger plus
    the conservation reconciliation against the metrics totals."""
    if self.service.attrib is None:
      self._send_json(
          {"error": "attribution disabled: construct RenderService with "
                    "attrib (serve --attrib)"}, status=503)
      return
    try:
      raw = urllib.parse.parse_qs(query).get("top", [None])[0]
      top = int(raw) if raw is not None else None
    except ValueError:
      self._send_json({"error": "top must be an integer"}, status=400)
      return
    self._send_json(self.service.attrib_snapshot(top=top))

  def _do_incidents(self, query: str) -> None:
    """``/debug/incidents``: the bundle ring index (newest first) +
    recorder stats; ``?id=incident-NNNNNN`` fetches one full bundle."""
    if self.service.incidents is None:
      self._send_json(
          {"error": "incidents disabled: construct RenderService with "
                    "incidents (serve --incident-dir)"}, status=503)
      return
    iid = urllib.parse.parse_qs(query).get("id", [None])[0]
    if iid:
      try:
        self._send_json(self.service.incidents.get(iid))
      except KeyError:
        self._send_json({"error": f"unknown incident {iid!r}"},
                        status=404)
      return
    self._send_json({"incidents": self.service.incidents.list(),
                     "stats": self.service.incidents.stats()})

  def _do_profile(self, query: str) -> None:
    try:
      seconds = float(
          urllib.parse.parse_qs(query).get("seconds", ["1.0"])[0])
    except ValueError:
      self._send_json({"error": "seconds must be a number"}, status=400)
      return
    try:
      # Blocks this handler thread for the capture window — render
      # traffic keeps flowing on the other threads, which is the point:
      # the profile shows live serving, not an idle device.
      self._send_json(self.service.profile(seconds))
    except ValueError as e:
      self._send_json({"error": str(e)}, status=400)
    except ProfileBusyError as e:
      self._send_json({"error": str(e)}, status=409,
                      extra_headers={"Retry-After": "1"})
    except RuntimeError as e:  # profiling not configured
      self._send_json({"error": str(e)}, status=503)

  def _do_session(self):
    """POST /session: one long-lived pose-in / frame-out exchange.

    The JSON hello body rides the normal validation path (same length
    cap and scene-id rules as /render); after the 200 the socket
    switches to length-prefixed binary frames (serve/session/protocol).
    Malformed pose streams close the session cleanly — an in-stream
    error frame then the end frame, never a 500 and never a dead
    dispatcher (the fuzz pin).
    """
    svc = self.service
    inbound_tid = _inbound_trace_id(self.headers)
    tid = inbound_tid or new_trace_id()
    tid_hdr = {"X-Trace-Id": tid}
    if svc.sessions is None:
      self._send_json(
          {"error": "sessions disabled: construct RenderService with "
                    "session= (serve --session)"},
          status=503, extra_headers=tid_hdr)
      return
    try:
      length = int(self.headers.get("Content-Length", "0"))
      if not 0 <= length <= _MAX_BODY_BYTES:
        raise ValueError(f"bad body length ({length} bytes)")
      req = json.loads(self.rfile.read(length) or b"{}")
      if not isinstance(req, dict):
        raise ValueError(f"body must be a JSON object, got {type(req).__name__}")
      scene_id = req["scene_id"]
      if not isinstance(scene_id, str):
        raise ValueError(
            f"scene_id must be a string, got {type(scene_id).__name__}")
      if any(ord(c) < 0x20 for c in scene_id):
        raise ValueError("scene_id must not contain control characters")
    except (KeyError, TypeError, ValueError, json.JSONDecodeError) as e:
      self._send_json({"error": f"bad request: {e}"}, status=400,
                      extra_headers=tid_hdr)
      return
    except (BrokenPipeError, ConnectionResetError):
      svc.metrics.record_client_disconnect()
      self.close_connection = True
      return
    if svc.scene_entry(scene_id) is None:
      self._send_json({"error": f"unknown scene {scene_id!r}"},
                      status=404, extra_headers=tid_hdr)
      return
    try:
      h, w = svc._full_hw(scene_id)
    except KeyError:
      self._send_json({"error": f"unknown scene {scene_id!r}"},
                      status=404, extra_headers=tid_hdr)
      return
    try:
      session = svc.sessions.open(
          scene_id,
          request_class=self.headers.get(brownout_mod.REQUEST_CLASS_HEADER))
    except session_mod.SessionLimitError as e:
      self._send_json(
          {"error": str(e), "retry_after_s": e.retry_after_s}, status=503,
          extra_headers={"Retry-After": str(max(1, math.ceil(e.retry_after_s))),
                         **tid_hdr})
      return
    # The exchange owns the socket from here: stream with no
    # Content-Length, and never reuse the connection afterwards.
    self.close_connection = True
    try:
      # Frames are small and interactive; Nagle + delayed ACK would
      # stall the stream for tens of milliseconds per exchange.
      self.connection.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
      pass
    try:
      self.send_response(200)
      self.send_header("Content-Type", "application/octet-stream")
      self.send_header("X-Trace-Id", tid)
      self.send_header("X-Session-Id", session.session_id)
      self.send_header("Connection", "close")
      self.end_headers()
      self.wfile.write(session_protocol.pack_hello(
          session.session_id, scene_id, (h, w, 3)))
      self.wfile.flush()
      session.serve_stream(self.rfile, self.wfile)
    except (BrokenPipeError, ConnectionResetError):
      svc.metrics.record_client_disconnect()
    finally:
      session.close(session.close_reason)

  def do_POST(self):  # noqa: N802 - stdlib name
    if self.path == "/session":
      self._do_session()
      return
    if self.path != "/render":
      self._send_json({"error": f"unknown path {self.path}"}, status=404)
      return
    # Every /render response — success, 4xx, 5xx — carries X-Trace-Id so
    # a client-reported failure is greppable in logs and /debug/traces.
    # An inbound W3C traceparent wins (proxy trace stitching); bad
    # requests never reach the tracer (nothing to trace) and reuse the
    # same id for their error response.
    inbound_tid = _inbound_trace_id(self.headers)
    tid_hdr = {"X-Trace-Id": inbound_tid or new_trace_id()}
    try:
      length = int(self.headers.get("Content-Length", "0"))
      if not 0 <= length <= _MAX_BODY_BYTES:
        # Negative lengths would turn rfile.read into a block-until-EOF
        # on a held-open socket — the same thread-leak DoS as oversize.
        raise ValueError(f"bad body length ({length} bytes)")
      req = json.loads(self.rfile.read(length) or b"{}")
      if not isinstance(req, dict):
        raise ValueError(f"body must be a JSON object, got {type(req).__name__}")
      scene_id = req["scene_id"]
      if not isinstance(scene_id, str):
        # A dict/list scene id would detonate as an unhashable key deep
        # inside the dispatcher — reject it at the door (fuzz pin).
        raise ValueError(
            f"scene_id must be a string, got {type(scene_id).__name__}")
      if any(ord(c) < 0x20 for c in scene_id):
        # Control characters are never legitimate scene ids, and \x1f
        # specifically is the tile/crop key separator (serve/tiles.py,
        # cluster/ring.py): letting it through would let a client
        # smuggle batch-key/ring-key tokens inside a scene id.
        raise ValueError("scene_id must not contain control characters")
      pose = np.asarray(req["pose"], np.float32)
      if pose.shape != (4, 4):
        raise ValueError(f"pose must be 4x4, got {pose.shape}")
      if not np.isfinite(pose).all():
        raise ValueError("pose contains non-finite values")
    except (KeyError, TypeError, ValueError, json.JSONDecodeError) as e:
      self._send_json({"error": f"bad request: {e}"}, status=400,
                      extra_headers=tid_hdr)
      return
    except (BrokenPipeError, ConnectionResetError):
      # Client hung up mid-upload: nothing to respond to — count it like
      # a mid-response disconnect instead of letting socketserver dump a
      # traceback.
      self.service.metrics.record_client_disconnect()
      self.close_connection = True
      return
    edge_on = self.service.edge is not None
    if edge_on:
      # Revalidation BEFORE any render work: a matching strong ETag
      # means the client's cached bytes are still exactly the cell's
      # resident frame, so the whole request costs one dict lookup.
      etag = self.service.edge_revalidate(
          scene_id, pose, self.headers.get("If-None-Match"))
      if etag is not None:
        max_age = self.service.edge.config.max_age_s
        self._send_bytes(b"", status=304, extra_headers={
            "ETag": etag, "Cache-Control": f"max-age={max_age}",
            "X-Edge-Cache": "revalidated", **tid_hdr})
        return
    # The handler owns the trace (not render_traced) so error responses
    # carry the same id the recorded trace has in /debug/traces.
    tr = self.service.tracer.start_trace("render", trace_id=inbound_tid,
                                         scene_id=str(scene_id), http=True)
    if tr.trace_id:
      tid_hdr = {"X-Trace-Id": tr.trace_id}
    bo_on = self.service.brownout is not None
    # The attribution ledger also needs the class-aware path: with only
    # --attrib on, the plain render() branch would drop X-Request-Class
    # and every cell would land "unlabeled".
    attrib_on = self.service.attrib is not None
    try:
      if edge_on or bo_on or attrib_on:
        img, edge_info = self.service.render_request(
            scene_id, pose,
            request_class=self.headers.get(brownout_mod.REQUEST_CLASS_HEADER),
            trace=tr)
        tid_hdr = dict(tid_hdr)
        if edge_on:
          tid_hdr["X-Edge-Cache"] = edge_info["edge"]
          tid_hdr["Cache-Control"] = f"max-age={edge_info['max_age_s']}"
          if edge_info["etag"] is not None:
            tid_hdr["ETag"] = edge_info["etag"]
        if bo_on:
          tid_hdr[brownout_mod.LEVEL_HEADER] = str(edge_info["level"])
        if edge_info.get("degraded"):
          # Degraded frames are always labelled and must never be
          # cached by any intermediary — they carry no ETag and the
          # no-store overrides any edge max-age set above.
          tid_hdr[brownout_mod.DEGRADED_HEADER] = "1"
          tid_hdr["Cache-Control"] = "no-store"
      else:
        img = self.service.render(scene_id, pose, trace=tr)
    except KeyError as e:
      self._send_json({"error": str(e)}, status=404,
                      extra_headers=tid_hdr)
      return
    except QueueFullError as e:
      # Shed at the door. A negative-cache fast shed knows when the cell
      # clears; a raw queue-full shed advises the standard 1s backoff; a
      # brownout shed additionally names the ladder level that refused
      # the request's class.
      if isinstance(e, brownout_mod.BrownoutShedError):
        tid_hdr = {brownout_mod.LEVEL_HEADER: str(e.level), **tid_hdr}
      if e.retry_after_s is not None:
        retry_after = max(1, math.ceil(e.retry_after_s))
        self._send_json({"error": str(e), "retry_after_s": e.retry_after_s},
                        status=503,
                        extra_headers={"Retry-After": str(retry_after),
                                       **tid_hdr})
      else:
        self._send_json({"error": str(e)}, status=503,
                        extra_headers={"Retry-After": "1", **tid_hdr})
      return
    except CircuitOpenError as e:
      # Fast-fail while the device is known-bad: tell the client exactly
      # when the next half-open probe could let it back in.
      retry_after = max(1, math.ceil(e.retry_after_s))
      self._send_json({"error": str(e), "retry_after_s": e.retry_after_s},
                      status=503,
                      extra_headers={"Retry-After": str(retry_after),
                                     **tid_hdr})
      return
    except TransientDeviceError as e:
      if getattr(e, "deadline_capped", False):
        # The DEADLINE bounded this failure, not the device: overload is
        # a 504, telling the client the device is flaky would misdirect.
        self._send_json({"error": f"request deadline exceeded: {e}"},
                        status=504, extra_headers=tid_hdr)
      else:
        self._send_json({"error": f"transient device failure: {e}"},
                        status=503,
                        extra_headers={"Retry-After": "1", **tid_hdr})
      return
    except FuturesTimeoutError:
      self._send_json({"error": "render timed out in queue"}, status=504,
                      extra_headers=tid_hdr)
      return
    except Exception as e:  # noqa: BLE001 - surfaced to the client
      self._send_json({"error": f"render failed: {e}"}, status=500,
                      extra_headers=tid_hdr)
      return
    img = np.ascontiguousarray(img, np.dtype("<f4"))
    if "application/octet-stream" in self.headers.get("Accept", ""):
      # Binary response: raw little-endian f32 pixels, shape/dtype in
      # headers — half the bytes of base64-in-JSON at 1080p (ROADMAP).
      self._send_bytes(
          img.tobytes(), content_type="application/octet-stream",
          extra_headers={
              "X-Image-Shape": ",".join(str(d) for d in img.shape),
              "X-Image-Dtype": "<f4",
              "X-Scene-Id": str(scene_id),
              **tid_hdr,
          })
      return
    self._send_json({
        "scene_id": scene_id,
        "shape": list(img.shape),
        "dtype": "<f4",
        "image_b64": base64.b64encode(img.tobytes()).decode(),
    }, extra_headers=tid_hdr)


def make_http_server(service: RenderService, host: str = "127.0.0.1",
                     port: int = 0) -> ThreadingHTTPServer:
  """A ready-to-``serve_forever`` threaded HTTP server (port 0 = ephemeral;
  the bound port is ``server.server_address[1]``)."""
  handler = functools.partial(_Handler, service)
  server = ThreadingHTTPServer((host, port), handler)
  server.daemon_threads = True
  return server
