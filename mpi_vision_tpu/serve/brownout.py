"""SLO-driven brownout: degrade, don't die.

Under sustained overload a backend has historically had two answers:
full-quality render or 503. MPI rendering has a middle path — quality
degrades smoothly with plane count and output resolution — so this
module turns the existing overload signals (SLO fast-window burn rate,
``obs/slo.py``; scheduler queue occupancy) into a **degradation
ladder**:

  * **L0** — full render, bit-identical to a service without brownout.
  * **L1** — reduced-plane compositing: the tile planner's content-culled
    plane list is thinned to ``plane_keep`` of its planes
    (``tiles.thin_planes``), reusing the PR 13 plane-subset render plan.
  * **L2** — half-resolution render, nearest-neighbour upsampled at
    readback (``engine.upsample_nearest``) on top of L1.
  * **L3** — stale-while-overloaded edge serving: the edge cache's warp
    tolerance widens by ``l3_warp_scale`` so nearby cached full-quality
    frames absorb traffic that would otherwise render; actual renders
    stay at L2 cost.
  * **L4** — shed with ``Retry-After`` (everything, not just low
    priority).

**Hysteresis**: levels step down one at a time (``step_dwell_s`` between
consecutive steps; the first descent from a healthy level is immediate)
and recover one at a time only after the fast window has read healthy
continuously for ``recover_dwell_s``. The band between "overloaded" and
"healthy" holds the current level AND restarts the healthy timer, so the
ladder cannot flap across a noisy threshold.

**Priority admission**: requests carry a class (``X-Request-Class``:
interactive / prefetch / background — the router forwards it, the scene
fetcher and edge prefetch paths mark themselves background) and higher
ladder levels shed lower-priority classes first: background at L2+,
prefetch at L3+, interactive only at L4.

**The recovery contract**: brownout sheds and degraded serves are
deliberate load management, NOT outages — they are counted in their own
``mpi_serve_brownout_*`` families and are **never** fed to
``SloTracker.record_bad``. Feeding them back would pin the burn rate
high and deadlock the ladder at its deepest level forever; excluding
them is what lets the fast window read healthy again and drive recovery.

**The cache contract**: a degraded frame must never poison the bit-exact
edge-cache contract. Degraded responses are always labelled
(``X-Degraded`` + ``X-Brownout-Level``), never ``put`` into the edge
cache, and never carry (or validate against) a full-quality ETag — the
edge tier only ever holds L0 bytes, which is exactly why serving from it
at L3 is safe.

Clock discipline: every timestamp comes through the injected ``clock``
(the serve/-wide rule; tests drive the ladder on fake clocks).
"""

from __future__ import annotations

import dataclasses
import threading
import time

from mpi_vision_tpu.serve import tiles as tiles_mod
from mpi_vision_tpu.serve.scheduler import QueueFullError

# The request-priority header (request AND forwarded by the router).
REQUEST_CLASS_HEADER = "X-Request-Class"
# Response headers: the level that admitted the request, and a marker
# present exactly when the served bytes are below full quality.
LEVEL_HEADER = "X-Brownout-Level"
DEGRADED_HEADER = "X-Degraded"

# Priority classes, highest first. Unknown/absent classes normalize to
# "interactive" — an unlabelled request is a user-facing request.
REQUEST_CLASSES = ("interactive", "prefetch", "background")

MAX_LEVEL = 4

# Ladder level at which each class is shed (level >= threshold sheds).
_SHED_AT = {"background": 2, "prefetch": 3, "interactive": 4}

# Trailing batch-key field marking a half-resolution (L2+) render. The
# scheduler coalesces on key equality, so degraded and full-quality
# requests can never share a flight, a crop memo entry, or a jit bucket.
HALF_RES_TOKEN = "half"

# Families that must NOT be summed across a fleet (a pooled "level 7"
# from three backends at L2/L2/L3 is meaningless) — the router's
# aggregated /metrics drops these; per-backend levels ride /stats.
NON_ADDITIVE_FAMILIES = frozenset({"mpi_serve_brownout_level"})


def normalize_class(value) -> str:
  """Map a header value onto a known class; unknown -> interactive."""
  if value is None:
    return "interactive"
  cls = str(value).strip().lower()
  return cls if cls in REQUEST_CLASSES else "interactive"


def shed_level(request_class: str) -> int:
  """The ladder level at which ``request_class`` is shed."""
  return _SHED_AT.get(normalize_class(request_class), MAX_LEVEL)


def half_res_key(key: str) -> str:
  """Append the L2 half-resolution marker to a batch/scene key."""
  return key + tiles_mod.KEY_SEP + HALF_RES_TOKEN


def split_degrade_key(key: str) -> tuple[str, bool]:
  """Strip a trailing half-res marker: ``(base_key, is_half_res)``."""
  suffix = tiles_mod.KEY_SEP + HALF_RES_TOKEN
  if key.endswith(suffix):
    return key[:-len(suffix)], True
  return key, False


@dataclasses.dataclass(frozen=True)
class BrownoutConfig:
  """Brownout knobs (the ``serve`` CLI's ``--brownout-*`` flags map 1:1).

  ``burn_high``/``queue_high`` trigger descent (either signal past its
  threshold reads overloaded); ``recover_burn``/``recover_queue`` must
  BOTH hold for ``recover_dwell_s`` before one recovery step — the gap
  between the two threshold pairs is the hysteresis band.
  """

  burn_high: float = 2.0
  queue_high: float = 0.5
  recover_burn: float = 1.0
  recover_queue: float = 0.25
  step_dwell_s: float = 2.0
  recover_dwell_s: float = 5.0
  # Signal-evaluation rate limit: admission is per-request, the burn/
  # queue reads need not be.
  eval_interval_s: float = 0.25
  # L1: fraction of the content-culled plane list kept.
  plane_keep: float = 0.5
  # L3: multiplier on the edge cache's warp tolerances.
  l3_warp_scale: float = 3.0
  shed_retry_after_s: float = 1.0
  max_level: int = MAX_LEVEL

  def __post_init__(self):
    for name in ("burn_high", "queue_high", "recover_burn", "recover_queue",
                 "shed_retry_after_s"):
      if getattr(self, name) <= 0:
        raise ValueError(f"{name} must be > 0, got {getattr(self, name)}")
    for name in ("step_dwell_s", "recover_dwell_s", "eval_interval_s"):
      if getattr(self, name) < 0:
        raise ValueError(f"{name} must be >= 0, got {getattr(self, name)}")
    if self.recover_burn >= self.burn_high:
      raise ValueError(
          f"recover_burn ({self.recover_burn}) must be < burn_high "
          f"({self.burn_high}) — the gap IS the hysteresis band")
    if self.recover_queue >= self.queue_high:
      raise ValueError(
          f"recover_queue ({self.recover_queue}) must be < queue_high "
          f"({self.queue_high}) — the gap IS the hysteresis band")
    if not 0.0 < self.plane_keep <= 1.0:
      raise ValueError(f"plane_keep must be in (0, 1], got {self.plane_keep}")
    if self.l3_warp_scale < 1.0:
      raise ValueError(
          f"l3_warp_scale must be >= 1, got {self.l3_warp_scale}")
    if not 1 <= self.max_level <= MAX_LEVEL:
      raise ValueError(
          f"max_level must be in [1, {MAX_LEVEL}], got {self.max_level}")


class BrownoutShedError(QueueFullError):
  """A request shed by brownout admission control (HTTP 503 +
  ``Retry-After``, riding the queue-full arm). Deliberate load
  management — callers must NOT feed it to ``SloTracker.record_bad``
  (see the module docstring's recovery contract)."""

  def __init__(self, request_class: str, level: int, retry_after_s: float):
    super().__init__(
        f"brownout L{level} shed {request_class!r} request "
        f"(retry after {retry_after_s:g}s)")
    self.request_class = request_class
    self.level = int(level)
    self.retry_after_s = float(retry_after_s)


class BrownoutController:
  """The ladder state machine: signals in, admission decisions out.

  ``burn_fn`` returns the hottest SLO fast-window burn rate
  (``SloTracker.fast_burn``); ``queue_fn`` the scheduler's queue
  occupancy in [0, 1]. Both are read at most every ``eval_interval_s``
  (``tick`` is called per admission). ``on_transition(old, new, reason)``
  fires outside the lock on every level change — the service wires it to
  the event log.
  """

  def __init__(self, config: BrownoutConfig | None = None,
               burn_fn=None, queue_fn=None, on_transition=None,
               clock=time.monotonic):
    self.config = config if config is not None else BrownoutConfig()
    self._burn_fn = burn_fn
    self._queue_fn = queue_fn
    self._on_transition = on_transition
    self._clock = clock
    self._lock = threading.Lock()
    self._level = 0
    # None = never evaluated / never changed level: the first descent
    # under overload is immediate (the dwell throttles CONSECUTIVE
    # steps, it must not delay the first response to an incident).
    self._last_eval: float | None = None
    self._level_since: float | None = None
    self._healthy_since: float | None = None
    self._last_burn = 0.0
    self._last_queue = 0.0
    self.transitions_down = 0
    self.transitions_up = 0

  @property
  def level(self) -> int:
    with self._lock:
      return self._level

  def tick(self) -> int:
    """Evaluate the signals (rate-limited) and return the current level."""
    transition = None
    with self._lock:
      now = self._clock()
      cfg = self.config
      if (self._last_eval is not None
          and now - self._last_eval < cfg.eval_interval_s):
        return self._level
      self._last_eval = now
      burn = float(self._burn_fn()) if self._burn_fn is not None else 0.0
      queue = float(self._queue_fn()) if self._queue_fn is not None else 0.0
      self._last_burn, self._last_queue = burn, queue
      overloaded = burn >= cfg.burn_high or queue >= cfg.queue_high
      healthy = burn <= cfg.recover_burn and queue <= cfg.recover_queue
      if overloaded:
        self._healthy_since = None
        if self._level < cfg.max_level and (
            self._level_since is None
            or now - self._level_since >= cfg.step_dwell_s):
          transition = (self._level, self._level + 1, "overload")
          self._level += 1
          self._level_since = now
          self.transitions_down += 1
      elif healthy and self._level > 0:
        if self._healthy_since is None:
          self._healthy_since = now
        elif now - self._healthy_since >= cfg.recover_dwell_s:
          transition = (self._level, self._level - 1, "recover")
          self._level -= 1
          self._level_since = now
          self.transitions_up += 1
          # Each recovery step earns its own dwell — a 4-level climb
          # back to L0 takes 4 sustained-healthy windows, by design.
          self._healthy_since = now
      else:
        # The hysteresis band: hold the level AND restart the healthy
        # timer, so a burn hovering between the thresholds can neither
        # descend nor creep back up — no flapping.
        self._healthy_since = None
      out = self._level
    if transition is not None and self._on_transition is not None:
      self._on_transition(*transition)
    return out

  def admit(self, request_class: str) -> int:
    """Admission control for one request: returns the ladder level the
    request was admitted at (captured ONCE — the render pipeline uses
    this level even if the ladder moves mid-flight), or raises
    ``BrownoutShedError`` when the class is shed at the current level."""
    cls = normalize_class(request_class)
    level = self.tick()
    if level >= _SHED_AT[cls]:
      raise BrownoutShedError(cls, level, self.config.shed_retry_after_s)
    return level

  def snapshot(self) -> dict:
    with self._lock:
      return {
          "enabled": True,
          "level": self._level,
          "max_level": self.config.max_level,
          "transitions": {"down": self.transitions_down,
                          "up": self.transitions_up},
          "signals": {"burn": round(self._last_burn, 4),
                      "queue_fraction": round(self._last_queue, 4)},
          "thresholds": {"burn_high": self.config.burn_high,
                         "queue_high": self.config.queue_high,
                         "recover_burn": self.config.recover_burn,
                         "recover_queue": self.config.recover_queue},
      }

  def reset_counters(self) -> None:
    """Zero the transition counters (load generators call this after
    warm-up, next to ``ServeMetrics.reset``). The level itself is live
    state and stays."""
    with self._lock:
      self.transitions_down = 0
      self.transitions_up = 0


def fleet_scale_signal(summary: dict | None) -> dict:
  """Distill the router's fleet brownout summary into the autoscaler's
  scale-up signal (``serve/cluster/autoscale.py`` consumes this).

  Brownout is the bridge while capacity spawns: any backend riding a
  nonzero ladder level is already paying for overload with quality, so
  a fleet-wide nonzero ``max_level`` is a scale-up trigger on its own —
  the autoscaler's new capacity is what lets the ladder descend back to
  L0 instead of camping in degraded service. Tolerates a missing or
  partial summary (backends without the controller contribute nothing).
  """
  summary = summary or {}
  levels = summary.get("levels") or {}
  max_level = summary.get("max_level")
  if max_level is None:
    max_level = max(levels.values(), default=0)
  return {
      "max_level": int(max_level),
      "backends_browned": len(levels),
      "backends_enabled": int(summary.get("backends_enabled") or 0),
  }
