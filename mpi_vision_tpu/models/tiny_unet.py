"""Tiny per-plane RGBA predictor (DeepView-style direct MPI prediction).

BASELINE config 5's model: instead of the stereo-magnification
background+blend parameterization (models/stereo_mag.py, notebook cell 10 —
which constrains per-plane RGB to a blend of the reference image and one
background image), this small U-Net predicts every plane's RGBA directly
from the plane-sweep volume, the DeepView-family approach (the reference
repo's viewer is the "deepview" template; the model family itself has no
reference implementation, so this is new capability sized for the
train-on-a-stereo-pair benchmark).

TPU-first layout: the PSV arrives plane-major ``[B, H, W, P, C]`` and planes
fold into the batch axis — every plane is processed by the same shared-weight
network in one big batched conv (MXU-friendly: one conv over B*P images
instead of P small convs), with a few cross-plane mixing convs operating on
channels-stacked features at the bottleneck so planes can exchange occlusion
evidence.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class TinyPlaneUNet(nn.Module):
  """PSV ``[B, H, W, P, C]`` -> MPI ``[B, H, W, P, 4]`` (rgb/alpha in (0,1)-ish).

  Output RGB is tanh in [-1, 1] (image range), alpha is sigmoid in (0, 1).
  H and W must be divisible by 4 (two stride-2 stages).
  """

  width: int = 32
  mix: int = 2   # cross-plane mixing convs at the bottleneck
  dtype: Any = None  # bf16 compute on the MXU; params/output stay f32

  @nn.compact
  def __call__(self, psv: jnp.ndarray) -> jnp.ndarray:
    b, h, w, p, c = psv.shape
    x = psv.transpose(0, 3, 1, 2, 4).reshape(b * p, h, w, c)
    if self.dtype is not None:
      x = x.astype(self.dtype)

    # Shared-weight per-plane encoder (planes folded into batch).
    e0 = nn.relu(nn.Conv(self.width, (3, 3), dtype=self.dtype, name="enc0")(x))
    e1 = nn.relu(nn.Conv(self.width * 2, (3, 3), strides=(2, 2),
                         dtype=self.dtype, name="enc1")(e0))
    e2 = nn.relu(nn.Conv(self.width * 4, (3, 3), strides=(2, 2),
                         dtype=self.dtype, name="enc2")(e1))

    # Cross-plane mixing: stack plane features on channels at 1/4 res.
    m = e2.reshape(b, p, h // 4, w // 4, -1)
    m = m.transpose(0, 2, 3, 1, 4).reshape(b, h // 4, w // 4, -1)
    for i in range(self.mix):
      m = nn.relu(nn.Conv(self.width * 4 * 2, (3, 3), dtype=self.dtype, name=f"mix{i}")(m))
    m = nn.Conv(p * self.width * 4, (1, 1), dtype=self.dtype, name="unmix")(m)
    m = m.reshape(b, h // 4, w // 4, p, -1)
    m = m.transpose(0, 3, 1, 2, 4).reshape(b * p, h // 4, w // 4, -1)

    # Shared-weight decoder with skips.
    d1 = nn.relu(nn.ConvTranspose(self.width * 2, (4, 4), strides=(2, 2),
                                  dtype=self.dtype, name="dec1")(jnp.concatenate([m, e2], -1)))
    d0 = nn.relu(nn.ConvTranspose(self.width, (4, 4), strides=(2, 2),
                                  dtype=self.dtype, name="dec0")(jnp.concatenate([d1, e1], -1)))
    out = nn.Conv(4, (1, 1), dtype=self.dtype, name="head")(jnp.concatenate([d0, e0], -1))

    out = out.astype(jnp.float32)
    rgb = jnp.tanh(out[..., :3])
    alpha = nn.sigmoid(out[..., 3:])
    out = jnp.concatenate([rgb, alpha], -1)
    return out.reshape(b, p, h, w, 4).transpose(0, 2, 3, 1, 4)


def psv_from_net_input(net_input: jnp.ndarray, num_planes: int) -> jnp.ndarray:
  """Split a stereo-mag net input ``[B, H, W, 3+3P]`` into a plane-major PSV
  ``[B, H, W, P, 3]`` plus the broadcast reference image as a 4th channel
  group is NOT added — the tiny model sees (psv_rgb ++ ref_rgb) per plane."""
  b, h, w, _ = net_input.shape
  ref = net_input[..., :3]
  psv = net_input[..., 3:].reshape(b, h, w, num_planes, 3)
  ref_b = jnp.broadcast_to(ref[..., None, :], psv.shape)
  return jnp.concatenate([psv, ref_b], axis=-1)   # [B, H, W, P, 6]
