"""Stereo-magnification U-Net and MPI assembly, TPU-native (flax.linen, NHWC).

Reference: ``StereoMagnificationModel`` + ``mpi_from_net_output``
(fast-torch-stereo-vision.ipynb cell 10). Architecture preserved exactly —
channel widths as multiples of ``ngf = 3 + 3P``, three stride-2 encoder
stages, a three-conv dilation-2 bottleneck, three ks=4/s=2 transpose-conv
decoder stages with skip concats from cnv3_3 / cnv2_2 / cnv1_2, and a
norm-free 1x1 Tanh head producing ``nout = 3 + 2P`` channels — but laid out
NHWC with channels-last concats, the layout XLA tiles best onto the TPU MXU.

Normalization note: the reference passes fastai's ``InstanceNorm`` *callable*
as ``ConvLayer(norm_type=...)``, which fastai only matches against its
``NormType`` enum — so the notebook's trained network effectively contains
**no norm layers** (and biased convs). ``norm=None`` reproduces that;
``norm='instance'`` (the default here) gives the paper's stated InstanceNorm.

Weight transfer: ``params_from_torch_state`` maps a state dict of the torch
mirror (``torchref/model.py``) onto this module's params — the basis of the
cross-framework parity tests.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class InstanceNorm(nn.Module):
  """Per-sample, per-channel normalization over (H, W) with affine params.

  Matches ``torch.nn.InstanceNorm2d(C, affine=True)``: biased variance,
  eps inside the sqrt.
  """

  epsilon: float = 1e-5

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    dt = x.dtype
    x32 = x.astype(jnp.float32)   # stats in f32 even under bf16 compute
    mean = x32.mean(axis=(-3, -2), keepdims=True)
    var = x32.var(axis=(-3, -2), keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + self.epsilon)
    c = x.shape[-1]
    scale = self.param("scale", nn.initializers.ones, (c,))
    bias = self.param("bias", nn.initializers.zeros, (c,))
    return (y * scale + bias).astype(dt)


class ConvBlock(nn.Module):
  """conv -> [norm] -> activation, with torch-equivalent padding semantics.

  The reference's fastai ``ConvLayer`` (norm-before-act ordering, bn_1st):
  ks=3 convs pad by ``dilation``, the ks=4/s=2 transpose conv pads by 1
  (doubling the spatial size exactly), the ks=1 head pads 0.
  """

  features: int
  kernel: int = 3
  stride: int = 1
  dilation: int = 1
  transpose: bool = False
  norm: str | None = "instance"
  act: str | None = "relu"
  dtype: Any = None               # computation dtype; params stay f32

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    if self.transpose:
      # torch ConvTranspose2d(ks, stride, padding=1): flax/lax pads the
      # stride-dilated input by (ks - 1 - padding) per side; transpose_kernel
      # gives lax.conv_transpose the gradient-of-conv (torch) semantics.
      pad = self.kernel - 1 - 1
      x = nn.ConvTranspose(
          self.features, (self.kernel, self.kernel),
          strides=(self.stride, self.stride),
          padding=((pad, pad), (pad, pad)), transpose_kernel=True,
          dtype=self.dtype, name="conv")(x)
    else:
      pad = self.dilation * (self.kernel - 1) // 2
      x = nn.Conv(
          self.features, (self.kernel, self.kernel),
          strides=(self.stride, self.stride),
          padding=((pad, pad), (pad, pad)),
          kernel_dilation=(self.dilation, self.dilation), dtype=self.dtype,
          name="conv")(x)
    if self.norm == "instance":
      x = InstanceNorm(name="norm")(x)
    elif self.norm is not None:
      raise ValueError(f"unknown norm: {self.norm!r}")
    if self.act == "relu":
      x = nn.relu(x)
    elif self.act == "tanh":
      x = jnp.tanh(x)
    elif self.act is not None:
      raise ValueError(f"unknown act: {self.act!r}")
    return x


class StereoMagnificationModel(nn.Module):
  """U-Net predicting MPI blend weights, alphas, and a background image.

  Input ``[B, H, W, 3 + 3P]`` (reference image ++ P-plane PSV of the source
  image, channels-last), output ``[B, H, W, 3 + 2P]`` in (-1, 1):
  P blend-weight channels, P alpha channels, 3 background-RGB channels.
  H and W must be divisible by 8 (three stride-2 stages).

  Reference: notebook cell 10 (spatial sizes annotated there for 224 input).
  """

  num_planes: int = 10
  norm: str | None = "instance"
  dtype: Any = None     # computation dtype: jnp.bfloat16 runs the convs on
                        # the MXU in bf16 (params/optimizer state stay f32,
                        # norm stats and the output are f32 — the standard
                        # TPU mixed-precision layout, SURVEY.md par.7's
                        # "f32 default with bf16 option")

  @nn.compact
  def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
    ngf = 3 + self.num_planes * 3
    nout = 3 + self.num_planes * 2
    n = self.norm
    if self.dtype is not None:
      x = x.astype(self.dtype)

    c1_1 = ConvBlock(ngf, name="cnv1_1", norm=n, dtype=self.dtype)(x)
    c1_2 = ConvBlock(ngf * 2, stride=2, name="cnv1_2", norm=n, dtype=self.dtype)(c1_1)

    c2_1 = ConvBlock(ngf * 2, name="cnv2_1", norm=n, dtype=self.dtype)(c1_2)
    c2_2 = ConvBlock(ngf * 4, stride=2, name="cnv2_2", norm=n, dtype=self.dtype)(c2_1)

    c3_1 = ConvBlock(ngf * 4, name="cnv3_1", norm=n, dtype=self.dtype)(c2_2)
    c3_2 = ConvBlock(ngf * 4, name="cnv3_2", norm=n, dtype=self.dtype)(c3_1)
    c3_3 = ConvBlock(ngf * 8, stride=2, name="cnv3_3", norm=n, dtype=self.dtype)(c3_2)

    c4_1 = ConvBlock(ngf * 8, dilation=2, name="cnv4_1", norm=n, dtype=self.dtype)(c3_3)
    c4_2 = ConvBlock(ngf * 8, dilation=2, name="cnv4_2", norm=n, dtype=self.dtype)(c4_1)
    c4_3 = ConvBlock(ngf * 8, dilation=2, name="cnv4_3", norm=n, dtype=self.dtype)(c4_2)

    x5 = jnp.concatenate([c4_3, c3_3], axis=-1)
    c5_1 = ConvBlock(ngf * 4, kernel=4, stride=2, transpose=True,
                     name="cnv5_1", norm=n, dtype=self.dtype)(x5)
    c5_2 = ConvBlock(ngf * 4, name="cnv5_2", norm=n, dtype=self.dtype)(c5_1)
    c5_3 = ConvBlock(ngf * 4, name="cnv5_3", norm=n, dtype=self.dtype)(c5_2)

    x6 = jnp.concatenate([c5_3, c2_2], axis=-1)
    c6_1 = ConvBlock(ngf * 2, kernel=4, stride=2, transpose=True,
                     name="cnv6_1", norm=n, dtype=self.dtype)(x6)
    c6_2 = ConvBlock(ngf * 2, name="cnv6_2", norm=n, dtype=self.dtype)(c6_1)

    x7 = jnp.concatenate([c6_2, c1_2], axis=-1)
    c7_1 = ConvBlock(nout, kernel=4, stride=2, transpose=True,
                     name="cnv7_1", norm=n, dtype=self.dtype)(x7)
    c7_2 = ConvBlock(nout, name="cnv7_2", norm=n, dtype=self.dtype)(c7_1)

    out = ConvBlock(nout, kernel=1, norm=None, act="tanh",
                    dtype=self.dtype, name="cnv8_1")(c7_2)
    return out.astype(jnp.float32)


def mpi_from_net_output(mpi_pred: jnp.ndarray, ref_img: jnp.ndarray) -> jnp.ndarray:
  """Assemble net output into an MPI ``[B, H, W, P, 4]``.

  The paper's background+blend parameterization (notebook cell 10,
  ``mpi_from_net_output``): tanh outputs rescaled to (0, 1) give P per-plane
  blend weights and P alphas; the last 3 channels are a background RGB image;
  per-plane RGB = ``w * ref_img + (1 - w) * bg``. One vectorized broadcast
  replaces the reference's per-plane Python concat loop.

  Args:
    mpi_pred: ``[B, H, W, 3 + 2P]`` network output in (-1, 1), NHWC.
    ref_img: ``[B, H, W, 3]`` the foreground/reference image (in [-1, 1]).

  Returns:
    ``[B, H, W, P, 4]`` RGBA layers, plane index aligned with the PSV depth
    order (index 0 = farthest when built from ``camera.inv_depths``).
  """
  num_planes = (mpi_pred.shape[-1] - 3) // 2
  blend = (mpi_pred[..., :num_planes] + 1.0) / 2.0          # [B,H,W,P]
  alphas = (mpi_pred[..., num_planes:2 * num_planes] + 1.0) / 2.0
  bg_rgb = mpi_pred[..., -3:]                               # [B,H,W,3]
  w = blend[..., None]                                      # [B,H,W,P,1]
  rgb = w * ref_img[..., None, :] + (1.0 - w) * bg_rgb[..., None, :]
  return jnp.concatenate([rgb, alphas[..., None]], axis=-1)


def _conv_kernel(w: np.ndarray) -> np.ndarray:
  # torch conv [out,in,kh,kw] / convtranspose [in,out,kh,kw] -> flax
  # (kh,kw,in,out) / transpose_kernel (kh,kw,out,in): same permutation.
  return np.transpose(w, (2, 3, 1, 0))


def params_from_torch_state(state: dict[str, Any], norm: str | None = "instance"):
  """Map the torch mirror's ``state_dict()`` to this module's param pytree.

  ``state`` values may be torch tensors or numpy arrays. Blocks are named
  ``cnv1_1 .. cnv8_1`` on both sides (``torchref/model.py``).
  """
  state = {k: np.asarray(getattr(v, "detach", lambda: v)().cpu()
                         if hasattr(v, "cpu") else v)
           for k, v in state.items()}
  params: dict[str, Any] = {}
  blocks = sorted({k.split(".")[0] for k in state})
  for b in blocks:
    entry: dict[str, Any] = {
        "conv": {
            "kernel": _conv_kernel(state[f"{b}.conv.weight"]),
            "bias": state[f"{b}.conv.bias"],
        }
    }
    if norm == "instance" and f"{b}.norm.weight" in state:
      entry["norm"] = {
          "scale": state[f"{b}.norm.weight"],
          "bias": state[f"{b}.norm.bias"],
      }
    params[b] = entry
  return {"params": params}
