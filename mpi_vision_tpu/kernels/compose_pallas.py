"""Fused Pallas TPU kernel for back-to-front MPI over-compositing.

The reference's ``over_composite`` (utils.py:136-157) is a Python loop holding
the full ``[P, B, H, W, 4]`` stack in device memory and re-reading the running
``out`` every step. On TPU the op is HBM-bandwidth-bound, so the kernel is
built around streaming: planes flow HBM -> VMEM tile by tile while the running
composite lives in a VMEM f32 scratch accumulator that never round-trips to
HBM until the final plane.

Layout: compositing is elementwise over (H, W) with a 3/4-channel axis, and
TPU tiles want (sublane=8k, lane=128k) trailing dims — so the kernel operates
on a *planar* layout ``[B, P, 4, H, W]`` where (H, W) are the trailing dims
and channels are a tiny leading axis, instead of the reference's channels-last
``[..., 4]`` (which would waste 124/128 lanes). ``over_composite_pallas``
accepts the public planes-leading NHWC layout and transposes at the boundary;
producers that can emit planar directly should call the ``_planar`` variant.

Grid: ``(B, H-tiles, W-tiles, P)`` with P innermost — the TPU grid is a
sequential loop, so each (b, i, j) tile finishes all P planes while its
accumulator stays resident in VMEM, and Pallas double-buffers the incoming
plane DMAs across grid steps automatically.

Differentiation: ``pl.pallas_call`` has no automatic reverse-mode; the public
entry points carry a ``jax.custom_vjp`` whose backward re-derives gradients
from the ``lax.scan`` reference implementation (core/compose.py) — the
forward is the bandwidth-critical benchmark path, the backward stays XLA.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_vision_tpu.core import compose


def _composite_kernel(rgba_ref, out_ref, acc_ref):
  """One (b, i, j, p) grid step: fold plane p into the VMEM accumulator."""
  p = pl.program_id(3)
  rgba = rgba_ref[0, 0].astype(jnp.float32)  # [4, th, tw]
  rgb = rgba[:3]
  alpha = rgba[3:4]

  @pl.when(p == 0)
  def _init():
    # Farthest plane: alpha ignored (utils.py:152-153).
    acc_ref[:] = rgb

  @pl.when(p > 0)
  def _fold():
    acc_ref[:] = rgb * alpha + acc_ref[:] * (1.0 - alpha)

  @pl.when(p == pl.num_programs(3) - 1)
  def _emit():
    out_ref[0] = acc_ref[:].astype(out_ref.dtype)


def _pick_tiles(height: int, width: int) -> tuple[int, int]:
  """Tile sizes: cap VMEM use, prefer lane-aligned widths for large frames."""
  tile_w = width if width <= 512 else 512
  tile_h = height if height <= 256 else 256
  return tile_h, tile_w


@functools.partial(jax.jit, static_argnames=("interpret",))
def _composite_planar_call(rgba: jnp.ndarray, interpret: bool) -> jnp.ndarray:
  b, p, _, h, w = rgba.shape
  th, tw = _pick_tiles(h, w)
  grid = (b, pl.cdiv(h, th), pl.cdiv(w, tw), p)
  return pl.pallas_call(
      _composite_kernel,
      grid=grid,
      in_specs=[
          pl.BlockSpec((1, 1, 4, th, tw), lambda bi, i, j, pi: (bi, pi, 0, i, j)),
      ],
      out_specs=pl.BlockSpec((1, 3, th, tw), lambda bi, i, j, pi: (bi, 0, i, j)),
      out_shape=jax.ShapeDtypeStruct((b, 3, h, w), rgba.dtype),
      scratch_shapes=[pltpu.VMEM((3, th, tw), jnp.float32)],
      interpret=interpret,
  )(rgba)


def _auto_interpret() -> bool:
  # The kernel targets Mosaic/TPU; everywhere else (CPU test meshes) the
  # Pallas interpreter provides the same semantics.
  return jax.default_backend() != "tpu"


@jax.custom_vjp
def over_composite_pallas_planar(rgba: jnp.ndarray) -> jnp.ndarray:
  """Composite a planar MPI stack ``[B, P, 4, H, W]`` -> ``[B, 3, H, W]``.

  Planes ordered back-to-front (index 0 = farthest, its alpha ignored), same
  contract as ``core.compose.over_composite`` modulo layout.
  """
  return _composite_planar_call(rgba, _auto_interpret())


def _planar_fwd(rgba):
  return over_composite_pallas_planar(rgba), rgba


def _planar_bwd(rgba, g):
  # [B, P, 4, H, W] -> the scan impl's [P, ..., 4] channels-last layout.
  def scan_planar(x):
    out = compose.over_composite_scan(jnp.moveaxis(jnp.swapaxes(x, 0, 1), 2, -1))
    return jnp.moveaxis(out, -1, 1)  # [B, 3, H, W]

  _, vjp = jax.vjp(scan_planar, rgba)
  return vjp(g)


over_composite_pallas_planar.defvjp(_planar_fwd, _planar_bwd)


def over_composite_pallas(rgba: jnp.ndarray) -> jnp.ndarray:
  """Composite ``[P, ..., H, W, 4]`` back-to-front RGBA planes to ``[..., H, W, 3]``.

  Drop-in for ``core.compose.over_composite(..., method='pallas')``: accepts
  the public planes-leading channels-last layout with any (possibly empty)
  batch dims between P and H, transposing to the kernel's planar layout at
  the boundary (one XLA transpose each way; callers that can produce planar
  tensors directly should use ``over_composite_pallas_planar``).
  """
  if rgba.shape[-1] != 4:
    raise ValueError(f"expected trailing RGBA axis of 4, got {rgba.shape}")
  p = rgba.shape[0]
  batch_shape = rgba.shape[1:-3]
  h, w = rgba.shape[-3], rgba.shape[-2]
  flat = rgba.reshape((p, -1) + rgba.shape[-3:])  # [P, B', H, W, 4]
  planar = jnp.moveaxis(jnp.swapaxes(flat, 0, 1), -1, 2)  # [B', P, 4, H, W]
  out = over_composite_pallas_planar(planar)  # [B', 3, H, W]
  return jnp.moveaxis(out, 1, -1).reshape(batch_shape + (h, w, 3))
