"""Pallas TPU backward pass for the fused MPI render kernels.

The reference trains with the renderer inside the loss (cell 12:38-42 of
fast-torch-stereo-vision.ipynb), so the warp+composite backward is on the
training hot path. The XLA route (``jax.vjp`` of the gather-based
``reference_render``) transposes the warp gathers into scatters, which TPUs
execute essentially scalar-by-scalar — the same reason the forward needed a
kernel. This module is the TPU-native backward: three steps, two of them
Pallas kernels that reuse the forward's sampling machinery.

With ``out = composite(warp(planes))`` and the warp linear in plane values,

  d planes = warp^T ( d composite/d warped (g) )

  1. ``warp_planes_fused`` — re-warp every plane WITHOUT compositing (the
     forward kernels minus the accumulator), emitting the warped stack
     ``[B, P, 4, H, W]``. Recompute-not-store: the forward stays fused and
     residual-free; one extra warp costs ~one forward.
  2. ``_composite_bwd`` — the over-composite VJP on the warped stack via
     ``jax.vjp`` of ``compose.over_composite_scan``: an elementwise scan
     transpose XLA fuses well; no gathers, nothing to hand-write.
  3. ``adjoint_warp_planes`` — the warp transpose, the actual new math.
     For a homography warp, warp^T is a *tent-filter* warp along the
     INVERSE map: contribution of gradient pixel (i, j) to source pixel
     (y, x) is ``relu(1-|u(j,i)-x|) * relu(1-|v(j,i)-y|)`` — the forward
     map evaluated at integer taps near ``hom^{-1}(x, y)``. Separable maps
     make the two factors independent (u affine in j, v affine in i), so
     the kernel is structurally the separable forward kernel with an
     ``n_taps``-wide tap fan (tent support is ``2/scale``, not 2) and no
     composite fold.

Gradients w.r.t. the homographies are NOT computed here: the fused
``custom_vjp`` takes them from the XLA reference path, which XLA dead-code
eliminates under jit whenever pose gradients are unused — the training
case (poses are data).

Like the forward, the adjoint has an exact envelope (``plan_adjoint_sep``:
band coverage and gather-window coverage of the inverse map, plus the
static tap-fan width); out-of-envelope poses keep the XLA backward.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_vision_tpu.core import compose
from mpi_vision_tpu.kernels import render_pallas as rp
from mpi_vision_tpu.kernels.render_pallas import BAND, CHUNK, STRIP, WIN


# ---------------------------------------------------------------------------
# Step 1: warp without compositing (forward kernels minus the accumulator).


def _warp_sep_kernel(hom_ref, planes_ref, out_ref, band_ref, sems,
                     *, num_planes, height, width, n_windows):
  """Separable warp of every plane: ``[B, P, 4, H, W]`` warped stack out."""
  bi = pl.program_id(0)
  s = pl.program_id(1)
  p = pl.program_id(2)
  n_s = pl.num_programs(1)
  step = (bi * n_s + s) * num_planes + p
  total = pl.num_programs(0) * n_s * num_planes
  slot = jax.lax.rem(step, 2)
  hom = [hom_ref[bi, p, k] for k in range(9)]
  oy0 = (s * STRIP).astype(jnp.float32)

  def band0_of(b_, p_, s_):
    return rp._ymin_of([hom_ref[b_, p_, k] for k in range(9)],
                       (s_ * STRIP).astype(jnp.float32), height, width)

  ymin = band0_of(bi, p, s)
  rp._sep_band_dma(planes_ref, band_ref, sems, band0_of, step=step,
                   total=total, slot=slot, bi=bi, s=s, p=p, n_s=n_s,
                   num_planes=num_planes)
  ky = rp._sep_ky(hom, oy0, ymin)

  def chunk_body(h, carry):
    pix = rp._sep_chunk_sample(hom, band_ref, slot, h, ky, n_windows, width)
    cols = pl.ds(pl.multiple_of(h * CHUNK, CHUNK), CHUNK)
    for c in range(4):
      out_ref[0, 0, c, :, cols] = pix[c]
    return carry

  jax.lax.fori_loop(0, width // CHUNK, chunk_body, 0)


@functools.partial(jax.jit, static_argnames=("n_windows", "interpret"))
def _warp_sep_call(planes, homs, n_windows: int, interpret: bool):
  batch, num_planes, _, height, width = planes.shape
  kernel = functools.partial(
      _warp_sep_kernel, num_planes=num_planes, height=height, width=width,
      n_windows=min(n_windows, width // WIN))
  return pl.pallas_call(
      kernel,
      grid=(batch, height // STRIP, num_planes),
      in_specs=[
          pl.BlockSpec(memory_space=pltpu.SMEM),
          pl.BlockSpec(memory_space=pl.ANY),
      ],
      out_specs=pl.BlockSpec((1, 1, 4, STRIP, width),
                             lambda b, s, p: (b, p, 0, s, 0)),
      out_shape=jax.ShapeDtypeStruct(
          (batch, num_planes, 4, height, width), jnp.float32),
      scratch_shapes=[
          pltpu.VMEM((2, 4, BAND, width), jnp.float32),
          pltpu.SemaphoreType.DMA((2,)),
      ],
      interpret=interpret,
  )(homs.reshape(batch, num_planes, 9).astype(jnp.float32),
    planes.astype(jnp.float32))


def _warp_shr_kernel(hom_ref, meta_ref, meta_next_ref, wq_ref, planes_ref,
                     out_ref, band_ref, sems,
                     *, num_planes, height, width, n_windows, n_taps, tw,
                     tsrc, bandg, slc):
  """Shared-gather (general homography) warp of every plane."""
  bi = pl.program_id(0)
  s = pl.program_id(1)
  t = pl.program_id(2)
  p = pl.program_id(3)
  n_s = pl.num_programs(1)
  n_t = pl.num_programs(2)
  step = ((bi * n_s + s) * n_t + t) * num_planes + p
  total = pl.num_programs(0) * n_s * n_t * num_planes
  slot = jax.lax.rem(step, 2)
  hom = [hom_ref[bi, p, k] for k in range(9)]
  c_t = tw // CHUNK
  ymin = pl.multiple_of(meta_ref[0, 0, 0, 0, p], 8)
  xmin = pl.multiple_of(meta_ref[0, 0, 0, 1, p], WIN)

  @pl.when(step == 0)
  def _first_dma():
    pltpu.make_async_copy(
        planes_ref.at[bi, p, :, pl.ds(ymin, bandg), pl.ds(xmin, tsrc)],
        band_ref.at[0], sems.at[0]).start()

  pltpu.make_async_copy(
      planes_ref.at[bi, p, :, pl.ds(ymin, bandg), pl.ds(xmin, tsrc)],
      band_ref.at[slot], sems.at[slot]).wait()

  @pl.when(step < total - 1)
  def _next_dma():
    same_tile = p + 1 < num_planes
    p_n = jnp.where(same_tile, p + 1, 0)
    last_tile = (t + 1 >= n_t) & (s + 1 >= n_s)
    b_n = jnp.where(same_tile | ~last_tile, bi, bi + 1)
    ymin_n = pl.multiple_of(meta_next_ref[0, 0, 0, 0, p_n], 8)
    xmin_n = pl.multiple_of(meta_next_ref[0, 0, 0, 1, p_n], WIN)
    pltpu.make_async_copy(
        planes_ref.at[b_n, p_n, :, pl.ds(ymin_n, bandg), pl.ds(xmin_n, tsrc)],
        band_ref.at[1 - slot], sems.at[1 - slot]).start()

  lane = jax.lax.broadcasted_iota(
      jnp.int32, (STRIP, tw), 1).astype(jnp.float32)
  sub = jax.lax.broadcasted_iota(
      jnp.int32, (STRIP, tw), 0).astype(jnp.float32)
  u, v = rp._uv(hom, lane + (t * tw).astype(jnp.float32),
                sub + (s * STRIP).astype(jnp.float32))
  u = jnp.where(jnp.isfinite(u), u, 0.0)
  v = jnp.where(jnp.isfinite(v), v, 0.0)

  for ci in range(c_t):
    w0 = pl.multiple_of(wq_ref[0, 0, 0, p, ci * 2], WIN)
    q0 = pl.multiple_of(wq_ref[0, 0, 0, p, ci * 2 + 1], 8)
    sl = slice(ci * CHUNK, (ci + 1) * CHUNK)
    pix = rp._shr_chunk_sample(u[:, sl], v[:, sl], band_ref, slot, ymin,
                               xmin, q0, w0, n_taps, n_windows, height,
                               width, slc)
    cols = pl.ds(pl.multiple_of(ci * CHUNK, CHUNK), CHUNK)
    for c in range(4):
      out_ref[0, 0, c, :, cols] = pix[c]


@functools.partial(
    jax.jit, static_argnames=("n_taps", "n_windows", "interpret", "slc",
                              "bandg"))
def _warp_shr_call(planes, homs, n_taps: int, n_windows: int,
                   interpret: bool, slc: int = rp.G_SHARED,
                   bandg: int = rp.G_BAND):
  grid, in_specs, operands, g = rp._shared_grid_setup(
      planes, homs, n_windows, slc=slc, bandg=bandg)
  kernel = functools.partial(
      _warp_shr_kernel, num_planes=g["num_planes"], height=g["height"],
      width=g["width"], n_windows=g["n_eff"], n_taps=n_taps, tw=g["tw"],
      tsrc=g["tsrc"], bandg=g["bandg"], slc=g["slc"])
  return pl.pallas_call(
      kernel,
      grid=grid,
      in_specs=in_specs,
      out_specs=pl.BlockSpec((1, 1, 4, STRIP, g["tw"]),
                             lambda b, s, t, p: (b, p, 0, s, t)),
      out_shape=jax.ShapeDtypeStruct(
          (g["batch"], g["num_planes"], 4, g["height"], g["width"]),
          jnp.float32),
      scratch_shapes=[
          pltpu.VMEM((2, 4, g["bandg"], g["tsrc"]), jnp.float32),
          pltpu.SemaphoreType.DMA((2,)),
      ],
      interpret=interpret,
  )(*operands)


def warp_planes_fused(planes, homs, separable: bool,
                      fwd_plan) -> jnp.ndarray:
  """Warp every plane (no composite): ``[B, P, 4, H, W]`` warped stack.

  ``fwd_plan`` is the forward kernel-variant choice: ``n_windows`` (int)
  for the separable path, a ``_plan_shared`` result for the general path —
  ``(n_taps, n_windows, slc, bandg)`` naming the SHARED_LEVELS slice-
  ladder level, or a legacy ``(n_taps, n_windows)`` 2-tuple running the
  base level. The warp re-runs exactly the slice geometry the forward
  planned, so every pose the shared forward accepts has a Pallas re-warp.
  """
  interpret = jax.default_backend() != "tpu"
  if separable:
    return _warp_sep_call(planes, homs, fwd_plan, interpret)
  n_taps, n_windows = fwd_plan[:2]
  slc, bandg = (fwd_plan[2:] if len(fwd_plan) == 4
                else (rp.G_SHARED, rp.G_BAND))
  return _warp_shr_call(planes, homs, n_taps, n_windows, interpret,
                        int(slc), int(bandg))


# ---------------------------------------------------------------------------
# Step 2: over-composite VJP on the warped stack (plain XLA).


def _composite_bwd(warped: jnp.ndarray, g: jnp.ndarray) -> jnp.ndarray:
  """d composite / d warped, contracted with ``g``.

  ``warped``: ``[B, P, 4, H, W]``; ``g``: ``[B, 3, H, W]``. Returns
  ``[B, P, 4, H, W]`` (RGB grads in channels 0-2, alpha grad in 3). The
  scan transpose is elementwise over pixels — XLA fuses it; no kernel.
  """
  w = jnp.swapaxes(jnp.moveaxis(warped, 2, -1), 0, 1)   # [P, B, H, W, 4]
  _, vjp = jax.vjp(compose.over_composite_scan, w)
  (dw,) = vjp(jnp.moveaxis(g, 1, -1))
  return jnp.moveaxis(jnp.swapaxes(dw, 0, 1), -1, 2)


# ---------------------------------------------------------------------------
# Step 3: the warp transpose (tent-filter warp along the inverse map).


def _band0_of(ci, di, oy0, height):
  """First gradient-image row (8-aligned, clamped) whose forward-mapped
  position can reach source strip ``oy0``: contributors to source rows
  ``[oy0, oy0+7]`` are rows i with ``v(i) in (oy0-1, oy0+8)``."""
  i_lo = (oy0 - 1.0 - di) / ci
  i_lo = jnp.where(jnp.isfinite(i_lo), i_lo, 0.0)
  b0 = jnp.clip(jnp.floor(i_lo).astype(jnp.int32) - 1, 0, height - BAND)
  return pl.multiple_of((b0 // 8) * 8, 8)


def _adjoint_sep_kernel(hom_ref, grad_ref, out_ref, band_ref, sems,
                        *, num_planes, height, width, n_taps, n_windows):
  """Separable warp transpose: ``d planes = warp^T(d warped)``.

  Grid ``(batch, source strip, plane)``. Per step, DMA the gradient-image
  band whose rows forward-map into the strip, then for each source pixel
  accumulate ``sum_j relu(1-|u(j)-x|) * sum_i relu(1-|v(i)-y|) * dwarp``:
  the horizontal factor as an ``n_taps`` tap fan from the inverse-mapped
  origin (tent support ``2/scale``), the vertical factor as the forward
  kernel's KY outer-product with the roles of strip rows and band rows
  swapped. Both factors evaluate the FORWARD map at integer taps, so the
  weights are exactly the forward kernel's a.e. bilinear derivatives.
  """
  bi = pl.program_id(0)
  s = pl.program_id(1)
  p = pl.program_id(2)
  n_s = pl.num_programs(1)
  step = (bi * n_s + s) * num_planes + p
  total = pl.num_programs(0) * n_s * num_planes
  slot = jax.lax.rem(step, 2)

  def inv_scalars(hom):
    # Separable: u = a*j + b, v = c*i + d in pixel space.
    a = hom[0] / hom[8]
    b = hom[2] / hom[8]
    c = hom[4] / hom[8]
    d = hom[5] / hom[8]
    return a, b, c, d

  hom = [hom_ref[bi, p, k] for k in range(9)]
  a, b, c, d = inv_scalars(hom)
  oy0 = (s * STRIP).astype(jnp.float32)

  def band0_of(b_, p_, s_):
    _, _, c_, d_ = inv_scalars([hom_ref[b_, p_, k] for k in range(9)])
    return _band0_of(c_, d_, (s_ * STRIP).astype(jnp.float32), height)

  band0 = band0_of(bi, p, s)
  rp._sep_band_dma(grad_ref, band_ref, sems, band0_of, step=step,
                   total=total, slot=slot, bi=bi, s=s, p=p, n_s=n_s,
                   num_planes=num_planes)

  # Vertical adjoint weights: ky2[r, q] = relu(1 - |v(band0+q) - (oy0+r)|)
  # — the forward KY with strip rows and band rows swapped (band rows are
  # gradient-image rows, always in-image by construction of band0).
  sub8 = jax.lax.broadcasted_iota(
      jnp.int32, (STRIP, CHUNK), 0).astype(jnp.float32)
  lane = jax.lax.broadcasted_iota(
      jnp.int32, (STRIP, CHUNK), 1).astype(jnp.float32)
  v_band = c * (lane + band0.astype(jnp.float32)) + d
  ky2 = jnp.maximum(0.0, 1.0 - jnp.abs(v_band - (sub8 + oy0)))
  inv_a = 1.0 / a

  def chunk_body(h, carry):
    ox0 = (h * CHUNK).astype(jnp.float32)
    xs = lane[:1] + ox0                                  # [1, CHUNK]
    jref = (xs - b) * inv_a                              # inverse map
    jhat_f = jnp.floor(jref - inv_a)                     # fan origin
    jhat = jhat_f.astype(jnp.int32)

    # Gather-window base from the chunk's inverse-mapped extents (mirrors
    # the forward's w0; the planner checked coverage).
    ja = (ox0 - b) * inv_a - inv_a
    jb = (ox0 + CHUNK - 1.0 - b) * inv_a - inv_a
    ja = jnp.where(jnp.isfinite(ja), ja, 0.0)
    jb = jnp.where(jnp.isfinite(jb), jb, 0.0)
    j_lo = jnp.floor(jnp.minimum(ja, jb)).astype(jnp.int32)
    w0 = jnp.clip((j_lo // WIN) * WIN, 0, width - n_windows * WIN)

    xles = None
    for tt in range(n_taps):
      jt = jhat + tt
      u_t = a * jt.astype(jnp.float32) + b
      wt = jnp.maximum(0.0, 1.0 - jnp.abs(u_t - xs))     # tent weight
      wt = jnp.where((jt >= 0) & (jt <= width - 1), wt, 0.0)
      rel0 = jt - w0
      for wi in range(n_windows):
        rel = rel0 - wi * WIN
        inw = (rel >= 0) & (rel < WIN)
        coeff = jnp.where(inw, wt, 0.0)
        idx = jnp.broadcast_to(jnp.clip(rel, 0, WIN - 1), (BAND, CHUNK))
        base = pl.multiple_of(w0 + wi * WIN, WIN)
        outs = []
        for ch in range(4):
          win = band_ref[slot, ch, :, pl.ds(base, WIN)]
          g = jnp.take_along_axis(win, idx, axis=1)
          outs.append(g * coeff)
        xles = outs if xles is None else [x + o for x, o in zip(xles, outs)]

    pix = [jnp.zeros((STRIP, CHUNK), jnp.float32) for _ in range(4)]
    for q in range(BAND):
      kyq = ky2[:, q:q + 1]
      pix = [acc + kyq * x[q:q + 1] for acc, x in zip(pix, xles)]
    cols = pl.ds(pl.multiple_of(h * CHUNK, CHUNK), CHUNK)
    for ch in range(4):
      out_ref[0, 0, ch, :, cols] = pix[ch]
    return carry

  jax.lax.fori_loop(0, width // CHUNK, chunk_body, 0)


@functools.partial(
    jax.jit, static_argnames=("n_taps", "n_windows", "interpret"))
def _adjoint_sep_call(grad_warped, homs, n_taps: int, n_windows: int,
                      interpret: bool):
  batch, num_planes, _, height, width = grad_warped.shape
  kernel = functools.partial(
      _adjoint_sep_kernel, num_planes=num_planes, height=height,
      width=width, n_taps=n_taps, n_windows=min(n_windows, width // WIN))
  return pl.pallas_call(
      kernel,
      grid=(batch, height // STRIP, num_planes),
      in_specs=[
          pl.BlockSpec(memory_space=pltpu.SMEM),
          pl.BlockSpec(memory_space=pl.ANY),
      ],
      out_specs=pl.BlockSpec((1, 1, 4, STRIP, width),
                             lambda b, s, p: (b, p, 0, s, 0)),
      out_shape=jax.ShapeDtypeStruct(
          (batch, num_planes, 4, height, width), jnp.float32),
      scratch_shapes=[
          pltpu.VMEM((2, 4, BAND, width), jnp.float32),
          pltpu.SemaphoreType.DMA((2,)),
      ],
      interpret=interpret,
  )(homs.reshape(batch, num_planes, 9).astype(jnp.float32),
    grad_warped.astype(jnp.float32))


def plan_adjoint_sep(homs, height: int, width: int):
  """Static ``(n_taps, n_windows)`` for the separable adjoint, or None.

  Mirrors the kernel's band / fan / window arithmetic in f64 (like
  ``fits_envelope``), with one-row/one-column safety margins so an f32
  divergence in the kernel's floor cannot escape coverage:

    * scales must be positive and finite (mirrored/degenerate maps -> XLA);
    * the tap fan ``floor(jref - 1/a) + [0, n_taps)`` must cover the tent
      support ``jref ± 1/a`` -> ``n_taps = floor(2/a) + 2``, capped at 6;
    * every gradient row that forward-maps within 1 of a source strip must
      lie in the strip's 24-row band (band start mirrors ``_band0_of``);
    * every tap column of a 128-column source chunk must lie in its
      ``n_windows`` gather windows (bases aligned down from the chunk's
      leftmost tap, mirroring the kernel's ``w0``).
  """
  h64 = np.asarray(homs, np.float64).reshape(-1, 3, 3)
  h32 = np.asarray(homs, np.float32).reshape(-1, 3, 3)
  with np.errstate(divide="ignore", invalid="ignore"):
    a = h64[:, 0, 0] / h64[:, 2, 2]
    b = h64[:, 0, 2] / h64[:, 2, 2]
    c = h64[:, 1, 1] / h64[:, 2, 2]
    d = h64[:, 1, 2] / h64[:, 2, 2]
    # The kernel's own f32 arithmetic, op for op, for the band/window
    # bases (the same mirroring strategy as _plan_shared_stats: the check
    # must see the very values the kernel computes, not a higher-precision
    # restatement of them).
    b32 = h32[:, 0, 2] / h32[:, 2, 2]
    c32 = h32[:, 1, 1] / h32[:, 2, 2]
    d32 = h32[:, 1, 2] / h32[:, 2, 2]
    inv_a32 = np.float32(1.0) / (h32[:, 0, 0] / h32[:, 2, 2])
  vals = np.stack([a, b, c, d])
  if not np.isfinite(vals).all() or (a <= 1e-6).any() or (c <= 1e-6).any():
    return None

  inv_a = 1.0 / a                                          # [P]
  n_taps = int(np.floor(2.0 * inv_a.max())) + 2
  if n_taps > 6:
    return None
  # A contributor within TOL of its tent boundary carries <= TOL weight, so
  # dropping it on an f32/f64 floor disagreement costs <= TOL — half the
  # 1e-3 parity budget (same tolerance policy as the forward planners).
  tol = 5e-4

  # Vertical: contributors to source rows [y0, y0+7] are gradient rows i
  # with v(i) in (y0-1, y0+8) — the open interval ((y0-1-d)/c, (y0+8-d)/c).
  n_strips = height // STRIP
  y0 = np.arange(n_strips, dtype=np.float64)[:, None] * STRIP  # [S, 1]
  i_lo = (y0 - 1.0 - d[None, :]) / c[None, :]              # [S, P]
  i_hi = (y0 + STRIP - d[None, :]) / c[None, :]
  q_lo = np.maximum(np.floor(i_lo - tol).astype(np.int64) + 1, 0)
  q_hi = np.minimum(np.ceil(i_hi + tol).astype(np.int64) - 1, height - 1)
  empty_v = q_lo > q_hi
  i_lo32 = ((y0.astype(np.float32) - np.float32(1.0) - d32[None, :])
            / c32[None, :])                                # _band0_of, f32
  # The kernel's scalar-core f32 divide is not guaranteed bit-identical to
  # this numpy mirror, so when the value sits near an integer its floor can
  # resolve either way; require coverage under BOTH resolutions (a generous
  # multi-ulp band), rejecting near-boundary poses to the XLA backward.
  eps_v = np.maximum(np.abs(i_lo32), 1.0) * np.float32(1e-5)
  for i_lo_c in (i_lo32 - eps_v, i_lo32 + eps_v):
    band0 = np.clip(np.floor(i_lo_c).astype(np.int64) - 1, 0,
                    height - BAND) // 8 * 8
    if not (empty_v | ((q_lo >= band0) & (q_hi <= band0 + BAND - 1))).all():
      return None

  # Horizontal: contributors to a chunk's columns [x0, x0+127] are
  # gradient columns j with u(j) in (x0-1, x0+128) — the open interval
  # (jref(x0) - 1/a, jref(x0+127) + 1/a) for a > 0.
  n_chunks = width // CHUNK
  x_edges = (np.arange(n_chunks, dtype=np.float64)[:, None] * CHUNK
             + np.array([0.0, CHUNK - 1.0]))               # [C, 2]
  jref = ((x_edges[..., None] - b) * inv_a).transpose(2, 0, 1)  # [P, C, 2]
  j_lo = np.maximum(
      np.floor(jref.min(axis=2) - inv_a[:, None] - tol).astype(np.int64) + 1,
      0)
  j_hi = np.minimum(
      np.ceil(jref.max(axis=2) + inv_a[:, None] + tol).astype(np.int64) - 1,
      width - 1)
  empty_h = j_lo > j_hi
  # The kernel's f32 window base: floor of the chunk-edge fan origins.
  x32 = x_edges.astype(np.float32)
  ja32 = ((x32[:, 0][None, :] - b32[:, None]) * inv_a32[:, None]
          - inv_a32[:, None])                              # [P, C]
  jb32 = ((x32[:, 1][None, :] - b32[:, None]) * inv_a32[:, None]
          - inv_a32[:, None])
  j_base = np.minimum(ja32, jb32)
  eps_h = np.maximum(np.abs(j_base), 1.0) * np.float32(1e-5)
  for n_windows in (2, 3):
    if width < n_windows * WIN:
      continue
    ok = True
    # Both floor resolutions of the kernel's f32 window base must cover
    # (same reasoning as the vertical band above).
    for j_base_c in (j_base - eps_h, j_base + eps_h):
      w0 = np.clip(np.floor(j_base_c).astype(np.int64) // WIN * WIN, 0,
                   width - n_windows * WIN)
      ok = ok and bool(
          (empty_h | ((j_lo >= w0)
                      & (j_hi <= w0 + n_windows * WIN - 1))).all())
    if ok:
      return n_taps, n_windows
  return None


# ---------------------------------------------------------------------------
# Step 3b: the general (rotation) warp transpose.
#
# Contributors to source pixel (x, y) are gradient pixels (j, i) whose
# forward-mapped position lands in the open ±1 box around (x, y) — the
# preimage of that box under the homography, i.e. the image of the box
# under hom^{-1}. Box corners map through the four shifted inverses
# ``hom^{-1} ∘ shift(±1, ±1)`` (denominator one-signed => corner extrema
# are exact), so the forward's corner-minima table machinery applies
# verbatim on the 4-shift union (``_corner_mins_union``). The kernel is
# the shared-gather forward with: per-column tap-fan origin from the
# shift-union minimum, an (n_tx x n_ty) 2-D tap fan, and per-tap weights
# ``relu(1-|u(j,i)-x|) * relu(1-|v(j,i)-y|)`` — the FORWARD map evaluated
# at the integer tap, exactly the forward kernel's a.e. bilinear
# derivative.

_SHIFTS = ((-1.0, -1.0), (-1.0, 1.0), (1.0, -1.0), (1.0, 1.0))


def _shift_matrices():
  return jnp.stack([
      jnp.array([[1.0, 0.0, dx], [0.0, 1.0, dy], [0.0, 0.0, 1.0]],
                jnp.float32) for dx, dy in _SHIFTS])


def _shifted_scalars(hom, dx, dy):
  """``hom ∘ shift(dx, dy)`` for a 9-scalar homography list."""
  return [hom[0], hom[1], hom[0] * dx + hom[1] * dy + hom[2],
          hom[3], hom[4], hom[3] * dx + hom[4] * dy + hom[5],
          hom[6], hom[7], hom[6] * dx + hom[7] * dy + hom[8]]


def _adjoint_shr_kernel(hom_ref, meta_ref, meta_next_ref, wq_ref, grad_ref,
                        homf_ref, out_ref, band_ref, sems,
                        *, num_planes, height, width, n_windows, n_tx,
                        n_ty, tw, tsrc, bandg, slc):
  """General warp transpose on 2-D source tiles.

  ``hom_ref`` holds the INVERSE homographies (fan origins + tables);
  ``homf_ref`` the forward ones (tap weights). Grid/DMA/table layout is
  the shared-gather forward's (see _shared_grid_setup).
  """
  bi = pl.program_id(0)
  s = pl.program_id(1)
  t = pl.program_id(2)
  p = pl.program_id(3)
  n_s = pl.num_programs(1)
  n_t = pl.num_programs(2)
  step = ((bi * n_s + s) * n_t + t) * num_planes + p
  total = pl.num_programs(0) * n_s * n_t * num_planes
  slot = jax.lax.rem(step, 2)
  homi = [hom_ref[bi, p, k] for k in range(9)]
  homf = [homf_ref[bi, p, k] for k in range(9)]
  c_t = tw // CHUNK
  ymin = pl.multiple_of(meta_ref[0, 0, 0, 0, p], 8)
  xmin = pl.multiple_of(meta_ref[0, 0, 0, 1, p], WIN)

  @pl.when(step == 0)
  def _first_dma():
    pltpu.make_async_copy(
        grad_ref.at[bi, p, :, pl.ds(ymin, bandg), pl.ds(xmin, tsrc)],
        band_ref.at[0], sems.at[0]).start()

  pltpu.make_async_copy(
      grad_ref.at[bi, p, :, pl.ds(ymin, bandg), pl.ds(xmin, tsrc)],
      band_ref.at[slot], sems.at[slot]).wait()

  @pl.when(step < total - 1)
  def _next_dma():
    same_tile = p + 1 < num_planes
    p_n = jnp.where(same_tile, p + 1, 0)
    last_tile = (t + 1 >= n_t) & (s + 1 >= n_s)
    b_n = jnp.where(same_tile | ~last_tile, bi, bi + 1)
    ymin_n = pl.multiple_of(meta_next_ref[0, 0, 0, 0, p_n], 8)
    xmin_n = pl.multiple_of(meta_next_ref[0, 0, 0, 1, p_n], WIN)
    pltpu.make_async_copy(
        grad_ref.at[b_n, p_n, :, pl.ds(ymin_n, bandg), pl.ds(xmin_n, tsrc)],
        band_ref.at[1 - slot], sems.at[1 - slot]).start()

  lane = jax.lax.broadcasted_iota(jnp.int32, (STRIP, tw), 1).astype(
      jnp.float32)
  sub = jax.lax.broadcasted_iota(jnp.int32, (STRIP, tw), 0).astype(
      jnp.float32)
  xs = lane + (t * tw).astype(jnp.float32)
  ys = sub + (s * STRIP).astype(jnp.float32)
  jmin = imin = None
  for dx, dy in _SHIFTS:
    jc, ic = rp._uv(_shifted_scalars(homi, dx, dy), xs, ys)
    jc = jnp.where(jnp.isfinite(jc), jc, 0.0)
    ic = jnp.where(jnp.isfinite(ic), ic, 0.0)
    jmin = jc if jmin is None else jnp.minimum(jmin, jc)
    imin = ic if imin is None else jnp.minimum(imin, ic)

  for ci in range(c_t):
    w0 = pl.multiple_of(wq_ref[0, 0, 0, p, ci * 2], WIN)
    q0 = pl.multiple_of(wq_ref[0, 0, 0, p, ci * 2 + 1], 8)
    sl = slice(ci * CHUNK, (ci + 1) * CHUNK)
    xsl = xs[:1, sl]                                     # [1, CHUNK]
    ysl = ys[:, sl]                                      # [STRIP, CHUNK]
    jhat = jnp.floor(jnp.min(jmin[:, sl], axis=0,
                             keepdims=True)).astype(jnp.int32)
    ihat = jnp.floor(imin[:, sl]).astype(jnp.int32)      # [STRIP, CHUNK]

    pix = [jnp.zeros((STRIP, CHUNK), jnp.float32) for _ in range(4)]
    for dj in range(n_tx):
      jt = jhat + dj                                     # [1, CHUNK]
      rel0 = jt - xmin - w0
      xle = None                                         # [G_SHARED, CHUNK]
      for wi in range(n_windows):
        rel = rel0 - wi * WIN
        inw = (rel >= 0) & (rel < WIN)
        idx = jnp.broadcast_to(jnp.clip(rel, 0, WIN - 1), (slc, CHUNK))
        base = pl.multiple_of(w0 + wi * WIN, WIN)
        outs = []
        for ch in range(4):
          win = band_ref[slot, ch, pl.ds(q0, slc), pl.ds(base, WIN)]
          g = jnp.take_along_axis(win, idx, axis=1)
          outs.append(jnp.where(inw, g, 0.0))
        xle = outs if xle is None else [a + o for a, o in zip(xle, outs)]

      jf = jt.astype(jnp.float32)
      for di in range(n_ty):
        it = ihat + di                                   # [STRIP, CHUNK]
        itf = it.astype(jnp.float32)
        den = homf[6] * jf + homf[7] * itf + homf[8]
        r = 1.0 / den
        u = (homf[0] * jf + homf[1] * itf + homf[2]) * r
        v = (homf[3] * jf + homf[4] * itf + homf[5]) * r
        w = (jnp.maximum(0.0, 1.0 - jnp.abs(u - xsl))
             * jnp.maximum(0.0, 1.0 - jnp.abs(v - ysl)))
        w = jnp.where(jnp.isfinite(w), w, 0.0)
        w = jnp.where((jt >= 0) & (jt <= width - 1)
                      & (it >= 0) & (it <= height - 1), w, 0.0)
        qi = it - (ymin + q0)
        for ch in range(4):
          sel = jnp.zeros((STRIP, CHUNK), jnp.float32)
          for k in range(slc // 8):
            vreg = xle[ch][8 * k:8 * (k + 1)]            # [8, CHUNK]
            gk = jnp.take_along_axis(vreg, jnp.clip(qi - 8 * k, 0, 7),
                                     axis=0)
            sel = jnp.where((qi >= 8 * k) & (qi < 8 * (k + 1)), gk, sel)
          pix[ch] += w * sel

    cols = pl.ds(pl.multiple_of(ci * CHUNK, CHUNK), CHUNK)
    for ch in range(4):
      out_ref[0, 0, ch, :, cols] = pix[ch]


def _inv_homs(homs32):
  """Normalized f32 inverses of ``[..., 3, 3]`` homographies.

  Closed-form adjugate, not ``jnp.linalg.inv``: the LU path lowers through
  ``lax.custom_linear_solve``, whose closure tracing breaks with an
  UnexpectedTracerError when the jitted stats are re-traced under
  ``ensure_compile_time_eval`` on jax 0.4.x (the planners' calling
  convention) — and the cofactor form is cheaper for 3x3 anyway.
  """
  m = homs32
  c00 = m[..., 1, 1] * m[..., 2, 2] - m[..., 1, 2] * m[..., 2, 1]
  c01 = m[..., 1, 2] * m[..., 2, 0] - m[..., 1, 0] * m[..., 2, 2]
  c02 = m[..., 1, 0] * m[..., 2, 1] - m[..., 1, 1] * m[..., 2, 0]
  c10 = m[..., 0, 2] * m[..., 2, 1] - m[..., 0, 1] * m[..., 2, 2]
  c11 = m[..., 0, 0] * m[..., 2, 2] - m[..., 0, 2] * m[..., 2, 0]
  c12 = m[..., 0, 1] * m[..., 2, 0] - m[..., 0, 0] * m[..., 2, 1]
  c20 = m[..., 0, 1] * m[..., 1, 2] - m[..., 0, 2] * m[..., 1, 1]
  c21 = m[..., 0, 2] * m[..., 1, 0] - m[..., 0, 0] * m[..., 1, 2]
  c22 = m[..., 0, 0] * m[..., 1, 1] - m[..., 0, 1] * m[..., 1, 0]
  det = m[..., 0, 0] * c00 + m[..., 0, 1] * c01 + m[..., 0, 2] * c02
  adj = jnp.stack([jnp.stack([c00, c10, c20], -1),
                   jnp.stack([c01, c11, c21], -1),
                   jnp.stack([c02, c12, c22], -1)], -2)
  # The det division looks redundant (the [2,2] renormalization cancels
  # it) but is kept deliberately: a singular homography must yield
  # inf/nan here — exactly as jnp.linalg.inv did — so the planners'
  # isfinite checks reject the pose; adj/adj[2,2] alone would return
  # finite garbage for det=0 and let a degenerate pose plan a kernel.
  inv = adj / det[..., None, None]
  return inv / inv[..., 2:3, 2:3]


def _union_mins_fn(height, width, tw):
  """mins_fn for _shared_grid_setup: 4-shift union corner minima."""
  shifts = _shift_matrices()                              # [4, 3, 3]

  def fn(h9):                                             # [P, 9]
    p = h9.shape[0]
    hmat = h9.reshape(p, 3, 3)
    stack = jnp.einsum("pij,kjl->kpil", hmat, shifts)     # [4, P, 3, 3]
    return rp._corner_mins_union(stack, height, width, tw)

  return fn


@functools.partial(
    jax.jit, static_argnames=("n_tx", "n_ty", "n_windows", "interpret",
                              "slc", "bandg"))
def _adjoint_shr_call(grad_warped, homs, n_tx: int, n_ty: int,
                      n_windows: int, interpret: bool,
                      slc: int = rp.G_SHARED, bandg: int = rp.G_BAND):
  batch, num_planes, _, height, width = grad_warped.shape
  homs32 = homs.reshape(batch, num_planes, 3, 3).astype(jnp.float32)
  hinv = _inv_homs(homs32)
  tw = rp._tile_sizes(height, width, n_windows)[0]
  grid, in_specs, operands, g = rp._shared_grid_setup(
      grad_warped, hinv.reshape(batch, num_planes, 9), n_windows,
      mins_fn=_union_mins_fn(height, width, tw), slc=slc, bandg=bandg)
  kernel = functools.partial(
      _adjoint_shr_kernel, num_planes=g["num_planes"], height=g["height"],
      width=g["width"], n_windows=g["n_eff"], n_tx=n_tx, n_ty=n_ty,
      tw=g["tw"], tsrc=g["tsrc"], bandg=g["bandg"], slc=g["slc"])
  return pl.pallas_call(
      kernel,
      grid=grid,
      in_specs=in_specs + [pl.BlockSpec(memory_space=pltpu.SMEM)],
      out_specs=pl.BlockSpec((1, 1, 4, STRIP, g["tw"]),
                             lambda b, s, t, p: (b, p, 0, s, t)),
      out_shape=jax.ShapeDtypeStruct(
          (g["batch"], g["num_planes"], 4, g["height"], g["width"]),
          jnp.float32),
      scratch_shapes=[
          pltpu.VMEM((2, 4, g["bandg"], g["tsrc"]), jnp.float32),
          pltpu.SemaphoreType.DMA((2,)),
      ],
      interpret=interpret,
  )(*operands, homs32.reshape(batch, num_planes, 9))


@functools.partial(jax.jit, static_argnames=("height", "width"))
def _plan_adjoint_shr_stats(homs: jnp.ndarray, height: int, width: int):
  """Device-side stats for the general adjoint plan (traceable, f32).

  Mirrors ``_plan_shared_stats``'s strategy on the INVERSE homographies
  with the 4-shift union extents: the very f32 values the adjoint call's
  tables and the kernel's fan origins see. Returns (den_ok, span_x,
  span_y, v_oks, h2_ok, h3_ok) — ``v_oks`` one per
  ``_shared_levels(height)`` slice-ladder level, as the forward's.
  """
  h9 = homs.reshape(-1, 3, 3).astype(jnp.float32)
  p = h9.shape[0]
  hinv = _inv_homs(h9)

  # Inverse denominator one-signed over the image corners (else corner
  # extrema of the inverse map are not extrema).
  cx = jnp.array([0.0, width - 1.0], jnp.float32)
  cy = jnp.array([0.0, height - 1.0], jnp.float32)
  d_flat = (hinv[:, 2, 0, None, None] * cx[None, :, None]
            + hinv[:, 2, 1, None, None] * cy[None, None, :]
            + hinv[:, 2, 2, None, None]).reshape(p, 4)
  den_ok = (jnp.isfinite(d_flat).all()
            & ((d_flat > 0).all(1) | (d_flat < 0).all(1)).all())

  tw, _, bandg, _ = rp._tile_sizes(height, width, 2)
  n_strips = height // STRIP
  shifts = _shift_matrices()
  stack = jnp.einsum("pij,kjl->kpil", hinv, shifts)       # [4, P, 3, 3]
  mins = rp._corner_mins_union(stack, height, width, tw)

  # Per-column strip extrema of the shift-union inverse coords, from the
  # strip's top/bottom rows (exact: monotone in the row for one-signed
  # denominators), unioned over the 4 shifts.
  cols = jnp.arange(width, dtype=jnp.float32)
  oyr = (jnp.arange(n_strips, dtype=jnp.float32)[:, None] * STRIP
         + jnp.array([0.0, STRIP - 1.0])).reshape(-1)
  u_r, v_r = rp._uv_vec(stack.reshape(4 * p, 3, 3),
                        cols[None, None, :], oyr[None, :, None])
  u_r = u_r.reshape(4, p, n_strips, 2, width)
  v_r = v_r.reshape(4, p, n_strips, 2, width)
  j_lo = u_r.min(axis=(0, 3))                             # [P, S, W]
  j_hi = u_r.max(axis=(0, 3))
  i_lo = v_r.min(axis=(0, 3))
  i_hi = v_r.max(axis=(0, 3))

  tol = 5e-4
  # Horizontal fan origin is shared per COLUMN (min over the strip's
  # rows), so its span is column-level: strip extrema over rows + shifts.
  span_x = (jnp.floor(j_hi + tol).astype(jnp.int32)
            - jnp.floor(j_lo - tol).astype(jnp.int32)).max()
  # Vertical fan origin is PER PIXEL, so its span is the 4-shift spread at
  # one pixel — evaluated at the strip-edge rows (the host wrapper adds
  # one safety tap for interior rows; the spread varies by ~|second
  # derivative| * 8 rows across a strip, orders below one tap for any
  # accepted pose, and the random-pose property test backs this
  # empirically).
  i_lo_px = v_r.min(axis=0)                               # [P, S, 2, W]
  i_hi_px = v_r.max(axis=0)
  span_y = (jnp.floor(i_hi_px + tol).astype(jnp.int32)
            - jnp.floor(i_lo_px - tol).astype(jnp.int32)).max()

  chunk_of_col = jnp.arange(width) // CHUNK
  empty_v = (i_hi <= -1) | (i_lo >= height)
  # Vertical coverage per slice-ladder level (ymin/q0 shift with the
  # level's bandg/slc), exactly as the forward's _plan_shared_stats.
  v_oks = []
  for slc_l, bandg_l in rp._shared_levels(height):
    _, _, ymin_cl, _, _, q0_l = rp._table_scalars(
        mins, height, width, tw, min(width, 640), bandg_l,
        min(2, min(width, 640) // WIN), slc_l)
    ymq = ((ymin_cl + q0_l)[:, :, chunk_of_col]).astype(jnp.float32)
    v_oks.append((empty_v | (
        (jnp.maximum(i_lo, 0.0) >= ymq - tol)
        & (jnp.minimum(i_hi, height - 1.0)
           <= ymq + slc_l - 1 + tol))).all())

  empty_h = (j_hi <= -1) | (j_lo >= width)
  h_oks = []
  for n_windows in (2, 3):
    _, tsrc, _, n_eff = rp._tile_sizes(height, width, n_windows)
    _, _, _, xmin_c, w0, _ = rp._table_scalars(
        mins, height, width, tw, tsrc, bandg, n_eff)
    xmw = ((xmin_c + w0)[:, :, chunk_of_col]).astype(jnp.float32)
    h_oks.append((empty_h | (
        (jnp.maximum(j_lo, 0.0) >= xmw - tol)
        & (jnp.minimum(j_hi, width - 1.0)
           <= xmw + n_eff * WIN - 1 + tol))).all())
  return den_ok, span_x, span_y, tuple(v_oks), h_oks[0], h_oks[1]


def plan_adjoint_shr(homs, height: int, width: int):
  """Static ``(n_tx, n_ty, n_windows, slc, bandg)`` for the general
  adjoint, or None — the last two name the SHARED_LEVELS slice-ladder
  level the adjoint's inverse-map geometry needs (chosen cheapest-first,
  independently of the forward's level).

  The tap fans must cover the shift-union contributor extents: ``span + 1``
  taps each way, capped at 5 (beyond that the pose is cheaper on the XLA
  backward anyway). ``homs`` concrete; batch axes flatten into planes.
  Memoized on the pose bytes (``render_pallas.plan_memo``).
  """
  a = np.asarray(homs)
  return rp.plan_memo("adj_shr", a, height, width,
                      lambda: _plan_adjoint_shr_uncached(a, height, width))


def _plan_adjoint_shr_uncached(homs: np.ndarray, height: int, width: int):
  # ensure_compile_time_eval: callers may sit under an ambient jit trace
  # (concrete homs as jit constants); the stats must still run eagerly.
  with jax.ensure_compile_time_eval():
    den_ok, span_x, span_y, v_oks, h2, h3 = jax.device_get(
        _plan_adjoint_shr_stats(jnp.asarray(homs), height, width))
  if not den_ok:
    return None
  # +1 to cover the span; vertical +1 more as the interior-row safety tap
  # (the stats sample per-pixel spreads at strip-edge rows only).
  n_tx, n_ty = int(span_x) + 1, int(span_y) + 2
  if n_tx > 5 or n_ty > 5:
    return None
  n_windows = 2 if h2 else 3 if h3 else None
  if n_windows is None:
    return None
  # Cheapest covering slice-ladder level first, as the forward planner:
  # gather traffic is linear in the slice height.
  for (slc, bandg), v_ok in zip(rp._shared_levels(height), v_oks):
    if v_ok:
      return n_tx, n_ty, n_windows, int(slc), int(bandg)
  return None


# ---------------------------------------------------------------------------
# Assembly.


def backward_planes(planes, homs, g, separable: bool, fwd_plan,
                    adj_plan) -> jnp.ndarray:
  """``d loss / d planes`` for ``g = d loss / d render``: warp, composite
  VJP, warp transpose. All arguments batched (``[B, P, 4, H, W]`` planes,
  ``[B, P, 3, 3]`` homs, ``[B, 3, H, W]`` g). ``adj_plan`` comes from
  ``plan_adjoint_sep`` (separable: ``(n_taps, n_windows)``) or
  ``plan_adjoint_shr`` (general: ``(n_tx, n_ty, n_windows, slc, bandg)``,
  slice-ladder level last; legacy 3-tuples run the base level)."""
  interpret = jax.default_backend() != "tpu"
  warped = warp_planes_fused(planes, homs, separable, fwd_plan)
  dwarped = _composite_bwd(warped, g)
  if separable:
    n_taps, n_windows = adj_plan
    return _adjoint_sep_call(dwarped, homs, n_taps, n_windows, interpret)
  n_tx, n_ty, n_windows = adj_plan[:3]
  slc, bandg = (adj_plan[3:] if len(adj_plan) == 5
                else (rp.G_SHARED, rp.G_BAND))
  return _adjoint_shr_call(dwarped, homs, n_tx, n_ty, n_windows, interpret,
                           int(slc), int(bandg))
