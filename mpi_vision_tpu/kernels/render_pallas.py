"""Fused Pallas TPU kernel: homography warp + bilinear sample + over-composite.

The reference renders a novel view by warping every MPI plane with
``grid_sample`` and compositing back-to-front (utils.py:267-294). A literal
port runs the warp as an XLA ``gather`` — which TPUs execute essentially
scalar-by-scalar (~6 s/frame at 1080p x 32 planes, measured). This kernel is
the TPU-native redesign that makes the 30 FPS target reachable: the whole
render is ONE kernel with no warped-plane stack, no XLA gather, and HBM
traffic within ~2x of the theoretical minimum (read each plane once).

Per grid step (strip of 8 output rows, one plane; planes innermost):

  1. A *source band* — the 24 source rows that can influence this strip,
     8-aligned so the HBM-tiling divisibility proof holds — is DMA'd into
     VMEM as ``[4, 24, W]`` (channels planar).
  2. For each 128-column output chunk, plane-homography coordinates (u, v)
     are evaluated directly on the VPU from the 3x3 matrix (pixel-space; the
     coordinate-normalization convention is folded into the matrix by
     ``pixel_homographies``).
  3. The bilinear x-taps come from ``tpu.dynamic_gather`` (the HW lane
     gather, ~750 G elem/s measured): the gather window is limited to one
     128-lane vreg, so taps are gathered from up to three 128-aligned
     windows of the band chosen per output row (``lax.cond`` skips windows a
     row does not touch), each tap gathering all 24 band rows at once.
  4. The vertical lerp is a ``relu(1 - |v - row|)`` weighted sum over the 24
     band rows — nonzero exactly at the two bilinear rows, so it reproduces
     exact 2-tap vertical interpolation (and zeros padding for free: rows
     outside the image are never in the clamped band) without a second
     gather axis.
  5. The running composite ``out = rgb*a + out*(1-a)`` lives in a VMEM f32
     accumulator across the plane axis of the grid (farthest plane's alpha
     ignored, utils.py:152-153), written to HBM once per strip.

Restrictions (documented contract): H % 8 == 0, W % 128 == 0, H >= 24, and
per-plane source extents bounded — a strip's source rows must fit the 24-row
band (17 usable after alignment slack: vertical scale <= ~1.5 with modest
tilt) and one output row's 128-column chunk must reach <= 2*128+1 = 257
source columns from its leftmost tap (separable path, 3 windows: horizontal
scale <= ~2.0) or <= 3*128+1 = 385 (general path, 4 windows: scale <= ~3.0).
Window bases are 128-aligned *down* from the leftmost tap, so these bounds
already include the worst-case (127-column) alignment slack.
``fits_envelope`` checks the exact contract eagerly (cheap: the separable
check is closed-form per strip/chunk) and ``render_mpi_fused`` uses it to
fall back to the XLA path for out-of-envelope concrete poses. Outside the
envelope (only reachable by jitting around the check) dropped taps produce
PARTIAL bilinear sums — dimmed, wrong pixels, not black — and the backward
pass (the XLA reference path via ``jax.custom_vjp``) does not match such a
forward; inside the envelope forward and backward agree.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_vision_tpu.core import compose, geometry, render, sampling
from mpi_vision_tpu.core.sampling import Convention

STRIP = 8      # output rows per grid step
BAND = 24      # source rows held in VMEM (8-aligned start)
CHUNK = 128    # output columns per inner step == one vreg of lanes
WIN = 128      # gather window width == max lane-gather span
SEP_WINDOWS = 3   # separable path: 2 unconditional + 1 conditional
MAX_WINDOWS = 4   # legacy general strip path: all conditional

# Tiled general path (rotations): 2-D output tiles with per-tile source
# rectangles and per-row 16-row band slices for the vertical lerp.
G_TILE_W = 384   # preferred output tile width (3 chunks)
G_BAND = 32      # source rows per tile band (8-aligned start)
G_SLICE = 16     # band rows gathered per output row (8-aligned offset)


def pixel_homographies(
    tgt_pose: jnp.ndarray,
    depths: jnp.ndarray,
    intrinsics: jnp.ndarray,
    height: int,
    width: int,
    convention: Convention = Convention.EXACT,
) -> jnp.ndarray:
  """Per-plane 3x3 maps from target *pixel* coords to source *pixel* coords.

  Composes the plane-induced homographies (core/render.py) with the
  convention's (0,1) normalization and the sampler's ``c*size - 0.5`` pixel
  mapping, so the kernel works in raw pixel space. For ``EXACT`` the
  composition is the identity; for the reference conventions it is a
  diagonal rescale + shift (the Q2/Q3 x/y-swapped scales, SURVEY.md §2.8).

  Returns ``[P, B, 3, 3]`` float32.
  """
  homs = render.plane_homographies(tgt_pose, depths, intrinsics)  # [P,B,3,3]
  if convention is Convention.EXACT:
    return homs.astype(jnp.float32)
  if convention is Convention.REF_HOMOGRAPHY:
    # c = (x/(H-1), y/(W-1)); px = c_x*W - 0.5, py = c_y*H - 0.5.
    post = np.array([
        [width / (height - 1), 0.0, -0.5],
        [0.0, height / (width - 1), -0.5],
        [0.0, 0.0, 1.0],
    ], dtype=np.float32)
  elif convention is Convention.REF_PROJECTION:
    # c = ((x+0.5)/H, (y+0.5)/W); px = c_x*W - 0.5, py = c_y*H - 0.5.
    post = np.array([
        [width / height, 0.0, 0.5 * width / height - 0.5],
        [0.0, height / width, 0.5 * height / width - 0.5],
        [0.0, 0.0, 1.0],
    ], dtype=np.float32)
  else:
    raise ValueError(f"unknown convention: {convention!r}")
  return jnp.asarray(post) @ homs.astype(jnp.float32)


def _uv(hom, ox, oy):
  """Apply a 3x3 pixel homography (list of 9 scalars) to pixel coords."""
  d = hom[6] * ox + hom[7] * oy + hom[8]
  r = 1.0 / d
  return (hom[0] * ox + hom[1] * oy + hom[2]) * r, \
         (hom[3] * ox + hom[4] * oy + hom[5]) * r


def _ymin_of(hom, oy0, height, width):
  """Scalar first-source-row (8-aligned, clamped) for a strip at ``oy0``."""
  cs = [_uv(hom, ox, oy)[1]
        for ox in (0.0, float(width - 1))
        for oy in (oy0, oy0 + STRIP - 1)]
  vmin = jnp.minimum(jnp.minimum(cs[0], cs[1]), jnp.minimum(cs[2], cs[3]))
  vmin = jnp.where(jnp.isfinite(vmin), vmin, 0.0)
  ymin = jnp.clip(jnp.floor(vmin).astype(jnp.int32) - 1, 0, height - BAND)
  return pl.multiple_of((ymin // 8) * 8, 8)


def _separable_kernel(hom_ref, planes_ref, out_ref, band_ref, acc_ref, sems,
                      *, num_planes, height, width, n_windows):
  """Fast path for axis-aligned (separable) homographies.

  With h01 = h10 = h20 = h21 = 0, ``u`` depends only on the output column
  and ``v`` only on the output row. All 8 rows of a strip then share their
  x-taps, so each gather serves the whole strip, and the vertical 2-tap lerp
  for the full [8, CHUNK] tile is one small MXU matmul
  ``KY[8, BAND] @ xle[BAND, CHUNK]``. Band DMAs are double-buffered across
  grid steps.

  ``n_windows`` (static: 2 or 3) is the per-chunk gather-window count, all
  unconditional — branchless beats ``lax.cond`` here (a scalar cond in the
  hot loop measured ~1.7x slower than just doing the third gather). Eager
  callers auto-select it from the concrete homographies (2 suffices for
  horizontal scale <= 1.0 at ANY alignment; 3 guarantees scale <= ~2.0).
  """
  s = pl.program_id(0)
  p = pl.program_id(1)
  step = s * num_planes + p
  total = pl.num_programs(0) * num_planes
  slot = jax.lax.rem(step, 2)
  hom = [hom_ref[p, k] for k in range(9)]
  oy0 = (s * STRIP).astype(jnp.float32)
  ymin = _ymin_of(hom, oy0, height, width)

  @pl.when(step == 0)
  def _first_dma():
    pltpu.make_async_copy(
        planes_ref.at[p, :, pl.ds(ymin, BAND), :],
        band_ref.at[0], sems.at[0]).start()

  pltpu.make_async_copy(
      planes_ref.at[p, :, pl.ds(ymin, BAND), :],
      band_ref.at[slot], sems.at[slot]).wait()

  @pl.when(step < total - 1)
  def _next_dma():
    p_n = jnp.where(p + 1 < num_planes, p + 1, 0)
    s_n = jnp.where(p + 1 < num_planes, s, s + 1)
    hom_n = [hom_ref[p_n, k] for k in range(9)]
    ymin_n = _ymin_of(hom_n, (s_n * STRIP).astype(jnp.float32), height, width)
    pltpu.make_async_copy(
        planes_ref.at[p_n, :, pl.ds(ymin_n, BAND), :],
        band_ref.at[1 - slot], sems.at[1 - slot]).start()

  # v depends only on the row: KY[r, q] = relu(1 - |v_r - (ymin + q)|) is the
  # exact vertical bilinear weight matrix (zeros padding included: band rows
  # are always in-image, rows outside the band weight to 0).
  sub8 = jax.lax.broadcasted_iota(jnp.int32, (STRIP, CHUNK), 0).astype(jnp.float32)
  lane = jax.lax.broadcasted_iota(jnp.int32, (STRIP, CHUNK), 1).astype(jnp.float32)
  v8 = (hom[4] * (sub8 + oy0) + hom[5]) / hom[8]
  ky = jnp.maximum(0.0, 1.0 - jnp.abs(v8 - (lane + ymin.astype(jnp.float32))))

  def chunk_body(h, carry):
    ox0 = (h * CHUNK).astype(jnp.float32)
    u = (hom[0] * (lane[:1] + ox0) + hom[2]) / hom[8]     # [1, CHUNK]
    x0f = jnp.floor(u)
    fx = u - x0f
    x0 = x0f.astype(jnp.int32)
    valid0 = (x0 >= 0) & (x0 <= width - 1)
    valid1 = (x0 + 1 >= 0) & (x0 + 1 <= width - 1)

    ua = (hom[0] * ox0 + hom[2]) / hom[8]
    ub = (hom[0] * (ox0 + CHUNK - 1) + hom[2]) / hom[8]
    ua = jnp.where(jnp.isfinite(ua), ua, 0.0)
    ub = jnp.where(jnp.isfinite(ub), ub, 0.0)
    x_lo = jnp.floor(jnp.minimum(ua, ub)).astype(jnp.int32)
    # Clamp so all n_windows gather windows are always in-range; window
    # bases align DOWN from x_lo, so guaranteed coverage from the leftmost
    # tap is (n_windows-1)*WIN + 1 columns.
    w0 = jnp.clip((x_lo // WIN) * WIN, 0, width - n_windows * WIN)

    xles = None
    for wi in range(n_windows):
      base = pl.multiple_of(w0 + wi * WIN, WIN)
      rel = x0 - base
      in0 = (rel >= 0) & (rel < WIN) & valid0
      in1 = (rel + 1 >= 0) & (rel + 1 < WIN) & valid1
      # Masks and lerp weights folded into two per-lane coefficients
      # (shared across channels and band rows; 0 * garbage == 0 exactly).
      a = jnp.where(in0, 1.0 - fx, 0.0)
      b = jnp.where(in1, fx, 0.0)
      i0 = jnp.broadcast_to(jnp.clip(rel, 0, WIN - 1), (BAND, CHUNK))
      i1 = jnp.broadcast_to(jnp.clip(rel + 1, 0, WIN - 1), (BAND, CHUNK))
      outs = []
      for c in range(4):
        win = band_ref[slot, c, :, pl.ds(base, WIN)]      # [BAND, WIN]
        g0 = jnp.take_along_axis(win, i0, axis=1)
        g1 = jnp.take_along_axis(win, i1, axis=1)
        outs.append(g0 * a + g1 * b)
      xles = outs if xles is None else [x + o for x, o in zip(xles, outs)]

    # Vertical lerp for the whole strip: outer-product accumulation over the
    # band rows, exact in f32 (ky columns are nonzero for <= 2 rows each).
    pix = [jnp.zeros((STRIP, CHUNK), jnp.float32) for _ in range(4)]
    for q in range(BAND):
      kyq = ky[:, q:q + 1]                                 # [STRIP, 1]
      pix = [acc + kyq * x[q:q + 1] for acc, x in zip(pix, xles)]
    rgb, alpha = pix[:3], pix[3]
    cols = pl.ds(pl.multiple_of(h * CHUNK, CHUNK), CHUNK)

    for c in range(3):

      @pl.when(p == 0)
      def _init(c=c):
        acc_ref[c, :, cols] = rgb[c]

      @pl.when(p > 0)
      def _fold(c=c):
        prev = acc_ref[c, :, cols]
        acc_ref[c, :, cols] = rgb[c] * alpha + prev * (1.0 - alpha)

    return carry

  jax.lax.fori_loop(0, width // CHUNK, chunk_body, 0)

  @pl.when(p == num_planes - 1)
  def _emit():
    out_ref[0] = acc_ref[:]


def _render_kernel(hom_ref, planes_ref, out_ref, band_ref, acc_ref, sem,
                   *, num_planes, height, width):
  s = pl.program_id(0)
  p = pl.program_id(1)
  oy0 = (s * STRIP).astype(jnp.float32)
  hom = [hom_ref[p, k] for k in range(9)]
  ymin = _ymin_of(hom, oy0, height, width)

  # Band DMA: rows [ymin, ymin+BAND) of all 4 channels of plane p.
  dma = pltpu.make_async_copy(
      planes_ref.at[p, :, pl.ds(ymin, BAND), :], band_ref, sem)
  dma.start()
  dma.wait()

  lane = jax.lax.broadcasted_iota(jnp.int32, (STRIP, CHUNK), 1).astype(jnp.float32)
  sub = jax.lax.broadcasted_iota(jnp.int32, (STRIP, CHUNK), 0).astype(jnp.float32)
  qrow = jax.lax.broadcasted_iota(jnp.int32, (BAND, CHUNK), 0).astype(jnp.float32)
  zero4 = lambda: tuple(jnp.zeros((BAND, CHUNK), jnp.float32) for _ in range(4))

  def chunk_body(h, carry):
    ox = lane + (h * CHUNK).astype(jnp.float32)
    oy = sub + oy0
    u, v = _uv(hom, ox, oy)                        # [STRIP, CHUNK]
    x0f = jnp.floor(u)
    fxs = u - x0f
    x0s = x0f.astype(jnp.int32)
    cols = pl.ds(pl.multiple_of(h * CHUNK, CHUNK), CHUNK)

    for r in range(STRIP):
      fx = fxs[r:r + 1]                            # [1, CHUNK]
      x0 = x0s[r:r + 1]
      v_r = v[r:r + 1]
      valid0 = (x0 >= 0) & (x0 <= width - 1)
      valid1 = (x0 + 1 >= 0) & (x0 + 1 <= width - 1)

      # This row's tap extent [x_lo, x_hi + 1] (u is monotone along the row).
      oy_s = oy0 + float(r)
      ua, _ = _uv(hom, (h * CHUNK).astype(jnp.float32), oy_s)
      ub, _ = _uv(hom, (h * CHUNK + CHUNK - 1).astype(jnp.float32), oy_s)
      ua = jnp.where(jnp.isfinite(ua), ua, 0.0)
      ub = jnp.where(jnp.isfinite(ub), ub, 0.0)
      x_lo = jnp.floor(jnp.minimum(ua, ub)).astype(jnp.int32)
      x_hi = jnp.floor(jnp.maximum(ua, ub)).astype(jnp.int32) + 1
      w0 = jnp.clip((x_lo // WIN) * WIN, 0, width - WIN)

      xles = zero4()
      for wi in range(MAX_WINDOWS):
        base = pl.multiple_of(w0 + wi * WIN, WIN)

        def hit(base=base, wi=wi):
          rel = x0 - w0 - wi * WIN
          in0 = (rel >= 0) & (rel < WIN) & valid0
          in1 = (rel + 1 >= 0) & (rel + 1 < WIN) & valid1
          i0 = jnp.broadcast_to(jnp.clip(rel, 0, WIN - 1), (BAND, CHUNK))
          i1 = jnp.broadcast_to(jnp.clip(rel + 1, 0, WIN - 1), (BAND, CHUNK))
          outs = []
          for c in range(4):
            win = band_ref[c, :, pl.ds(base, WIN)]  # [BAND, WIN]
            g0 = jnp.take_along_axis(win, i0, axis=1)
            g1 = jnp.take_along_axis(win, i1, axis=1)
            outs.append(jnp.where(in0, g0, 0.0) * (1.0 - fx)
                        + jnp.where(in1, g1, 0.0) * fx)
          return tuple(outs)

        need = ((base <= x_hi + 1) & (base + WIN > x_lo)
                & (base <= width - WIN))
        got = jax.lax.cond(need, hit, zero4)
        xles = tuple(a + b for a, b in zip(xles, got))

      # Vertical 2-tap lerp as a weighted band reduction; band rows outside
      # the image are excluded by construction (band is clamped in-image).
      ky = jnp.maximum(0.0, 1.0 - jnp.abs(v_r - (qrow + ymin.astype(jnp.float32))))
      pix = [jnp.sum(x * ky, axis=0, keepdims=True) for x in xles]  # [1,CHUNK]
      rgb, alpha = pix[:3], pix[3]

      for c in range(3):

        @pl.when(p == 0)
        def _init(c=c):
          # Farthest plane: alpha ignored (utils.py:152-153).
          acc_ref[c, r:r + 1, cols] = rgb[c]

        @pl.when(p > 0)
        def _fold(c=c):
          prev = acc_ref[c, r:r + 1, cols]
          acc_ref[c, r:r + 1, cols] = rgb[c] * alpha + prev * (1.0 - alpha)

    return carry

  jax.lax.fori_loop(0, width // CHUNK, chunk_body, 0)

  @pl.when(p == num_planes - 1)
  def _emit():
    out_ref[0] = acc_ref[:]


def _tile_sizes(height: int, width: int, n_windows: int):
  """Static tile geometry for the tiled general kernel."""
  tw = next(t for t in (G_TILE_W, 256, CHUNK) if width % t == 0)
  tsrc = min(width, 640 if n_windows == 2 else 1024)
  bandg = G_BAND if height >= G_BAND else BAND
  n_eff = min(n_windows, tsrc // WIN)
  return tw, tsrc, bandg, n_eff


def _tiled_kernel(hom_ref, meta_ref, meta_next_ref, wq_ref, planes_ref,
                  out_ref, band_ref, acc_ref, sems,
                  *, num_planes, height, width, n_windows, tw, tsrc, bandg):
  """General-homography render on 2-D output tiles (the rotation path).

  The legacy strip path holds one 24-row source band for a full-width row
  strip, so any rotation whose source rows drift more than ~16 over the
  whole width (≈0.2° pan at 1080p) falls outside it. Tiling the output into
  ``[STRIP, tw]`` blocks bounds the drift per tile: each (strip, tile,
  plane) step DMAs its own ``[4, bandg, tsrc]`` source rectangle, raising
  the envelope to ~2-3° of rotation at 1080p with exact bilinear output.

  Per output row the vertical lerp reads only a 16-row slice of the band
  (``pl.ds(q0, G_SLICE)``, 8-aligned per row-chunk) — 2x fewer gathered
  elements than a full-band gather. x-taps come from ``n_windows``
  unconditional 128-lane windows per row-chunk, bases aligned down from
  that row's leftmost tap relative to the tile origin.

  All data-dependent scalars (tile band origins ``ymin``/``xmin``, per-
  row-chunk window base ``w0`` and band-slice offset ``q0``) are
  precomputed VECTORIZED on the VPU by ``_tiled_call`` (inside the same
  jit) and fed in as SMEM-blocked tables: an earlier revision derived them
  in-kernel from chunk-boundary homography evaluations, and those ~48
  scalar-core divides per grid step dominated the whole frame (~60 of
  149 ms at 1080p). ``_plan_tiled`` is the host-side mirror of the table
  math for the envelope/fallback decision.
  """
  s = pl.program_id(0)
  t = pl.program_id(1)
  p = pl.program_id(2)
  n_t = pl.num_programs(1)
  step = (s * n_t + t) * num_planes + p
  total = pl.num_programs(0) * n_t * num_planes
  slot = jax.lax.rem(step, 2)
  hom = [hom_ref[p, k] for k in range(9)]
  c_t = tw // CHUNK
  ymin = pl.multiple_of(meta_ref[0, 0, 0, p], 8)
  xmin = pl.multiple_of(meta_ref[0, 0, 1, p], WIN)

  @pl.when(step == 0)
  def _first_dma():
    pltpu.make_async_copy(
        planes_ref.at[p, :, pl.ds(ymin, bandg), pl.ds(xmin, tsrc)],
        band_ref.at[0], sems.at[0]).start()

  pltpu.make_async_copy(
      planes_ref.at[p, :, pl.ds(ymin, bandg), pl.ds(xmin, tsrc)],
      band_ref.at[slot], sems.at[slot]).wait()

  @pl.when(step < total - 1)
  def _next_dma():
    same_tile = p + 1 < num_planes
    p_n = jnp.where(same_tile, p + 1, 0)
    ymin_n = pl.multiple_of(meta_next_ref[0, 0, 0, p_n], 8)
    xmin_n = pl.multiple_of(meta_next_ref[0, 0, 1, p_n], WIN)
    pltpu.make_async_copy(
        planes_ref.at[p_n, :, pl.ds(ymin_n, bandg), pl.ds(xmin_n, tsrc)],
        band_ref.at[1 - slot], sems.at[1 - slot]).start()

  lane = jax.lax.broadcasted_iota(jnp.int32, (STRIP, tw), 1).astype(jnp.float32)
  sub = jax.lax.broadcasted_iota(jnp.int32, (STRIP, tw), 0).astype(jnp.float32)
  u, v = _uv(hom, lane + (t * tw).astype(jnp.float32),
             sub + (s * STRIP).astype(jnp.float32))          # [STRIP, tw]
  x0f = jnp.floor(u)
  fxs = u - x0f
  x0s = x0f.astype(jnp.int32)
  qrow = jax.lax.broadcasted_iota(
      jnp.int32, (G_SLICE, CHUNK), 0).astype(jnp.float32)

  for r in range(STRIP):
    for ci in range(c_t):
      w0 = pl.multiple_of(wq_ref[0, 0, p, r, ci * 2], WIN)
      q0 = pl.multiple_of(wq_ref[0, 0, p, r, ci * 2 + 1], 8)

      sl = slice(ci * CHUNK, (ci + 1) * CHUNK)
      fx = fxs[r:r + 1, sl]                                  # [1, CHUNK]
      x0 = x0s[r:r + 1, sl]
      v_r = v[r:r + 1, sl]
      valid0 = (x0 >= 0) & (x0 <= width - 1)
      valid1 = (x0 + 1 >= 0) & (x0 + 1 <= width - 1)
      xrel = x0 - xmin

      xles = None
      for wi in range(n_windows):
        base = pl.multiple_of(w0 + wi * WIN, WIN)
        rel = xrel - base
        in0 = (rel >= 0) & (rel < WIN) & valid0
        in1 = (rel + 1 >= 0) & (rel + 1 < WIN) & valid1
        a = jnp.where(in0, 1.0 - fx, 0.0)
        b = jnp.where(in1, fx, 0.0)
        i0 = jnp.broadcast_to(jnp.clip(rel, 0, WIN - 1), (G_SLICE, CHUNK))
        i1 = jnp.broadcast_to(jnp.clip(rel + 1, 0, WIN - 1), (G_SLICE, CHUNK))
        outs = []
        for c in range(4):
          win = band_ref[slot, c, pl.ds(q0, G_SLICE), pl.ds(base, WIN)]
          g0 = jnp.take_along_axis(win, i0, axis=1)
          g1 = jnp.take_along_axis(win, i1, axis=1)
          outs.append(g0 * a + g1 * b)
        xles = outs if xles is None else [x + o for x, o in zip(xles, outs)]

      ky = jnp.maximum(
          0.0, 1.0 - jnp.abs(v_r - (qrow + (ymin + q0).astype(jnp.float32))))
      pix = [jnp.sum(x * ky, axis=0, keepdims=True) for x in xles]
      rgb, alpha = pix[:3], pix[3]
      cols = pl.ds(pl.multiple_of(ci * CHUNK, CHUNK), CHUNK)

      for c in range(3):

        @pl.when(p == 0)
        def _init(c=c):
          acc_ref[c, r:r + 1, cols] = rgb[c]

        @pl.when(p > 0)
        def _fold(c=c):
          prev = acc_ref[c, r:r + 1, cols]
          acc_ref[c, r:r + 1, cols] = rgb[c] * alpha + prev * (1.0 - alpha)

  @pl.when(p == num_planes - 1)
  def _emit():
    out_ref[0] = acc_ref[:]


def _tiled_tables(homs: jnp.ndarray, height: int, width: int,
                  tw: int, tsrc: int, bandg: int, n_eff: int):
  """Device-side (traceable) per-tile/per-row-chunk scalar tables.

  Returns ``meta [S, T, P, 2]`` (tile band origin ymin, xmin) and
  ``wq [P, H, C, 2]`` (per-row-chunk gather-window base relative to xmin,
  and band-slice offset relative to ymin), all int32 and all aligned for
  direct use as DMA/slice offsets. ``_plan_tiled`` mirrors this math on
  the host for the envelope decision.
  """
  p = homs.shape[0]
  h9 = homs.reshape(p, 3, 3).astype(jnp.float32)
  c_t = tw // CHUNK
  n_chunks = width // CHUNK
  n_strips = height // STRIP
  n_tiles = width // tw

  def uv(ox, oy):
    den = (h9[:, 2, 0, None, None] * ox + h9[:, 2, 1, None, None] * oy
           + h9[:, 2, 2, None, None])
    u = (h9[:, 0, 0, None, None] * ox + h9[:, 0, 1, None, None] * oy
         + h9[:, 0, 2, None, None]) / den
    v = (h9[:, 1, 0, None, None] * ox + h9[:, 1, 1, None, None] * oy
         + h9[:, 1, 2, None, None]) / den
    return (jnp.where(jnp.isfinite(u), u, 0.0),
            jnp.where(jnp.isfinite(v), v, 0.0))

  # Tile-corner extents -> per-tile band origins.
  oyc = (jnp.arange(n_strips, dtype=jnp.float32)[:, None] * STRIP
         + jnp.array([0.0, STRIP - 1.0])).reshape(-1)        # [S*2]
  oxc = (jnp.arange(n_tiles, dtype=jnp.float32)[:, None] * tw
         + jnp.array([0.0, tw - 1.0])).reshape(-1)           # [T*2]
  u_c, v_c = uv(oxc[None, None, :], oyc[None, :, None])      # [P, S*2, T*2]
  umin = u_c.reshape(p, n_strips, 2, n_tiles, 2).min(axis=(2, 4))
  vmin = v_c.reshape(p, n_strips, 2, n_tiles, 2).min(axis=(2, 4))
  ymin = jnp.clip(jnp.floor(vmin).astype(jnp.int32) - 1, 0,
                  height - bandg) // 8 * 8                   # [P, S, T]
  xmin = jnp.clip(jnp.floor(umin).astype(jnp.int32), 0,
                  width - tsrc) // WIN * WIN

  # Per-row chunk-boundary extents -> window base / band-slice offset.
  rows = jnp.arange(height, dtype=jnp.float32)
  oxb = jnp.arange(n_chunks + 1, dtype=jnp.float32) * CHUNK
  u_b, v_b = uv(oxb[None, None, :], rows[None, :, None])     # [P, H, B]
  x_lo = jnp.floor(
      jnp.minimum(u_b[..., :-1], u_b[..., 1:])).astype(jnp.int32)
  v_lo = jnp.minimum(v_b[..., :-1], v_b[..., 1:])            # [P, H, C]
  tile_of_chunk = jnp.arange(n_chunks) // c_t
  ymin_rc = jnp.repeat(ymin, STRIP, axis=1)[:, :, tile_of_chunk]
  xmin_rc = jnp.repeat(xmin, STRIP, axis=1)[:, :, tile_of_chunk]
  w0 = jnp.clip((x_lo - xmin_rc) // WIN * WIN, 0, tsrc - n_eff * WIN)
  q0 = jnp.clip((jnp.floor(v_lo).astype(jnp.int32) - ymin_rc) // 8 * 8,
                0, bandg - G_SLICE)
  # Layouts put the per-step-blocked axes first (Pallas requires the last
  # two block dims to equal the array dims for SMEM blocks).
  meta = jnp.stack([ymin, xmin], axis=-1).transpose(1, 2, 3, 0)  # [S,T,2,P]
  wq = (jnp.stack([w0, q0], axis=-1)                             # [P,H,C,2]
        .reshape(p, n_strips, STRIP, n_tiles, c_t, 2)
        .transpose(1, 3, 0, 2, 4, 5)
        .reshape(n_strips, n_tiles, p, STRIP, c_t * 2))
  return meta, wq


@functools.partial(jax.jit, static_argnames=("n_windows", "interpret"))
def _tiled_call(planes: jnp.ndarray, homs: jnp.ndarray,
                n_windows: int, interpret: bool) -> jnp.ndarray:
  num_planes, _, height, width = planes.shape
  if height % STRIP or width % CHUNK:
    raise ValueError(
        f"H must be a multiple of {STRIP} and W of {CHUNK}; got "
        f"{height}x{width} (pad the MPI, or use an XLA method)")
  if height < BAND:
    raise ValueError(f"H must be >= {BAND}, got {height}")
  tw, tsrc, bandg, n_eff = _tile_sizes(height, width, n_windows)
  c_t = tw // CHUNK
  n_strips, n_tiles = height // STRIP, width // tw
  homs32 = homs.reshape(num_planes, 9).astype(jnp.float32)
  meta, wq = _tiled_tables(homs32, height, width, tw, tsrc, bandg, n_eff)

  def next_index(s, t, p):
    # The (s, t, p) grid steps with p innermost; clamp at the final step.
    same_tile = p + 1 < num_planes
    t_n = jnp.where(same_tile, t, jnp.where(t + 1 < n_tiles, t + 1, 0))
    s_n = jnp.minimum(
        jnp.where(same_tile | (t + 1 < n_tiles), s, s + 1), n_strips - 1)
    return s_n, t_n, 0, 0

  kernel = functools.partial(
      _tiled_kernel, num_planes=num_planes, height=height, width=width,
      n_windows=n_eff, tw=tw, tsrc=tsrc, bandg=bandg)
  return pl.pallas_call(
      kernel,
      grid=(n_strips, n_tiles, num_planes),
      in_specs=[
          pl.BlockSpec(memory_space=pltpu.SMEM),   # [P, 9] homographies
          pl.BlockSpec((1, 1, 2, num_planes), lambda s, t, p: (s, t, 0, 0),
                       memory_space=pltpu.SMEM),   # meta (this step's tile)
          pl.BlockSpec((1, 1, 2, num_planes), next_index,
                       memory_space=pltpu.SMEM),   # meta (next step's tile)
          pl.BlockSpec((1, 1, num_planes, STRIP, 2 * c_t),
                       lambda s, t, p: (s, t, 0, 0, 0),
                       memory_space=pltpu.SMEM),   # per-row-chunk w0/q0
          pl.BlockSpec(memory_space=pl.ANY),       # [P, 4, H, W] planes (HBM)
      ],
      out_specs=pl.BlockSpec(
          (1, 3, STRIP, tw), lambda s, t, p: (0, 0, s, t)),
      out_shape=jax.ShapeDtypeStruct((1, 3, height, width), jnp.float32),
      scratch_shapes=[
          pltpu.VMEM((2, 4, bandg, tsrc), jnp.float32),
          pltpu.VMEM((3, STRIP, tw), jnp.float32),
          pltpu.SemaphoreType.DMA((2,)),
      ],
      interpret=interpret,
  )(homs32, meta, meta, wq, planes.astype(jnp.float32))[0]


def is_separable(homs, atol: float = 1e-6) -> bool:
  """Whether pixel homographies are axis-aligned (fast-path eligible).

  True when h01 = h10 = h20 = h21 = 0 for every plane — the case for any
  camera translation / zoom (no rotation), which makes u a function of the
  output column only and v of the row only. Call eagerly (outside jit).
  """
  h = np.asarray(homs).reshape(-1, 9)
  return bool(np.all(np.abs(h[:, [1, 3, 6, 7]]) <= atol * np.abs(h[:, 8:9])))


def fits_envelope(homs, height: int, width: int,
                  separable: bool | None = None) -> bool:
  """Eagerly check the fused kernel's exact coverage contract.

  Mirrors the kernel's band / gather-window arithmetic: every in-image
  bilinear tap of every output pixel must land inside the 24-row source band
  its strip DMAs and inside the gather windows its 128-column chunk reaches
  (3 windows separable, 4 general, bases 128-aligned down from the leftmost
  tap). Extrema are evaluated at strip/chunk boundaries, exact for
  projective maps whose denominator keeps one sign over the image (checked);
  sign-changing denominators reject. ``homs`` must be concrete ([P, 3, 3]).
  """
  h = np.asarray(homs, np.float64).reshape(-1, 3, 3)
  if separable is None:
    separable = is_separable(homs)
  n_win = SEP_WINDOWS if separable else MAX_WINDOWS
  p = h.shape[0]

  # Denominator one-signed over the image (else u/v are not edge-monotone).
  cx = np.array([0.0, width - 1.0])
  cy = np.array([0.0, height - 1.0])
  d_corner = (h[:, 2, 0, None, None] * cx[None, :, None]
              + h[:, 2, 1, None, None] * cy[None, None, :])    # [P, 2, 2]
  d_flat = (d_corner + h[:, 2, 2, None, None]).reshape(p, 4)
  if not np.isfinite(d_flat).all():
    return False
  if not np.all((d_flat > 0).all(1) | (d_flat < 0).all(1)):
    return False

  def uv(ox, oy):
    # ox [...,], oy [...] broadcastable against a trailing plane axis.
    den = h[:, 2, 0] * ox + h[:, 2, 1] * oy + h[:, 2, 2]
    u = (h[:, 0, 0] * ox + h[:, 0, 1] * oy + h[:, 0, 2]) / den
    v = (h[:, 1, 0] * ox + h[:, 1, 1] * oy + h[:, 1, 2]) / den
    return u, v

  # --- vertical: per strip, the kernel's corner-based band must hold all
  # in-image taps of every row in the strip (row extrema at ox in {0, W-1}).
  # Separable fast path: v is linear in the row (denominator constant), so
  # strip-corner rows are exact extrema — O(P*S) instead of O(P*H).
  n_strips = height // STRIP
  if separable:
    oy = (np.arange(n_strips, dtype=np.float64)[:, None] * STRIP
          + np.array([0.0, STRIP - 1.0]))                      # [S, 2]
    v_c = ((h[:, 1, 1] * oy[..., None] + h[:, 1, 2])
           / h[:, 2, 2]).transpose(2, 0, 1)                    # [P, S, 2]
    v_c = np.where(np.isfinite(v_c), v_c, 0.0)
    v_lo, v_hi = v_c.min(axis=2), v_c.max(axis=2)              # [P, S]
    vmin_strip = v_lo
  else:
    rows = np.arange(height, dtype=np.float64)                 # [H]
    _, v_edge = uv(cx[:, None, None], rows[None, :, None])     # [2, H, P]
    v_lo = v_edge.min(axis=0).T                                # [P, H]
    v_hi = v_edge.max(axis=0).T
    vs = v_edge.reshape(2, n_strips, STRIP, p)[:, :, [0, STRIP - 1]]
    vmin_strip = np.where(np.isfinite(vs), vs, 0.0).min(axis=(0, 2)).T
  ymin = np.clip(np.floor(vmin_strip).astype(np.int64) - 1, 0,
                 height - BAND) // 8 * 8                       # [P, S]
  if not separable:
    ymin = np.repeat(ymin, STRIP, axis=1)                      # [P, H]
  q_lo = np.maximum(np.floor(v_lo), 0)
  q_hi = np.minimum(np.floor(v_hi) + 1, height - 1)
  # A row is tap-free only when every v is <= -1 or >= H: the boundary taps
  # (row 0 for v in (-1, 0), row H-1 for v in (H-1, H)) carry weight.
  row_empty = (v_hi <= -1) | (v_lo >= height)
  v_ok = row_empty | ((q_lo >= ymin) & (q_hi <= ymin + BAND - 1))
  if not v_ok.all():
    return False

  # --- horizontal: per row and 128-column chunk, all in-image taps must fit
  # the window union [w0, w0 + n_win*WIN) ∩ [0, width) (chunk-edge extrema).
  # Separable fast path: u is row-independent — O(P*C) instead of O(P*C*H).
  if separable:
    x_lo, x_hi = _sep_tap_extents(h, width)                    # [P, C]
  else:
    n_chunks = width // CHUNK
    ox_edges = (np.arange(n_chunks, dtype=np.float64)[:, None] * CHUNK
                + np.array([0.0, CHUNK - 1.0]))                # [C, 2]
    rows = np.arange(height, dtype=np.float64)
    u_e, _ = uv(ox_edges[:, :, None, None], rows[None, None, :, None])
    u_e = np.moveaxis(u_e, -1, 0)                              # [P, C, 2, H]
    u_lo = u_e.min(axis=2)                                     # [P, C, H]
    u_hi = u_e.max(axis=2)
    x_lo = np.floor(np.where(np.isfinite(u_lo), u_lo, 0.0)).astype(np.int64)
    x_hi = np.floor(
        np.where(np.isfinite(u_hi), u_hi, 0.0)).astype(np.int64) + 1
  w0_max = width - 2 * WIN if separable else width - WIN
  w0 = np.clip(x_lo // WIN * WIN, 0, max(w0_max, 0))
  cover_end = np.minimum(w0 + n_win * WIN, width)
  chunk_empty = (x_hi < 0) | (x_lo > width - 1)
  u_ok = chunk_empty | (np.minimum(x_hi, width - 1) <= cover_end - 1)
  return bool(u_ok.all())


def _plan_tiled(homs, height: int, width: int):
  """Minimal window count (2 or 3) for the tiled general kernel, or None.

  The host-side mirror of ``_tiled_tables``: every in-image bilinear tap
  of every output pixel must land inside its tile's ``[bandg, tsrc]``
  source rectangle, its row's ``G_SLICE`` band rows, and its row-chunk's
  gather windows. Returns None (caller falls back to XLA) when the pose is
  outside the kernel envelope or a homography denominator changes sign
  over the image (poles break the edge-monotonicity both this plan and the
  table math rely on). ``homs`` must be concrete ([P, 3, 3]).

  Mirror precision: this runs in f64 while the device tables are f32, so a
  floor() input within ~1 ulp of an integer can resolve differently. Such
  divergence only ever drops a tap whose bilinear weight is the distance
  to that same integer boundary (~1e-4 on 1080p-scale coordinates), so an
  approved pose stays within the 1e-3 parity budget even on mismatch.
  """
  h = np.asarray(homs, np.float64).reshape(-1, 3, 3)
  p = h.shape[0]
  cx = np.array([0.0, width - 1.0])
  cy = np.array([0.0, height - 1.0])
  d_flat = (h[:, 2, 0, None, None] * cx[None, :, None]
            + h[:, 2, 1, None, None] * cy[None, None, :]
            + h[:, 2, 2, None, None]).reshape(p, 4)
  if not np.isfinite(d_flat).all():
    return None
  if not np.all((d_flat > 0).all(1) | (d_flat < 0).all(1)):
    return None

  tw = next(t for t in (G_TILE_W, 256, CHUNK) if width % t == 0)
  c_t = tw // CHUNK
  n_chunks = width // CHUNK
  n_strips = height // STRIP

  def uv(ox, oy):
    den = (h[:, 2, 0, None, None] * ox + h[:, 2, 1, None, None] * oy
           + h[:, 2, 2, None, None])
    u = (h[:, 0, 0, None, None] * ox + h[:, 0, 1, None, None] * oy
         + h[:, 0, 2, None, None]) / den
    v = (h[:, 1, 0, None, None] * ox + h[:, 1, 1, None, None] * oy
         + h[:, 1, 2, None, None]) / den
    return (np.where(np.isfinite(u), u, 0.0),
            np.where(np.isfinite(v), v, 0.0))

  # Tile-corner extents -> per-tile band/slab origins (mirrors tile_origin).
  oyc = (np.arange(n_strips, dtype=np.float64)[:, None] * STRIP
         + np.array([0.0, STRIP - 1.0])).reshape(-1)         # [S*2]
  oxc = (np.arange(width // tw, dtype=np.float64)[:, None] * tw
         + np.array([0.0, tw - 1.0])).reshape(-1)            # [T*2]
  u_c, v_c = uv(oxc[None, None, :], oyc[None, :, None])      # [P, S*2, T*2]
  u_c = u_c.reshape(p, n_strips, 2, -1, 2)
  v_c = v_c.reshape(p, n_strips, 2, -1, 2)
  umin_tile = u_c.min(axis=(2, 4))                           # [P, S, T]
  vmin_tile = v_c.min(axis=(2, 4))
  bandg = G_BAND if height >= G_BAND else BAND
  ymin = np.clip(np.floor(vmin_tile).astype(np.int64) - 1, 0,
                 height - bandg) // 8 * 8                    # [P, S, T]

  # Per-row chunk-boundary evals (mirrors the kernel's bu/bv scalars).
  rows = np.arange(height, dtype=np.float64)
  oxb = np.arange(n_chunks + 1, dtype=np.float64) * CHUNK
  u_b, v_b = uv(oxb[None, None, :], rows[None, :, None])     # [P, H, B]
  x_lo = np.floor(np.minimum(u_b[..., :-1], u_b[..., 1:])).astype(np.int64)
  x_hi = np.floor(np.maximum(u_b[..., :-1], u_b[..., 1:])).astype(np.int64) + 1
  v_lo = np.minimum(v_b[..., :-1], v_b[..., 1:])             # [P, H, C]
  v_hi = np.maximum(v_b[..., :-1], v_b[..., 1:])

  # Chunk ci belongs to tile ci // c_t; row r to strip r // STRIP.
  tile_of_chunk = np.arange(n_chunks) // c_t
  ymin_rc = np.repeat(ymin, STRIP, axis=1)[:, :, tile_of_chunk]  # [P, H, C]

  q0 = np.clip((np.floor(v_lo).astype(np.int64) - ymin_rc) // 8 * 8,
               0, bandg - G_SLICE)
  q_lo = np.maximum(np.floor(v_lo), 0)
  q_hi = np.minimum(np.floor(v_hi) + 1, height - 1)
  empty_v = (v_hi <= -1) | (v_lo >= height)
  v_ok = empty_v | ((q_lo >= ymin_rc + q0)
                    & (q_hi <= ymin_rc + q0 + G_SLICE - 1))
  if not v_ok.all():
    return None

  empty_h = (x_hi < 0) | (x_lo > width - 1)
  for n_windows in (2, 3):
    tsrc = min(width, 640 if n_windows == 2 else 1024)
    n_eff = min(n_windows, tsrc // WIN)
    xmin = np.clip(np.floor(umin_tile).astype(np.int64), 0,
                   width - tsrc) // WIN * WIN                # [P, S, T]
    xmin_rc = np.repeat(xmin, STRIP, axis=1)[:, :, tile_of_chunk]
    w0 = np.clip((x_lo - xmin_rc) // WIN * WIN, 0, tsrc - n_eff * WIN)
    h_ok = empty_h | (
        (np.maximum(x_lo, 0) >= xmin_rc)
        & (np.minimum(x_hi, width - 1) <= xmin_rc + w0 + n_eff * WIN - 1))
    if h_ok.all():
      return n_windows
  return None


def _sep_tap_extents(h, width: int):
  """Per-chunk integer tap extents [x_lo, x_hi] for separable homographies.

  ``h``: ``[P, 3, 3]`` float64. u is row-independent, so chunk-edge u values
  are exact extrema. Shared by ``fits_envelope`` and the window auto-tuner
  so the check and the tuner cannot diverge from each other.
  """
  n_chunks = width // CHUNK
  ox_edges = (np.arange(n_chunks, dtype=np.float64)[:, None] * CHUNK
              + np.array([0.0, CHUNK - 1.0]))                  # [C, 2]
  u_e = ((h[:, 0, 0] * ox_edges[..., None] + h[:, 0, 2])
         / h[:, 2, 2]).transpose(2, 0, 1)                      # [P, C, 2]
  u_e = np.where(np.isfinite(u_e), u_e, 0.0)
  x_lo = np.floor(u_e.min(axis=2)).astype(np.int64)
  x_hi = np.floor(u_e.max(axis=2)).astype(np.int64) + 1
  return x_lo, x_hi


@functools.partial(
    jax.jit, static_argnames=("separable", "n_windows", "interpret"))
def _fused_call(planes: jnp.ndarray, homs: jnp.ndarray,
                separable: bool, n_windows: int,
                interpret: bool) -> jnp.ndarray:
  num_planes, _, height, width = planes.shape
  if height % STRIP or width % CHUNK:
    raise ValueError(
        f"H must be a multiple of {STRIP} and W of {CHUNK}; got "
        f"{height}x{width} (pad the MPI, or use an XLA method)")
  if height < BAND:
    raise ValueError(f"H must be >= {BAND}, got {height}")
  if separable and width < 2 * WIN:
    raise ValueError(f"separable path needs W >= {2 * WIN}, got {width}")
  if separable:
    kernel = functools.partial(
        _separable_kernel, num_planes=num_planes, height=height, width=width,
        n_windows=min(n_windows, width // WIN))
    band_shape, sems = (2, 4, BAND, width), pltpu.SemaphoreType.DMA((2,))
  else:
    kernel = functools.partial(
        _render_kernel, num_planes=num_planes, height=height, width=width)
    band_shape, sems = (4, BAND, width), pltpu.SemaphoreType.DMA
  return pl.pallas_call(
      kernel,
      grid=(height // STRIP, num_planes),
      in_specs=[
          pl.BlockSpec(memory_space=pltpu.SMEM),   # [P, 9] homographies
          pl.BlockSpec(memory_space=pl.ANY),       # [P, 4, H, W] planes (HBM)
      ],
      out_specs=pl.BlockSpec((1, 3, STRIP, width), lambda s, p: (0, 0, s, 0)),
      out_shape=jax.ShapeDtypeStruct((1, 3, height, width), jnp.float32),
      scratch_shapes=[
          pltpu.VMEM(band_shape, jnp.float32),
          pltpu.VMEM((3, STRIP, width), jnp.float32),
          sems,
      ],
      interpret=interpret,
  )(homs.reshape(num_planes, 9).astype(jnp.float32),
    planes.astype(jnp.float32))[0]


def reference_render(planes: jnp.ndarray, homs: jnp.ndarray) -> jnp.ndarray:
  """XLA gather-path render with the kernel's pixel-space contract.

  Used as the numerical oracle in tests and as the VJP of the fused kernel.
  """
  _, _, h, w = planes.shape
  nhwc = jnp.moveaxis(planes, 1, -1)[:, None]            # [P, 1, H, W, 4]
  grid = jnp.moveaxis(geometry.homogeneous_grid(h, w), 0, -1)
  pts = geometry.apply_homography(grid, homs[:, None])
  xy = geometry.from_homogeneous(pts)                    # [P, 1, H, W, 2]
  # Sampler maps (0,1) coords via px = c*W - 0.5; invert to feed raw pixels.
  coords = (xy + 0.5) / jnp.array([w, h], xy.dtype)
  warped = sampling.bilinear_sample(nhwc, coords)
  out = compose.over_composite_scan(warped)              # [1, H, W, 3]
  return jnp.moveaxis(out[0], -1, 0)


def _make_fused(separable: bool, n_windows: int):

  @jax.custom_vjp
  def fused(planes, homs):
    return _fused_call(planes, homs, separable, n_windows,
                       jax.default_backend() != "tpu")

  def fwd(planes, homs):
    return fused(planes, homs), (planes, homs)

  def bwd(res, g):
    planes, homs = res
    _, vjp = jax.vjp(reference_render, planes, homs)
    return vjp(g)

  fused.defvjp(fwd, bwd)
  return fused


_FUSED = {(sep, n): _make_fused(sep, n)
          for sep, n in ((False, 2), (True, 2), (True, SEP_WINDOWS))}


def _make_tiled(n_windows: int):

  @jax.custom_vjp
  def tiled(planes, homs):
    return _tiled_call(planes, homs, n_windows,
                       jax.default_backend() != "tpu")

  def fwd(planes, homs):
    return tiled(planes, homs), (planes, homs)

  def bwd(res, g):
    planes, homs = res
    _, vjp = jax.vjp(reference_render, planes, homs)
    return vjp(g)

  tiled.defvjp(fwd, bwd)
  return tiled


_TILED = {n: _make_tiled(n) for n in (2, 3)}

# Jitted fallback: the eager reference path materializes per-op temporaries
# (several GB at 1080p x 32 planes); under jit XLA schedules them.
_reference_render_jit = jax.jit(reference_render)


def _sep_windows_needed(homs, height: int, width: int) -> int:
  """Minimal separable-path window count (2 or 3) for concrete homographies.

  2 covers any chunk whose taps span <= WIN+1 source columns from the
  aligned-down base (always true for |h00/h22| <= 1.0); chunks reaching
  further need the third window. Mirrors the kernel's w0 computation.
  """
  h = np.asarray(homs, np.float64).reshape(-1, 3, 3)
  x_lo, x_hi = _sep_tap_extents(h, width)
  w0 = np.clip(x_lo // WIN * WIN, 0, max(width - 2 * WIN, 0))
  need3 = np.minimum(x_hi, width - 1) >= w0 + 2 * WIN
  return SEP_WINDOWS if bool(need3.any()) else 2


def render_mpi_fused(planes: jnp.ndarray, homs: jnp.ndarray,
                     separable: bool = False,
                     check: bool = True) -> jnp.ndarray:
  """Render an MPI to a novel view in one fused TPU kernel.

  Args:
    planes: ``[P, 4, H, W]`` planar RGBA MPI, back-to-front.
    homs: ``[P, 3, 3]`` target-pixel -> source-pixel homographies
      (``pixel_homographies(...)[:, b]`` for batch entry b).
    separable: static flag selecting the shared-gather fast path; only valid
      when ``is_separable(homs)`` (axis-aligned warps, e.g. any pure camera
      translation/zoom). The result is identical either way; the fast path
      is ~10x quicker.
    check: when ``homs`` is concrete (not a jit tracer), verify the kernel's
      coverage envelope with ``fits_envelope`` and transparently fall back
      to the XLA ``reference_render`` path if the pose is outside it, so
      out-of-envelope poses return correct pixels instead of silently
      dropping taps. The separable check is O(P·(S+C)) host math —
      microseconds against a ~30 ms 1080p render. The separable gather-
      window count is also auto-tuned from the concrete homographies
      (2 when the pose provably needs no third window — any horizontal
      scale <= 1.0, the usual novel-view case — else 3). Under jit the
      homographies are tracers: no check is possible, the separable path
      conservatively uses 3 windows, and callers jitting over poses own the
      envelope (or should use an XLA method).

  Returns:
    ``[3, H, W]`` rendered view, float32.
  """
  _, _, height, width = planes.shape
  shapes_ok = not (height % STRIP or width % CHUNK) and height >= BAND
  homs_concrete = not isinstance(homs, jax.core.Tracer)
  if separable:
    n_windows = SEP_WINDOWS
    if homs_concrete and shapes_ok:
      n_windows = _sep_windows_needed(homs, height, width)
    if (check and homs_concrete and shapes_ok
        and not fits_envelope(homs, height, width, True)):
      return _reference_render_jit(planes, homs)
    return _FUSED[True, n_windows](planes, homs)

  # General path: rotations go through the tiled kernel, planned eagerly
  # (per-tile origins + window counts mirrored from concrete homographies).
  if check and homs_concrete and shapes_ok:
    plan = _plan_tiled(homs, height, width)
    if plan is None:
      return _reference_render_jit(planes, homs)
    return _TILED[plan](planes, homs)
  # Traced or opted-out general calls keep the legacy strip kernel (tiny
  # rotation envelope; callers own it via fits_envelope).
  return _FUSED[False, 2](planes, homs)
