"""Fused Pallas TPU kernel: homography warp + bilinear sample + over-composite.

The reference renders a novel view by warping every MPI plane with
``grid_sample`` and compositing back-to-front (utils.py:267-294). A literal
port runs the warp as an XLA ``gather`` — which TPUs execute essentially
scalar-by-scalar (~6 s/frame at 1080p x 32 planes, measured). This kernel is
the TPU-native redesign that makes the 30 FPS target reachable: the whole
render is ONE kernel with no warped-plane stack, no XLA gather, and HBM
traffic within ~2x of the theoretical minimum (read each plane once).

Three kernels share the architecture (strip of 8 output rows per grid step,
planes innermost, double-buffered source-band DMA, running composite in a
VMEM accumulator, farthest plane's alpha ignored per utils.py:152-153):

  - ``_separable_kernel``: axis-aligned homographies (any pure camera
    translation/zoom). u depends only on the column and v only on the row,
    so all 8 rows share their x-tap gathers over a full-width 24-row band
    and the vertical 2-tap lerp is one small MXU matmul per chunk.
  - ``_shared_kernel``: general homographies (rotations), on 2-D output
    tiles with per-tile source rectangles. u at a fixed column is monotone
    in the row (one-signed denominator), so a strip's x-taps per column
    form a fan of 2-3 consecutive columns shared by all 8 rows — the
    gathers amortize across the strip like the separable path. Vertical
    taps are selected per pixel with single-vreg sublane gathers over a
    slice whose height escalates with the pose (``SHARED_LEVELS``: 24-48
    rows — about 1 to ~13 degrees of yaw at 1080p, gather cost linear in
    the slice). All data-dependent scalars come from SMEM tables computed
    vectorized (in the same jit) from cell-corner homography evaluations.
  - ``_banded_kernel``: the per-row middle tier for rotations past the
    slice ladder. Per-ROW gather windows and band
    slices with pose-adaptive tile geometry (``_banded_family``);
    ~8x the shared kernel's gather traffic, still ~an order
    of magnitude above the XLA gather fallback. Dispatch chains
    shared -> banded -> XLA so cost degrades gradually with pose, where
    the reference's one-size grid_sample path (utils.py:104-134) is
    pose-independent.

The bilinear x-taps come from ``tpu.dynamic_gather`` (the HW lane gather,
~750 G elem/s measured); the gather window is one 128-lane vreg, so taps
are gathered from 2-3 statically-planned 128-aligned windows per chunk.

Restrictions (documented contract): tile geometry wants H % 8 == 0,
W % 128 == 0, H >= 24, W >= 256 — other sizes are zero-padded
bottom/right automatically and cropped back, which is EXACT under the
sampler's zeros padding. Per-plane source extents bounded: the separable
strip band allows vertical
scale <= ~1.5; windows cover <= 2*128+1 = 257 source columns per chunk from
the leftmost tap (3 windows: <= ~2.0 horizontal scale). The shared kernel's
per-tile rectangles allow up to ~13 degrees of rotation at 1080p
(per-column row-drift <= 2 for the 3-tap fan, vertical tap span <= 48
rows per strip-chunk at the top slice-ladder level, same window bounds). ``fits_envelope`` / ``_plan_shared`` check the
exact contract eagerly — microseconds of host math — and
``render_mpi_fused`` falls back to the XLA path for out-of-envelope
concrete poses. Under jit no check is possible, so checked calls RAISE and
the unchecked opt-in (``check=False``) is explicit: no code path renders
unchecked taps by default. Outside the envelope (only reachable via that
opt-in) dropped taps produce PARTIAL bilinear sums — dimmed, wrong pixels,
not black — and the backward pass (the XLA reference path via
``jax.custom_vjp``) does not match such a forward; inside the envelope
forward and backward agree.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_vision_tpu.core import compose, geometry, render, sampling
from mpi_vision_tpu.core.sampling import Convention

STRIP = 8      # output rows per grid step
BAND = 24      # source rows held in VMEM (8-aligned start)
CHUNK = 128    # output columns per inner step == one vreg of lanes
WIN = 128      # gather window width == max lane-gather span
SEP_WINDOWS = 3   # separable path: 2 unconditional + 1 conditional

# Shared-gather general path (rotations): 2-D output tiles with per-tile
# source rectangles; horizontal gathers shared by all STRIP rows of a chunk
# (a small tap fan covers the rows' x-drift), vertical taps selected by
# single-vreg sublane gathers.
G_TILE_W = 384   # preferred output tile width (3 chunks)
G_BAND = 32      # source rows per tile band (8-aligned start), base level
G_SHARED = 24    # band rows in the shared gather slice, base level

# Slice-escalation ladder for the shared kernel: (slice rows, band rows).
# A chunk's vertical taps must fit its slice, so the slice height caps the
# per-chunk v-drift — about a degree of yaw at 1080p for the 24-row base.
# Wider slices buy rotation envelope (~13 degrees at the 48-row top) at a
# linear cost in gather traffic (every lane gather spans slc sublanes) and
# DMA amplification (taller tile bands), still far below the banded tier's
# per-row-window formulation. The planner walks the ladder cheapest-first.
SHARED_LEVELS = ((24, 32), (32, 48), (40, 64), (48, 80))


def pixel_homographies(
    tgt_pose: jnp.ndarray,
    depths: jnp.ndarray,
    intrinsics: jnp.ndarray,
    height: int,
    width: int,
    convention: Convention = Convention.EXACT,
) -> jnp.ndarray:
  """Per-plane 3x3 maps from target *pixel* coords to source *pixel* coords.

  Composes the plane-induced homographies (core/render.py) with the
  convention's (0,1) normalization and the sampler's ``c*size - 0.5`` pixel
  mapping, so the kernel works in raw pixel space. For ``EXACT`` the
  composition is the identity; for the reference conventions it is a
  diagonal rescale + shift (the Q2/Q3 x/y-swapped scales, SURVEY.md §2.8).

  Returns ``[P, B, 3, 3]`` float32.
  """
  homs = render.plane_homographies(tgt_pose, depths, intrinsics)  # [P,B,3,3]
  if convention is Convention.EXACT:
    return homs.astype(jnp.float32)
  if convention is Convention.REF_HOMOGRAPHY:
    # c = (x/(H-1), y/(W-1)); px = c_x*W - 0.5, py = c_y*H - 0.5.
    post = np.array([
        [width / (height - 1), 0.0, -0.5],
        [0.0, height / (width - 1), -0.5],
        [0.0, 0.0, 1.0],
    ], dtype=np.float32)
  elif convention is Convention.REF_PROJECTION:
    # c = ((x+0.5)/H, (y+0.5)/W); px = c_x*W - 0.5, py = c_y*H - 0.5.
    post = np.array([
        [width / height, 0.0, 0.5 * width / height - 0.5],
        [0.0, height / width, 0.5 * height / width - 0.5],
        [0.0, 0.0, 1.0],
    ], dtype=np.float32)
  else:
    raise ValueError(f"unknown convention: {convention!r}")
  return jnp.asarray(post) @ homs.astype(jnp.float32)


def _uv(hom, ox, oy):
  """Apply a 3x3 pixel homography (list of 9 scalars) to pixel coords."""
  d = hom[6] * ox + hom[7] * oy + hom[8]
  r = 1.0 / d
  return (hom[0] * ox + hom[1] * oy + hom[2]) * r, \
         (hom[3] * ox + hom[4] * oy + hom[5]) * r


def _ymin_of(hom, oy0, height, width):
  """Scalar first-source-row (8-aligned, clamped) for a strip at ``oy0``."""
  cs = [_uv(hom, ox, oy)[1]
        for ox in (0.0, float(width - 1))
        for oy in (oy0, oy0 + STRIP - 1)]
  vmin = jnp.minimum(jnp.minimum(cs[0], cs[1]), jnp.minimum(cs[2], cs[3]))
  vmin = jnp.where(jnp.isfinite(vmin), vmin, 0.0)
  ymin = jnp.clip(jnp.floor(vmin).astype(jnp.int32) - 1, 0, height - BAND)
  return pl.multiple_of((ymin // 8) * 8, 8)


def _sep_band_dma(src_ref, band_ref, sems, band0_of, *, step, total, slot,
                  bi, s, p, n_s, num_planes):
  """Double-buffered full-width band DMA for separable-grid kernels.

  Grid contract: ``(batch, strip, plane)`` with plane innermost. Waits for
  this step's ``[4, BAND, W]`` band (from ``src_ref[b, p]`` rows
  ``band0_of(b, p, s)``) in ``band_ref[slot]`` and prefetches the next
  step's into the other slot. ``band0_of`` maps grid indices to the band's
  8-aligned first row (reading homography scalars itself). Shared by the
  forward separable kernel and the backward warp/adjoint kernels
  (render_pallas_bwd) so the prefetch roll-over logic cannot fork.
  """

  @pl.when(step == 0)
  def _first_dma():
    pltpu.make_async_copy(
        src_ref.at[bi, p, :, pl.ds(band0_of(bi, p, s), BAND), :],
        band_ref.at[0], sems.at[0]).start()

  pltpu.make_async_copy(
      src_ref.at[bi, p, :, pl.ds(band0_of(bi, p, s), BAND), :],
      band_ref.at[slot], sems.at[slot]).wait()

  @pl.when(step < total - 1)
  def _next_dma():
    same_strip = p + 1 < num_planes
    p_n = jnp.where(same_strip, p + 1, 0)
    s_wrap = jnp.where(s + 1 < n_s, s + 1, 0)
    s_n = jnp.where(same_strip, s, s_wrap)
    b_n = jnp.where(same_strip | (s + 1 < n_s), bi, bi + 1)
    pltpu.make_async_copy(
        src_ref.at[b_n, p_n, :, pl.ds(band0_of(b_n, p_n, s_n), BAND), :],
        band_ref.at[1 - slot], sems.at[1 - slot]).start()


def _sep_ky(hom, oy0, ymin):
  """Vertical bilinear weight matrix for a separable strip.

  v depends only on the row: ``KY[r, q] = relu(1 - |v_r - (ymin + q)|)``
  is the exact vertical weight of band row ``q`` for strip row ``r``
  (zeros padding included: band rows are always in-image, rows outside
  the band weight to 0). Shared by the forward separable kernel and the
  backward warp kernel. Only the first BAND of the CHUNK lane columns are
  meaningful (consumers index ``ky[:, q]`` for q < BAND).
  """
  sub8 = jax.lax.broadcasted_iota(
      jnp.int32, (STRIP, CHUNK), 0).astype(jnp.float32)
  lane = jax.lax.broadcasted_iota(
      jnp.int32, (STRIP, CHUNK), 1).astype(jnp.float32)
  v8 = (hom[4] * (sub8 + oy0) + hom[5]) / hom[8]
  return jnp.maximum(
      0.0, 1.0 - jnp.abs(v8 - (lane + ymin.astype(jnp.float32))))


def _sep_chunk_sample(hom, band_ref, slot, h, ky, n_windows, width):
  """Warp-sample one [STRIP, CHUNK] output chunk from a separable band.

  The per-chunk sampling core of the separable path, shared by the forward
  kernel and the backward-pass warp kernel (render_pallas_bwd): horizontal
  bilinear taps gathered from ``n_windows`` 128-aligned windows of the
  ``[4, BAND, W]`` band at ``band_ref[slot]``, then the vertical lerp
  ``ky`` (``[STRIP, >=BAND]``: per-row weights over band rows) applied as
  an outer-product accumulation. Returns 4 ``[STRIP, CHUNK]`` channels.
  """
  lane1 = jax.lax.broadcasted_iota(jnp.int32, (1, CHUNK), 1).astype(jnp.float32)
  ox0 = (h * CHUNK).astype(jnp.float32)
  u = (hom[0] * (lane1 + ox0) + hom[2]) / hom[8]        # [1, CHUNK]
  x0f = jnp.floor(u)
  fx = u - x0f
  x0 = x0f.astype(jnp.int32)
  valid0 = (x0 >= 0) & (x0 <= width - 1)
  valid1 = (x0 + 1 >= 0) & (x0 + 1 <= width - 1)

  ua = (hom[0] * ox0 + hom[2]) / hom[8]
  ub = (hom[0] * (ox0 + CHUNK - 1) + hom[2]) / hom[8]
  ua = jnp.where(jnp.isfinite(ua), ua, 0.0)
  ub = jnp.where(jnp.isfinite(ub), ub, 0.0)
  x_lo = jnp.floor(jnp.minimum(ua, ub)).astype(jnp.int32)
  # Clamp so all n_windows gather windows are always in-range; window
  # bases align DOWN from x_lo, so guaranteed coverage from the leftmost
  # tap is (n_windows-1)*WIN + 1 columns.
  w0 = jnp.clip((x_lo // WIN) * WIN, 0, width - n_windows * WIN)

  xles = None
  for wi in range(n_windows):
    base = pl.multiple_of(w0 + wi * WIN, WIN)
    rel = x0 - base
    in0 = (rel >= 0) & (rel < WIN) & valid0
    in1 = (rel + 1 >= 0) & (rel + 1 < WIN) & valid1
    # Masks and lerp weights folded into two per-lane coefficients
    # (shared across channels and band rows; 0 * garbage == 0 exactly).
    a = jnp.where(in0, 1.0 - fx, 0.0)
    b = jnp.where(in1, fx, 0.0)
    i0 = jnp.broadcast_to(jnp.clip(rel, 0, WIN - 1), (BAND, CHUNK))
    i1 = jnp.broadcast_to(jnp.clip(rel + 1, 0, WIN - 1), (BAND, CHUNK))
    outs = []
    for c in range(4):
      win = band_ref[slot, c, :, pl.ds(base, WIN)]      # [BAND, WIN]
      g0 = jnp.take_along_axis(win, i0, axis=1)
      g1 = jnp.take_along_axis(win, i1, axis=1)
      outs.append(g0 * a + g1 * b)
    xles = outs if xles is None else [x + o for x, o in zip(xles, outs)]

  # Vertical lerp for the whole strip: outer-product accumulation over the
  # band rows, exact in f32 (ky columns are nonzero for <= 2 rows each).
  pix = [jnp.zeros((STRIP, CHUNK), jnp.float32) for _ in range(4)]
  for q in range(BAND):
    kyq = ky[:, q:q + 1]                                 # [STRIP, 1]
    pix = [acc + kyq * x[q:q + 1] for acc, x in zip(pix, xles)]
  return pix


def _separable_kernel(hom_ref, planes_ref, out_ref, band_ref, acc_ref, sems,
                      *, num_planes, height, width, n_windows):
  """Fast path for axis-aligned (separable) homographies.

  With h01 = h10 = h20 = h21 = 0, ``u`` depends only on the output column
  and ``v`` only on the output row. All 8 rows of a strip then share their
  x-taps, so each gather serves the whole strip, and the vertical 2-tap lerp
  for the full [8, CHUNK] tile is one small MXU matmul
  ``KY[8, BAND] @ xle[BAND, CHUNK]``. Band DMAs are double-buffered across
  grid steps. The leading grid axis is the batch (one MPI + pose set per
  entry — the whole batch is ONE kernel launch); the composite accumulator
  resets at each entry's first plane.

  ``n_windows`` (static: 2 or 3) is the per-chunk gather-window count, all
  unconditional — branchless beats ``lax.cond`` here (a scalar cond in the
  hot loop measured ~1.7x slower than just doing the third gather). Eager
  callers auto-select it from the concrete homographies (2 suffices for
  horizontal scale <= 1.0 at ANY alignment; 3 guarantees scale <= ~2.0).
  """
  bi = pl.program_id(0)
  s = pl.program_id(1)
  p = pl.program_id(2)
  n_s = pl.num_programs(1)
  step = (bi * n_s + s) * num_planes + p
  total = pl.num_programs(0) * n_s * num_planes
  slot = jax.lax.rem(step, 2)
  hom = [hom_ref[bi, p, k] for k in range(9)]
  oy0 = (s * STRIP).astype(jnp.float32)

  def band0_of(b_, p_, s_):
    return _ymin_of([hom_ref[b_, p_, k] for k in range(9)],
                    (s_ * STRIP).astype(jnp.float32), height, width)

  ymin = band0_of(bi, p, s)
  _sep_band_dma(planes_ref, band_ref, sems, band0_of, step=step,
                total=total, slot=slot, bi=bi, s=s, p=p, n_s=n_s,
                num_planes=num_planes)

  ky = _sep_ky(hom, oy0, ymin)

  def chunk_body(h, carry):
    pix = _sep_chunk_sample(hom, band_ref, slot, h, ky, n_windows, width)
    rgb, alpha = pix[:3], pix[3]
    cols = pl.ds(pl.multiple_of(h * CHUNK, CHUNK), CHUNK)

    for c in range(3):

      @pl.when(p == 0)
      def _init(c=c):
        acc_ref[c, :, cols] = rgb[c]

      @pl.when(p > 0)
      def _fold(c=c):
        prev = acc_ref[c, :, cols]
        acc_ref[c, :, cols] = rgb[c] * alpha + prev * (1.0 - alpha)

    return carry

  jax.lax.fori_loop(0, width // CHUNK, chunk_body, 0)

  @pl.when(p == num_planes - 1)
  def _emit():
    out_ref[0] = acc_ref[:]


def _tile_sizes(height: int, width: int, n_windows: int,
                bandg: int = G_BAND):
  """Static tile geometry for the shared-gather general kernel."""
  tw = next(t for t in (G_TILE_W, 256, CHUNK) if width % t == 0)
  tsrc = min(width, 640 if n_windows == 2 else 1024)
  bandg = bandg if height >= bandg else BAND
  n_eff = min(n_windows, tsrc // WIN)
  return tw, tsrc, bandg, n_eff


def _shared_levels(height: int):
  """The slice-ladder levels usable at ``height``: (slc, bandg) with the
  same small-image band fallback as ``_tile_sizes``, slices strictly
  increasing (a taller band with the same slice adds cost, not coverage).
  """
  out = []
  for slc, bandg in SHARED_LEVELS:
    bg = bandg if height >= bandg else BAND
    sl = min(slc, bg)
    if not out or sl > out[-1][0]:
      out.append((sl, bg))
  return tuple(out)


def _shr_chunk_sample(usl, vsl, band_ref, slot, ymin, xmin, q0, w0,
                      n_taps, n_windows, height, width,
                      slc: int = G_SHARED):
  """Warp-sample one [STRIP, CHUNK] output chunk from a 2-D source band.

  The per-chunk sampling core of the shared-gather general path, shared by
  the forward kernel and the backward-pass warp kernel (render_pallas_bwd).
  ``usl``/``vsl`` are the chunk's source coords; the band at
  ``band_ref[slot]`` is the ``[4, bandg, tsrc]`` rectangle whose origin is
  ``(ymin, xmin)``; ``q0``/``w0`` are the chunk's band-slice offset and
  gather-window base within it. Horizontal taps are a fan of ``n_taps``
  consecutive columns from ``floor(min_row u)`` shared by all strip rows;
  vertical taps are selected per pixel with single-vreg sublane gathers
  over a ``slc``-row slice (a SHARED_LEVELS slice height; the base 24).
  Returns 4 ``[STRIP, CHUNK]`` channels.
  """
  xhat_f = jnp.floor(jnp.min(usl, axis=0, keepdims=True))  # [1, CHUNK]
  xhat = xhat_f.astype(jnp.int32)

  # Vertical taps: slice-relative row of floor(v) and its in-image lerp
  # weights (off-image rows weight to 0 — zeros padding, utils.py:174).
  y0f = jnp.floor(vsl)
  fy = vsl - y0f
  y0 = y0f.astype(jnp.int32)
  qi = y0 - (ymin + q0)                                    # [STRIP, CHUNK]
  w_a = jnp.where((y0 >= 0) & (y0 <= height - 1), 1.0 - fy, 0.0)
  w_b = jnp.where((y0 + 1 >= 0) & (y0 + 1 <= height - 1), fy, 0.0)

  pix = [jnp.zeros(usl.shape, jnp.float32) for _ in range(4)]
  for tt in range(n_taps):
    xt = xhat + tt
    # Exact bilinear weight of integer tap column xt: nonzero (= 1-fx or
    # fx) exactly when xt is one of the pixel's two taps.
    ct = jnp.maximum(0.0, 1.0 - jnp.abs(usl - (xhat_f + float(tt))))
    ct = jnp.where((xt >= 0) & (xt <= width - 1), ct, 0.0)

    rel0 = xt - xmin - w0            # [1, CHUNK], window-0-relative
    xle = None                       # per-channel [slc, CHUNK]
    for wi in range(n_windows):
      rel = rel0 - wi * WIN
      inw = (rel >= 0) & (rel < WIN)
      idx = jnp.broadcast_to(jnp.clip(rel, 0, WIN - 1),
                             (slc,) + usl.shape[1:])
      base = pl.multiple_of(w0 + wi * WIN, WIN)
      outs = []
      for c in range(4):
        win = band_ref[slot, c, pl.ds(q0, slc), pl.ds(base, WIN)]
        g = jnp.take_along_axis(win, idx, axis=1)
        outs.append(jnp.where(inw, g, 0.0))
      xle = outs if xle is None else [a + o for a, o in zip(xle, outs)]

    for c in range(4):
      acc_a = jnp.zeros(usl.shape, jnp.float32)
      acc_b = jnp.zeros(usl.shape, jnp.float32)
      for k in range(slc // 8):
        vreg = xle[c][8 * k:8 * (k + 1)]                   # [8, CHUNK]
        ga = jnp.take_along_axis(vreg, jnp.clip(qi - 8 * k, 0, 7), axis=0)
        gb = jnp.take_along_axis(
            vreg, jnp.clip(qi + 1 - 8 * k, 0, 7), axis=0)
        acc_a = jnp.where((qi >= 8 * k) & (qi < 8 * (k + 1)), ga, acc_a)
        acc_b = jnp.where(
            (qi + 1 >= 8 * k) & (qi + 1 < 8 * (k + 1)), gb, acc_b)
      pix[c] += ct * (w_a * acc_a + w_b * acc_b)
  return pix


def _shared_kernel(hom_ref, meta_ref, meta_next_ref, wq_ref, planes_ref,
                   out_ref, band_ref, acc_ref, sems,
                   *, num_planes, height, width, n_windows, n_taps, tw,
                   tsrc, bandg, slc=G_SHARED):
  """General-homography render on 2-D output tiles (the rotation path).

  The key structural fact this kernel exploits: with a one-signed
  denominator, ``u`` at a fixed column is monotone in the row, so across
  the 8 rows of a strip the integer x-taps of a column span
  ``floor(u_min)..floor(u_max)+1`` — for small rotations a fan of
  ``n_taps`` (2 or 3) consecutive columns starting at
  ``x̂(j) = floor(min_r u(r, j))``. All 8 rows therefore SHARE one lane
  gather per (tap, window, channel) over a ``slc``-row band slice (a
  SHARED_LEVELS ladder level, 24-48 rows), instead of the ~8x gather
  traffic of a per-row formulation (a pure yaw pan has h01 = h21 = 0: u
  is exactly row-independent and the fan is 2 — the bilinear taps
  themselves).

  The vertical 2-tap lerp picks, per output pixel, rows
  ``floor(v), floor(v)+1`` of the gathered slice. Sublane-axis
  ``take_along_axis`` is HW-supported for a single [8, 128] vreg with
  per-sublane/per-lane indices, so each tap is selected with ``slc/8``
  single-vreg sublane gathers + masks (one per 8-row group of the
  slice) — O(1) per pixel, not an O(slc) weighted reduction.

  Tiling the output into ``[STRIP, tw]`` blocks bounds source drift per
  tile: each (strip, tile, plane) step DMAs its own ``[4, bandg, tsrc]``
  source rectangle (double-buffered). All data-dependent scalars (tile
  band origins ``ymin``/``xmin``, per-chunk window base ``w0`` and band-
  slice offset ``q0``) are precomputed VECTORIZED on the VPU by
  ``_shared_tables`` (inside the same jit, from cell-corner homography
  evaluations — exact extrema for one-signed denominators) and fed in as
  SMEM-blocked tables; in-kernel scalar-core divides measured ~60 of
  149 ms at 1080p in an earlier revision. ``_plan_shared`` is the host-
  side mirror of the table math for the envelope/fallback decision and
  the static (n_taps, n_windows) choice.
  """
  bi = pl.program_id(0)
  s = pl.program_id(1)
  t = pl.program_id(2)
  p = pl.program_id(3)
  n_s = pl.num_programs(1)
  n_t = pl.num_programs(2)
  step = ((bi * n_s + s) * n_t + t) * num_planes + p
  total = pl.num_programs(0) * n_s * n_t * num_planes
  slot = jax.lax.rem(step, 2)
  hom = [hom_ref[bi, p, k] for k in range(9)]
  c_t = tw // CHUNK
  ymin = pl.multiple_of(meta_ref[0, 0, 0, 0, p], 8)
  xmin = pl.multiple_of(meta_ref[0, 0, 0, 1, p], WIN)

  @pl.when(step == 0)
  def _first_dma():
    pltpu.make_async_copy(
        planes_ref.at[bi, p, :, pl.ds(ymin, bandg), pl.ds(xmin, tsrc)],
        band_ref.at[0], sems.at[0]).start()

  pltpu.make_async_copy(
      planes_ref.at[bi, p, :, pl.ds(ymin, bandg), pl.ds(xmin, tsrc)],
      band_ref.at[slot], sems.at[slot]).wait()

  @pl.when(step < total - 1)
  def _next_dma():
    same_tile = p + 1 < num_planes
    p_n = jnp.where(same_tile, p + 1, 0)
    last_tile = (t + 1 >= n_t) & (s + 1 >= n_s)
    b_n = jnp.where(same_tile | ~last_tile, bi, bi + 1)
    ymin_n = pl.multiple_of(meta_next_ref[0, 0, 0, 0, p_n], 8)
    xmin_n = pl.multiple_of(meta_next_ref[0, 0, 0, 1, p_n], WIN)
    pltpu.make_async_copy(
        planes_ref.at[b_n, p_n, :, pl.ds(ymin_n, bandg), pl.ds(xmin_n, tsrc)],
        band_ref.at[1 - slot], sems.at[1 - slot]).start()

  lane = jax.lax.broadcasted_iota(jnp.int32, (STRIP, tw), 1).astype(jnp.float32)
  sub = jax.lax.broadcasted_iota(jnp.int32, (STRIP, tw), 0).astype(jnp.float32)
  u, v = _uv(hom, lane + (t * tw).astype(jnp.float32),
             sub + (s * STRIP).astype(jnp.float32))          # [STRIP, tw]
  u = jnp.where(jnp.isfinite(u), u, 0.0)
  v = jnp.where(jnp.isfinite(v), v, 0.0)

  for ci in range(c_t):
    w0 = pl.multiple_of(wq_ref[0, 0, 0, p, ci * 2], WIN)
    q0 = pl.multiple_of(wq_ref[0, 0, 0, p, ci * 2 + 1], 8)
    sl = slice(ci * CHUNK, (ci + 1) * CHUNK)
    pix = _shr_chunk_sample(u[:, sl], v[:, sl], band_ref, slot, ymin, xmin,
                            q0, w0, n_taps, n_windows, height, width, slc)
    rgb, alpha = pix[:3], pix[3]
    cols = pl.ds(pl.multiple_of(ci * CHUNK, CHUNK), CHUNK)
    for c in range(3):

      @pl.when(p == 0)
      def _init(c=c):
        # Farthest plane: alpha ignored (utils.py:152-153).
        acc_ref[c, :, cols] = rgb[c]

      @pl.when(p > 0)
      def _fold(c=c):
        prev = acc_ref[c, :, cols]
        acc_ref[c, :, cols] = rgb[c] * alpha + prev * (1.0 - alpha)

  @pl.when(p == num_planes - 1)
  def _emit():
    out_ref[0] = acc_ref[:]


def _uv_vec(h9, ox, oy):
  """Vectorized homography eval with non-finite guards (traceable)."""
  den = (h9[:, 2, 0, None, None] * ox + h9[:, 2, 1, None, None] * oy
         + h9[:, 2, 2, None, None])
  u = (h9[:, 0, 0, None, None] * ox + h9[:, 0, 1, None, None] * oy
       + h9[:, 0, 2, None, None]) / den
  v = (h9[:, 1, 0, None, None] * ox + h9[:, 1, 1, None, None] * oy
       + h9[:, 1, 2, None, None]) / den
  return (jnp.where(jnp.isfinite(u), u, 0.0),
          jnp.where(jnp.isfinite(v), v, 0.0))


def _corner_mins(h9, height: int, width: int, tw: int):
  """Cell-corner u/v minima per (strip, chunk) and (strip, tile).

  Cell corners are strip top/bottom rows x chunk-boundary columns — exact
  extrema for one-signed denominators, because u and v are monotone in
  each coordinate with the other fixed. Chunk cells aggregate to tile
  cells (c_t chunks per tile). Shared by ``_shared_tables`` and
  ``_plan_shared_stats`` so the plan cannot diverge from the tables.
  """
  p = h9.shape[0]
  c_t = tw // CHUNK
  n_chunks = width // CHUNK
  n_strips = height // STRIP
  n_tiles = width // tw
  oyc = (jnp.arange(n_strips, dtype=jnp.float32)[:, None] * STRIP
         + jnp.array([0.0, STRIP - 1.0])).reshape(-1)        # [S*2]
  oxb = (jnp.arange(n_chunks, dtype=jnp.float32)[:, None] * CHUNK
         + jnp.array([0.0, CHUNK - 1.0])).reshape(-1)        # [C*2]
  u_c, v_c = _uv_vec(h9, oxb[None, None, :], oyc[None, :, None])
  u_c = u_c.reshape(p, n_strips, 2, n_chunks, 2)
  v_c = v_c.reshape(p, n_strips, 2, n_chunks, 2)
  umin_chunk = u_c.min(axis=(2, 4))                          # [P, S, C]
  vmin_chunk = v_c.min(axis=(2, 4))
  umin_tile = umin_chunk.reshape(p, n_strips, n_tiles, c_t).min(axis=3)
  vmin_tile = vmin_chunk.reshape(p, n_strips, n_tiles, c_t).min(axis=3)
  return umin_chunk, vmin_chunk, umin_tile, vmin_tile


def _table_scalars(mins, height: int, width: int, tw: int, tsrc: int,
                   bandg: int, n_eff: int, slc: int = G_SHARED):
  """Aligned table scalars (ymin, xmin [P,S,T]; w0, q0 [P,S,C]) from
  cell-corner minima; the single source of truth for both the SMEM tables
  and the plan's coverage checks."""
  umin_chunk, vmin_chunk, umin_tile, vmin_tile = mins
  c_t = tw // CHUNK
  n_chunks = width // CHUNK
  ymin = jnp.clip(jnp.floor(vmin_tile).astype(jnp.int32) - 1, 0,
                  height - bandg) // 8 * 8                   # [P, S, T]
  xmin = jnp.clip(jnp.floor(umin_tile).astype(jnp.int32), 0,
                  width - tsrc) // WIN * WIN
  tile_of_chunk = jnp.arange(n_chunks) // c_t
  ymin_c = ymin[:, :, tile_of_chunk]                         # [P, S, C]
  xmin_c = xmin[:, :, tile_of_chunk]
  w0 = jnp.clip((jnp.floor(umin_chunk).astype(jnp.int32) - xmin_c)
                // WIN * WIN, 0, tsrc - n_eff * WIN)
  q0 = jnp.clip((jnp.floor(vmin_chunk).astype(jnp.int32) - ymin_c)
                // 8 * 8, 0, bandg - min(slc, bandg))
  return ymin, xmin, ymin_c, xmin_c, w0, q0


def _corner_mins_union(h9_stack: jnp.ndarray, height: int, width: int,
                       tw: int):
  """Cell-corner minima unioned over a stack of homographies.

  ``h9_stack``: ``[K, P, 3, 3]`` — K variants per plane (e.g. the four
  ``hom ∘ shift(±1, ±1)`` maps whose union bounds the backward pass's
  ±1-pixel contributor box). Returns the same four arrays as
  ``_corner_mins`` with minima taken elementwise across K.
  """
  k, p = h9_stack.shape[:2]
  mins = _corner_mins(h9_stack.reshape(k * p, 3, 3), height, width, tw)
  return tuple(m.reshape((k, p) + m.shape[1:]).min(axis=0) for m in mins)


def _shared_tables(homs: jnp.ndarray, height: int, width: int,
                   tw: int, tsrc: int, bandg: int, n_eff: int,
                   mins=None, slc: int = G_SHARED):
  """Device-side (traceable) per-tile/per-chunk scalar tables.

  Returns ``meta [S, T, 2, P]`` (tile band origin ymin, xmin) and
  ``wq [S, T, P, 2*c_t]`` (per-chunk gather-window base relative to xmin
  and band-slice offset relative to ymin, shared by the whole strip),
  all int32 and aligned for direct use as DMA/slice offsets.
  ``_plan_shared`` runs the same math (same helpers, same dtype) for the
  envelope decision. ``mins`` overrides the cell-corner minima (the
  backward pass feeds the shift-union minima from
  ``_corner_mins_union``).
  """
  p = homs.shape[0]
  h9 = homs.reshape(p, 3, 3).astype(jnp.float32)
  c_t = tw // CHUNK
  n_strips = height // STRIP
  n_tiles = width // tw
  if mins is None:
    mins = _corner_mins(h9, height, width, tw)
  ymin, xmin, _, _, w0, q0 = _table_scalars(
      mins, height, width, tw, tsrc, bandg, n_eff, slc)
  # Layouts put the per-step-blocked axes first (Pallas requires the last
  # two block dims to equal the array dims for SMEM blocks).
  meta = jnp.stack([ymin, xmin], axis=-1).transpose(1, 2, 3, 0)  # [S,T,2,P]
  wq = (jnp.stack([w0, q0], axis=-1)                             # [P,S,C,2]
        .reshape(p, n_strips, n_tiles, c_t, 2)
        .transpose(1, 2, 0, 3, 4)
        .reshape(n_strips, n_tiles, p, c_t * 2))
  return meta, wq


def _next_step_index(batch: int, n_strips: int, n_tiles: int,
                     num_planes: int):
  """Index map for the NEXT (b, s, t, p) grid step (p innermost), clamped
  at the final step — the double-buffer prefetch's subtle core, shared by
  every tiled kernel (shared-gather forward, banded tier, and the backward
  kernels via ``_shared_grid_setup``) so the prefetch logic cannot fork.
  Returns ``(b, s, t, p) -> (b_n, s_n, t_n, 0, 0)``.
  """

  def next_index(b, s, t, p):
    same_tile = p + 1 < num_planes
    t_n = jnp.where(same_tile, t, jnp.where(t + 1 < n_tiles, t + 1, 0))
    s_roll = jnp.where(t + 1 < n_tiles, s,
                       jnp.where(s + 1 < n_strips, s + 1, 0))
    s_n = jnp.where(same_tile, s, s_roll)
    last_tile = (t + 1 >= n_tiles) & (s + 1 >= n_strips)
    b_n = jnp.minimum(
        jnp.where(same_tile | ~last_tile, b, b + 1), batch - 1)
    return b_n, s_n, t_n, 0, 0

  return next_index


def _shared_grid_setup(planes: jnp.ndarray, homs: jnp.ndarray,
                       n_windows: int, mins_fn=None,
                       slc: int = G_SHARED, bandg: int = G_BAND):
  """Everything a shared-gather-style pallas_call needs besides its kernel
  body and out specs: tile geometry, SMEM tables, grid, in_specs (incl.
  the subtle next-step prefetch index map), and operands. Shared by the
  forward ``_shared_call`` and the backward warp/adjoint
  (render_pallas_bwd) so the prefetch logic cannot fork. ``mins_fn``
  (per-entry ``homs9 -> _corner_mins``-shaped tuple) overrides the
  cell-corner minima feeding the tables (the adjoint feeds shift-union
  minima)."""
  batch, num_planes, _, height, width = planes.shape
  if height % STRIP or width % CHUNK:
    raise ValueError(
        f"H must be a multiple of {STRIP} and W of {CHUNK}; got "
        f"{height}x{width} (pad the MPI, or use an XLA method)")
  if height < BAND:
    raise ValueError(f"H must be >= {BAND}, got {height}")
  tw, tsrc, bandg, n_eff = _tile_sizes(height, width, n_windows, bandg)
  c_t = tw // CHUNK
  n_strips, n_tiles = height // STRIP, width // tw
  homs32 = homs.reshape(batch, num_planes, 9).astype(jnp.float32)
  meta, wq = jax.vmap(
      lambda h: _shared_tables(
          h, height, width, tw, tsrc, bandg, n_eff,
          mins=None if mins_fn is None else mins_fn(h),
          slc=min(slc, bandg))
  )(homs32)                          # [B, S, T, 2, P], [B, S, T, P, 2c]

  next_index = _next_step_index(batch, n_strips, n_tiles, num_planes)
  grid = (batch, n_strips, n_tiles, num_planes)
  in_specs = [
      pl.BlockSpec(memory_space=pltpu.SMEM),   # [B, P, 9] homographies
      pl.BlockSpec((1, 1, 1, 2, num_planes),
                   lambda b, s, t, p: (b, s, t, 0, 0),
                   memory_space=pltpu.SMEM),   # meta (this step's tile)
      pl.BlockSpec((1, 1, 1, 2, num_planes), next_index,
                   memory_space=pltpu.SMEM),   # meta (next step's tile)
      pl.BlockSpec((1, 1, 1, num_planes, 2 * c_t),
                   lambda b, s, t, p: (b, s, t, 0, 0),
                   memory_space=pltpu.SMEM),   # per-chunk w0/q0
      pl.BlockSpec(memory_space=pl.ANY),       # [B, P, 4, H, W] (HBM)
  ]
  operands = (homs32, meta, meta, wq, planes.astype(jnp.float32))
  geom = dict(tw=tw, tsrc=tsrc, bandg=bandg, n_eff=n_eff, c_t=c_t,
              batch=batch, num_planes=num_planes, height=height,
              width=width, slc=min(slc, bandg))
  return grid, in_specs, operands, geom


@functools.partial(
    jax.jit, static_argnames=("n_taps", "n_windows", "interpret", "slc",
                              "bandg"))
def _shared_call(planes: jnp.ndarray, homs: jnp.ndarray,
                 n_taps: int, n_windows: int, interpret: bool,
                 slc: int = G_SHARED, bandg: int = G_BAND) -> jnp.ndarray:
  """Shared-gather kernel call on a batch ``[B, P, 4, H, W]`` (one launch
  for the whole batch)."""
  grid, in_specs, operands, g = _shared_grid_setup(
      planes, homs, n_windows, slc=slc, bandg=bandg)
  kernel = functools.partial(
      _shared_kernel, num_planes=g["num_planes"], height=g["height"],
      width=g["width"], n_windows=g["n_eff"], n_taps=n_taps, tw=g["tw"],
      tsrc=g["tsrc"], bandg=g["bandg"], slc=g["slc"])
  return pl.pallas_call(
      kernel,
      grid=grid,
      in_specs=in_specs,
      out_specs=pl.BlockSpec(
          (1, 3, STRIP, g["tw"]), lambda b, s, t, p: (b, 0, s, t)),
      out_shape=jax.ShapeDtypeStruct(
          (g["batch"], 3, g["height"], g["width"]), jnp.float32),
      scratch_shapes=[
          pltpu.VMEM((2, 4, g["bandg"], g["tsrc"]), jnp.float32),
          pltpu.VMEM((3, STRIP, g["tw"]), jnp.float32),
          pltpu.SemaphoreType.DMA((2,)),
      ],
      interpret=interpret,
  )(*operands)


# --- Banded per-row middle tier (large rotations) -----------------------
# The shared-gather kernel's strip-shared tap fan caps out when a strip's
# rows stop sharing x-taps (fan > 3 columns) or a chunk's vertical taps
# leave the shared slice — with the SHARED_LEVELS ladder, roughly 13
# degrees of yaw at 1080p at the 48-row top level.
# The reference renders ANY pose through one uniform grid_sample path
# (utils.py:267-294, utils.py:104-134) with pose-independent cost; without
# a middle tier, poses past the shared envelope fall ~45x to the XLA
# gather path. This tier trades gather sharing for generality: per-ROW
# gather windows and band slices (each output row picks its own), with
# tile geometry chosen per pose from a static family — taller bands,
# taller row slices, and narrower tiles buy rotation envelope at the cost
# of DMA read amplification. ~10-12 degrees of roll at 1080p fits the
# (128-wide tile, 64-row band, 32-row slice) member; the planner picks
# the cheapest covering member, so small rotations that just miss the
# shared envelope pay near-shared-tier DMA cost, not worst-case.

# (bandg, slice_rows): the two tall members trade DMA amplification for
# rotation envelope — at 1080p they carry yaw to ~20 deg and roll past
# ~12 deg where the (64, 32) member stops covering (planner-verified per
# pose; VMEM stays modest: a [2, 4, 128, 896] f32 band is 3.7 MB).
_BANDED_LEVELS = ((32, 16), (48, 24), (64, 32), (96, 48), (128, 64))


def _banded_family(height: int, width: int):
  """Static (tw, bandg, slice_rows, tsrc, n_eff) configs, cheapest first.

  Cost ranks by DMA bytes per output pixel (bandg*tsrc / (STRIP*tw))
  PLUS the per-row gather traffic (n_eff * slice_rows vreg-gathers per
  chunk-row), calibrated so the two terms match the roofline's measured
  proportions at the (128-tile, 64-band, 32-slice, 3-window) member
  (artifacts/general_kernel_roofline.md: ~19 FPS gather vs ~41 FPS DMA
  ceiling — gathers bind, so a taller slice must not be preferred just
  because its wider tile reads fewer bytes). Coverage is verified
  exactly per config by ``_plan_banded``, so the ranking only decides
  preference among covering configs. ``tw`` must divide the
  (tile-padded) width; W % 128 == 0 guarantees at least the CHUNK-wide
  member.
  """
  cfgs = []
  for tw in (t for t in (G_TILE_W, 256, CHUNK) if width % t == 0):
    for bandg, slc in _BANDED_LEVELS:
      bg = min(bandg, height // 8 * 8)
      sl = min(slc, bg)
      for n_win in (2, 3):
        tsrc = min(width, tw + WIN * (n_win + 1))
        n_eff = min(n_win, tsrc // WIN)
        cfgs.append((tw, bg, sl, tsrc, n_eff))
  seen, out = set(), []
  for c in sorted(cfgs, key=lambda c: (c[1] * c[3]) / (STRIP * c[0])
                  + c[4] * c[2]):
    if c not in seen:
      seen.add(c)
      out.append(c)
  return out


def _banded_tables(homs: jnp.ndarray, height: int, width: int, tw: int,
                   tsrc: int, bandg: int, slice_rows: int, n_eff: int):
  """Device-side per-tile / per-ROW scalar tables for the banded kernel.

  Same shape discipline as ``_shared_tables`` but the window base ``w0``
  and band-slice offset ``q0`` are per (row, chunk) — computed from
  chunk-boundary homography evaluations per row, exact extrema bounds for
  one-signed denominators (monotone in x at a fixed row; the boundary at
  ``(ci+1)*CHUNK`` over-reaches the chunk's last pixel by one column,
  which only widens the bound — conservative). Returns ``meta
  [S, T, 2, P]`` and ``wq [S, T, P, STRIP, 2*c_t]``, int32, aligned for
  direct use as DMA/slice offsets. ``_plan_banded`` mirrors this math on
  the host for the envelope decision.
  """
  p = homs.shape[0]
  h9 = homs.reshape(p, 3, 3).astype(jnp.float32)
  c_t = tw // CHUNK
  n_chunks = width // CHUNK
  n_strips = height // STRIP
  n_tiles = width // tw
  _, _, umin_tile, vmin_tile = _corner_mins(h9, height, width, tw)
  ymin = jnp.clip(jnp.floor(vmin_tile).astype(jnp.int32) - 1, 0,
                  height - bandg) // 8 * 8                   # [P, S, T]
  xmin = jnp.clip(jnp.floor(umin_tile).astype(jnp.int32), 0,
                  width - tsrc) // WIN * WIN

  rows = jnp.arange(height, dtype=jnp.float32)
  oxb = jnp.arange(n_chunks + 1, dtype=jnp.float32) * CHUNK
  u_b, v_b = _uv_vec(h9, oxb[None, None, :], rows[None, :, None])  # [P,H,C+1]
  x_lo = jnp.floor(
      jnp.minimum(u_b[..., :-1], u_b[..., 1:])).astype(jnp.int32)
  v_lo = jnp.minimum(v_b[..., :-1], v_b[..., 1:])            # [P, H, C]
  tile_of_chunk = jnp.arange(n_chunks) // c_t
  ymin_rc = jnp.repeat(ymin, STRIP, axis=1)[:, :, tile_of_chunk]
  xmin_rc = jnp.repeat(xmin, STRIP, axis=1)[:, :, tile_of_chunk]
  w0 = jnp.clip((x_lo - xmin_rc) // WIN * WIN, 0, tsrc - n_eff * WIN)
  q0 = jnp.clip((jnp.floor(v_lo).astype(jnp.int32) - ymin_rc) // 8 * 8,
                0, bandg - slice_rows)
  meta = jnp.stack([ymin, xmin], axis=-1).transpose(1, 2, 3, 0)  # [S,T,2,P]
  wq = (jnp.stack([w0, q0], axis=-1)                             # [P,H,C,2]
        .reshape(p, n_strips, STRIP, n_tiles, c_t, 2)
        .transpose(1, 3, 0, 2, 4, 5)
        .reshape(n_strips, n_tiles, p, STRIP, c_t * 2))
  return meta, wq


def _banded_kernel(hom_ref, meta_ref, meta_next_ref, wq_ref, planes_ref,
                   out_ref, band_ref, acc_ref, sems,
                   *, num_planes, height, width, n_windows, tw, tsrc,
                   bandg, slice_rows):
  """Per-row general-homography render on 2-D output tiles (middle tier).

  Structure matches ``_shared_kernel`` (same grid, same double-buffered
  per-tile band DMA, same SMEM table plumbing) but sampling is per ROW:
  each of the strip's 8 rows picks its own ``n_windows`` 128-lane gather
  windows (base ``w0`` from its leftmost tap) and its own ``slice_rows``
  band slice (offset ``q0``), then the vertical 2-tap lerp is a
  tent-filter weighted reduction over the slice. No cross-row sharing —
  ~8x the gather traffic of the shared kernel — but the envelope is set
  only by per-row-chunk drift against ``slice_rows`` and the tile band,
  not by a strip-wide tap fan: the family's tall-band members hold to
  ~10+ degrees of rotation at 1080p where the shared kernel caps out
  around one degree.
  """
  bi = pl.program_id(0)
  s = pl.program_id(1)
  t = pl.program_id(2)
  p = pl.program_id(3)
  n_s = pl.num_programs(1)
  n_t = pl.num_programs(2)
  step = ((bi * n_s + s) * n_t + t) * num_planes + p
  total = pl.num_programs(0) * n_s * n_t * num_planes
  slot = jax.lax.rem(step, 2)
  hom = [hom_ref[bi, p, k] for k in range(9)]
  c_t = tw // CHUNK
  ymin = pl.multiple_of(meta_ref[0, 0, 0, 0, p], 8)
  xmin = pl.multiple_of(meta_ref[0, 0, 0, 1, p], WIN)

  @pl.when(step == 0)
  def _first_dma():
    pltpu.make_async_copy(
        planes_ref.at[bi, p, :, pl.ds(ymin, bandg), pl.ds(xmin, tsrc)],
        band_ref.at[0], sems.at[0]).start()

  pltpu.make_async_copy(
      planes_ref.at[bi, p, :, pl.ds(ymin, bandg), pl.ds(xmin, tsrc)],
      band_ref.at[slot], sems.at[slot]).wait()

  @pl.when(step < total - 1)
  def _next_dma():
    same_tile = p + 1 < num_planes
    p_n = jnp.where(same_tile, p + 1, 0)
    last_tile = (t + 1 >= n_t) & (s + 1 >= n_s)
    b_n = jnp.where(same_tile | ~last_tile, bi, bi + 1)
    ymin_n = pl.multiple_of(meta_next_ref[0, 0, 0, 0, p_n], 8)
    xmin_n = pl.multiple_of(meta_next_ref[0, 0, 0, 1, p_n], WIN)
    pltpu.make_async_copy(
        planes_ref.at[b_n, p_n, :, pl.ds(ymin_n, bandg), pl.ds(xmin_n, tsrc)],
        band_ref.at[1 - slot], sems.at[1 - slot]).start()

  lane = jax.lax.broadcasted_iota(jnp.int32, (STRIP, tw), 1).astype(jnp.float32)
  sub = jax.lax.broadcasted_iota(jnp.int32, (STRIP, tw), 0).astype(jnp.float32)
  u, v = _uv(hom, lane + (t * tw).astype(jnp.float32),
             sub + (s * STRIP).astype(jnp.float32))          # [STRIP, tw]
  u = jnp.where(jnp.isfinite(u), u, 0.0)
  v = jnp.where(jnp.isfinite(v), v, 0.0)
  x0f = jnp.floor(u)
  fxs = u - x0f
  x0s = x0f.astype(jnp.int32)
  qrow = jax.lax.broadcasted_iota(
      jnp.int32, (slice_rows, CHUNK), 0).astype(jnp.float32)

  for r in range(STRIP):
    for ci in range(c_t):
      w0 = pl.multiple_of(wq_ref[0, 0, 0, p, r, ci * 2], WIN)
      q0 = pl.multiple_of(wq_ref[0, 0, 0, p, r, ci * 2 + 1], 8)

      sl = slice(ci * CHUNK, (ci + 1) * CHUNK)
      fx = fxs[r:r + 1, sl]                                  # [1, CHUNK]
      x0 = x0s[r:r + 1, sl]
      v_r = v[r:r + 1, sl]
      valid0 = (x0 >= 0) & (x0 <= width - 1)
      valid1 = (x0 + 1 >= 0) & (x0 + 1 <= width - 1)
      xrel = x0 - xmin

      xles = None
      for wi in range(n_windows):
        base = pl.multiple_of(w0 + wi * WIN, WIN)
        rel = xrel - base
        in0 = (rel >= 0) & (rel < WIN) & valid0
        in1 = (rel + 1 >= 0) & (rel + 1 < WIN) & valid1
        a = jnp.where(in0, 1.0 - fx, 0.0)
        b = jnp.where(in1, fx, 0.0)
        i0 = jnp.broadcast_to(jnp.clip(rel, 0, WIN - 1),
                              (slice_rows, CHUNK))
        i1 = jnp.broadcast_to(jnp.clip(rel + 1, 0, WIN - 1),
                              (slice_rows, CHUNK))
        outs = []
        for c in range(4):
          win = band_ref[slot, c, pl.ds(q0, slice_rows), pl.ds(base, WIN)]
          g0 = jnp.take_along_axis(win, i0, axis=1)
          g1 = jnp.take_along_axis(win, i1, axis=1)
          outs.append(g0 * a + g1 * b)
        xles = outs if xles is None else [x + o for x, o in zip(xles, outs)]

      ky = jnp.maximum(
          0.0, 1.0 - jnp.abs(v_r - (qrow + (ymin + q0).astype(jnp.float32))))
      pix = [jnp.sum(x * ky, axis=0, keepdims=True) for x in xles]
      rgb, alpha = pix[:3], pix[3]
      cols = pl.ds(pl.multiple_of(ci * CHUNK, CHUNK), CHUNK)

      for c in range(3):

        @pl.when(p == 0)
        def _init(c=c):
          # Farthest plane: alpha ignored (utils.py:152-153).
          acc_ref[c, r:r + 1, cols] = rgb[c]

        @pl.when(p > 0)
        def _fold(c=c):
          prev = acc_ref[c, r:r + 1, cols]
          acc_ref[c, r:r + 1, cols] = rgb[c] * alpha + prev * (1.0 - alpha)

  @pl.when(p == num_planes - 1)
  def _emit():
    out_ref[0] = acc_ref[:]


@functools.partial(jax.jit, static_argnames=(
    "tw", "bandg", "slice_rows", "tsrc", "n_windows", "interpret"))
def _banded_call(planes: jnp.ndarray, homs: jnp.ndarray, tw: int, bandg: int,
                 slice_rows: int, tsrc: int, n_windows: int,
                 interpret: bool) -> jnp.ndarray:
  """Banded-tier kernel call on a batch ``[B, P, 4, H, W]`` (one launch)."""
  batch, num_planes, _, height, width = planes.shape
  if height % STRIP or width % CHUNK or width % tw:
    raise ValueError(
        f"H must be a multiple of {STRIP} and W of {CHUNK} and of tw={tw}; "
        f"got {height}x{width} (pad the MPI, or use an XLA method)")
  if height < bandg:
    raise ValueError(f"H must be >= bandg={bandg}, got {height}")
  c_t = tw // CHUNK
  n_strips, n_tiles = height // STRIP, width // tw
  homs32 = homs.reshape(batch, num_planes, 9).astype(jnp.float32)
  meta, wq = jax.vmap(
      lambda h: _banded_tables(h, height, width, tw, tsrc, bandg,
                               slice_rows, n_windows)
  )(homs32)                    # [B, S, T, 2, P], [B, S, T, P, STRIP, 2c]

  next_index = _next_step_index(batch, n_strips, n_tiles, num_planes)
  kernel = functools.partial(
      _banded_kernel, num_planes=num_planes, height=height, width=width,
      n_windows=n_windows, tw=tw, tsrc=tsrc, bandg=bandg,
      slice_rows=slice_rows)
  return pl.pallas_call(
      kernel,
      grid=(batch, n_strips, n_tiles, num_planes),
      in_specs=[
          pl.BlockSpec(memory_space=pltpu.SMEM),   # [B, P, 9] homographies
          pl.BlockSpec((1, 1, 1, 2, num_planes),
                       lambda b, s, t, p: (b, s, t, 0, 0),
                       memory_space=pltpu.SMEM),   # meta (this step's tile)
          pl.BlockSpec((1, 1, 1, 2, num_planes), next_index,
                       memory_space=pltpu.SMEM),   # meta (next step's tile)
          pl.BlockSpec((1, 1, 1, num_planes, STRIP, 2 * c_t),
                       lambda b, s, t, p: (b, s, t, 0, 0, 0),
                       memory_space=pltpu.SMEM),   # per-row w0/q0
          pl.BlockSpec(memory_space=pl.ANY),       # [B, P, 4, H, W] (HBM)
      ],
      out_specs=pl.BlockSpec(
          (1, 3, STRIP, tw), lambda b, s, t, p: (b, 0, s, t)),
      out_shape=jax.ShapeDtypeStruct(
          (batch, 3, height, width), jnp.float32),
      scratch_shapes=[
          pltpu.VMEM((2, 4, bandg, tsrc), jnp.float32),
          pltpu.VMEM((3, STRIP, tw), jnp.float32),
          pltpu.SemaphoreType.DMA((2,)),
      ],
      interpret=interpret,
  )(homs32, meta, meta, wq, planes.astype(jnp.float32))


def is_separable(homs, atol: float = 1e-6) -> bool:
  """Whether pixel homographies are axis-aligned (fast-path eligible).

  True when h01 = h10 = h20 = h21 = 0 for every plane — the case for any
  camera translation / zoom (no rotation), which makes u a function of the
  output column only and v of the row only. Call eagerly (outside jit).
  """
  h = np.asarray(homs).reshape(-1, 9)
  return bool(np.all(np.abs(h[:, [1, 3, 6, 7]]) <= atol * np.abs(h[:, 8:9])))


def fits_envelope(homs, height: int, width: int,
                  separable: bool | None = None) -> bool:
  """Eagerly check the fused kernels' exact coverage contract.

  For separable homographies, mirrors the separable strip kernel's band /
  gather-window arithmetic: every in-image bilinear tap of every output
  pixel must land inside the 24-row source band its strip DMAs and inside
  the gather windows its 128-column chunk reaches (bases 128-aligned down
  from the leftmost tap). Extrema are evaluated at strip/chunk boundaries,
  exact for projective maps whose denominator keeps one sign over the image
  (checked); sign-changing denominators reject. For general homographies,
  answers for the full Pallas dispatch chain — the shared-gather kernel OR
  the banded per-row middle tier (``_plan_shared`` / ``_plan_banded``), the
  same chain ``render_mpi_fused(check=True)`` walks before falling back to
  XLA. ``homs`` must be concrete; leading batch axes flatten into the plane
  axis ([P, 3, 3] or [B, P, 3, 3]).

  A True result licenses ``check=False`` rendering — but for general
  poses that only the BANDED tier covers, only together with the explicit
  ``("banded", ...)`` plan from ``plan_fused``: the shared-gather kernel
  (what an unplanned ``check=False`` call runs, at the top slice-ladder
  level) cannot cover banded-only poses at any level. Shared-envelope and
  separable poses are safe unplanned.
  """
  auto = separable is None
  if auto:
    separable = is_separable(homs)
  if not separable:
    return (_plan_shared(homs, height, width) is not None
            or _plan_banded(homs, height, width) is not None)
  if not auto and not is_separable(homs):
    # A caller-asserted separable flag on non-separable homographies is a
    # contract violation; reject so checked callers fall back safely.
    return False
  h = np.asarray(homs, np.float64).reshape(-1, 3, 3)
  n_win = SEP_WINDOWS
  p = h.shape[0]

  # Denominator one-signed over the image (else u/v are not edge-monotone).
  cx = np.array([0.0, width - 1.0])
  cy = np.array([0.0, height - 1.0])
  d_corner = (h[:, 2, 0, None, None] * cx[None, :, None]
              + h[:, 2, 1, None, None] * cy[None, None, :])    # [P, 2, 2]
  d_flat = (d_corner + h[:, 2, 2, None, None]).reshape(p, 4)
  if not np.isfinite(d_flat).all():
    return False
  if not np.all((d_flat > 0).all(1) | (d_flat < 0).all(1)):
    return False

  # --- vertical: per strip, the kernel's corner-based band must hold all
  # in-image taps of every row in the strip. v is linear in the row
  # (denominator constant for separable maps), so strip-corner rows are
  # exact extrema — O(P*S) instead of O(P*H).
  n_strips = height // STRIP
  oy = (np.arange(n_strips, dtype=np.float64)[:, None] * STRIP
        + np.array([0.0, STRIP - 1.0]))                      # [S, 2]
  v_c = ((h[:, 1, 1] * oy[..., None] + h[:, 1, 2])
         / h[:, 2, 2]).transpose(2, 0, 1)                    # [P, S, 2]
  v_c = np.where(np.isfinite(v_c), v_c, 0.0)
  v_lo, v_hi = v_c.min(axis=2), v_c.max(axis=2)              # [P, S]
  ymin = np.clip(np.floor(v_lo).astype(np.int64) - 1, 0,
                 height - BAND) // 8 * 8                     # [P, S]
  q_lo = np.maximum(np.floor(v_lo), 0)
  q_hi = np.minimum(np.floor(v_hi) + 1, height - 1)
  # A row is tap-free only when every v is <= -1 or >= H: the boundary taps
  # (row 0 for v in (-1, 0), row H-1 for v in (H-1, H)) carry weight.
  row_empty = (v_hi <= -1) | (v_lo >= height)
  v_ok = row_empty | ((q_lo >= ymin) & (q_hi <= ymin + BAND - 1))
  if not v_ok.all():
    return False

  # --- horizontal: per 128-column chunk, all in-image taps must fit the
  # window union [w0, w0 + n_win*WIN) ∩ [0, width) (chunk-edge extrema;
  # u is row-independent for separable maps — O(P*C)).
  x_lo, x_hi = _sep_tap_extents(h, width)                    # [P, C]
  w0 = np.clip(x_lo // WIN * WIN, 0, max(width - 2 * WIN, 0))
  cover_end = np.minimum(w0 + n_win * WIN, width)
  chunk_empty = (x_hi < 0) | (x_lo > width - 1)
  u_ok = chunk_empty | (np.minimum(x_hi, width - 1) <= cover_end - 1)
  return bool(u_ok.all())


@functools.partial(jax.jit, static_argnames=("height", "width"))
def _plan_shared_stats(homs: jnp.ndarray, height: int, width: int):
  """Device-side reductions behind ``_plan_shared`` (traceable, f32).

  Returns: denominator-one-signed, max per-column floor-span of u across
  a strip's rows, a tuple of vertical-coverage oks (one per
  ``_shared_levels(height)`` slice-ladder level), and horizontal window
  coverage ok for the 2- and 3-window variants. Runs the SAME table math
  as ``_shared_tables`` (same helpers, same dtype), plus the per-COLUMN
  checks the tables cannot express; per-column u/v extrema over a strip's
  rows are evaluated at the strip's top/bottom rows — exact, because with
  a one-signed denominator u and v are monotone in the row at a fixed
  column. An earlier host-numpy f64 version of this took ~2 s per call at
  1080p x 32 planes (the per-column [P, S, W] arrays); on-device it is
  sub-millisecond and its floors see the very f32 values the tables use.
  """
  h9 = homs.reshape(-1, 3, 3).astype(jnp.float32)
  p = h9.shape[0]
  cx = jnp.array([0.0, width - 1.0], jnp.float32)
  cy = jnp.array([0.0, height - 1.0], jnp.float32)
  d_flat = (h9[:, 2, 0, None, None] * cx[None, :, None]
            + h9[:, 2, 1, None, None] * cy[None, None, :]
            + h9[:, 2, 2, None, None]).reshape(p, 4)
  den_ok = (jnp.isfinite(d_flat).all()
            & ((d_flat > 0).all(1) | (d_flat < 0).all(1)).all())

  tw, _, _, _ = _tile_sizes(height, width, 2)
  n_strips = height // STRIP
  mins = _corner_mins(h9, height, width, tw)

  # Per-column strip extrema from the strip's top/bottom rows: [P, S, 2, W].
  cols = jnp.arange(width, dtype=jnp.float32)
  oyr = (jnp.arange(n_strips, dtype=jnp.float32)[:, None] * STRIP
         + jnp.array([0.0, STRIP - 1.0])).reshape(-1)
  u_r, v_r = _uv_vec(h9, cols[None, None, :], oyr[None, :, None])
  u_r = u_r.reshape(p, n_strips, 2, width)
  v_r = v_r.reshape(p, n_strips, 2, width)
  v_lo = v_r.min(axis=2)                                     # [P, S, W]
  v_hi = v_r.max(axis=2)
  # Tap-fan span with TOL slack on BOTH floors: the kernel recomputes the
  # fan origin floor(min_r u) in Mosaic f32, which can resolve one lower
  # than this XLA f32 evaluation when min u sits within an ulp of an
  # integer — shifting the whole fan down and dropping the FAR-end tap,
  # whose bilinear weight is frac(u_max), i.e. arbitrarily large. Widening
  # the span whenever either extreme is within TOL of an integer makes the
  # fan cover both floor resolutions (near-boundary poses may escalate to
  # the 3-tap variant or the XLA fallback — correctness over speed).
  tol = 5e-4
  u_lo = u_r.min(axis=2)                                     # [P, S, W]
  u_hi = u_r.max(axis=2)
  span = (jnp.floor(u_hi + tol).astype(jnp.int32)
          - jnp.floor(u_lo - tol).astype(jnp.int32))
  span_max = span.max()

  # Coverage comparisons run in VALUE space with tolerance TOL: f32 op
  # reordering can wobble a per-column u/v a few ulps across the integer
  # boundary its chunk-corner min floored at (observed: column minima one
  # ulp below the corner value), and an integer-exact check would then
  # spuriously reject. A tap within TOL of the boundary carries <= TOL
  # bilinear weight, so accepting it changes the output by <= TOL — half
  # the 1e-3 parity budget at TOL = 5e-4 (image coordinates <= ~2000 keep
  # the f32 ulp <= ~1.2e-4 after the in-image clamps below).
  tol = 5e-4
  chunk_of_col = jnp.arange(width) // CHUNK
  # A column is tap-free only when every v is <= -1 or >= H: the boundary
  # taps (row 0 for v in (-1, 0), row H-1 for v in (H-1, H)) carry weight.
  empty_v = (v_hi <= -1) | (v_lo >= height)
  # Vertical coverage is n_windows-independent (any tsrc gives the same
  # ymin/q0 formulas); evaluate it with the 2-window geometry, once per
  # slice-ladder level (ymin/q0 shift with the level's bandg/slc).
  v_oks = []
  for slc_l, bandg_l in _shared_levels(height):
    _, _, ymin_cl, _, _, q0_l = _table_scalars(
        mins, height, width, tw, min(width, 640), bandg_l,
        min(2, min(width, 640) // WIN), slc_l)
    ymq = ((ymin_cl + q0_l)[:, :, chunk_of_col]).astype(jnp.float32)
    v_oks.append((empty_v | (
        (jnp.maximum(v_lo, 0.0) >= ymq - tol)
        & (jnp.minimum(v_hi, height - 1.0)
           <= ymq + slc_l - 1 + tol))).all())

  # The tap fan [xhat, xhat + span + 1] covers each column's x-taps by
  # construction; in-image taps must land in the chunk's window union.
  u_lo = u_r.min(axis=2)                                     # [P, S, W]
  u_hi = u_r.max(axis=2)
  empty_h = (u_hi <= -1) | (u_lo >= width)
  h_oks = []
  for n_windows in (2, 3):
    _, tsrc, bandg_h, n_eff = _tile_sizes(height, width, n_windows)
    # xmin/w0 are bandg/slc-independent; any level gives the same values.
    _, _, _, xmin_c, w0, _ = _table_scalars(
        mins, height, width, tw, tsrc, bandg_h, n_eff)
    xmw = ((xmin_c + w0)[:, :, chunk_of_col]).astype(jnp.float32)
    h_oks.append((empty_h | (
        (jnp.maximum(u_lo, 0.0) >= xmw - tol)
        & (jnp.minimum(u_hi + 1.0, width - 1.0)
           <= xmw + n_eff * WIN - 1 + tol))).all())
  return den_ok, span_max, tuple(v_oks), h_oks[0], h_oks[1]


# --- Host-planning memos -----------------------------------------------
# A render loop re-using a pose set (benchmark iterations, a viewer orbit,
# steady-state training batches) must not pay a device_get round-trip plus
# jitted-stats dispatch per frame. Tiny bounded FIFO dicts: pose arrays are
# [P, 3, 3] floats, so both the strong refs (id stability) and the byte
# keys are negligible.
_HOST_HOMS_CACHE: dict = {}
_PLAN_MEMO: dict = {}
_MEMO_CAP = 64


def _host_homs(homs) -> np.ndarray:
  """Host copy of a concrete device array, id-memoized.

  The strong reference stored with each entry keeps the keyed id valid for
  the cache's lifetime (no id reuse after GC)."""
  key = id(homs)
  hit = _HOST_HOMS_CACHE.get(key)
  if hit is not None and hit[0] is homs:
    return hit[1]
  a = np.asarray(jax.device_get(homs))
  if len(_HOST_HOMS_CACHE) >= _MEMO_CAP:
    _HOST_HOMS_CACHE.pop(next(iter(_HOST_HOMS_CACHE)))
  _HOST_HOMS_CACHE[key] = (homs, a)
  return a


def plan_memo(kind: str, homs_np: np.ndarray, height: int, width: int,
              compute):
  """Memoize a host planner result on the pose bytes + geometry."""
  key = (kind, homs_np.tobytes(), height, width)
  if key in _PLAN_MEMO:
    return _PLAN_MEMO[key]
  out = compute()
  if len(_PLAN_MEMO) >= _MEMO_CAP:
    _PLAN_MEMO.pop(next(iter(_PLAN_MEMO)))
  _PLAN_MEMO[key] = out
  return out


def _plan_shared(homs, height: int, width: int):
  """Static ``(n_taps, n_windows, slc, bandg)`` for the shared-gather
  kernel, or None. Memoized on the pose bytes (see ``plan_memo``).

  Thin host wrapper over the jitted ``_plan_shared_stats``: decides the
  tap-fan width (``2 + max floor-span of u across a strip's rows``, capped
  at 3), the minimal window count (2 or 3) whose coverage holds, and the
  cheapest SHARED_LEVELS slice-ladder level whose vertical coverage holds;
  returns None (caller falls back to the banded tier, then XLA) when no
  level covers the pose or a homography denominator changes sign over the
  image (poles break the monotonicity the extrema rely on). ``homs`` must
  be concrete; leading batch axes flatten into the plane axis ([P, 3, 3]
  or [B, P, 3, 3] — the plan covers every entry).

  Precision: the stats run in f32 with the same formulas (and helpers) as
  the device tables, so plan and tables see identical values up to XLA op
  reordering (~1 ulp). A floor() input that close to an integer can still
  resolve differently from the kernel's in-kernel u/v evaluation; such
  divergence only ever drops a tap whose bilinear weight is the distance
  to that same integer boundary (~1e-4 on 1080p-scale coordinates), so an
  approved pose stays within the 1e-3 parity budget even on mismatch.
  """
  a = np.asarray(homs)
  return plan_memo("shared", a, height, width,
                   lambda: _plan_shared_uncached(a, height, width))


def _plan_shared_uncached(homs: np.ndarray, height: int, width: int):
  # ensure_compile_time_eval: callers may sit under an ambient jit trace
  # (concrete homs as jit constants); the stats must still run eagerly.
  with jax.ensure_compile_time_eval():
    den_ok, span_max, v_oks, h2, h3 = jax.device_get(
        _plan_shared_stats(jnp.asarray(homs), height, width))
  if not den_ok:
    return None
  n_taps = int(span_max) + 2
  if n_taps > 3:
    return None
  n_windows = 2 if h2 else 3 if h3 else None
  if n_windows is None:
    return None
  # Walk the slice ladder cheapest-first: gather traffic is linear in the
  # slice height, so the first covering level is the fastest.
  for (slc, bandg), v_ok in zip(_shared_levels(height), v_oks):
    if v_ok:
      return n_taps, n_windows, slc, bandg
  return None


def _plan_banded(homs, height: int, width: int):
  """Cheapest covering banded-tier config, or None. Memoized (plan_memo).

  The host-side mirror of ``_banded_tables``: walks ``_banded_family`` in
  DMA-cost order and returns the first ``(tw, bandg, slice_rows, tsrc,
  n_eff)`` under which every in-image bilinear tap of every output pixel
  lands inside its tile's ``[bandg, tsrc]`` source rectangle, its row's
  ``slice_rows`` band slice, and its row-chunk's gather windows. Returns
  None when no family member covers the pose set (caller falls back to
  XLA) or a homography denominator changes sign over the image (poles
  break the edge-monotonicity the extent math relies on). ``homs`` must
  be concrete; leading batch axes flatten into the plane axis.

  Mirror precision: this runs in f64 while the device tables are f32.
  Near an integer boundary the two can FLOOR differently, and because the
  slice/window offsets are quantized (``//8*8``, ``//WIN*WIN``) a
  divergent floor shifts the whole slice or window by 8 rows / 128
  columns — which would drop full-weight taps, not just a boundary tap.
  The planner therefore verifies coverage under BOTH floor resolutions:
  every floored quantity is evaluated at value−tol and value+tol
  (tol = 5e-4, comfortably above the f32 evaluation error at 1080p-scale
  coordinates) and a config is approved only if it covers both. Residual
  exposure is a tap whose extent estimate itself is off by >tol — not
  possible for one-signed denominators (the boundary evaluations are
  exact extrema up to rounding).
  """
  a = np.asarray(homs)
  return plan_memo("banded", a, height, width,
                   lambda: _plan_banded_uncached(a, height, width))


def _plan_banded_uncached(homs: np.ndarray, height: int, width: int):
  h = np.asarray(homs, np.float64).reshape(-1, 3, 3)
  p = h.shape[0]
  cx = np.array([0.0, width - 1.0])
  cy = np.array([0.0, height - 1.0])
  d_flat = (h[:, 2, 0, None, None] * cx[None, :, None]
            + h[:, 2, 1, None, None] * cy[None, None, :]
            + h[:, 2, 2, None, None]).reshape(p, 4)
  if not np.isfinite(d_flat).all():
    return None
  if not np.all((d_flat > 0).all(1) | (d_flat < 0).all(1)):
    return None

  def uv(ox, oy):
    den = (h[:, 2, 0, None, None] * ox + h[:, 2, 1, None, None] * oy
           + h[:, 2, 2, None, None])
    u = (h[:, 0, 0, None, None] * ox + h[:, 0, 1, None, None] * oy
         + h[:, 0, 2, None, None]) / den
    v = (h[:, 1, 0, None, None] * ox + h[:, 1, 1, None, None] * oy
         + h[:, 1, 2, None, None]) / den
    return (np.where(np.isfinite(u), u, 0.0),
            np.where(np.isfinite(v), v, 0.0))

  # Per-row chunk-boundary extents (config-independent; the boundary at
  # (ci+1)*CHUNK over-reaches the chunk's last pixel by one column, which
  # only widens the bound — conservative, and exactly what the tables use).
  n_chunks = width // CHUNK
  n_strips = height // STRIP
  rows = np.arange(height, dtype=np.float64)
  oxb = np.arange(n_chunks + 1, dtype=np.float64) * CHUNK
  u_b, v_b = uv(oxb[None, None, :], rows[None, :, None])     # [P, H, C+1]
  u_lo = np.minimum(u_b[..., :-1], u_b[..., 1:])             # [P, H, C]
  u_hi = np.maximum(u_b[..., :-1], u_b[..., 1:])
  v_lo = np.minimum(v_b[..., :-1], v_b[..., 1:])
  v_hi = np.maximum(v_b[..., :-1], v_b[..., 1:])
  # A chunk-row is tap-free only when every v is <= -1 or >= H (boundary
  # taps carry weight) — likewise horizontally.
  empty_v = (v_hi <= -1) | (v_lo >= height)
  empty_h = (u_hi <= -1) | (u_lo >= width)

  # The device tables floor f32 values; this mirror floors f64 ones. A
  # divergent floor shifts a QUANTIZED offset (q0 by 8 rows, w0/xmin by
  # 128 columns, ymin by 8 rows), so coverage must hold under BOTH
  # resolutions: each floored quantity is evaluated at value-tol and
  # value+tol and both passes must cover. The coverage comparisons
  # themselves run in VALUE space with tol slack (as _plan_shared_stats):
  # a tap within tol of a slice/window boundary carries <= tol bilinear
  # weight, so admitting it costs <= tol — half the 1e-3 parity budget.
  tol = 5e-4

  def covers(cfg, eps):
    tw, bandg, slc, tsrc, n_eff = cfg
    c_t = tw // CHUNK
    n_tiles = width // tw
    # Tile-corner extents -> per-tile band origins (mirrors _corner_mins).
    oyc = (np.arange(n_strips, dtype=np.float64)[:, None] * STRIP
           + np.array([0.0, STRIP - 1.0])).reshape(-1)       # [S*2]
    oxc = (np.arange(n_tiles, dtype=np.float64)[:, None] * tw
           + np.array([0.0, tw - 1.0])).reshape(-1)          # [T*2]
    u_c, v_c = uv(oxc[None, None, :], oyc[None, :, None])    # [P, S*2, T*2]
    umin_tile = u_c.reshape(p, n_strips, 2, n_tiles, 2).min(axis=(2, 4))
    vmin_tile = v_c.reshape(p, n_strips, 2, n_tiles, 2).min(axis=(2, 4))
    ymin = np.clip(np.floor(vmin_tile + eps).astype(np.int64) - 1, 0,
                   height - bandg) // 8 * 8                  # [P, S, T]
    xmin = np.clip(np.floor(umin_tile + eps).astype(np.int64), 0,
                   width - tsrc) // WIN * WIN

    tile_of_chunk = np.arange(n_chunks) // c_t
    ymin_rc = np.repeat(ymin, STRIP, axis=1)[:, :, tile_of_chunk]
    xmin_rc = np.repeat(xmin, STRIP, axis=1)[:, :, tile_of_chunk]
    q0 = np.clip((np.floor(v_lo + eps).astype(np.int64) - ymin_rc)
                 // 8 * 8, 0, bandg - slc)
    w0 = np.clip((np.floor(u_lo + eps).astype(np.int64) - xmin_rc)
                 // WIN * WIN, 0, tsrc - n_eff * WIN)
    ymq = (ymin_rc + q0).astype(np.float64)
    xmw = (xmin_rc + w0).astype(np.float64)
    v_ok = empty_v | (
        (np.maximum(v_lo, 0.0) >= ymq - tol)
        & (np.minimum(v_hi, height - 1.0) <= ymq + slc - 1 + tol))
    h_ok = empty_h | (
        (np.maximum(u_lo, 0.0) >= xmw - tol)
        & (np.minimum(u_hi + 1.0, width - 1.0)
           <= xmw + n_eff * WIN - 1 + tol))
    return bool(v_ok.all() and h_ok.all())

  for cfg in _banded_family(height, width):
    if covers(cfg, -tol) and covers(cfg, tol):
      return cfg
  return None


def _sep_tap_extents(h, width: int):
  """Per-chunk integer tap extents [x_lo, x_hi] for separable homographies.

  ``h``: ``[P, 3, 3]`` float64. u is row-independent, so chunk-edge u values
  are exact extrema. Shared by ``fits_envelope`` and the window auto-tuner
  so the check and the tuner cannot diverge from each other.
  """
  n_chunks = width // CHUNK
  ox_edges = (np.arange(n_chunks, dtype=np.float64)[:, None] * CHUNK
              + np.array([0.0, CHUNK - 1.0]))                  # [C, 2]
  u_e = ((h[:, 0, 0] * ox_edges[..., None] + h[:, 0, 2])
         / h[:, 2, 2]).transpose(2, 0, 1)                      # [P, C, 2]
  u_e = np.where(np.isfinite(u_e), u_e, 0.0)
  x_lo = np.floor(u_e.min(axis=2)).astype(np.int64)
  x_hi = np.floor(u_e.max(axis=2)).astype(np.int64) + 1
  return x_lo, x_hi


@functools.partial(jax.jit, static_argnames=("n_windows", "interpret"))
def _fused_call(planes: jnp.ndarray, homs: jnp.ndarray, n_windows: int,
                interpret: bool) -> jnp.ndarray:
  """Separable-path kernel call on a batch ``[B, P, 4, H, W]`` (one launch
  for the whole batch); general homographies go through ``_shared_call``."""
  batch, num_planes, _, height, width = planes.shape
  if height % STRIP or width % CHUNK:
    raise ValueError(
        f"H must be a multiple of {STRIP} and W of {CHUNK}; got "
        f"{height}x{width} (pad the MPI, or use an XLA method)")
  if height < BAND:
    raise ValueError(f"H must be >= {BAND}, got {height}")
  if width < 2 * WIN:
    raise ValueError(f"separable path needs W >= {2 * WIN}, got {width}")
  kernel = functools.partial(
      _separable_kernel, num_planes=num_planes, height=height, width=width,
      n_windows=min(n_windows, width // WIN))
  return pl.pallas_call(
      kernel,
      grid=(batch, height // STRIP, num_planes),
      in_specs=[
          pl.BlockSpec(memory_space=pltpu.SMEM),   # [B, P, 9] homographies
          pl.BlockSpec(memory_space=pl.ANY),       # [B, P, 4, H, W] (HBM)
      ],
      out_specs=pl.BlockSpec((1, 3, STRIP, width),
                             lambda b, s, p: (b, 0, s, 0)),
      out_shape=jax.ShapeDtypeStruct((batch, 3, height, width), jnp.float32),
      scratch_shapes=[
          pltpu.VMEM((2, 4, BAND, width), jnp.float32),
          pltpu.VMEM((3, STRIP, width), jnp.float32),
          pltpu.SemaphoreType.DMA((2,)),
      ],
      interpret=interpret,
  )(homs.reshape(batch, num_planes, 9).astype(jnp.float32),
    planes.astype(jnp.float32))


def reference_render(planes: jnp.ndarray, homs: jnp.ndarray) -> jnp.ndarray:
  """XLA gather-path render with the kernel's pixel-space contract.

  Used as the numerical oracle in tests and as the VJP of the fused kernel.
  ``planes`` ``[P, 4, H, W]``, ``homs`` ``[P, 3, 3]``.
  """
  _, _, h, w = planes.shape
  nhwc = jnp.moveaxis(planes, 1, -1)[:, None]            # [P, 1, H, W, 4]
  grid = jnp.moveaxis(geometry.homogeneous_grid(h, w), 0, -1)
  pts = geometry.apply_homography(grid, homs[:, None])
  xy = geometry.from_homogeneous(pts)                    # [P, 1, H, W, 2]
  # Sampler maps (0,1) coords via px = c*W - 0.5; invert to feed raw pixels.
  coords = (xy + 0.5) / jnp.array([w, h], xy.dtype)
  warped = sampling.bilinear_sample(nhwc, coords)
  out = compose.over_composite_scan(warped)              # [1, H, W, 3]
  return jnp.moveaxis(out[0], -1, 0)


# Batched oracle [B, P, 4, H, W] x [B, P, 3, 3] -> [B, 3, H, W]: the VJP of
# both fused kernels and the fallback for batched out-of-envelope calls.
_reference_render_batch = jax.vmap(reference_render)


# adj_plan sentinel: plan the backward INSIDE bwd, from the concrete
# residual homographies, only when a gradient is actually taken. Forward-
# only rendering (the FPS path) must not pay per-call adjoint planning
# (host math + device round-trips); under jit the residuals are tracers
# and lazy resolves to the XLA backward (pass plan_fused's adj_plan for
# the Pallas backward there).
LAZY_ADJ = "lazy"


def _resolve_adj(adj_plan, planes, homs, separable: bool):
  """``bwd``-time adjoint plan: pass tuples through, resolve LAZY_ADJ from
  concrete residuals (None — the XLA backward — when traced or rejected)."""
  if not (isinstance(adj_plan, str) and adj_plan == LAZY_ADJ):
    return adj_plan
  if isinstance(homs, jax.core.Tracer):
    return None
  from mpi_vision_tpu.kernels import render_pallas_bwd
  h, w = planes.shape[-2:]
  planner = (render_pallas_bwd.plan_adjoint_sep if separable
             else render_pallas_bwd.plan_adjoint_shr)
  return planner(homs, h, w)


@functools.lru_cache(maxsize=None)
def _make_fused(n_windows: int,
                adj_plan: tuple[int, int] | str | None = None):
  """Separable-path fused render with a custom VJP.

  With ``adj_plan`` (a ``render_pallas_bwd.plan_adjoint_sep`` result, or
  LAZY_ADJ to plan at bwd time from concrete residuals), d planes comes
  from the Pallas backward (warp, composite VJP, tent-filter warp
  transpose); without it, the whole backward routes through the XLA
  reference path as before. d homs always comes from the XLA path — XLA
  dead-code-eliminates it under jit when pose gradients are unused (the
  training case: poses are data).
  """

  @jax.custom_vjp
  def fused(planes, homs):
    return _fused_call(planes, homs, n_windows,
                       jax.default_backend() != "tpu")

  def fwd(planes, homs):
    return fused(planes, homs), (planes, homs)

  def bwd(res, g):
    planes, homs = res
    plan = _resolve_adj(adj_plan, planes, homs, separable=True)
    if plan is None:
      _, vjp = jax.vjp(_reference_render_batch, planes, homs)
      return vjp(g)
    from mpi_vision_tpu.kernels import render_pallas_bwd
    dplanes = render_pallas_bwd.backward_planes(
        planes, homs, g, separable=True, fwd_plan=n_windows,
        adj_plan=plan)
    # homs-only VJP: transposition never touches the planes input, so the
    # XLA planes scatter is skipped even eagerly (and the whole branch is
    # DCE'd under jit when pose gradients are unused — the training case).
    _, vjp_h = jax.vjp(lambda hh: _reference_render_batch(planes, hh), homs)
    (dhoms,) = vjp_h(g)
    return dplanes, dhoms

  fused.defvjp(fwd, bwd)
  return fused


@functools.lru_cache(maxsize=None)
def _make_shared(n_taps: int, n_windows: int,
                 adj_plan: tuple | str | None = None,
                 slc: int = G_SHARED, bandg: int = G_BAND):
  """General-path fused render with a custom VJP (see _make_fused: with
  ``adj_plan`` — a ``render_pallas_bwd.plan_adjoint_shr`` result or
  LAZY_ADJ — d planes runs on the Pallas backward; d homs stays on the
  XLA path, DCE'd under jit when pose gradients are unused). The backward
  re-warp runs the same ``(slc, bandg)`` slice-ladder level the forward
  planned, so every shared-envelope pose has a Pallas backward; the
  adjoint warp-transpose kernel plans its own geometry over the inverse
  map (``plan_adjoint_shr``), independent of the forward's level."""

  @jax.custom_vjp
  def shared(planes, homs):
    return _shared_call(planes, homs, n_taps, n_windows,
                        jax.default_backend() != "tpu", slc, bandg)

  def fwd(planes, homs):
    return shared(planes, homs), (planes, homs)

  def bwd(res, g):
    planes, homs = res
    plan = _resolve_adj(adj_plan, planes, homs, separable=False)
    if plan is None:
      _, vjp = jax.vjp(_reference_render_batch, planes, homs)
      return vjp(g)
    from mpi_vision_tpu.kernels import render_pallas_bwd
    dplanes = render_pallas_bwd.backward_planes(
        planes, homs, g, separable=False,
        fwd_plan=(n_taps, n_windows, slc, bandg), adj_plan=plan)
    _, vjp_h = jax.vjp(lambda hh: _reference_render_batch(planes, hh), homs)
    (dhoms,) = vjp_h(g)
    return dplanes, dhoms

  shared.defvjp(fwd, bwd)
  return shared


@functools.lru_cache(maxsize=None)
def _make_banded(cfg: tuple):
  """Banded-tier render with a custom VJP.

  The backward always routes through the XLA reference path: the banded
  tier is the correctness/perf middle ground for large rotations, and its
  training traffic is rare enough that a dedicated adjoint kernel hasn't
  earned its complexity yet (the XLA VJP is always correct, just slower).
  """
  tw, bandg, slc, tsrc, n_eff = cfg

  @jax.custom_vjp
  def banded(planes, homs):
    return _banded_call(planes, homs, tw, bandg, slc, tsrc, n_eff,
                        jax.default_backend() != "tpu")

  def fwd(planes, homs):
    return banded(planes, homs), (planes, homs)

  def bwd(res, g):
    planes, homs = res
    _, vjp = jax.vjp(_reference_render_batch, planes, homs)
    return vjp(g)

  banded.defvjp(fwd, bwd)
  return banded


class _SharedGetter:
  """Dict-compatible view over ``_make_shared`` (tests index by plan)."""

  def __getitem__(self, key):
    if len(key) == 2:
      return _make_shared(key[0], key[1])
    if len(key) == 4 and all(isinstance(k, (int, np.integer)) for k in key):
      # A _plan_shared 4-tuple (n_taps, n_windows, slc, bandg): the
      # adjoint-plan slot is positional third in _make_shared.
      return _make_shared(key[0], key[1], None, key[2], key[3])
    return _make_shared(*key)


_SHARED = _SharedGetter()

# Jitted fallback: the eager reference path materializes per-op temporaries
# (several GB at 1080p x 32 planes); under jit XLA schedules them.
_reference_render_jit = jax.jit(_reference_render_batch)


def _sep_windows_needed(homs, height: int, width: int) -> int:
  """Minimal separable-path window count (2 or 3) for concrete homographies.

  2 covers any chunk whose taps span <= WIN+1 source columns from the
  aligned-down base (always true for |h00/h22| <= 1.0); chunks reaching
  further need the third window. Mirrors the kernel's w0 computation.
  """
  h = np.asarray(homs, np.float64).reshape(-1, 3, 3)
  x_lo, x_hi = _sep_tap_extents(h, width)
  w0 = np.clip(x_lo // WIN * WIN, 0, max(width - 2 * WIN, 0))
  need3 = np.minimum(x_hi, width - 1) >= w0 + 2 * WIN
  return SEP_WINDOWS if bool(need3.any()) else 2


# Default sentinel for render_mpi_fused's plan: distinguishes "no plan
# supplied" (conservative kernel) from an explicit plan=None, which is what
# _plan_shared returns for OUT-OF-ENVELOPE poses and must never silently
# run a kernel that would drop taps.
PLAN_UNSET = object()


def plan_fused(homs, height: int, width: int):
  """Host-side plan bundle for JITTED fused rendering at ``(H, W)``.

  For callers whose poses are jit ARGUMENTS (e.g. a train step rendering a
  batch's poses): plan eagerly per batch from the concrete homographies —
  microseconds of host math — and pass the bundle's fields to
  ``render_mpi_fused(..., check=False, separable=..., plan=...,
  adj_plan=...)`` (or ``core.render.render_mpi`` which forwards them).
  Plans are made at the kernel's auto-padded geometry, which is exactly
  where an off-tile-grid render executes. Returns None when the pose set
  is outside the forward envelope (use an XLA method for that batch);
  ``adj_plan`` is None when only the BACKWARD must fall back to XLA
  (safe — the XLA VJP is always correct, just slower).
  """
  # One device->host transfer serves every planner below (they each
  # np.asarray their input, which is then already host-side).
  homs = homs if isinstance(homs, np.ndarray) else _host_homs(homs)
  sep = is_separable(homs)
  hp = max(-(-height // STRIP) * STRIP, BAND)
  wp = -(-width // CHUNK) * CHUNK
  from mpi_vision_tpu.kernels import render_pallas_bwd
  if sep:
    wp = max(wp, 2 * WIN)
    if not fits_envelope(homs, hp, wp, True):
      return None
    return dict(separable=True,
                plan=_sep_windows_needed(homs, hp, wp),
                adj_plan=render_pallas_bwd.plan_adjoint_sep(homs, hp, wp))
  plan = _plan_shared(homs, hp, wp)
  if plan is not None:
    # The backward re-warp runs the planned slice level and the adjoint
    # kernel plans its own inverse-map geometry, so every shared-envelope
    # pose gets a Pallas backward when the adjoint planner accepts it.
    return dict(separable=False, plan=plan,
                adj_plan=render_pallas_bwd.plan_adjoint_shr(homs, hp, wp))
  bplan = _plan_banded(homs, hp, wp)
  if bplan is None:
    return None
  # Banded middle tier: Pallas forward, XLA backward (adj_plan=None is the
  # explicit keep-the-XLA-VJP sentinel, always correct).
  return dict(separable=False, plan=("banded",) + bplan, adj_plan=None)


def render_mpi_fused(planes: jnp.ndarray, homs: jnp.ndarray,
                     separable: bool = False,
                     check: bool = True,
                     plan: tuple[int, int] | int | None | object = PLAN_UNSET,
                     adj_plan: tuple | None | object = PLAN_UNSET
                     ) -> jnp.ndarray:
  """Render an MPI to a novel view in one fused TPU kernel.

  Args:
    planes: ``[P, 4, H, W]`` planar RGBA MPI, back-to-front — or a batch
      ``[B, P, 4, H, W]`` (one MPI + pose per entry), rendered as ONE
      kernel launch with a batch grid axis (the kernel-variant and
      envelope decisions are made once over the whole batch's
      homographies).
    homs: ``[P, 3, 3]`` target-pixel -> source-pixel homographies
      (``pixel_homographies(...)[:, b]`` for batch entry b); ``[B, P, 3,
      3]`` when batched.
    separable: static flag selecting the separable fast path; only valid
      when ``is_separable(homs)`` (axis-aligned warps, e.g. any pure camera
      translation/zoom). The result is identical either way; the fast path
      is ~4x quicker than the shared-gather general kernel.
    check: when True (the default) and ``homs`` is concrete, verify the
      kernel's coverage envelope (``fits_envelope`` / ``_plan_shared``)
      and degrade gracefully for poses outside it: general poses past the
      shared-gather envelope try the banded per-row middle tier
      (``_plan_banded`` — Pallas forward, XLA backward) before falling
      all the way back to the XLA ``reference_render`` path, so
      out-of-envelope poses return correct pixels instead of silently
      dropping taps — host math costs microseconds-to-sub-second against
      a ~30 ms 1080p render, memoized per pose set. The check also
      statically tunes the
      gather-window count (and, on the general path, the tap-fan width)
      from the concrete homographies. Under jit the homographies are
      tracers and NO check is possible, so ``check=True`` raises: pass
      ``check=False`` to run the Pallas kernel with conservative static
      parameters — you then own the envelope (verify representative poses
      eagerly with ``fits_envelope`` first) — or jit an XLA method
      (``core.render.render_mpi(method='scan'|'fused')``) instead. No code
      path renders unchecked taps by default.
    plan: with ``check=False`` only — an explicit kernel-variant plan from
      an eager ``plan_fused`` (or ``_plan_shared``) call on the concrete
      poses: ``(n_taps, n_windows, slc, bandg)`` for the general path
      (the last two name the SHARED_LEVELS slice-ladder level; legacy
      2-tuples run the base level), the window count (int) for the
      separable path, or a ``("banded", tw, bandg, slice_rows, tsrc,
      n_windows)`` tag selecting the per-row banded middle tier (large
      rotations). Jitted/shard_mapped callers use
      this to run the planned variant instead of the conservative
      maximum. Plans for sizes off the tile grid must be made at the
      auto-padded geometry (``plan_fused`` does). Passing the planner's
      ``None`` result raises: None means the pose set is OUTSIDE the
      envelope, and the only correct options are an XLA method or the
      ``check=True`` fallback.
    adj_plan: with ``check=False`` only — the backward-pass plan from
      ``plan_fused`` (``plan_adjoint_sep``/``plan_adjoint_shr``), enabling
      the Pallas backward (kernels/render_pallas_bwd) for jitted callers.
      An explicit None keeps the XLA backward — always correct, just
      slower (unlike ``plan``, where None would mean dropping taps).
      Left unset, the backward plans itself lazily at VJP time: eager
      gradients get the Pallas backward automatically, jitted ones (traced
      residuals) the XLA backward — and forward-only rendering never pays
      adjoint planning.

  Returns:
    ``[3, H, W]`` rendered view, float32 (``[B, 3, H, W]`` when batched).
  """
  # Capture concreteness BEFORE any array ops: under an ambient jit even
  # `homs[None]` on a closure-constant array yields a tracer, but the
  # original concrete values are exactly what the eager planners need —
  # so a jitted caller whose poses are constants still gets checked,
  # optimally-planned kernels.
  np_homs = None
  if not isinstance(homs, jax.core.Tracer):
    np_homs = _host_homs(homs)
    if np_homs.ndim == 3:
      np_homs = np_homs[None]
  single = planes.ndim == 4
  if single:
    planes, homs = planes[None], homs[None]
  out = _render_mpi_fused_batch(planes, homs, np_homs, separable, check,
                                plan, adj_plan)
  return out[0] if single else out


def _pad_to_tiles(planes: jnp.ndarray, separable: bool):
  """Zero-pad H to a multiple of 8 (>= BAND) and W to a multiple of 128.

  EXACT under the sampler's zeros-padding semantics (utils.py:174): a tap
  beyond the original extent contributed 0 before; with padding it reads a
  zero plane value (and zero alpha) — identical pixels, identical
  gradients. The output is cropped back by the caller. Only the separable
  kernel needs W >= 2*WIN (its unconditional two gather windows); the
  general kernel runs fine at W == 128, so don't double its width.
  """
  _, _, _, height, width = planes.shape
  h_tgt = max(-(-height // STRIP) * STRIP, BAND)      # BAND is 8-aligned
  w_tgt = -(-width // CHUNK) * CHUNK
  if separable:
    w_tgt = max(w_tgt, 2 * WIN)
  padded = jnp.pad(
      planes,
      ((0, 0), (0, 0), (0, 0), (0, h_tgt - height), (0, w_tgt - width)))
  return padded, height, width


def _render_mpi_fused_batch(planes, homs, np_homs, separable, check, plan,
                            adj_plan):
  """``np_homs``: host copy of ``homs`` for the eager planners, or None
  when the homographies are traced (check must then be False)."""
  _, _, _, height, width = planes.shape
  if (height % STRIP or width % CHUNK or height < BAND
      or (separable and width < 2 * WIN)):
    if not check and plan is PLAN_UNSET:
      # A check=False caller with no explicit plan validated their
      # envelope at the ORIGINAL size; silently re-running the geometry at
      # the padded size would void that validation (coverage tables shift
      # with H/W). Make the mismatch loud, naming the violated constraint.
      # (With an explicit plan, auto-pad proceeds: plan_fused makes plans
      # at exactly this padded geometry.)
      limits = (f"H % {STRIP} == 0, W % {CHUNK} == 0, H >= {BAND}"
                + (f", W >= {2 * WIN} (separable path)" if separable
                   else ""))
      raise ValueError(
          f"{height}x{width} violates the kernel tile contract ({limits}) "
          "and check=False: pass the plan_fused bundle (plans at the "
          "padded size), pad the MPI yourself, use check=True, or an XLA "
          "method.")
    # Auto-pad to the kernel's tile geometry (exact; see _pad_to_tiles)
    # and crop the render back to the requested size; the envelope check
    # below then runs at the padded size the kernel actually executes.
    padded, h0, w0 = _pad_to_tiles(planes, separable)
    out = _render_mpi_fused_batch(padded, homs, np_homs, separable, check,
                                  plan, adj_plan)
    return out[..., :h0, :w0]
  if check and np_homs is None:
    raise ValueError(
        "render_mpi_fused(check=True) needs concrete homographies; under "
        "jit pass check=False (you own the coverage envelope — verify "
        "representative poses with fits_envelope eagerly first) or use an "
        "XLA method (core.render.render_mpi(method='scan'|'fused')). "
        "(Homographies that are jit CONSTANTS — closed over, not "
        "arguments — keep working with check=True.)")
  if plan is None:
    raise ValueError(
        "plan=None: the planner rejected this pose set (outside the kernel "
        "envelope) — rendering with any kernel variant would drop taps. "
        "Use an XLA method or the check=True fallback.")
  # Default adjoint plan when the caller passed none: fully eager calls
  # defer planning to VJP time (LAZY_ADJ — forward-only rendering, the FPS
  # path, must not pay per-call adjoint planning), but a call whose poses
  # are concrete jit CONSTANTS (np_homs captured, yet ``homs`` already a
  # tracer) plans NOW from np_homs — at bwd time the residuals are tracers
  # and lazy would silently regress to the XLA backward. Once per trace,
  # not per call.
  def _default_adj(planner):
    if adj_plan is not PLAN_UNSET:
      return adj_plan
    if np_homs is not None and isinstance(homs, jax.core.Tracer):
      return planner(np_homs, height, width)
    return LAZY_ADJ

  from mpi_vision_tpu.kernels import render_pallas_bwd
  if separable:
    if check and not is_separable(np_homs):
      raise ValueError(
          "separable=True but the homographies are not separable "
          "(is_separable(homs) is False); the separable kernel would "
          "silently render wrong pixels. Pass separable=False (the "
          "shared-gather general kernel) or fix the pose.")
    n_windows = plan if isinstance(plan, int) else SEP_WINDOWS
    adj = _default_adj(render_pallas_bwd.plan_adjoint_sep)
    if np_homs is not None:
      n_windows = _sep_windows_needed(np_homs, height, width)
    if check and not fits_envelope(np_homs, height, width, True):
      return _reference_render_jit(planes, homs)
    return _make_fused(n_windows, adj)(planes, homs)

  # General path: the shared-gather kernel, planned eagerly (tap fan +
  # window count mirrored from concrete homographies); poses past its
  # envelope try the banded per-row middle tier before falling all the
  # way to XLA (shared -> banded -> XLA, mirroring the reference's
  # pose-independent grid_sample path, utils.py:104-134). Traced opt-in
  # calls get an explicit caller-supplied plan (plan_fused) — which may
  # name the banded tier — or the conservative static maximum (3 taps,
  # 3 windows) with the XLA backward.
  if check:
    plan = _plan_shared(np_homs, height, width)
    if plan is not None:
      adj = _default_adj(render_pallas_bwd.plan_adjoint_shr)
      return _make_shared(plan[0], plan[1], adj, plan[2], plan[3])(
          planes, homs)
    bplan = _plan_banded(np_homs, height, width)
    if bplan is None:
      return _reference_render_jit(planes, homs)
    return _make_banded(bplan)(planes, homs)
  if isinstance(plan, tuple) and plan and plan[0] == "banded":
    return _make_banded(plan[1:])(planes, homs)
  adj = _default_adj(render_pallas_bwd.plan_adjoint_shr)
  if plan is PLAN_UNSET:
    # Conservative static maximum: 3 taps, 3 windows, and the TOP usable
    # slice-ladder level — its vertical coverage is a superset of every
    # lower level's, so any pose the shared planner would accept at ANY
    # level renders correctly here (a fits_envelope=True caller may sit
    # anywhere on the ladder). Costs more DMA than a planned call; poses
    # that only the banded tier covers still need an explicit
    # ("banded", ...) plan from plan_fused.
    n_taps, n_windows = 3, 3
    slc, bandg = _shared_levels(height)[-1]
  else:
    # Legacy 2-tuple plans run the base slice level; _plan_shared /
    # plan_fused emit 4-tuples naming the slice-ladder level.
    n_taps, n_windows = plan[0], plan[1]
    slc, bandg = (plan[2], plan[3]) if len(plan) > 2 else (G_SHARED, G_BAND)
  return _make_shared(n_taps, n_windows, adj, slc, bandg)(planes, homs)
