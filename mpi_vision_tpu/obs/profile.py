"""On-demand device profiling around live serving traffic.

``DeviceProfiler`` wraps the ``jax.profiler`` trace context (reused from
``debug.trace`` so there is exactly one profiler entry point in the
repo) to capture N seconds of whatever the serving stack is doing —
XLA compute, transfers, host callbacks — into a TensorBoard/XProf
logdir. The capture window just sleeps: the traffic being profiled is
the live request load, not a synthetic workload.

Exactly one capture at a time: ``jax.profiler.start_trace`` is global
per process, so a second concurrent capture would either fail or
corrupt the first. The guard is a non-blocking lock — a concurrent
``/debug/profile`` gets ``ProfileBusyError`` (HTTP 409) instead of
queueing behind a capture it didn't ask for.
"""

from __future__ import annotations

import os
import threading
import time

# The longest capture the HTTP endpoint will accept: profiles grow with
# wall time and a forgotten ?seconds=86400 must not fill the disk.
MAX_CAPTURE_SECONDS = 300.0


class ProfileBusyError(RuntimeError):
  """A capture is already in flight (the HTTP layer maps this to 409)."""


class DeviceProfiler:
  """Concurrency-guarded ``jax.profiler`` captures into ``logdir``.

  Args:
    logdir: root directory; each capture writes ``profile_<n>/`` under it.
    trace_ctx: the trace context factory (``logdir -> context manager``);
      defaults to ``debug.trace`` (= ``jax.profiler.trace``). Injectable
      so tests exercise the guard without a real profiler session.
    clock / sleep: injectable time sources (lint: no bare time reads).
  """

  def __init__(self, logdir: str, trace_ctx=None, clock=time.monotonic,
               sleep=time.sleep):
    if not logdir:
      raise ValueError("profiler needs a non-empty logdir")
    self.logdir = str(logdir)
    if trace_ctx is None:
      from mpi_vision_tpu import debug

      trace_ctx = debug.trace
    self._trace_ctx = trace_ctx
    self._clock = clock
    self._sleep = sleep
    self._lock = threading.Lock()
    self.captures = 0

  @property
  def busy(self) -> bool:
    if self._lock.acquire(blocking=False):
      self._lock.release()
      return False
    return True

  def capture(self, seconds: float) -> dict:
    """Profile live traffic for ``seconds``; returns the capture summary.

    Raises ``ValueError`` on an out-of-range window and
    ``ProfileBusyError`` when a capture is already running.
    """
    seconds = float(seconds)
    if not 0 < seconds <= MAX_CAPTURE_SECONDS:
      raise ValueError(
          f"seconds must be in (0, {MAX_CAPTURE_SECONDS:g}], got {seconds}")
    if not self._lock.acquire(blocking=False):
      raise ProfileBusyError(
          "a profile capture is already in flight; retry when it finishes")
    try:
      self.captures += 1
      run_dir = os.path.join(self.logdir, f"profile_{self.captures:04d}")
      os.makedirs(run_dir, exist_ok=True)
      t0 = self._clock()
      with self._trace_ctx(run_dir):
        self._sleep(seconds)
      return {
          "logdir": run_dir,
          "seconds": seconds,
          "wall_s": round(self._clock() - t0, 3),
          "capture": self.captures,
      }
    finally:
      self._lock.release()
