"""On-box time-series ring: bounded history for every metric family.

``/stats`` and ``/metrics`` are point-in-time — the question an incident
review actually asks is historical: "what did p99 look like during the
last rolling restart?". A real TSDB answers it, but a serving box must
answer it *without* one: ``TsdbRecorder`` samples the process's own
Prometheus exposition on a fixed cadence and keeps, per series, a
bounded ring of ``(wall_ts, value)`` points — a flight recorder, not a
database. Bounded twice (``max_points`` per series, ``max_series``
total) so a family with runaway label cardinality costs a counter, not
memory.

Served at ``GET /debug/tsdb?family=&recent=&points=`` on serve backends
(and the cluster router, which fans the same query out to every backend
and carries its own ring over the *aggregated* exposition — so one query
reads fleet history). The off-host shipper (``obs/ship.py``) batches
incremental snapshots of the same ring to a collector.

Sampling rides the exposition text through ``obs.prom.parse_metrics_text``
— every family any registry exports (native-histogram buckets, SLO
quantile gauges, edge counters) lands in the ring with zero per-family
wiring, and a family added next PR is recorded automatically.

Clocks are injectable (the serve/-wide rule; clock-lint covers this
file): timestamps are wall time because history is a cross-process
artifact — a router's ring and a backend's ring must be orderable side
by side, like the event log.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import deque

from mpi_vision_tpu.obs import prom

PREFIX = "mpi_obs_tsdb_"


@dataclasses.dataclass(frozen=True)
class TsdbConfig:
  """Ring knobs (the ``serve``/``cluster`` CLI ``--tsdb-*`` flags map 1:1).

  ``interval_s`` is the sampling cadence; ``max_points`` bounds each
  series' ring (``interval_s * max_points`` of history — 10 s * 512 ~=
  85 min at the defaults); ``max_series`` bounds the whole recorder.

  Compaction (ROADMAP flight-recorder follow-on): with
  ``compact_after_s`` set, points older than it are *thinned* to one
  kept point per ``compact_stride * interval_s`` instead of scrolling
  off the ring — old history trades resolution for span, so the same
  ``max_points`` byte budget covers roughly ``compact_stride`` times
  more wall time at coarse grain while the recent window stays
  full-resolution. None disables (classic pure ring).
  """

  interval_s: float = 10.0
  max_points: int = 512
  max_series: int = 4096
  compact_after_s: float | None = None
  compact_stride: int = 8

  def __post_init__(self):
    if self.interval_s <= 0:
      raise ValueError(f"interval_s must be > 0, got {self.interval_s}")
    if self.max_points < 1:
      raise ValueError(f"max_points must be >= 1, got {self.max_points}")
    if self.max_series < 1:
      raise ValueError(f"max_series must be >= 1, got {self.max_series}")
    if self.compact_after_s is not None and self.compact_after_s <= 0:
      raise ValueError(
          f"compact_after_s must be > 0, got {self.compact_after_s}")
    if self.compact_stride < 2:
      # 1 would "compact" to the identity and silently disable the knob.
      raise ValueError(
          f"compact_stride must be >= 2, got {self.compact_stride}")


class TsdbRecorder:
  """Samples one exposition callable into bounded per-series rings.

  Args:
    collect: ``() -> str`` returning a Prometheus text exposition (a
      service's ``_render_metrics_text``; the router's aggregated one).
    config: ring knobs.
    clock: wall-clock source for point timestamps (injectable).
    sleep-free cadence: ``start()`` runs ``sample()`` every
      ``interval_s`` on a daemon thread gated by a stop event (tests
      drive ``sample()`` directly with a fake clock instead).
  """

  def __init__(self, collect, config: TsdbConfig | None = None,
               clock=time.time):
    self._collect = collect
    self.config = config if config is not None else TsdbConfig()
    self._clock = clock
    self._lock = threading.Lock()
    # (family, sample_name, labels_tuple) -> deque[(ts, value)]
    self._series: dict[tuple, deque] = {}
    self._stop = threading.Event()
    self._thread: threading.Thread | None = None
    self.samples = 0
    self.sample_errors = 0
    self.dropped_series = 0
    self.compacted_points = 0
    # Compaction cadence: at most one point per series crosses the age
    # cutoff per sampling tick, so sweeping every sample would rescan
    # O(all resident points) under the lock for nothing — one sweep per
    # stride drops the same points at 1/stride the cost.
    self._compact_countdown = self.config.compact_stride

  def now(self) -> float:
    """The recorder's wall clock — public so bundle builders (the
    incident recorder's collector) window ``snapshot_since`` against
    the same source that stamped the points."""
    return self._clock()

  # -- sampling ------------------------------------------------------------

  def sample(self) -> int:
    """Take one sample of every family; returns series touched.

    A failing collector costs a counter, never the caller — the
    recorder rides a daemon loop and must not be able to die of one bad
    render.
    """
    try:
      parsed = prom.parse_metrics_text(self._collect())
    except Exception:  # noqa: BLE001 - recording must not kill the loop
      with self._lock:
        self.sample_errors += 1
      return 0
    ts = round(self._clock(), 3)
    touched = 0
    with self._lock:
      for family, fam in parsed.items():
        for (sample_name, labels), value in fam["samples"].items():
          if not math.isfinite(value):
            # NaN ("no data", e.g. idle quantile gauges) and infinities
            # must not enter the ring: json.dumps would emit literal
            # NaN/Infinity tokens — invalid JSON for every /debug/tsdb
            # consumer and ship-sink collector.
            continue
          key = (family, sample_name, labels)
          ring = self._series.get(key)
          if ring is None:
            if len(self._series) >= self.config.max_series:
              self.dropped_series += 1
              continue
            ring = self._series[key] = deque(
                maxlen=self.config.max_points)
          ring.append((ts, float(value)))
          touched += 1
      self.samples += 1
      if self.config.compact_after_s is not None:
        self._compact_countdown -= 1
        if self._compact_countdown <= 0:
          self._compact_countdown = self.config.compact_stride
          self._compact_locked(ts)
    return touched

  def _compact_locked(self, now: float) -> None:
    """Thin every ring's old tail to the coarse stride (idempotent).

    Points with ``ts < now - compact_after_s`` keep only one sample per
    ``compact_stride * interval_s`` of wall time (the oldest in each
    stride window survives — its timestamp anchors the window, so a
    re-run keeps the same points and compaction converges). Recent
    points are untouched.
    """
    cutoff = now - self.config.compact_after_s
    stride_s = self.config.compact_stride * self.config.interval_s
    for key, ring in self._series.items():
      if not ring or ring[0][0] >= cutoff:
        continue  # nothing old enough
      kept: list = []
      last_kept_old: float | None = None
      dropped = 0
      for ts, value in ring:
        if ts >= cutoff:
          kept.append((ts, value))
        elif last_kept_old is None or ts - last_kept_old >= stride_s:
          kept.append((ts, value))
          last_kept_old = ts
        else:
          dropped += 1
      if dropped:
        self._series[key] = deque(kept, maxlen=self.config.max_points)
        self.compacted_points += dropped

  def _loop(self) -> None:
    while not self._stop.wait(self.config.interval_s):
      self.sample()

  def start(self) -> "TsdbRecorder":
    if self._thread is not None:
      raise RuntimeError("TsdbRecorder already started")
    self._thread = threading.Thread(target=self._loop,
                                    name="mpi-obs-tsdb", daemon=True)
    self._thread.start()
    return self

  def stop(self) -> None:
    self._stop.set()
    if self._thread is not None:
      self._thread.join(5.0)
      self._thread = None

  # -- queries -------------------------------------------------------------

  def families(self) -> list[str]:
    with self._lock:
      return sorted({key[0] for key in self._series})

  def query(self, family: str, recent_s: float | None = None,
            points: int | None = None, since_ts: float | None = None) -> dict:
    """Windowed series of one family (the ``/debug/tsdb`` payload).

    ``recent_s`` bounds the window to the trailing seconds, ``points``
    caps points per series (newest kept), ``since_ts`` filters to
    points strictly after a wall timestamp (the shipper's incremental
    cursor).
    """
    floor = None
    if recent_s is not None:
      floor = self._clock() - float(recent_s)
    if since_ts is not None:
      floor = max(floor, float(since_ts)) if floor is not None \
          else float(since_ts)
    out = []
    with self._lock:
      for (fam, sample_name, labels), ring in sorted(self._series.items()):
        if fam != family:
          continue
        pts = [[ts, value] for ts, value in ring
               if floor is None or ts > floor]
        if points is not None:
          # pts[-0:] would be the WHOLE list: <= 0 means none, not all.
          pts = pts[-int(points):] if int(points) > 0 else []
        if pts:
          out.append({"name": sample_name, "labels": dict(labels),
                      "points": pts})
    return {"family": family, "series": out}

  def snapshot_since(self, since_ts: float | None,
                     max_points_per_series: int = 64) -> dict:
    """Every family's points after ``since_ts`` (the shipper's batch
    item). Bounded per series so one batch can never carry the whole
    ring — truncation keeps the OLDEST points: the shipper's cursor
    advances past what was shipped, so a kept-newest cut would strand
    the older points behind the cursor forever, while kept-oldest just
    drains the backlog across ticks."""
    out: dict[str, list] = {}
    with self._lock:
      for (family, sample_name, labels), ring in sorted(
          self._series.items()):
        pts = [[ts, value] for ts, value in ring
               if since_ts is None or ts > since_ts]
        if not pts:
          continue
        out.setdefault(family, []).append({
            "name": sample_name, "labels": dict(labels),
            "points": pts[:max_points_per_series]})
    return out

  # -- introspection -------------------------------------------------------

  def stats(self) -> dict:
    with self._lock:
      return {
          "interval_s": self.config.interval_s,
          "max_points": self.config.max_points,
          "max_series": self.config.max_series,
          "series": len(self._series),
          "points": sum(len(ring) for ring in self._series.values()),
          "families": len({key[0] for key in self._series}),
          "samples": self.samples,
          "sample_errors": self.sample_errors,
          "dropped_series": self.dropped_series,
          "compacted_points": self.compacted_points,
          "compact_after_s": self.config.compact_after_s,
          "compact_stride": self.config.compact_stride,
      }


def parse_query(params: dict) -> tuple[str | None, float | None, int | None]:
  """``(family, recent_s, points)`` from parse_qs output — the one
  ``/debug/tsdb`` parameter contract, shared by the backend and router
  handlers. Raises ValueError on malformed numbers (handlers map it to
  400)."""
  family = params.get("family", [None])[0]
  recent = params.get("recent", [None])[0]
  recent = float(recent) if recent is not None else None
  points = params.get("points", [None])[0]
  points = int(points) if points is not None else None
  return family, recent, points


def registry(stats: dict | None) -> prom.Registry:
  """The ``mpi_obs_tsdb_*`` families (zeros while the ring is off — the
  always-exposed convention, so dashboards never depend on a knob)."""
  stats = stats or {}
  reg = prom.Registry()
  p = PREFIX
  reg.counter(p + "samples_total",
              "Sampling sweeps taken over the exposition.",
              stats.get("samples", 0))
  reg.counter(p + "sample_errors_total",
              "Sampling sweeps that failed (collector raised).",
              stats.get("sample_errors", 0))
  reg.counter(p + "dropped_series_total",
              "New series refused at the max_series cap.",
              stats.get("dropped_series", 0))
  reg.counter(p + "compacted_points_total",
              "Old points thinned to the coarse stride (downsampling).",
              stats.get("compacted_points", 0))
  reg.gauge(p + "series", "Series resident in the ring.",
            stats.get("series", 0))
  reg.gauge(p + "points", "Points resident across all series.",
            stats.get("points", 0))
  return reg
