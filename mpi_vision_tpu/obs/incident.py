"""SLO-triggered incident recorder: the self-capturing black box.

When a burn-rate alert fires, an operator today hand-stitches four
surfaces under time pressure — ``/debug/traces``, ``/debug/tsdb``,
``/debug/events``, ``/stats``. This module captures that stitch *at the
moment of the fire edge*, automatically: ``RenderService`` hooks
``note_alert`` beside its ``_on_slo_alert`` callback, and on each fire
edge (deduplicated per alert name until the clear edge — one bundle per
incident, not one per evaluation) a daemon worker thread snapshots a
self-contained JSON bundle off the request path:

  * the firing objective + burn numbers (the alert record itself),
  * the slowest-trace exemplars from the Tracer ring,
  * the tsdb window covering the spike,
  * the recent event slice,
  * brownout ladder state and the top-K attribution cells at fire time,
  * optionally a ``DeviceProfiler`` capture (``--incident-profile``).

Bundles are written atomically (tmp + rename, the repo-wide publish
idiom) into a bounded on-disk ring (``--incident-dir``, keep-K oldest
pruned), listed/fetched at ``/debug/incidents``, and handed to the
``TelemetryShipper`` so they ride its batch -> retry -> disk-spool path
off-host — a sink outage loses nothing.

What exactly goes in the bundle is the *service's* decision: the
recorder takes a ``collect(alert) -> dict`` callable (adoption
pattern — a pre-built recorder without one is wired by the service,
like the shipper's tsdb), keeping this module free of serve imports.
Clocks are injectable (clock-lint covers this file); tests drive
``drain()`` directly instead of starting the worker.
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import re
import threading
import time

from mpi_vision_tpu.obs import prom
from mpi_vision_tpu.obs.events import NULL_EVENTS

PREFIX = "mpi_obs_incident_"

_BUNDLE_RE = re.compile(r"^incident-(\d+)\.json$")


@dataclasses.dataclass(frozen=True)
class IncidentConfig:
  """Recorder knobs (the ``serve`` CLI ``--incident-*`` flags map 1:1).

  ``dir`` is the on-disk bundle ring; ``keep`` bounds it (oldest bundle
  pruned past it). ``tsdb_window_s`` is how much history the bundle
  freezes around the fire edge; ``top_k_cells`` bounds the attribution
  slice; ``profile_seconds`` > 0 additionally wraps a device-profiler
  capture into the bundle (needs the service's profiler configured).
  """

  dir: str
  keep: int = 8
  top_k_cells: int = 8
  tsdb_window_s: float = 300.0
  events_recent: int = 64
  traces_recent: int = 8
  profile_seconds: float = 0.0

  def __post_init__(self):
    if not self.dir:
      raise ValueError("IncidentConfig.dir must be set")
    if self.keep < 1:
      raise ValueError(f"keep must be >= 1, got {self.keep}")
    if self.top_k_cells < 0:
      raise ValueError(f"top_k_cells must be >= 0, got {self.top_k_cells}")
    if self.tsdb_window_s <= 0:
      raise ValueError(
          f"tsdb_window_s must be > 0, got {self.tsdb_window_s}")
    if self.events_recent < 0:
      raise ValueError(
          f"events_recent must be >= 0, got {self.events_recent}")
    if self.traces_recent < 0:
      raise ValueError(
          f"traces_recent must be >= 0, got {self.traces_recent}")
    if self.profile_seconds < 0:
      raise ValueError(
          f"profile_seconds must be >= 0, got {self.profile_seconds}")


class IncidentRecorder:
  """Fire-edge-triggered bundle capture with a bounded disk ring.

  Args:
    config: ring/window knobs.
    collect: ``(alert: dict) -> dict`` building the bundle's context
      (traces, tsdb window, events, attribution ...). May be None at
      construction — the adopting service wires its own, like the
      shipper's tsdb.
    on_bundle: optional ``(bundle: dict) -> None`` called after each
      capture lands on disk (the service wires the shipper's
      ``note_incident`` here); failures are counted, never fatal.
    events: event-log emitter for ``incident_captured`` /
      ``incident_capture_failed``.
    clock: monotonic source for capture durations.
    wall: wall-clock source for bundle timestamps (cross-process
      artifact, like the event log's).

  ``note_alert`` is O(1) and safe from the alert-callback path; capture
  runs on the worker thread (``start()``) or via ``drain()`` in tests.
  """

  def __init__(self, config: IncidentConfig, collect=None, on_bundle=None,
               events=NULL_EVENTS, clock=time.monotonic, wall=time.time):
    self.config = config
    self.collect = collect
    self.on_bundle = on_bundle
    self.events = events
    self._clock = clock
    self._wall = wall
    self._lock = threading.Lock()
    self._queue: queue.SimpleQueue = queue.SimpleQueue()
    self._firing: set[str] = set()
    self._thread: threading.Thread | None = None
    self._index: list[dict] = []  # oldest first, mirrors the disk ring
    self._seq = 0
    self.captures = 0
    self.capture_errors = 0
    self.suppressed = 0
    self.pending = 0
    self.pruned = 0
    self.ship_errors = 0
    os.makedirs(config.dir, exist_ok=True)
    # Resume past bundles a previous process left behind: the sequence
    # continues after the highest resident file (restarting at 1 would
    # rename OVER retained incidents) and the index lists them.
    for name in sorted(os.listdir(config.dir)):
      m = _BUNDLE_RE.match(name)
      if m is None:
        continue
      self._seq = max(self._seq, int(m.group(1)))
      path = os.path.join(config.dir, name)
      entry = {"id": name[:-len(".json")], "alert": None,
               "captured_at": None, "bytes": 0}
      try:
        entry["bytes"] = os.path.getsize(path)
        with open(path, "r") as fh:
          head = json.load(fh)
        entry["alert"] = (head.get("alert") or {}).get("alert")
        entry["captured_at"] = head.get("captured_at")
      except (OSError, ValueError):
        pass
      self._index.append(entry)

  # -- the alert edge (request-path cheap) ---------------------------------

  def note_alert(self, name: str, firing: bool, details=None) -> None:
    """Queue one capture on a fire edge; dedup until the clear edge.

    A re-fire of an already-firing alert is suppressed (counted) — one
    bundle per incident. The clear edge only releases the dedup latch;
    it never captures.
    """
    with self._lock:
      if not firing:
        self._firing.discard(name)
        return
      if name in self._firing:
        self.suppressed += 1
        return
      self._firing.add(name)
      self.pending += 1
    self._queue.put({"alert": name, "details": dict(details or {}),
                     "noted_at": round(self._wall(), 3)})

  # -- capture (worker thread / drain) -------------------------------------

  def _capture(self, job: dict) -> None:
    t0 = self._clock()
    with self._lock:
      self._seq += 1
      seq = self._seq
    incident_id = f"incident-{seq:06d}"
    context = {}
    if self.collect is not None:
      try:
        context = self.collect(job) or {}
      except Exception as e:  # noqa: BLE001 - a failing collector must
        # still leave a bundle naming the alert (a black box that dies
        # of the crash it was recording is no black box).
        with self._lock:
          self.capture_errors += 1
        context = {"collect_error": repr(e)}
    bundle = {
        "kind": "mpi_incident",
        "id": incident_id,
        "seq": seq,
        "alert": job,
        "captured_at": round(self._wall(), 3),
        "capture_s": None,  # stamped below, after the context snapshot
        **context,
    }
    bundle["capture_s"] = round(self._clock() - t0, 6)
    path = os.path.join(self.config.dir, incident_id + ".json")
    body = json.dumps(bundle).encode()
    try:
      tmp = path + ".tmp"
      with open(tmp, "wb") as fh:
        fh.write(body)
      os.replace(tmp, path)
    except OSError as e:
      with self._lock:
        self.capture_errors += 1
        self.pending -= 1
      self.events.emit("incident_capture_failed", incident=incident_id,
                       alert=job["alert"], error=repr(e))
      return
    with self._lock:
      self.captures += 1
      self.pending -= 1
      self._index.append({"id": incident_id, "alert": job["alert"],
                          "captured_at": bundle["captured_at"],
                          "bytes": len(body)})
      prune = self._index[:max(len(self._index) - self.config.keep, 0)]
      del self._index[:len(prune)]
    for entry in prune:
      try:
        os.remove(os.path.join(self.config.dir, entry["id"] + ".json"))
      except OSError:
        pass
      with self._lock:
        self.pruned += 1
    self.events.emit("incident_captured", incident=incident_id,
                     alert=job["alert"], bytes=len(body),
                     capture_s=bundle["capture_s"])
    if self.on_bundle is not None:
      try:
        self.on_bundle(bundle)
      except Exception:  # noqa: BLE001 - shipping is best-effort here;
        # the bundle is already durable on disk.
        with self._lock:
          self.ship_errors += 1

  def drain(self) -> int:
    """Capture every queued fire edge synchronously; returns how many.
    The worker loop body — tests (and an un-started adopted recorder)
    call it directly for deterministic captures."""
    done = 0
    while True:
      try:
        job = self._queue.get_nowait()
      except queue.Empty:
        return done
      if job is None:
        continue  # a stop sentinel racing a manual drain
      self._capture(job)
      done += 1

  def _loop(self) -> None:
    while True:
      job = self._queue.get()
      if job is None:
        return
      self._capture(job)

  def start(self) -> "IncidentRecorder":
    if self._thread is not None:
      raise RuntimeError("IncidentRecorder already started")
    self._thread = threading.Thread(target=self._loop,
                                    name="mpi-obs-incident", daemon=True)
    self._thread.start()
    return self

  def stop(self) -> None:
    """Stop the worker after it finishes everything already queued (the
    sentinel lands behind pending fire edges, so a capture racing close
    still reaches disk)."""
    if self._thread is not None:
      self._queue.put(None)
      self._thread.join(5.0)
      self._thread = None

  # -- introspection -------------------------------------------------------

  def list(self) -> list[dict]:
    """The bundle index, newest first (the ``/debug/incidents`` body)."""
    with self._lock:
      return [dict(entry) for entry in reversed(self._index)]

  def get(self, incident_id: str) -> dict:
    """One full bundle by id; raises KeyError when unknown (handlers
    map it to 404). Reads disk so a bundle from a previous process is
    fetchable too."""
    if _BUNDLE_RE.match(str(incident_id) + ".json") is None:
      raise KeyError(f"unknown incident {incident_id!r}")
    path = os.path.join(self.config.dir, str(incident_id) + ".json")
    try:
      with open(path, "r") as fh:
        return json.load(fh)
    except (OSError, ValueError):
      raise KeyError(f"unknown incident {incident_id!r}") from None

  def stats(self) -> dict:
    with self._lock:
      return {
          "dir": self.config.dir,
          "keep": self.config.keep,
          "captures": self.captures,
          "capture_errors": self.capture_errors,
          "suppressed": self.suppressed,
          "pending": self.pending,
          "pruned": self.pruned,
          "ship_errors": self.ship_errors,
          "bundles": len(self._index),
          "bundle_bytes": sum(e["bytes"] for e in self._index),
          "firing": sorted(self._firing),
      }


def registry(stats: dict | None) -> prom.Registry:
  """The ``mpi_obs_incident_*`` families (zeros while the recorder is
  off — the always-exposed convention)."""
  stats = stats or {}
  reg = prom.Registry()
  p = PREFIX
  reg.counter(p + "captures_total",
              "Incident bundles captured on SLO fire edges.",
              stats.get("captures", 0))
  reg.counter(p + "capture_errors_total",
              "Captures that failed (collector raised or disk write "
              "failed).", stats.get("capture_errors", 0))
  reg.counter(p + "suppressed_total",
              "Fire edges deduplicated while the same alert was still "
              "firing.", stats.get("suppressed", 0))
  reg.counter(p + "pruned_total",
              "Bundles pruned from the on-disk ring past keep-K.",
              stats.get("pruned", 0))
  reg.counter(p + "ship_errors_total",
              "Bundles whose shipper hand-off raised (bundle stays on "
              "disk).", stats.get("ship_errors", 0))
  reg.gauge(p + "pending", "Fire edges queued for capture.",
            stats.get("pending", 0))
  reg.gauge(p + "bundles", "Bundles resident in the on-disk ring.",
            stats.get("bundles", 0))
  reg.gauge(p + "bundle_bytes", "Bytes of bundles resident on disk.",
            stats.get("bundle_bytes", 0))
  return reg


class LifecycleIncidentTap:
  """Turn fleet-lifecycle EVENTS into incident fire/clear edges.

  The SLO engine owns alert edges on the request path; fleet-lifecycle
  incidents (a quarantine, a crash loop, a gossip peer death, an
  autoscale action) surface only in the event stream. This tap is an
  ``EventLog`` sink (tee it next to ``file_sink``): each JSON line is
  parsed and mapped onto ``IncidentRecorder.note_alert`` episodes, so
  the `/debug/incidents` ring captures ONE black-box bundle per
  lifecycle episode with the recorder's existing dedup latch:

    * ``backend_quarantined`` fires ``quarantine:{backend}`` (and
      closes any crash-loop episode — the quarantine verdict subsumes
      it); ``backend_readmit`` clears both.
    * ``backend_restart`` with ``attempt >= 2`` fires
      ``crash_loop:{backend}`` (the first restart of an episode is
      routine; the second consecutive one is a loop); a successful
      first-attempt restart clears it.
    * ``gossip_peer_failure`` fires ``gossip_peer:{peer}``;
      ``gossip_peer_recovered`` clears it.
    * ``autoscale_{up,down,abort}`` are point-in-time decisions, not
      conditions: each fires AND immediately clears a key unique per
      event (the log's own seq), so every decision captures exactly
      one bundle and can never latch.

  Parse or mapping failures are counted, never raised — a sink that
  throws would take the event log down with it.
  """

  def __init__(self, recorder: IncidentRecorder):
    self.recorder = recorder
    self.taps = 0
    self.errors = 0

  def __call__(self, line: str) -> None:
    self.sink(line)

  def sink(self, line: str) -> None:
    try:
      record = json.loads(line)
      self.note_event(record)
    except Exception:  # noqa: BLE001 - sinks must never throw upward
      self.errors += 1

  def note_event(self, record: dict) -> None:
    kind = record.get("kind")
    note = self.recorder.note_alert
    if kind == "backend_quarantined":
      backend = record.get("backend")
      note(f"crash_loop:{backend}", firing=False)
      note(f"quarantine:{backend}", firing=True, details=record)
    elif kind == "backend_readmit":
      backend = record.get("backend")
      note(f"quarantine:{backend}", firing=False)
      note(f"crash_loop:{backend}", firing=False)
    elif kind == "backend_restart" and record.get("ok"):
      backend = record.get("backend")
      if (record.get("attempt") or 0) >= 2:
        note(f"crash_loop:{backend}", firing=True, details=record)
      else:
        note(f"crash_loop:{backend}", firing=False)
    elif kind == "gossip_peer_failure":
      note(f"gossip_peer:{record.get('peer')}", firing=True,
           details=record)
    elif kind == "gossip_peer_recovered":
      note(f"gossip_peer:{record.get('peer')}", firing=False)
    elif kind in ("autoscale_up", "autoscale_down", "autoscale_abort"):
      name = f"{kind}:{record.get('seq')}"
      note(name, firing=True, details=record)
      note(name, firing=False)
    else:
      return
    self.taps += 1
