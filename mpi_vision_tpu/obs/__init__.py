"""Observability layer for the serving stack: tracing, Prometheus, profiling.

The instrumentation backbone the ROADMAP's perf work (async
double-buffering, multi-host serving) will be measured with — per-stage
visibility in the FastNeRF/Potamoi style, where every latency claim is a
per-stage accounting, not a single end-to-end number:

  * ``trace`` — request tracing: a lock-guarded, injectable-clock
    ``Tracer`` hands each ``/render`` a trace id and records a span tree
    (queue-wait, batch-assembly, dispatch with retry attempts, bake,
    h2d/compute/readback), emitted as structured JSON log lines and kept
    in a bounded ring served at ``/debug/traces``. Disabled tracing
    routes every call through the ``NULL_TRACE``/``NULL_TRACER``
    singletons — empty methods, no allocation, no locking.
  * ``prom`` — Prometheus text exposition: a small metric registry
    rendering the ``/stats`` snapshot (every ``ServeMetrics`` counter,
    the latency histogram, breaker state, cache stats) in the standard
    ``# TYPE``/``# HELP`` format for ``/metrics``.
  * ``profile`` — on-demand device profiling: a concurrency-guarded
    wrapper over ``jax.profiler`` (via ``debug.trace``) capturing live
    traffic for N seconds (``/debug/profile``, ``serve --profile-dir``).
  * ``slo`` — the judgment layer over the raw counters: sliding-window
    availability + latency objectives with multi-window burn-rate
    alerting (``SloTracker``), surfaced in ``/stats``, ``/metrics``
    (``mpi_slo_*``), and the ``/healthz`` state machine.
  * ``events`` — a bounded structured lifecycle event log (breaker
    transitions, failovers, scene swaps, checkpoint lifecycle, NaN
    rollbacks, alert fire/clear) served at ``/debug/events`` with an
    optional JSONL file sink.
  * ``hist`` — native (sparse exponential-bucket) histograms with
    per-bucket trace-id exemplars: percentile-true latency families
    (``mpi_serve_*_nativehist``) that merge exactly across time buckets
    and backends, powering the quantile SLOs and pooled fleet quantiles.
  * ``tsdb`` — the on-box time-series ring: every metric family sampled
    on a cadence into bounded per-series rings, served at
    ``/debug/tsdb`` (the router fans the query out fleet-wide).
  * ``ship`` — off-host telemetry shipping: rotated event-log segments,
    SLO alert edges, and incremental tsdb snapshots batched to an HTTP
    sink with retry + disk spool (imported as ``mpi_vision_tpu.obs.ship``,
    not re-exported here — it layers on ``serve.resilience``).
"""

from mpi_vision_tpu.obs.events import NULL_EVENTS, EventLog, file_sink
from mpi_vision_tpu.obs.hist import NativeHistogram
from mpi_vision_tpu.obs.tsdb import TsdbConfig, TsdbRecorder
from mpi_vision_tpu.obs.profile import DeviceProfiler, ProfileBusyError
from mpi_vision_tpu.obs.prom import (
    ExpositionCache,
    Metric,
    Registry,
    aggregate_metrics_texts,
    parse_metrics_text,
    render_serve_metrics,
    serve_registry,
)
from mpi_vision_tpu.obs.slo import SloConfig, SloTracker
from mpi_vision_tpu.obs.trace import (
    NULL_TRACE,
    NULL_TRACER,
    SpanRecorder,
    Trace,
    Tracer,
    new_trace_id,
)
