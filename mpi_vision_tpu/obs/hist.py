"""Native (sparse, exponential-bucket) histograms with exemplars.

The flight recorder's measurement primitive. The classic fixed-bucket
latency histogram (``serve/metrics.py``'s ``LATENCY_BUCKETS_S``) answers
"how many requests beat 100 ms" but not "what IS p99" — quantiles read
off 13 hand-picked bounds are only as accurate as the nearest bound, and
a pool aggregator can do nothing better. A native histogram puts every
positive observation into an exponential bucket ``(base^(i-1), base^i]``
with ``base = 2^(1/scale)``, so:

  * **resolution is relative and uniform** — at ``scale = 4`` every
    bucket is ~19% wide, so a quantile estimate is within ~9% of truth
    at any magnitude, from 100 us cache hits to 30 s retry storms,
    without choosing bounds in advance;
  * **histograms merge exactly** — two histograms at one scale share the
    same bucket index space, so pooling across time buckets (the SLO
    windows) or across backends (the cluster router) is a per-index
    count sum, never a lossy re-bucketing. This is what lets the router
    aggregate them instead of dropping them as non-additive the way the
    ratio gauges are;
  * **exemplars ride the buckets** — each bucket remembers the most
    recent trace id observed in it, so "p99 is 1.4 s" links directly to
    a recorded trace of an actual 1.4 s request (``/debug/traces``).

Everything is a plain dict-of-ints snapshot away from JSON, so the same
representation rides ``/stats``, the Prometheus exposition
(``mpi_serve_*_nativehist`` families), the SLO windows, and the
off-host shipper.

No locking here: every holder (``ServeMetrics``, ``SloTracker``) already
serializes access under its own lock. No clock reads either (exemplars
are ordered by arrival, not time) — clock-lint covers this file.
"""

from __future__ import annotations

import math

# Buckets per power of two. base = 2**(1/SCALE) ~= 1.189: ~19% relative
# bucket width, worst-case ~9% quantile error — comfortably inside any
# latency objective's slack, at ~40 resident buckets for the us..minutes
# range real serving latencies span. One shared scale for the whole
# stack keeps every histogram in one index space, which is what makes
# the text-exposition pool merge a plain per-sample sum.
SCALE = 4

# Index clamp: base^-160 ~= 1e-12 s and base^120 ~= 1e9 s. Observations
# beyond these land in the edge bucket instead of growing the sparse
# map without bound (a hostile/buggy caller recording 1e-300 must not
# allocate 4000 buckets).
MIN_IDX = -160
MAX_IDX = 120

# The quantiles the convenience gauges export (/metrics, tsdb, router
# pool view). Labels use the short string forms below.
QUANTILES = (0.5, 0.9, 0.99)

# Per-backend quantile gauges are statements about ONE process — summing
# p99s across a pool is meaningless, so the cluster router drops this
# family from its summed exposition and computes its own pooled
# quantiles from the (correctly merged) native-histogram buckets.
NON_ADDITIVE_FAMILIES = frozenset({
    "mpi_serve_request_quantile_seconds",
})


def bucket_index(value: float, scale: int = SCALE) -> int:
  """The bucket index of a positive observation (clamped)."""
  idx = math.ceil(math.log2(value) * scale)
  return min(max(idx, MIN_IDX), MAX_IDX)


def bucket_bounds(idx: int, scale: int = SCALE) -> tuple[float, float]:
  """The ``(lower, upper]`` value range of bucket ``idx``."""
  return 2.0 ** ((idx - 1) / scale), 2.0 ** (idx / scale)


class NativeHistogram:
  """A sparse exponential-bucket histogram with per-bucket exemplars.

  ``record`` is O(1); ``quantile`` and ``snapshot`` are O(resident
  buckets) (tens, by construction). Non-positive observations land in
  the zero bucket (latencies are >= 0; a 0.0 is a legitimate "free"
  operation, not an error).
  """

  __slots__ = ("scale", "count", "sum", "zero", "buckets", "exemplars")

  def __init__(self, scale: int = SCALE):
    if scale < 1:
      raise ValueError(f"scale must be >= 1, got {scale}")
    self.scale = int(scale)
    self.count = 0
    self.sum = 0.0
    self.zero = 0
    self.buckets: dict[int, int] = {}
    # idx -> (exemplar_id, observed_value); newest observation wins so
    # the exemplar always points at a trace the ring plausibly still
    # holds.
    self.exemplars: dict[int, tuple[str, float]] = {}

  def record(self, value: float, exemplar: str | None = None) -> None:
    value = float(value)
    self.count += 1
    self.sum += value
    if value <= 0.0:
      self.zero += 1
      return
    idx = bucket_index(value, self.scale)
    self.buckets[idx] = self.buckets.get(idx, 0) + 1
    if exemplar:
      self.exemplars[idx] = (str(exemplar), value)

  def merge_from(self, other: "NativeHistogram | None") -> None:
    """Fold another live histogram into this one (exact merge — the SLO
    windows pool their per-time-bucket histograms this way)."""
    if other is None or other.count == 0:
      return
    if other.scale != self.scale:
      raise ValueError(
          f"cannot merge scale {other.scale} into {self.scale}")
    self.count += other.count
    self.sum += other.sum
    self.zero += other.zero
    for idx, n in other.buckets.items():
      self.buckets[idx] = self.buckets.get(idx, 0) + n
    for idx, pair in other.exemplars.items():
      mine = self.exemplars.get(idx)
      if mine is None or pair[1] >= mine[1]:
        self.exemplars[idx] = pair

  def merge_snapshot(self, snap: dict | None) -> None:
    """Fold another histogram's snapshot into this one (exact merge).

    Scales must match (the stack-wide ``SCALE`` guarantees it); on an
    exemplar collision the larger observed value wins — the tail is
    what an operator chasing a quantile alert wants to click through.
    """
    if not snap or not snap.get("count"):
      return
    if int(snap.get("scale", self.scale)) != self.scale:
      raise ValueError(
          f"cannot merge scale {snap.get('scale')} into {self.scale}")
    self.count += int(snap["count"])
    self.sum += float(snap["sum"])
    self.zero += int(snap.get("zero", 0))
    for key, n in (snap.get("buckets") or {}).items():
      idx = int(key)
      self.buckets[idx] = self.buckets.get(idx, 0) + int(n)
    for key, ex in (snap.get("exemplars") or {}).items():
      idx = int(key)
      pair = (str(ex["trace_id"]), float(ex["value"]))
      mine = self.exemplars.get(idx)
      if mine is None or pair[1] >= mine[1]:
        self.exemplars[idx] = pair

  def quantile(self, q: float) -> float | None:
    """Estimated value at quantile ``q`` (None while empty).

    Linear interpolation inside the containing bucket — bounded by the
    bucket's ~``1/scale`` relative width, which is the whole point of
    exponential buckets.
    """
    if not 0.0 <= q <= 1.0:
      raise ValueError(f"q must be in [0, 1], got {q}")
    if self.count == 0:
      return None
    rank = q * self.count
    if rank <= self.zero:
      return 0.0
    cum = self.zero
    for idx in sorted(self.buckets):
      n = self.buckets[idx]
      if cum + n >= rank:
        lo, hi = bucket_bounds(idx, self.scale)
        frac = (rank - cum) / n
        return lo + frac * (hi - lo)
      cum += n
    # Numerically possible only via float rank rounding: everything
    # counted, answer is the top of the highest bucket.
    return bucket_bounds(max(self.buckets), self.scale)[1]

  def fraction_over(self, threshold: float) -> float:
    """Estimated fraction of observations above ``threshold``."""
    if self.count == 0:
      return 0.0
    over = 0.0
    for idx, n in self.buckets.items():
      lo, hi = bucket_bounds(idx, self.scale)
      if lo >= threshold:
        over += n
      elif hi > threshold:
        over += n * (hi - threshold) / (hi - lo)
    return min(over / self.count, 1.0)

  def snapshot(self) -> dict:
    """JSON-ready state (str bucket keys; rides /stats and the shipper)."""
    return {
        "scale": self.scale,
        "count": self.count,
        "sum": round(self.sum, 6),
        "zero": self.zero,
        "buckets": {str(idx): n for idx, n in sorted(self.buckets.items())},
        "exemplars": {
            str(idx): {"trace_id": tid, "value": round(value, 6)}
            for idx, (tid, value) in sorted(self.exemplars.items())},
    }


def merge(snapshots) -> NativeHistogram:
  """A fresh histogram holding the exact merge of ``snapshots``
  (None/empty entries contribute nothing)."""
  out = NativeHistogram()
  for snap in snapshots:
    out.merge_snapshot(snap)
  return out


def quantile_of(snapshot: dict | None, q: float) -> float | None:
  """``quantile(q)`` straight off a snapshot dict (None while empty)."""
  if not snapshot or not snapshot.get("count"):
    return None
  return merge([snapshot]).quantile(q)


def q_label(q: float) -> str:
  """The ``q=`` label value for a quantile gauge ("0.99", "0.5")."""
  return f"{q:g}"


def add_family(reg, name: str, help_text: str, items) -> None:
  """Render native-histogram snapshots as one exposition family.

  ``items`` is ``[(extra_labels_dict, snapshot_or_None), ...]`` (one
  entry per label group — e.g. one per ``phase``). Emitted samples:
  ``_bucket{idx=,le=}`` per resident bucket (``le`` is the bucket's
  upper bound, for humans; ``idx`` is the merge key), ``_zero``,
  ``_sum``, ``_count``. Bucket samples carry their exemplar
  OpenMetrics-style (`` # {trace_id="..."} value``). Because every
  histogram shares ``SCALE``, the cluster aggregator's per-sample sum
  IS the exact bucket merge.
  """
  m = reg.histogram_family(name, help_text)
  for labels, snap in items:
    labels = dict(labels or {})
    snap = snap or {}
    scale = int(snap.get("scale", SCALE))
    exemplars = snap.get("exemplars") or {}
    for key, n in (snap.get("buckets") or {}).items():
      idx = int(key)
      ex = exemplars.get(key)
      m.sample(n, {**labels, "idx": str(idx),
                   "le": f"{bucket_bounds(idx, scale)[1]:.6g}"},
               suffix="_bucket",
               exemplar=(ex["trace_id"], ex["value"]) if ex else None)
    m.sample(snap.get("zero", 0), labels, suffix="_zero")
    m.sample(snap.get("sum", 0.0), labels, suffix="_sum")
    m.sample(snap.get("count", 0), labels, suffix="_count")


def snapshots_from_samples(samples: dict) -> dict:
  """Reconstruct snapshots from one family's parsed exposition samples.

  The router-side inverse of ``add_family``: ``samples`` is the
  ``{(sample_name, labels_tuple): value}`` map ``parse_metrics_text``
  returns for a ``*_nativehist`` family (already pool-summed by
  ``aggregate_metrics_texts`` — per-``idx`` sums are the exact merge).
  Returns ``{group_labels_tuple: snapshot}`` keyed by the labels minus
  ``idx``/``le``.
  """
  groups: dict[tuple, dict] = {}

  def group(labels) -> dict:
    key = tuple(kv for kv in labels if kv[0] not in ("idx", "le"))
    return groups.setdefault(key, {"scale": SCALE, "count": 0, "sum": 0.0,
                                   "zero": 0, "buckets": {},
                                   "exemplars": {}})

  for (sample_name, labels), value in samples.items():
    if sample_name.endswith("_bucket"):
      idx = next((v for k, v in labels if k == "idx"), None)
      if idx is None:
        continue
      g = group(labels)
      g["buckets"][idx] = g["buckets"].get(idx, 0) + int(value)
    elif sample_name.endswith("_zero"):
      group(labels)["zero"] += int(value)
    elif sample_name.endswith("_sum"):
      group(labels)["sum"] += float(value)
    elif sample_name.endswith("_count"):
      group(labels)["count"] += int(value)
  return groups
