"""Prometheus text exposition for the serving metrics.

A small registry (``Metric`` families collected into a ``Registry``,
rendered as ``# HELP``/``# TYPE`` + samples) so ``/metrics`` is built
declaratively here instead of string-formatted through ``server.py``.
``serve_registry`` maps the ``/stats`` snapshot — every ``ServeMetrics``
counter, the cumulative latency histogram, breaker state, cache stats —
onto stable metric names a stock Prometheus scraper ingests as-is.

Conventions follow the exposition-format spec: counters end in
``_total``, histograms emit cumulative ``_bucket{le=...}`` series plus
``_sum``/``_count``, enum-style state (breaker) is one gauge per state
with exactly one sample at 1. ``parse_metrics_text`` is the minimal
inverse used by the tier-1 test that pins ``/metrics`` against
``/stats``.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time

from mpi_vision_tpu.obs import hist as hist_mod

_TYPES = ("counter", "gauge", "histogram")

# The shared metric-name prefix: one grep (or one Grafana variable) finds
# every series this stack exports.
PREFIX = "mpi_serve_"


def _escape_help(text: str) -> str:
  return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value: str) -> str:
  return (value.replace("\\", "\\\\").replace("\n", "\\n")
          .replace('"', '\\"'))


def format_value(value) -> str:
  """Prometheus sample value: integers bare, floats via repr, +Inf/NaN."""
  if value is None:
    return "NaN"
  if isinstance(value, bool):
    return "1" if value else "0"
  if isinstance(value, int):
    return str(value)
  value = float(value)
  if math.isinf(value):
    return "+Inf" if value > 0 else "-Inf"
  if math.isnan(value):
    return "NaN"
  if value == int(value) and abs(value) < 1e15:
    return str(int(value))
  return repr(value)


@dataclasses.dataclass
class Metric:
  """One metric family: name, type, help, and its samples.

  Samples are ``(suffix, labels, value, exemplar)`` — suffix is appended
  to the family name (histograms use ``_bucket``/``_sum``/``_count``).
  ``exemplar`` is an optional ``(trace_id, observed_value)`` pair,
  rendered OpenMetrics-style after the sample
  (`` # {trace_id="..."} value``) — the native-histogram families use it
  to link a bucket to a recorded trace.
  """

  name: str
  mtype: str
  help: str

  def __post_init__(self):
    if self.mtype not in _TYPES:
      raise ValueError(f"metric type must be one of {_TYPES}, "
                       f"got {self.mtype!r}")
    self.samples: list[tuple[str, dict, object, tuple | None]] = []

  def sample(self, value, labels: dict | None = None,
             suffix: str = "", exemplar: tuple | None = None) -> "Metric":
    self.samples.append((suffix, dict(labels or {}), value, exemplar))
    return self

  def render(self) -> str:
    lines = [f"# HELP {self.name} {_escape_help(self.help)}",
             f"# TYPE {self.name} {self.mtype}"]
    for suffix, labels, value, exemplar in self.samples:
      label_str = ""
      if labels:
        inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                         for k, v in labels.items())
        label_str = "{" + inner + "}"
      line = f"{self.name}{suffix}{label_str} {format_value(value)}"
      if exemplar is not None:
        tid, observed = exemplar
        line += (f' # {{trace_id="{_escape_label(str(tid))}"}} '
                 f"{format_value(observed)}")
      lines.append(line)
    return "\n".join(lines)


class Registry:
  """An ordered collection of metric families rendered as one exposition."""

  def __init__(self):
    self._metrics: list[Metric] = []

  def extend(self, other: "Registry") -> "Registry":
    """Append every family of ``other`` into this registry (one joint
    exposition; the caller owns name uniqueness across the two)."""
    self._metrics.extend(other._metrics)
    return self

  def counter(self, name: str, help: str, value=None,
              labels: dict | None = None) -> Metric:
    m = Metric(name, "counter", help)
    if value is not None:
      m.sample(value, labels)
    self._metrics.append(m)
    return m

  def gauge(self, name: str, help: str, value=None,
            labels: dict | None = None) -> Metric:
    m = Metric(name, "gauge", help)
    if value is not None:
      m.sample(value, labels)
    self._metrics.append(m)
    return m

  def histogram(self, name: str, help: str, buckets, sum_value,
                count) -> Metric:
    """``buckets``: iterable of ``(upper_bound_or_inf, cumulative_count)``
    in ascending bound order; the ``+Inf`` bucket is added when absent."""
    m = Metric(name, "histogram", help)
    saw_inf = False
    for le, cum in buckets:
      saw_inf = saw_inf or math.isinf(float(le))
      m.sample(cum, {"le": format_value(float(le))}, suffix="_bucket")
    if not saw_inf:
      m.sample(count, {"le": "+Inf"}, suffix="_bucket")
    m.sample(sum_value, suffix="_sum")
    m.sample(count, suffix="_count")
    self._metrics.append(m)
    return m

  def histogram_family(self, name: str, help: str) -> Metric:
    """A histogram-typed family whose samples the caller fills directly
    (the native-histogram families: ``_bucket{idx=,le=}`` / ``_zero`` /
    ``_sum`` / ``_count``, see ``obs/hist.py``)."""
    m = Metric(name, "histogram", help)
    self._metrics.append(m)
    return m

  def enum(self, name: str, help: str, states, active: str) -> Metric:
    """One gauge sample per state; the active one is 1, the rest 0."""
    m = Metric(name, "gauge", help)
    for state in states:
      m.sample(1 if state == active else 0, {"state": state})
    self._metrics.append(m)
    return m

  def render(self) -> str:
    return "\n".join(m.render() for m in self._metrics) + "\n"


def serve_registry(stats: dict,
                   latency_hist: dict | None = None) -> Registry:
  """Map a ``RenderService.stats()`` snapshot onto the metric families.

  ``latency_hist`` is ``ServeMetrics.latency_histogram()`` (cumulative
  bucket counts + sum + count); None omits the histogram family.
  """
  reg = Registry()
  p = PREFIX
  reg.gauge(p + "uptime_seconds",
            "Seconds since the metrics window started.",
            stats.get("uptime_s", 0.0))
  reg.counter(p + "requests_total", "Completed render requests.",
              stats.get("requests", 0))
  reg.counter(p + "batches_total", "Device dispatches (micro-batches).",
              stats.get("batches", 0))
  reg.counter(p + "device_render_seconds_total",
              "Cumulative device time inside engine render calls.",
              stats.get("device_render_seconds", 0.0))
  phases = stats.get("device_phase_seconds") or {}
  phase_m = reg.counter(
      p + "device_phase_seconds_total",
      "Device render time split by phase (h2d / compute / readback).")
  for phase in ("h2d", "compute", "readback"):
    phase_m.sample(phases.get(phase, 0.0), {"phase": phase})
  errors = stats.get("errors") or {}
  err_m = reg.counter(
      p + "errors_total",
      "Failed requests by class (transient device / permanent bad-input "
      "/ deadline-expired).")
  for cls in ("transient", "permanent", "deadline"):
    err_m.sample(errors.get(cls, 0), {"class": cls})
  reg.counter(p + "rejected_total",
              "Submissions shed at the door (queue full).",
              stats.get("rejected", 0))
  res = stats.get("resilience") or {}
  for key, help_text in (
      ("retries", "Retry attempts after transient dispatch failures."),
      ("watchdog_trips", "Dispatches abandoned by the hang watchdog."),
      ("fallback_renders", "Batches served by the degraded-mode "
                           "fallback engine."),
      ("breaker_opens", "Circuit breaker CLOSED->OPEN transitions."),
      ("breaker_fastfails", "Requests fast-failed against an open "
                            "circuit."),
      ("client_disconnects", "Clients that hung up mid-response."),
  ):
    reg.counter(p + key + "_total", help_text, res.get(key, 0))
  reg.gauge(p + "queue_depth", "Pending requests in the scheduler queue.",
            stats.get("queue_depth", 0))
  pipeline = stats.get("pipeline") or {}
  gap = pipeline.get("dispatch_gap") or {}
  reg.gauge(p + "inflight", "Flights currently in the pipeline window.",
            pipeline.get("inflight", 0))
  reg.counter(p + "dispatch_gaps_total",
              "Launches that found the device idle (nothing in flight).",
              gap.get("count", 0))
  reg.counter(p + "dispatch_gap_seconds_total",
              "Cumulative device idle time between flights.",
              gap.get("total_s", 0.0))
  reg.counter(p + "out_of_order_completions_total",
              "Flights completed while an earlier dispatch was in flight.",
              pipeline.get("out_of_order_completions", 0))
  reg.counter(p + "abandoned_batches_total",
              "Flights abandoned with device work possibly still running.",
              pipeline.get("abandoned_batches", 0))
  if latency_hist is not None:
    reg.histogram(p + "request_latency_seconds",
                  "End-to-end request latency (enqueue to response).",
                  latency_hist["buckets"], latency_hist["sum"],
                  latency_hist["count"])
  hist = stats.get("batch_size_hist") or {}
  sizes = sorted(int(k) for k in hist)
  cum, total_reqs, buckets = 0, 0, []
  for size in sizes:
    cum += hist[str(size)]
    total_reqs += size * hist[str(size)]
    buckets.append((float(size), cum))
  reg.histogram(p + "batch_size",
                "Coalesced requests per device dispatch.",
                buckets, total_reqs, stats.get("batches", 0))
  # Native histograms (obs/hist.py): sparse exponential buckets with
  # per-bucket trace-id exemplars — percentile-true latency families the
  # router can pool-merge exactly (shared idx space: per-sample sums ARE
  # the bucket merge). Always exposed, zeros and all.
  nh = stats.get("hist") or {}
  hist_mod.add_family(
      reg, p + "request_latency_nativehist",
      "Request latency (seconds) in native exponential buckets with "
      "trace-id exemplars.", [({}, nh.get("request"))])
  hist_mod.add_family(
      reg, p + "phase_latency_nativehist",
      "Per-dispatch device phase duration (seconds) in native buckets, "
      "label phase=h2d|compute|readback.",
      [({"phase": phase}, (nh.get("phase") or {}).get(phase))
       for phase in ("h2d", "compute", "readback")])
  hist_mod.add_family(
      reg, p + "batch_latency_nativehist",
      "Per-dispatch device render time (seconds) in native buckets.",
      [({}, nh.get("batch"))])
  wpe = nh.get("warp_pose_error") or {}
  hist_mod.add_family(
      reg, p + "edge_warp_pose_error",
      "Pose error of every edge warp-serve (how far the served frame's "
      "render pose was from the request), label component=trans "
      "(scene units) | rot_deg (degrees).",
      [({"component": "trans"}, wpe.get("trans")),
       ({"component": "rot_deg"}, wpe.get("rot_deg"))])
  quant = reg.gauge(
      p + "request_quantile_seconds",
      "Request-latency quantiles estimated from the native histogram "
      "(NaN while idle), label q.")
  for q in hist_mod.QUANTILES:
    quant.sample(hist_mod.quantile_of(nh.get("request"), q),
                 {"q": hist_mod.q_label(q)})
  # Edge frame cache (serve/edge/): families are always exposed (zeros
  # while the cache is off) so dashboards and the README metric
  # reference never depend on a knob.
  edge = stats.get("edge") or {}
  reg.counter(p + "edge_hits_total",
              "Edge frame-cache exact view-cell hits (served stored "
              "bytes).", edge.get("hits", 0))
  reg.counter(p + "edge_warp_serves_total",
              "Edge near-misses served by warping the nearest cached "
              "frame.", edge.get("warp_serves", 0))
  reg.counter(p + "edge_misses_total",
              "Edge lookups that fell through to a real render.",
              edge.get("misses", 0))
  reg.counter(p + "edge_revalidations_total",
              "If-None-Match revalidations answered 304 (no render, no "
              "body).", edge.get("revalidations", 0))
  reg.counter(p + "edge_evictions_total",
              "Edge frame-cache LRU evictions.", edge.get("evictions", 0))
  reg.counter(p + "edge_invalidations_total",
              "Edge frames dropped by scene swaps / live reloads.",
              edge.get("invalidations", 0))
  reg.gauge(p + "edge_bytes", "Bytes of rendered frames resident in the "
            "edge cache.", edge.get("bytes", 0))
  reg.gauge(p + "edge_frames", "Rendered frames resident in the edge "
            "cache.", edge.get("frames", 0))
  reg.counter(p + "edge_negative_hits_total",
              "Requests shed fast by a live negative entry (view cell "
              "known queue-full within its negative TTL).",
              edge.get("negative_hits", 0))
  reg.gauge(p + "edge_negative_entries",
            "Live negative entries (view cells recently shed "
            "queue-full).", edge.get("negative_entries", 0))
  # Tile-granular serving (serve/tiles.py): frustum-cull outcomes + the
  # per-tile baked cache. Always exposed (zeros while --tiled is off).
  tiles = stats.get("tiles") or {}
  reg.counter(p + "tile_requests_total",
              "Requests rendered through a tile plan (frustum-culled "
              "crop of a tiled scene).", tiles.get("tiled_requests", 0))
  reg.counter(p + "tile_touched_total",
              "Source tiles the request frusta could sample.",
              tiles.get("touched_total", 0))
  reg.counter(p + "tile_rendered_total",
              "Source tiles inside the dispatched crops.",
              tiles.get("rendered_total", 0))
  reg.counter(p + "tile_culled_total",
              "Source tiles skipped by frustum culling.",
              tiles.get("culled_total", 0))
  tcache = stats.get("tile_cache") or {}
  reg.counter(p + "tile_cache_hits_total", "Baked-tile cache hits.",
              tcache.get("hits", 0))
  reg.counter(p + "tile_cache_misses_total",
              "Baked-tile cache misses (per-tile bakes).",
              tcache.get("misses", 0))
  reg.counter(p + "tile_cache_evictions_total",
              "Baked-tile LRU evictions (cold tiles freed while hot "
              "tiles stay).", tcache.get("evictions", 0))
  reg.counter(p + "tile_cache_invalidations_total",
              "Baked tiles dropped because their bytes changed (live "
              "reload swaps ONLY these).", tcache.get("invalidations", 0))
  reg.gauge(p + "tile_cache_bytes", "Bytes of baked tiles resident.",
            tcache.get("bytes", 0))
  reg.gauge(p + "tile_cache_tiles", "Baked tiles resident.",
            tcache.get("scenes", 0))
  # Scene-asset delivery tier (serve/assets/): manifest + content-
  # addressed tile/layer assets on the serving side, tile-diff scene
  # sync on the fetching side. Always exposed (zeros while off).
  assets = stats.get("assets") or {}
  acache = assets.get("cache") or {}
  reg.counter(p + "asset_manifest_requests_total",
              "GET /scene/{id}/manifest requests (including 304s and "
              "404s).", assets.get("manifest_requests", 0))
  reg.counter(p + "asset_requests_total",
              "GET /scene/{id}/asset/{digest} requests (including 304s "
              "and 404s).", assets.get("requests", 0))
  reg.counter(p + "asset_not_found_total",
              "Asset-tier requests answered 404 (unknown scene, unknown "
              "or no-longer-live digest).", assets.get("not_found", 0))
  reg.counter(p + "asset_not_modified_total",
              "Asset-tier If-None-Match revalidations answered 304 (no "
              "body).", assets.get("not_modified", 0))
  reg.counter(p + "asset_bytes_total",
              "Body bytes served by the asset tier (manifests + "
              "assets).", assets.get("bytes_served", 0))
  reg.counter(p + "asset_encodes_total",
              "Assets (re-)encoded from live scene data (publish or "
              "LRU miss).", assets.get("encodes", 0))
  reg.counter(p + "asset_publish_rejects_total",
              "Corrupt bakes refused at the digest-vs-bytes gate.",
              assets.get("publish_rejects", 0))
  reg.counter(p + "asset_cache_evictions_total",
              "Encoded assets evicted by the asset LRU.",
              acache.get("evictions", 0))
  reg.gauge(p + "asset_cache_bytes",
            "Encoded asset bytes resident in the asset LRU.",
            acache.get("bytes", 0))
  reg.gauge(p + "asset_cache_assets",
            "Encoded assets resident in the asset LRU.",
            acache.get("assets", 0))
  sync = stats.get("scene_sync") or {}
  reg.counter(p + "scene_sync_runs_total",
              "Completed tile-diff scene syncs pulled into this service "
              "(SceneFetcher).", sync.get("runs", 0))
  reg.counter(p + "scene_sync_tiles_fetched_total",
              "Tiles fetched by scene syncs (digest changed or scene "
              "new).", sync.get("tiles_fetched", 0))
  reg.counter(p + "scene_sync_tiles_reused_total",
              "Tiles reused locally by scene syncs (digest unchanged — "
              "the bytes the diff protocol never moved).",
              sync.get("tiles_reused", 0))
  reg.counter(p + "scene_sync_bytes_total",
              "Bytes fetched over the wire by scene syncs.",
              sync.get("bytes_fetched", 0))
  reg.counter(p + "scene_sync_failures_total",
              "Scene syncs that failed (unreachable source, bad "
              "manifest, digest mismatch).", sync.get("failures", 0))
  reg.counter(p + "scene_sync_retries_total",
              "Transient per-fetch failures retried with backoff inside "
              "scene syncs (RetryPolicy) instead of failing the sweep.",
              sync.get("retries", 0))
  # Brownout ladder (serve/brownout.py): always exposed (zeros at L0 /
  # while brownout is off). The level gauge is NON-additive across a
  # fleet (brownout.NON_ADDITIVE_FAMILIES): the router's pooled /metrics
  # drops it and per-backend levels ride the /stats brownout block.
  bo = stats.get("brownout") or {}
  reg.gauge(p + "brownout_level",
            "Current brownout ladder level (0 = full quality ... 4 = "
            "shed with Retry-After).", bo.get("level", 0))
  bo_trans = bo.get("transitions") or {}
  trans_m = reg.counter(
      p + "brownout_transitions_total",
      "Ladder level changes, label direction=down (deeper degradation) "
      "| up (recovery).")
  for direction in ("down", "up"):
    trans_m.sample(bo_trans.get(direction, 0), {"direction": direction})
  bo_sheds = bo.get("sheds") or {}
  shed_m = reg.counter(
      p + "brownout_sheds_total",
      "Requests shed by brownout admission control, label class. "
      "Deliberate load management — excluded from the SLO bad stream.")
  for cls in ("interactive", "prefetch", "background"):
    shed_m.sample(bo_sheds.get(cls, 0), {"class": cls})
  bo_deg = bo.get("degraded") or {}
  deg_m = reg.counter(
      p + "brownout_degraded_total",
      "Responses served below full quality, label level (the ladder "
      "tier that produced them — never cached, never ETag'd).")
  for lvl in ("1", "2", "3", "4"):
    deg_m.sample(bo_deg.get(lvl, 0), {"level": lvl})
  # Session streaming tier (serve/session/): always exposed (zeros while
  # sessions are off).
  sess = stats.get("session") or {}
  reg.gauge(p + "session_active", "Open pose-stream sessions.",
            sess.get("active", 0))
  reg.counter(p + "session_opened_total",
              "Streaming sessions admitted (POST /session accepted).",
              sess.get("opened", 0))
  reg.counter(p + "session_closed_total",
              "Sessions ended for any reason (idle reaps included).",
              sess.get("closed", 0))
  reg.counter(p + "session_rejected_total",
              "Session opens shed at the session bound "
              "(503 + Retry-After).", sess.get("rejected", 0))
  reg.counter(p + "session_idle_reaped_total",
              "Sessions closed by the idle reaper.",
              sess.get("idle_reaped", 0))
  reg.counter(p + "session_frames_total",
              "Frames streamed to session clients.", sess.get("frames", 0))
  reg.counter(p + "session_frame_errors_total",
              "Session frames that failed and were surfaced as in-stream "
              "error frames.", sess.get("frame_errors", 0))
  reg.counter(p + "session_flushes_total",
              "Fused drains of a session's pose queue — each submits its "
              "poses concurrently so the scheduler coalesces one flight.",
              sess.get("flushes", 0))
  sess_pf = sess.get("prefetch") or {}
  reg.counter(p + "session_prefetch_issued_total",
              "Speculative prefetch-class renders issued for predicted "
              "view cells.", sess_pf.get("issued", 0))
  reg.counter(p + "session_prefetch_hits_total",
              "Real session frames served from a cell the prefetcher "
              "warmed.", sess_pf.get("hits", 0))
  reg.counter(p + "session_prefetch_suppressed_total",
              "Prefetch rounds skipped because the brownout ladder sat at "
              "L3+ (predictor muted at the source).",
              sess_pf.get("suppressed", 0))
  cache = stats.get("cache") or {}
  reg.counter(p + "cache_hits_total", "Scene-cache hits.",
              cache.get("hits", 0))
  reg.counter(p + "cache_misses_total", "Scene-cache misses (bakes).",
              cache.get("misses", 0))
  reg.counter(p + "cache_evictions_total", "Scene-cache LRU evictions.",
              cache.get("evictions", 0))
  reg.gauge(p + "cache_bytes", "Bytes of baked scenes resident.",
            cache.get("bytes", 0))
  reg.gauge(p + "cache_scenes", "Baked scenes resident.",
            cache.get("scenes", 0))
  breaker = stats.get("breaker")
  if breaker is not None:
    reg.enum(p + "breaker_state",
             "Circuit breaker state (one-hot).",
             ("closed", "open", "half_open"), breaker.get("state", ""))
    reg.gauge(p + "breaker_consecutive_failures",
              "Consecutive primary failures counted by the breaker.",
              breaker.get("consecutive_failures", 0))
  return reg


def render_serve_metrics(stats: dict,
                         latency_hist: dict | None = None) -> str:
  """The ``/metrics`` response body for one stats snapshot."""
  return serve_registry(stats, latency_hist).render()


class ExpositionCache:
  """Memoize a rendered exposition string for a short TTL.

  ``/metrics`` renders a full snapshot per scrape — cheap for one
  Prometheus at 15 s intervals, not for an aggregated cluster endpoint
  that fans out to every backend per scrape (ROADMAP obs follow-on). A
  ~250 ms TTL bounds staleness well below any real scrape interval while
  collapsing scrape storms to one render per window.

  The render runs under the lock, so concurrent scrapes inside one
  window cost exactly one render (the rest return the cached string).
  ``ttl_s <= 0`` disables caching entirely. The clock is injectable —
  the serve/-wide rule (tests pin freshness/staleness with fake clocks).
  """

  def __init__(self, render_fn, ttl_s: float = 0.25, clock=time.monotonic):
    self._render_fn = render_fn
    self.ttl_s = float(ttl_s)
    self._clock = clock
    self._lock = threading.Lock()
    self._text: str | None = None
    self._rendered_at = 0.0
    self.renders = 0
    self.cache_hits = 0

  def get(self) -> str:
    with self._lock:
      now = self._clock()
      if (self.ttl_s > 0 and self._text is not None
          and now - self._rendered_at < self.ttl_s):
        self.cache_hits += 1
        return self._text
      text = self._render_fn()
      self.renders += 1
      self._text = text
      self._rendered_at = now
      return text

  def invalidate(self) -> None:
    """Drop the cached string (the next ``get`` re-renders)."""
    with self._lock:
      self._text = None


def aggregate_metrics_texts(texts, extra: "Registry | None" = None,
                            drop=frozenset(),
                            collect: dict | None = None) -> str:
  """Sum several Prometheus expositions into one (the cluster /metrics).

  Every sample with the same ``(family, sample name, labels)`` key is
  summed across inputs — the right aggregation for counters and
  histograms, and for the gauges this stack exports (queue depths and
  cache bytes add; the breaker one-hot becomes "backends per state";
  ``uptime_seconds`` becomes total backend-seconds). Families keep
  first-seen order and HELP/TYPE text; ``extra`` (e.g. the router's own
  registry) is appended verbatim after the aggregated families.

  ``drop`` names families to OMIT from the aggregate: ratio/config
  gauges (SLO targets, attainment ratios, burn rates) are meaningless
  summed — 3 backends' 0.99 target would read 2.97, and one idle
  backend's NaN attainment would poison the whole fleet's sample. Those
  stay per-backend surfaces (``/stats``'s fan-out carries them); the
  summable slices (window request/bad counts, alert-firing one-hots,
  edge counters) still aggregate.

  Dead backends simply contribute nothing — aggregated counters dip when
  a backend is lost, which is itself the signal (the router's
  ``mpi_cluster_backend_up`` gauge says which one).

  ``collect``, when given, is filled with the aggregated families
  (``{family: {"samples": {key: value}, ...}}``) so a caller that needs
  the parsed form (the router's pooled-quantile computation) does not
  re-parse the multi-thousand-line output it just produced.
  """
  order: list[str] = []
  fams: dict[str, dict] = {}
  for text in texts:
    for name, fam in parse_metrics_text(text).items():
      if name in drop:
        continue
      agg = fams.get(name)
      if agg is None:
        agg = fams[name] = {"type": fam["type"], "help": fam["help"],
                            "samples": {}, "exemplars": {}, "order": []}
        order.append(name)
      for key, value in fam["samples"].items():
        if key not in agg["samples"]:
          agg["samples"][key] = 0.0
          agg["order"].append(key)
        agg["samples"][key] += value
      for key, exemplar in fam.get("exemplars", {}).items():
        # Exemplars survive the merge: counts add, but an exemplar is one
        # concrete observation — keep the largest across the pool (the
        # tail an operator chasing a quantile alert wants to see).
        mine = agg["exemplars"].get(key)
        if mine is None or exemplar[1] >= mine[1]:
          agg["exemplars"][key] = exemplar
  if collect is not None:
    collect.update(fams)
  lines = []
  for name in order:
    fam = fams[name]
    if fam["help"]:
      lines.append(f"# HELP {name} {fam['help']}")
    if fam["type"]:
      lines.append(f"# TYPE {name} {fam['type']}")
    for sample_name, labels in fam["order"]:
      label_str = ""
      if labels:
        inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                         for k, v in labels)
        label_str = "{" + inner + "}"
      line = (f"{sample_name}{label_str} "
              f"{format_value(fam['samples'][(sample_name, labels)])}")
      exemplar = fam["exemplars"].get((sample_name, labels))
      if exemplar is not None:
        line += (f' # {{trace_id="{_escape_label(str(exemplar[0]))}"}} '
                 f"{format_value(exemplar[1])}")
      lines.append(line)
  out = "\n".join(lines) + ("\n" if lines else "")
  if extra is not None:
    out += extra.render()
  return out


def strip_exemplars(text: str) -> str:
  """The exposition without exemplar suffixes.

  Exemplars (`` # {...} v``) are OpenMetrics syntax; the classic
  ``text/plain; version=0.0.4`` format allows only a timestamp after the
  value, and a vanilla Prometheus scrape that meets one fails the ENTIRE
  scrape. The HTTP layer serves this stripped form by default and the
  exemplar-ful form at ``?exemplars=1`` (which the cluster router's
  scrape uses, so exemplars still survive the pool merge).
  """
  if " # " not in text:
    return text
  return "\n".join(
      line if line.startswith("#") else line.partition(" # ")[0]
      for line in text.splitlines()) + ("\n" if text.endswith("\n") else "")


def parse_metrics_text(text: str) -> dict:
  """Minimal exposition-format parser (the test-side inverse).

  Returns ``{family: {"type": str, "help": str, "samples":
  {(sample_name, (("label", "value"), ...)): float}, "exemplars":
  {same key: (trace_id, observed_value)}}}``. Handles exactly what
  ``Registry.render`` emits (OpenMetrics-style `` # {...} v`` exemplars
  included; no timestamps, no escaped-quote labels with commas inside).
  """
  out: dict = {}

  def family(name: str) -> dict:
    return out.setdefault(name, {"type": None, "help": None,
                                 "samples": {}, "exemplars": {}})

  for line in text.splitlines():
    line = line.strip()
    if not line:
      continue
    if line.startswith("# HELP "):
      _, _, rest = line.partition("# HELP ")
      name, _, help_text = rest.partition(" ")
      family(name)["help"] = help_text
    elif line.startswith("# TYPE "):
      _, _, rest = line.partition("# TYPE ")
      name, _, mtype = rest.partition(" ")
      family(name)["type"] = mtype
    elif line.startswith("#"):
      continue
    else:
      exemplar = None
      if " # " in line:
        line, _, exemplar_part = line.partition(" # ")
        ex_labels, _, ex_value = exemplar_part.rpartition(" ")
        tid = ex_labels.partition('trace_id="')[2].partition('"')[0]
        try:
          exemplar = (tid, float(ex_value))
        except ValueError:
          exemplar = None
      name_part, _, value_str = line.rpartition(" ")
      labels: tuple = ()
      if "{" in name_part:
        sample_name, _, label_part = name_part.partition("{")
        label_part = label_part.rstrip("}")
        pairs = []
        for item in filter(None, label_part.split(",")):
          k, _, v = item.partition("=")
          pairs.append((k, v.strip('"')))
        labels = tuple(sorted(pairs))
      else:
        sample_name = name_part
      base = sample_name
      for suffix in ("_bucket", "_zero", "_sum", "_count"):
        if base.endswith(suffix) and base[:-len(suffix)] in out:
          base = base[:-len(suffix)]
          break
      value = float(value_str) if value_str != "+Inf" else math.inf
      fam = family(base)
      fam["samples"][(sample_name, labels)] = value
      if exemplar is not None:
        fam["exemplars"][(sample_name, labels)] = exemplar
  return out
