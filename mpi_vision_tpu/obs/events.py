"""Bounded structured lifecycle event log.

Metrics say *how much*; traces say *where one request went*; neither
answers "what happened to this fleet at 14:32?". ``EventLog`` is the
third leg: a lock-guarded, bounded ring of structured lifecycle events —
breaker transitions, failovers, scene swaps, checkpoint save / restore /
quarantine, NaN rollbacks, preemptions, watchdog trips, SLO alert
fire/clear — each a plain JSON-ready dict with a monotone sequence
number and a wall-clock timestamp.

Finished events go two places: the bounded ring (served at
``/debug/events``; oldest events drop when the ring is full, counted in
``dropped``) and an optional ``sink`` callable receiving one JSON line
per event (``serve --event-log FILE`` appends them to a file). A dying
sink costs a counter, never the emitting thread — the event log rides
hot paths (breaker transitions fire inside the dispatch loop) and must
never be able to fail them.

Clocks are injectable (the serve/-wide rule, pinned by
``tests/serve/test_clock_lint.py``): event timestamps use wall time by
default because events are cross-process artifacts (a router's failover
and a backend's breaker-open must be orderable side by side), unlike the
monotonic in-process spans.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import Counter, deque


def file_sink(path: str, max_bytes: int | None = None, keep: int = 3):
  """A sink appending one line per event to ``path`` (line-buffered).

  Opened once, append mode — a restarted process extends the log rather
  than truncating the fleet's history.

  Retention (``serve --event-log-max-bytes``, ROADMAP SLO follow-on):
  with ``max_bytes`` set, a write that pushes the file past it rotates
  ``path -> path.1 -> ... -> path.keep`` (oldest dropped), so a
  long-running fleet's JSONL log is bounded at roughly
  ``(keep + 1) * max_bytes``. A failed rotation costs a counter
  (``sink.rotate_errors``) and the sink keeps appending to the current
  file — retention must never be able to kill the event stream it
  retains.
  """
  if max_bytes is not None and max_bytes <= 0:
    raise ValueError(f"max_bytes must be positive, got {max_bytes}")
  if keep < 1:
    raise ValueError(f"keep must be >= 1, got {keep}")
  # The sink owns its own lock: EventLog.emit deliberately calls sinks
  # OUTSIDE its lock (a slow write must not serialize emitters against
  # the ring), so concurrent emitters land here in parallel — and a
  # rotation closing the file under another thread's write would lose
  # that thread's event. The pre-rotation sink was safe by accident
  # (one fh.write is atomic under CPython); rotation makes the
  # write-then-maybe-swap a real critical section.
  lock = threading.Lock()
  state = {"fh": open(path, "a", buffering=1)}
  state["size"] = state["fh"].tell()

  def _rotate_locked() -> None:
    try:
      state["fh"].close()
      # The oldest slot is about to be overwritten: that segment's
      # events leave local disk forever UNLESS a shipper (obs/ship.py)
      # already delivered-and-deleted it. Counting the drop here is
      # what closes the /debug/events retention blind spot — the
      # snapshot can now say how many segments rotated away unshipped.
      if os.path.exists(f"{path}.{keep}"):
        sink.segments_dropped += 1
      for i in range(keep - 1, 0, -1):
        rotated = f"{path}.{i}"
        if os.path.exists(rotated):
          os.replace(rotated, f"{path}.{i + 1}")
      os.replace(path, f"{path}.1")
      sink.rotations += 1
    except OSError:
      sink.rotate_errors += 1
    finally:
      # Reopen whatever is at ``path`` now: the fresh file after a clean
      # rotation, or the over-size original after a failed one — either
      # way the stream keeps flowing.
      state["fh"] = open(path, "a", buffering=1)
      state["size"] = state["fh"].tell()

  def sink(line: str) -> None:
    with lock:
      state["fh"].write(line + "\n")
      state["size"] += len(line) + 1
      if max_bytes is not None and state["size"] >= max_bytes:
        _rotate_locked()

  def close() -> None:
    with lock:
      state["fh"].close()

  sink.rotations = 0
  sink.rotate_errors = 0
  sink.segments_dropped = 0
  sink.close = close
  return sink


class EventLog:
  """Bounded ring + optional line sink for lifecycle events.

  Args:
    capacity: events retained for ``/debug/events`` (oldest dropped).
    clock: wall-clock source for the ``ts_unix_s`` field (injectable).
    sink: optional ``str -> None`` receiving one JSON line per event.
  """

  def __init__(self, capacity: int = 512, clock=time.time, sink=None):
    if capacity < 1:
      raise ValueError(f"capacity must be >= 1, got {capacity}")
    self._clock = clock
    self.sink = sink
    self._lock = threading.Lock()
    self._ring: deque = deque(maxlen=capacity)
    self._by_kind: Counter = Counter()
    self._seq = 0
    self.emitted = 0
    self.dropped = 0
    self.sink_errors = 0

  def emit(self, kind: str, **fields) -> dict:
    """Record one event; returns the stored record.

    ``fields`` must be JSON-serializable (they ride ``/debug/events``
    and the line sink verbatim). Never raises on a failing sink.
    """
    with self._lock:
      self._seq += 1
      record = {"seq": self._seq, "ts_unix_s": round(self._clock(), 6),
                "kind": str(kind), **fields}
      if len(self._ring) == self._ring.maxlen:
        self.dropped += 1
      self._ring.append(record)
      self._by_kind[str(kind)] += 1
      self.emitted += 1
      sink = self.sink
    if sink is not None:
      # Outside the lock: a slow file write must not serialize emitters,
      # and a dying sink must cost a counter, not the emitting thread.
      try:
        sink(json.dumps(record))
      except Exception:  # noqa: BLE001 - sink failure is not the emitter's
        with self._lock:
          self.sink_errors += 1
    return record

  def count(self, kind: str) -> int:
    """Lifetime count of events of ``kind`` (ring eviction included)."""
    with self._lock:
      return self._by_kind.get(str(kind), 0)

  def snapshot(self, recent: int = 128, kind: str | None = None) -> dict:
    """The ``/debug/events`` payload: counters + the most recent events
    (optionally filtered to one ``kind``).

    With a rotating file sink attached, a ``retention`` block accounts
    for the JSONL segments the ring endpoint can no longer see: how many
    rotations happened and how many segments rotated off local disk
    entirely (``segments_dropped`` stays 0 while a shipper keeps
    delivering-and-deleting them first).
    """
    with self._lock:
      events = list(self._ring)
      if kind is not None:
        events = [e for e in events if e["kind"] == kind]
      out = {
          "emitted": self.emitted,
          "dropped": self.dropped,
          "sink_errors": self.sink_errors,
          "capacity": self._ring.maxlen,
          "by_kind": dict(sorted(self._by_kind.items())),
          "events": events[-recent:] if recent > 0 else [],
      }
      sink = self.sink
    if sink is not None and hasattr(sink, "rotations"):
      out["retention"] = {
          "rotations": sink.rotations,
          "rotate_errors": sink.rotate_errors,
          "segments_dropped": getattr(sink, "segments_dropped", 0),
      }
    return out


class _NullEventLog:
  """The disabled-events singleton: ``emit`` is a no-op (no allocation,
  no lock) so library code can emit unconditionally."""

  __slots__ = ()
  emitted = 0
  dropped = 0
  sink_errors = 0

  def emit(self, kind, **fields):  # noqa: ARG002 - mirror EventLog
    return None

  def count(self, kind):  # noqa: ARG002
    return 0

  def snapshot(self, recent: int = 128, kind=None):  # noqa: ARG002
    return {"emitted": 0, "dropped": 0, "sink_errors": 0, "capacity": 0,
            "by_kind": {}, "events": []}


NULL_EVENTS = _NullEventLog()
