"""Resource-attribution ledger: who is eating the fleet, by cell.

The stack can say *that* the device is busy (``phase_seconds``), *that*
the queue is deep, and *that* an SLO is burning — but not **who** is
responsible. This module answers that: every completed request
accumulates its device phase-seconds (its share of the flight's
``last_timings`` split), queue-wait, response bytes, and cache/edge/
tile contribution into a bounded ``(scene_id x request-class x
brownout-level)`` cell. The two ROADMAP follow-ons that need the answer
— per-scene brownout ladders and the evidence-driven autoscaler — read
it from here; the incident recorder (``obs/incident.py``) freezes the
top cells into every bundle.

Bounds follow the repo's per-scene idiom (``serve/metrics.py``,
``obs/slo.py``): at most ``scene_cap`` distinct scenes, the rest folded
into ``_other`` so scene-id cardinality can never balloon the ledger.
The class dimension is the three brownout classes plus ``unlabeled``
(requests that entered below the front door), the level dimension is
the ladder's 0..4 — the whole table is a few hundred cells at worst.

**Conservation invariant**: the ledger is fed from inside
``ServeMetrics.record_request`` (requests) and from the scheduler's
flight retirement (device shares summing to exactly what
``record_batch`` added), so summed cells reconcile with the
pre-existing ``requests`` / ``phase_seconds`` totals — ``conservation``
surfaces the reconciliation, and a tier-1 pin holds it both in-process
and through the router's pool merge. Every ``mpi_serve_attrib_*``
family is **additive** (plain counters), so the cluster router's
summed ``/metrics`` aggregates a fleet-wide ledger with zero router
code — by design these names must never enter a ``NON_ADDITIVE``
drop list.

Recording is lock-cheap: one small-dict update under one lock, no
clock reads at all (latency/queue-wait are measured by the callers on
their injected clocks and handed in).
"""

from __future__ import annotations

import dataclasses
import math
import threading

from mpi_vision_tpu.obs import prom

PREFIX = "mpi_serve_attrib_"

# Scene-dimension bound, same value and same ``_other`` fold as the
# per-scene tables in serve/metrics.py and obs/slo.py.
SCENE_CAP = 32
OVERFLOW_SCENE = "_other"

# Requests that never passed the brownout front door (raw scheduler
# submissions, internal warmups) — distinct from "interactive", which is
# what an *unlabelled HTTP request* normalizes to.
UNLABELED_CLASS = "unlabeled"

PHASES = ("h2d", "compute", "readback")


@dataclasses.dataclass(frozen=True)
class AttribConfig:
  """Ledger knobs (the ``serve`` CLI ``--attrib-*`` flags map 1:1)."""

  scene_cap: int = SCENE_CAP

  def __post_init__(self):
    if self.scene_cap < 1:
      raise ValueError(f"scene_cap must be >= 1, got {self.scene_cap}")


def _new_cell() -> dict:
  return {"requests": 0,
          "device_s": dict.fromkeys(PHASES, 0.0),
          "queue_wait_s": 0.0,
          "bytes_out": 0,
          "edge_hits": 0,
          "edge_warps": 0,
          "tiles_touched": 0}


def _merge_cell(into: dict, cell: dict) -> None:
  """Accumulate one cell into another (same schema) — shared by the
  ledger's totals and the router's fleet merge."""
  for key, value in cell.items():
    if key == "device_s":
      for phase, secs in value.items():
        into["device_s"][phase] = into["device_s"].get(phase, 0.0) + secs
    elif isinstance(value, (int, float)):
      into[key] = into.get(key, 0) + value


def cell_device_seconds(cell: dict) -> float:
  """A cell's total device time across phases (the ranking key)."""
  return sum((cell.get("device_s") or {}).values())


class AttribLedger:
  """Bounded per-``(scene, class, level)`` resource accounting.

  All recording methods are O(1) dict updates under one lock and are
  safe from the request path. ``reset()`` zeroes everything — it rides
  ``ServeMetrics.reset()`` so bench warmup discards ledger history
  together with the counters it must reconcile against.
  """

  def __init__(self, config: AttribConfig | None = None):
    self.config = config if config is not None else AttribConfig()
    self._lock = threading.Lock()
    self._cells: dict[tuple, dict] = {}
    self._scenes: set[str] = set()
    self.overflow_requests = 0

  def _key(self, scene_id, request_class, level) -> tuple:
    scene = str(scene_id) if scene_id is not None else "_unknown"
    if scene not in self._scenes:
      if len(self._scenes) >= self.config.scene_cap:
        scene = OVERFLOW_SCENE
      else:
        self._scenes.add(scene)
    cls = request_class if request_class else UNLABELED_CLASS
    return (scene, str(cls), int(level))

  # -- recording (request path) --------------------------------------------

  def record(self, scene_id, request_class=None, level: int = 0, *,
             device: dict | None = None, queue_wait_s: float = 0.0,
             edge: str | None = None) -> None:
    """Account one completed request into its cell.

    ``device`` is the request's share of its flight's phase split
    (``{"h2d": s, "compute": s, "readback": s}``; None for edge
    hits/warps, which never touched the device). ``edge`` is ``"hit"``
    or ``"warp"`` when the edge cache served the bytes.
    """
    with self._lock:
      key = self._key(scene_id, request_class, level)
      cell = self._cells.get(key)
      if cell is None:
        cell = self._cells[key] = _new_cell()
      if key[0] == OVERFLOW_SCENE:
        self.overflow_requests += 1
      cell["requests"] += 1
      if device:
        dev = cell["device_s"]
        for phase in PHASES:
          dev[phase] += device.get(phase, 0.0)
      if queue_wait_s > 0.0:
        cell["queue_wait_s"] += queue_wait_s
      if edge == "hit":
        cell["edge_hits"] += 1
      elif edge == "warp":
        cell["edge_warps"] += 1

  def record_bytes(self, scene_id, request_class=None, level: int = 0,
                   nbytes: int = 0) -> None:
    """Account response payload bytes (recorded after the render, so it
    is a separate O(1) touch of the same cell)."""
    if nbytes <= 0:
      return
    with self._lock:
      key = self._key(scene_id, request_class, level)
      cell = self._cells.get(key)
      if cell is None:
        cell = self._cells[key] = _new_cell()
      cell["bytes_out"] += int(nbytes)

  def record_tiles(self, scene_id, request_class=None, level: int = 0,
                   tiles: int = 0) -> None:
    """Account the source tiles a request's frustum could sample (tiled
    scenes only) — the per-request tile-tier demand signal."""
    if tiles <= 0:
      return
    with self._lock:
      key = self._key(scene_id, request_class, level)
      cell = self._cells.get(key)
      if cell is None:
        cell = self._cells[key] = _new_cell()
      cell["tiles_touched"] += int(tiles)

  def reset(self) -> None:
    with self._lock:
      self._cells.clear()
      self._scenes.clear()
      self.overflow_requests = 0

  # -- introspection -------------------------------------------------------

  def _totals_locked(self) -> dict:
    totals = _new_cell()
    for cell in self._cells.values():
      _merge_cell(totals, cell)
    return totals

  def snapshot(self, top: int | None = None,
               reference: dict | None = None) -> dict:
    """The ``/debug/attrib`` payload / ``/stats`` ``attrib`` block.

    Cells are sorted hottest-first by total device-seconds (requests
    break ties); ``top`` truncates (``cells_total`` still reports the
    full population). ``reference`` (``{"requests": n,
    "device_phase_seconds": {...}}`` from the metrics snapshot) adds
    the conservation reconciliation.
    """
    with self._lock:
      cells = [{"scene": key[0], "class": key[1], "level": key[2],
                **{k: (dict(v) if isinstance(v, dict) else v)
                   for k, v in cell.items()}}
               for key, cell in self._cells.items()]
      totals = self._totals_locked()
      overflow = self.overflow_requests
      scenes = len(self._scenes)
    cells.sort(key=lambda c: (cell_device_seconds(c), c["requests"]),
               reverse=True)
    out = {
        "cells": cells[:top] if top is not None else cells,
        "cells_total": len(cells),
        "totals": totals,
        "scenes": scenes,
        "scene_cap": self.config.scene_cap,
        "overflow_requests": overflow,
    }
    if reference is not None:
      out["conservation"] = self.conservation(
          reference.get("requests", 0),
          reference.get("device_phase_seconds") or {})
    return out

  def top_cells(self, k: int) -> list[dict]:
    """The ``k`` hottest cells by device-seconds (the incident bundle's
    "who was eating the device when it fired" slice)."""
    return self.snapshot(top=max(int(k), 0))["cells"]

  def conservation(self, requests: int, phase_seconds: dict) -> dict:
    """Reconcile cell sums against the metrics layer's own totals.

    Request counts must match exactly (both sides increment on the same
    ``record_request`` call); device seconds reconcile within float
    tolerance (each flight's phase split is divided across its batch
    and re-summed here).
    """
    with self._lock:
      totals = self._totals_locked()
    request_delta = int(requests) - totals["requests"]
    phase_ok = all(
        math.isclose(totals["device_s"][phase],
                     phase_seconds.get(phase, 0.0),
                     rel_tol=1e-6, abs_tol=1e-6)
        for phase in PHASES)
    return {
        "ok": request_delta == 0 and phase_ok,
        "ledger_requests": totals["requests"],
        "reference_requests": int(requests),
        "request_delta": request_delta,
        "ledger_device_s": dict(totals["device_s"]),
        "reference_device_s": {phase: phase_seconds.get(phase, 0.0)
                               for phase in PHASES},
    }


def merge_snapshots(snapshots) -> dict:
  """Merge several backends' ``attrib`` blocks into one fleet ledger
  (the cluster router's ``/stats`` summary). Cells with the same
  ``(scene, class, level)`` coordinates sum field-wise — the same
  aggregation the pool-summed ``/metrics`` families get for free."""
  fleet: dict[tuple, dict] = {}
  totals = _new_cell()
  overflow = 0
  backends = 0
  for snap in snapshots:
    if not snap:
      continue
    backends += 1
    overflow += snap.get("overflow_requests", 0)
    _merge_cell(totals, snap.get("totals") or {})
    for cell in snap.get("cells") or []:
      key = (cell.get("scene"), cell.get("class"), cell.get("level"))
      into = fleet.get(key)
      if into is None:
        into = fleet[key] = _new_cell()
      _merge_cell(into, cell)
  cells = [{"scene": key[0], "class": key[1], "level": key[2], **cell}
           for key, cell in fleet.items()]
  cells.sort(key=lambda c: (cell_device_seconds(c), c["requests"]),
             reverse=True)
  return {"cells": cells, "cells_total": len(cells), "totals": totals,
          "overflow_requests": overflow, "backends": backends}


def registry(snapshot: dict | None) -> prom.Registry:
  """The ``mpi_serve_attrib_*`` families (family headers always exposed,
  samples per live cell). Every family is a plain counter/additive
  gauge, so the router's pool merge sums a correct fleet ledger —
  never add one of these names to a NON_ADDITIVE drop set."""
  snap = snapshot or {}
  reg = prom.Registry()
  p = PREFIX
  req_m = reg.counter(
      p + "requests_total",
      "Completed requests per attribution cell, labels scene / class / "
      "level. Cell sums reconcile with mpi_serve_requests_total "
      "(conservation invariant).")
  dev_m = reg.counter(
      p + "device_seconds_total",
      "Device time attributed per cell, labels scene / class / level / "
      "phase (h2d | compute | readback). Cell sums reconcile with "
      "mpi_serve_device_phase_seconds_total.")
  wait_m = reg.counter(
      p + "queue_wait_seconds_total",
      "Scheduler queue wait attributed per cell (enqueue to dispatch).")
  bytes_m = reg.counter(
      p + "bytes_out_total",
      "Response payload bytes attributed per cell.")
  edge_m = reg.counter(
      p + "edge_serves_total",
      "Requests a cell answered from the edge frame cache instead of "
      "the device, label kind=hit | warp.")
  tiles_m = reg.counter(
      p + "tiles_touched_total",
      "Source tiles the cell's request frusta could sample (tiled "
      "scenes).")
  for cell in snap.get("cells") or []:
    labels = {"scene": cell["scene"], "class": cell["class"],
              "level": str(cell["level"])}
    req_m.sample(cell["requests"], labels)
    for phase in PHASES:
      secs = (cell.get("device_s") or {}).get(phase, 0.0)
      dev_m.sample(secs, {**labels, "phase": phase})
    wait_m.sample(cell.get("queue_wait_s", 0.0), labels)
    bytes_m.sample(cell.get("bytes_out", 0), labels)
    edge_m.sample(cell.get("edge_hits", 0), {**labels, "kind": "hit"})
    edge_m.sample(cell.get("edge_warps", 0), {**labels, "kind": "warp"})
    tiles_m.sample(cell.get("tiles_touched", 0), labels)
  reg.counter(p + "overflow_requests_total",
              "Requests folded into the _other scene past the scene "
              "cap.", snap.get("overflow_requests", 0))
  reg.gauge(p + "cells", "Attribution cells resident in the ledger.",
            snap.get("cells_total", 0))
  return reg
