"""Request tracing: trace ids, span trees, ring buffer, JSON log lines.

One ``Trace`` per request; spans are flat records with parent handles, so
the tree covers phases that do not nest lexically (queue-wait starts on
the submitter thread and ends on the dispatcher thread). Times come from
the tracer's injectable clock — the same clock the scheduler uses, so
span edges and request deadlines share one timebase.

Finished traces go three places: an optional ``emit`` callable receives
one structured JSON line per trace (ship to a log pipeline), a bounded
ring buffer holds the most recent N for ``/debug/traces``, and a
slowest-N exemplar set retains the worst offenders past ring eviction —
the trace you want during an incident is precisely the one a FIFO ring
would have dropped first.

Disabled tracing is the ``NULL_TRACE``/``NULL_TRACER`` singletons: every
method is an empty body on a shared object — no allocation, no lock, no
clock read — so the hot path's cost with tracing off is a handful of
no-op method calls.

``SpanRecorder`` solves the batching fan-out: a micro-batch shares one
dispatch (one set of attempt/bake/h2d/compute/readback timings) across
many requests' traces, so the dispatcher records shared spans once and
``replay``\\ s them onto every batch member's trace.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from collections import deque


def new_trace_id() -> str:
  """A fresh 16-hex-char trace id — the one id format repo-wide (the
  HTTP layer mints these for untraceable requests too, so the header
  format never diverges from recorded traces)."""
  return uuid.uuid4().hex[:16]


class _NullTrace:
  """The disabled-tracing singleton: every operation is a no-op.

  ``trace_id`` is the empty string — callers that must hand out an id
  anyway (the HTTP layer's ``X-Trace-Id``) generate their own on top.
  """

  trace_id = ""
  __slots__ = ()

  def start_span(self, name, parent=0, **attrs) -> int:  # noqa: ARG002
    return 0

  def end_span(self, handle, error=None, **attrs) -> None:  # noqa: ARG002
    pass

  def add_span(self, name, t0, t1, parent=0, error=None,  # noqa: ARG002
               **attrs) -> int:
    return 0

  def finish(self, error=None) -> None:  # noqa: ARG002
    pass


NULL_TRACE = _NullTrace()


class Trace:
  """One request's span tree. Span handles are 1-based ints (0 = root).

  Methods are lock-guarded: a trace is touched by the submitter thread
  (root + queue-wait), the dispatcher thread (everything else), and on
  error paths both may race to ``finish`` — which is idempotent, first
  caller wins.
  """

  __slots__ = ("trace_id", "name", "attrs", "t_start", "t_end", "error",
               "_spans", "_tracer", "_lock", "_finished")

  def __init__(self, tracer: "Tracer", name: str, attrs: dict,
               trace_id: str | None = None):
    self.trace_id = trace_id or new_trace_id()
    self.name = name
    self.attrs = attrs
    self._tracer = tracer
    self._lock = threading.Lock()
    self._spans: list[dict] = []
    self.t_start = tracer._clock()
    self.t_end: float | None = None
    self.error: str | None = None
    self._finished = False

  def start_span(self, name: str, parent: int = 0, **attrs) -> int:
    """Open a span; returns its handle (close with ``end_span``)."""
    with self._lock:
      self._spans.append({"name": name, "parent": parent,
                          "t0": self._tracer._clock(), "t1": None,
                          "error": None, "attrs": attrs})
      return len(self._spans)

  def end_span(self, handle: int, error: str | None = None,
               **attrs) -> None:
    if handle <= 0:
      return
    with self._lock:
      span = self._spans[handle - 1]
      if span["t1"] is None:
        span["t1"] = self._tracer._clock()
      if error is not None:
        span["error"] = error
      if attrs:
        span["attrs"].update(attrs)

  def add_span(self, name: str, t0: float, t1: float, parent: int = 0,
               error: str | None = None, **attrs) -> int:
    """Record an already-timed span (shared batch timings, sub-phases)."""
    with self._lock:
      self._spans.append({"name": name, "parent": parent, "t0": t0,
                          "t1": t1, "error": error, "attrs": attrs})
      return len(self._spans)

  def finish(self, error: str | None = None) -> None:
    """Close the trace: record duration, emit, ring. Idempotent —
    the dispatcher and a timed-out caller may both reach here."""
    with self._lock:
      if self._finished:
        return
      self._finished = True
      self.t_end = self._tracer._clock()
      self.error = error
    self._tracer._record_finished(self)

  @property
  def duration_s(self) -> float:
    end = self.t_end if self.t_end is not None else self._tracer._clock()
    return end - self.t_start

  def to_dict(self) -> dict:
    """JSON-ready form; span times are ms relative to the trace start
    (absolute monotonic timestamps mean nothing outside the process)."""
    with self._lock:
      t0 = self.t_start
      end = self.t_end if self.t_end is not None else t0
      out = {
          "trace_id": self.trace_id,
          "name": self.name,
          "duration_ms": round((end - t0) * 1e3, 3),
          "error": self.error,
          "spans": [],
      }
      if self.attrs:
        out["attrs"] = dict(self.attrs)
      for i, s in enumerate(self._spans):
        s1 = s["t1"] if s["t1"] is not None else end
        span = {
            "id": i + 1,
            "parent": s["parent"],
            "name": s["name"],
            "t0_ms": round((s["t0"] - t0) * 1e3, 3),
            "duration_ms": round((s1 - s["t0"]) * 1e3, 3),
        }
        if s["error"] is not None:
          span["error"] = s["error"]
        if s["attrs"]:
          span["attrs"] = {k: v for k, v in s["attrs"].items()}
        out["spans"].append(span)
      return out


class Tracer:
  """Trace factory + finished-trace sinks (emit / ring / slowest-N).

  Args:
    enabled: False routes ``start_trace`` to the shared ``NULL_TRACE``
      singleton — the zero-overhead off switch.
    clock: injectable monotonic clock; share it with the scheduler so
      spans and deadlines agree.
    emit: optional callable receiving one JSON line per finished trace.
    ring: finished traces retained for ``/debug/traces`` (FIFO).
    slow_keep: slowest-N exemplars retained past ring eviction.
  """

  def __init__(self, enabled: bool = True, clock=time.monotonic,
               emit=None, ring: int = 256, slow_keep: int = 16):
    if ring < 1:
      raise ValueError(f"ring must be >= 1, got {ring}")
    if slow_keep < 0:
      raise ValueError(f"slow_keep must be >= 0, got {slow_keep}")
    self.enabled = bool(enabled)
    self.emit = emit
    self._clock = clock
    self._lock = threading.Lock()
    self._ring: deque = deque(maxlen=ring)
    self._slow_keep = slow_keep
    self._slowest: list[tuple[float, int, dict]] = []  # sorted ascending
    self._seq = 0
    self.started = 0
    self.finished = 0
    self.emit_errors = 0

  def start_trace(self, name: str, trace_id: str | None = None, **attrs):
    """A new ``Trace`` — or ``NULL_TRACE`` when tracing is disabled.

    ``trace_id`` overrides the generated id (the HTTP layer passes an
    inbound W3C ``traceparent`` trace-id through so a fronting proxy
    can stitch its trace to the recorded one)."""
    if not self.enabled:
      return NULL_TRACE
    with self._lock:
      self.started += 1
    return Trace(self, name, attrs, trace_id=trace_id)

  def _record_finished(self, trace: Trace) -> None:
    record = trace.to_dict()
    line = None
    if self.emit is not None:
      line = json.dumps({"event": "trace", **record})
    with self._lock:
      self.finished += 1
      self._seq += 1
      self._ring.append(record)
      if self._slow_keep > 0:
        dur = record["duration_ms"]
        if (len(self._slowest) < self._slow_keep
            or dur > self._slowest[0][0]):
          self._slowest.append((dur, self._seq, record))
          self._slowest.sort(key=lambda x: (x[0], x[1]))
          self._slowest = self._slowest[-self._slow_keep:]
    if line is not None:
      # finish() runs on the scheduler's only dispatcher thread: a dying
      # emit sink (closed stderr pipe, full log socket) must cost dropped
      # trace lines, never the dispatcher. Ring/exemplars stay intact.
      try:
        self.emit(line)
      except Exception:  # noqa: BLE001 - sink failure is not our caller's
        with self._lock:
          self.emit_errors += 1

  def snapshot(self, recent: int = 32) -> dict:
    """The ``/debug/traces`` payload: counters + recent + slowest."""
    with self._lock:
      return {
          "enabled": self.enabled,
          "started": self.started,
          "finished": self.finished,
          "emit_errors": self.emit_errors,
          "ring_size": self._ring.maxlen,
          "recent": list(self._ring)[-recent:] if recent > 0 else [],
          "slowest": [r for _, _, r in reversed(self._slowest)],
      }

  def find(self, trace_id: str) -> list[dict]:
    """Every retained finished-trace record carrying ``trace_id``.

    Searches the ring AND the slowest-N exemplars (an incident trace
    evicted from the ring is exactly the one being searched for) and
    de-duplicates records living in both. The ``/debug/traces?id=``
    endpoint serves this; the cluster router fans the same query out to
    every backend so one id yields the stitched cross-process tree.
    """
    with self._lock:
      out, seen = [], set()
      for rec in list(self._ring) + [r for _, _, r in self._slowest]:
        if rec.get("trace_id") == trace_id and id(rec) not in seen:
          seen.add(id(rec))
          out.append(rec)
      return out

  def reset(self) -> None:
    """Drop recorded traces and counters (load generators call this after
    warm-up, mirroring ``ServeMetrics.reset``)."""
    with self._lock:
      self.started = 0
      self.finished = 0
      self.emit_errors = 0
      self._ring.clear()
      self._slowest = []


NULL_TRACER = Tracer(enabled=False)


class SpanRecorder:
  """Collect shared span records once, replay onto many traces.

  The dispatcher runs ONE device dispatch for a whole micro-batch; its
  attempt/bake/h2d/compute/readback timings belong in every batch
  member's trace. Records are plain dicts with intra-recorder parent
  indices; ``replay`` re-parents them under a per-trace anchor span.

  ``begin``/``end`` maintain a parent stack so records created inside a
  group (e.g. a bake inside a retry attempt) nest under it. The stack is
  only meaningful on the group-owning (dispatcher) thread; a watchdog
  attempt thread that may outlive its group must capture
  ``current_parent()`` at entry and record with an explicit ``parent`` —
  then an abandoned attempt's late records still land under the *dead*
  attempt, not whichever group is live when they arrive. All mutation is
  lock-guarded because exactly that zombie thread can append
  concurrently with the dispatcher's next begin/record. Records appended
  after ``replay`` are dropped.
  """

  _AUTO = object()  # record(): "parent = whatever group is open now"

  def __init__(self, clock=time.monotonic):
    self._clock = clock
    self._lock = threading.Lock()
    self.records: list[dict] = []
    self._stack: list[int] = []

  def current_parent(self) -> int | None:
    """The open group's record index (capture at attempt entry)."""
    with self._lock:
      return self._stack[-1] if self._stack else None

  def record(self, name: str, t0: float, t1: float,
             error: str | None = None, parent=_AUTO, **attrs) -> int:
    with self._lock:
      if parent is SpanRecorder._AUTO:
        parent = self._stack[-1] if self._stack else None
      self.records.append({"name": name, "parent": parent, "t0": t0,
                           "t1": t1, "error": error, "attrs": attrs})
      return len(self.records) - 1

  def begin(self, name: str, **attrs) -> int:
    """Open a group: records made before ``end`` nest under it."""
    t0 = self._clock()
    with self._lock:
      parent = self._stack[-1] if self._stack else None
      self.records.append({"name": name, "parent": parent, "t0": t0,
                           "t1": None, "error": None, "attrs": attrs})
      idx = len(self.records) - 1
      self._stack.append(idx)
      return idx

  def end(self, idx: int, error: str | None = None, **attrs) -> None:
    t1 = self._clock()
    with self._lock:
      rec = self.records[idx]
      if rec["t1"] is None:
        rec["t1"] = t1
      if error is not None:
        rec["error"] = error
      if attrs:
        rec["attrs"].update(attrs)
      if self._stack and self._stack[-1] == idx:
        self._stack.pop()

  def replay(self, trace, parent: int = 0) -> None:
    """Copy every record into ``trace``, rooted under ``parent``."""
    handles: dict[int, int] = {}
    end = self._clock()
    with self._lock:
      snapshot = list(self.records)
    for i, rec in enumerate(snapshot):
      p = handles.get(rec["parent"], parent)
      t1 = rec["t1"] if rec["t1"] is not None else end
      handles[i] = trace.add_span(
          rec["name"], rec["t0"], t1, parent=p, error=rec["error"],
          **rec["attrs"])
