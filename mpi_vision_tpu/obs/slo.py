"""SLO engine: sliding-window objectives + multi-window burn-rate alerts.

The judgment layer on top of the raw telemetry (PR 3's counters say what
happened; this module says whether the fleet is *meeting objectives*).
Objectives over the serve request stream:

  * **availability** — a request is good when it completed without an
    error (errors, queue sheds, and breaker fast-fails are bad events:
    the user saw a failure either way).
  * **latency** — a *completed* request is good when its end-to-end
    latency is under ``latency_threshold_s`` (FastNeRF's 200 FPS target
    is only meaningful against exactly this kind of tracked bound).
  * **latency quantile** (``quantile`` set, e.g. 0.99 — the flight-
    recorder upgrade): "p99 render < threshold", judged from a **native
    histogram** (``obs/hist.py``) pooled over the window's time buckets
    — percentile-true, not a fixed threshold count. With ``per_scene``
    on, the same objective is additionally judged per scene over the
    bounded per-scene table, so one hot scene's tail pages before it
    drowns in the fleet average (alert names ``latency_p99:scene_007``).

Alerting follows the SRE-workbook multi-window burn-rate scheme: the
**burn rate** is ``(1 - attainment) / (1 - target)`` — 1.0 means the
error budget is being consumed exactly at the sustainable rate, 10x
means ten times too fast. An alert fires when the burn rate exceeds
``burn_threshold`` over BOTH the slow window (the problem is material)
and the fast window (the problem is happening *now*, not a stale spike
still inside the long window), and clears as soon as the fast window's
burn drops back under the threshold — recovery is visible within
``fast_window_s`` instead of lingering for the whole slow window.
Quantile alerts use the same two-window shape with the quantile itself
as the signal: fire when the windowed quantile exceeds the threshold in
both windows, clear when the fast window's quantile recovers; their
reported ``burn_rate`` is the ``quantile / threshold`` ratio.

Implementation is a ring of coarse time buckets (O(1) record, O(buckets)
snapshot, bounded memory regardless of traffic; each bucket carries a
small native histogram when the quantile objective is on), driven
entirely by an injectable clock so every rotation/alert edge is testable
with fake time (``tests/serve/test_slo.py``,
``tests/serve/test_flight_recorder.py``; clock-lint covers this file).

``SloTracker.registry()`` renders the state as ``mpi_slo_*`` Prometheus
families; ``verdict()`` turns a snapshot into the pass/fail block
``bench/serve_load.py`` embeds in its JSON so BENCH lines trend against
explicit objectives.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time

from mpi_vision_tpu.obs import hist as hist_mod
from mpi_vision_tpu.obs import prom

PREFIX = "mpi_slo_"

# Families a pool aggregator must NOT sum across backends: targets,
# ratios, thresholds, and quantiles are per-backend statements (3 x 0.99
# targets summed would read 2.97, and an idle backend's NaN attainment
# would poison the fleet sample). The cluster router drops these from
# its summed exposition; the per-backend values stay reachable through
# the /stats fan-out, and the router computes its own POOLED quantiles
# from the (exactly merged) native-histogram buckets. Everything else
# mpi_slo_* exports sums meaningfully (window counts add; alert_firing
# becomes "firing backends"; scene_alerts_firing becomes "firing scene
# alerts fleet-wide").
NON_ADDITIVE_FAMILIES = frozenset({
    PREFIX + "objective_target",
    PREFIX + "attainment_ratio",
    PREFIX + "burn_rate",
    PREFIX + "latency_threshold_seconds",
    PREFIX + "burn_threshold",
    PREFIX + "quantile",
    PREFIX + "quantile_latency_seconds",
    PREFIX + "quantile_threshold_seconds",
})

_OBJECTIVES = ("availability", "latency")

# Per-scene quantile tracking is bounded exactly like the per-scene
# latency table in serve/metrics.py: at most this many distinct scenes,
# the rest aggregated under "_other" so scene-id cardinality can never
# balloon the ring.
PER_SCENE_CAP = 32


@dataclasses.dataclass(frozen=True)
class SloConfig:
  """Objectives + alerting knobs (the ``serve`` CLI flags map 1:1).

  Defaults suit a serving demo fleet: 99% availability, 95% of requests
  under 1 s, alert at 10x budget burn confirmed over a 60 s fast / 600 s
  slow window pair. ``min_requests`` keeps a single bad request on an
  idle service from paging. ``quantile`` (``--slo-quantile``, e.g. 0.99)
  adds the histogram-quantile objective "p-quantile latency under
  ``latency_threshold_s``"; ``per_scene`` (``--slo-per-scene``)
  additionally judges it per scene.
  """

  availability_target: float = 0.99
  latency_threshold_s: float = 1.0
  latency_target: float = 0.95
  fast_window_s: float = 60.0
  slow_window_s: float = 600.0
  burn_threshold: float = 10.0
  bucket_s: float | None = None  # None: fast_window_s / 12, floored 0.25
  min_requests: int = 10
  quantile: float | None = None  # e.g. 0.99; None = no quantile objective
  per_scene: bool = False

  def __post_init__(self):
    for name in ("availability_target", "latency_target"):
      v = getattr(self, name)
      if not 0.0 < v < 1.0:
        raise ValueError(f"{name} must be in (0, 1), got {v}")
    if self.latency_threshold_s <= 0:
      raise ValueError(
          f"latency_threshold_s must be > 0, got {self.latency_threshold_s}")
    if not 0 < self.fast_window_s <= self.slow_window_s:
      raise ValueError(
          f"need 0 < fast_window_s <= slow_window_s, got "
          f"{self.fast_window_s} / {self.slow_window_s}")
    if self.burn_threshold <= 0:
      raise ValueError(
          f"burn_threshold must be > 0, got {self.burn_threshold}")
    if self.bucket_s is not None and not (
        0 < self.bucket_s <= self.fast_window_s):
      raise ValueError(
          f"bucket_s must be in (0, fast_window_s], got {self.bucket_s}")
    if self.quantile is not None and not 0.0 < self.quantile < 1.0:
      raise ValueError(
          f"quantile must be in (0, 1), got {self.quantile}")
    if self.per_scene and self.quantile is None:
      raise ValueError("per_scene objectives require quantile (the "
                       "per-scene objective IS the quantile one)")

  def resolved_bucket_s(self) -> float:
    if self.bucket_s is not None:
      return float(self.bucket_s)
    return max(self.fast_window_s / 12.0, 0.25)

  def target(self, objective: str) -> float:
    return (self.availability_target if objective == "availability"
            else self.latency_target)

  def quantile_name(self) -> str | None:
    """The quantile objective's name ("latency_p99" for 0.99)."""
    if self.quantile is None:
      return None
    return f"latency_p{self.quantile * 100:g}"


class _Alert:
  """One objective's fire/clear state machine (single-threaded under the
  tracker's lock)."""

  __slots__ = ("firing", "fired", "cleared", "since")

  def __init__(self):
    self.firing = False
    self.fired = 0
    self.cleared = 0
    self.since: float | None = None  # tracker-clock time of last fire


class _Bucket:
  """One time bucket of the sliding window (plus its native histogram
  and bounded per-scene histograms when the quantile objective is on)."""

  __slots__ = ("idx", "total", "bad", "lat_total", "lat_bad", "hist",
               "scenes")

  def __init__(self, idx: int, with_hist: bool):
    self.idx = idx
    self.total = 0
    self.bad = 0
    self.lat_total = 0
    self.lat_bad = 0
    self.hist = hist_mod.NativeHistogram() if with_hist else None
    self.scenes: dict[str, hist_mod.NativeHistogram] | None = (
        {} if with_hist else None)


def burn_rate(bad: int, total: int, target: float) -> float:
  """Error-budget consumption rate over one window (0 when idle)."""
  if total <= 0:
    return 0.0
  return (bad / total) / (1.0 - target)


def worst_exemplar(hist) -> dict | None:
  """The largest-valued trace exemplar across a histogram's buckets —
  the request an operator chasing a quantile alert wants to click
  through first (resolvable at ``/debug/traces`` while the ring holds
  it). None when no recorded latency carried a trace id."""
  if hist is None or not hist.exemplars:
    return None
  tid, value = max(hist.exemplars.values(), key=lambda pair: pair[1])
  return {"trace_id": tid, "value_ms": round(value * 1e3, 3)}


class SloTracker:
  """Sliding-window SLO accounting + burn-rate alerting over requests.

  Args:
    config: objectives + alert knobs.
    clock: injectable monotonic clock driving bucket rotation and alert
      edges (share with the serving stack's other clocks).
    on_alert: optional ``(objective, firing, details) -> None`` callback
      fired on every alert transition (the serving layer routes it into
      the event log). Per-scene quantile alerts arrive with names like
      ``latency_p99:scene_007`` and a ``scene`` detail. Exceptions are
      swallowed and counted — alerting must not be able to fail the
      request path.
  """

  def __init__(self, config: SloConfig | None = None, clock=time.monotonic,
               on_alert=None):
    self.config = config if config is not None else SloConfig()
    self._clock = clock
    self.on_alert = on_alert
    self._bucket_s = self.config.resolved_bucket_s()
    # +1: the current (partial) bucket rides along with a full slow
    # window of closed ones.
    self._ring_len = int(math.ceil(
        self.config.slow_window_s / self._bucket_s)) + 1
    self._lock = threading.Lock()
    self.alert_errors = 0
    self.reset()

  def reset(self) -> None:
    """Drop all window state and alert history (load generators call
    this after warm-up, mirroring ``ServeMetrics.reset``)."""
    with self._lock:
      self._buckets: list[_Bucket] = []
      self._alerts: dict[str, _Alert] = {
          name: _Alert() for name in _OBJECTIVES}
      qname = self.config.quantile_name()
      if qname is not None:
        self._alerts[qname] = _Alert()
      # Bounded per-scene key table (the "_other" overflow mirrors
      # serve/metrics.py's per-scene cap).
      self._scene_keys: set[str] = set()
      # Memo for the merged quantile windows: (total, bucket idx) ->
      # result. See _quantile_windows_locked.
      self._qwindows_memo: tuple | None = None
      self.total = 0
      self.bad = 0

  # -- recording -----------------------------------------------------------

  def _bucket_locked(self, now: float) -> tuple[_Bucket, bool]:
    """The current bucket, plus whether it was freshly opened."""
    idx = int(now // self._bucket_s)
    rotated = not self._buckets or self._buckets[-1].idx < idx
    if rotated:
      self._buckets.append(
          _Bucket(idx, with_hist=self.config.quantile is not None))
      floor = idx - self._ring_len + 1
      while self._buckets and self._buckets[0].idx < floor:
        self._buckets.pop(0)
    return self._buckets[-1], rotated

  def _scene_key_locked(self, scene_id: str) -> str:
    key = str(scene_id)
    if key not in self._scene_keys:
      if len(self._scene_keys) >= PER_SCENE_CAP:
        return "_other"
      self._scene_keys.add(key)
    return key

  def record(self, ok: bool, latency_s: float | None = None,
             count: int = 1, scene_id: str | None = None,
             trace_id: str | None = None, availability: bool = True) -> None:
    """Account ``count`` request outcomes.

    ``ok=False`` consumes availability budget; ``latency_s`` (completed
    requests only) additionally scores the latency objective and — with
    the quantile objective on — lands in the window's native histogram
    (``scene_id`` additionally in the bounded per-scene one).
    ``trace_id`` becomes the latency's bucket exemplar, so a quantile
    alert carries a worst-offender trace resolvable at /debug/traces.
    ``availability=False`` scores ONLY the latency objective — for
    streams whose success accounting rides separate events (the train
    queue: attempt outcomes are the availability signal; per-step
    latency samples must not dilute it with good events).
    """
    with self._lock:
      bucket, rotated = self._bucket_locked(self._clock())
      bad = not ok
      if availability:
        bucket.total += count
        self.total += count
        if bad:
          bucket.bad += count
          self.bad += count
      if latency_s is not None:
        bucket.lat_total += count
        if latency_s > self.config.latency_threshold_s:
          bucket.lat_bad += count
          bad = True
        if bucket.hist is not None:
          for _ in range(count):
            bucket.hist.record(latency_s, exemplar=trace_id)
          if self.config.per_scene and scene_id is not None:
            key = self._scene_key_locked(scene_id)
            scene_hist = bucket.scenes.get(key)
            if scene_hist is None:
              scene_hist = bucket.scenes[key] = hist_mod.NativeHistogram()
            for _ in range(count):
              scene_hist.record(latency_s, exemplar=trace_id)
      # The full alert evaluation walks the whole bucket ring; this is
      # the serving hot path (every completed request lands here), so
      # only run it when an edge is actually possible: a bad event can
      # FIRE, any event can CLEAR a firing alert (good traffic dilutes
      # the fast burn), and a bucket rotation ages bad history out.
      # Healthy steady state — good events, nothing firing — pays one
      # scan per bucket_s instead of one per request; snapshot()/
      # alerts_firing() still re-check on every scrape. Quantile edges
      # are evaluated only on ROTATION (record-side): their evaluation
      # merges every in-window histogram — far too heavy to run per bad
      # request during exactly the incident that makes requests bad —
      # and the windowed quantile only moves materially at bucket
      # granularity anyway. Scrapes (healthz probes, /stats, /metrics)
      # still evaluate them every time, so quantile alert latency is
      # bounded by min(bucket_s, scrape interval).
      need_check = (bad or rotated
                    or any(a.firing for a in self._alerts.values()))
    if need_check:
      self.check(quantiles=rotated)

  def record_bad(self, count: int = 1) -> None:
    """Shorthand for failures with no latency sample (errors, sheds)."""
    self.record(ok=False, count=count)

  def fast_burn(self) -> float:
    """The hottest fast-window burn rate across both objectives — the
    brownout controller's overload signal.

    Cheap (one ring walk, no histogram merge) because it is read on the
    admission path. Objectives under ``min_requests`` in the window read
    0.0: a cold window must not read as an outage, and an emptying
    window is exactly how the ladder recovers.
    """
    with self._lock:
      now = self._clock()
      total, bad, lat_total, lat_bad = self._window_locked(
          now, self.config.fast_window_s)
    worst = 0.0
    if total >= self.config.min_requests:
      worst = burn_rate(bad, total, self.config.availability_target)
    if lat_total >= self.config.min_requests:
      worst = max(worst,
                  burn_rate(lat_bad, lat_total, self.config.latency_target))
    return worst

  # -- window math ---------------------------------------------------------

  def _window_floor(self, now: float, window_s: float) -> int:
    return int(now // self._bucket_s) - int(
        math.ceil(window_s / self._bucket_s)) + 1

  def _window_locked(self, now: float, window_s: float) -> tuple:
    """(total, bad, lat_total, lat_bad) over the trailing window."""
    floor = self._window_floor(now, window_s)
    total = bad = lat_total = lat_bad = 0
    for bucket in self._buckets:
      if bucket.idx >= floor:
        total += bucket.total
        bad += bucket.bad
        lat_total += bucket.lat_total
        lat_bad += bucket.lat_bad
    return total, bad, lat_total, lat_bad

  def _burns_locked(self, now: float) -> dict:
    """Per-objective per-window (total, bad, burn) triples."""
    out = {}
    for wname, wsec in (("fast", self.config.fast_window_s),
                        ("slow", self.config.slow_window_s)):
      total, bad, lat_total, lat_bad = self._window_locked(now, wsec)
      out.setdefault("availability", {})[wname] = (
          total, bad,
          burn_rate(bad, total, self.config.availability_target))
      out.setdefault("latency", {})[wname] = (
          lat_total, lat_bad,
          burn_rate(lat_bad, lat_total, self.config.latency_target))
    return out

  def _window_hists_locked(self, now: float, window_s: float) -> tuple:
    """``(pooled_hist, {scene: pooled_hist})`` over the trailing window
    — the native-histogram merge that makes windowed quantiles exact
    (per-bucket counts add; no re-bucketing)."""
    floor = self._window_floor(now, window_s)
    pooled = hist_mod.NativeHistogram()
    scenes: dict[str, hist_mod.NativeHistogram] = {}
    for bucket in self._buckets:
      if bucket.idx < floor or bucket.hist is None:
        continue
      pooled.merge_from(bucket.hist)
      if bucket.scenes:
        for key, scene_hist in bucket.scenes.items():
          acc = scenes.get(key)
          if acc is None:
            acc = scenes[key] = hist_mod.NativeHistogram()
          acc.merge_from(scene_hist)
    return pooled, scenes

  def _quantile_windows_locked(self, now: float) -> dict | None:
    """``{"fast": (hist, scene_hists), "slow": (...)}`` or None when the
    quantile objective is off.

    Memoized on ``(total events, current bucket index)``: the merged
    windows only change when data arrives or the window slides a bucket,
    but one scrape evaluates them several times (``alerts_firing`` +
    ``snapshot`` + the snapshot's own window entries) and a healthz
    probe must not pay the full ring-merge three times per poll. The
    memoized histograms are read-only to every consumer.
    """
    if self.config.quantile is None:
      return None
    key = (self.total, int(now // self._bucket_s))
    if self._qwindows_memo is not None and self._qwindows_memo[0] == key:
      return self._qwindows_memo[1]
    out = {
        "fast": self._window_hists_locked(now, self.config.fast_window_s),
        "slow": self._window_hists_locked(now, self.config.slow_window_s),
    }
    self._qwindows_memo = (key, out)
    return out

  # -- alerting ------------------------------------------------------------

  def _alert_locked(self, name: str) -> _Alert:
    alert = self._alerts.get(name)
    if alert is None:
      alert = self._alerts[name] = _Alert()
    return alert

  def check(self, quantiles: bool = True) -> list[str]:
    """Evaluate alert transitions; returns objectives that CHANGED state.

    Called from every ``record`` and every ``snapshot`` (so a scrape of
    an idle service still clears a stale alert once the fast window
    drains). ``quantiles=False`` (record's mid-bucket calls) skips the
    quantile objectives: their evaluation merges every in-window
    histogram, which must not run per request on the serving hot path.
    """
    transitions = []
    callbacks = []
    with self._lock:
      now = self._clock()
      burns = self._burns_locked(now)
      thr = self.config.burn_threshold
      for name in _OBJECTIVES:
        fast_total, _, fast_burn = burns[name]["fast"]
        slow_total, _, slow_burn = burns[name]["slow"]
        alert = self._alerts[name]
        if not alert.firing:
          # Fire: budget burning too fast over BOTH windows (the fast
          # window confirms the problem is current), with enough traffic
          # in the fast window to mean anything.
          if (fast_total >= self.config.min_requests
              and fast_burn >= thr and slow_burn >= thr):
            alert.firing = True
            alert.fired += 1
            alert.since = now
            transitions.append(name)
            callbacks.append((name, True, {
                "fast_burn": round(fast_burn, 3),
                "slow_burn": round(slow_burn, 3),
                "threshold": thr}))
        elif fast_burn < thr:
          # Clear: the fast window says the bleeding stopped (the slow
          # window may stay elevated for its whole width — that is
          # history, not an ongoing incident).
          alert.firing = False
          alert.cleared += 1
          alert.since = None
          transitions.append(name)
          callbacks.append((name, False, {
              "fast_burn": round(fast_burn, 3), "threshold": thr}))
      qwindows = (self._quantile_windows_locked(now) if quantiles
                  else None)
      if qwindows is not None:
        qname = self.config.quantile_name()
        fast_hist, fast_scenes = qwindows["fast"]
        slow_hist, slow_scenes = qwindows["slow"]
        self._check_quantile_locked(
            qname, None, fast_hist, slow_hist, now, transitions, callbacks)
        if self.config.per_scene:
          # Every scene in the slow window, plus any scene whose alert
          # is still firing (its traffic may have vanished — the clear
          # edge must still happen).
          firing_scenes = {n.partition(":")[2] for n, a in
                          self._alerts.items()
                          if ":" in n and a.firing}
          for scene in sorted(set(slow_scenes) | firing_scenes):
            self._check_quantile_locked(
                f"{qname}:{scene}", scene, fast_scenes.get(scene),
                slow_scenes.get(scene), now, transitions, callbacks)
    for name, firing, details in callbacks:
      if self.on_alert is not None:
        try:
          self.on_alert(name, firing, details)
        except Exception:  # noqa: BLE001 - alerting must not fail requests
          with self._lock:
            self.alert_errors += 1
    return transitions

  def _check_quantile_locked(self, name, scene, fast_hist, slow_hist,
                             now, transitions, callbacks) -> None:
    """One quantile alert's fire/clear decision (global or per-scene)."""
    cfg = self.config
    thr_s = cfg.latency_threshold_s
    fast_q = fast_hist.quantile(cfg.quantile) if fast_hist is not None \
        else None
    slow_q = slow_hist.quantile(cfg.quantile) if slow_hist is not None \
        else None
    alert = self._alert_locked(name)
    detail_base = {"quantile": cfg.quantile,
                   "threshold_ms": round(thr_s * 1e3, 3)}
    if scene is not None:
      detail_base["scene"] = scene
    if not alert.firing:
      if (fast_hist is not None and fast_hist.count >= cfg.min_requests
          and fast_q is not None and fast_q > thr_s
          and slow_q is not None and slow_q > thr_s):
        alert.firing = True
        alert.fired += 1
        alert.since = now
        transitions.append(name)
        exemplar = worst_exemplar(slow_hist) or worst_exemplar(fast_hist)
        callbacks.append((name, True, {
            **detail_base,
            "fast_ms": round(fast_q * 1e3, 3),
            "slow_ms": round(slow_q * 1e3, 3),
            # The worst offender's trace id rides the fire edge so the
            # page links straight to a recorded /debug/traces entry.
            **({"exemplar": exemplar} if exemplar is not None else {})}))
    elif fast_q is None or fast_q <= thr_s:
      alert.firing = False
      alert.cleared += 1
      alert.since = None
      transitions.append(name)
      callbacks.append((name, False, {
          **detail_base,
          "fast_ms": None if fast_q is None else round(fast_q * 1e3, 3)}))

  def alerts_firing(self) -> list[str]:
    self.check()
    with self._lock:
      return sorted(n for n, a in self._alerts.items() if a.firing)

  # -- export --------------------------------------------------------------

  @staticmethod
  def _quantile_window_entry(hist, q: float, thr_s: float,
                             window_s: float) -> dict:
    """One window's slice of a quantile objective's snapshot entry.

    Shape-compatible with the burn objectives' windows (requests / bad /
    attained / burn_rate) so the router's fleet summary aggregates it
    unchanged; ``bad`` is the histogram's over-threshold estimate and
    ``burn_rate`` is the quantile/threshold ratio.
    """
    count = hist.count if hist is not None else 0
    q_val = hist.quantile(q) if hist is not None else None
    over = (round(hist.fraction_over(thr_s) * count)
            if hist is not None and count else 0)
    out = {
        "window_s": window_s,
        "requests": count,
        "bad": over,
        "attained": (round(1.0 - over / count, 6) if count else None),
        "burn_rate": (round(q_val / thr_s, 4) if q_val is not None else 0.0),
        "quantile_ms": (round(q_val * 1e3, 3)
                        if q_val is not None else None),
    }
    exemplar = worst_exemplar(hist)
    if exemplar is not None:
      out["exemplar"] = exemplar
    return out

  def snapshot(self) -> dict:
    """The ``/stats`` ``slo`` block (JSON-ready)."""
    self.check()
    with self._lock:
      now = self._clock()
      burns = self._burns_locked(now)
      qwindows = self._quantile_windows_locked(now)
      cfg = self.config
      out = {
          "config": {
              "availability_target": cfg.availability_target,
              "latency_threshold_ms": round(cfg.latency_threshold_s * 1e3, 3),
              "latency_target": cfg.latency_target,
              "fast_window_s": cfg.fast_window_s,
              "slow_window_s": cfg.slow_window_s,
              "burn_threshold": cfg.burn_threshold,
              "min_requests": cfg.min_requests,
              **({"quantile": cfg.quantile,
                  "per_scene": cfg.per_scene}
                 if cfg.quantile is not None else {}),
          },
          "objectives": {},
          "alerts_firing": [],
          "alert_errors": self.alert_errors,
      }

      def alert_block(alert: _Alert) -> dict:
        block = {
            "firing": alert.firing,
            "fired": alert.fired,
            "cleared": alert.cleared,
        }
        if alert.since is not None:
          block["for_s"] = round(now - alert.since, 3)
        return block

      for name in _OBJECTIVES:
        alert = self._alerts[name]
        windows = {}
        for wname, wsec in (("fast", cfg.fast_window_s),
                            ("slow", cfg.slow_window_s)):
          total, bad, burn = burns[name][wname]
          windows[wname] = {
              "window_s": wsec,
              "requests": total,
              "bad": bad,
              "attained": (round(1.0 - bad / total, 6) if total else None),
              "burn_rate": round(burn, 4),
          }
        entry = {
            "target": cfg.target(name),
            "fast": windows["fast"],
            "slow": windows["slow"],
            "alert": alert_block(alert),
        }
        if name == "latency":
          entry["threshold_ms"] = round(cfg.latency_threshold_s * 1e3, 3)
        out["objectives"][name] = entry
      if qwindows is not None:
        qname = cfg.quantile_name()
        thr_s = cfg.latency_threshold_s
        fast_hist, fast_scenes = qwindows["fast"]
        slow_hist, slow_scenes = qwindows["slow"]
        out["objectives"][qname] = {
            "quantile": cfg.quantile,
            "threshold_ms": round(thr_s * 1e3, 3),
            "fast": self._quantile_window_entry(
                fast_hist, cfg.quantile, thr_s, cfg.fast_window_s),
            "slow": self._quantile_window_entry(
                slow_hist, cfg.quantile, thr_s, cfg.slow_window_s),
            "alert": alert_block(self._alert_locked(qname)),
        }
        if cfg.per_scene:
          per_scene = {}
          scenes = set(slow_scenes) | {
              n.partition(":")[2] for n, a in self._alerts.items()
              if ":" in n and (a.firing or a.fired)}
          for scene in sorted(scenes):
            per_scene[scene] = {
                "fast": self._quantile_window_entry(
                    fast_scenes.get(scene), cfg.quantile, thr_s,
                    cfg.fast_window_s),
                "slow": self._quantile_window_entry(
                    slow_scenes.get(scene), cfg.quantile, thr_s,
                    cfg.slow_window_s),
                "alert": alert_block(
                    self._alert_locked(f"{qname}:{scene}")),
            }
          out["per_scene"] = per_scene
      out["alerts_firing"] = sorted(
          n for n, a in self._alerts.items() if a.firing)
      return out

  def registry(self, snapshot: dict | None = None) -> prom.Registry:
    """The ``mpi_slo_*`` Prometheus families for one snapshot.

    Pool-aggregation note (``obs.prom.aggregate_metrics_texts`` sums
    samples): ``mpi_slo_alert_firing`` summed across a cluster counts
    FIRING BACKENDS — exactly the fleet-level signal the router wants —
    and ``mpi_slo_scene_alerts_firing`` counts firing per-scene alerts
    fleet-wide. The quantile/ratio gauges are in
    ``NON_ADDITIVE_FAMILIES`` and never pool-summed.
    """
    snap = snapshot if snapshot is not None else self.snapshot()
    reg = prom.Registry()
    p = PREFIX
    objective = reg.gauge(p + "objective_target",
                          "Configured SLO target (good-event fraction).")
    attained = reg.gauge(
        p + "attainment_ratio",
        "Good-event fraction over the window (NaN while idle).")
    requests = reg.gauge(p + "window_requests",
                         "Events scored in the window.")
    bad = reg.gauge(p + "window_bad", "Bad events in the window.")
    burn = reg.gauge(
        p + "burn_rate",
        "Error-budget consumption rate over the window (1.0 = exactly "
        "sustainable; quantile objectives report quantile/threshold).")
    firing = reg.gauge(p + "alert_firing",
                       "1 while the objective's burn-rate alert fires.")
    fired = reg.counter(p + "alerts_fired_total",
                        "Alert fire transitions.")
    cleared = reg.counter(p + "alerts_cleared_total",
                          "Alert clear transitions.")
    quantile_entries = []
    for name, entry in snap["objectives"].items():
      labels = {"slo": name}
      if "quantile" in entry:
        quantile_entries.append((name, entry))
      else:
        objective.sample(entry["target"], labels)
      for wname in ("fast", "slow"):
        wlabels = {"slo": name, "window": wname}
        w = entry[wname]
        attained.sample(w["attained"], wlabels)
        requests.sample(w["requests"], wlabels)
        bad.sample(w["bad"], wlabels)
        burn.sample(w["burn_rate"], wlabels)
      firing.sample(1 if entry["alert"]["firing"] else 0, labels)
      fired.sample(entry["alert"]["fired"], labels)
      cleared.sample(entry["alert"]["cleared"], labels)
    if quantile_entries:
      q_gauge = reg.gauge(p + "quantile",
                          "The quantile the objective judges (e.g. 0.99).")
      q_lat = reg.gauge(
          p + "quantile_latency_seconds",
          "Windowed latency at the objective's quantile, estimated from "
          "the pooled native histogram (NaN while idle).")
      q_thr = reg.gauge(p + "quantile_threshold_seconds",
                        "The quantile objective's latency bound.")
      for name, entry in quantile_entries:
        labels = {"slo": name}
        q_gauge.sample(entry["quantile"], labels)
        q_thr.sample(entry["threshold_ms"] / 1e3, labels)
        for wname in ("fast", "slow"):
          q_ms = entry[wname]["quantile_ms"]
          q_lat.sample(None if q_ms is None else q_ms / 1e3,
                       {"slo": name, "window": wname})
    if "per_scene" in snap:
      reg.gauge(
          p + "scene_alerts_firing",
          "Per-scene quantile alerts currently firing (pool-summed: "
          "firing scene alerts fleet-wide).",
          sum(1 for scene in snap["per_scene"].values()
              if scene["alert"]["firing"]))
    reg.gauge(p + "latency_threshold_seconds",
              "The latency objective's good-request bound.",
              snap["config"]["latency_threshold_ms"] / 1e3)
    reg.gauge(p + "burn_threshold",
              "Burn rate at which the alert fires (both windows).",
              snap["config"]["burn_threshold"])
    return reg

  def metrics_text(self) -> str:
    return self.registry().render()


def verdict(snapshot: dict | None) -> dict | None:
  """The bench-side pass/fail block for one ``SloTracker.snapshot()``.

  Attainment over the SLOW window is the score (the fast window is for
  alert edges, not report cards). Quantile objectives pass when the slow
  window's pooled quantile beats the threshold. ``pass`` is None while
  the window saw no traffic; per-scene objectives report their own
  ``pass`` inside the ``per_scene`` block without flipping the global
  one (a single toy scene must not fail a fleet-wide bench line — the
  alert counters still say it paged). Returns None for services running
  without SLO tracking.
  """
  if not snapshot:
    return None
  out = {"objectives": {}, "alerts_firing": list(snapshot["alerts_firing"])}
  ok = True
  scored = False
  for name, entry in snapshot["objectives"].items():
    slow = entry["slow"]
    if "quantile" in entry:
      q_ms = slow["quantile_ms"]
      passed = None if q_ms is None else q_ms <= entry["threshold_ms"]
      out["objectives"][name] = {
          "quantile": entry["quantile"],
          "threshold_ms": entry["threshold_ms"],
          "quantile_ms": q_ms,
          "requests": slow["requests"],
          "burn_fast": entry["fast"]["burn_rate"],
          "burn_slow": slow["burn_rate"],
          "alerts_fired": entry["alert"]["fired"],
          "pass": passed,
      }
    else:
      attained = slow["attained"]
      passed = None if attained is None else attained >= entry["target"]
      out["objectives"][name] = {
          "target": entry["target"],
          "attained": attained,
          "requests": slow["requests"],
          "burn_fast": entry["fast"]["burn_rate"],
          "burn_slow": slow["burn_rate"],
          "alerts_fired": entry["alert"]["fired"],
          "pass": passed,
      }
    if passed is not None:
      scored = True
      ok = ok and passed
  if "per_scene" in snapshot:
    failing = sorted(
        scene for scene, entry in snapshot["per_scene"].items()
        if entry["slow"]["quantile_ms"] is not None
        and entry["slow"]["quantile_ms"]
        > snapshot["config"]["latency_threshold_ms"])
    out["per_scene"] = {
        "scenes": len(snapshot["per_scene"]),
        "failing": failing,
        "alerts_fired": sum(entry["alert"]["fired"]
                            for entry in snapshot["per_scene"].values()),
        "pass": not failing if snapshot["per_scene"] else None,
    }
  out["pass"] = ok if scored else None
  return out
