"""SLO engine: sliding-window objectives + multi-window burn-rate alerts.

The judgment layer on top of the raw telemetry (PR 3's counters say what
happened; this module says whether the fleet is *meeting objectives*).
Two objectives over the serve request stream, both expressed as "fraction
of good events":

  * **availability** — a request is good when it completed without an
    error (errors, queue sheds, and breaker fast-fails are bad events:
    the user saw a failure either way).
  * **latency** — a *completed* request is good when its end-to-end
    latency is under ``latency_threshold_s`` (FastNeRF's 200 FPS target
    is only meaningful against exactly this kind of tracked bound).

Alerting follows the SRE-workbook multi-window burn-rate scheme: the
**burn rate** is ``(1 - attainment) / (1 - target)`` — 1.0 means the
error budget is being consumed exactly at the sustainable rate, 10x
means ten times too fast. An alert fires when the burn rate exceeds
``burn_threshold`` over BOTH the slow window (the problem is material)
and the fast window (the problem is happening *now*, not a stale spike
still inside the long window), and clears as soon as the fast window's
burn drops back under the threshold — recovery is visible within
``fast_window_s`` instead of lingering for the whole slow window.

Implementation is a ring of coarse time buckets (O(1) record, O(buckets)
snapshot, bounded memory regardless of traffic), driven entirely by an
injectable clock so every rotation/alert edge is testable with fake time
(``tests/serve/test_slo.py``; clock-lint covers this file).

``SloTracker.registry()`` renders the state as ``mpi_slo_*`` Prometheus
families; ``verdict()`` turns a snapshot into the pass/fail block
``bench/serve_load.py`` embeds in its JSON so BENCH lines trend against
explicit objectives.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time

from mpi_vision_tpu.obs import prom

PREFIX = "mpi_slo_"

# Families a pool aggregator must NOT sum across backends: targets,
# ratios, and thresholds are per-backend statements (3 x 0.99 targets
# summed would read 2.97, and an idle backend's NaN attainment would
# poison the fleet sample). The cluster router drops these from its
# summed exposition; the per-backend values stay reachable through the
# /stats fan-out. Everything else mpi_slo_* exports sums meaningfully
# (window counts add; alert_firing becomes "firing backends").
NON_ADDITIVE_FAMILIES = frozenset({
    PREFIX + "objective_target",
    PREFIX + "attainment_ratio",
    PREFIX + "burn_rate",
    PREFIX + "latency_threshold_seconds",
    PREFIX + "burn_threshold",
})

_OBJECTIVES = ("availability", "latency")


@dataclasses.dataclass(frozen=True)
class SloConfig:
  """Objectives + alerting knobs (the ``serve`` CLI flags map 1:1).

  Defaults suit a serving demo fleet: 99% availability, 95% of requests
  under 1 s, alert at 10x budget burn confirmed over a 60 s fast / 600 s
  slow window pair. ``min_requests`` keeps a single bad request on an
  idle service from paging.
  """

  availability_target: float = 0.99
  latency_threshold_s: float = 1.0
  latency_target: float = 0.95
  fast_window_s: float = 60.0
  slow_window_s: float = 600.0
  burn_threshold: float = 10.0
  bucket_s: float | None = None  # None: fast_window_s / 12, floored 0.25
  min_requests: int = 10

  def __post_init__(self):
    for name in ("availability_target", "latency_target"):
      v = getattr(self, name)
      if not 0.0 < v < 1.0:
        raise ValueError(f"{name} must be in (0, 1), got {v}")
    if self.latency_threshold_s <= 0:
      raise ValueError(
          f"latency_threshold_s must be > 0, got {self.latency_threshold_s}")
    if not 0 < self.fast_window_s <= self.slow_window_s:
      raise ValueError(
          f"need 0 < fast_window_s <= slow_window_s, got "
          f"{self.fast_window_s} / {self.slow_window_s}")
    if self.burn_threshold <= 0:
      raise ValueError(
          f"burn_threshold must be > 0, got {self.burn_threshold}")
    if self.bucket_s is not None and not (
        0 < self.bucket_s <= self.fast_window_s):
      raise ValueError(
          f"bucket_s must be in (0, fast_window_s], got {self.bucket_s}")

  def resolved_bucket_s(self) -> float:
    if self.bucket_s is not None:
      return float(self.bucket_s)
    return max(self.fast_window_s / 12.0, 0.25)

  def target(self, objective: str) -> float:
    return (self.availability_target if objective == "availability"
            else self.latency_target)


class _Alert:
  """One objective's fire/clear state machine (single-threaded under the
  tracker's lock)."""

  __slots__ = ("firing", "fired", "cleared", "since")

  def __init__(self):
    self.firing = False
    self.fired = 0
    self.cleared = 0
    self.since: float | None = None  # tracker-clock time of last fire


def burn_rate(bad: int, total: int, target: float) -> float:
  """Error-budget consumption rate over one window (0 when idle)."""
  if total <= 0:
    return 0.0
  return (bad / total) / (1.0 - target)


class SloTracker:
  """Sliding-window SLO accounting + burn-rate alerting over requests.

  Args:
    config: objectives + alert knobs.
    clock: injectable monotonic clock driving bucket rotation and alert
      edges (share with the serving stack's other clocks).
    on_alert: optional ``(objective, firing, details) -> None`` callback
      fired on every alert transition (the serving layer routes it into
      the event log). Exceptions are swallowed and counted — alerting
      must not be able to fail the request path.
  """

  def __init__(self, config: SloConfig | None = None, clock=time.monotonic,
               on_alert=None):
    self.config = config if config is not None else SloConfig()
    self._clock = clock
    self.on_alert = on_alert
    self._bucket_s = self.config.resolved_bucket_s()
    # +1: the current (partial) bucket rides along with a full slow
    # window of closed ones.
    self._ring_len = int(math.ceil(
        self.config.slow_window_s / self._bucket_s)) + 1
    self._lock = threading.Lock()
    self.alert_errors = 0
    self.reset()

  def reset(self) -> None:
    """Drop all window state and alert history (load generators call
    this after warm-up, mirroring ``ServeMetrics.reset``)."""
    with self._lock:
      # Ring of [bucket_index, total, bad, lat_total, lat_bad].
      self._buckets: list[list] = []
      self._alerts = {name: _Alert() for name in _OBJECTIVES}
      self.total = 0
      self.bad = 0

  # -- recording -----------------------------------------------------------

  def _bucket_locked(self, now: float) -> tuple[list, bool]:
    """The current bucket, plus whether it was freshly opened."""
    idx = int(now // self._bucket_s)
    rotated = not self._buckets or self._buckets[-1][0] < idx
    if rotated:
      self._buckets.append([idx, 0, 0, 0, 0])
      floor = idx - self._ring_len + 1
      while self._buckets and self._buckets[0][0] < floor:
        self._buckets.pop(0)
    return self._buckets[-1], rotated

  def record(self, ok: bool, latency_s: float | None = None,
             count: int = 1) -> None:
    """Account ``count`` request outcomes.

    ``ok=False`` consumes availability budget; ``latency_s`` (completed
    requests only) additionally scores the latency objective.
    """
    with self._lock:
      bucket, rotated = self._bucket_locked(self._clock())
      bucket[1] += count
      self.total += count
      bad = not ok
      if bad:
        bucket[2] += count
        self.bad += count
      if latency_s is not None:
        bucket[3] += count
        if latency_s > self.config.latency_threshold_s:
          bucket[4] += count
          bad = True
      # The full alert evaluation walks the whole bucket ring; this is
      # the serving hot path (every completed request lands here), so
      # only run it when an edge is actually possible: a bad event can
      # FIRE, any event can CLEAR a firing alert (good traffic dilutes
      # the fast burn), and a bucket rotation ages bad history out.
      # Healthy steady state — good events, nothing firing — pays one
      # scan per bucket_s instead of one per request; snapshot()/
      # alerts_firing() still re-check on every scrape.
      need_check = (bad or rotated
                    or any(a.firing for a in self._alerts.values()))
    if need_check:
      self.check()

  def record_bad(self, count: int = 1) -> None:
    """Shorthand for failures with no latency sample (errors, sheds)."""
    self.record(ok=False, count=count)

  # -- window math ---------------------------------------------------------

  def _window_locked(self, now: float, window_s: float) -> tuple:
    """(total, bad, lat_total, lat_bad) over the trailing window."""
    floor = int(now // self._bucket_s) - int(
        math.ceil(window_s / self._bucket_s)) + 1
    total = bad = lat_total = lat_bad = 0
    for idx, t, b, lt, lb in self._buckets:
      if idx >= floor:
        total += t
        bad += b
        lat_total += lt
        lat_bad += lb
    return total, bad, lat_total, lat_bad

  def _burns_locked(self, now: float) -> dict:
    """Per-objective per-window (total, bad, burn) triples."""
    out = {}
    for wname, wsec in (("fast", self.config.fast_window_s),
                        ("slow", self.config.slow_window_s)):
      total, bad, lat_total, lat_bad = self._window_locked(now, wsec)
      out.setdefault("availability", {})[wname] = (
          total, bad,
          burn_rate(bad, total, self.config.availability_target))
      out.setdefault("latency", {})[wname] = (
          lat_total, lat_bad,
          burn_rate(lat_bad, lat_total, self.config.latency_target))
    return out

  # -- alerting ------------------------------------------------------------

  def check(self) -> list[str]:
    """Evaluate alert transitions; returns objectives that CHANGED state.

    Called from every ``record`` and every ``snapshot`` (so a scrape of
    an idle service still clears a stale alert once the fast window
    drains).
    """
    transitions = []
    callbacks = []
    with self._lock:
      now = self._clock()
      burns = self._burns_locked(now)
      thr = self.config.burn_threshold
      for name in _OBJECTIVES:
        fast_total, _, fast_burn = burns[name]["fast"]
        slow_total, _, slow_burn = burns[name]["slow"]
        alert = self._alerts[name]
        if not alert.firing:
          # Fire: budget burning too fast over BOTH windows (the fast
          # window confirms the problem is current), with enough traffic
          # in the fast window to mean anything.
          if (fast_total >= self.config.min_requests
              and fast_burn >= thr and slow_burn >= thr):
            alert.firing = True
            alert.fired += 1
            alert.since = now
            transitions.append(name)
            callbacks.append((name, True, {
                "fast_burn": round(fast_burn, 3),
                "slow_burn": round(slow_burn, 3),
                "threshold": thr}))
        elif fast_burn < thr:
          # Clear: the fast window says the bleeding stopped (the slow
          # window may stay elevated for its whole width — that is
          # history, not an ongoing incident).
          alert.firing = False
          alert.cleared += 1
          alert.since = None
          transitions.append(name)
          callbacks.append((name, False, {
              "fast_burn": round(fast_burn, 3), "threshold": thr}))
    for name, firing, details in callbacks:
      if self.on_alert is not None:
        try:
          self.on_alert(name, firing, details)
        except Exception:  # noqa: BLE001 - alerting must not fail requests
          with self._lock:
            self.alert_errors += 1
    return transitions

  def alerts_firing(self) -> list[str]:
    self.check()
    with self._lock:
      return [n for n in _OBJECTIVES if self._alerts[n].firing]

  # -- export --------------------------------------------------------------

  def snapshot(self) -> dict:
    """The ``/stats`` ``slo`` block (JSON-ready)."""
    self.check()
    with self._lock:
      now = self._clock()
      burns = self._burns_locked(now)
      cfg = self.config
      out = {
          "config": {
              "availability_target": cfg.availability_target,
              "latency_threshold_ms": round(cfg.latency_threshold_s * 1e3, 3),
              "latency_target": cfg.latency_target,
              "fast_window_s": cfg.fast_window_s,
              "slow_window_s": cfg.slow_window_s,
              "burn_threshold": cfg.burn_threshold,
              "min_requests": cfg.min_requests,
          },
          "objectives": {},
          "alerts_firing": [],
          "alert_errors": self.alert_errors,
      }
      for name in _OBJECTIVES:
        alert = self._alerts[name]
        windows = {}
        for wname, wsec in (("fast", cfg.fast_window_s),
                            ("slow", cfg.slow_window_s)):
          total, bad, burn = burns[name][wname]
          windows[wname] = {
              "window_s": wsec,
              "requests": total,
              "bad": bad,
              "attained": (round(1.0 - bad / total, 6) if total else None),
              "burn_rate": round(burn, 4),
          }
        entry = {
            "target": cfg.target(name),
            "fast": windows["fast"],
            "slow": windows["slow"],
            "alert": {
                "firing": alert.firing,
                "fired": alert.fired,
                "cleared": alert.cleared,
            },
        }
        if alert.since is not None:
          entry["alert"]["for_s"] = round(now - alert.since, 3)
        if name == "latency":
          entry["threshold_ms"] = round(cfg.latency_threshold_s * 1e3, 3)
        out["objectives"][name] = entry
        if alert.firing:
          out["alerts_firing"].append(name)
      return out

  def registry(self, snapshot: dict | None = None) -> prom.Registry:
    """The ``mpi_slo_*`` Prometheus families for one snapshot.

    Pool-aggregation note (``obs.prom.aggregate_metrics_texts`` sums
    samples): ``mpi_slo_alert_firing`` summed across a cluster counts
    FIRING BACKENDS — exactly the fleet-level signal the router wants.
    """
    snap = snapshot if snapshot is not None else self.snapshot()
    reg = prom.Registry()
    p = PREFIX
    objective = reg.gauge(p + "objective_target",
                          "Configured SLO target (good-event fraction).")
    attained = reg.gauge(
        p + "attainment_ratio",
        "Good-event fraction over the window (NaN while idle).")
    requests = reg.gauge(p + "window_requests",
                         "Events scored in the window.")
    bad = reg.gauge(p + "window_bad", "Bad events in the window.")
    burn = reg.gauge(
        p + "burn_rate",
        "Error-budget consumption rate over the window (1.0 = exactly "
        "sustainable).")
    firing = reg.gauge(p + "alert_firing",
                       "1 while the objective's burn-rate alert fires.")
    fired = reg.counter(p + "alerts_fired_total",
                        "Alert fire transitions.")
    cleared = reg.counter(p + "alerts_cleared_total",
                          "Alert clear transitions.")
    for name, entry in snap["objectives"].items():
      labels = {"slo": name}
      objective.sample(entry["target"], labels)
      for wname in ("fast", "slow"):
        wlabels = {"slo": name, "window": wname}
        w = entry[wname]
        attained.sample(w["attained"], wlabels)
        requests.sample(w["requests"], wlabels)
        bad.sample(w["bad"], wlabels)
        burn.sample(w["burn_rate"], wlabels)
      firing.sample(1 if entry["alert"]["firing"] else 0, labels)
      fired.sample(entry["alert"]["fired"], labels)
      cleared.sample(entry["alert"]["cleared"], labels)
    reg.gauge(p + "latency_threshold_seconds",
              "The latency objective's good-request bound.",
              snap["config"]["latency_threshold_ms"] / 1e3)
    reg.gauge(p + "burn_threshold",
              "Burn rate at which the alert fires (both windows).",
              snap["config"]["burn_threshold"])
    return reg

  def metrics_text(self) -> str:
    return self.registry().render()


def verdict(snapshot: dict | None) -> dict | None:
  """The bench-side pass/fail block for one ``SloTracker.snapshot()``.

  Attainment over the SLOW window is the score (the fast window is for
  alert edges, not report cards). ``pass`` is None while the window saw
  no traffic. Returns None for services running without SLO tracking.
  """
  if not snapshot:
    return None
  out = {"objectives": {}, "alerts_firing": list(snapshot["alerts_firing"])}
  ok = True
  scored = False
  for name, entry in snapshot["objectives"].items():
    slow = entry["slow"]
    attained = slow["attained"]
    passed = None if attained is None else attained >= entry["target"]
    out["objectives"][name] = {
        "target": entry["target"],
        "attained": attained,
        "requests": slow["requests"],
        "burn_fast": entry["fast"]["burn_rate"],
        "burn_slow": slow["burn_rate"],
        "alerts_fired": entry["alert"]["fired"],
        "pass": passed,
    }
    if passed is not None:
      scored = True
      ok = ok and passed
  out["pass"] = ok if scored else None
  return out
