"""Off-host telemetry shipping: batches, retries, and a disk spool.

On-box telemetry dies with the box. The shipper is the flight
recorder's off-host leg: a daemon thread that periodically batches

  * **rotated event-log segments** — the ``FILE.1 .. FILE.<keep>`` files
    ``obs.events.file_sink`` rotation produces, which are invisible to
    the ``/debug/events`` ring (the retention blind spot): each shipped
    segment is deleted locally, so rotation only ever *drops* a segment
    the sink outlasted;
  * **SLO alert edges** — every fire/clear record, queued by the serving
    layer off the request path;
  * **incremental tsdb snapshots** — every series' points since the last
    successful ship (``obs.tsdb.TsdbRecorder.snapshot_since``),

and POSTs them as one JSON body to a configured HTTP sink. Failures ride
the existing ``serve.resilience.RetryPolicy`` (bounded exponential
backoff); a batch that still cannot be delivered spools to disk under a
byte budget (oldest spool file dropped when over it, counted) and drains
oldest-first when the sink recovers — a sink outage shorter than the
spool budget loses nothing. Everything is counted
(``mpi_obs_ship_*``), nothing is fatal, and none of it ever runs on the
request path (``note_alert`` is a lock-guarded deque append).

Clock, sleep, and transport are injectable (clock-lint covers this
file); tests drive ``tick()`` directly against a fake sink.
"""

from __future__ import annotations

import dataclasses
import functools
import http.client
import json
import os
import random
import re
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from mpi_vision_tpu.obs import prom
from mpi_vision_tpu.serve.resilience import RetryPolicy

PREFIX = "mpi_obs_ship_"

# Alert edges retained while the sink is down and the spool is off; past
# this the OLDEST edges drop (counted) — the ring bound, like the event
# log's.
MAX_PENDING_ALERTS = 256

# Claimed-but-undelivered event-log segments retained on disk during a
# sink outage. Claiming frees rotation's FILE.N slots, so without a cap
# a long outage under a busy event stream would grow FILE.ship.* without
# bound — the exact disk bound events_keep existed to provide. Past it
# the OLDEST claims drop (counted): newest telemetry survives, the
# outage window is bounded.
MAX_CLAIMED_SEGMENTS = 32

# Incident bundles retained in memory between ticks. Bundles are big
# (a frozen tsdb window each) and rare (one per fire edge, deduplicated
# by the recorder) — a deep backlog here would mean the interval is
# longer than the incident cadence, and the recorder's own disk ring
# still holds everything this cap sheds.
MAX_PENDING_INCIDENTS = 8


@dataclasses.dataclass(frozen=True)
class ShipConfig:
  """Shipper knobs (the ``serve`` CLI ``--ship-*`` flags map 1:1).

  ``url`` is the HTTP sink (POST, JSON body). ``spool_dir`` enables the
  disk spool (None: undeliverable batches drop, counted);
  ``spool_budget_bytes`` bounds it. ``events_path``/``events_keep``
  point at the event-log JSONL file whose rotated segments the shipper
  picks up (empty: no segment shipping).
  """

  url: str
  interval_s: float = 10.0
  timeout_s: float = 5.0
  spool_dir: str | None = None
  spool_budget_bytes: int = 64 << 20
  events_path: str | None = None
  events_keep: int = 3
  retry: RetryPolicy = RetryPolicy(max_retries=2, backoff_base_s=0.2,
                                   backoff_max_s=2.0)

  def __post_init__(self):
    if not self.url:
      raise ValueError("ShipConfig.url must be set")
    if self.interval_s <= 0:
      raise ValueError(f"interval_s must be > 0, got {self.interval_s}")
    if self.timeout_s <= 0:
      raise ValueError(f"timeout_s must be > 0, got {self.timeout_s}")
    if self.spool_budget_bytes <= 0:
      raise ValueError(
          f"spool_budget_bytes must be > 0, got {self.spool_budget_bytes}")
    if self.events_keep < 1:
      raise ValueError(f"events_keep must be >= 1, got {self.events_keep}")


class HttpPostTransport:
  """The default shipper->sink transport (stdlib urllib, no deps).

  ``post`` returns the HTTP status for any completed conversation and
  raises ``ConnectionError`` when none happened (refused, reset, DNS,
  timeout) — same contract as the cluster router's transport.
  """

  def post(self, url: str, body: bytes, timeout: float) -> int:
    req = urllib.request.Request(
        url, data=body, method="POST",
        headers={"Content-Type": "application/json"})
    try:
      with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status
    except urllib.error.HTTPError as e:
      with e:
        return e.code
    except (urllib.error.URLError, ConnectionError, TimeoutError,
            OSError, http.client.HTTPException) as e:
      # HTTPException (BadStatusLine, IncompleteRead, ...) is NOT an
      # OSError: a half-dead sink writing a garbled response must look
      # like a down sink (retry, then spool) — the same mapping the
      # cluster router's transport makes. Letting it escape would drop
      # the batch's already-drained alert edges with only a tick_error.
      raise ConnectionError(str(e) or repr(e)) from e


class TelemetryShipper:
  """Batches telemetry to an HTTP sink with retry + disk spool.

  Args:
    config: sink/spool/cadence knobs.
    tsdb: optional ``obs.tsdb.TsdbRecorder`` whose incremental snapshots
      ride each batch.
    transport: injectable sink transport (tests); default urllib POST.
    clock: wall-clock source for batch timestamps and the tsdb cursor.
    sleep: injectable retry-backoff sleep.
  """

  def __init__(self, config: ShipConfig, tsdb=None, transport=None,
               clock=time.time, sleep=time.sleep, seed: int = 0):
    self.config = config
    self.tsdb = tsdb
    self.transport = transport if transport is not None \
        else HttpPostTransport()
    self._clock = clock
    self._sleep = sleep
    self._rng = random.Random(seed)
    self._lock = threading.Lock()
    self._pending_alerts: deque = deque(maxlen=MAX_PENDING_ALERTS)
    self._pending_incidents: deque = deque(maxlen=MAX_PENDING_INCIDENTS)
    self._alerts_dropped_marker = 0
    self._stop = threading.Event()
    self._thread: threading.Thread | None = None
    self._spool_seq = 0
    self._last_tsdb_ts: float | None = None
    self.batches_shipped = 0
    self.posts = 0
    self.post_failures = 0
    self.retries = 0
    self.alert_edges = 0
    self.alert_edges_dropped = 0
    self.incident_bundles = 0
    self.incident_bundles_dropped = 0
    self.segments_shipped = 0
    self.segments_dropped = 0
    self.segment_errors = 0
    self.spooled = 0
    self.spool_dropped = 0
    self.tick_errors = 0
    # In-memory spool accounting: stats() feeds every /metrics render
    # (and every tsdb sample), which must not pay a directory walk +
    # per-file stat per scrape during exactly the outage that fills the
    # spool. Kept in sync by _spool/_drain_spool; seeded by one scan.
    self._spool_file_count = 0
    self._spool_bytes = 0
    if config.spool_dir:
      os.makedirs(config.spool_dir, exist_ok=True)
    # Resume the sequence past anything a previous process left behind
    # (spooled batches AND claimed segments): restarting at 1 would
    # os.replace OVER them — losing exactly the telemetry the spool/
    # claim files exist to preserve — and break the oldest-first order.
    for path in self._spool_files():
      name = os.path.basename(path)
      try:
        self._spool_seq = max(self._spool_seq,
                              int(name[len("spool-"):-len(".json")]))
      except ValueError:
        continue
      self._spool_file_count += 1
      try:
        self._spool_bytes += os.path.getsize(path)
      except OSError:
        pass
    for path in self._claimed_paths():
      try:
        self._spool_seq = max(self._spool_seq,
                              int(path.rpartition(".ship.")[2]))
      except ValueError:
        continue

  # -- inputs (never the request path's problem) ---------------------------

  def note_alert(self, record: dict) -> None:
    """Queue one SLO alert edge for the next batch (O(1), lock-guarded
    append — safe to call from the alert callback path)."""
    with self._lock:
      if len(self._pending_alerts) == self._pending_alerts.maxlen:
        self.alert_edges_dropped += 1
      self._pending_alerts.append(dict(record))
      self.alert_edges += 1

  def note_incident(self, bundle: dict) -> None:
    """Queue one incident bundle (``obs.incident``) for the next batch.

    Bundles ride the same batch -> retry -> disk-spool arc as alert
    edges, so a sink outage shorter than the spool budget loses none of
    them and recovery drains them in capture order. O(1) append — the
    recorder's daemon worker calls this, never the request path."""
    with self._lock:
      if len(self._pending_incidents) == self._pending_incidents.maxlen:
        self.incident_bundles_dropped += 1
      self._pending_incidents.append(bundle)
      self.incident_bundles += 1

  # -- shipping ------------------------------------------------------------

  def _post_with_retry(self, body: bytes) -> bool:
    """One delivery attempt arc through the RetryPolicy; True = landed."""
    policy = self.config.retry
    attempt = 0
    while True:
      with self._lock:
        self.posts += 1
      try:
        status = self.transport.post(self.config.url, body,
                                     self.config.timeout_s)
        if 200 <= status < 300:
          return True
      except Exception:  # noqa: BLE001 - ANY transport failure is "sink
        # down": the batch's alert edges are already drained, so an
        # exception escaping here (instead of retry -> spool) would be
        # silent telemetry loss counted only as a tick_error.
        pass
      with self._lock:
        self.post_failures += 1
      attempt += 1
      if attempt > policy.max_retries:
        return False
      with self._lock:
        self.retries += 1
      self._sleep(policy.backoff_s(attempt, self._rng))

  # -- spool ---------------------------------------------------------------

  def _spool_files(self) -> list[str]:
    if not self.config.spool_dir:
      return []
    try:
      names = sorted(n for n in os.listdir(self.config.spool_dir)
                     if n.startswith("spool-") and n.endswith(".json"))
    except OSError:
      return []
    return [os.path.join(self.config.spool_dir, n) for n in names]

  def _spool(self, body: bytes) -> bool:
    """Persist one undeliverable batch; oldest files drop past the byte
    budget (a bounded spool that refuses new data would lose the NEWEST
    telemetry — exactly the window an operator wants)."""
    if not self.config.spool_dir:
      return False
    with self._lock:
      self._spool_seq += 1
      seq = self._spool_seq
    path = os.path.join(self.config.spool_dir, f"spool-{seq:08d}.json")
    try:
      tmp = path + ".tmp"
      with open(tmp, "wb") as fh:
        fh.write(body)
      os.replace(tmp, path)
    except OSError:
      return False
    with self._lock:
      self.spooled += 1
      self._spool_file_count += 1
      self._spool_bytes += len(body)
    files = self._spool_files()
    total = 0
    sizes = {}
    for f in files:
      try:
        sizes[f] = os.path.getsize(f)
        total += sizes[f]
      except OSError:
        continue
    # Never evict the file just written (files[-1], highest seq): the
    # True return tells tick() the batch is covered and the cursor
    # advances — evicting it here would silently lose exactly that
    # window. A single batch larger than the whole budget overshoots it
    # by one batch, bounded.
    for f in files[:-1]:
      if total <= self.config.spool_budget_bytes:
        break
      try:
        os.remove(f)
        total -= sizes.get(f, 0)
        with self._lock:
          self.spool_dropped += 1
          self._spool_file_count -= 1
          self._spool_bytes -= sizes.get(f, 0)
      except OSError:
        pass
    return True

  def _drain_spool(self) -> None:
    """Replay spooled batches oldest-first; stop at the first failure
    (the sink is still down — retrying the rest only burns backoff)."""
    for path in self._spool_files():
      try:
        body = open(path, "rb").read()
      except OSError:
        continue
      if not self._post_with_retry(body):
        return
      with self._lock:
        self.batches_shipped += 1
      try:
        os.remove(path)
        with self._lock:
          self._spool_file_count -= 1
          self._spool_bytes -= len(body)
      except OSError:
        pass

  # -- event-log segments --------------------------------------------------

  def _segment_paths(self) -> list[str]:
    """Rotated event-log segments, oldest first (``FILE.<keep>`` is the
    next to be dropped by rotation, so it ships first)."""
    if not self.config.events_path:
      return []
    out = []
    for i in range(self.config.events_keep, 0, -1):
      path = f"{self.config.events_path}.{i}"
      if os.path.exists(path):
        out.append(path)
    return out

  def _claimed_paths(self) -> list[str]:
    """Segments already claimed (renamed ``FILE.ship.N``) but not yet
    delivered — a previous tick's sink outage, or a crashed process."""
    if not self.config.events_path:
      return []
    directory = os.path.dirname(self.config.events_path) or "."
    prefix = os.path.basename(self.config.events_path) + ".ship."
    try:
      names = sorted(n for n in os.listdir(directory)
                     if n.startswith(prefix))
    except OSError:
      return []
    return [os.path.join(directory, n) for n in names]

  def pending_segments(self) -> int:
    """Rotated (or claimed-but-undelivered) segments still on disk."""
    return len(self._segment_paths()) + len(self._claimed_paths())

  def _claim_segments(self) -> list[str]:
    """Atomically rename each rotated segment out of rotation's
    namespace (``FILE.N`` -> ``FILE.ship.<seq>``) BEFORE shipping it.

    Rotation only ever touches ``FILE.1..FILE.<keep>``, so once claimed
    a segment can neither be overwritten by a rotation that happens
    mid-POST nor — the race this protocol exists to kill — deleted by
    name after rotation already put a NEWER, unshipped segment at that
    name. A claim that fails (rotation won the rename) just means the
    file moved; it is picked up next tick.
    """
    claimed = []
    for path in self._segment_paths():
      with self._lock:
        self._spool_seq += 1
        seq = self._spool_seq
      target = f"{self.config.events_path}.ship.{seq:08d}"
      try:
        os.replace(path, target)
      except OSError:
        continue
      claimed.append(target)
    # Bound the claim backlog (see MAX_CLAIMED_SEGMENTS): drop oldest.
    backlog = self._claimed_paths()
    for path in backlog[:max(len(backlog) - MAX_CLAIMED_SEGMENTS, 0)]:
      try:
        os.remove(path)
        with self._lock:
          self.segments_dropped += 1
      except OSError:
        pass
    return claimed

  def _ship_segments(self) -> None:
    """Ship each claimed segment as its own POST and delete it locally —
    once the bytes are off-host, the rotation slot is free and the
    retention blind spot closes. Undelivered claims stay on disk for the
    next tick (they survive restarts too). Claiming (and its backlog
    trim) runs BEFORE the listing, so the iteration never holds paths
    the trim just deleted (which would double-book every trimmed
    segment as a segment_error)."""
    self._claim_segments()
    for path in self._claimed_paths():
      try:
        content = open(path, "r", errors="replace").read()
      except OSError:
        with self._lock:
          self.segment_errors += 1
        continue
      body = json.dumps({
          "kind": "mpi_events_segment",
          "segment": os.path.basename(path),
          "sent_at": round(self._clock(), 3),
          "lines": content.count("\n"),
          "content": content,
      }).encode()
      if not self._post_with_retry(body):
        return  # sink down: claimed segments wait for the next tick
      with self._lock:
        self.segments_shipped += 1
      try:
        os.remove(path)
      except OSError:
        with self._lock:
          self.segment_errors += 1

  # -- the periodic cycle --------------------------------------------------

  def _build_batch(self) -> tuple[bytes | None, float | None]:
    """One batch plus the tsdb cursor it covers.

    The cursor is derived from the points actually INCLUDED, never a
    fresh clock read: a sampler sweep that stamped its timestamp before
    this ran but appended after would fall between a clock-read cursor
    and the snapshot — skipped forever. Specifically it is the MINIMUM
    over series of each series' last shipped timestamp: when per-series
    truncation held some series back, a max would strand their
    remainder behind the cursor; the min re-ships a few already-sent
    points instead (duplicates are fine for a collector, loss is not).
    Batches with no tsdb item leave the cursor alone.
    """
    now = round(self._clock(), 3)
    with self._lock:
      alerts = list(self._pending_alerts)
      self._pending_alerts.clear()
      incidents = list(self._pending_incidents)
      self._pending_incidents.clear()
      tsdb_cursor = self._last_tsdb_ts
    cursor = tsdb_cursor
    items: list[dict] = []
    if alerts:
      items.append({"kind": "slo_alert_edges", "edges": alerts})
    if incidents:
      items.append({"kind": "incidents", "bundles": incidents})
    if self.tsdb is not None:
      families = self.tsdb.snapshot_since(tsdb_cursor)
      if families:
        items.append({"kind": "tsdb", "since": tsdb_cursor,
                      "families": families})
        cursor = min(series["points"][-1][0]
                     for series_list in families.values()
                     for series in series_list)
    if not items:
      return None, cursor
    return json.dumps({"kind": "mpi_telemetry", "sent_at": now,
                       "items": items}).encode(), cursor

  def tick(self) -> None:
    """One shipping cycle: drain the spool, ship rotated segments, ship
    the current batch (spooling it on failure). Never raises."""
    try:
      self._drain_spool()
      self._ship_segments()
      body, cursor = self._build_batch()
      if body is None:
        return
      if self._post_with_retry(body):
        with self._lock:
          self.batches_shipped += 1
          self._last_tsdb_ts = cursor
      elif self._spool(body):
        # Spooled: the batch's tsdb points are covered (they reach the
        # sink on drain) — advance the cursor so recovery does not
        # double-ship them.
        with self._lock:
          self._last_tsdb_ts = cursor
      else:
        # Neither delivered nor spooled (spool off or unwritable): the
        # batch is gone but its tsdb points still sit in the ring —
        # leave the cursor so the next tick re-ships them for free.
        # Only the alert edges are truly lost, counted here.
        with self._lock:
          self.spool_dropped += 1
    except Exception:  # noqa: BLE001 - shipping must never kill its thread
      with self._lock:
        self.tick_errors += 1

  def _loop(self) -> None:
    while not self._stop.wait(self.config.interval_s):
      self.tick()

  def start(self) -> "TelemetryShipper":
    if self._thread is not None:
      raise RuntimeError("TelemetryShipper already started")
    self._thread = threading.Thread(target=self._loop,
                                    name="mpi-obs-ship", daemon=True)
    self._thread.start()
    return self

  def stop(self) -> None:
    self._stop.set()
    if self._thread is not None:
      self._thread.join(5.0)
      self._thread = None

  # -- introspection -------------------------------------------------------

  def stats(self) -> dict:
    with self._lock:
      return {
          "url": self.config.url,
          "interval_s": self.config.interval_s,
          "batches_shipped": self.batches_shipped,
          "posts": self.posts,
          "post_failures": self.post_failures,
          "retries": self.retries,
          "alert_edges": self.alert_edges,
          "alert_edges_dropped": self.alert_edges_dropped,
          "alert_edges_pending": len(self._pending_alerts),
          "incident_bundles": self.incident_bundles,
          "incident_bundles_dropped": self.incident_bundles_dropped,
          "incident_bundles_pending": len(self._pending_incidents),
          "segments_shipped": self.segments_shipped,
          "segments_dropped": self.segments_dropped,
          "segment_errors": self.segment_errors,
          "spooled": self.spooled,
          "spool_dropped": self.spool_dropped,
          "spool_files": self._spool_file_count,
          "spool_bytes": self._spool_bytes,
          "tick_errors": self.tick_errors,
      }


def registry(stats: dict | None) -> prom.Registry:
  """The ``mpi_obs_ship_*`` families (zeros while shipping is off — the
  always-exposed convention)."""
  stats = stats or {}
  reg = prom.Registry()
  p = PREFIX
  reg.counter(p + "batches_total", "Telemetry batches delivered to the "
              "sink (spool replays included).",
              stats.get("batches_shipped", 0))
  reg.counter(p + "posts_total", "HTTP POST attempts against the sink.",
              stats.get("posts", 0))
  reg.counter(p + "failures_total",
              "POST attempts that failed (transport error or non-2xx).",
              stats.get("post_failures", 0))
  reg.counter(p + "retries_total",
              "Backoff retries inside delivery arcs.",
              stats.get("retries", 0))
  reg.counter(p + "alert_edges_total", "SLO alert edges queued for "
              "shipping.", stats.get("alert_edges", 0))
  reg.counter(p + "alert_edges_dropped_total",
              "Alert edges dropped from the pending ring while the sink "
              "was down.", stats.get("alert_edges_dropped", 0))
  reg.counter(p + "incident_bundles_total",
              "Incident bundles queued for shipping (obs.incident).",
              stats.get("incident_bundles", 0))
  reg.counter(p + "incident_bundles_dropped_total",
              "Incident bundles dropped from the pending ring (the "
              "recorder's disk ring still holds them).",
              stats.get("incident_bundles_dropped", 0))
  reg.counter(p + "segments_shipped_total",
              "Rotated event-log segments delivered and deleted locally.",
              stats.get("segments_shipped", 0))
  reg.counter(p + "segments_dropped_total",
              "Claimed segments dropped past the claim-backlog bound "
              "during a long sink outage.",
              stats.get("segments_dropped", 0))
  reg.counter(p + "spooled_total",
              "Batches written to the disk spool during sink outages.",
              stats.get("spooled", 0))
  reg.counter(p + "spool_dropped_total",
              "Batches dropped past the spool byte budget (or with the "
              "spool disabled).", stats.get("spool_dropped", 0))
  reg.counter(p + "segment_errors_total",
              "Segment reads/deletes that failed (I/O).",
              stats.get("segment_errors", 0))
  reg.counter(p + "tick_errors_total",
              "Shipping cycles that raised (the never-fatal backstop — "
              "a climbing value means the shipper is broken, not the "
              "sink).", stats.get("tick_errors", 0))
  reg.gauge(p + "spool_bytes", "Bytes waiting in the disk spool.",
            stats.get("spool_bytes", 0))
  reg.gauge(p + "spool_files", "Batches waiting in the disk spool.",
            stats.get("spool_files", 0))
  return reg


class _SinkHandler(BaseHTTPRequestHandler):
  """The collector side of the shipping contract: accept one POSTed
  JSON batch, durably write it to the sink directory (temp file +
  atomic rename, the repo-wide publish idiom), and only then answer
  2xx — the shipper deletes segments on 2xx, so an early OK would be
  the one way this pipeline could lose telemetry."""

  def __init__(self, sink: "ShipSink", *args, **kwargs):
    self.sink = sink
    super().__init__(*args, **kwargs)

  def log_message(self, fmt, *args):  # noqa: A003 - stdlib signature
    pass

  def _send(self, body: bytes, status: int = 200) -> None:
    try:
      self.send_response(status)
      self.send_header("Content-Type", "application/json")
      self.send_header("Content-Length", str(len(body)))
      self.end_headers()
      self.wfile.write(body)
    except (BrokenPipeError, ConnectionResetError):
      self.close_connection = True

  def do_GET(self):  # noqa: N802 - stdlib name
    if self.path == "/healthz":
      self._send(json.dumps({"status": "ok", "role": "ship-sink",
                             **self.sink.stats()}).encode())
    elif self.path == "/stats":
      self._send(json.dumps(self.sink.stats()).encode())
    else:
      self._send(json.dumps({"error": f"unknown path {self.path}"}).encode(),
                 status=404)

  def do_POST(self):  # noqa: N802 - stdlib name
    try:
      length = int(self.headers.get("Content-Length", "0"))
    except ValueError:
      self._send(json.dumps({"error": "bad Content-Length"}).encode(),
                 status=400)
      return
    if length <= 0 or length > self.sink.max_body_bytes:
      self._send(json.dumps(
          {"error": f"body must be 1..{self.sink.max_body_bytes} "
                    "bytes"}).encode(), status=413 if length > 0 else 400)
      return
    body = self.rfile.read(length)
    try:
      json.loads(body)
    except ValueError:
      self.sink.note_reject()
      self._send(json.dumps({"error": "body is not JSON"}).encode(),
                 status=400)
      return
    try:
      path = self.sink.accept(body)
    except OSError as e:
      # Disk trouble must read as a delivery failure so the shipper
      # retries/spools — a 2xx here would delete the only copy.
      self._send(json.dumps({"error": f"sink write failed: {e}"}).encode(),
                 status=503)
      return
    self._send(json.dumps({"ok": True, "stored": os.path.basename(path)})
               .encode())


class ShipSink:
  """A directory-backed batch store for the collector CLI (`ship-sink`).

  Each accepted batch lands as ``batch-NNNNNNNN.json`` (monotonic
  sequence, atomic rename). Resuming over an existing directory
  continues the numbering after the highest resident file, so restarts
  never overwrite delivered telemetry.
  """

  def __init__(self, directory: str, max_body_bytes: int = 8 << 20):
    self.directory = os.path.abspath(directory)
    os.makedirs(self.directory, exist_ok=True)
    self.max_body_bytes = int(max_body_bytes)
    self._lock = threading.Lock()
    self.received = 0
    self.rejected = 0
    self.bytes_received = 0
    seqs = [int(m.group(1)) for m in
            (re.match(r"batch-(\d+)\.json$", name)
             for name in os.listdir(self.directory)) if m]
    self._seq = max(seqs, default=0)

  def note_reject(self) -> None:
    with self._lock:
      self.rejected += 1

  def accept(self, body: bytes) -> str:
    """Durably store one batch; returns its path (raises OSError on
    disk failure — the handler maps that to a retryable 503)."""
    with self._lock:
      self._seq += 1
      seq = self._seq
    path = os.path.join(self.directory, f"batch-{seq:08d}.json")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
      f.write(body)
      f.flush()
      os.fsync(f.fileno())
    os.replace(tmp, path)
    with self._lock:
      self.received += 1
      self.bytes_received += len(body)
    return path

  def stats(self) -> dict:
    with self._lock:
      return {"dir": self.directory, "received": self.received,
              "rejected": self.rejected,
              "bytes_received": self.bytes_received,
              "next_seq": self._seq + 1}


def make_sink_server(directory: str, host: str = "127.0.0.1",
                     port: int = 0) -> "tuple[ThreadingHTTPServer, ShipSink]":
  """A ready-to-``serve_forever`` threaded collector for the shipper's
  POSTed batches (the ``ship-sink`` CLI's engine). Port 0 = ephemeral;
  the bound port is ``server.server_address[1]``."""
  sink = ShipSink(directory)
  handler = functools.partial(_SinkHandler, sink)
  server = ThreadingHTTPServer((host, port), handler)
  server.daemon_threads = True
  return server, sink
