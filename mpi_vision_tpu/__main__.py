"""``python -m mpi_vision_tpu`` — see cli.py."""

import sys

from mpi_vision_tpu.cli import main

sys.exit(main())
