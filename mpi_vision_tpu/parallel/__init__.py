"""Mesh parallelism: DP view rendering, plane-sharded composite, placement."""

from mpi_vision_tpu.parallel.mesh import (
    make_mesh,
    over_composite_planes_sharded,
    render_views_sharded,
    replicate,
    shard_batch,
)
