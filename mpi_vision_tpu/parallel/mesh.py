"""Device-mesh parallelism for MPI rendering and compositing.

The reference is single-GPU (SURVEY.md §2: no torch.distributed, no NCCL —
"mpi" means multi-plane image). Scaling on TPU is therefore new capability
designed mesh-first, the standard JAX way: build a ``jax.sharding.Mesh``,
annotate shardings, and let ``shard_map`` + XLA collectives place the
communication on ICI.

Two parallel axes exist in the workload (SURVEY.md §5.7):

  * **views** — embarrassingly parallel. ``render_views_sharded`` shards a
    batch of target poses over the ``data`` mesh axis with the MPI
    replicated; zero cross-chip traffic inside the render.
  * **planes** — the over-composite is a scan over planes, but each plane is
    an affine map ``out -> rgb*a + (1-a)*out`` and affine maps compose
    associatively (core/compose.py). ``over_composite_planes_sharded``
    shards planes across the ``planes`` axis: every device folds its local
    planes into ONE (A, B) pair, pairs are all-gathered (tiny: 4 channels x
    pixels per device), and the ordered fold finishes locally. This is the
    long-axis / sequence-parallel analogue for MPIs — the plane axis plays
    the role sequence length plays in ring attention.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi_vision_tpu.compat import shard_map

from mpi_vision_tpu.core import compose, render
from mpi_vision_tpu.core.sampling import Convention


def make_mesh(axis_names: tuple[str, ...] = ("data",),
              shape: tuple[int, ...] | None = None,
              devices=None) -> Mesh:
  """A device mesh over all (or the given) devices.

  Defaults to a 1-D ``('data',)`` mesh across every visible device; pass
  ``shape`` for multi-axis layouts, e.g. ``axis_names=('data', 'planes'),
  shape=(2, 4)``.
  """
  devices = jax.devices() if devices is None else devices
  if shape is None:
    shape = (len(devices),) + (1,) * (len(axis_names) - 1)
  arr = np.asarray(devices).reshape(shape)
  return Mesh(arr, axis_names)


def render_views_sharded(
    rgba_layers: jnp.ndarray,
    tgt_poses: jnp.ndarray,
    depths: jnp.ndarray,
    intrinsics: jnp.ndarray,
    mesh: Mesh,
    axis: str = "data",
    convention: Convention = Convention.REF_HOMOGRAPHY,
    method: str = "fused",
    tgt_intrinsics: jnp.ndarray | None = None,
    out_hw: tuple[int, int] | None = None,
    **render_kwargs,
) -> jnp.ndarray:
  """Render a batch of V target views, views sharded over a mesh axis.

  The MPI (one scene) is replicated; each device renders ``V / n_devices``
  views independently — the BASELINE config-4 layout (64 views over a DP
  mesh). V must be divisible by the axis size.

  Args:
    rgba_layers: ``[H, W, P, 4]`` single-scene MPI, back-to-front.
    tgt_poses: ``[V, 4, 4]`` source-cam -> target-cam transforms.
    depths: ``[P]`` descending plane depths.
    intrinsics: ``[3, 3]`` shared camera intrinsics.
    **render_kwargs: forwarded to ``core.render.render_mpi``. For
      ``method='fused_pallas'`` the poses are tracers inside shard_map, so
      kernel plans must come from OUTSIDE: with concrete ``tgt_poses`` and
      no explicit plan this function plans the whole pose set eagerly
      (``kernels.render_pallas.plan_fused``) and forwards the bundle
      (check=False + separable/plan/adj_plan); a pose set outside the
      kernel envelope raises (pass an XLA ``method`` for those). Traced
      pose batches keep requiring the caller's explicit plan.

  Returns:
    ``[V, H, W, 3]`` rendered views, sharded over ``axis``.
  """
  n = mesh.shape[axis]
  v = tgt_poses.shape[0]
  if v % n:
    raise ValueError(f"view count {v} not divisible by mesh axis {axis}={n}")

  # Auto-plan only when the caller supplied NO fused-kernel knobs (an
  # explicit adj_plan=None — the keep-the-XLA-backward escape hatch — or
  # separable/check must never be silently overridden).
  if (method == "fused_pallas"
      and not {"plan", "adj_plan", "separable", "check"} & set(render_kwargs)):
    from mpi_vision_tpu.kernels import render_pallas

    h, w = rgba_layers.shape[0], rgba_layers.shape[1]
    homs = render_pallas.pixel_homographies(
        jnp.asarray(tgt_poses), jnp.asarray(depths),
        jnp.broadcast_to(jnp.asarray(intrinsics)[None],
                         (v, 3, 3)), h, w, convention)      # [P, V, 3, 3]
    if isinstance(homs, jax.core.Tracer):
      # Poses/depths/intrinsics traced: plans must come from the caller.
      raise ValueError(
          "render_views_sharded(method='fused_pallas') under jit needs an "
          "explicit plan_fused bundle (check=False + separable/plan/"
          "adj_plan) — traced inputs cannot be planned here")
    bundle = render_pallas.plan_fused(jnp.moveaxis(homs, 1, 0), h, w)
    if bundle is None:
      raise ValueError(
          "pose set outside the fused-kernel envelope; use an XLA method "
          "(method='fused'|'scan') for this batch")
    render_kwargs = dict(render_kwargs, check=False,
                         separable=bundle["separable"],
                         plan=bundle["plan"], adj_plan=bundle["adj_plan"])

  # Tile-cropped sources (serve/tiles.py): the crop-corrected source
  # intrinsics ride in `intrinsics`, the original camera here, and the
  # rendered frame keeps the full target dims. Both replicate like the
  # source intrinsics; None defaults preserve the historical behavior.
  tgt_k = intrinsics if tgt_intrinsics is None else tgt_intrinsics

  def local_render(mpi, poses, k, k_t):
    # mpi [1, H, W, P, 4] (replicated), poses [V/n, 4, 4].
    kw = dict(render_kwargs)
    if tgt_intrinsics is not None or out_hw is not None:
      # Only the cropped path threads these through: fused_pallas (which
      # rejects them) and the historical call shapes stay untouched.
      kw.update(tgt_intrinsics=k_t.reshape(3, 3), out_hw=out_hw)
    return render.render_views(mpi[0], poses, depths, k.reshape(3, 3),
                               convention=convention, method=method, **kw)

  # fused_pallas only: pallas_call outputs don't carry the vma metadata the
  # checker needs (each shard's render is fully local, so nothing is lost);
  # every XLA method keeps the replication checker on.
  fn = shard_map(
      local_render, mesh=mesh,
      in_specs=(P(), P(axis), P(), P()),
      out_specs=P(axis), check_vma=(method != "fused_pallas"))
  return fn(rgba_layers[None], tgt_poses, intrinsics, tgt_k)


def _fold_plane_shard(shard: jnp.ndarray, axis: str, n: int) -> jnp.ndarray:
  """Composite a device's plane shard, finishing across ``axis``.

  Inside shard_map: ``shard [P/n, ..., 4]`` back-to-front. Only the GLOBAL
  index-0 plane (axis_index == 0) gets the reference's first-opaque
  treatment; the shard folds to one affine (A, B) pair via
  ``associative_scan``, the tiny pairs are all-gathered over ``axis``
  (the only cross-device traffic: 4/3-channel images), and the ordered
  fold finishes locally. Shared by the 1-D and 2-D mesh composites so the
  first-plane/fold-order semantics cannot drift between them.
  """
  first = jax.lax.axis_index(axis) == 0
  coeff, offset = compose.plane_affine(shard, first_opaque=False)
  coeff = jnp.where(first, coeff.at[0].set(0.0), coeff)
  offset = jnp.where(first, offset.at[0].set(shard[0, ..., :3]), offset)
  a, b = jax.lax.associative_scan(compose.combine_affine, (coeff, offset),
                                  axis=0)
  a, b = a[-1], b[-1]                       # this shard as ONE affine map
  a_all = jax.lax.all_gather(a, axis)       # [n, ..., 1]
  b_all = jax.lax.all_gather(b, axis)       # [n, ..., 3]
  out = b_all[0]
  for i in range(1, n):                     # ordered fold, n is tiny
    out = b_all[i] + a_all[i] * out
  return out


def over_composite_planes_sharded(
    rgba: jnp.ndarray,
    mesh: Mesh,
    axis: str = "planes",
) -> jnp.ndarray:
  """Back-to-front composite with the plane axis sharded across devices.

  ``rgba``: ``[P, ..., 4]`` back-to-front; the axis size must divide P.
  Same contract as ``core.compose.over_composite`` (farthest plane's alpha
  ignored). O(P/n) local work + one all-gather of 4/3-channel images
  (see ``_fold_plane_shard``).
  """
  p = rgba.shape[0]
  n = mesh.shape[axis]
  if p % n:
    raise ValueError(f"plane count {p} not divisible by mesh axis {axis}={n}")

  # check_vma=False: the ordered fold after the all_gather yields the same
  # value on every device, but shard_map cannot infer that replication.
  fn = shard_map(lambda shard: _fold_plane_shard(shard, axis, n),
                 mesh=mesh, in_specs=(P(axis),), out_specs=P(),
                 check_vma=False)
  return fn(rgba)


def replicate(x, mesh: Mesh):
  """Place a pytree fully replicated on ``mesh``."""
  sharding = NamedSharding(mesh, P())
  return jax.tree.map(lambda a: jax.device_put(a, sharding), x)


def batch_spec(a, mesh: Mesh, axis: str = "data") -> P:
  """Partition spec for one batch leaf.

  Rank >= 2 leaves with a divisible leading dim are batch-sharded; rank <= 1
  leaves are treated as shared per-scene constants (``mpi_planes [P]``) and
  replicated — a divisibility test alone would mis-shard such constants
  whenever P happens to divide the device count. Known limitation: a genuine
  rank-1 per-sample leaf (e.g. scalar labels ``[B]``) is also replicated.
  """
  shardable = getattr(a, "ndim", 0) >= 2 and a.shape[0] % mesh.shape[axis] == 0
  return P(axis) if shardable else P()


def shard_batch(x, mesh: Mesh, axis: str = "data"):
  """Place a pytree with its leading dim sharded over ``axis`` (leaves that
  don't divide the axis size are replicated — see ``batch_spec``)."""
  return jax.tree.map(
      lambda a: jax.device_put(a, NamedSharding(mesh, batch_spec(a, mesh, axis))),
      x)


def render_views_planes_sharded(
    rgba_layers: jnp.ndarray,
    tgt_poses: jnp.ndarray,
    depths: jnp.ndarray,
    intrinsics: jnp.ndarray,
    mesh: Mesh,
    view_axis: str = "data",
    plane_axis: str = "planes",
    convention: Convention = Convention.REF_HOMOGRAPHY,
) -> jnp.ndarray:
  """Render a view batch on a 2-D (views x planes) mesh.

  The combined layout of the two parallel axes (the DP + sequence-parallel
  analog for MPIs): views shard over ``view_axis`` exactly as in
  ``render_views_sharded``, while the PLANE axis — the depth scan the
  composite is sequential over — shards over ``plane_axis``. Each device
  warps only its local plane shard for its local views, folds those planes
  into ONE affine (A, B) pair (``core.compose.plane_affine`` /
  ``associative_scan``), and a single tiny ``all_gather`` of the pairs
  over ``plane_axis`` (4 channels x pixels per device — the only
  cross-chip traffic) finishes the ordered fold locally, as in
  ``over_composite_planes_sharded``.

  ``rgba_layers``: ``[H, W, P, 4]`` back-to-front; ``tgt_poses``
  ``[V, 4, 4]``; ``depths`` ``[P]`` descending; ``intrinsics`` ``[3, 3]``.
  The mesh axis sizes must divide V and P respectively. Returns
  ``[V, H, W, 3]`` sharded over ``view_axis``.
  """
  n_v, n_p = mesh.shape[view_axis], mesh.shape[plane_axis]
  v, p = tgt_poses.shape[0], rgba_layers.shape[2]
  if v % n_v or p % n_p:
    raise ValueError(
        f"views {v} / planes {p} not divisible by mesh axes "
        f"{view_axis}={n_v} / {plane_axis}={n_p}")

  def local(mpi, poses, k, dep):
    # mpi [H, W, P/np, 4]; poses [V/nv, 4, 4]; dep [P/np].
    vn = poses.shape[0]
    planes = jnp.moveaxis(mpi, 2, 0)[:, None]              # [P/np,1,H,W,4]
    planes = jnp.broadcast_to(planes, planes.shape[:1] + (vn,)
                              + planes.shape[2:])
    warped = render.warp_planes(planes, poses, dep,
                                jnp.broadcast_to(k[None], (vn, 3, 3)),
                                convention=convention)     # [P/np,V/nv,H,W,4]
    return _fold_plane_shard(warped, plane_axis, n_p)      # [V/nv, H, W, 3]

  # check_vma=False: as in over_composite_planes_sharded, the post-gather
  # fold replicates over the plane axis in value but not in inferred vma.
  fn = shard_map(
      local, mesh=mesh,
      in_specs=(P(None, None, plane_axis), P(view_axis), P(),
                P(plane_axis)),
      out_specs=P(view_axis), check_vma=False)
  return fn(rgba_layers, tgt_poses, intrinsics, depths)
