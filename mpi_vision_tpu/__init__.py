"""mpi_vision_tpu — a TPU-native multi-plane-image framework.

JAX/XLA/Pallas re-design of the capabilities of Findeton/mpi-vision (a torch
port of Google's Stereo Magnification): differentiable MPI rendering via
plane-induced homographies and plane-sweep cost volumes, with the
stereo-magnification U-Net + VGG-perceptual training pipeline, data loading,
mesh-parallel batched rendering, and DeepView HTML viewer export built on
top. Subpackages: ``kernels`` (fused Pallas render, forward and backward),
``models``, ``train``, ``data``, ``parallel``, ``viewer``, ``torchref`` (the
CPU-torch parity oracle), and ``compat`` (the reference's star-import
surface under original names with ``backend={'jax','torch'}``). The core
function surface is re-exported below.
"""

from mpi_vision_tpu.core.camera import (
    crop_image_and_adjust_intrinsics,
    crop_to_bounding_box,
    deprocess_image,
    depth_to_space,
    intrinsics_matrix,
    inv_depths,
    preprocess_image,
    scale_intrinsics,
    space_to_depth,
)
from mpi_vision_tpu.core.compose import over_composite
from mpi_vision_tpu.core.geometry import (
    apply_homography,
    from_homogeneous,
    homogeneous_grid,
    inverse_homography,
    relative_pose,
    safe_divide,
)
from mpi_vision_tpu.core.render import plane_homographies, render_mpi, warp_planes
from mpi_vision_tpu.core.sampling import Convention, bilinear_sample
from mpi_vision_tpu.core.sweep import (
    cam2pixel,
    format_network_input,
    pixel2cam,
    plane_sweep,
    plane_sweep_one,
    projective_inverse_warp,
    projective_pixel_transform,
)
from mpi_vision_tpu.data.realestate import open_image, resize_with_intrinsics

__version__ = "0.1.0"
