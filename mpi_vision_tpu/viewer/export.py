"""MPI viewer export: layer PNGs + a self-contained CSS-3D HTML viewer.

Reference behavior (notebook cell 18 + deepview-mpi-viewer-template.html):
RGBA layers in [-1, 1] are rescaled to [0, 1] (alpha passed through), saved
as PNGs, base64-embedded into an HTML page that renders the MPI with CSS
``preserve-3d`` transforms — layers spaced uniformly in inverse depth with
index 0 farthest, each pre-scaled so the stack aligns exactly when viewed
head-on and produces parallax under pose changes.

The HTML here is an original implementation of that behavior (not a copy of
the reference template): a ``perspective: f px`` stage whose focal length is
``0.5 * w / tan(fov/2)`` (the reference's focal model, template:304), layers
at ``translateZ(-z) scale((f+z)/f)`` with ``z = f * (d/d_near - 1)``, and
pointer controls — move for parallax, drag to rotate, shift-drag to
translate, wheel to dolly, digit keys to inspect single layers, ``a`` for
alpha view.
"""

from __future__ import annotations

import base64
import io
import os
from typing import Sequence

import numpy as np

_HTML_TEMPLATE = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>MPI viewer — mpi_vision_tpu</title>
<style>
  html, body { margin: 0; background: #111; height: 100%; overflow: hidden;
               font: 12px monospace; color: #ccc; }
  #stage { position: absolute; inset: 0; display: flex;
           align-items: center; justify-content: center; }
  #frustum { position: relative; transform-style: preserve-3d; }
  .layer { position: absolute; left: 0; top: 0; width: 100%; height: 100%;
           transform-style: preserve-3d; backface-visibility: hidden;
           pointer-events: none; }
  .alpha .layer img { filter: grayscale(1) contrast(0); }
  #hud { position: fixed; left: 8px; bottom: 8px; opacity: .7;
         user-select: none; }
</style>
</head>
<body>
<div id="stage"><div id="frustum"></div></div>
<div id="hud">drag: rotate · shift-drag: pan · wheel: dolly ·
1-9/0: solo layer · a: alpha · r: reset</div>
<script>
"use strict";
const mpiSources = __MPI_SOURCES__;
const cfg = { w: __W__, h: __H__, near: __NEAR__, far: __FAR__,
              fov: __FOV__ };

const focal = 0.5 * cfg.w / Math.tan(cfg.fov * Math.PI / 360);
const P = mpiSources.length;
// Inverse-depth uniform spacing, index 0 = farthest (matches inv_depths).
const depths = [];
for (let i = 0; i < P; i++) {
  const inv = 1 / cfg.far + (1 / cfg.near - 1 / cfg.far) * (P > 1 ? i / (P - 1) : 1);
  depths.push(1 / inv);
}

const frustum = document.getElementById("frustum");
const stage = document.getElementById("stage");
frustum.style.width = cfg.w + "px";
frustum.style.height = cfg.h + "px";
stage.style.perspective = focal + "px";

const layers = [];
for (let i = 0; i < P; i++) {
  const div = document.createElement("div");
  div.className = "layer";
  const img = document.createElement("img");
  img.src = mpiSources[i];
  img.style.width = "100%"; img.style.height = "100%";
  div.appendChild(img);
  // z grows with scene depth relative to the nearest layer; (f+z)/f undoes
  // the perspective shrink so the stack aligns exactly head-on.
  const z = focal * (depths[i] / depths[P - 1] - 1);
  div.style.transform =
      `translateZ(${-z}px) scale(${(focal + z) / focal})`;
  div.dataset.z = z;
  frustum.appendChild(div);
  layers.push(div);
}

// Drag rotation accumulates into `base`; hover parallax is a small
// additive offset on top, so releasing a drag never snaps the view back.
const base = { rx: 0, ry: 0, tx: 0, ty: 0, tz: 0 };
const hover = { rx: 0, ry: 0 };
let solo = -1, dragging = false, lastX = 0, lastY = 0;

function apply() {
  frustum.style.transform =
      `translate3d(${base.tx}px, ${base.ty}px, ${base.tz}px) ` +
      `rotateX(${base.rx + hover.rx}deg) rotateY(${base.ry + hover.ry}deg)`;
  layers.forEach((l, i) =>
      l.style.opacity = (solo < 0 || solo === i) ? 1 : 0.04);
}

window.addEventListener("pointerdown", e => {
  dragging = true; lastX = e.clientX; lastY = e.clientY;
});
window.addEventListener("pointerup", () => dragging = false);
window.addEventListener("pointermove", e => {
  if (dragging) {
    if (e.shiftKey) {
      base.tx += e.clientX - lastX; base.ty += e.clientY - lastY;
    } else {
      base.ry += (e.clientX - lastX) * 0.15;
      base.rx -= (e.clientY - lastY) * 0.15;
    }
    lastX = e.clientX; lastY = e.clientY;
  } else {
    hover.ry = (e.clientX / innerWidth - 0.5) * 6;
    hover.rx = -(e.clientY / innerHeight - 0.5) * 6;
  }
  apply();
});
window.addEventListener("wheel", e => {
  base.tz -= e.deltaY * 0.5; apply();
});
window.addEventListener("keydown", e => {
  if (e.key >= "0" && e.key <= "9") {
    const k = e.key === "0" ? 9 : +e.key - 1;
    solo = (k < P && solo !== k) ? k : -1;
  } else if (e.key === "a") {
    document.body.classList.toggle("alpha");
  } else if (e.key === "r") {
    Object.assign(base, { rx: 0, ry: 0, tx: 0, ty: 0, tz: 0 }); solo = -1;
  }
  apply();
});
apply();
</script>
</body>
</html>
"""


def layer_to_png_bytes(rgba: np.ndarray) -> bytes:
  """One ``[H, W, 4]`` RGBA layer in [-1, 1] -> PNG bytes.

  RGB is rescaled [-1, 1] -> [0, 1]; alpha is passed through as-is (already
  (0, 1) from the MPI assembly) — the reference's ``save_image`` (cell 18).
  """
  from PIL import Image

  rgb = np.rint(
      np.clip((rgba[..., :3] + 1.0) / 2.0, 0, 1) * 255).astype(np.uint8)
  a = np.rint(np.clip(rgba[..., 3:], 0, 1) * 255).astype(np.uint8)
  buf = io.BytesIO()
  Image.fromarray(np.concatenate([rgb, a], -1), "RGBA").save(buf, "PNG")
  return buf.getvalue()


def save_layer_pngs(rgba_layers: np.ndarray, out_dir: str,
                    prefix: str = "mpi") -> list[str]:
  """Save ``[H, W, P, 4]`` layers as ``<prefix>00.png ...`` (cell 18)."""
  os.makedirs(out_dir, exist_ok=True)
  paths = []
  for i in range(rgba_layers.shape[2]):
    path = os.path.join(out_dir, f"{prefix}{i:02d}.png")
    with open(path, "wb") as f:
      f.write(layer_to_png_bytes(np.asarray(rgba_layers[:, :, i])))
    paths.append(path)
  return paths


def to_data_uri(png_bytes: bytes) -> str:
  return "data:image/png;base64," + base64.b64encode(png_bytes).decode()


def export_viewer_html(rgba_layers: np.ndarray, out_path: str,
                       near: float = 1.0, far: float = 100.0,
                       fov_deg: float = 60.0) -> str:
  """Write a self-contained HTML MPI viewer for ``[H, W, P, 4]`` layers.

  ``near``/``far`` must match the plane depths the MPI was built with
  (``inv_depths(near, far, P)``, index 0 farthest); ``fov_deg`` sets the
  CSS focal length. Returns ``out_path``.
  """
  rgba_layers = np.asarray(rgba_layers)
  h, w, p, _ = rgba_layers.shape
  uris = [to_data_uri(layer_to_png_bytes(rgba_layers[:, :, i]))
          for i in range(p)]
  html = (_HTML_TEMPLATE
          .replace("__MPI_SOURCES__",
                   "[" + ",".join(f'"{u}"' for u in uris) + "]")
          .replace("__W__", str(w)).replace("__H__", str(h))
          .replace("__NEAR__", repr(float(near)))
          .replace("__FAR__", repr(float(far)))
          .replace("__FOV__", repr(float(fov_deg))))
  with open(out_path, "w") as f:
    f.write(html)
  return out_path


def load_fixture_mpi(test_dir: str, prefix: str = "rgba_",
                     count: int | None = None) -> np.ndarray:
  """Load a baked PNG MPI (e.g. the reference's ``test/rgba_00..09.png``)
  into ``[H, W, P, 4]`` in [-1, 1] (alpha in (0, 1))."""
  from PIL import Image

  names = sorted(n for n in os.listdir(test_dir)
                 if n.startswith(prefix) and n.endswith(".png"))
  if count is not None:
    names = names[:count]
  layers = []
  for n in names:
    arr = np.asarray(
        Image.open(os.path.join(test_dir, n)).convert("RGBA"),
        np.float32) / 255.0
    layers.append(np.concatenate([arr[..., :3] * 2.0 - 1.0, arr[..., 3:]], -1))
  return np.stack(layers, axis=2)
