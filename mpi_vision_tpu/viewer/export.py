"""MPI viewer export: layer PNGs + a self-contained CSS-3D HTML viewer.

Reference behavior (notebook cell 18 + deepview-mpi-viewer-template.html):
RGBA layers in [-1, 1] are rescaled to [0, 1] (alpha passed through), saved
as PNGs, base64-embedded into an HTML page that renders the MPI with CSS
``preserve-3d`` transforms — layers spaced uniformly in inverse depth with
index 0 farthest, each pre-scaled so the stack aligns exactly when viewed
head-on and produces parallax under pose changes.

The HTML here is an original implementation of that behavior (not a copy of
the reference template): a ``perspective: f px`` stage whose focal length is
``0.5 * w / tan(fov/2)`` (the reference's focal model, template:304), layers
at ``translateZ(-z) scale((f+z)/f)`` with ``z = f * (d/d_near - 1)``, and
pointer controls — move for parallax, drag to rotate, shift-drag to
translate, wheel to dolly, digit keys to inspect single layers, ``a`` for
alpha view.

Inspection/motion features matching the reference template's surface:
depth-colormap modes (``d`` cycles off/turbo/magma — procedural colormaps
tinting each layer through its own alpha mask; template:220-267), sway and
wander auto-motion (``s``/``w``; template:488-495, 620-639), a clickable
per-layer minis bar with solo/under/over selection (``[``/``]``, ``m``;
template:506-598), and URL parameters — ``url``/``n`` load an external
``mpi$$.png`` sequence instead of the embedded MPI, plus
``near``/``far``/``fov``/``move``/``depth``/``mini``/``solo`` overrides
(template:641-686).
"""

from __future__ import annotations

import base64
import io
import os
from typing import Sequence

import numpy as np

_HTML_TEMPLATE = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>MPI viewer — mpi_vision_tpu</title>
<style>
  html, body { margin: 0; background: #111; height: 100%; overflow: hidden;
               font: 12px monospace; color: #ccc; }
  #stage { position: absolute; inset: 0; display: flex;
           align-items: center; justify-content: center; }
  #frustum { position: relative; transform-style: preserve-3d; }
  .layer { position: absolute; left: 0; top: 0; width: 100%; height: 100%;
           transform-style: preserve-3d; backface-visibility: hidden;
           pointer-events: none; }
  .layer .tint { position: absolute; inset: 0; display: none; }
  .depthmap .layer img { visibility: hidden; }
  .depthmap .layer .tint { display: block; }
  .alpha .layer img { filter: grayscale(1) contrast(0); }
  /* Excluded-layer silhouettes (the reference's white/black feColorMatrix
     inspection filters, template:693-698): keep the alpha shape, flatten
     the RGB to black or white. */
  body.silh-black .layer.excluded img { filter: brightness(0); }
  body.silh-white .layer.excluded img { filter: brightness(0) invert(1); }
  #hud { position: fixed; left: 8px; bottom: 8px; opacity: .7;
         user-select: none; }
  #minis { position: fixed; right: 8px; top: 8px; bottom: 8px; width: 96px;
           overflow-y: auto; display: flex; flex-direction: column;
           gap: 4px; }
  #minis img { width: 100%; border: 1px solid #333; cursor: pointer;
               background: #222; }
  #minis img.sel { border-color: #fc0; }
  body.nominis #minis { display: none; }
</style>
</head>
<body>
<div id="stage"><div id="frustum"></div></div>
<div id="minis"></div>
<div id="hud">drag: rotate · shift-drag: pan · wheel: dolly ·
1-9/0: solo · [: under · ]: over · x: dim/black/white others ·
a: alpha · d: depth map · s: sway · w: wander · m: minis · r: reset</div>
<script>
"use strict";
const embeddedSources = __MPI_SOURCES__;
const cfg = { w: __W__, h: __H__, near: __NEAR__, far: __FAR__,
              fov: __FOV__, move: "none", depth: 0, mini: 1, solo: -1 };

// ---- URL parameters: viewing config + external mpi$$.png sequences -----
// ?url=lores/scene/rgba_$$.png&n=10 loads an external MPI instead of the
// embedded one ($$ -> zero-padded index); near/far/fov/move/depth/mini/solo
// override the embedded defaults.
const q = new URLSearchParams(location.search);
let mpiSources = embeddedSources;
if (q.get("url") && q.get("n")) {
  const n = +q.get("n");
  if (Number.isInteger(n) && n > 0) {
    mpiSources = [];
    for (let i = 0; i < n; i++) {
      mpiSources.push(q.get("url").replace("$$", String(i).padStart(2, "0")));
    }
  } else {
    console.warn(`ignoring ?url: n=${q.get("n")} is not a positive integer`);
  }
}
for (const k of ["near", "far", "fov", "depth", "mini", "solo"]) {
  if (q.get(k) !== null) {
    const v = +q.get(k);
    // near/far/fov must be finite AND positive (1/near, tan(fov/2) blow up
    // at 0); a bad value falls back to the embedded default with a warning.
    const ok = Number.isFinite(v)
        && (!["near", "far", "fov"].includes(k) || v > 0);
    if (ok) cfg[k] = v;
    else console.warn(`ignoring ?${k}=${q.get(k)}`);
  }
}
if (q.get("move")) cfg.move = q.get("move");

const focal = 0.5 * cfg.w / Math.tan(cfg.fov * Math.PI / 360);
const P = mpiSources.length;
// Inverse-depth uniform spacing, index 0 = farthest (matches inv_depths).
const depths = [];
for (let i = 0; i < P; i++) {
  const inv = 1 / cfg.far + (1 / cfg.near - 1 / cfg.far) * (P > 1 ? i / (P - 1) : 1);
  depths.push(1 / inv);
}

// ---- depth colormaps (procedural; original implementations) ------------
// turbo: rational-polynomial fit of the published colormap; magma: lerped
// anchor table. t in [0, 1] -> "rgb(...)" (t = 0 far, t = 1 near).
function turbo(t) {
  t = Math.min(1, Math.max(0, t));
  const r = 34.61 + t * (1172.33 + t * (-10793.56 + t * (33300.12 + t * (-38394.49 + t * 14825.05))));
  const g = 23.31 + t * (557.33 + t * (1225.33 + t * (-3574.96 + t * (1073.77 + t * 707.56))));
  const b = 27.2 + t * (3211.1 + t * (-15327.97 + t * (27814.0 + t * (-22569.18 + t * 6838.66))));
  const c = v => Math.round(Math.min(255, Math.max(0, v)));
  return `rgb(${c(r)},${c(g)},${c(b)})`;
}
const MAGMA_ANCHORS = [
  [0, 0, 4], [28, 16, 68], [79, 18, 123], [129, 37, 129], [181, 54, 122],
  [229, 80, 100], [251, 135, 97], [254, 194, 135], [252, 253, 191]];
function magma(t) {
  t = Math.min(1, Math.max(0, t)) * (MAGMA_ANCHORS.length - 1);
  const i = Math.min(MAGMA_ANCHORS.length - 2, Math.floor(t)), f = t - i;
  const mix = (a, b) => Math.round(a + (b - a) * f);
  const lo = MAGMA_ANCHORS[i], hi = MAGMA_ANCHORS[i + 1];
  return `rgb(${mix(lo[0], hi[0])},${mix(lo[1], hi[1])},${mix(lo[2], hi[2])})`;
}
const COLORMAPS = [null, turbo, magma];

const frustum = document.getElementById("frustum");
const stage = document.getElementById("stage");
const minisBar = document.getElementById("minis");
frustum.style.width = cfg.w + "px";
frustum.style.height = cfg.h + "px";
stage.style.perspective = focal + "px";

const layers = [], minis = [];
for (let i = 0; i < P; i++) {
  const div = document.createElement("div");
  div.className = "layer";
  const img = document.createElement("img");
  img.src = mpiSources[i];
  img.style.width = "100%"; img.style.height = "100%";
  div.appendChild(img);
  // Depth-map tint: a colored pane masked by the layer's own alpha.
  const tint = document.createElement("div");
  tint.className = "tint";
  tint.style.maskImage = `url("${mpiSources[i]}")`;
  tint.style.webkitMaskImage = `url("${mpiSources[i]}")`;
  tint.style.maskSize = "100% 100%";
  tint.style.webkitMaskSize = "100% 100%";
  div.appendChild(tint);
  // z grows with scene depth relative to the nearest layer; (f+z)/f undoes
  // the perspective shrink so the stack aligns exactly head-on.
  const z = focal * (depths[i] / depths[P - 1] - 1);
  div.style.transform =
      `translateZ(${-z}px) scale(${(focal + z) / focal})`;
  div.dataset.z = z;
  frustum.appendChild(div);
  layers.push(div);

  // Layer mini: click = solo, shift-click = this-and-under,
  // alt-click = this-and-over; click the selection again to clear.
  const mini = document.createElement("img");
  mini.src = mpiSources[i];
  mini.title = `layer ${i} (depth ${depths[i].toFixed(2)})`;
  mini.addEventListener("click", e => {
    const mode = e.shiftKey ? "under" : (e.altKey ? "over" : "solo");
    if (sel.index === i && sel.mode === mode) {
      sel.index = -1;
    } else {
      sel.index = i; sel.mode = mode;
    }
    apply();
  });
  minisBar.prepend(mini);   // nearest layer on top, like the stack
  minis.push(mini);
}

// Drag rotation accumulates into `base`; hover parallax and the motion
// modes are additive offsets on top, so neither snaps the view back.
const base = { rx: 0, ry: 0, tx: 0, ty: 0, tz: 0 };
const hover = { rx: 0, ry: 0 };
const auto = { rx: 0, ry: 0 };
const sel = { index: cfg.solo, mode: "solo" };
let depthMode = cfg.depth % COLORMAPS.length;
let silhMode = "dim";             // dim | black | white (excluded layers)
let moveMode = cfg.move;          // none | sway | wander
let dragging = false, lastX = 0, lastY = 0;
if (!cfg.mini) document.body.classList.add("nominis");

function visible(i) {
  if (sel.index < 0) return true;
  if (sel.mode === "solo") return i === sel.index;
  if (sel.mode === "under") return i <= sel.index;
  return i >= sel.index;          // over
}

function setSilhMode(mode) {
  silhMode = mode;                // dim | black | white
  document.body.classList.toggle("silh-black", mode === "black");
  document.body.classList.toggle("silh-white", mode === "white");
}

function setDepthMode(mode) {
  // Tint colors depend only on (layer index, mode): set them here once,
  // not in the per-frame apply() path.
  depthMode = mode % COLORMAPS.length;
  document.body.classList.toggle("depthmap", depthMode > 0);
  if (depthMode > 0) {
    layers.forEach((l, i) => {
      const t = P > 1 ? i / (P - 1) : 1;   // 0 = farthest
      l.querySelector(".tint").style.background = COLORMAPS[depthMode](t);
    });
  }
}

function setMoveMode(mode) {
  moveMode = mode;
  if (mode === "none") { auto.rx = auto.ry = 0; }  // no stale swing offset
}

function apply() {
  frustum.style.transform =
      `translate3d(${base.tx}px, ${base.ty}px, ${base.tz}px) ` +
      `rotateX(${base.rx + hover.rx + auto.rx}deg) ` +
      `rotateY(${base.ry + hover.ry + auto.ry}deg)`;
  layers.forEach((l, i) => {
    const vis = visible(i);
    l.classList.toggle("excluded", !vis);
    // Depth-map mode shows tint panes, which the silhouette img filters
    // cannot reach — keep excluded layers dimmed there so the selection
    // stays visible.
    const silh = silhMode !== "dim" && depthMode === 0;
    l.style.opacity = vis ? 1 : (silh ? 1 : 0.04);
  });
  minis.forEach((m, i) => m.classList.toggle("sel",
      sel.index >= 0 && visible(i)));
}

// Motion modes: sway is a gentle fixed-frequency pan; wander is a slow
// two-frequency Lissajous drift over both axes.
function tick(ms) {
  const t = ms / 1000;
  if (moveMode === "sway") {
    auto.ry = 4 * Math.sin(t * 1.1); auto.rx = 0;
  } else if (moveMode === "wander") {
    auto.ry = 3.5 * Math.sin(t * 0.53) + 1.5 * Math.sin(t * 1.31);
    auto.rx = 2.0 * Math.sin(t * 0.71) + 1.0 * Math.cos(t * 0.37);
  } else {
    auto.rx = auto.ry = 0;
  }
  if (moveMode !== "none") apply();
  requestAnimationFrame(tick);
}

window.addEventListener("pointerdown", e => {
  dragging = true; lastX = e.clientX; lastY = e.clientY;
});
window.addEventListener("pointerup", () => dragging = false);
window.addEventListener("pointermove", e => {
  if (dragging) {
    if (e.shiftKey) {
      base.tx += e.clientX - lastX; base.ty += e.clientY - lastY;
    } else {
      base.ry += (e.clientX - lastX) * 0.15;
      base.rx -= (e.clientY - lastY) * 0.15;
    }
    lastX = e.clientX; lastY = e.clientY;
  } else {
    hover.ry = (e.clientX / innerWidth - 0.5) * 6;
    hover.rx = -(e.clientY / innerHeight - 0.5) * 6;
  }
  apply();
});
window.addEventListener("wheel", e => {
  base.tz -= e.deltaY * 0.5; apply();
});
window.addEventListener("keydown", e => {
  if (e.key >= "0" && e.key <= "9") {
    const k = e.key === "0" ? 9 : +e.key - 1;
    if (k < P && !(sel.index === k && sel.mode === "solo")) {
      sel.index = k; sel.mode = "solo";
    } else sel.index = -1;
  } else if (e.key === "[" && sel.index >= 0) {
    sel.mode = "under";
  } else if (e.key === "]" && sel.index >= 0) {
    sel.mode = "over";
  } else if (e.key === "x") {
    setSilhMode(silhMode === "dim" ? "black"
        : (silhMode === "black" ? "white" : "dim"));
  } else if (e.key === "a") {
    document.body.classList.toggle("alpha");
  } else if (e.key === "d") {
    setDepthMode(depthMode + 1);
  } else if (e.key === "s") {
    setMoveMode(moveMode === "sway" ? "none" : "sway");
  } else if (e.key === "w") {
    setMoveMode(moveMode === "wander" ? "none" : "wander");
  } else if (e.key === "m") {
    document.body.classList.toggle("nominis");
  } else if (e.key === "r") {
    Object.assign(base, { rx: 0, ry: 0, tx: 0, ty: 0, tz: 0 });
    sel.index = -1; setDepthMode(0); setMoveMode("none");
    setSilhMode("dim");
  }
  apply();
});
setDepthMode(depthMode);
setMoveMode(moveMode);
apply();
requestAnimationFrame(tick);
</script>
</body>
</html>
"""


def layer_to_png_bytes(rgba: np.ndarray) -> bytes:
  """One ``[H, W, 4]`` RGBA layer in [-1, 1] -> PNG bytes.

  RGB is rescaled [-1, 1] -> [0, 1]; alpha is passed through as-is (already
  (0, 1) from the MPI assembly) — the reference's ``save_image`` (cell 18).
  """
  from PIL import Image

  rgb = np.rint(
      np.clip((rgba[..., :3] + 1.0) / 2.0, 0, 1) * 255).astype(np.uint8)
  a = np.rint(np.clip(rgba[..., 3:], 0, 1) * 255).astype(np.uint8)
  buf = io.BytesIO()
  Image.fromarray(np.concatenate([rgb, a], -1), "RGBA").save(buf, "PNG")
  return buf.getvalue()


def save_layer_pngs(rgba_layers: np.ndarray, out_dir: str,
                    prefix: str = "mpi") -> list[str]:
  """Save ``[H, W, P, 4]`` layers as ``<prefix>00.png ...`` (cell 18)."""
  os.makedirs(out_dir, exist_ok=True)
  paths = []
  for i in range(rgba_layers.shape[2]):
    path = os.path.join(out_dir, f"{prefix}{i:02d}.png")
    with open(path, "wb") as f:
      f.write(layer_to_png_bytes(np.asarray(rgba_layers[:, :, i])))
    paths.append(path)
  return paths


def to_data_uri(png_bytes: bytes) -> str:
  return "data:image/png;base64," + base64.b64encode(png_bytes).decode()


def render_viewer_html(sources: list, w: int, h: int,
                       near: float = 1.0, far: float = 100.0,
                       fov_deg: float = 60.0) -> str:
  """Template the CSS-3D viewer against ``sources`` (one image source
  per plane, index 0 farthest) — data URIs for the self-contained
  export, or plain URLs so a browser pulls each layer through the
  content-addressed asset path (``GET /scene/{id}/viewer``)."""
  return (_HTML_TEMPLATE
          .replace("__MPI_SOURCES__",
                   "[" + ",".join(f'"{u}"' for u in sources) + "]")
          .replace("__W__", str(w)).replace("__H__", str(h))
          .replace("__NEAR__", repr(float(near)))
          .replace("__FAR__", repr(float(far)))
          .replace("__FOV__", repr(float(fov_deg))))


def export_viewer_html(rgba_layers: np.ndarray, out_path: str,
                       near: float = 1.0, far: float = 100.0,
                       fov_deg: float = 60.0) -> str:
  """Write a self-contained HTML MPI viewer for ``[H, W, P, 4]`` layers.

  ``near``/``far`` must match the plane depths the MPI was built with
  (``inv_depths(near, far, P)``, index 0 farthest); ``fov_deg`` sets the
  CSS focal length. Returns ``out_path``.
  """
  rgba_layers = np.asarray(rgba_layers)
  h, w, p, _ = rgba_layers.shape
  uris = [to_data_uri(layer_to_png_bytes(rgba_layers[:, :, i]))
          for i in range(p)]
  html = render_viewer_html(uris, w, h, near=near, far=far,
                            fov_deg=fov_deg)
  with open(out_path, "w") as f:
    f.write(html)
  return out_path


def load_fixture_mpi(test_dir: str, prefix: str = "rgba_",
                     count: int | None = None) -> np.ndarray:
  """Load a baked PNG MPI (e.g. the reference's ``test/rgba_00..09.png``)
  into ``[H, W, P, 4]`` in [-1, 1] (alpha in (0, 1))."""
  from PIL import Image

  names = sorted(n for n in os.listdir(test_dir)
                 if n.startswith(prefix) and n.endswith(".png"))
  if count is not None:
    names = names[:count]
  layers = []
  for n in names:
    arr = np.asarray(
        Image.open(os.path.join(test_dir, n)).convert("RGBA"),
        np.float32) / 255.0
    layers.append(np.concatenate([arr[..., :3] * 2.0 - 1.0, arr[..., 3:]], -1))
  return np.stack(layers, axis=2)
