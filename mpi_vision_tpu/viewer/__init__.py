"""Viewer export: MPI layer PNGs + self-contained CSS-3D HTML viewer."""

from mpi_vision_tpu.viewer.export import (
    export_viewer_html,
    layer_to_png_bytes,
    load_fixture_mpi,
    save_layer_pngs,
    to_data_uri,
)
