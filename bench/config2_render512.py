"""BASELINE config 2: 32-plane 512x512 MPI, 8 novel target poses,
single-chip jit render.

Times the fused Pallas path over an 8-pose orbit (mixed small rotations +
translations — the general kernel, planned per pose) and reports total
novel-view frames/s. Target: the BASELINE.json north star is 30 FPS at
1080p; 512^2 x 32 planes is ~7.8x fewer pixels, so the same per-pixel
budget implies >= 30 FPS here comfortably — the target is kept at 30 FPS
(frames/s, not pixels/s) for comparability.

Usage: python bench/config2_render512.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from _common import emit, log, time_fn

H = W = 512
PLANES = 32
VIEWS = 8
TARGET_FPS = 30.0


def orbit_poses(n: int) -> np.ndarray:
  """n poses on a small orbit: alternating pans/tilts + trucking."""
  poses = []
  for i in range(n):
    ang = np.radians(0.8) * np.sin(2 * np.pi * i / n)
    c, s = np.cos(ang), np.sin(ang)
    pose = np.eye(4, dtype=np.float32)
    if i % 2 == 0:
      pose[:3, :3] = [[c, 0, s], [0, 1, 0], [-s, 0, c]]     # yaw
    else:
      pose[:3, :3] = [[1, 0, 0], [0, c, -s], [0, s, c]]     # pitch
    pose[0, 3] = 0.06 * np.cos(2 * np.pi * i / n)
    pose[2, 3] = -0.04 * np.sin(2 * np.pi * i / n)
    poses.append(pose)
  return np.stack(poses)


def main() -> None:
  import jax
  import jax.numpy as jnp

  from mpi_vision_tpu.core.camera import inv_depths
  from mpi_vision_tpu.kernels import render_pallas as rp

  on_tpu = jax.default_backend() == "tpu"
  # Off-TPU the Pallas kernels run in interpret mode — minutes per frame at
  # 512^2 x 32 — so shrink to a layout-validating dryrun.
  h, w, planes_n = (H, W, PLANES) if on_tpu else (48, 256, 4)
  log(f"backend={jax.default_backend()} config: {h}x{w}x{planes_n}")
  planes = jax.jit(
      lambda k: jax.random.uniform(k, (planes_n, 4, h, w)))(
          jax.random.PRNGKey(0))
  jax.block_until_ready(planes)
  depths = jnp.asarray(np.asarray(inv_depths(1.0, 100.0, planes_n)))
  k = np.array([[0.5 * w, 0, w / 2], [0, 0.5 * w, h / 2], [0, 0, 1]],
               np.float32)
  poses = orbit_poses(VIEWS)
  homs = [
      rp.pixel_homographies(jnp.asarray(p)[None], depths,
                            jnp.asarray(k)[None], h, w)[:, 0]
      for p in poses
  ]
  plans = [rp._plan_shared(hm, h, w) for hm in homs]
  log(f"plans: {plans}")
  if any(p is None for p in plans):
    raise SystemExit("an orbit pose fell out of the kernel envelope")

  def render_all(planes_, homs_):
    return [rp.render_mpi_fused(planes_, hm, separable=False)
            for hm in homs_]

  _, sec = time_fn(render_all, planes, homs, iters=10 if on_tpu else 2)
  fps = VIEWS / sec
  log(f"{VIEWS} views in {sec * 1e3:.1f} ms -> {fps:.1f} frames/s")
  emit("mpi_render_512_32plane_8pose_fps" if on_tpu
       else "mpi_render_512_dryrun_fps", fps, "frames/s",
       fps / TARGET_FPS if on_tpu else 1.0, views=VIEWS)


if __name__ == "__main__":
  main()
