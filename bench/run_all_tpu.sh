#!/bin/bash
# Run the full TPU bench battery, writing one artifact per script into
# artifacts/. Intended for an idle host (contention skews the axon-tunnel
# dispatch numbers). Each script prints its JSON line on stdout; stderr
# diagnostics go to the matching .log file.
set -u
cd "$(dirname "$0")/.."
mkdir -p artifacts
stamp=$(date -u +%Y%m%dT%H%M%SZ)
run() {
  name=$1; shift
  if [ -s "artifacts/${name}.json" ]; then
    echo "=== $name already done; skipping ==="
    return 0
  fi
  echo "=== $name ($(date -u +%H:%M:%SZ)) ==="
  # Write to .tmp and move into place only on success, so the done-marker
  # path can never hold a partial artifact (even if this shell is killed
  # mid-run, the tunnel-flap scenario this script exists for).
  timeout 1800 python "$@" >"artifacts/${name}.json.tmp" 2>"artifacts/${name}.log"
  rc=$?
  if [ $rc -eq 0 ]; then
    mv -f "artifacts/${name}.json.tmp" "artifacts/${name}.json"
  else
    mv -f "artifacts/${name}.json.tmp" "artifacts/${name}.json.failed" 2>/dev/null
  fi
  echo "rc=$rc $(cat artifacts/${name}.json 2>/dev/null | tail -1)"
}
echo "battery start $stamp"
run tpu_r05_headline bench.py
run tpu_r05_config1 bench/config1_composite.py
run tpu_r05_config2 bench/config2_render512.py
run tpu_r05_config3 bench/config3_sweep.py
run tpu_r05_config4 bench/config4_sharded.py
run tpu_r05_config5 bench/config5_tiny_unet.py
run tpu_r05_train_speed bench/train_speed.py
run tpu_r05_render_bwd bench/render_bwd.py
# The reference training config end-to-end (VERDICT r3 item 5): 224 px,
# 10 planes, synthetic scenes, planned Pallas render fwd+bwd in the loss,
# viewer HTML of a validation MPI exported alongside.
run tpu_r05_train_ref224 -m mpi_vision_tpu train --synthetic \
    --synthetic-scenes 8 --img-size 224 --num-planes 10 --epochs 25 \
    --planned-render --lr-find --lr-find-steps 40 \
    --ckpt "$(pwd)/artifacts/train_ref224_ckpt" \
    --export-html artifacts/train_ref224_viewer.html
# Random-VGG vs plain-L2 ablation at the reference config (VERDICT r3
# item 9).
run tpu_r05_ablate_vgg bench/ablate_vgg.py
run tpu_r05_profile bench/profile_render.py
echo "battery done $(date -u +%H:%M:%SZ)"
