"""Train-step timing at the reference's configs (VERDICT r2 item 10).

Times the jitted VGG-perceptual train step (renderer inside the backward
pass) at the notebook's two published configs — 224^2 x 10 planes
(40-41 s/epoch over 150 scenes on the reference's Colab GPU, i.e.
~0.27 s/step) and the cell-7 "also works" 480^2 x 33 planes
(~6 min/epoch, ~2.4 s/step) — to decide with numbers whether the
XLA-gather backward through the renderer needs a Pallas backward kernel.

Emits one JSON line per config with seconds/step and vs_baseline =
reference_step_seconds / ours (>= 1.0 means we beat the Colab GPU).

Usage: python bench/train_speed.py [--steps 8]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from _common import emit, log

# Reference wall-times (BASELINE.md): 40.5 s / 150 scenes and 360 s / 150.
REF_STEP_S = {224: 40.5 / 150.0, 480: 360.0 / 150.0}


def _pose(rotate: bool) -> np.ndarray:
  pose = np.eye(4, dtype=np.float32)
  pose[0, 3] = 0.05
  if rotate:
    r = np.radians(0.5)
    c, s = np.cos(r), np.sin(r)
    pose[:3, :3] = [[c, 0, s], [0, 1, 0], [-s, 0, c]]
  return pose


def _batch(rng, hw: int, p: int, rotate: bool = False):
  pose = _pose(rotate)
  return {
      "net_input": rng.uniform(-1, 1, (1, hw, hw, 3 + 3 * p)).astype(
          np.float32),
      "ref_img": rng.uniform(-1, 1, (1, hw, hw, 3)).astype(np.float32),
      "tgt_img": rng.uniform(-1, 1, (1, hw, hw, 3)).astype(np.float32),
      "tgt_img_cfw": pose[None],
      "ref_img_wfc": np.eye(4, dtype=np.float32)[None],
      "intrinsics": np.asarray(
          [[[hw / 2.0, 0, hw / 2.0], [0, hw / 2.0, hw / 2.0], [0, 0, 1]]],
          np.float32),
  }


def time_config(hw: int, planes: int, steps: int, planned: bool,
                rotate: bool = False, bf16: bool = False) -> float:
  import jax
  import jax.numpy as jnp

  from mpi_vision_tpu import config
  from mpi_vision_tpu.core.camera import inv_depths

  cfg = config.TrainConfig(
      data=config.DataConfig(img_size=hw, num_planes=planes),
      compute_dtype="bfloat16" if bf16 else None)
  state = cfg.make_train_state(jax.random.PRNGKey(0))
  step = cfg.make_train_step(planned=planned)  # default VGG, resize 224
  rng = np.random.default_rng(0)
  batch = {k: jnp.asarray(v)
           for k, v in _batch(rng, hw, planes, rotate).items()}
  batch["mpi_planes"] = inv_depths(
      cfg.data.depth_near, cfg.data.depth_far, planes)

  state, metrics = step(state, batch)         # compile + warm-up
  jax.block_until_ready(metrics["loss"])
  t0 = time.perf_counter()
  for _ in range(steps):
    state, metrics = step(state, batch)
  jax.block_until_ready(metrics["loss"])
  return (time.perf_counter() - t0) / steps


def main() -> None:
  ap = argparse.ArgumentParser()
  ap.add_argument("--steps", type=int, default=8)
  args = ap.parse_args()

  import jax

  on_tpu = jax.default_backend() == "tpu"
  log(f"backend={jax.default_backend()}")
  configs = [(224, 10), (480, 33)] if on_tpu else [(64, 4)]
  for hw, planes in configs:
    ref = REF_STEP_S.get(hw)
    extra = {}
    best = None
    # XLA render step vs the planned fused-Pallas step (forward+backward)
    # vs the bf16-compute U-Net; at 480^2 also a rotated pose (the general
    # adjoint kernel's case).
    for tag, planned, rotate, bf16 in (
        ("xla", False, False, False),
        ("planned", True, False, False),
        ("xla_bf16", False, False, True),
        ("planned_rot", True, hw >= 480, False)):
      if tag == "planned_rot" and not rotate:
        continue
      sec = time_config(hw, planes, args.steps, planned, rotate, bf16)
      extra[f"{tag}_s"] = round(sec, 4)
      if tag in ("xla", "planned"):
        # bf16 stays a side field: the headline seconds must compare f32
        # against the f32 Colab reference, not ride a precision change.
        best = sec if best is None else min(best, sec)
      log(f"{hw}^2 x {planes} planes [{tag}]: {sec * 1e3:.0f} ms/step"
          + (f" (reference Colab GPU ~{ref * 1e3:.0f} ms)" if ref else ""))
    emit(f"train_step_{hw}px_{planes}planes_seconds", best, "s/step",
         (ref / best) if ref else 1.0, img_size=hw, planes=planes, **extra)


if __name__ == "__main__":
  main()
