"""BASELINE config 5: tiny per-plane RGBA predictor (DeepView-style) trained
on a stereo pair, then inference.

Trains ``models.tiny_unet.TinyPlaneUNet`` — direct per-plane RGBA
prediction from the PSV, the DeepView-family parameterization — on ONE
synthetic stereo pair (overfit, as the config prescribes) with the L2
render loss, then times jitted inference (PSV -> MPI -> novel view).

Metrics: inference fps (value; target 30 — the model must keep a live
novel-view loop interactive) plus train seconds and final loss as fields.

Usage: python bench/config5_tiny_unet.py [--steps 150]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from _common import emit, log, time_fn

TARGET_FPS = 30.0


def main() -> None:
  ap = argparse.ArgumentParser()
  ap.add_argument("--steps", type=int, default=150)
  ap.add_argument("--img-size", type=int, default=64)
  ap.add_argument("--num-planes", type=int, default=8)
  args = ap.parse_args()

  import jax
  import jax.numpy as jnp
  import optax

  from mpi_vision_tpu.core import render
  from mpi_vision_tpu.data import realestate
  from mpi_vision_tpu.models import tiny_unet

  log(f"backend={jax.default_backend()}")
  root = tempfile.mkdtemp(prefix="mpi_synth_")
  realestate.synthesize_dataset(root, num_scenes=1, frames=3,
                                img_size=args.img_size, seed=0)
  ds = realestate.RealEstateDataset(root, img_size=args.img_size,
                                    num_planes=args.num_planes, is_valid=True)
  batch = next(realestate.iterate_batches(ds, shuffle=False))

  model = tiny_unet.TinyPlaneUNet()
  psv = tiny_unet.psv_from_net_input(batch["net_input"], args.num_planes)
  params = model.init(jax.random.PRNGKey(0), psv)

  def loss_fn(p, psv_, batch_):
    mpi = model.apply(p, psv_)                       # [B, H, W, P, 4]
    rel = batch_["tgt_img_cfw"] @ batch_["ref_img_wfc"]
    out = render.render_mpi(mpi, rel, batch_["mpi_planes"][0],
                            batch_["intrinsics"])
    return jnp.mean((out - batch_["tgt_img"]) ** 2)

  tx = optax.adam(1e-3)
  opt_state = tx.init(params)

  @jax.jit
  def step(p, o, psv_, batch_):
    loss, grads = jax.value_and_grad(loss_fn)(p, psv_, batch_)
    updates, o = tx.update(grads, o)
    return optax.apply_updates(p, updates), o, loss

  t0 = time.time()
  losses = []
  for _ in range(args.steps):
    params, opt_state, loss = step(params, opt_state, psv, batch)
    losses.append(loss)
  losses = [float(l) for l in jax.device_get(losses)]
  train_s = time.time() - t0
  log(f"train: {args.steps} steps in {train_s:.1f}s "
      f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")
  if not losses[-1] < losses[0]:
    raise SystemExit("tiny-UNet failed to overfit the stereo pair")

  @jax.jit
  def infer(p, psv_, batch_):
    mpi = model.apply(p, psv_)
    rel = batch_["tgt_img_cfw"] @ batch_["ref_img_wfc"]
    return render.render_mpi(mpi, rel, batch_["mpi_planes"][0],
                             batch_["intrinsics"])

  _, sec = time_fn(infer, params, psv, batch, iters=20)
  fps = 1.0 / sec
  log(f"inference: {sec * 1e3:.2f} ms -> {fps:.1f} fps")
  emit("tiny_unet_stereo_pair_inference_fps", fps, "frames/s",
       fps / TARGET_FPS, train_seconds=round(train_s, 1),
       first_loss=round(losses[0], 5), final_loss=round(losses[-1], 5),
       steps=args.steps)


if __name__ == "__main__":
  main()
