"""Which render tier do TRAINING batches actually hit? (VERDICT r4 item 7)

The banded per-row middle tier keeps its XLA backward on the argument
that training traffic rarely lands there (kernels/render_pallas.py,
_make_banded docstring). With the SHARED_LEVELS slice ladder covering
~13 degrees of yaw at 1080p, the banded tier now starts at rotations the
stereo-magnification training distribution (notebook cell 8: consecutive
RealEstate10K frames, timestamp window 16e3-500e3 microseconds) should
essentially never produce. This script measures that claim instead of
asserting it: plan every batch of a training epoch stream exactly as the
planned train step does (train.loop.plan_batch_render) and count tiers.

Prints ONE JSON line:
  {"metric": "train_tier_banded_frac", "value": <fraction of batches in
   the banded tier>, "separable": n, "shared_base": n, "shared_wide": n,
   "banded": n, "xla": n, ...}
and mirrors it to artifacts/tier_traffic.json when run from the repo.

The dataset is the hermetic synthetic one (same generator the bench
battery and train_ref224 use); poses are camera trucks, so expect the
separable tier to dominate — the measurement exists to put a number on
the banded share (and to be re-run against a real RealEstate10K layout
via --dataset when one is available).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
  ap = argparse.ArgumentParser()
  ap.add_argument("--dataset", default=None,
                  help="RealEstate10K-layout root (default: synthesize)")
  ap.add_argument("--img-size", type=int, default=224)   # cell 8:89
  ap.add_argument("--num-planes", type=int, default=10)  # cell 8:90
  ap.add_argument("--scenes", type=int, default=8)
  ap.add_argument("--batches", type=int, default=200)
  ap.add_argument("--rot-deg", type=float, default=0.0,
                  help="per-frame rotation jitter for the synthetic "
                       "scenes (deg); real clips carry small inter-frame "
                       "rotations, so run the census at e.g. 2.0 too")
  ap.add_argument("--seed", type=int, default=0)
  args = ap.parse_args()

  import numpy as np

  from mpi_vision_tpu import config
  from mpi_vision_tpu.data import realestate
  from mpi_vision_tpu.kernels import render_pallas as rp
  from mpi_vision_tpu.train.loop import plan_batch_render

  t0 = time.time()
  root = args.dataset
  tmp = None
  if root is not None and args.rot_deg:
    raise SystemExit(
        "--rot-deg only applies to the synthesized dataset; a real "
        "--dataset carries its own poses (drop one of the two flags)")
  if root is None:
    tmp = tempfile.TemporaryDirectory(prefix="mpi_tier_")
    root = tmp.name
    realestate.synthesize_dataset(root, num_scenes=args.scenes, frames=4,
                                  img_size=args.img_size, seed=args.seed,
                                  rot_deg=args.rot_deg)
  cfg = config.DataConfig(dataset_path=root, img_size=args.img_size,
                          num_planes=args.num_planes)
  dataset = cfg.make_dataset(rng=np.random.default_rng(args.seed))
  order = np.random.default_rng(args.seed + 1)

  counts = {"separable": 0, "shared_base": 0, "shared_wide": 0,
            "banded": 0, "xla": 0}
  # How often the BACKWARD stays on Pallas: a batch with a kernel plan
  # but adj_plan None keeps the Pallas forward with the XLA backward
  # (the banded tier by design; a shared/separable batch only when the
  # adjoint planner rejects its pose).
  adj_engaged = adj_fallback = 0
  got = 0
  while got < args.batches:
    for batch in realestate.iterate_batches(dataset, batch_size=1,
                                            rng=order):
      bundle = plan_batch_render(batch)
      if bundle is None:
        counts["xla"] += 1
      elif bundle["separable"]:
        counts["separable"] += 1
      elif isinstance(bundle["plan"], tuple) and bundle["plan"][0] == "banded":
        counts["banded"] += 1
      elif (bundle["plan"][2], bundle["plan"][3]) == (rp.G_SHARED,
                                                      rp.G_BAND):
        counts["shared_base"] += 1
      else:
        counts["shared_wide"] += 1
      if bundle is not None:
        if bundle["adj_plan"] is not None:
          adj_engaged += 1
        else:
          adj_fallback += 1
      got += 1
      if got >= args.batches:
        break

  out = {
      "metric": "train_tier_banded_frac",
      "value": round(counts["banded"] / max(1, got), 4),
      "unit": "fraction",
      "vs_baseline": None,
      **counts,
      "pallas_backward_engaged": adj_engaged,
      "xla_backward_fallback": adj_fallback,
      "batches": got,
      "img_size": args.img_size,
      "num_planes": args.num_planes,
      "dataset": "synthetic" if tmp is not None else args.dataset,
      "rot_deg": args.rot_deg,
      "seconds": round(time.time() - t0, 1),
  }
  print(json.dumps(out))
  art = os.path.join(os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))), "artifacts")
  if os.path.isdir(art):
    name = ("tier_traffic.json" if args.rot_deg == 0.0
            else f"tier_traffic_rot{args.rot_deg:g}.json")
    with open(os.path.join(art, name), "w") as fh:
      fh.write(json.dumps(out) + "\n")
  if tmp is not None:
    tmp.cleanup()


if __name__ == "__main__":
  main()
