"""BASELINE config 4: 1080p 32-plane MPI, 64-view batch, shard_map DP mesh.

Runs ``parallel.mesh.render_views_sharded`` (views sharded over the 'data'
axis, MPI replicated, zero cross-chip traffic inside the render) with the
fused Pallas kernel on each shard. Two modes, auto-selected by backend:

  * TPU (one real chip here): a 1-device mesh times the PER-CHIP slice of
    the config — 64 novel views at 1080p x 32 planes — and reports
    views/s/chip (target: the 30 FPS north star per chip). A v5e-4 run is
    this number x4, since views are embarrassingly parallel.
  * CPU (virtual mesh, e.g. XLA_FLAGS=--xla_force_host_platform_device_count=8):
    a dryrun at reduced size validating the sharded layout end to end
    (also exercised by tests/test_parallel.py and __graft_entry__'s
    multichip dryrun).

The 64 poses alternate separable (truck/dolly) and small-pan views; the
general-kernel plan is computed EAGERLY on the concrete pose set and passed
through shard_map via the explicit plan override (inside shard_map the
poses are tracers, so the checked path cannot run per view).

Usage: python bench/config4_sharded.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from _common import emit, log, time_fn

VIEWS = 64
TARGET_VIEWS_PER_S = 30.0


def pan_poses(n: int) -> np.ndarray:
  poses = []
  for i in range(n):
    pose = np.eye(4, dtype=np.float32)
    ang = np.radians(1.0) * np.sin(2 * np.pi * i / n)
    c, s = np.cos(ang), np.sin(ang)
    pose[:3, :3] = [[c, 0, s], [0, 1, 0], [-s, 0, c]]
    pose[0, 3] = 0.08 * np.cos(2 * np.pi * i / n)
    pose[2, 3] = -0.05 * np.sin(2 * np.pi * i / n)
    poses.append(pose)
  return np.stack(poses)


def main() -> None:
  import jax
  import jax.numpy as jnp

  from mpi_vision_tpu.core.camera import inv_depths
  from mpi_vision_tpu.kernels import render_pallas as rp
  from mpi_vision_tpu.parallel import mesh as pmesh

  on_tpu = jax.default_backend() == "tpu"
  h, w, planes_n, views = (1080, 1920, 32, VIEWS) if on_tpu else (48, 256, 4, 8)
  log(f"backend={jax.default_backend()} devices={len(jax.devices())} "
      f"config: {views} views {h}x{w}x{planes_n}")

  mesh = pmesh.make_mesh()
  mpi = jax.jit(lambda k: jax.random.uniform(k, (h, w, planes_n, 4)))(
      jax.random.PRNGKey(0))
  jax.block_until_ready(mpi)
  depths = jnp.asarray(np.asarray(inv_depths(1.0, 100.0, planes_n)))
  k = np.array([[0.5 * w, 0, w / 2], [0, 0.5 * w, h / 2], [0, 0, 1]],
               np.float32)
  poses = pan_poses(views)

  # Eager plan over the whole concrete pose set: the general kernel variant
  # every shard will run (poses are tracers inside shard_map).
  from mpi_vision_tpu.core.sampling import Convention
  homs_all = rp.pixel_homographies(
      jnp.asarray(poses), depths, jnp.asarray(k)[None].repeat(views, 0),
      h, w).transpose(1, 0, 2, 3).reshape(-1, 3, 3)
  plan = rp._plan_shared(homs_all, h, w)
  log(f"eager plan over {views} poses: {plan}")
  if plan is None:
    raise SystemExit("pose set fell out of the shared-kernel envelope")

  def run(mpi_, poses_):
    # convention=EXACT matches the pixel_homographies call the plan was
    # computed from (the default REF_HOMOGRAPHY would rescale differently
    # on this non-square frame and void the envelope check).
    return pmesh.render_views_sharded(
        mpi_, poses_, depths, jnp.asarray(k), mesh,
        convention=Convention.EXACT,
        method="fused_pallas", separable=False, check=False, plan=plan)

  out, sec = time_fn(run, mpi, jnp.asarray(poses),
                     iters=5 if on_tpu else 2)
  vps = views / sec
  per_chip = vps / len(jax.devices())
  log(f"{views} views in {sec * 1e3:.1f} ms -> {vps:.2f} views/s "
      f"({per_chip:.2f}/chip on {len(jax.devices())} devices)")

  emit("mpi_render_1080p_32plane_64view_sharded_views_per_s_chip"
       if on_tpu else "mpi_render_sharded_dryrun_views_per_s",
       per_chip, "views/s/chip",
       per_chip / TARGET_VIEWS_PER_S if on_tpu else 1.0,
       views=views, devices=len(jax.devices()))


if __name__ == "__main__":
  main()
