"""Shared helpers for the BASELINE benchmark config scripts (BASELINE.md).

Each config script prints ONE JSON line ``{"metric", "value", "unit",
"vs_baseline", ...}`` on stdout (diagnostics on stderr), mirroring the
repo-root ``bench.py`` contract. ``vs_baseline`` is oriented so that >= 1.0
means "target met": ``value / target`` for throughput metrics (higher is
better) and ``budget / value`` for error metrics (lower is better).
"""

from __future__ import annotations

import json
import os
import sys
import time


def repo_root() -> str:
  return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def log(msg: str) -> None:
  print(msg, file=sys.stderr, flush=True)


def time_fn(fn, *args, iters: int = 10):
  """(result, seconds_per_call) with a compile/warm-up call first."""
  import jax

  out = fn(*args)
  jax.block_until_ready(out)
  t0 = time.perf_counter()
  for _ in range(iters):
    out = fn(*args)
  jax.block_until_ready(out)
  return out, (time.perf_counter() - t0) / iters


def emit(metric: str, value: float, unit: str, vs_baseline: float,
         **extra) -> None:
  print(json.dumps({
      "metric": metric,
      "value": round(float(value), 4),
      "unit": unit,
      "vs_baseline": round(float(vs_baseline), 4),
      **extra,
  }))
