#!/bin/bash
# Probe the TPU tunnel every 3 minutes; when a trivial device program
# succeeds, run the full bench battery (bench/run_all_tpu.sh) once and exit.
# Survives tunnel flaps during the battery: if the headline artifact is
# missing or empty afterwards, keep watching and retry.
set -u
cd "$(dirname "$0")/.."
log=artifacts/tpu_watch.log
mkdir -p artifacts
echo "watch start $(date -u +%H:%M:%SZ)" >>"$log"
while true; do
  if timeout 120 python -c "
import jax, jax.numpy as jnp
jnp.ones((128,128)).sum().block_until_ready()
print(jax.devices())
" >>"$log" 2>&1; then
    echo "tunnel up $(date -u +%H:%M:%SZ); running battery" >>"$log"
    bash bench/run_all_tpu.sh >>"$log" 2>&1
    if [ -s artifacts/tpu_r03_headline.json ]; then
      echo "battery complete $(date -u +%H:%M:%SZ)" >>"$log"
      exit 0
    fi
    echo "headline artifact empty; tunnel likely flapped — rewatching" >>"$log"
  fi
  sleep 180
done
