#!/bin/bash
# Probe the TPU tunnel every 3 minutes; when a trivial device program
# succeeds, run the full bench battery (bench/run_all_tpu.sh) once and exit.
# Survives tunnel flaps during the battery: if the headline artifact is
# missing or empty afterwards, keep watching and retry.
set -u
cd "$(dirname "$0")/.."
log=artifacts/tpu_watch.log
mkdir -p artifacts
echo "watch start $(date -u +%H:%M:%SZ)" >>"$log"
batteries=0
while true; do
  if timeout 120 python -c "
import jax, jax.numpy as jnp
jnp.ones((128,128)).sum().block_until_ready()
print(jax.devices())
" >>"$log" 2>&1; then
    echo "tunnel up $(date -u +%H:%M:%SZ); running battery" >>"$log"
    bash bench/run_all_tpu.sh >>"$log" 2>&1
    batteries=$((batteries + 1))
    # Complete only when EVERY artifact landed (run_all skips ones already
    # done, so a mid-battery tunnel flap resumes where it left off).
    missing=0
    for n in headline config1 config2 config3 config4 config5 train_speed; do
      [ -s "artifacts/tpu_r03_${n}.json" ] || missing=$((missing + 1))
    done
    if [ "$missing" -eq 0 ]; then
      echo "battery complete $(date -u +%H:%M:%SZ)" >>"$log"
      exit 0
    fi
    if [ "$batteries" -ge 5 ]; then
      # A benchmark that still has no artifact after 5 batteries is failing
      # deterministically, not flapping; stop hogging the TPU host.
      echo "giving up after $batteries batteries; $missing missing" >>"$log"
      exit 1
    fi
    echo "$missing artifacts still empty; tunnel likely flapped — rewatching" >>"$log"
  fi
  sleep 180
done
