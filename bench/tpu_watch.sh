#!/bin/bash
# Watch for the TPU tunnel to come back, then run the full bench battery
# (bench/run_all_tpu.sh) and exit once every artifact has landed.
#
# Probing is two-tier because killing a python process mid-axon-init can
# re-stick the tunnel lease (see .claude/skills/verify: the claim lingers
# until the lease expires). Tier 1 is a TCP connect to the local
# compile-helper port (8103) — no axon involvement, safe to run every 3
# minutes. A python probe (tier 2) runs only when the port accepts, or as
# a rate-limited fallback every 45 minutes in case the port is not the
# right signal; its timeout is generous so it is rarely killed mid-init.
set -u
cd "$(dirname "$0")/.."
log=artifacts/tpu_watch.log
mkdir -p artifacts
echo "watch start $(date -u +%H:%M:%SZ)" >>"$log"
batteries=0
last_py_probe=0
while true; do
  now=$(date +%s)
  tcp_up=0
  if timeout 5 bash -c '</dev/tcp/127.0.0.1/8103' 2>/dev/null; then
    tcp_up=1
  fi
  if [ "$tcp_up" -eq 1 ] || [ $((now - last_py_probe)) -ge 2700 ]; then
    last_py_probe=$now
    if timeout 600 python -c "
import jax, jax.numpy as jnp
jnp.ones((128,128)).sum().block_until_ready()
print(jax.devices())
" >>"$log" 2>&1; then
      echo "tunnel up $(date -u +%H:%M:%SZ); running battery" >>"$log"
      bash bench/run_all_tpu.sh >>"$log" 2>&1
      batteries=$((batteries + 1))
      missing=0
      for n in headline config1 config2 config3 config4 config5 train_speed render_bwd train_ref224 ablate_vgg profile; do
        [ -s "artifacts/tpu_r05_${n}.json" ] || missing=$((missing + 1))
      done
      if [ "$missing" -eq 0 ]; then
        echo "battery complete $(date -u +%H:%M:%SZ)" >>"$log"
        exit 0
      fi
      if [ "$batteries" -ge 5 ]; then
        # A benchmark with no artifact after 5 batteries is failing
        # deterministically, not flapping; stop hogging the TPU host.
        echo "giving up after $batteries batteries; $missing missing" >>"$log"
        exit 1
      fi
      echo "$missing artifacts still empty; tunnel likely flapped — rewatching" >>"$log"
    fi
  fi
  sleep 180
done
