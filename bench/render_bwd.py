"""Renderer-backward timing: Pallas backward vs the XLA gather/scatter VJP.

The training loss renders through the MPI pipeline (cell 12:38-42), so
``d loss / d planes`` through warp+composite is the training hot path.
This script times ``jax.grad`` of a scalar loss through the fused renderer
(kernels/render_pallas_bwd: warp, composite VJP, tent-filter adjoint)
against the same gradient through the XLA reference path, at the
reference's two training configs (224^2 x 10 planes, cell 14; 480^2 x 33
planes, cell 7 md) and the 1080p x 32 inference size — the measurement
VERDICT r2 item 10 asked for.

One JSON line: value = Pallas-backward seconds/step at the 480^2 config,
vs_baseline = XLA seconds / Pallas seconds there (>= 1.0 means the Pallas
backward wins); per-config fields for the rest.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

import _common  # noqa: E402


CONFIGS = (
    ("train224", 224, 224, 10),
    ("train480", 480, 480, 33),
    ("infer1080", 1080, 1920, 32),
)


def main() -> None:
  import jax
  import jax.numpy as jnp

  from mpi_vision_tpu.core.camera import inv_depths
  from mpi_vision_tpu.kernels import render_pallas as rp

  on_tpu = jax.default_backend() == "tpu"
  rng = np.random.default_rng(0)
  results = {}
  for name, h, w, p in CONFIGS:
    if not on_tpu and h > 256:
      _common.log(f"{name}: skipped off-TPU")
      continue
    planes = jnp.asarray(rng.uniform(0, 1, (p, 4, h, w)).astype(np.float32))
    depths = jnp.asarray(np.asarray(inv_depths(1.0, 100.0, p)))
    pose = np.eye(4, dtype=np.float32)
    r = np.radians(0.5)
    c, s = np.cos(r), np.sin(r)
    pose[:3, :3] = [[c, 0, s], [0, 1, 0], [-s, 0, c]]
    pose[0, 3], pose[2, 3] = 0.03, -0.02
    k = np.array([[0.5 * w, 0, w / 2], [0, 0.5 * w, h / 2], [0, 0, 1]],
                 np.float32)
    homs = rp.pixel_homographies(
        jnp.asarray(pose)[None], depths, jnp.asarray(k)[None], h, w)[:, 0]
    # plan_fused plans at the kernel's auto-padded geometry — exactly what
    # render_mpi_fused executes for off-tile-grid sizes.
    bundle = rp.plan_fused(homs, h, w)
    if bundle is None or bundle["separable"] or bundle["adj_plan"] is None:
      _common.log(f"{name}: pose outside kernel/adjoint envelope; skipped")
      continue

    loss_pallas = jax.jit(jax.grad(
        lambda pl_: jnp.sum(rp.render_mpi_fused(pl_, homs,
                                                separable=False) ** 2)))
    loss_xla = jax.jit(jax.grad(
        lambda pl_: jnp.sum(rp.reference_render(pl_, homs) ** 2)))
    _, t_pallas = _common.time_fn(loss_pallas, planes, iters=5)
    _, t_xla = _common.time_fn(loss_xla, planes, iters=3)
    results[f"{name}_pallas_s"] = round(t_pallas, 4)
    results[f"{name}_xla_s"] = round(t_xla, 4)
    results[f"{name}_speedup"] = round(t_xla / t_pallas, 2)
    _common.log(f"{name}: pallas {t_pallas:.4f}s  xla {t_xla:.4f}s  "
                f"speedup {t_xla / t_pallas:.2f}x")

  key = "train480_pallas_s"
  if key not in results:
    if on_tpu:
      raise SystemExit("no 480^2 measurement (outside kernel envelope?)")
    # Off-TPU (interpret-mode) smoke run: emit whatever was measured so the
    # script exercises end to end, flagged as not a real number.
    _common.emit("render_backward_480p33_seconds", -1.0, "s/step", 0.0,
                 note="no TPU: interpret-mode smoke only", **results)
    return
  _common.emit(
      "render_backward_480p33_seconds",
      results[key],
      "s/step",
      results["train480_speedup"],
      **results)


if __name__ == "__main__":
  main()
