"""VGG-perceptual vs plain-L2 training ablation (VERDICT r3 item 9).

The reference trains with a pretrained-VGG16 perceptual loss (notebook
cell 12:17-60); pretrained ImageNet weights are unreachable offline, so
every perceptual loss this repo computes uses random (He-init) VGG
features. This script quantifies what those random features buy over the
plain L2 metric loss — the honest substitute for the unreproducible
pretrained-weights comparison:

  * synthesize the hermetic procedural dataset;
  * train the SAME initial model twice on the SAME batch stream — once
    with the (random-)VGG perceptual loss, once with plain L2;
  * render held-out validation novel views with both and report L1 and
    PSNR against the target frames.

Prints ONE JSON line: {"metric": "vgg_ablation_val_psnr_db", "value":
<psnr of the VGG-trained model>, "l2_psnr": ..., "vgg_l1": ...,
"l2_l1": ..., "steps": N, ...}. Run with --img-size 64 for a quick CPU
pass; defaults are the reference config (224 px, 10 planes, cell 8).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
  ap = argparse.ArgumentParser()
  ap.add_argument("--img-size", type=int, default=224)    # cell 8:89
  ap.add_argument("--num-planes", type=int, default=10)   # cell 8:90
  ap.add_argument("--scenes", type=int, default=8)
  ap.add_argument("--steps", type=int, default=200)
  ap.add_argument("--seed", type=int, default=0)
  args = ap.parse_args()

  import jax
  import jax.numpy as jnp
  import numpy as np

  from mpi_vision_tpu import config
  from mpi_vision_tpu.data import realestate
  from mpi_vision_tpu.train import loop as train_loop
  from mpi_vision_tpu.train import loss as loss_lib
  from mpi_vision_tpu.train import vgg as vgg_lib

  t0 = time.time()
  tmp = tempfile.TemporaryDirectory(prefix="mpi_ablate_")
  realestate.synthesize_dataset(tmp.name, num_scenes=args.scenes, frames=4,
                                img_size=args.img_size, seed=args.seed)
  cfg = config.TrainConfig(
      data=config.DataConfig(dataset_path=tmp.name, img_size=args.img_size,
                             num_planes=args.num_planes))
  valid = cfg.data.make_dataset(is_valid=True)
  vgg_params = vgg_lib.default_params()

  def batches(n):
    """n batches from a FIXED stream, identical for both arms.

    The dataset is rebuilt per arm: RealEstateDataset draws frame
    triplets from its own stateful rng, so sharing one dataset object
    would hand the second arm a different triplet sequence and conflate
    loss choice with batch content.
    """
    dataset = cfg.data.make_dataset(rng=np.random.default_rng(args.seed))
    order = np.random.default_rng(args.seed + 1)
    got = 0
    while got < n:
      for b in realestate.iterate_batches(dataset, batch_size=1, rng=order):
        yield b
        got += 1
        if got >= n:
          return

  def eval_model(state):
    """Mean L1 / PSNR of rendered validation novel views vs targets."""
    l1s, mses = [], []
    for i in range(len(valid)):
      ex = valid[i]
      batch = {k: jnp.asarray(np.asarray(v))[None] for k, v in ex.items()}
      pred = state.apply_fn({"params": state.params}, batch["net_input"])
      out = loss_lib.render_novel_view(pred, batch)
      diff = np.asarray(out[0]) - np.asarray(batch["tgt_img"][0])
      l1s.append(float(np.abs(diff).mean()))
      mses.append(float((diff ** 2).mean()))
    # Images live in [-1, 1]: PSNR against that 2.0 peak-to-peak range.
    psnr = float(10 * np.log10(4.0 / np.mean(mses)))
    return float(np.mean(l1s)), psnr

  results = {}
  for kind in ("vgg", "l2"):
    state = cfg.make_train_state(jax.random.PRNGKey(args.seed))
    step = train_loop.make_train_step(
        vgg_params if kind == "vgg" else None, resize=cfg.vgg_resize)
    state, losses = train_loop.fit(state, batches(args.steps), step=step)
    l1, psnr = eval_model(state)
    results[kind] = dict(l1=l1, psnr=psnr, first_loss=losses[0],
                         final_loss=losses[-1])
    print(f"ablate: {kind} trained {len(losses)} steps "
          f"loss {losses[0]:.4f}->{losses[-1]:.4f} "
          f"val L1={l1:.4f} PSNR={psnr:.2f} dB", file=sys.stderr)

  print(json.dumps({
      "metric": "vgg_ablation_val_psnr_db",
      "value": round(results["vgg"]["psnr"], 3),
      "unit": "dB",
      "vs_baseline": None,
      "l2_psnr": round(results["l2"]["psnr"], 3),
      "vgg_l1": round(results["vgg"]["l1"], 5),
      "l2_l1": round(results["l2"]["l1"], 5),
      "vgg_final_loss": round(results["vgg"]["final_loss"], 5),
      "l2_final_loss": round(results["l2"]["final_loss"], 5),
      "img_size": args.img_size,
      "num_planes": args.num_planes,
      "steps": args.steps,
      "seconds": round(time.time() - t0, 1),
  }))
  tmp.cleanup()


if __name__ == "__main__":
  main()
