"""Training-queue chaos bench: drain a hostile job mix under supervision.

Submits a job set that exercises every supervision edge at once — a
clean job, a poison job that crashes on every attempt (must be
quarantined at exactly the restart budget while everything else keeps
draining), a crash-once job (must requeue, resume, and complete), and a
wedge job whose step counter stalls (must be SIGKILLed and retried) —
then runs ``TrainSupervisor.run_until_drained`` and prints ONE JSON
line::

  {"metric": "train_queue_chaos", "value": <jobs completed>,
   "unit": "jobs", "quarantines": ..., "wedges": ..., "requeues": ...,
   "publishes": ..., "slo": {...}, "seconds": ...}

Two modes:

  * real (default): jobs are actual ``cli train --ckpt`` subprocesses
    with ``--inject-fault`` schedules from the job specs — the
    full-stack drill (CPU-sized: tiny synthetic scenes).
  * ``--dry`` (or ``TRAIN_QUEUE_DRY=1``): the same supervisor state
    machine over a scripted fake launcher/transport on a FAKE clock —
    the whole drill in milliseconds, which is what tier-1 registers
    (tests/test_train_queue.py::test_chaos_bench_dry_smoke). Guard rot
    in the queue's decision path is caught here, not in a babysat run.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg: str) -> None:
  print(msg, file=sys.stderr, flush=True)


# --- dry mode: scripted fakes on a fake clock ------------------------------


class _FakeClock:
  def __init__(self, t: float = 1000.0):
    self.t = t

  def __call__(self) -> float:
    return self.t

  def sleep(self, seconds: float) -> None:
    self.t += max(float(seconds), 0.0)


class _FakeHandle:
  """One scripted attempt: completes/crashes after a delay, or wedges
  (health answers, step counter frozen) forever."""

  def __init__(self, clock, behavior: str, started: float, port: int,
               run_s: float = 2.0):
    self.clock = clock
    self.behavior = behavior
    self.started = started
    self.port = port
    self.run_s = run_s
    self.killed: int | None = None
    self.sigterm_at: float | None = None
    self.ckpt_dir = "<dry>"
    self.steps = 0

  def poll(self):
    now = self.clock()
    if self.killed is not None:
      return -self.killed
    if self.sigterm_at is not None:
      return 0  # the train CLI's preempt save exits clean
    if self.behavior == "wedge":
      self.steps = 1  # one step, then frozen forever
      return None
    if now - self.started < self.run_s:
      self.steps = int(now - self.started) + 1
      return None
    return 1 if self.behavior == "crash" else 0

  def kill(self, sig):
    if sig == signal.SIGTERM:
      self.sigterm_at = self.clock()
    else:
      self.killed = int(sig)

  def metrics_address(self):
    return f"127.0.0.1:{self.port}"


class _FakeLauncher:
  """job spec ``behavior`` -> scripted handle; ``crash_once`` crashes on
  attempt 0 and completes on the retry, ``wedge`` wedges on attempt 0
  and completes on the retry."""

  def __init__(self, clock):
    self.clock = clock
    self.handles: list[_FakeHandle] = []

  def __call__(self, job, attempt, resume):
    behavior = job.spec.get("behavior", "ok")
    if behavior in ("crash_once", "wedge") and attempt > 0:
      behavior = "ok"
    if behavior == "crash_once":
      behavior = "crash"
    handle = _FakeHandle(self.clock, behavior, self.clock(),
                         port=9000 + len(self.handles))
    self.handles.append(handle)
    return handle


class _FakeTransport:
  """Keyed by the probed address: a probe of job A must never be
  answered with job B's counters (cross-attribution would reset the
  wrong stall clock)."""

  def __init__(self, launcher):
    self.launcher = launcher

  def request(self, method, url, body=None, headers=None, timeout=None):
    for handle in self.launcher.handles:
      if (handle.poll() is None
          and url == f"http://{handle.metrics_address()}/healthz"):
        return 200, {}, json.dumps({
            "status": "ok", "steps": handle.steps,
            "last_step_ms": 25.0}).encode()
    raise ConnectionError("no live attempt at this address")


class _FakePublishStore:
  def __init__(self):
    self.published = 0

  def publish_from(self, src_root, meta_extra=None):
    self.published += 1
    return self.published - 1, 0


def run_dry(budget: int = 1) -> dict:
  from mpi_vision_tpu.obs.slo import SloConfig, SloTracker, verdict
  from mpi_vision_tpu.train.queue import JobQueue
  from mpi_vision_tpu.train.supervisor import TrainSupervisor

  clock = _FakeClock()
  root = tempfile.mkdtemp(prefix="mpi_train_queue_dry_")
  queue = JobQueue(root, lease_s=60.0, clock=clock)
  for job_id, behavior in (("clean", "ok"), ("poison", "crash"),
                           ("flaky", "crash_once"), ("stuck", "wedge")):
    queue.submit({"behavior": behavior}, job_id=job_id)
  launcher = _FakeLauncher(clock)
  slo = SloTracker(SloConfig(latency_threshold_s=1.0), clock=clock)
  publish = _FakePublishStore()
  supervisor = TrainSupervisor(
      queue, launcher=launcher, publish_store=publish, concurrency=2,
      probe_s=0.5, wedge_after=3, startup_grace_s=1.0,
      restart_budget=budget, budget_window_s=600.0,
      backoff_base_s=0.5, backoff_max_s=2.0, slo=slo,
      transport=_FakeTransport(launcher), clock=clock,
      sleep=clock.sleep, log=log)
  t0 = clock()
  drained = supervisor.run_until_drained(timeout_s=300.0)
  # Mid-story preemption drill: requeue-and-resume is already covered by
  # the crash path above; preempt() on a drained queue must be a no-op.
  assert supervisor.preempt() == []
  snap = supervisor.snapshot()
  counts = snap["queue"]["counts"]
  assert drained, f"dry drill did not drain: {counts}"
  assert counts["done"] == 3 and counts["quarantined"] == 1, counts
  poison = queue.get("poison")
  assert poison.attempts == 1 + budget, (
      f"poison quarantined at {poison.attempts} attempts, "
      f"expected 1 + budget({budget})")
  assert snap["wedges"] == 1, snap
  return {
      "metric": "train_queue_chaos",
      "value": counts["done"],
      "unit": "jobs",
      "dry": True,
      "drained": drained,
      "jobs": counts,
      "quarantines": snap["quarantines"],
      "wedges": snap["wedges"],
      "requeues": snap["requeues"],
      "failures": snap["failures"],
      "publishes": publish.published,
      "poison_attempts": poison.attempts,
      "restart_budget": budget,
      "slo": verdict(slo.snapshot()),
      "seconds": round(clock() - t0, 3),
  }


# --- real mode: actual train subprocesses ----------------------------------


def run_real(args) -> dict:
  from mpi_vision_tpu.ckpt import CheckpointStore
  from mpi_vision_tpu.obs.events import EventLog
  from mpi_vision_tpu.obs.slo import SloConfig, SloTracker, verdict
  from mpi_vision_tpu.train.queue import JobQueue
  from mpi_vision_tpu.train.supervisor import TrainSupervisor

  root = args.root or tempfile.mkdtemp(prefix="mpi_train_queue_bench_")
  base = {"epochs": 1, "img_size": args.img_size,
          "num_planes": args.num_planes, "synthetic_scenes": 2,
          "save_every": 1, "seed": 0}
  events = EventLog()
  queue = JobQueue(os.path.join(root, "queue"), lease_s=60.0,
                   events=events)
  queue.submit(dict(base), job_id="clean")
  queue.submit({**base, "faults": ["crash@step=0,hard"]}, job_id="poison")
  queue.submit({**base, "seed": 1,
                "faults": ["crash@step=1,hard,attempt=0"]}, job_id="flaky")
  # The wedge case the docstring promises: attempt 0 hangs mid-run (the
  # supervisor must SIGKILL it once the step counter stalls past
  # wedge_after probes), the retry runs clean.
  queue.submit({**base, "seed": 2,
                "faults": ["hang@step=1,seconds=600,attempt=0"]},
               job_id="stuck")
  publish = CheckpointStore(os.path.join(root, "publish"), events=events)
  slo = SloTracker(SloConfig(latency_threshold_s=args.slo_step_latency_ms
                             / 1e3))
  supervisor = TrainSupervisor(
      queue, work_root=os.path.join(root, "work"), publish_store=publish,
      concurrency=args.concurrency, probe_s=0.2,
      wedge_after=args.wedge_after,
      restart_budget=args.restart_budget, budget_window_s=600.0,
      backoff_base_s=0.1, backoff_max_s=1.0, slo=slo, events=events,
      log=log)
  t0 = time.time()
  drained = supervisor.run_until_drained(timeout_s=args.timeout_s)
  snap = supervisor.snapshot()
  counts = snap["queue"]["counts"]
  return {
      "metric": "train_queue_chaos",
      "value": counts["done"],
      "unit": "jobs",
      "dry": False,
      "drained": drained,
      "jobs": counts,
      "quarantines": snap["quarantines"],
      "wedges": snap["wedges"],
      "requeues": snap["requeues"],
      "failures": snap["failures"],
      "publishes": snap["publishes"],
      "publish_steps": publish.steps(),
      "poison_attempts": (queue.get("poison").attempts
                          if queue.get("poison") else None),
      "restart_budget": args.restart_budget,
      "slo": verdict(slo.snapshot()),
      "seconds": round(time.time() - t0, 1),
  }


def main(argv=None) -> int:
  ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
  ap.add_argument("--dry", action="store_true",
                  help="scripted fakes on a fake clock (tier-1 smoke); "
                       "TRAIN_QUEUE_DRY=1 implies it")
  ap.add_argument("--root", default="",
                  help="work directory (default: fresh temp dir)")
  ap.add_argument("--img-size", type=int, default=32)
  ap.add_argument("--num-planes", type=int, default=4)
  ap.add_argument("--concurrency", type=int, default=2)
  ap.add_argument("--restart-budget", type=int, default=1)
  ap.add_argument("--wedge-after", type=int, default=25,
                  help="stalled probes (at 0.2s cadence) before a hung "
                       "trainer is SIGKILLed — 5s of stall, enough to "
                       "clear real inter-step gaps at these toy sizes")
  ap.add_argument("--slo-step-latency-ms", type=float, default=60000.0)
  ap.add_argument("--timeout-s", type=float, default=600.0)
  args = ap.parse_args(argv)
  dry = args.dry or os.environ.get("TRAIN_QUEUE_DRY") == "1"
  out = run_dry(budget=args.restart_budget) if dry else run_real(args)
  print(json.dumps(out))
  return 0


if __name__ == "__main__":
  sys.exit(main())
