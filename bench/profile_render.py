"""Capture a jax.profiler trace of the fused render kernels (TPU only).

Writes a perfetto/tensorboard-compatible trace of steady-state frames of
every render tier at 1080p x 32 planes — separable (truck+dolly), shared
base (1-degree pan), shared wide-slice ladder (10-degree pan), and the
banded per-row tier (14-degree pan) — plus Pallas-backward gradients of
the base rotation path, under ``artifacts/trace_r05/``. All forward paths
run the PLANNED-JIT API (plan_fused once, then one compiled dispatch per
frame): eager check=True timing through the axon tunnel measures host
dispatch, not kernels (the round-4 lesson). The trace is the input for
kernel-level optimization (which ops bind: gathers, DMA waits, or the
scalar core) without needing live chip time to investigate.

One JSON line: value = 1.0 if the trace directory was written, with the
capture's frame timings as side fields. Off-TPU this is a no-op (emits
value 0.0) — interpret-mode traces carry no kernel timing.
"""

from __future__ import annotations

import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

import _common  # noqa: E402

TRACE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "artifacts", "trace_r05")


def main() -> None:
  import jax
  import jax.numpy as jnp

  from mpi_vision_tpu.core.camera import inv_depths
  from mpi_vision_tpu.kernels import render_pallas as rp

  if jax.default_backend() != "tpu":
    _common.log("no TPU: interpret-mode traces carry no kernel timing")
    _common.emit("render_profile_trace_written", 0.0, "bool", 0.0,
                 note="skipped off-TPU")
    return

  h, w, p = 1080, 1920, 32
  planes = jax.jit(lambda k: jax.random.uniform(k, (p, 4, h, w)))(
      jax.random.PRNGKey(0))
  jax.block_until_ready(planes)
  depths = jnp.asarray(np.asarray(inv_depths(1.0, 100.0, p)))
  k = np.array([[0.5 * w, 0, w / 2], [0, 0.5 * w, h / 2], [0, 0, 1]],
               np.float32)

  def homs_for(ry_deg, tx, tz):
    pose = np.eye(4, dtype=np.float32)
    r = np.radians(ry_deg)
    c, s = np.cos(r), np.sin(r)
    pose[:3, :3] = [[c, 0, s], [0, 1, 0], [-s, 0, c]]
    pose[0, 3], pose[2, 3] = tx, tz
    return rp.pixel_homographies(
        jnp.asarray(pose)[None], depths, jnp.asarray(k)[None], h, w)[:, 0]

  import functools
  import shutil
  import time

  cases = {
      "separable": homs_for(0.0, 0.08, -0.05),
      "rot1": homs_for(1.0, 0.05, -0.03),
      "rot10": homs_for(10.0, 0.05, 0.0),    # shared wide-slice ladder
      "banded14": homs_for(14.0, 0.05, 0.0),  # banded per-row tier
  }
  renderers = {}
  for name, case_homs in cases.items():
    bundle = rp.plan_fused(case_homs, h, w)
    if bundle is None:
      _common.log(f"{name}: plan_fused rejected the pose; skipping")
      continue
    fn = jax.jit(functools.partial(
        rp.render_mpi_fused, separable=bundle["separable"], check=False,
        plan=bundle["plan"], adj_plan=None))
    jax.block_until_ready(fn(planes, case_homs))   # compile outside trace
    renderers[name] = fn

  # Gradient through the fused render (the training hot path): warm up so
  # the trace holds steady-state kernels, not compiles.
  homs_rot = cases["rot1"]
  grad_rot = jax.jit(jax.grad(
      lambda pl_: jnp.sum(rp.render_mpi_fused(pl_, homs_rot,
                                              separable=False) ** 2)))
  jax.block_until_ready(grad_rot(planes))

  # Clear stale captures: a leftover trace from a killed previous run must
  # not let a failed capture report trace_written=1.0.
  shutil.rmtree(TRACE_DIR, ignore_errors=True)
  os.makedirs(TRACE_DIR, exist_ok=True)
  timings = {}
  with jax.profiler.trace(TRACE_DIR):
    for name, fn in renderers.items():
      iters = 20 if name in ("separable", "rot1") else 8
      t0 = time.perf_counter()
      for _ in range(iters):
        out = fn(planes, cases[name])
      jax.block_until_ready(out)
      timings[name] = (time.perf_counter() - t0) / iters
    t0 = time.perf_counter()
    for _ in range(5):
      g = grad_rot(planes)
    jax.block_until_ready(g)
    timings["rot1_grad"] = (time.perf_counter() - t0) / 5

  written = bool(glob.glob(os.path.join(TRACE_DIR, "**", "*.pb"),
                           recursive=True)
                 or glob.glob(os.path.join(TRACE_DIR, "**", "*.json.gz"),
                              recursive=True)
                 or glob.glob(os.path.join(TRACE_DIR, "**", "*.trace*"),
                              recursive=True))
  _common.log(f"trace at {TRACE_DIR} (written={written}); " + ", ".join(
      f"{k} {v * 1e3:.1f} ms" for k, v in timings.items()))
  _common.emit("render_profile_trace_written", 1.0 if written else 0.0,
               "bool", 1.0 if written else 0.0, trace_dir=TRACE_DIR,
               **{f"{k}_ms": round(v * 1e3, 2) for k, v in timings.items()})


if __name__ == "__main__":
  main()
