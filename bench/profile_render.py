"""Capture a jax.profiler trace of the fused render kernels (TPU only).

Writes a perfetto/tensorboard-compatible trace of ~20 frames of each
headline path — separable (truck+dolly) and general (1-degree pan) at
1080p x 32 planes — plus Pallas-backward gradients of the rotation path,
under ``artifacts/trace_r03/``. The trace is the input for the next round's
kernel-level optimization (which ops bind: gathers, DMA waits, or the
scalar core) without needing live chip time to investigate.

One JSON line: value = 1.0 if the trace directory was written, with the
capture's frame timings as side fields. Off-TPU this is a no-op (emits
value 0.0) — interpret-mode traces carry no kernel timing.
"""

from __future__ import annotations

import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

import _common  # noqa: E402

TRACE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "artifacts", "trace_r03")


def main() -> None:
  import jax
  import jax.numpy as jnp

  from mpi_vision_tpu.core.camera import inv_depths
  from mpi_vision_tpu.kernels import render_pallas as rp

  if jax.default_backend() != "tpu":
    _common.log("no TPU: interpret-mode traces carry no kernel timing")
    _common.emit("render_profile_trace_written", 0.0, "bool", 0.0,
                 note="skipped off-TPU")
    return

  h, w, p = 1080, 1920, 32
  planes = jax.jit(lambda k: jax.random.uniform(k, (p, 4, h, w)))(
      jax.random.PRNGKey(0))
  jax.block_until_ready(planes)
  depths = jnp.asarray(np.asarray(inv_depths(1.0, 100.0, p)))
  k = np.array([[0.5 * w, 0, w / 2], [0, 0.5 * w, h / 2], [0, 0, 1]],
               np.float32)

  def homs_for(ry_deg, tx, tz):
    pose = np.eye(4, dtype=np.float32)
    r = np.radians(ry_deg)
    c, s = np.cos(r), np.sin(r)
    pose[:3, :3] = [[c, 0, s], [0, 1, 0], [-s, 0, c]]
    pose[0, 3], pose[2, 3] = tx, tz
    return rp.pixel_homographies(
        jnp.asarray(pose)[None], depths, jnp.asarray(k)[None], h, w)[:, 0]

  homs_sep = homs_for(0.0, 0.08, -0.05)
  homs_rot = homs_for(1.0, 0.05, -0.03)

  # Warm up (compile outside the trace so the trace holds steady-state).
  jax.block_until_ready(rp.render_mpi_fused(planes, homs_sep, separable=True))
  jax.block_until_ready(rp.render_mpi_fused(planes, homs_rot,
                                            separable=False))

  # Gradient through the fused render (the training hot path): warm up so
  # the trace holds steady-state kernels, not compiles.
  grad_rot = jax.jit(jax.grad(
      lambda pl_: jnp.sum(rp.render_mpi_fused(pl_, homs_rot,
                                              separable=False) ** 2)))
  jax.block_until_ready(grad_rot(planes))

  import shutil
  import time
  # Clear stale captures: a leftover trace from a killed previous run must
  # not let a failed capture report trace_written=1.0.
  shutil.rmtree(TRACE_DIR, ignore_errors=True)
  os.makedirs(TRACE_DIR, exist_ok=True)
  with jax.profiler.trace(TRACE_DIR):
    t0 = time.perf_counter()
    for _ in range(20):
      out = rp.render_mpi_fused(planes, homs_sep, separable=True)
    jax.block_until_ready(out)
    t_sep = (time.perf_counter() - t0) / 20
    t0 = time.perf_counter()
    for _ in range(20):
      out = rp.render_mpi_fused(planes, homs_rot, separable=False)
    jax.block_until_ready(out)
    t_rot = (time.perf_counter() - t0) / 20
    t0 = time.perf_counter()
    for _ in range(5):
      g = grad_rot(planes)
    jax.block_until_ready(g)
    t_bwd = (time.perf_counter() - t0) / 5

  written = bool(glob.glob(os.path.join(TRACE_DIR, "**", "*.pb"),
                           recursive=True)
                 or glob.glob(os.path.join(TRACE_DIR, "**", "*.json.gz"),
                              recursive=True)
                 or glob.glob(os.path.join(TRACE_DIR, "**", "*.trace*"),
                              recursive=True))
  _common.log(f"trace at {TRACE_DIR} (written={written}); "
              f"separable {t_sep * 1e3:.1f} ms, rotation {t_rot * 1e3:.1f} ms, "
              f"rotation grad {t_bwd * 1e3:.1f} ms")
  _common.emit("render_profile_trace_written", 1.0 if written else 0.0,
               "bool", 1.0 if written else 0.0,
               separable_ms=round(t_sep * 1e3, 2),
               rotation_ms=round(t_rot * 1e3, 2),
               rotation_grad_ms=round(t_bwd * 1e3, 2), trace_dir=TRACE_DIR)


if __name__ == "__main__":
  main()
