"""BASELINE config 3: stereo plane-sweep cost volume, 64 depth hypotheses.

The "notebook pair" is RealEstate10K imagery the zero-egress environment
cannot fetch, so the stereo pair is the hermetic synthetic scene pair (the
same generator the data-pipeline tests use) at the notebook's 224^2 full
pipeline scale plus a 640x400 fixture-sized variant. Times the jitted
vmapped sweep (core/sweep.py — the projection path, utils.py:452-471) and
checks it against the torch oracle.

Metric: sweeps/s at 64 hypotheses, 224^2 (the notebook's image size).
Target: the reference computes its 10-plane PSV per sample inside a
40 s/150-scene epoch, i.e. ~3.75 sweeps/s CPU-side (cell 16); at 6.4x the
hypothesis count we keep that 3.75/s as the bar (beating it at 64
hypotheses means the PSV stage can never bottleneck a reference-style
epoch).

Usage: python bench/config3_sweep.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from _common import emit, log, time_fn

HYPOTHESES = 64
SIZE = 224
TARGET_SWEEPS_PER_S = 3.75
L1_BUDGET = 1e-3


def main() -> None:
  import jax
  import jax.numpy as jnp
  import torch

  from mpi_vision_tpu.core.camera import inv_depths
  from mpi_vision_tpu.core.sweep import plane_sweep
  from mpi_vision_tpu.torchref import oracle

  log(f"backend={jax.default_backend()}")
  rng = np.random.default_rng(0)
  img = rng.uniform(-1, 1, (1, SIZE, SIZE, 3)).astype(np.float32)
  pose = np.eye(4, dtype=np.float32)
  pose[0, 3] = 0.08                      # stereo baseline
  k = np.array([[0.9 * SIZE, 0, SIZE / 2], [0, 0.9 * SIZE, SIZE / 2],
                [0, 0, 1]], np.float32)
  depths = jnp.asarray(np.asarray(inv_depths(1.0, 100.0, HYPOTHESES)))

  fn = jax.jit(plane_sweep)
  psv, sec = time_fn(fn, jnp.asarray(img), depths, jnp.asarray(pose)[None],
                     jnp.asarray(k)[None], iters=20)
  log(f"psv {psv.shape}: {sec * 1e3:.1f} ms/sweep -> {1 / sec:.2f} sweeps/s")

  want = oracle.plane_sweep(
      torch.from_numpy(img), torch.from_numpy(np.asarray(depths)),
      torch.from_numpy(pose)[None], torch.from_numpy(k)[None]).numpy()
  l1 = float(np.abs(np.asarray(psv) - want).max())
  log(f"L1 vs torch oracle: {l1:.2e}")
  if l1 > L1_BUDGET:
    raise SystemExit(f"PSV L1 {l1} exceeds the {L1_BUDGET} parity budget")

  emit("plane_sweep_64hyp_224_sweeps_per_s", 1.0 / sec, "sweeps/s",
       (1.0 / sec) / TARGET_SWEEPS_PER_S, l1_vs_torch=l1,
       hypotheses=HYPOTHESES)


if __name__ == "__main__":
  main()
