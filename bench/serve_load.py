"""Closed-loop load generator for the serve/ subsystem.

Drives ``serve.RenderService`` in-process (no sockets — the HTTP shell is
a thin JSON wrapper; what this measures is the cache -> scheduler ->
engine path, which is where batching and tail latency live). C worker
threads run a closed loop: pick a scene (round-robin with a hot-scene
skew so the cache sees realistic reuse), draw a small random pose, call
``service.render``, repeat. Closed loop means offered concurrency == C,
so micro-batching is exercised exactly as a threaded HTTP front end
would exercise it.

Prints ONE JSON line (stdout; diagnostics on stderr) with the headline
serving numbers::

  {"metric": "serve_load", "value": <renders_per_sec>,
   "unit": "renders/s", "renders_per_sec": ..., "p50_ms": ...,
   "p99_ms": ..., "cache_hit_rate": ..., ...}

``--dry`` (env ``SERVE_LOAD_DRY=1``) shrinks scenes and duration so the
whole loop runs in seconds on CPU — the tier-1 smoke mode
(tests/test_serve_load_dry.py), mirroring bench.py's BENCH_DRY.

``--chaos`` wraps the engine in ``serve.FaultyEngine`` with a seeded,
deterministic fault schedule (transient errors + slow dispatches) and
lets workers ride the resilience layer instead of aborting — the JSON
line then carries the chaos injection accounting next to the usual
serving numbers. ``--chaos --dry`` is the tier-1-safe chaos smoke.
Error/resilience counters and the final breaker state are in the JSON
on EVERY run (chaos or not), so outage behavior trends across BENCH
rounds.

``--trace`` turns on request tracing (``obs.Tracer``) and adds a
``trace`` block — finished-trace count, slowest exemplar, and the span
names covering the request path; ``--trace --dry`` is the tier-1 smoke
pinning the span tree end to end.

``--cluster`` measures the multi-host tier instead: spawn N real backend
processes (``serve/cluster.BackendPool``), route closed-loop traffic
through a ``Router`` (consistent-hash placement, per-backend breakers),
and — unless ``--no-cluster-kill`` — SIGKILL one backend mid-window as a
chaos phase, so the JSON records failover behavior (reroutes, breaker
isolation, post-kill throughput) next to the usual serving numbers.
``--cluster --dry`` is the tier-1 smoke. ``--chaos-crashloop`` swaps the
single kill for the self-healing drill: the fleet supervisor
(``serve/cluster/supervisor.py``) runs over the pool and one backend is
killed every time it comes back up until its ``--restart-budget``
quarantines it; the JSON then records restarts, containment (the
quarantine), and post-quarantine throughput. ``--chaos-router`` is the
router-HA drill: TWO router replica processes (gossip peers behind one
on-disk supervision lease) front the pool, the supervising router is
SIGKILLed under live load on the other, and the JSON records the pinned
arc — zero failed requests on the survivor, the bounded lease takeover,
and a backend killed AFTER the takeover still respawned through the new
leader's restart webhook. ``--chaos-router --dry`` is the tier-1 smoke.

``--tiled-ab`` measures the tile-granular serving path
(``serve/tiles.py``): the SAME closed-loop load over ONE high-res
depth-stratified scene, once through a tiled service (fixed tile grid,
frustum-culled crops, content-culled planes) and once through the
monolithic path, in one process. The pose pool pans/tilts a narrow-FOV
camera across the scene so frusta touch a *fraction* of the tiles —
the Tiled-MPI serving shape — and the JSON line carries both arms, the
p50/throughput ratios, the tiles touched/culled accounting, and the
PINNED parity block: the full-coverage (identity) pose must render
bit-exactly equal in both arms or the run aborts. ``--tiled-ab --dry``
is the tier-1 smoke.

``--overload-ab`` measures the brownout ladder (``serve/brownout.py``):
the SAME phased closed-loop load — a baseline window, a ramp to ~3x the
baseline worker count, then a recovery tail — run once with the
brownout controller armed and once shed-only (no controller; overload
resolves by queue-full 503s alone), in one process. Workers carry the
priority-class mix (half interactive, a quarter each prefetch and
background) and the JSON line carries both arms: per-class goodput,
interactive p99, shed/degrade accounting, the sampled brownout level
trajectory (which must return to L0 in the tail), and each arm's SLO
verdict — the brownout arm holds its availability objective through the
ramp while the shed-only arm violates it. ``--overload-ab --dry`` is
the tier-1 smoke.

``--inflight N`` sets the streaming-pipeline window (concurrent
in-flight batches; 1 = the legacy blocking dispatch) and the JSON gains
the pipeline accounting: ``dispatch_gap`` (device idle between
flights — the "device never waits on the host" proof),
``out_of_order_completions``, ``abandoned_batches``, and the per-scene
latency breakdown. ``--ab`` runs the SAME load twice — pipelined
(``--inflight``) then blocking (window 1) — in one process and emits a
single ``serve_load_ab`` JSON line with both arms plus the speedup, so
the streaming win is measurable on the CPU path and trendable across
BENCH rounds. ``--ab --dry`` is the tier-1 smoke.

Usage: python bench/serve_load.py [--duration 10] [--concurrency 8] ...
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from _common import log as _log


def build_parser() -> argparse.ArgumentParser:
  ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
  ap.add_argument("--duration", type=float, default=10.0,
                  help="measured load seconds (after warm-up)")
  ap.add_argument("--concurrency", type=int, default=8,
                  help="closed-loop worker threads")
  ap.add_argument("--scenes", type=int, default=4)
  ap.add_argument("--img-size", type=int, default=256)
  ap.add_argument("--num-planes", type=int, default=16)
  ap.add_argument("--max-batch", type=int, default=8)
  ap.add_argument("--max-wait-ms", type=float, default=3.0)
  ap.add_argument("--inflight", type=int, default=4,
                  help="streaming-pipeline window (concurrent in-flight "
                       "batches; 1 = legacy blocking dispatch)")
  ap.add_argument("--ab", action="store_true",
                  help="run the load twice — pipelined (--inflight) vs "
                       "blocking (window 1) — and emit one serve_load_ab "
                       "JSON line with both arms + speedup")
  ap.add_argument("--edge", action="store_true",
                  help="serve through the pose-quantized edge frame "
                       "cache (serve/edge/) and report its hit/warp/"
                       "miss accounting")
  ap.add_argument("--edge-ab", action="store_true",
                  help="run the load twice — edge cache on, then off — "
                       "and emit one serve_load_edge_ab JSON line with "
                       "both arms, the p50 drop, and the hit rate")
  ap.add_argument("--edge-trans-cell", type=float, default=0.02,
                  help="edge view-cell translation pitch (--edge/"
                       "--edge-ab); the bench default is finer than the "
                       "serve default so warps show next to exact hits")
  ap.add_argument("--asset-ab", action="store_true",
                  help="measure the content-addressed asset delivery "
                       "tier (serve/assets): manifest+asset cold fetch, "
                       "304 revalidation, and a cross-process tile-diff "
                       "SceneFetcher sync (full vs quarter-scene diff "
                       "bytes) in one process; emits one "
                       "serve_load_asset_ab JSON line. --asset-ab --dry "
                       "is the tier-1 smoke")
  ap.add_argument("--overload-ab", action="store_true",
                  help="brownout-vs-shed-only A/B under a ~3x traffic "
                       "ramp (serve/brownout.py): per-class goodput, "
                       "interactive p99, level trajectory, and both "
                       "arms' SLO verdicts in one "
                       "serve_load_overload_ab JSON line. "
                       "--overload-ab --dry is the tier-1 smoke")
  ap.add_argument("--tiled-ab", action="store_true",
                  help="run the load twice — tile-granular service "
                       "(frustum-culled crops) vs monolithic — over one "
                       "high-res depth-stratified scene with a panning "
                       "narrow-FOV pose pool, and emit one "
                       "serve_load_tiled_ab JSON line with both arms, "
                       "the tile accounting, and the pinned bit-exact "
                       "full-coverage parity check")
  ap.add_argument("--tile-size", type=int, default=64,
                  help="tile edge in pixels for the tiled arm "
                       "(--tiled-ab; dry mode shrinks it with the scene)")
  ap.add_argument("--tiled-regions", type=int, default=4,
                  help="depth-staircase regions per scene axis "
                       "(--tiled-ab; see synthetic_tiled_scene)")
  ap.add_argument("--fov-scale", type=float, default=2.0,
                  help="target-camera focal length as a multiple of the "
                       "scene width (--tiled-ab): > 1 narrows the FOV so "
                       "pan/tilt poses view a fraction of the scene — "
                       "the frustum-culling workload")
  ap.add_argument("--zipf-poses", type=int, default=0,
                  help="draw poses Zipf-distributed from a pool of this "
                       "many fixed poses (rank r with p ~ 1/r^s) instead "
                       "of fresh-random — the orbit-a-hot-viewpoint "
                       "traffic shape the edge cache exists for; 0 = "
                       "fresh random poses")
  ap.add_argument("--zipf-s", type=float, default=1.1,
                  help="Zipf exponent for --zipf-poses")
  ap.add_argument("--cache-mb", type=int, default=2048)
  ap.add_argument("--method", default="fused",
                  choices=("fused", "scan", "assoc"))
  ap.add_argument("--sharded", default="auto", choices=("auto", "on", "off"))
  ap.add_argument("--seed", type=int, default=0)
  ap.add_argument("--dry", action="store_true",
                  help="tier-1 smoke mode: tiny scenes, ~2 s of load "
                       "(also env SERVE_LOAD_DRY=1)")
  ap.add_argument("--chaos", action="store_true",
                  help="inject scheduled faults (FaultyEngine) and report "
                       "the resilience layer's accounting")
  ap.add_argument("--chaos-error-rate", type=float, default=0.12,
                  help="per-dispatch transient-error probability")
  ap.add_argument("--chaos-slow-rate", type=float, default=0.04,
                  help="per-dispatch slow-dispatch probability")
  ap.add_argument("--trace", action="store_true",
                  help="trace every request (obs.Tracer) and report the "
                       "trace accounting + slowest-exemplar span names "
                       "in the JSON")
  ap.add_argument("--incident-dir", type=str, default="",
                  help="arm the SLO-triggered incident recorder "
                       "(obs/incident.py) in the --overload-ab arms "
                       "(bundles under <dir>/<arm>/) and run the "
                       "deterministic capture drill (<dir>/drill/); the "
                       "JSON carries per-arm incident stats + the drill "
                       "verdict")
  ap.add_argument("--cluster", action="store_true",
                  help="measure the multi-host tier: spawn backend "
                       "processes, route through serve/cluster.Router, "
                       "and (default) SIGKILL one backend mid-window")
  ap.add_argument("--cluster-backends", type=int, default=3,
                  help="backend processes to spawn (--cluster)")
  ap.add_argument("--cluster-replication", type=int, default=2,
                  help="ring replication factor (--cluster)")
  ap.add_argument("--cluster-kill", action=argparse.BooleanOptionalAction,
                  default=True,
                  help="SIGKILL the hottest scene's primary backend at "
                       "half the measured window (--cluster)")
  ap.add_argument("--chaos-crashloop", action="store_true",
                  help="crash-loop drill (--cluster): run the fleet "
                       "supervisor, kill one backend every time it "
                       "comes back up until its restart budget "
                       "quarantines it, and report restarts / "
                       "containment / post-quarantine throughput")
  ap.add_argument("--restart-budget", type=int, default=2,
                  help="supervisor restarts allowed before the "
                       "crash-looping backend is quarantined "
                       "(--chaos-crashloop)")
  ap.add_argument("--chaos-router", action="store_true",
                  help="router-HA drill (--cluster): TWO router "
                       "replicas (gossip peers, shared supervision "
                       "lease) front the pool; the supervising router "
                       "is SIGKILLed under live load — traffic on the "
                       "survivor must not fail, the lease must be "
                       "taken over, and a backend killed AFTER the "
                       "takeover must still be respawned (through the "
                       "new leader's restart hook)")
  ap.add_argument("--autoscale-ab", action="store_true",
                  help="elastic-fleet A/B (--cluster): replay the same "
                       "~3x traffic ramp against a fixed single-backend "
                       "pool and against the autoscaler, and report p99 "
                       "+ backend-count + brownout-level trajectories, "
                       "a calibrated SLO verdict per arm, and the "
                       "scale-down zero-drop check")
  ap.add_argument("--session", action="store_true",
                  help="serve the closed loop through pose-in/frame-out "
                       "streaming sessions (POST /session over real "
                       "sockets): per-client smooth camera trajectories, "
                       "pipelined poses fused into shared device "
                       "flights, trajectory-predictive edge-cache "
                       "prefetch (serve/session/)")
  ap.add_argument("--session-ab", action="store_true",
                  help="session-vs-request-per-frame A/B: the same "
                       "smooth trajectories replayed once through "
                       "streaming sessions and once as one POST /render "
                       "per frame, in one process, plus the PINNED "
                       "bit-exactness check (session frames == direct "
                       "renders of the same poses, edge off); "
                       "--session-ab --dry is the tier-1 smoke")
  return ap


def chaos_schedule(seed: int, error_rate: float, slow_rate: float,
                   slow_s: float = 0.02):
  """A deterministic ``dispatch_index -> Fault | None`` schedule.

  Each dispatch index draws from its own ``random.Random(f"{seed}:{idx}")``
  stream, so the schedule is a pure function of (seed, index) — two runs
  at one seed inject byte-identical fault sequences regardless of thread
  timing. (String seeds: tuple seeding is gone in Python 3.11+.)
  """
  from mpi_vision_tpu.serve import Fault

  def schedule(idx: int):
    x = random.Random(f"{seed}:{idx}").random()
    if x < error_rate:
      return Fault("error")
    if x < error_rate + slow_rate:
      return Fault("slow", seconds=slow_s)
    return None

  return schedule


def slo_window_config(duration: float):
  """Objectives sized to the measured window so the verdict block judges
  THIS run: the fast window reacts inside the load window (alerts can
  fire and clear during a chaos phase) and the slow window spans the
  whole measurement (the report card covers every request). The
  histogram-quantile objective (p99 under the latency threshold, judged
  from the pooled native histogram) and its per-scene variant are on so
  every BENCH line trends a percentile-true p99 verdict, not just
  threshold counts."""
  from mpi_vision_tpu.obs import SloConfig

  fast = max(duration / 4.0, 0.5)
  return SloConfig(fast_window_s=fast,
                   slow_window_s=max(2.0 * duration, fast),
                   bucket_s=max(fast / 8.0, 0.1),
                   quantile=0.99, per_scene=True)


def attrib_record(stats: dict) -> dict:
  """The bench JSON's attribution block: bounded top cells, the window
  totals, and the conservation verdict (cell sums reconciled against
  the metrics layer's own request/phase totals). Empty when the run's
  service had no ledger, so older record consumers see no key at all
  rather than a null."""
  snap = stats.get("attrib")
  if not snap:
    return {}
  return {"attrib": {
      "cells_total": snap["cells_total"],
      "overflow_requests": snap["overflow_requests"],
      "totals": snap["totals"],
      "top_cells": snap["cells"][:8],
      "conservation": snap.get("conservation"),
  }}


def device_seconds_by_class(stats: dict) -> dict | None:
  """Device seconds summed per request class from the attribution cells
  — the overload A/B's resource answer: the ladder should shift device
  time toward interactive work, not just admit more of it."""
  snap = stats.get("attrib")
  if not snap:
    return None
  out: dict = {}
  for cell in snap["cells"]:
    out[cell["class"]] = out.get(cell["class"], 0.0) + sum(
        (cell.get("device_s") or {}).values())
  return {c: round(s, 6) for c, s in sorted(out.items(), key=str)}


def cluster_slo_verdict(router_stats: dict) -> dict | None:
  """The fleet-level pass/fail block from the router's aggregated view
  (pool-weighted slow-window attainment vs the backends' targets)."""
  fleet = router_stats.get("slo") or {}
  attainment = fleet.get("attainment") or {}
  targets = None
  for st in router_stats.get("backends", {}).values():
    slo = st.get("slo") if isinstance(st, dict) else None
    if isinstance(slo, dict) and "objectives" in slo:
      # Quantile objectives carry a threshold, not a fractional target;
      # the fleet attainment table only scores the fractional ones.
      targets = {n: o["target"] for n, o in slo["objectives"].items()
                 if "target" in o}
      break
  if not targets or not attainment:
    return None
  out = {"objectives": {},
         "alerts_firing": dict(fleet.get("alerts_firing", {}))}
  ok, scored = True, False
  for name, tot in sorted(attainment.items()):
    target = targets.get(name)
    attained = tot["attained"]
    passed = (None if attained is None or target is None
              else attained >= target)
    out["objectives"][name] = {
        "target": target, "attained": attained,
        "requests": tot["requests"], "pass": passed,
    }
    if passed is not None:
      scored = True
      ok = ok and passed
  out["pass"] = ok if scored else None
  return out


def random_pose(rng: np.random.Generator) -> np.ndarray:
  """A small random truck/dolly/pedestal move (typical MPI viewing)."""
  pose = np.eye(4, dtype=np.float32)
  pose[:3, 3] = rng.uniform(-0.05, 0.05, 3).astype(np.float32)
  return pose


def zipf_pose_sampler(n: int, s: float, seed: int):
  """``rng -> pose`` drawing from ``n`` fixed poses with Zipf(s) ranks.

  The pool is a pure function of the seed (workers share it; their own
  rngs only pick ranks), so repeat draws of a popular rank are the SAME
  pose — the exact-reuse traffic a view-cell cache monetizes, with a
  long tail of rarely-seen poses that miss, exactly like a hot scene
  orbit plus stragglers.
  """
  pool_rng = np.random.default_rng([seed, 777])
  pool = [random_pose(pool_rng) for _ in range(n)]
  weights = 1.0 / np.arange(1, n + 1, dtype=np.float64) ** s
  cumulative = np.cumsum(weights / weights.sum())

  def sample(rng: np.random.Generator) -> np.ndarray:
    return pool[int(np.searchsorted(cumulative, rng.random()))]

  return sample


def cluster_main(args) -> int:
  """The --cluster measurement: real backend processes, routed traffic,
  and a chaos phase — either the classic single SIGKILL (failover) or,
  with ``--chaos-crashloop``, a supervised crash loop: one backend dies
  every time it comes back up until its restart budget quarantines it.
  One JSON line like the in-process path, plus a ``cluster`` block
  (failovers, breaker isolation, per-backend forwards, post-kill /
  post-quarantine throughput, supervisor accounting)."""
  from mpi_vision_tpu.serve.cluster import (
      BackendPool,
      FleetSupervisor,
      Router,
  )

  env = dict(os.environ)
  env.setdefault("JAX_PLATFORMS", "cpu")  # N local procs share one box
  pool = BackendPool(
      args.cluster_backends, scenes=args.scenes, img_size=args.img_size,
      planes=args.num_planes, seed=args.seed, env=env, log=_log)
  supervisor = None
  try:
    _log(f"serve_load: spawning {args.cluster_backends} backend(s) "
         f"[{args.scenes} scenes {args.img_size}x{args.img_size}"
         f"x{args.num_planes}]")
    backends = pool.start()
    # Quick breaker so the kill phase shows isolation INSIDE the window:
    # a couple of failed forwards open the dead backend's circuit and
    # traffic stops probing the corpse.
    router = Router(backends, replication=args.cluster_replication,
                    breaker_threshold=2, breaker_reset_s=60.0,
                    render_timeout_s=60.0)
    ids = pool.scene_ids()
    victim = (router.placement(ids[0])[0]
              if (args.cluster_kill or args.chaos_crashloop) else None)
    if args.chaos_crashloop:
      # Fast supervision so the whole detect -> restart -> quarantine
      # arc lands inside the bench window; the budget window is wide so
      # every injected crash counts toward containment.
      supervisor = FleetSupervisor(
          pool, router=router, events=router.events, probe_s=0.1,
          restart_budget=args.restart_budget, budget_window_s=600.0,
          backoff_base_s=0.2, backoff_max_s=1.0, log=_log).start()

    stop = threading.Event()
    counts = [0] * args.concurrency
    post_kill_counts = [0] * args.concurrency
    post_quarantine_counts = [0] * args.concurrency
    killed = threading.Event()
    quarantined_evt = threading.Event()
    failure_counts: collections.Counter = collections.Counter()
    failure_lock = threading.Lock()

    # Every cluster record carries a sampled pool-size/brownout-level
    # timeline — scaling (and chaos) trajectories are inspectable even
    # with the autoscaler off.
    timeline: list[dict] = []
    timeline_stop = threading.Event()

    def timeline_sampler(t_start: float) -> None:
      from mpi_vision_tpu.serve.brownout import fleet_scale_signal

      step = max(args.duration / 100.0, 0.05)
      level = 0
      n = 0
      while not timeline_stop.is_set():
        if n % 10 == 0:
          # The /stats fan-out is the expensive half; refresh the
          # brownout level at a tenth of the sampling cadence.
          try:
            level = fleet_scale_signal(
                router.stats().get("brownout"))["max_level"]
          except Exception:  # noqa: BLE001 - sampling outlives chaos
            pass
        timeline.append({
            "t": round(time.perf_counter() - t_start, 3),
            "backends": len(router.backend_ids()),
            "ejected": len(router.ejected()),
            "brownout_max_level": level,
        })
        n += 1
        timeline_stop.wait(step)

    def worker(idx: int) -> None:
      rng = np.random.default_rng(args.seed + 1 + idx)
      while not stop.is_set():
        sid = ids[0] if (rng.random() < 0.5 or len(ids) == 1) \
            else ids[int(rng.integers(1, len(ids)))]
        body = json.dumps({"scene_id": sid,
                           "pose": random_pose(rng).tolist()}).encode()
        try:
          status, _, _ = router.forward_render(sid, body)
        except Exception as e:  # noqa: BLE001 - chaos is the workload
          with failure_lock:
            failure_counts[type(e).__name__] += 1
          time.sleep(0.005)
          continue
        if status != 200:
          with failure_lock:
            failure_counts[f"http_{status}"] += 1
          continue
        counts[idx] += 1
        if killed.is_set():
          post_kill_counts[idx] += 1
        if quarantined_evt.is_set():
          post_quarantine_counts[idx] += 1

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(args.concurrency)]
    t0 = time.perf_counter()
    for t in threads:
      t.start()
    sampler = threading.Thread(target=timeline_sampler, args=(t0,),
                               daemon=True)
    sampler.start()
    crashloop = None
    if args.chaos_crashloop:
      time.sleep(args.duration / 4)  # clean phase
      _log(f"serve_load: crash-looping {victim} (restart budget "
           f"{args.restart_budget})")
      kills = 0
      crash_t0 = time.perf_counter()
      # The arc is respawn-bound (each restart is a real process spawn),
      # so the loop runs on its own deadline, not the load window's.
      # Dry mode (the tier-1 smoke) fails FAST on a containment
      # regression — a 300 s spin inside the suite would mask the real
      # failure as a global tier-1 timeout.
      crash_deadline = crash_t0 + (45.0 if args.dry else 300.0)
      while time.perf_counter() < crash_deadline:
        state = supervisor.state(victim)
        if state == FleetSupervisor.QUARANTINED:
          break
        if state in (None, FleetSupervisor.UP) and pool.alive(victim):
          pool.kill(victim)
          kills += 1
          killed.set()
        time.sleep(0.05)
      quarantine_after_s = time.perf_counter() - crash_t0
      contained = supervisor.state(victim) == FleetSupervisor.QUARANTINED
      if contained:
        # Only a real quarantine starts the post-quarantine window — a
        # containment regression must not fabricate a trendable
        # post-quarantine throughput number.
        quarantined_evt.set()
      _log(f"serve_load: {victim} "
           + (f"quarantined after {kills} kills "
              f"({quarantine_after_s:.1f}s)" if contained
              else "NOT quarantined before the drill deadline"))
      time.sleep(args.duration / 2)  # post-quarantine measured tail
      sup_snap = supervisor.snapshot()
      crashloop = {
          "victim": victim,
          "kills": kills,
          "restart_budget": args.restart_budget,
          "restarts": sup_snap["backends"].get(victim, {}).get(
              "restarts", 0),
          "quarantined": contained,
          "quarantine_after_s": round(quarantine_after_s, 3),
          "post_quarantine_requests": (sum(post_quarantine_counts)
                                       if contained else None),
          "post_quarantine_rps": (round(
              sum(post_quarantine_counts) / max(args.duration / 2, 1e-9),
              3) if contained else None),
          "events": {
              "backend_restart": router.events.count("backend_restart"),
              "backend_quarantined":
                  router.events.count("backend_quarantined"),
          },
      }
    elif victim is not None:
      time.sleep(args.duration / 2)
      pool.kill(victim)
      killed.set()
      _log(f"serve_load: killed backend {victim} at half-window "
           f"(scenes fail over to replicas)")
      time.sleep(args.duration / 2)
    else:
      time.sleep(args.duration)
    stop.set()
    timeline_stop.set()
    for t in threads:
      t.join(60)
    sampler.join(10)
    elapsed = time.perf_counter() - t0
    if supervisor is not None:
      supervisor.stop()

    total = sum(counts)
    if total == 0:
      raise SystemExit("serve_load: no requests completed in the window")
    snap = router.metrics.snapshot()
    health = router.healthz()
    rstats = router.stats()  # one fan-out: backend slo blocks + summary
    breakers = {b: snap["state"] for b, snap in health["breakers"].items()}
    rps = total / elapsed
    record = {
        "metric": "serve_load",
        "value": round(rps, 3),
        "unit": "renders/s",
        "renders_per_sec": round(rps, 3),
        "requests": total,
        "concurrency": args.concurrency,
        "dry": bool(args.dry),
        "chaos": False,
        "cluster": {
            "backends": len(backends),
            "replication": args.cluster_replication,
            "killed": victim,
            "post_kill_requests": sum(post_kill_counts),
            "failovers": snap["failovers"],
            "replica_exhausted": snap["replica_exhausted"],
            "breaker_fastfails": snap["breaker_fastfails"],
            "retry_budget_exhausted": snap["retry_budget_exhausted"],
            "restarts": snap["restarts"],
            "quarantines": snap["quarantines"],
            "forwards": snap["forwards"],
            "breakers": breakers,
            "ejected": health["ejected"],
            "health": health["status"],
            "failed_requests": dict(sorted(failure_counts.items())),
            "timeline": timeline,
            # Fleet SLO state as the router aggregates it (firing
            # alerts per backend, hottest burns, pooled attainment).
            "slo": rstats.get("slo"),
            **({"crashloop": crashloop} if crashloop is not None else {}),
        },
        # The same verdict block the in-process runs carry, judged from
        # the pool-weighted slow-window attainment.
        "slo": cluster_slo_verdict(rstats),
    }
    print(json.dumps(record))
    return 0
  finally:
    if supervisor is not None:
      supervisor.stop()
    pool.close()


def _autoscale_arm(args, autoscale: bool, duration: float = None) -> dict:
  """One --autoscale-ab arm: a pool of ONE backend under a ~3x traffic
  ramp (paced baseline -> closed-loop surge -> paced tail). The
  ``autoscale`` arm runs the supervisor + autoscaler over it (queue
  pressure earns capacity, post-surge idleness retires it); the fixed
  arm rides the same ramp on its single backend. Emits p99 +
  backend-count + brownout-level trajectories and a calibrated SLO
  verdict judged over the surge's second half — by then the autoscaler
  has warmed and admitted capacity, and the fixed pool is still
  drowning in its queue."""
  from mpi_vision_tpu.serve.brownout import fleet_scale_signal
  from mpi_vision_tpu.serve.cluster import (
      AutoscaleConfig,
      AutoscalePolicy,
      Autoscaler,
      BackendPool,
      FleetSupervisor,
      Router,
  )

  env = dict(os.environ)
  env.setdefault("JAX_PLATFORMS", "cpu")
  # Mid-window spawns race the surge for CPU: the compilation cache
  # keeps a scaled-up backend's startup to process + import cost.
  env.setdefault("JAX_COMPILATION_CACHE_DIR",
                 os.path.join(os.path.dirname(os.path.dirname(
                     os.path.abspath(__file__))), ".jax_cache"))
  duration = args.duration if duration is None else duration
  arm = "autoscale" if autoscale else "fixed"
  # The bounded backend queue IS the verdict's yardstick: one backend
  # cannot hold the whole surge inside it (overflow 503s -> availability
  # violations), two backends trivially can. A capacity bound is
  # deterministic on a noisy shared-CPU box where latency quantiles are
  # not — the queue still builds real DEPTH first (the scale-up signal).
  extra = ["--max-batch", str(args.max_batch),
           "--max-wait-ms", str(args.max_wait_ms),
           "--max-queue", str(max(8, 2 * args.concurrency))]
  pool = BackendPool(1, scenes=args.scenes, img_size=args.img_size,
                     planes=args.num_planes, seed=args.seed, env=env,
                     extra_args=extra, log=_log)
  supervisor = None
  try:
    _log(f"serve_load: autoscale-ab arm '{arm}' — 1 backend, "
         f"base {args.concurrency} paced workers, surge to "
         f"{3 * args.concurrency} closed-loop")
    backends = pool.start()
    # Queue-full 503s are the WORKLOAD here, not a backend death: a
    # high threshold + fast reset keeps the breaker from latching the
    # only backend open and converting overload into a fake outage.
    router = Router(backends, replication=2, breaker_threshold=25,
                    breaker_reset_s=0.5, render_timeout_s=60.0)
    ids = pool.scene_ids()

    # Client-side calibration through the router: the objective is a
    # multiple of THIS box's single-stream ROUTED render (HTTP hop
    # included), so the verdict is meaningful on any CPU.
    rng = np.random.default_rng(args.seed)
    samples = []
    for _ in range(5):
      body = json.dumps({"scene_id": ids[0],
                         "pose": random_pose(rng).tolist()}).encode()
      t_req = time.perf_counter()
      router.forward_render(ids[0], body)
      samples.append(time.perf_counter() - t_req)
    single = float(np.median(samples))
    # 10x the unloaded median, floored at 120ms: sits between the two
    # operating points the A/B is built to separate. The closed-loop
    # saturation knee is near-vertical (measured on one dry backend:
    # p99 ~42ms at 8 streams, ~1s at 16), so backends UNDER the knee
    # pass with >30ms margin and a lone backend pushed past it by the
    # surge fails by hundreds of ms — the verdict is not noise-scale.
    objective_s = max(10.0 * single, 0.12)

    drain_s = max(duration / 40.0, 0.1)
    autoscaler = None
    if autoscale:
      # max 2: ONE earned backend halves the surge. A deeper pool would
      # keep spawning — and on a single CPU host every cold spawn steals
      # cores from serving, polluting the judged window it paid for.
      config = AutoscaleConfig(
          min_backends=1, max_backends=2,
          # Trip on sustained depth >= 2 (the paced baseline holds ~0;
          # only the closed-loop surge can keep a queue at all) — the
          # spawn must START as early in the ramp as possible, because
          # it races the surge itself for cores. Recover at 0.5: dips
          # mid-band freeze the accumulated pressure, not reset it.
          queue_high=1.5, queue_recover=0.5,
          # Queue depth is this drill's ONLY trip signal. The bounded
          # queue converts the pre-admit surge into 503s, which keep
          # the SLO fast-burn above its recover band long past the
          # surge — with burn in the calm gate the idle timer would
          # never run and the scale-down could not be demonstrated.
          burn_high=1e9, burn_recover=1e8,
          up_sustain_s=duration / 100.0,
          down_sustain_s=duration / 12.0,
          up_cooldown_s=duration / 40.0,
          down_cooldown_s=duration / 40.0,
          budget=6, budget_window_s=600.0)
      autoscaler = Autoscaler(
          AutoscalePolicy(config), pool, router, events=router.events,
          scenes=ids, eval_interval_s=duration / 100.0,
          drain_s=drain_s, log=_log)
      supervisor = FleetSupervisor(
          pool, router=router, events=router.events, probe_s=0.1,
          load_refresh_s=duration / 100.0, autoscaler=autoscaler,
          log=_log).start()

    n_base = args.concurrency
    n_total = 5 * args.concurrency
    ramp = (0.08 * duration, 0.8 * duration)
    # Judge ONLY the surge's final stretch: a cold spawn races the surge
    # itself for cores (roughly 8-15s from fire to warmed admit on a
    # contended CPU host), so the earned capacity only shows near the
    # ramp's end — while the fixed arm is still queuing there.
    judge = (0.68 * duration, 0.8 * duration)
    stop = threading.Event()
    lock = threading.Lock()
    latencies: list[tuple[float, float]] = []  # (t_rel, seconds)
    failures: list[tuple[float, str]] = []     # (t_rel, kind)
    t0 = time.perf_counter()
    wall_t0 = time.time()

    def worker(idx: int) -> None:
      w_rng = np.random.default_rng(args.seed + 1 + idx)
      surge = idx >= n_base
      while not stop.is_set():
        now = time.perf_counter() - t0
        if surge and now < ramp[0]:
          time.sleep(0.005)
          continue
        if surge and now >= ramp[1]:
          return
        sid = ids[0] if (w_rng.random() < 0.5 or len(ids) == 1) \
            else ids[int(w_rng.integers(1, len(ids)))]
        body = json.dumps({"scene_id": sid,
                           "pose": random_pose(w_rng).tolist()}).encode()
        t_req = time.perf_counter()
        try:
          status, _, _ = router.forward_render(sid, body)
        except Exception as e:  # noqa: BLE001 - overload is the workload
          with lock:
            failures.append((round(time.perf_counter() - t0, 3),
                             type(e).__name__))
          time.sleep(0.005)
          continue
        if status != 200:
          with lock:
            failures.append((round(time.perf_counter() - t0, 3),
                             f"http_{status}"))
          continue
        with lock:
          latencies.append((round(time.perf_counter() - t0, 3),
                            time.perf_counter() - t_req))
        if not surge:
          # The paced baseline/tail: low utilization is the
          # scale-down signal, so base load must not be closed-loop.
          time.sleep(duration / 30.0)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(n_total)]
    for t in threads:
      t.start()
    timeline: list[dict] = []
    step = max(duration / 100.0, 0.05)
    level = 0
    n = 0
    while time.perf_counter() - t0 < duration:
      if n % 10 == 0:
        try:
          level = fleet_scale_signal(
              router.stats().get("brownout"))["max_level"]
        except Exception:  # noqa: BLE001 - sampling outlives scaling
          pass
      timeline.append({
          "t": round(time.perf_counter() - t0, 3),
          "backends": len(router.backend_ids()),
          "ejected": len(router.ejected()),
          "brownout_max_level": level,
      })
      n += 1
      time.sleep(step)
    stop.set()
    for t in threads:
      t.join(60)
    elapsed = time.perf_counter() - t0
    if supervisor is not None:
      supervisor.stop()

    if not latencies:
      raise SystemExit(
          f"serve_load: autoscale-ab arm '{arm}' completed no requests")
    lat_all = [s for _, s in latencies]
    judged = [s for t, s in latencies if judge[0] <= t < judge[1]]
    judged_failed = sum(1 for t, _ in failures if judge[0] <= t < judge[1])
    judged_avail = (round(len(judged) / (len(judged) + judged_failed), 4)
                    if judged or judged_failed else None)
    p99 = round(float(np.percentile(lat_all, 99)) * 1e3, 3)
    p99_judged = (round(float(np.percentile(judged, 99)) * 1e3, 3)
                  if judged else None)
    # 20-bucket p99 trajectory: the A/B's shape proof next to the
    # backend-count trajectory.
    buckets: list[list[float]] = [[] for _ in range(20)]
    for t, s in latencies:
      buckets[min(19, int(t / duration * 20))].append(s)
    p99_trajectory = [
        (round(float(np.percentile(b, 99)) * 1e3, 3) if b else None)
        for b in buckets]

    # Zero-drop scale-down: no client failure may land inside any
    # retire window (eject -> drain -> SIGTERM -> ring move).
    down_windows = []
    scale_down_failed = 0
    for ev in router.events.snapshot(recent=256,
                                     kind="autoscale_down")["events"]:
      if ev["kind"] == "autoscale_down":
        t_ev = ev["ts_unix_s"] - wall_t0
        window = (t_ev - drain_s - 1.0, t_ev + 1.0)
        down_windows.append([round(w, 3) for w in window])
        scale_down_failed += sum(
            1 for ts, _ in failures if window[0] <= ts <= window[1])

    backend_counts = [p["backends"] for p in timeline]
    record = {
        "arm": arm,
        "requests": len(latencies),
        "rps": round(len(latencies) / elapsed, 3),
        "failed": dict(sorted(collections.Counter(
            k for _, k in failures).items())),
        "single_stream_ms": round(single * 1e3, 3),
        "objective_ms": round(objective_s * 1e3, 3),
        "p99_ms": p99,
        "judged_window": [round(j, 3) for j in judge],
        "judged_p99_ms": p99_judged,
        # The verdict is AVAILABILITY under the bounded queue: one
        # backend cannot hold the surge inside --max-queue (sustained
        # overflow 503s), scaled capacity can. Latency stays reported
        # (p99 + trajectory) but does not judge — on a shared CPU box
        # its run-to-run noise exceeds the effect under test.
        "slo": {"availability_target": 0.99,
                "judged_ok": len(judged),
                "judged_failed": judged_failed,
                "judged_availability": judged_avail,
                "objective_ms": round(objective_s * 1e3, 3),
                "judged_p99_ms": p99_judged,
                "pass": (None if judged_avail is None
                         else judged_avail >= 0.99)},
        "p99_trajectory_ms": p99_trajectory,
        "timeline": timeline,
        "backends_max": max(backend_counts, default=1),
        "backends_final": backend_counts[-1] if backend_counts else 1,
        "scale_down_windows": down_windows,
        "scale_down_window_failed": scale_down_failed,
    }
    if autoscaler is not None:
      record["autoscale"] = autoscaler.snapshot()
      record["events"] = {
          k: router.events.count(k)
          for k in ("autoscale_up", "autoscale_down", "autoscale_abort")}
      record["scale_events"] = [
          {"t": round(ev["ts_unix_s"] - wall_t0, 3), "kind": ev["kind"],
           "backend": ev.get("backend")}
          for ev in router.events.snapshot(recent=256)["events"]
          if ev["kind"].startswith("autoscale_")]
    return record
  finally:
    if supervisor is not None:
      supervisor.stop()
    pool.close()


def autoscale_ab_main(args) -> int:
  """--cluster --autoscale-ab: the elastic-fleet proof on one CPU box.
  Same ~3x ramp over both arms; the autoscaler arm must grow under the
  surge (warmed admit), hold the calibrated SLO verdict the fixed pool
  violates, shrink back in the tail, and drop zero requests doing it."""
  # The full duration exists to give the autoscale arm's mid-surge cold
  # spawn room to land its warmed admit before the judge window. The
  # fixed arm pays no spawn tax — its capacity verdict (one bounded
  # queue vs a 4x closed-loop surge) is decided within seconds of the
  # surge starting — so it rides the same proportional ramp at half the
  # wall clock.
  fixed = _autoscale_arm(args, autoscale=False,
                         duration=args.duration / 2.0)
  scaled = _autoscale_arm(args, autoscale=True)
  record = {
      "metric": "serve_load_autoscale_ab",
      # Headline: judged-window availability gained by scaling (> 0
      # means the elastic fleet held traffic the fixed pool shed).
      "value": (round(scaled["slo"]["judged_availability"]
                      - fixed["slo"]["judged_availability"], 4)
                if scaled["slo"]["judged_availability"] is not None
                and fixed["slo"]["judged_availability"] is not None
                else None),
      "unit": "judged_availability_delta_autoscale_minus_fixed",
      "p99_ratio_fixed_over_autoscale": (
          round(fixed["judged_p99_ms"] / scaled["judged_p99_ms"], 3)
          if fixed.get("judged_p99_ms") and scaled.get("judged_p99_ms")
          else None),
      "concurrency": args.concurrency,
      "duration_s": args.duration,
      "autoscale": scaled,
      "fixed": fixed,
      "grew": scaled["backends_max"] > 1,
      "shrank": scaled["backends_final"] < scaled["backends_max"],
      "scale_down_window_failed": scaled["scale_down_window_failed"],
      "dry": bool(args.dry),
  }
  print(json.dumps(record))
  return 0


def _free_port() -> int:
  import socket

  s = socket.socket()
  try:
    s.bind(("127.0.0.1", 0))
    return s.getsockname()[1]
  finally:
    s.close()


def _http_json(url: str, timeout: float = 5.0) -> dict:
  import urllib.request

  with urllib.request.urlopen(url, timeout=timeout) as resp:
    return json.loads(resp.read().decode())


def _metric_value(url: str, family: str, timeout: float = 5.0) -> float:
  """One un-labelled sample from a Prometheus exposition (0.0 if absent)."""
  import re
  import urllib.request

  with urllib.request.urlopen(url, timeout=timeout) as resp:
    text = resp.read().decode()
  m = re.search(rf"^{re.escape(family)}(?:{{}})? ([0-9.eE+-]+)$", text,
                re.MULTILINE)
  return float(m.group(1)) if m else 0.0


class _RestartHookServer:
  """The bench-side half of the remote restart webhook: the router's
  RemoteBackendPool shells out to a helper that POSTs the backend id
  here, and THIS process (the one owning the BackendPool) respawns it
  on its old port — the k8s-operator shape with the bench as operator."""

  def __init__(self, pool):
    import http.server
    from urllib.parse import parse_qs, urlparse

    outer = self
    self.pool = pool
    self.invocations = 0
    self.failures = 0
    self._lock = threading.Lock()

    class Handler(http.server.BaseHTTPRequestHandler):
      def do_POST(self):  # noqa: N802 - stdlib naming
        bid = (parse_qs(urlparse(self.path).query).get("backend")
               or [""])[0]
        try:
          outer.pool.restart(bid)
        except Exception as e:  # noqa: BLE001 - reported to the hook
          with outer._lock:
            outer.failures += 1
          self.send_response(500)
          self.end_headers()
          self.wfile.write(repr(e).encode())
          return
        with outer._lock:
          outer.invocations += 1
        self.send_response(200)
        self.end_headers()
        self.wfile.write(b"ok")

      def log_message(self, *a):  # noqa: ARG002 - quiet
        pass

    self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    self.port = self.httpd.server_address[1]
    self._thread = threading.Thread(target=self.httpd.serve_forever,
                                    daemon=True)
    self._thread.start()

  def close(self) -> None:
    self.httpd.shutdown()
    self._thread.join(10)


_HOOK_HELPER = """\
import sys
import urllib.parse
import urllib.request

req = urllib.request.Request(
    "http://127.0.0.1:{port}/restart?backend="
    + urllib.parse.quote(sys.argv[1]),
    data=b"", method="POST")
with urllib.request.urlopen(req, timeout=180) as resp:
    body = resp.read()
sys.exit(0 if resp.status == 200 else 1)
"""


def _spawn_router(node_id: str, port: int, peer_port: int, backends: dict,
                  lease_dir: str, hook_cmd: str, workdir: str,
                  env: dict):
  """One router replica subprocess: --join over the shared pool,
  --supervise behind the shared file lease, gossiping with its peer.
  Returns (popen, log_path)."""
  import subprocess

  log_path = os.path.join(workdir, f"{node_id}.log")
  argv = [
      sys.executable, "-m", "mpi_vision_tpu", "cluster",
      "--join", ",".join(addr for _, addr in sorted(backends.items())),
      "--host", "127.0.0.1", "--port", str(port),
      "--node-id", node_id,
      "--peers", f"127.0.0.1:{peer_port}",
      "--gossip-interval-s", "0.2",
      "--supervise",
      "--lease-dir", lease_dir,
      "--lease-ttl-s", "1.0",
      "--restart-hook", hook_cmd,
      "--restart-hook-timeout-s", "180",
      "--probe-s", "0.2", "--wedge-after", "2",
      "--restart-budget", "3", "--restart-window-s", "600",
      "--replication", "2",
      "--breaker-threshold", "2", "--breaker-reset-s", "60",
      "--render-timeout-s", "60", "--retry-budget", "1.0",
  ]
  log_fh = open(log_path, "ab")
  try:
    popen = subprocess.Popen(argv, stdout=log_fh, stderr=log_fh, env=env)
  finally:
    log_fh.close()
  return popen, log_path


def _await_router(name: str, popen, url: str, log_path: str,
                  deadline_s: float = 120.0) -> None:
  t0 = time.perf_counter()
  while time.perf_counter() - t0 < deadline_s:
    if popen.poll() is not None:
      break
    try:
      if _http_json(url + "/healthz", timeout=2.0).get("status") \
          in ("ok", "degraded"):
        return
    except (OSError, ValueError):
      pass
    time.sleep(0.1)
  tail = ""
  try:
    with open(log_path, "rb") as fh:
      tail = fh.read()[-2000:].decode(errors="replace")
  except OSError:
    pass
  raise SystemExit(f"serve_load: router {name} not healthy "
                   f"within {deadline_s:.0f}s:\n{tail}")


def _lease_owner(url: str) -> "str | None":
  """The FRESH supervision-lease holder as this router reports it."""
  try:
    lease = _http_json(url + "/healthz", timeout=2.0).get(
        "supervision_lease")
  except (OSError, ValueError):
    return None
  if not isinstance(lease, dict) or not lease.get("fresh"):
    return None
  return lease.get("owner")


def chaos_router_main(args) -> int:
  """The router-HA drill (--cluster --chaos-router): two router replica
  PROCESSES — gossip peers sharing one on-disk supervision lease — front
  one backend pool, with restarts flowing through a remote webhook back
  to this process (the pool's owner). Under live load on the standby
  router, the supervising router is SIGKILLed: the pinned arc is zero
  failed requests on the survivor, a bounded lease takeover, and a
  backend killed AFTER the takeover still being respawned — by the NEW
  leader, through the hook. One serve_load JSON line with a
  ``cluster.chaos_router`` block carrying the whole arc."""
  import shlex
  import signal as signal_mod
  import tempfile
  import urllib.error
  import urllib.request

  from mpi_vision_tpu.serve.cluster import BackendPool

  env = dict(os.environ)
  env.setdefault("JAX_PLATFORMS", "cpu")
  pool = BackendPool(
      args.cluster_backends, scenes=args.scenes, img_size=args.img_size,
      planes=args.num_planes, seed=args.seed, env=env, log=_log)
  hook_server = None
  routers = {}  # node_id -> (popen, log_path, url)
  tmpdir = tempfile.mkdtemp(prefix="serve_load_chaos_router_")
  phase_deadline_s = 45.0 if args.dry else 300.0
  try:
    _log(f"serve_load: spawning {args.cluster_backends} backend(s) "
         f"[{args.scenes} scenes {args.img_size}x{args.img_size}"
         f"x{args.num_planes}]")
    backends = pool.start()
    ids = pool.scene_ids()

    hook_server = _RestartHookServer(pool)
    helper = os.path.join(tmpdir, "restart_hook.py")
    with open(helper, "w") as fh:
      fh.write(_HOOK_HELPER.format(port=hook_server.port))
    hook_cmd = f"{shlex.quote(sys.executable)} {shlex.quote(helper)}"
    lease_dir = os.path.join(tmpdir, "lease")
    os.makedirs(lease_dir, exist_ok=True)

    port_a, port_b = _free_port(), _free_port()
    # Leader first: routerA claims the lease before routerB exists, so
    # the drill's roles are deterministic (A supervises, B is standby).
    popen_a, log_a = _spawn_router("routerA", port_a, port_b, backends,
                                   lease_dir, hook_cmd, tmpdir, env)
    url_a = f"http://127.0.0.1:{port_a}"
    _await_router("routerA", popen_a, url_a, log_a)
    routers["routerA"] = (popen_a, log_a, url_a)
    t0 = time.perf_counter()
    while _lease_owner(url_a) != "routerA":
      if time.perf_counter() - t0 > phase_deadline_s:
        raise SystemExit("serve_load: routerA never acquired the "
                         "supervision lease")
      time.sleep(0.1)
    popen_b, log_b = _spawn_router("routerB", port_b, port_a, backends,
                                   lease_dir, hook_cmd, tmpdir, env)
    url_b = f"http://127.0.0.1:{port_b}"
    _await_router("routerB", popen_b, url_b, log_b)
    routers["routerB"] = (popen_b, log_b, url_b)
    _log(f"serve_load: routerA (leader) on {url_a}, "
         f"routerB (survivor) on {url_b}")

    # Closed-loop load against the SURVIVOR only: its router process
    # never dies, so every failure it returns counts against the pin.
    stop = threading.Event()
    counts = [0] * args.concurrency
    post_kill_counts = [0] * args.concurrency
    router_killed = threading.Event()
    failure_counts: collections.Counter = collections.Counter()
    failure_lock = threading.Lock()

    def worker(idx: int) -> None:
      rng = np.random.default_rng(args.seed + 1 + idx)
      while not stop.is_set():
        sid = ids[0] if (rng.random() < 0.5 or len(ids) == 1) \
            else ids[int(rng.integers(1, len(ids)))]
        body = json.dumps({"scene_id": sid,
                           "pose": random_pose(rng).tolist()}).encode()
        req = urllib.request.Request(
            url_b + "/render", data=body,
            headers={"Content-Type": "application/json"})
        try:
          with urllib.request.urlopen(req, timeout=60) as resp:
            resp.read()
            status = resp.status
        except urllib.error.HTTPError as e:
          with failure_lock:
            failure_counts[f"http_{e.code}"] += 1
          time.sleep(0.005)
          continue
        except Exception as e:  # noqa: BLE001 - chaos is the workload
          with failure_lock:
            failure_counts[type(e).__name__] += 1
          time.sleep(0.005)
          continue
        if status != 200:
          with failure_lock:
            failure_counts[f"http_{status}"] += 1
          continue
        counts[idx] += 1
        if router_killed.is_set():
          post_kill_counts[idx] += 1

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(args.concurrency)]
    load_t0 = time.perf_counter()
    for t in threads:
      t.start()
    time.sleep(args.duration / 4)  # clean phase: both routers up

    # Phase 1: SIGKILL the supervising router (no drain, no lease
    # release — a host loss). The survivor must observe the stale lease
    # and take over supervision without dropping its own traffic.
    _log("serve_load: SIGKILL routerA (the supervision leader)")
    popen_a.send_signal(signal_mod.SIGKILL)
    popen_a.wait(30)
    router_killed.set()
    takeover_t0 = time.perf_counter()
    takeover_s = None
    while time.perf_counter() - takeover_t0 < phase_deadline_s:
      if _lease_owner(url_b) == "routerB":
        takeover_s = time.perf_counter() - takeover_t0
        break
      time.sleep(0.1)
    _log("serve_load: lease "
         + (f"taken over by routerB after {takeover_s:.2f}s"
            if takeover_s is not None
            else "NOT taken over before the drill deadline"))

    # Phase 2: only meaningful after a takeover — kill a backend and
    # prove the NEW leader still heals the fleet through the hook.
    victim = None
    respawned = False
    respawn_s = None
    if takeover_s is not None:
      victim = sorted(backends)[0]
      _log(f"serve_load: SIGKILL backend {victim} (the new leader must "
           "respawn it via the restart hook)")
      pool.kill(victim)
      respawn_t0 = time.perf_counter()
      while time.perf_counter() - respawn_t0 < phase_deadline_s:
        if hook_server.invocations >= 1 and pool.alive(victim):
          respawned = True
          respawn_s = time.perf_counter() - respawn_t0
          break
        time.sleep(0.1)
      _log(f"serve_load: {victim} "
           + (f"respawned via hook after {respawn_s:.2f}s" if respawned
              else "NOT respawned before the drill deadline"))
    time.sleep(args.duration / 4)  # measured tail on the healed fleet
    stop.set()
    for t in threads:
      t.join(60)
    elapsed = time.perf_counter() - load_t0

    total = sum(counts)
    if total == 0:
      raise SystemExit("serve_load: no requests completed in the window")
    health = _http_json(url_b + "/healthz", timeout=10.0)
    stats = _http_json(url_b + "/stats", timeout=10.0)
    takeovers_total = _metric_value(
        url_b + "/metrics", "mpi_cluster_supervisor_takeovers_total",
        timeout=10.0)
    lease_held = _metric_value(
        url_b + "/metrics", "mpi_cluster_supervisor_lease_held",
        timeout=10.0)
    gossip = stats.get("gossip") or {}
    rps = total / elapsed
    record = {
        "metric": "serve_load",
        "value": round(rps, 3),
        "unit": "renders/s",
        "renders_per_sec": round(rps, 3),
        "requests": total,
        "concurrency": args.concurrency,
        "dry": bool(args.dry),
        "chaos": False,
        "cluster": {
            "backends": len(backends),
            "replication": 2,
            "failed_requests": dict(sorted(failure_counts.items())),
            "post_kill_requests": sum(post_kill_counts),
            "health": health.get("status"),
            "chaos_router": {
                "routers": 2,
                "killed_router": "routerA",
                "survivor": "routerB",
                "lease_taken_over": takeover_s is not None,
                "takeover_s": (round(takeover_s, 3)
                               if takeover_s is not None else None),
                "takeovers_total": takeovers_total,
                "lease_held": lease_held,
                "lease_owner": _lease_owner(url_b),
                "backend_killed": victim,
                "backend_respawned": respawned,
                "respawn_s": (round(respawn_s, 3)
                              if respawn_s is not None else None),
                "hook_invocations": hook_server.invocations,
                "hook_failures": hook_server.failures,
                "gossip": {
                    "rounds": gossip.get("rounds"),
                    "peers": {p: e.get("ok")
                              for p, e in (gossip.get("peers")
                                           or {}).items()},
                },
            },
        },
    }
    print(json.dumps(record))
    return 0
  finally:
    for node_id, (popen, _, _) in routers.items():
      if popen.poll() is None:
        popen.terminate()
    for node_id, (popen, _, _) in routers.items():
      try:
        popen.wait(30)
      except Exception:  # noqa: BLE001 - last resort below
        popen.kill()
    if hook_server is not None:
      hook_server.close()
    pool.close()


def inprocess_run(args, inflight: int, edge: bool = False) -> dict:
  """One measured in-process load window at the given pipeline window;
  returns the headline JSON record (the single-run mode prints exactly
  this; ``--ab`` / ``--edge-ab`` call it twice). ``edge`` serves the
  closed loop through ``RenderService.render_edge`` (the pose-quantized
  frame cache) instead of the raw scheduler path."""
  from mpi_vision_tpu.obs import attrib as attrib_lib
  from mpi_vision_tpu.obs import slo as slo_mod
  from mpi_vision_tpu.serve import (
      FaultyEngine,
      RenderEngine,
      RenderService,
      ResilienceConfig,
      Tracer,
  )
  from mpi_vision_tpu.serve.edge import EdgeConfig

  use_mesh = {"auto": None, "on": True, "off": False}[args.sharded]
  engine = None
  tracer = Tracer() if args.trace else None
  resilience = ResilienceConfig()
  if args.chaos:
    # Schedule armed AFTER warm-up: warm-up dispatches bypass the
    # resilience layer, so an injected fault there would abort the run
    # before measurement starts.
    engine = FaultyEngine(RenderEngine(method=args.method, use_mesh=use_mesh))
    # Chaos wants the loop lively: short backoffs and a quick half-open
    # probe so the run exercises open AND re-close inside the window.
    resilience = ResilienceConfig(
        max_retries=3, backoff_base_s=0.01, backoff_max_s=0.1,
        breaker_threshold=5, breaker_reset_s=0.25, watchdog_s=30.0,
        seed=args.seed)
  svc = RenderService(
      cache_bytes=args.cache_mb << 20, max_batch=args.max_batch,
      max_wait_ms=args.max_wait_ms, max_inflight=inflight,
      method=args.method, use_mesh=use_mesh,
      engine=engine, resilience=resilience, tracer=tracer,
      edge=(EdgeConfig(trans_cell=args.edge_trans_cell) if edge else None),
      slo=slo_window_config(args.duration),
      attrib=attrib_lib.AttribConfig())
  ids = svc.add_synthetic_scenes(
      args.scenes, height=args.img_size, width=args.img_size,
      planes=args.num_planes, seed=args.seed)
  _log(f"serve_load: {len(ids)} scenes "
       f"[{args.img_size}x{args.img_size}x{args.num_planes}], "
       f"inflight {inflight}, engine {svc.engine.describe()}")

  # Warm-up outside the measured window: bake every scene and compile all
  # batch buckets so the measurement is steady-state serving, not XLA
  # compiles.
  svc.warmup()
  svc.metrics.reset()  # measured window starts clean
  svc.scheduler.reset_gap_clock()  # no gap spanning warmup->measurement
  if tracer is not None:
    tracer.reset()  # warm-up bakes would hog the slowest-N exemplars
  if args.chaos:
    engine.schedule = chaos_schedule(args.seed, args.chaos_error_rate,
                                     args.chaos_slow_rate)
    _log("serve_load: warm-up done; chaos schedule armed")
  else:
    _log("serve_load: warm-up done")

  stop = threading.Event()
  errors: list[Exception] = []
  counts = [0] * args.concurrency
  failure_counts: collections.Counter = collections.Counter()
  failure_lock = threading.Lock()
  draw_pose = (zipf_pose_sampler(args.zipf_poses, args.zipf_s, args.seed)
               if args.zipf_poses > 0 else random_pose)

  def worker(idx: int) -> None:
    rng = np.random.default_rng(args.seed + 1 + idx)
    while not stop.is_set():
      # Hot-scene skew: half the traffic on scene 0, the rest uniform —
      # the cache must show reuse, not a uniform scan.
      sid = ids[0] if (rng.random() < 0.5 or len(ids) == 1) \
          else ids[int(rng.integers(1, len(ids)))]
      try:
        if edge:
          # render_edge owns the trace end to end (hits/warps finish it
          # up front, misses hand it to the flight) — --trace composes.
          svc.render_edge(
              sid, draw_pose(rng), timeout=600,
              trace=svc.tracer.start_trace("render", scene_id=sid))
        elif args.trace:
          svc.render_traced(sid, draw_pose(rng), timeout=600)
        else:
          svc.render(sid, draw_pose(rng), timeout=600)
      except Exception as e:  # noqa: BLE001 - chaos rides through, else exit
        if not args.chaos:
          errors.append(e)
          return
        # Under chaos, failures ARE the workload: classify-and-continue,
        # like a real client retrying against a flapping service.
        with failure_lock:
          failure_counts[type(e).__name__] += 1
        time.sleep(0.005)  # don't spin against an open breaker
        continue
      counts[idx] += 1

  threads = [threading.Thread(target=worker, args=(i,), daemon=True)
             for i in range(args.concurrency)]
  t0 = time.perf_counter()
  for t in threads:
    t.start()
  time.sleep(args.duration)
  stop.set()
  for t in threads:
    t.join(60)
  elapsed = time.perf_counter() - t0
  svc.close()

  if errors:
    raise SystemExit(f"serve_load: worker failed: {errors[0]!r}")
  total = sum(counts)
  if total == 0:
    raise SystemExit("serve_load: no requests completed in the window")

  stats = svc.stats()
  lat = stats["latency_ms"] or {}
  rps = total / elapsed
  record = {
      "metric": "serve_load",
      "value": round(rps, 3),
      "unit": "renders/s",
      "renders_per_sec": round(rps, 3),
      "p50_ms": lat.get("p50"),
      "p99_ms": lat.get("p99"),
      "cache_hit_rate": stats["cache"]["hit_rate"],
      "requests": total,
      "batches": stats["batches"],
      "mean_batch_size": stats["mean_batch_size"],
      "concurrency": args.concurrency,
      "inflight": inflight,
      # The pipeline proof points: device idle between flights (must be
      # ~0 when streaming), completions that beat an earlier dispatch,
      # and abandoned flights; plus the per-scene latency breakdown for
      # hot-scene regression hunting.
      "dispatch_gap": stats["pipeline"]["dispatch_gap"],
      "out_of_order_completions":
          stats["pipeline"]["out_of_order_completions"],
      "abandoned_batches": stats["pipeline"]["abandoned_batches"],
      "per_scene": stats["per_scene"],
      "device": stats["engine"]["platform"],
      "sharded": stats["engine"]["sharded"],
      "dry": bool(args.dry),
      "chaos": bool(args.chaos),
      "zipf_poses": args.zipf_poses or None,
      # Edge frame-cache accounting (hit/warp/miss split + hit rate)
      # when the run served through serve/edge/.
      **({"edge": stats["edge"]} if "edge" in stats else {}),
      # Error + resilience accounting rides EVERY run's JSON (not just
      # chaos): outage behavior must trend across BENCH rounds, and a
      # clean round proving zeros is itself the trend line (ROADMAP).
      "errors": stats["errors"],
      "rejected": stats["rejected"],
      "resilience": stats["resilience"],
      "breaker_state": (stats["breaker"]["state"]
                        if "breaker" in stats else None),
      # The SLO verdict block: objectives vs slow-window attainment,
      # burn rates, and whether alerts fired — BENCH lines now trend
      # against explicit objectives instead of raw percentiles.
      "slo": slo_mod.verdict(stats.get("slo")),
      # Resource attribution: who ate the window (scene x class x
      # level), plus the conservation check proving the cells sum back
      # to the metrics totals.
      **attrib_record(stats),
  }
  if args.chaos:
    record["chaos_injected"] = stats["engine"]["fault_injection"]
    record["chaos_failed_requests"] = dict(sorted(failure_counts.items()))
  if tracer is not None:
    snap = tracer.snapshot()
    slowest = snap["slowest"]
    record["trace"] = {
        "finished": snap["finished"],
        "slowest_ms": slowest[0]["duration_ms"] if slowest else None,
        # Span-name coverage across the slowest exemplars: the smoke
        # test pins that the tree really covers the whole request path.
        "span_names": sorted({s["name"] for t in slowest
                              for s in t["spans"]}),
    }
  return record


def look_pose(pan_rad: float, tilt_rad: float) -> np.ndarray:
  """A pure-rotation 'look' pose: pan about y, then tilt about x.

  Rotation is depth-independent (K R K^-1 — no parallax), so a pan of
  θ shifts every plane's taps by ~fx·tanθ: with a narrow FOV the
  frustum walks clean off parts of the scene, which is exactly the
  fraction-of-tiles-touched workload the tiled path exists for.
  """
  import math

  c, s = math.cos(pan_rad), math.sin(pan_rad)
  ry = np.array([[c, 0, s], [0, 1, 0], [-s, 0, c]], np.float32)
  c2, s2 = math.cos(tilt_rad), math.sin(tilt_rad)
  rx = np.array([[1, 0, 0], [0, c2, -s2], [0, s2, c2]], np.float32)
  pose = np.eye(4, dtype=np.float32)
  pose[:3, :3] = ry @ rx
  return pose


# The --tiled-ab pose pool (pan, tilt) in radians, tuned for the
# default --fov-scale 2.0 (half-FOV ~14 deg): index 0 is the pinned
# full-coverage pose; the rest touch decreasing tile fractions, down to
# a corner view. Zipf-ranked below so the traffic shape has a hot
# partially-culled view plus a tail — like a viewer orbiting a room.
_TILED_POOL = ((0.0, 0.0), (0.25, 0.0), (-0.25, 0.15), (0.35, 0.2),
               (-0.3, -0.25), (0.15, -0.1), (0.45, 0.35), (0.0, 0.3))


def tiled_run(args, tile: "int | None") -> tuple[dict, dict]:
  """One measured closed-loop window over the depth-stratified scene —
  tiled service when ``tile`` is an int, monolithic when None. Returns
  ``(record, parity_frames)`` where ``parity_frames`` maps pool index
  -> rendered frame for the cross-arm parity checks."""
  from mpi_vision_tpu.core import camera
  from mpi_vision_tpu.obs import attrib as attrib_lib
  from mpi_vision_tpu.obs import slo as slo_mod
  from mpi_vision_tpu.serve import RenderService
  from mpi_vision_tpu.serve.server import synthetic_tiled_scene

  use_mesh = {"auto": None, "on": True, "off": False}[args.sharded]
  layers, depths, k = synthetic_tiled_scene(
      "tiled_scene", height=args.img_size, width=args.img_size,
      planes=args.num_planes, regions=args.tiled_regions, seed=args.seed)
  if args.fov_scale != 1.0:
    fx = args.fov_scale * args.img_size
    k = np.asarray(camera.intrinsics_matrix(
        fx, fx, args.img_size / 2.0, args.img_size / 2.0), np.float32)
  svc = RenderService(
      cache_bytes=args.cache_mb << 20, max_batch=args.max_batch,
      max_wait_ms=args.max_wait_ms, max_inflight=args.inflight,
      method=args.method, use_mesh=use_mesh, tile=tile,
      slo=slo_window_config(args.duration),
      attrib=attrib_lib.AttribConfig())
  svc.add_scene("tiled_scene", layers, depths, k)
  arm = f"tiled (tile {tile})" if tile is not None else "monolithic"
  _log(f"serve_load: tiled-ab arm [{arm}] — scene "
       f"{args.img_size}x{args.img_size}x{args.num_planes}, "
       f"fov-scale {args.fov_scale}, engine {svc.engine.describe()}")

  # Dry mode (the tier-1 smoke) halves the pose pool and skips the warm
  # burst: the smoke pins the contract, not the speedup, and tier-1
  # seconds are the scarce resource.
  tiled_pool = _TILED_POOL[:4] if args.dry else _TILED_POOL
  pool = [look_pose(p, t) for p, t in tiled_pool]
  weights = 1.0 / np.arange(1, len(pool) + 1, dtype=np.float64) ** 1.1
  # Hot rank = the half-coverage pan (index 1); the pinned full-coverage
  # pose rides in the tail so both arms keep compiling/serving it.
  order = [o for o in (1, 3, 2, 5, 4, 0, 7, 6) if o < len(pool)]
  cumulative = np.cumsum(weights / weights.sum())

  svc.warmup()
  # Compile pass: every pool signature once (bucket 1), then an
  # unmeasured burst of the closed loop so the hot signatures' larger
  # batch buckets compile outside the measured window.
  parity_frames = {i: svc.render("tiled_scene", pool[i], timeout=600)
                   for i in range(len(pool))}
  if not args.dry:
    warm_stop = threading.Event()

    def warm_worker(idx: int) -> None:
      rng = np.random.default_rng([args.seed, 99, idx])
      while not warm_stop.is_set():
        pose = pool[order[int(np.searchsorted(cumulative, rng.random()))]]
        svc.render("tiled_scene", pose, timeout=600)

    warm_threads = [threading.Thread(target=warm_worker, args=(i,),
                                     daemon=True)
                    for i in range(args.concurrency)]
    for t in warm_threads:
      t.start()
    time.sleep(min(args.duration / 2.0, 4.0))
    warm_stop.set()
    for t in warm_threads:
      t.join(60)
  svc.metrics.reset()
  svc.scheduler.reset_gap_clock()
  _log(f"serve_load: tiled-ab arm [{arm}] warm; measuring "
       f"{args.duration:g}s")

  stop = threading.Event()
  errors: list[Exception] = []
  counts = [0] * args.concurrency

  def worker(idx: int) -> None:
    rng = np.random.default_rng(args.seed + 1 + idx)
    while not stop.is_set():
      pose = pool[order[int(np.searchsorted(cumulative, rng.random()))]]
      try:
        svc.render("tiled_scene", pose, timeout=600)
      except Exception as e:  # noqa: BLE001 - clean arms: abort on failure
        errors.append(e)
        return
      counts[idx] += 1

  threads = [threading.Thread(target=worker, args=(i,), daemon=True)
             for i in range(args.concurrency)]
  t0 = time.perf_counter()
  for t in threads:
    t.start()
  time.sleep(args.duration)
  stop.set()
  for t in threads:
    t.join(60)
  elapsed = time.perf_counter() - t0
  stats = svc.stats()
  svc.close()
  if errors:
    raise SystemExit(f"serve_load: tiled-ab worker failed: {errors[0]!r}")
  total = sum(counts)
  if total == 0:
    raise SystemExit("serve_load: no requests completed in the window")
  lat = stats["latency_ms"] or {}
  rps = total / elapsed
  record = {
      "arm": "tiled" if tile is not None else "full",
      "renders_per_sec": round(rps, 3),
      "p50_ms": lat.get("p50"),
      "p99_ms": lat.get("p99"),
      "requests": total,
      "batches": stats["batches"],
      "mean_batch_size": stats["mean_batch_size"],
      "device": stats["engine"]["platform"],
      "slo": slo_mod.verdict(stats.get("slo")),
      **attrib_record(stats),
  }
  if tile is not None:
    record["tiles"] = stats["tiles"]
    record["tile_cache"] = stats["tile_cache"]
  return record, parity_frames


def tiled_ab_main(args) -> int:
  """The tiled-vs-monolithic A/B: one depth-stratified scene, one
  panning narrow-FOV pose pool, two measured arms in one process. The
  parity block is PINNED: the full-coverage pose (identity — every tile
  touched, every plane kept) must render bit-exactly equal through both
  paths, or the run aborts; culled poses report their max abs pixel
  difference (conservative frustum + zero-padded sampling keep it at
  float-rounding scale)."""
  tiled, tiled_frames = tiled_run(args, args.tile_size)
  full, full_frames = tiled_run(args, None)
  bit_exact = bool(np.array_equal(tiled_frames[0], full_frames[0]))
  culled_diff = max(
      float(np.abs(tiled_frames[i] - full_frames[i]).max())
      for i in range(1, len(tiled_frames)))
  if not bit_exact:
    raise SystemExit(
        "serve_load: PINNED parity failure — the full-coverage pose "
        "rendered differently through the tiled path (max abs diff "
        f"{float(np.abs(tiled_frames[0] - full_frames[0]).max()):g})")
  tiles = tiled.get("tiles") or {}
  total = tiles.get("tiled_requests") or 0
  speedup = (full["p50_ms"] / tiled["p50_ms"]
             if tiled["p50_ms"] and full["p50_ms"] else None)
  record = {
      "metric": "serve_load_tiled_ab",
      "value": round(speedup, 4) if speedup is not None else None,
      "unit": "x_p50_full_over_tiled",
      "p50_ms_tiled": tiled["p50_ms"],
      "p50_ms_full": full["p50_ms"],
      "throughput_x": (round(tiled["renders_per_sec"]
                             / full["renders_per_sec"], 4)
                       if full["renders_per_sec"] else None),
      "tile": args.tile_size,
      "tiles_total": (-(-args.img_size // args.tile_size)) ** 2,
      "parity": {
          "full_coverage_bit_exact": bit_exact,
          "culled_pose_max_abs_diff": culled_diff,
      },
      "tiles_touched_mean": tiles.get("mean_touched"),
      "tiles_culled_frac": (round(
          tiles.get("culled_total", 0)
          / max((tiles.get("culled_total", 0)
                 + tiles.get("rendered_total", 0)), 1), 4)
          if total else None),
      "fov_scale": args.fov_scale,
      "img_size": args.img_size,
      "num_planes": args.num_planes,
      "tiled": tiled,
      "full": full,
      "device": tiled["device"],
      "dry": bool(args.dry),
  }
  print(json.dumps(record))
  return 0


def ab_main(args) -> int:
  """The pipelined-vs-blocking A/B: the same closed-loop load, once at
  ``--inflight`` and once at window 1 (the legacy blocking dispatch), in
  one process so XLA compiles and scene bakes are identical. One JSON
  line carries both arms + the speedup and each arm's dispatch-gap —
  blocking shows a real gap per batch, pipelined must show ~0."""
  if args.inflight < 2:
    raise SystemExit("--ab needs --inflight >= 2 (the pipelined arm)")
  _log(f"serve_load: A/B arm 1/2 — pipelined (inflight {args.inflight})")
  pipelined = inprocess_run(args, args.inflight)
  _log("serve_load: A/B arm 2/2 — blocking (inflight 1)")
  blocking = inprocess_run(args, 1)
  speedup = (pipelined["renders_per_sec"] / blocking["renders_per_sec"]
             if blocking["renders_per_sec"] else None)
  record = {
      "metric": "serve_load_ab",
      "value": round(speedup, 4) if speedup is not None else None,
      "unit": "x_pipelined_over_blocking",
      "speedup": round(speedup, 4) if speedup is not None else None,
      "pipelined": pipelined,
      "blocking": blocking,
      "device": pipelined["device"],
      "dry": bool(args.dry),
  }
  print(json.dumps(record))
  return 0


def edge_ab_main(args) -> int:
  """The edge-on-vs-off A/B: the same closed-loop load served through
  the pose-quantized frame cache and then through the raw scheduler
  path, in one process (identical XLA compiles and scene bakes). One
  JSON line carries both arms, the hit/warp/miss split, and the p50
  drop — the number that must fall at high hit rates for the edge tier
  to earn its bytes. Pair with ``--zipf-poses`` for the orbit-a-hot-
  viewpoint traffic shape the cache is built for."""
  if args.zipf_poses == 0:
    # Fresh-random poses essentially never repeat a view cell inside a
    # bench window; default the sampler on so the A/B measures the
    # cache's design load rather than its worst case.
    args.zipf_poses = 32
  _log(f"serve_load: edge A/B arm 1/2 — edge cache on "
       f"(zipf {args.zipf_poses} poses, s={args.zipf_s})")
  edge_on = inprocess_run(args, args.inflight, edge=True)
  _log("serve_load: edge A/B arm 2/2 — edge cache off")
  edge_off = inprocess_run(args, args.inflight)
  p50_on, p50_off = edge_on["p50_ms"], edge_off["p50_ms"]
  speedup = (p50_off / p50_on) if (p50_on and p50_off) else None
  edge_stats = edge_on.get("edge") or {}
  record = {
      "metric": "serve_load_edge_ab",
      "value": round(speedup, 4) if speedup is not None else None,
      "unit": "x_p50_off_over_on",
      "p50_ms_edge_on": p50_on,
      "p50_ms_edge_off": p50_off,
      "p50_drop_pct": (round((1.0 - p50_on / p50_off) * 100.0, 2)
                       if speedup is not None else None),
      "throughput_x": (round(edge_on["renders_per_sec"]
                             / edge_off["renders_per_sec"], 4)
                       if edge_off["renders_per_sec"] else None),
      "hit_rate": edge_stats.get("hit_rate"),
      "hits": edge_stats.get("hits"),
      "warp_serves": edge_stats.get("warp_serves"),
      "misses": edge_stats.get("misses"),
      "zipf_poses": args.zipf_poses,
      "zipf_s": args.zipf_s,
      "edge_on": edge_on,
      "edge_off": edge_off,
      "device": edge_on["device"],
      "dry": bool(args.dry),
  }
  print(json.dumps(record))
  return 0


def asset_ab_main(args) -> int:
  """The asset-delivery A/B (serve/assets): one tiled service, measured
  through its content-addressed manifest + asset surface, in one
  process.

  Four measured legs: COLD (manifest + every tile asset over real
  HTTP), WARM (the same GETs with ``If-None-Match`` — the immutable
  contract must answer 304 with empty bodies), FULL SYNC (a fresh
  replica ``SceneFetcher`` pulls every tile), and DIFF SYNC (after a
  ``swap_scenes`` that mutates ~a quarter of the scene, the replica
  re-syncs and must transfer ONLY the changed tiles). The headline
  value is diff-sync bytes over the full checkpoint bytes — the
  serve-layers-not-frames number. The run aborts if the diff sync moved
  at least as many bytes as the full sync (the tier-1 ``--dry`` pin)."""
  import urllib.request

  from mpi_vision_tpu.serve import RenderService
  from mpi_vision_tpu.serve.assets import SceneFetcher
  from mpi_vision_tpu.serve.server import (
      make_http_server,
      synthetic_tiled_scene,
  )

  layers, depths, k = synthetic_tiled_scene(
      "asset_scene", height=args.img_size, width=args.img_size,
      planes=args.num_planes, regions=args.tiled_regions, seed=args.seed)
  svc = RenderService(cache_bytes=args.cache_mb << 20,
                      tile=args.tile_size)
  svc.add_scene("asset_scene", layers, depths, k)
  httpd = make_http_server(svc, port=0)
  threading.Thread(target=httpd.serve_forever, daemon=True).start()
  base_url = f"http://127.0.0.1:{httpd.server_address[1]}"
  _log(f"serve_load: asset-ab — scene {args.img_size}x{args.img_size}"
       f"x{args.num_planes}, tile {args.tile_size}, origin {base_url}")

  def fetch(path, etag=None):
    req = urllib.request.Request(base_url + path)
    if etag:
      req.add_header("If-None-Match", etag)
    try:
      with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, resp.headers.get("ETag"), resp.read()
    except urllib.error.HTTPError as e:
      if e.code == 304:
        return 304, e.headers.get("ETag"), b""
      raise

  # COLD: manifest + every tile asset, timed over real HTTP.
  t0 = time.perf_counter()
  _, manifest_etag, manifest_body = fetch("/scene/asset_scene/manifest")
  manifest = json.loads(manifest_body)
  digests = [d for row in manifest["tiles"] for d in row]
  etags = {}
  cold_bytes = len(manifest_body)
  for digest in digests:
    _, etag, body = fetch(manifest["asset_path"] + digest)
    etags[digest] = etag
    cold_bytes += len(body)
  cold_s = time.perf_counter() - t0

  # WARM: the immutable contract — every conditional GET must 304.
  t0 = time.perf_counter()
  status, _, body = fetch("/scene/asset_scene/manifest",
                          etag=manifest_etag)
  warm_bytes, warm_304 = len(body), int(status == 304)
  for digest in digests:
    status, _, body = fetch(manifest["asset_path"] + digest,
                            etag=etags[digest])
    warm_bytes += len(body)
    warm_304 += int(status == 304)
  warm_s = time.perf_counter() - t0
  if warm_304 != len(digests) + 1:
    raise SystemExit(
        f"serve_load: asset-ab revalidation failure — expected "
        f"{len(digests) + 1} 304s, got {warm_304}")

  # FULL SYNC: a fresh tiled replica pulls the whole scene tile-by-tile.
  replica = RenderService(cache_bytes=args.cache_mb << 20,
                          tile=args.tile_size)
  fetcher = SceneFetcher(replica, base_url)
  full = fetcher.sync_scene("asset_scene")

  # DIFF SYNC: mutate ~a quarter of the scene on the origin, re-sync —
  # only the changed-digest tiles may move.
  rgba2 = np.array(layers, copy=True)
  h, w = rgba2.shape[0] // 2, rgba2.shape[1] // 2
  rgba2[:h, :w] = np.clip(rgba2[:h, :w] + 0.125, 0.0, 1.0)
  svc.swap_scenes({"asset_scene": (rgba2, depths, k)})
  diff = fetcher.sync_scene("asset_scene")
  if diff["bytes_fetched"] >= full["bytes_fetched"]:
    raise SystemExit(
        "serve_load: asset-ab PINNED diff failure — the quarter-scene "
        f"re-sync moved {diff['bytes_fetched']} bytes vs "
        f"{full['bytes_fetched']} for the full sync")
  httpd.shutdown()
  svc.close()
  replica.close()

  full_ckpt_bytes = full["scene_bytes"]
  record = {
      "metric": "serve_load_asset_ab",
      "value": round(diff["bytes_fetched"] / full_ckpt_bytes, 4),
      "unit": "diff_bytes_over_full_checkpoint_bytes",
      "cold": {"seconds": round(cold_s, 4), "bytes": cold_bytes,
               "assets": len(digests)},
      "warm": {"seconds": round(warm_s, 4), "bytes": warm_bytes,
               "not_modified": warm_304},
      "full_sync": {"seconds": full["seconds"],
                    "bytes": full["bytes_fetched"],
                    "tiles_fetched": full["tiles_fetched"]},
      "diff_sync": {"seconds": diff["seconds"],
                    "bytes": diff["bytes_fetched"],
                    "tiles_fetched": diff["tiles_fetched"],
                    "tiles_reused": diff["tiles_reused"]},
      "full_checkpoint_bytes": full_ckpt_bytes,
      "diff_vs_full_sync": round(
          diff["bytes_fetched"] / max(full["bytes_fetched"], 1), 4),
      "tiles_total": len(digests),
      "tile": args.tile_size,
      "img_size": args.img_size,
      "num_planes": args.num_planes,
      "dry": bool(args.dry),
  }
  print(json.dumps(record))
  return 0


def session_trajectory(idx: int, seed: int, step: float):
  """Infinite smooth constant-velocity camera path for client ``idx``.

  The step outruns the edge warp radius (a camera FLYING through the
  scene, not orbiting one viewpoint), so every frame lands in a fresh
  view cell: without prefetch it is a full render, with prefetch the
  constant-velocity predictor's next-cell guess is exactly where the
  camera arrives a few frames later — the design load for
  trajectory-predictive prefetch. Bounces off +-1.6 so long windows stay
  bounded; the box is wide relative to the step so straight segments are
  much longer than the prefetch lead (a bounce mid-prediction is a miss,
  and the EMA predictor re-converges within a frame or two)."""
  rng = np.random.default_rng([seed, 4242, idx])
  pos = rng.uniform(-0.05, 0.05, 3).astype(np.float64)
  vel = rng.normal(size=3)
  vel *= step / max(float(np.linalg.norm(vel)), 1e-9)
  while True:
    pose = np.eye(4, dtype=np.float32)
    pose[:3, 3] = pos.astype(np.float32)
    yield pose
    pos = pos + vel
    for axis in range(3):
      if abs(pos[axis]) > 1.6:
        vel[axis] = -vel[axis]


def _session_service(args, session_cfg, edge: bool):
  """A served-over-real-sockets RenderService for the session bench:
  returns ``(svc, ids, httpd, host, port)`` with warm-up done and the
  measured window's metrics reset."""
  from mpi_vision_tpu.obs import attrib as attrib_lib
  from mpi_vision_tpu.serve import RenderService, make_http_server
  from mpi_vision_tpu.serve.edge import EdgeConfig

  use_mesh = {"auto": None, "on": True, "off": False}[args.sharded]
  svc = RenderService(
      cache_bytes=args.cache_mb << 20, max_batch=args.max_batch,
      max_wait_ms=args.max_wait_ms, max_inflight=args.inflight,
      method=args.method, use_mesh=use_mesh,
      # Warp tolerance scaled to the lattice (not the absolute default):
      # the flying-camera trajectory must be able to OUTRUN warp serving,
      # or both arms degenerate into a warp microbenchmark.
      edge=(EdgeConfig(trans_cell=args.edge_trans_cell,
                       warp_max_trans=2.0 * args.edge_trans_cell)
            if edge else None),
      session=session_cfg,
      slo=slo_window_config(args.duration),
      attrib=attrib_lib.AttribConfig())
  ids = svc.add_synthetic_scenes(
      args.scenes, height=args.img_size, width=args.img_size,
      planes=args.num_planes, seed=args.seed)
  svc.warmup()
  svc.metrics.reset()
  svc.scheduler.reset_gap_clock()
  httpd = make_http_server(svc, port=0)
  threading.Thread(target=httpd.serve_forever, daemon=True).start()
  host, port = httpd.server_address[0], httpd.server_address[1]
  return svc, ids, httpd, host, port


def session_run(args, streaming: bool) -> dict:
  """One measured window of smooth-trajectory traffic over real sockets —
  through streaming sessions when ``streaming``, else one POST /render
  per frame on the same service shape. Client-side per-frame latency is
  the headline (both arms pay the same transport), server stats ride
  along."""
  import urllib.request

  from mpi_vision_tpu.obs import slo as slo_mod
  from mpi_vision_tpu.serve.session import SessionConfig
  from mpi_vision_tpu.serve.session.protocol import SessionClient

  # One fresh view cell per frame: past the scaled warp radius (2x
  # cell), so a frame is either a real render or a prefetch-warmed hit.
  step = 3.0 * args.edge_trans_cell
  session_cfg = SessionConfig(
      max_sessions=max(8, args.concurrency)) if streaming else None
  svc, ids, httpd, host, port = _session_service(args, session_cfg,
                                                 edge=True)
  _log(f"serve_load: session arm "
       f"({'streaming' if streaming else 'request-per-frame'}) — "
       f"{args.concurrency} clients, step {step:.4f} "
       f"({args.edge_trans_cell:g} cell)")
  stop = threading.Event()
  errors: list[Exception] = []
  counts = [0] * args.concurrency
  latencies: list[list[float]] = [[] for _ in range(args.concurrency)]
  # Poses a streaming client keeps in flight: deep enough that the
  # session drains multi-pose flushes (the fusion under test), shallow
  # enough that per-frame latency stays a latency, not a queue length.
  window = 2 * (session_cfg.fuse_max if session_cfg else 4)

  def stream_worker(idx: int) -> None:
    poses = session_trajectory(idx, args.seed, step)
    sid = ids[idx % len(ids)]
    try:
      client = SessionClient(host, port, sid, timeout=120)
    except Exception as e:  # noqa: BLE001 - open failure aborts the arm
      errors.append(e)
      return
    send_times: list[float] = []
    credit = threading.Semaphore(window)

    def writer() -> None:
      try:
        while not stop.is_set():
          if not credit.acquire(timeout=0.2):
            continue
          send_times.append(time.perf_counter())
          client.send_pose(next(poses))
        client.end()
      except (OSError, ValueError):
        pass  # reader side reports the failure

    wt = threading.Thread(target=writer, daemon=True)
    wt.start()
    try:
      for seq, _img in client.frames():
        latencies[idx].append(time.perf_counter() - send_times[seq])
        counts[idx] += 1
        credit.release()
    except Exception as e:  # noqa: BLE001 - error frame / torn socket
      errors.append(e)
    finally:
      wt.join(30)
      client.close()

  def request_worker(idx: int) -> None:
    poses = session_trajectory(idx, args.seed, step)
    sid = ids[idx % len(ids)]
    base = f"http://{host}:{port}/render"
    while not stop.is_set():
      body = json.dumps(
          {"scene_id": sid, "pose": next(poses).tolist()}).encode()
      t0 = time.perf_counter()
      try:
        req = urllib.request.Request(
            base, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
          resp.read()
      except Exception as e:  # noqa: BLE001 - clean arms: first error aborts
        errors.append(e)
        return
      latencies[idx].append(time.perf_counter() - t0)
      counts[idx] += 1

  worker = stream_worker if streaming else request_worker
  threads = [threading.Thread(target=worker, args=(i,), daemon=True)
             for i in range(args.concurrency)]
  t0 = time.perf_counter()
  for t in threads:
    t.start()
  time.sleep(args.duration)
  stop.set()
  for t in threads:
    t.join(60)
  elapsed = time.perf_counter() - t0
  httpd.shutdown()
  svc.close()

  if errors:
    raise SystemExit(f"serve_load: session worker failed: {errors[0]!r}")
  total = sum(counts)
  if total == 0:
    raise SystemExit("serve_load: no frames completed in the window")
  lat_ms = np.sort(np.concatenate(
      [np.asarray(l) for l in latencies if l])) * 1e3
  stats = svc.stats()
  record = {
      "mode": "session" if streaming else "request_per_frame",
      "frames": total,
      "frames_per_sec": round(total / elapsed, 3),
      "p50_ms": round(float(np.percentile(lat_ms, 50)), 3),
      "p99_ms": round(float(np.percentile(lat_ms, 99)), 3),
      "mean_batch_size": stats["mean_batch_size"],
      "edge": stats.get("edge"),
      "errors": stats["errors"],
      "rejected": stats["rejected"],
      "slo": slo_mod.verdict(stats.get("slo")),
      "device_seconds_by_class": device_seconds_by_class(stats),
      **attrib_record(stats),
  }
  if streaming:
    record["session"] = stats["session"]
  return record


def session_parity_check(args) -> dict:
  """The PINNED cross-path check: frames streamed through a session must
  be bit-identical to direct renders of the same poses. Edge cache OFF —
  a view-cell hit legitimately serves a cell-mate's pixels, which is
  exactly what this check must not excuse — and prefetch off with it."""
  from mpi_vision_tpu.serve.session import SessionConfig
  from mpi_vision_tpu.serve.session.protocol import SessionClient

  n_poses = 6
  svc, ids, httpd, host, port = _session_service(
      args, SessionConfig(prefetch_horizon=0), edge=False)
  poses_iter = session_trajectory(0, args.seed, 3.0 * args.edge_trans_cell)
  poses = [next(poses_iter) for _ in range(n_poses)]
  try:
    direct = [np.asarray(svc.render(ids[0], p, timeout=600)) for p in poses]
    client = SessionClient(host, port, ids[0], timeout=120)
    with client:
      for p in poses:
        client.send_pose(p)
      client.end()
      streamed = {seq: img for seq, img in client.frames()}
  finally:
    httpd.shutdown()
    svc.close()
  if len(streamed) != n_poses:
    raise SystemExit(f"serve_load: PINNED parity failure — session "
                     f"returned {len(streamed)}/{n_poses} frames")
  worst = 0.0
  for i, want in enumerate(direct):
    got = streamed[i]
    if not np.array_equal(got, want):
      worst = max(worst, float(np.abs(got - want).max()))
  if worst:
    raise SystemExit(
        "serve_load: PINNED parity failure — session frames differ from "
        f"direct renders of the same poses (max abs diff {worst:g})")
  return {"poses": n_poses, "bit_exact": True}


def session_ab_main(args) -> int:
  """The session-vs-request-per-frame A/B: the same smooth trajectories,
  the same service shape, real sockets in both arms — once as streaming
  sessions (pipelined poses, fused flights, predictive prefetch) and
  once as one POST /render per frame. The parity block is PINNED."""
  parity = session_parity_check(args)
  _log("serve_load: session A/B arm 1/2 — streaming sessions")
  sess = session_run(args, streaming=True)
  _log("serve_load: session A/B arm 2/2 — request per frame")
  req = session_run(args, streaming=False)
  throughput_x = (sess["frames_per_sec"] / req["frames_per_sec"]
                  if req["frames_per_sec"] else None)
  sess_stats = sess.get("session") or {}
  prefetch = dict(sess_stats.get("prefetch") or {})
  issued = prefetch.get("issued") or 0
  prefetch["hit_rate"] = (round(prefetch.get("hits", 0) / issued, 4)
                          if issued else None)
  record = {
      "metric": "serve_load_session_ab",
      "value": round(throughput_x, 4) if throughput_x is not None else None,
      "unit": "x_session_over_request",
      "frames_per_sec_session": sess["frames_per_sec"],
      "frames_per_sec_request": req["frames_per_sec"],
      "p50_ms_session": sess["p50_ms"],
      "p50_ms_request": req["p50_ms"],
      "p99_ms_session": sess["p99_ms"],
      "p99_ms_request": req["p99_ms"],
      # The fusion win: poses per fused flush (session bookkeeping) and
      # poses per device flight (scheduler bookkeeping) — the number
      # BENCH_r08 recorded stuck at ~1 for request-per-frame traffic.
      "mean_flush_size": sess_stats.get("mean_flush_size"),
      "mean_batch_size_session": sess["mean_batch_size"],
      "mean_batch_size_request": req["mean_batch_size"],
      "prefetch": prefetch,
      "parity": parity,
      "session": sess,
      "request": req,
      "dry": bool(args.dry),
  }
  print(json.dumps(record))
  return 0


def session_main(args) -> int:
  """Single-arm session mode: the streaming window plus the pinned
  parity block, no request-per-frame comparison arm."""
  parity = session_parity_check(args)
  record = dict(session_run(args, streaming=True))
  record.update({"metric": "serve_load_session",
                 "value": record["frames_per_sec"],
                 "unit": "frames/s", "parity": parity,
                 "dry": bool(args.dry)})
  print(json.dumps(record))
  return 0


def _overload_calibrate(args) -> float:
  """Anchor the latency objective to THIS box. The single-stream render
  is what a healthy service owes one client, so the objective is a
  multiple of that measurement rather than a wall-clock constant a
  slower CPU could never meet at any ladder level. Calibrated once and
  shared by both arms — the A/B judges two policies against one budget.
  """
  from mpi_vision_tpu.serve import RenderService

  use_mesh = {"auto": None, "on": True, "off": False}[args.sharded]
  svc = RenderService(
      cache_bytes=args.cache_mb << 20, max_batch=args.max_batch,
      max_wait_ms=args.max_wait_ms, max_inflight=args.inflight,
      method=args.method, use_mesh=use_mesh)
  try:
    ids = svc.add_synthetic_scenes(
        args.scenes, height=args.img_size, width=args.img_size,
        planes=args.num_planes, seed=args.seed)
    svc.warmup()
    rng = np.random.default_rng(args.seed)
    samples = []
    for _ in range(5):
      t_req = time.perf_counter()
      svc.render_request(ids[0], random_pose(rng), timeout=60)
      samples.append(time.perf_counter() - t_req)
  finally:
    svc.close()
  single = float(np.median(samples))
  # 16x single-stream: room for batching + a healthy queue, but far
  # below the multi-second pileup a saturated full-res queue produces.
  threshold_s = max(16.0 * single, 0.05)
  _log(f"serve_load: overload calibration — single-stream "
       f"{single * 1e3:.1f} ms, latency objective "
       f"{threshold_s * 1e3:.1f} ms")
  return threshold_s


def overload_run(args, with_brownout: bool,
                 latency_threshold_s: float | None = None) -> dict:
  """One phased overload window: baseline -> ~3x worker ramp ->
  recovery tail, closed-loop, classes mixed half interactive / quarter
  prefetch / quarter background. ``with_brownout`` arms the ladder
  (dwell/eval scaled to the bench window so it can climb AND return to
  L0 inside one run); off, the same overload resolves by queue-full
  sheds alone — the baseline a degradation ladder must beat."""
  from mpi_vision_tpu.obs import SloConfig
  from mpi_vision_tpu.obs import attrib as attrib_lib
  from mpi_vision_tpu.obs import incident as incident_lib
  from mpi_vision_tpu.obs import slo as slo_mod
  from mpi_vision_tpu.serve import RenderService
  from mpi_vision_tpu.serve import brownout as brownout_mod
  from mpi_vision_tpu.serve.scheduler import QueueFullError

  use_mesh = {"auto": None, "on": True, "off": False}[args.sharded]
  duration = args.duration
  fast = max(duration / 10.0, 0.2)
  slo = SloConfig(fast_window_s=fast,
                  slow_window_s=max(4.0 * duration, fast),
                  bucket_s=max(fast / 8.0, 0.025), min_requests=5,
                  latency_threshold_s=latency_threshold_s or 1.0)
  bo_cfg = None
  if with_brownout:
    # Thresholds sized to the closed-loop shape: a baseline of
    # ``concurrency`` workers keeps ~c/(2c)=0.5 of the queue occupied
    # at worst (usually less — the pipeline drains it), so recovery
    # gates above that baseline occupancy and overload trips only under
    # the 3x ramp.
    bo_cfg = brownout_mod.BrownoutConfig(
        step_dwell_s=duration / 25.0,
        recover_dwell_s=duration / 50.0,
        eval_interval_s=duration / 400.0,
        queue_high=0.6, recover_queue=0.3)
  arm = "brownout" if with_brownout else "shed_only"
  # --incident-dir arms the black box per arm (subdir each, so the two
  # arms' bundles never prune each other's ring).
  incidents = None
  if args.incident_dir:
    incidents = incident_lib.IncidentConfig(
        dir=os.path.join(args.incident_dir, arm))
  svc = RenderService(
      cache_bytes=args.cache_mb << 20, max_batch=args.max_batch,
      max_wait_ms=args.max_wait_ms, max_inflight=args.inflight,
      method=args.method, use_mesh=use_mesh,
      max_queue=max(4, 2 * args.concurrency),
      slo=slo, brownout=bo_cfg,
      attrib=attrib_lib.AttribConfig(), incidents=incidents)
  ids = svc.add_synthetic_scenes(
      args.scenes, height=args.img_size, width=args.img_size,
      planes=args.num_planes, seed=args.seed)
  _log(f"serve_load: overload arm '{arm}' — {len(ids)} scenes "
       f"[{args.img_size}x{args.img_size}x{args.num_planes}], "
       f"base {args.concurrency} workers, ramp to {3 * args.concurrency}")
  svc.warmup()
  svc.metrics.reset()
  svc.scheduler.reset_gap_clock()
  if svc.brownout is not None:
    svc.brownout.reset_counters()

  n_base = args.concurrency
  n_total = 3 * args.concurrency
  classes = ("interactive", "interactive", "prefetch", "background")
  ramp = (0.2 * duration, 0.7 * duration)
  t0 = time.perf_counter()
  stop = threading.Event()
  lock = threading.Lock()
  ok: collections.Counter = collections.Counter()
  shed: collections.Counter = collections.Counter()
  rejected: collections.Counter = collections.Counter()
  failed: collections.Counter = collections.Counter()
  interactive_ms: list[float] = []

  def worker(idx: int) -> None:
    rng = np.random.default_rng(args.seed + 1 + idx)
    cls = classes[idx % len(classes)]
    surge = idx >= n_base
    while not stop.is_set():
      now = time.perf_counter() - t0
      if surge and now < ramp[0]:
        time.sleep(0.005)
        continue
      if surge and now >= ramp[1]:
        return  # the surge ends; the tail is the recovery phase
      sid = ids[0] if (rng.random() < 0.5 or len(ids) == 1) \
          else ids[int(rng.integers(1, len(ids)))]
      t_req = time.perf_counter()
      try:
        svc.render_request(sid, random_pose(rng), request_class=cls,
                           timeout=60)
      except brownout_mod.BrownoutShedError:
        with lock:
          shed[cls] += 1
        # Honor the 503's Retry-After in bench-window proportion — a
        # shed client that redials in 2ms defeats any admission control.
        time.sleep(duration / 20.0)
        continue
      except QueueFullError:
        with lock:
          rejected[cls] += 1
        time.sleep(duration / 20.0)  # same client behavior in both arms
        continue
      except Exception as e:  # noqa: BLE001 - overload is the workload
        with lock:
          failed[type(e).__name__] += 1
        time.sleep(0.002)
        continue
      dt_ms = (time.perf_counter() - t_req) * 1e3
      with lock:
        ok[cls] += 1
        if cls == "interactive":
          interactive_ms.append(dt_ms)

  threads = [threading.Thread(target=worker, args=(i,), daemon=True)
             for i in range(n_total)]
  for t in threads:
    t.start()
  # The main thread doubles as the level sampler: the trajectory is the
  # A/B's shape proof (climb under the ramp, L0 again in the tail).
  trajectory: list[int] = []
  step = duration / 100.0
  while time.perf_counter() - t0 < duration:
    if svc.brownout is not None:
      # Admission ticks the ladder too, but when every client is parked
      # in shed backoff the sampler is the only reliable heartbeat —
      # recovery must not depend on traffic cadence.
      svc.brownout.tick()
      trajectory.append(svc.brownout.level)
    else:
      trajectory.append(0)
    time.sleep(step)
  stop.set()
  for t in threads:
    t.join(60)
  elapsed = time.perf_counter() - t0
  stats = svc.stats()
  svc.close()

  total_ok = sum(ok.values())
  if total_ok == 0:
    raise SystemExit(f"serve_load: overload arm '{arm}' completed "
                     "no requests")
  p99 = (round(float(np.percentile(interactive_ms, 99)), 3)
         if interactive_ms else None)
  return {
      "arm": arm,
      "requests_ok": {c: ok.get(c, 0) for c in set(classes)},
      "goodput_rps": {c: round(ok.get(c, 0) / elapsed, 3)
                      for c in set(classes)},
      "interactive_p99_ms": p99,
      "sheds": {c: shed.get(c, 0) for c in set(classes)},
      "queue_rejects": {c: rejected.get(c, 0) for c in set(classes)},
      "failed": dict(sorted(failed.items())),
      "brownout": stats.get("brownout"),
      "level_trajectory": trajectory,
      "max_level": max(trajectory, default=0),
      "final_level": trajectory[-1] if trajectory else 0,
      "returned_to_l0": bool(trajectory) and trajectory[-1] == 0,
      "errors": stats["errors"],
      "rejected": stats["rejected"],
      "slo": slo_mod.verdict(stats.get("slo")),
      # Who actually ate the device while the arm ran — the ladder's
      # worth shows up here as device seconds shifted toward
      # interactive, not just as admitted-request counts.
      "device_seconds_by_class": device_seconds_by_class(stats),
      **attrib_record(stats),
      **({"incidents": {**stats["incidents"],
                        "index": [b["id"] for b in svc.incidents.list()]}}
         if "incidents" in stats else {}),
  }


def incident_drill(args, drill_dir: str) -> dict:
  """Deterministic end-to-end black-box proof: a one-scene service with
  a latency objective no render can meet (sub-microsecond threshold,
  min_requests=1), so the burn-rate alert MUST fire within a handful of
  requests — and the incident recorder must turn that fire edge into a
  bundle on disk carrying the run's attribution cells. The two A/B arms
  only capture when THIS box's overload actually breaches the
  calibrated objective; the drill pins the capture path itself, every
  run, dry included."""
  from mpi_vision_tpu.obs import SloConfig
  from mpi_vision_tpu.obs import attrib as attrib_lib
  from mpi_vision_tpu.obs import incident as incident_lib
  from mpi_vision_tpu.serve import RenderService

  use_mesh = {"auto": None, "on": True, "off": False}[args.sharded]
  slo = SloConfig(fast_window_s=0.5, slow_window_s=1.0, bucket_s=0.1,
                  min_requests=1, latency_threshold_s=1e-6)
  svc = RenderService(
      cache_bytes=args.cache_mb << 20, max_batch=args.max_batch,
      max_wait_ms=args.max_wait_ms, max_inflight=args.inflight,
      method=args.method, use_mesh=use_mesh, slo=slo,
      attrib=attrib_lib.AttribConfig(),
      incidents=incident_lib.IncidentConfig(dir=drill_dir, keep=4))
  try:
    ids = svc.add_synthetic_scenes(
        1, height=args.img_size, width=args.img_size,
        planes=args.num_planes, seed=args.seed)
    svc.warmup()
    rng = np.random.default_rng(args.seed)
    deadline = time.perf_counter() + 30.0
    while (svc.incidents.stats()["captures"] == 0
           and time.perf_counter() < deadline):
      # Every request breaches the impossible threshold; recording
      # evaluates the alert edges, the fire edge queues the capture.
      svc.render_request(ids[0], random_pose(rng),
                         request_class="interactive", timeout=60)
      time.sleep(0.05)  # let windows age + the capture thread run
    index = svc.incidents.list()
    stats = svc.stats()
  finally:
    svc.close()
  if not index:
    raise SystemExit("serve_load: incident drill captured no bundle — "
                     "the alert->capture path is broken")
  bundle = svc.incidents.get(index[0]["id"])
  return {
      "dir": drill_dir,
      "captures": stats["incidents"]["captures"],
      "bundle_id": bundle["id"],
      "alert": bundle["alert"]["alert"],
      "bundle_keys": sorted(bundle),
      "attrib_cells": len(bundle.get("attrib_top") or []),
      "conservation_ok": stats["attrib"]["conservation"]["ok"],
  }


def overload_ab_main(args) -> int:
  """The brownout-vs-shed-only A/B: the same ~3x phased overload, once
  with the degradation ladder armed and once resolving by queue-full
  503s alone, in one process. The headline number is the interactive
  goodput ratio — degrading low-priority work and render fidelity must
  buy MORE completed interactive requests than indiscriminate
  shedding, with the level trajectory back at L0 by the tail. With
  ``--incident-dir`` both arms run with the black box armed and a
  deterministic incident drill proves the alert->bundle path."""
  threshold_s = _overload_calibrate(args)
  _log("serve_load: overload A/B arm 1/2 — brownout ladder armed")
  brownout = overload_run(args, with_brownout=True,
                          latency_threshold_s=threshold_s)
  _log("serve_load: overload A/B arm 2/2 — shed-only")
  shed_only = overload_run(args, with_brownout=False,
                           latency_threshold_s=threshold_s)
  g_bo = brownout["goodput_rps"]["interactive"]
  g_shed = shed_only["goodput_rps"]["interactive"]
  ratio = round(g_bo / g_shed, 4) if g_shed else None
  record = {
      "metric": "serve_load_overload_ab",
      "value": ratio,
      "unit": "x_interactive_goodput_brownout_over_shed",
      "interactive_goodput_x": ratio,
      "interactive_p99_ms_brownout": brownout["interactive_p99_ms"],
      "interactive_p99_ms_shed_only": shed_only["interactive_p99_ms"],
      "latency_threshold_ms": round(threshold_s * 1e3, 3),
      "max_level": brownout["max_level"],
      "returned_to_l0": brownout["returned_to_l0"],
      "brownout": brownout,
      "shed_only": shed_only,
      "device_seconds_by_class": {
          "brownout": brownout.get("device_seconds_by_class"),
          "shed_only": shed_only.get("device_seconds_by_class"),
      },
      "dry": bool(args.dry),
  }
  if args.incident_dir:
    record["incident_drill"] = incident_drill(
        args, os.path.join(args.incident_dir, "drill"))
  print(json.dumps(record))
  return 0


def main(argv=None) -> int:
  args = build_parser().parse_args(argv)
  if os.environ.get("SERVE_LOAD_DRY", "") not in ("", "0", "false"):
    args.dry = True
  if args.dry:
    args.duration = min(args.duration, 2.0)
    args.concurrency = min(args.concurrency, 4)
    args.scenes = min(args.scenes, 2)
    args.img_size = min(args.img_size, 32)
    args.num_planes = min(args.num_planes, 4)
    args.cluster_backends = min(args.cluster_backends, 3)
    args.tile_size = min(args.tile_size, max(8, args.img_size // 4))
  if args.inflight < 1:
    raise SystemExit(f"--inflight must be >= 1, got {args.inflight}")
  if args.tile_size < 8:
    raise SystemExit(f"--tile-size must be >= 8, got {args.tile_size}")
  if args.session or args.session_ab:
    if (args.chaos or args.ab or args.edge_ab or args.cluster
        or args.edge or args.tiled_ab or args.overload_ab
        or args.asset_ab):
      raise SystemExit("--session/--session-ab measure the streaming "
                       "session tier on their own service; they do not "
                       "combine with --chaos/--ab/--edge-ab/--edge/"
                       "--cluster/--tiled-ab/--overload-ab/--asset-ab")
    return session_ab_main(args) if args.session_ab else session_main(args)
  if args.asset_ab:
    if (args.chaos or args.ab or args.edge_ab or args.cluster
        or args.edge or args.tiled_ab or args.overload_ab):
      raise SystemExit("--asset-ab measures the asset delivery tier on "
                       "its own service; it does not combine with "
                       "--chaos/--ab/--edge-ab/--edge/--cluster/"
                       "--tiled-ab/--overload-ab")
    return asset_ab_main(args)
  if args.overload_ab:
    if (args.chaos or args.ab or args.edge_ab or args.cluster
        or args.edge or args.tiled_ab or args.asset_ab):
      raise SystemExit("--overload-ab compares clean in-process arms; "
                       "it does not combine with --chaos/--ab/--edge-ab/"
                       "--edge/--cluster/--tiled-ab/--asset-ab")
    return overload_ab_main(args)
  if args.tiled_ab:
    if args.chaos or args.ab or args.edge_ab or args.cluster or args.edge:
      raise SystemExit("--tiled-ab compares clean in-process arms; it "
                       "does not combine with --chaos/--ab/--edge-ab/"
                       "--edge/--cluster")
    return tiled_ab_main(args)
  if args.chaos_crashloop and not args.cluster:
    raise SystemExit("--chaos-crashloop drills the multi-host tier; "
                     "add --cluster")
  if args.chaos_router and not args.cluster:
    raise SystemExit("--chaos-router drills the multi-host tier; "
                     "add --cluster")
  if args.autoscale_ab and not args.cluster:
    raise SystemExit("--autoscale-ab drills the multi-host tier; "
                     "add --cluster")
  if args.chaos_router and args.chaos_crashloop:
    raise SystemExit("--chaos-router and --chaos-crashloop are separate "
                     "drills; run them in separate rounds")
  if args.autoscale_ab and (args.chaos_router or args.chaos_crashloop):
    raise SystemExit("--autoscale-ab compares clean elastic/fixed arms; "
                     "it does not combine with --chaos-router/"
                     "--chaos-crashloop")
  if args.cluster:
    if args.ab or args.edge_ab:
      raise SystemExit("--ab/--edge-ab measure the in-process path; "
                       "they do not combine with --cluster")
    if args.edge:
      raise SystemExit("--edge measures the in-process path; spawn edge-"
                       "caching backends with --backend-args "
                       "'--edge-cache' via the cluster CLI instead")
    if args.dry:
      args.duration = max(args.duration, 4.0)  # give the kill phase room
    if args.autoscale_ab:
      if args.dry:
        # Spawning + warming a backend mid-window takes ~8-15s on CPU
        # (it races the surge for cores); the ramp needs room for the
        # scale-up, a post-admit judge stretch, AND the idle tail.
        # Batch 1 keeps one dry backend saturable (tiny renders drain
        # the surge before queue depth — the trip signal — can build),
        # and 4 base workers make the 5x surge 16 closed-loop streams:
        # past one dry backend's near-vertical saturation knee (p99
        # jumps from ~42ms at 8 streams to ~1s at 16), so splitting
        # them across two backends lands BACK under the knee and flips
        # the verdict by an order of magnitude, not noise.
        # 36s: a contended cold spawn lands its warmed admit anywhere
        # from ~10 to ~20s after the surge begins; the judge window
        # (0.68-0.8 of the run) must start AFTER the worst observed
        # admit with margin, or the verdict measures spawn-time noise.
        args.duration = max(args.duration, 36.0)
        args.max_batch = 1
        args.concurrency = 4
      return autoscale_ab_main(args)
    if args.chaos_router:
      return chaos_router_main(args)
    return cluster_main(args)
  if args.edge_ab:
    if args.chaos or args.ab:
      raise SystemExit("--edge-ab compares clean edge-on/off arms; it "
                       "does not combine with --chaos or --ab")
    return edge_ab_main(args)
  if args.ab:
    if args.chaos:
      raise SystemExit("--ab compares clean arms; it does not combine "
                       "with --chaos")
    return ab_main(args)
  print(json.dumps(inprocess_run(args, args.inflight, edge=args.edge)))
  return 0


if __name__ == "__main__":
  sys.exit(main())
