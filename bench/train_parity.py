"""Two-stack training-trajectory parity: JAX vs the torch mirror.

VERDICT r2 item 3 ("prove training"): run >= 200 optimization steps of the
full renderer-in-the-loss pipeline (net -> MPI -> differentiable render ->
VGG-perceptual loss -> Adam) in BOTH stacks from IDENTICAL weights on
IDENTICAL synthetic batches, and assert the loss trajectories track. The
reference's own training evidence is its notebook loss table
(fast-torch-stereo-vision.ipynb cell 16; BASELINE.md) on RealEstate10K —
an external 4 GB dataset this zero-egress environment cannot fetch — so the
hermetic equivalent is trajectory parity on the procedural dataset plus the
recorded curve artifact.

Writes ``artifacts/train_parity.json`` (per-step losses for both stacks +
summary stats) and exits non-zero if the trajectories diverge.

Usage: python bench/train_parity.py [--steps 200] [--out artifacts/...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_batches(steps: int, img_size: int, num_planes: int):
  """Materialize `steps` identical-for-both-stacks batches (numpy)."""
  from mpi_vision_tpu.data import realestate

  root = tempfile.mkdtemp(prefix="mpi_synth_")
  realestate.synthesize_dataset(root, num_scenes=4, frames=4,
                                img_size=img_size, seed=0)
  ds = realestate.RealEstateDataset(
      root, img_size=img_size, num_planes=num_planes,
      rng=np.random.default_rng(7))
  batches = []
  order_rng = np.random.default_rng(11)
  while len(batches) < steps:
    for batch in realestate.iterate_batches(ds, batch_size=1, rng=order_rng):
      batches.append({k: np.asarray(v) for k, v in batch.items()})
      if len(batches) >= steps:
        break
  return batches


def run_jax(batches, torch_net_state, torch_vgg_state, num_planes: int,
            lr: float):
  import jax
  import jax.numpy as jnp
  import optax

  from mpi_vision_tpu.models import stereo_mag
  from mpi_vision_tpu.train import loop as train_loop
  from mpi_vision_tpu.train import vgg

  params = stereo_mag.params_from_torch_state(torch_net_state)["params"]
  model = stereo_mag.StereoMagnificationModel(num_planes=num_planes)
  state = train_loop.TrainState.create(
      apply_fn=model.apply, params=params, tx=optax.adam(lr))
  vgg_params = vgg.params_from_torch_state(torch_vgg_state)
  step = train_loop.make_train_step(vgg_params, resize=None)
  losses = []
  for batch in batches:
    state, metrics = step(state, {k: jnp.asarray(v)
                                  for k, v in batch.items()})
    losses.append(metrics["loss"])
  return [float(l) for l in jax.device_get(losses)]


def run_torch(batches, net, features, lr: float):
  import torch

  from mpi_vision_tpu.torchref import loss as torch_loss

  opt = torch.optim.Adam(net.parameters(), lr=lr)
  losses = []
  for np_batch in batches:
    batch = {k: torch.as_tensor(v) for k, v in np_batch.items()}
    net_input = batch["net_input"].permute(0, 3, 1, 2)     # NHWC -> NCHW
    opt.zero_grad()
    loss = torch_loss.vgg_perceptual_loss(
        net(net_input), batch, features, resize=None)
    loss.backward()
    opt.step()
    losses.append(float(loss.detach()))
  return losses


def main() -> None:
  ap = argparse.ArgumentParser()
  ap.add_argument("--steps", type=int, default=200)
  ap.add_argument("--img-size", type=int, default=64)
  ap.add_argument("--num-planes", type=int, default=5)
  ap.add_argument("--lr", type=float, default=2e-4)   # reference, cell 15-16
  ap.add_argument("--out", default=os.path.join(
      os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
      "artifacts", "train_parity.json"))
  args = ap.parse_args()

  import torch

  from mpi_vision_tpu.torchref import model as torch_model
  from mpi_vision_tpu.torchref import vgg as torch_vgg

  t0 = time.time()
  batches = build_batches(args.steps, args.img_size, args.num_planes)
  print(f"built {len(batches)} batches in {time.time() - t0:.1f}s",
        file=sys.stderr)

  # One shared initialization: torch inits, JAX transfers.
  torch.manual_seed(0)
  net = torch_model.StereoMagnificationModel(num_planes=args.num_planes)
  features = torch_vgg.build_features()
  for p in features.parameters():       # frozen, as in the reference
    p.requires_grad_(False)
  net_state0 = {k: v.clone() for k, v in net.state_dict().items()}
  vgg_state = {k: v.clone() for k, v in features.state_dict().items()}

  t0 = time.time()
  jax_losses = run_jax(batches, net_state0, vgg_state, args.num_planes,
                       args.lr)
  t_jax = time.time() - t0
  print(f"jax: {len(jax_losses)} steps in {t_jax:.1f}s "
        f"first={jax_losses[0]:.4f} last={jax_losses[-1]:.4f}",
        file=sys.stderr)

  t0 = time.time()
  torch_losses = run_torch(batches, net, features, args.lr)
  t_torch = time.time() - t0
  print(f"torch: {len(torch_losses)} steps in {t_torch:.1f}s "
        f"first={torch_losses[0]:.4f} last={torch_losses[-1]:.4f}",
        file=sys.stderr)

  jl, tl = np.asarray(jax_losses), np.asarray(torch_losses)
  rel = np.abs(jl - tl) / np.maximum(np.abs(tl), 1e-6)
  summary = {
      "steps": args.steps,
      "img_size": args.img_size,
      "num_planes": args.num_planes,
      "lr": args.lr,
      "first_loss": {"jax": jl[0].item(), "torch": tl[0].item()},
      "final_loss": {"jax": jl[-1].item(), "torch": tl[-1].item()},
      "max_rel_diff_first10": rel[:10].max().item(),
      "mean_rel_diff": rel.mean().item(),
      "max_rel_diff": rel.max().item(),
      "jax_seconds": t_jax,
      "torch_seconds": t_torch,
      "jax_losses": jax_losses,
      "torch_losses": torch_losses,
  }
  os.makedirs(os.path.dirname(args.out), exist_ok=True)
  with open(args.out, "w") as f:
    json.dump(summary, f, indent=1)
  print(json.dumps({k: summary[k] for k in (
      "steps", "first_loss", "final_loss", "max_rel_diff_first10",
      "mean_rel_diff")}))

  # Trajectory assertions: identical start (shared weights), tight tracking
  # early (before f32 divergence compounds), loose tracking overall, and
  # actual learning in both stacks.
  ok = (rel[0] < 1e-3 and rel[:10].max() < 0.02 and rel.mean() < 0.10
        and jl[-1] < jl[0] and tl[-1] < tl[0])
  if not ok:
    raise SystemExit(f"trajectory divergence: rel0={rel[0]:.2e} "
                     f"first10={rel[:10].max():.3f} mean={rel.mean():.3f}")
  print("trajectory parity OK", file=sys.stderr)


if __name__ == "__main__":
  main()
