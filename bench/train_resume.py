"""Crash-safe training smoke: kill / corrupt / resume a tiny run, prove
bit-exact recovery.

Drives ``train.loop.fit_resumable`` + ``ckpt.CheckpointStore`` over a
procedurally generated batch stream (pure function of (seed, epoch,
index) — the bit-exact-resume contract) with a tiny L2-loss model, and
prints ONE JSON line (stdout; diagnostics on stderr)::

  {"metric": "train_resume", "value": <final_step>, "unit": "steps",
   "digest": <sha256 of the final checkpoint's arrays>,
   "resumed_from": ..., "preempted": ..., "nan_rollbacks": ...,
   "quarantined": ..., "saves": ...}

``digest`` hashes the final SAVED checkpoint (params + optimizer state
+ step, read back from disk) — two runs that print the same digest
walked bit-identical parameter streams AND round-tripped them through
the store.

Scheduled faults make it a crash-test victim (tests/test_train_resume.py):

  --crash-at N        hard-SIGKILL the process (from inside the fault
                      source) before global step N — the acceptance
                      test's mid-epoch kill; rerunning with the same
                      --dir resumes from the newest good checkpoint.
  --soft-crash-at N   ``SimulatedCrash`` instead (nonzero rc, atexit
                      still runs) — the in-process variant.
  --corrupt-save N    corrupt (truncate) the checkpoint published by
                      save index N after it lands: resume must
                      quarantine it and fall back to the previous good
                      one, and STILL reach the bit-identical digest.
  --nan-at N          poison the batch at step N (NaN guard rollback +
                      LR cut path).
  --preempt-at N      set the preemption flag at step N (SIGTERM
                      semantics without a signal).

``--selftest`` runs the whole story in ONE process — fresh run, soft
crash, resume, digest comparison — the cheapest tier-1 smoke.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from _common import log as _log


def build_parser() -> argparse.ArgumentParser:
  ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
  ap.add_argument("--dir", default="",
                  help="checkpoint store root (required unless --selftest)")
  ap.add_argument("--epochs", type=int, default=3)
  ap.add_argument("--batches", type=int, default=4,
                  help="batches per epoch")
  ap.add_argument("--save-every", type=int, default=2)
  ap.add_argument("--keep", type=int, default=8)
  ap.add_argument("--img-size", type=int, default=16)
  ap.add_argument("--planes", type=int, default=2)
  ap.add_argument("--lr", type=float, default=1e-3)
  ap.add_argument("--seed", type=int, default=0)
  ap.add_argument("--fresh", action="store_true",
                  help="ignore existing checkpoints (resume='never')")
  ap.add_argument("--crash-at", type=int, default=-1)
  ap.add_argument("--soft-crash-at", type=int, default=-1)
  ap.add_argument("--corrupt-save", type=int, default=-1)
  ap.add_argument("--nan-at", type=int, default=-1)
  ap.add_argument("--preempt-at", type=int, default=-1)
  ap.add_argument("--selftest", action="store_true",
                  help="one-process crash+resume bit-exactness check")
  return ap


def make_batch(seed: int, epoch: int, index: int, hw: int, planes: int):
  """One synthetic batch, a pure function of (seed, epoch, index)."""
  rng = np.random.default_rng([seed, epoch, index])
  pose = np.eye(4, dtype=np.float32)
  pose[0, 3] = 0.04
  half = np.float32(hw / 2)
  k = np.array([[half, 0, half], [0, half, half], [0, 0, 1]], np.float32)
  return {
      "net_input": rng.uniform(
          -1, 1, (1, hw, hw, 3 + 3 * planes)).astype(np.float32),
      "ref_img": rng.uniform(-1, 1, (1, hw, hw, 3)).astype(np.float32),
      "tgt_img": rng.uniform(-1, 1, (1, hw, hw, 3)).astype(np.float32),
      "tgt_img_cfw": np.stack([pose]),
      "ref_img_wfc": np.stack([np.eye(4, dtype=np.float32)]),
      "intrinsics": np.stack([k]),
      "mpi_planes": np.linspace(1.0, 0.01, planes, dtype=np.float32),
  }


def store_digest(store) -> str:
  """sha256 over the newest checkpoint's arrays (read back from disk)."""
  restored = store.restore()
  if restored is None:
    return ""
  h = hashlib.sha256()
  for key in sorted(restored.arrays):
    arr = np.asarray(restored.arrays[key], order="C")
    h.update(key.encode())
    h.update(str(arr.dtype).encode())
    h.update(arr.tobytes())
  return h.hexdigest()


def run(args, ckpt_dir: str, resume: str):
  import jax

  from mpi_vision_tpu.ckpt import (
      CheckpointStore,
      NanGuard,
      PreemptionGuard,
      TrainFault,
      TrainFaultSource,
  )
  from mpi_vision_tpu.train import loop as train_loop

  faults = TrainFaultSource()
  if args.crash_at >= 0:
    faults.at_step(args.crash_at, TrainFault("crash", hard=True))
  if args.soft_crash_at >= 0:
    faults.at_step(args.soft_crash_at, TrainFault("crash", hard=False))
  if args.nan_at >= 0:
    faults.at_step(args.nan_at, TrainFault("nan"))
  if args.preempt_at >= 0:
    faults.at_step(args.preempt_at, TrainFault("preempt"))
  if args.corrupt_save >= 0:
    faults.at_save(args.corrupt_save, TrainFault("corrupt"))

  store = CheckpointStore(ckpt_dir, keep=args.keep, fault_hook=faults.store_hook)
  state = train_loop.create_train_state(
      jax.random.PRNGKey(args.seed), num_planes=args.planes,
      image_size=(args.img_size, args.img_size), learning_rate=args.lr,
      norm=None, mutable_lr=True)
  step = train_loop.make_train_step(vgg_params=None)

  def make_batches(epoch: int):
    return [make_batch(args.seed, epoch, i, args.img_size, args.planes)
            for i in range(args.batches)]

  with PreemptionGuard() as preemption:
    state, report = train_loop.fit_resumable(
        state, args.epochs, make_batches, store, step=step,
        save_every=args.save_every, resume=resume,
        nan_guard=NanGuard(), preemption=preemption,
        fault_source=faults, log=_log,
        meta={"model": {"num_planes": args.planes, "img_size": args.img_size,
                        "norm": None}})
  # Digest the artifact a consumer would restore, not the in-memory
  # state: equality across runs proves store round-trip AND bit-exact
  # training in one check.
  digest = store_digest(CheckpointStore(ckpt_dir, keep=args.keep))
  return {
      "metric": "train_resume",
      "value": report["final_step"],
      "unit": "steps",
      "digest": digest,
      "resumed_from": report["resumed_from"],
      "preempted": report["preempted"],
      "nan_rollbacks": report["nan_rollbacks"],
      "quarantined": report["quarantined"],
      "saves": report["saves"],
      "losses": len(report["losses"]),
      "injected": faults.injected,
  }


def selftest(args) -> dict:
  """Fresh / soft-crash / resume in one process; digests must agree."""
  import tempfile

  from mpi_vision_tpu.ckpt import SimulatedCrash

  base = argparse.Namespace(**vars(args))
  for field in ("crash_at", "soft_crash_at", "corrupt_save", "nan_at",
                "preempt_at"):
    setattr(base, field, -1)

  with tempfile.TemporaryDirectory(prefix="mpi_resume_self_") as root:
    clean = run(base, os.path.join(root, "clean"), resume="never")
    crash_dir = os.path.join(root, "crashed")
    crash_args = argparse.Namespace(**vars(base))
    crash_args.soft_crash_at = args.epochs * args.batches // 2
    try:
      run(crash_args, crash_dir, resume="never")
      raise SystemExit("selftest: scheduled crash never fired")
    except SimulatedCrash:
      _log(f"selftest: crashed at step {crash_args.soft_crash_at} as "
           "scheduled")
    resumed = run(base, crash_dir, resume="auto")
  ok = (clean["digest"] == resumed["digest"] and clean["digest"]
        and resumed["resumed_from"] is not None)
  if not ok:
    raise SystemExit(
        f"selftest: resumed digest {resumed['digest'][:12]} != clean "
        f"{clean['digest'][:12]} (resumed_from={resumed['resumed_from']})")
  return {
      "metric": "train_resume_selftest",
      "value": 1,
      "unit": "ok",
      "bit_exact": True,
      "final_step": clean["value"],
      "resumed_from": resumed["resumed_from"],
      "digest": clean["digest"],
  }


def main(argv=None) -> None:
  # The hardened CPU mesh (shared with tests/conftest.py): hermetic off
  # any tunneled TPU backend, and the repo's persistent compile cache
  # keeps the many tiny victim subprocesses from re-paying XLA compiles.
  from _cpu_mesh import force_cpu_mesh

  force_cpu_mesh(8)
  args = build_parser().parse_args(argv)
  if args.selftest:
    print(json.dumps(selftest(args)))
    return
  if not args.dir:
    raise SystemExit("--dir is required (or pass --selftest)")
  out = run(args, os.path.abspath(args.dir),
            resume="never" if args.fresh else "auto")
  print(json.dumps(out))


if __name__ == "__main__":
  main()
