"""BASELINE config 1: over-composite the scene_009 fixture MPI (10 planes,
640x400 — the reference repo's ``test/rgba_00..09.png``) to one frontal
view, and compare against the CPU-torch oracle.

Metric: max per-pixel L1 vs torch (budget 1e-3, BASELINE.md). Also reports
the jitted composite throughput as an extra field.

Usage: python bench/config1_composite.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from _common import emit, log, repo_root, time_fn

L1_BUDGET = 1e-3


def load_fixture_mpi() -> np.ndarray:
  """[P, H, W, 4] float32 in [0, 1], back-to-front (index 0 = farthest,
  matching the viewer's layer order, template:309-315)."""
  from PIL import Image

  base = os.path.join(repo_root(), "tests", "fixtures", "scene_009")
  planes = [
      np.asarray(Image.open(os.path.join(base, f"rgba_{i:02d}.png")),
                 np.float32) / 255.0
      for i in range(10)
  ]
  return np.stack(planes)


def main() -> None:
  import jax.numpy as jnp
  import torch

  from mpi_vision_tpu.core import compose
  from mpi_vision_tpu.torchref import oracle

  mpi = load_fixture_mpi()                     # [P, H, W, 4]
  log(f"fixture MPI: {mpi.shape}")

  want = oracle.over_composite(torch.from_numpy(mpi)).numpy()
  got, sec = time_fn(
      lambda x: compose.over_composite_scan(x[:, None])[0],
      jnp.asarray(mpi), iters=20)
  l1 = float(np.abs(np.asarray(got) - want).max())
  log(f"composite: {1.0 / sec:.1f} frames/s, L1 vs torch {l1:.2e}")

  emit("fixture_composite_l1_vs_torch", l1, "max_abs_diff",
       L1_BUDGET / max(l1, 1e-12), frames_per_s=round(1.0 / sec, 2))
  if l1 > L1_BUDGET:
    raise SystemExit(f"L1 {l1} exceeds the {L1_BUDGET} parity budget")


if __name__ == "__main__":
  main()
