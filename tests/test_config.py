"""Config system tests (SURVEY.md §5.6): the defaults ARE the reference."""

import dataclasses

import jax
import numpy as np

from mpi_vision_tpu import config


def test_reference_defaults():
  c = config.TrainConfig()
  assert c.data.img_size == 224 and c.data.num_planes == 10
  assert (c.data.depth_near, c.data.depth_far) == (1.0, 100.0)
  assert (c.data.min_dist, c.data.max_dist) == (16e3, 500e3)
  assert c.data.batch_size == 1
  assert c.learning_rate == 2e-4 and c.epochs == 20
  assert c.vgg_resize == 224


def test_scaled_480():
  c = config.TrainConfig.scaled_480()
  assert c.data.img_size == 480 and c.data.num_planes == 33
  assert c.learning_rate == 2e-4  # only the data shape changes


def test_frozen():
  import pytest
  with pytest.raises(dataclasses.FrozenInstanceError):
    config.TrainConfig().learning_rate = 1.0


def test_make_train_state_and_step(rng):
  c = config.TrainConfig(
      data=config.DataConfig(img_size=32, num_planes=4), vgg_resize=None)
  state = c.make_train_state(jax.random.PRNGKey(0))
  step = c.make_train_step(vgg_params=None)   # L2 metric loss
  hw, p = 32, 4
  pose = np.eye(4, dtype=np.float32)
  batch = {
      "net_input": np.asarray(
          rng.uniform(-1, 1, (1, hw, hw, 3 + 3 * p)), np.float32),
      "ref_img": np.asarray(rng.uniform(-1, 1, (1, hw, hw, 3)), np.float32),
      "tgt_img": np.asarray(rng.uniform(-1, 1, (1, hw, hw, 3)), np.float32),
      "tgt_img_cfw": pose[None],
      "ref_img_wfc": pose[None],
      "intrinsics": np.asarray(
          [[[16.0, 0, 16], [0, 16.0, 16], [0, 0, 1]]], np.float32),
      "mpi_planes": np.asarray(config.RenderConfig(num_planes=p).depths()),
  }
  state2, metrics = step(state, batch)
  assert np.isfinite(float(metrics["loss"]))
  assert int(state2.step) == 1


def test_render_config_depths_descending():
  d = np.asarray(config.RenderConfig().depths())
  assert d.shape == (32,) and (np.diff(d) < 0).all()
  assert d[0] == 100.0 and d[-1] == 1.0
