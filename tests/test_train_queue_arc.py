"""The training-queue acceptance arc (ISSUE 12's signature pin).

ONE module-scoped drill over REAL ``cli train`` subprocesses: a 3-job
queue holding

  * ``good``   — a clean job (the uninterrupted digest baseline),
  * ``poison`` — hard-SIGKILLs itself before step 0 on EVERY attempt
    (the crash-looper), and
  * ``victim`` — hard-SIGKILLs itself mid-run on attempt 0 only (the
    kill-and-resume case; same spec + seed as ``good``),

drained by a ``TrainSupervisor`` publishing completed checkpoints into a
watch store that an in-process ``--reload-ckpt-s`` serving stack
(``CheckpointWatcher`` -> ``scenes_from_checkpoint`` -> ``swap_scenes``,
the serve CLI's reload path in miniature) swaps live under constant
render traffic. The pins, each its own test over the one shared run:

  * the poison job is quarantined at EXACTLY its restart budget while
    the sibling jobs complete;
  * the SIGKILLed-then-requeued victim's final checkpoint digest is
    bit-identical to the uninterrupted run's;
  * both completed checkpoints are published and served live with zero
    dropped requests across the swap.
"""

import hashlib
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from _cpu_mesh import hardened_env  # noqa: E402

SPEC = {"epochs": 1, "img_size": 32, "num_planes": 4,
        "synthetic_scenes": 2, "save_every": 1, "seed": 7}
RESTART_BUDGET = 1  # poison: 1 first attempt + 1 retry, then quarantine


def _digest(ckpt_root: str) -> str:
  """sha256 over the newest checkpoint's arrays, read back from disk
  (the bench/train_resume.py digest contract)."""
  from mpi_vision_tpu.ckpt import CheckpointStore

  restored = CheckpointStore(ckpt_root).restore()
  assert restored is not None, f"no checkpoint under {ckpt_root}"
  h = hashlib.sha256()
  for key in sorted(restored.arrays):
    arr = np.asarray(restored.arrays[key], order="C")
    h.update(key.encode())
    h.update(str(arr.dtype).encode())
    h.update(arr.tobytes())
  return h.hexdigest()


@pytest.fixture(scope="module")
def arc(tmp_path_factory):
  from mpi_vision_tpu.ckpt import CheckpointStore, CheckpointWatcher
  from mpi_vision_tpu.ckpt.export import scenes_from_checkpoint
  from mpi_vision_tpu.obs.events import EventLog
  from mpi_vision_tpu.obs.slo import SloConfig, SloTracker
  from mpi_vision_tpu.serve import RenderService
  from mpi_vision_tpu.train.queue import JobQueue
  from mpi_vision_tpu.train.supervisor import (
      SubprocessLauncher,
      TrainSupervisor,
  )

  root = tmp_path_factory.mktemp("train_queue_arc")
  env = hardened_env(1)
  # Share the suite's persistent XLA cache so reruns skip the compiles.
  env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(REPO, ".jax_cache")

  events = EventLog(capacity=1024)
  queue = JobQueue(str(root / "queue"), lease_s=120.0, events=events)
  queue.submit(dict(SPEC), job_id="good")
  # The poison job crashes before its first step ever runs, so it never
  # compiles a train step — keep its model tiny too (16px): both its
  # spawns are pure process+init overhead.
  queue.submit({**SPEC, "seed": 3, "img_size": 16, "synthetic_scenes": 1,
                "faults": ["crash@step=0,hard"]}, job_id="poison")
  queue.submit({**SPEC, "faults": ["crash@step=1,hard,attempt=0"]},
               job_id="victim")
  publish = CheckpointStore(str(root / "publish"), keep=8, events=events)
  slo = SloTracker(SloConfig(latency_threshold_s=60.0))
  supervisor = TrainSupervisor(
      queue, launcher=SubprocessLauncher(str(root / "work"), env=env),
      publish_store=publish, concurrency=2, probe_s=0.25,
      probe_timeout_s=2.0, wedge_after=200, startup_grace_s=120.0,
      restart_budget=RESTART_BUDGET, budget_window_s=600.0,
      backoff_base_s=0.1, backoff_max_s=0.5, slo=slo, events=events)
  supervisor.start()

  # Serving side: once the FIRST publish lands, stand up the serve CLI's
  # --reload-ckpt-s machinery in miniature and hammer it with renders
  # while the remaining publishes swap scenes live.
  deadline = time.monotonic() + 240.0
  while publish.latest_step() is None and time.monotonic() < deadline:
    time.sleep(0.1)
  assert publish.latest_step() is not None, (
      "no job published within the deadline; events: "
      f"{events.snapshot(recent=40)['events']}")
  first_step = publish.latest_step()
  scenes, info = scenes_from_checkpoint(str(root / "publish"), scenes=1,
                                        stable_ids=True)
  svc = RenderService(max_batch=4, max_wait_ms=0.5, use_mesh=False,
                      resilience=None)
  for sid, rgba, depths, k in scenes:
    svc.add_scene(sid, rgba, depths, k)
  scene_ids = [s[0] for s in scenes]

  last_bake: list = []

  def reload_step(step):
    new_scenes, _ = scenes_from_checkpoint(str(root / "publish"), scenes=1,
                                           stable_ids=True)
    svc.swap_scenes({sid: (rgba, depths, k)
                     for sid, rgba, depths, k in new_scenes}, prebake=True)
    last_bake[:] = new_scenes

  watcher = CheckpointWatcher(publish, reload_step, poll_s=0.2,
                              initial_step=first_step).start()
  stop = threading.Event()
  failures: list = []
  completed = [0]

  def hammer():
    i = 0
    pose = np.eye(4, dtype=np.float32)
    while not stop.is_set():
      i += 1
      pose[0, 3] = 0.001 * (i % 7)
      try:
        img = svc.render(scene_ids[0], pose, timeout=60)
        assert img.shape[-1] == 3
      except BaseException as e:  # noqa: BLE001 - ANY failure is the bug
        failures.append(e)
        return
      completed[0] += 1
      # Throttled: constant coverage across the swaps without starving
      # the training subprocesses of the box's one core.
      time.sleep(0.02)

  threads = [threading.Thread(target=hammer, daemon=True)
             for _ in range(1)]
  for t in threads:
    t.start()

  while time.monotonic() < deadline:
    with supervisor._lock:
      busy = bool(supervisor._running)
    if not busy and queue.drained():
      break
    time.sleep(0.1)
  # Let the watcher observe the final publish under load, then wind down.
  final_deadline = time.monotonic() + 10.0
  while (watcher.seen_step != publish.latest_step()
         and time.monotonic() < final_deadline):
    time.sleep(0.1)
  stop.set()
  for t in threads:
    t.join(30)
  supervisor.stop()
  watcher.stop()

  yield {
      "root": root, "queue": queue, "supervisor": supervisor,
      "publish": publish, "events": events, "slo": slo, "svc": svc,
      "watcher": watcher, "failures": failures,
      "completed": completed[0], "scene_ids": scene_ids,
      "first_step": first_step, "last_bake": last_bake,
  }
  svc.close()


def test_queue_drained_with_poison_quarantined_at_exact_budget(arc):
  queue = arc["queue"]
  assert queue.drained(), queue.counts()
  assert queue.get("good").state == "done"
  assert queue.get("victim").state == "done"
  poison = queue.get("poison")
  assert poison.state == "quarantined", poison.record
  # EXACTLY the budget: 1 first attempt + RESTART_BUDGET retries.
  assert poison.attempts == 1 + RESTART_BUDGET
  assert arc["supervisor"].quarantines_total == 1
  assert arc["events"].count("training_job_quarantined") == 1
  text = arc["supervisor"].metrics_text()
  assert "mpi_train_queue_quarantines_total 1" in text


def test_sigkilled_then_requeued_job_is_bit_exact(arc):
  root = arc["root"]
  victim = arc["queue"].get("victim")
  # It really died by SIGKILL once and was requeued + resumed.
  assert victim.attempts == 2
  assert any(h["event"] == "requeued" for h in victim.record["history"])
  assert victim.record["history"][-1]["event"] == "done"
  base = _digest(str(root / "work" / "good" / "ckpt"))
  resumed = _digest(str(root / "work" / "victim" / "ckpt"))
  assert resumed == base, (
      "SIGKILL-mid-job + requeue + resume diverged from the "
      "uninterrupted sibling (same spec, same seed)")


def test_completed_jobs_published_and_served_live_with_zero_drops(arc):
  from mpi_vision_tpu.serve import RenderService

  publish = arc["publish"]
  # Both completed jobs published (monotone steps), quarantined one did
  # not.
  assert len(publish.steps()) == 2, publish.steps()
  assert arc["supervisor"].publishes_total == 2
  assert arc["supervisor"].publish_errors == 0
  # The second publish was swapped in live by the watcher...
  assert arc["watcher"].snapshot()["reloads"] >= 1
  assert arc["watcher"].seen_step == publish.latest_step()
  # ...with ZERO dropped requests under constant traffic.
  assert not arc["failures"], f"renders failed: {arc['failures'][:3]}"
  assert arc["completed"] > 0
  # And the pixels now serving provably come from the NEWEST publish:
  # the live service's render matches a service that only ever saw the
  # final reload's bake.
  got = arc["svc"].render(arc["scene_ids"][0],
                          np.eye(4, dtype=np.float32))
  assert arc["last_bake"], "watcher never delivered a reload bake"
  with RenderService(max_batch=2, max_wait_ms=0.5, use_mesh=False,
                     resilience=None) as fresh:
    sid, rgba, depths, k = arc["last_bake"][0]
    fresh.add_scene(sid, rgba, depths, k)
    np.testing.assert_array_equal(got, fresh.render(
        sid, np.eye(4, dtype=np.float32)))


def test_queue_slos_scored_in_the_slo_engine(arc):
  snap = arc["slo"].snapshot()
  avail = snap["objectives"]["availability"]["slow"]
  # EXACTLY the 5 attempt outcomes: good ok, poison bad x2, victim bad +
  # ok. Step-latency samples score only the latency objective — they
  # must not dilute the crash-loop out of the availability burn rate.
  assert avail["requests"] == 5, avail
  assert avail["bad"] == 3, avail
  assert snap["objectives"]["latency"]["slow"]["bad"] == 0


def test_two_real_workers_drain_one_queue_without_double_runs(
    tmp_path_factory):
  """ISSUE 15's multi-worker smoke over REAL subprocesses: two
  supervisors (distinct owners, separate work roots) drain one shared
  queue directory. The on-disk lease protocol must hand each job to
  exactly one worker — both jobs complete, each spawned exactly once,
  and the two workers' spawn counts sum to the job count."""
  from mpi_vision_tpu.train.queue import JobQueue
  from mpi_vision_tpu.train.supervisor import (
      SubprocessLauncher,
      TrainSupervisor,
  )

  root = tmp_path_factory.mktemp("train_queue_two_workers")
  env = hardened_env(1)
  env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(REPO, ".jax_cache")
  tiny = {"epochs": 1, "img_size": 16, "num_planes": 4,
          "synthetic_scenes": 1, "save_every": 1, "seed": 5}
  queue_dir = str(root / "queue")
  submitter = JobQueue(queue_dir, lease_s=120.0)
  submitter.submit(dict(tiny), job_id="jobA")
  submitter.submit({**tiny, "seed": 6}, job_id="jobB")

  def worker(owner):
    queue = JobQueue(queue_dir, lease_s=120.0)
    return TrainSupervisor(
        queue, launcher=SubprocessLauncher(str(root / owner), env=env),
        concurrency=1, probe_s=0.25, wedge_after=200,
        startup_grace_s=120.0, restart_budget=2, budget_window_s=600.0,
        backoff_base_s=0.1, backoff_max_s=0.5, owner=owner)

  sup1, sup2 = worker("worker1"), worker("worker2")
  sup1.start()
  sup2.start()
  try:
    deadline = time.monotonic() + 240.0
    while time.monotonic() < deadline:
      with sup1._lock:
        busy1 = bool(sup1._running)
      with sup2._lock:
        busy2 = bool(sup2._running)
      if not busy1 and not busy2 and submitter.drained():
        break
      time.sleep(0.1)
  finally:
    sup1.stop()
    sup2.stop()
  assert submitter.drained(), submitter.snapshot()
  for job_id in ("jobA", "jobB"):
    job = submitter.get(job_id)
    assert job.state == "done", job.record
    # Exactly one attempt ran it: no double-lease, no lost-and-retried.
    assert job.attempts == 1, job.record
  # Both spawns happened, each under exactly one owner.
  assert sup1.spawns_total + sup2.spawns_total == 2
  assert sup1.failures_total + sup2.failures_total == 0
