"""Tests for the Pallas backward pass (interpret mode on CPU).

Oracle: ``jax.vjp`` of ``reference_render`` — the same XLA path the
forward kernels are pinned against, whose own gradients are covered by
tests/test_sampling.py (bilinear grads vs torch).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_vision_tpu.core.camera import inv_depths
from mpi_vision_tpu.kernels import render_pallas as rp
from mpi_vision_tpu.kernels import render_pallas_bwd as rpb


def _mpi(rng, p, h, w, batch=None):
  shape = (p, 4, h, w) if batch is None else (batch, p, 4, h, w)
  return jnp.asarray(rng.uniform(0, 1, shape).astype(np.float32))


def _intrinsics(h, w):
  return jnp.asarray(
      np.array([[0.6 * w, 0, w / 2], [0, 0.6 * w, h / 2], [0, 0, 1]],
               np.float32))[None]


def _pose(tx=0.0, ty=0.0, tz=0.0, rx=0.0, ry=0.0):
  pose = np.eye(4, dtype=np.float32)
  cx, sx = np.cos(rx), np.sin(rx)
  cy, sy = np.cos(ry), np.sin(ry)
  rot_x = np.array([[1, 0, 0], [0, cx, -sx], [0, sx, cx]], np.float32)
  rot_y = np.array([[cy, 0, sy], [0, 1, 0], [-sy, 0, cy]], np.float32)
  pose[:3, :3] = rot_y @ rot_x
  pose[:3, 3] = [tx, ty, tz]
  return jnp.asarray(pose)[None]


def _homs(h, w, p=4, **pose_kw):
  depths = inv_depths(1.0, 100.0, p)
  return rp.pixel_homographies(
      _pose(**pose_kw), depths, _intrinsics(h, w), h, w)[:, 0]


def _roll_homs(h, w, p, deg, tx=0.0):
  """In-plane roll: v drifts with the tile column, escalating the
  SHARED_LEVELS slice ladder at small geometries (3 deg -> (32, 48),
  6 deg -> (40, 64) at 64x384)."""
  rz = np.radians(deg)
  pose = np.eye(4, dtype=np.float32)
  c, s = np.cos(rz), np.sin(rz)
  pose[:3, :3] = np.array([[c, -s, 0], [s, c, 0], [0, 0, 1]], np.float32)
  pose[0, 3] = tx
  depths = inv_depths(1.0, 100.0, p)
  return rp.pixel_homographies(
      jnp.asarray(pose)[None], depths, _intrinsics(h, w), h, w)[:, 0]


def _reference_warp(planes, homs):
  """Per-plane XLA warp (reference_render without the composite)."""
  from mpi_vision_tpu.core import geometry, sampling
  _, _, h, w = planes.shape
  nhwc = jnp.moveaxis(planes, 1, -1)[:, None]
  grid = jnp.moveaxis(geometry.homogeneous_grid(h, w), 0, -1)
  pts = geometry.apply_homography(grid, homs[:, None])
  xy = geometry.from_homogeneous(pts)
  coords = (xy + 0.5) / jnp.array([w, h], xy.dtype)
  warped = sampling.bilinear_sample(nhwc, coords)       # [P, 1, H, W, 4]
  return jnp.moveaxis(warped[:, 0], -1, 1)              # [P, 4, H, W]


TRANSLATION = dict(tx=0.06, ty=-0.03, tz=-0.04)
ROTATION = dict(tx=0.04, ty=0.02, tz=0.03, rx=0.006, ry=-0.008)


class TestWarpPlanesFused:

  def test_separable_matches_reference_warp(self, rng):
    p, h, w = 4, 32, 256
    planes = _mpi(rng, p, h, w)
    homs = _homs(h, w, p, **TRANSLATION)
    assert rp.is_separable(homs)
    n_windows = rp._sep_windows_needed(homs, h, w)
    got = rpb.warp_planes_fused(planes[None], homs[None], True, n_windows)[0]
    want = _reference_warp(planes, homs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

  def test_general_matches_reference_warp(self, rng):
    p, h, w = 4, 32, 256
    planes = _mpi(rng, p, h, w)
    homs = _homs(h, w, p, **ROTATION)
    assert not rp.is_separable(homs)
    plan = rp._plan_shared(homs, h, w)
    assert plan is not None
    got = rpb.warp_planes_fused(planes[None], homs[None], False, plan)[0]
    want = _reference_warp(planes, homs)
    # f32 tap-boundary wobble on the shared-gather path: the kernel's
    # in-kernel u/v and the XLA warp's coords can floor one ulp apart near
    # integer boundaries, worth <= the boundary tap's weight (~1e-4); the
    # repo-wide parity budget is 1e-3 (BASELINE.md).
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)


class TestPlanAdjointSep:

  def test_normal_translation_accepted(self):
    h, w = 32, 256
    plan = rpb.plan_adjoint_sep(_homs(h, w, **TRANSLATION), h, w)
    assert plan is not None
    n_taps, n_windows = plan
    assert 2 <= n_taps <= 6 and n_windows in (2, 3)

  def test_mirrored_map_rejected(self):
    homs = jnp.asarray(
        np.diag([-1.0, 1.0, 1.0]).astype(np.float32))[None]
    assert rpb.plan_adjoint_sep(homs, 32, 256) is None

  def test_extreme_minification_rejected(self):
    # Forward scale 0.2 => tent support 10 source columns: fan > 6 taps.
    homs = jnp.asarray(np.diag([0.2, 1.0, 1.0]).astype(np.float32))[None]
    assert rpb.plan_adjoint_sep(homs, 32, 256) is None


class TestBackwardPlanes:

  def _check(self, rng, pose_kw, p=4, h=32, w=256, batch=1, atol=2e-4):
    planes = _mpi(rng, p, h, w, batch=batch)
    homs = jnp.stack([_homs(h, w, p, **pose_kw)] * batch)
    assert rp.is_separable(homs)
    assert rp.fits_envelope(homs, h, w, True)
    n_windows = rp._sep_windows_needed(homs, h, w)
    adj_plan = rpb.plan_adjoint_sep(homs, h, w)
    assert adj_plan is not None
    g = jnp.asarray(rng.normal(size=(batch, 3, h, w)).astype(np.float32))
    got = rpb.backward_planes(planes, homs, g, True, n_windows, adj_plan)
    _, vjp = jax.vjp(rp._reference_render_batch, planes, homs)
    want, _ = vjp(g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=atol)

  def test_translation(self, rng):
    self._check(rng, TRANSLATION)

  def test_zoom(self, rng):
    self._check(rng, dict(tz=0.25))

  def test_batched(self, rng):
    self._check(rng, TRANSLATION, batch=2)

  def test_identity(self, rng):
    self._check(rng, {})

  def test_property_random_separable_poses(self, rng):
    """Accepted poses' Pallas backward matches the XLA VJP."""
    h, w, p = 32, 256, 3
    checked = 0
    for _ in range(12):
      pose_kw = dict(
          tx=float(rng.uniform(-0.15, 0.15)),
          ty=float(rng.uniform(-0.15, 0.15)),
          tz=float(rng.uniform(-0.3, 0.3)))
      homs = _homs(h, w, p, **pose_kw)
      if not rp.fits_envelope(homs, h, w, True):
        continue
      if rpb.plan_adjoint_sep(homs, h, w) is None:
        continue
      self._check(rng, pose_kw, p=p, h=h, w=w)
      checked += 1
    assert checked >= 6


class TestBackwardPlanesGeneral:

  def _check(self, rng, pose_kw, p=4, h=32, w=256, batch=1, atol=1e-3):
    planes = _mpi(rng, p, h, w, batch=batch)
    homs = jnp.stack([_homs(h, w, p, **pose_kw)] * batch)
    assert not rp.is_separable(homs)
    fwd_plan = rp._plan_shared(homs, h, w)
    assert fwd_plan is not None
    adj_plan = rpb.plan_adjoint_shr(homs, h, w)
    assert adj_plan is not None, "general adjoint plan rejected"
    g = jnp.asarray(rng.normal(size=(batch, 3, h, w)).astype(np.float32))
    got = rpb.backward_planes(planes, homs, g, False, fwd_plan, adj_plan)
    _, vjp = jax.vjp(rp._reference_render_batch, planes, homs)
    want, _ = vjp(g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=atol)

  def test_small_rotation(self, rng):
    self._check(rng, ROTATION)

  @pytest.mark.xfail(
      strict=False,
      reason="pre-existing (seed b1e451b): 24/131072 adjoint elements "
             "miss atol=1e-3 by up to ~0.16 for the yaw+pan pose — the "
             "general adjoint's window seams drop/double a tap's "
             "contribution exactly where the forward property sweeps "
             "disagree with the oracle; tracked as one kernel defect")
  def test_yaw_pan(self, rng):
    self._check(rng, dict(ry=0.004, tx=0.03))

  def test_batched(self, rng):
    self._check(rng, ROTATION, batch=2)

  def test_plan_sane(self):
    h, w = 32, 256
    plan = rpb.plan_adjoint_shr(_homs(h, w, **ROTATION), h, w)
    assert plan is not None
    n_tx, n_ty, n_windows, slc, bandg = plan
    assert 2 <= n_tx <= 5 and 2 <= n_ty <= 5 and n_windows in (2, 3)
    assert (slc, bandg) in rp._shared_levels(h)

  def test_property_random_rotation_poses(self, rng):
    """Accepted general poses' Pallas backward matches the XLA VJP."""
    h, w, p = 32, 256, 3
    checked = 0
    for _ in range(12):
      pose_kw = dict(
          tx=float(rng.uniform(-0.1, 0.1)),
          tz=float(rng.uniform(-0.2, 0.2)),
          rx=float(rng.uniform(-0.008, 0.008)),
          ry=float(rng.uniform(-0.008, 0.008)))
      homs = _homs(h, w, p, **pose_kw)
      if rp.is_separable(homs):
        continue
      if rp._plan_shared(homs, h, w) is None:
        continue
      if rpb.plan_adjoint_shr(homs, h, w) is None:
        continue
      self._check(rng, pose_kw, p=p, h=h, w=w)
      checked += 1
    assert checked >= 6

  def test_grad_through_public_api_rotation(self, rng):
    p, h, w = 4, 32, 256
    planes = _mpi(rng, p, h, w)
    homs = _homs(h, w, p, **ROTATION)
    wmat = jnp.asarray(rng.normal(size=(3, h, w)).astype(np.float32))
    got = jax.grad(lambda pl_: jnp.sum(
        rp.render_mpi_fused(pl_, homs, separable=False) * wmat))(planes)
    want = jax.grad(lambda pl_: jnp.sum(
        rp.reference_render(pl_, homs) * wmat))(planes)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)


class TestFusedVjpIntegration:

  def test_grad_through_render_mpi_fused_matches_reference(self, rng):
    p, h, w = 4, 32, 256
    planes = _mpi(rng, p, h, w)
    homs = _homs(h, w, p, **TRANSLATION)
    wmat = jnp.asarray(rng.normal(size=(3, h, w)).astype(np.float32))

    def loss_fused(pl_):
      return jnp.sum(rp.render_mpi_fused(pl_, homs, separable=True) * wmat)

    def loss_ref(pl_):
      return jnp.sum(rp.reference_render(pl_, homs) * wmat)

    got = jax.grad(loss_fused)(planes)
    want = jax.grad(loss_ref)(planes)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)

  def test_pallas_backward_actually_engaged(self, rng, monkeypatch):
    """The separable in-envelope grad path runs the Pallas backward."""
    p, h, w = 3, 32, 256
    planes = _mpi(rng, p, h, w)
    homs = _homs(h, w, p, **TRANSLATION)
    calls = []
    real = rpb.backward_planes

    def spy(*args, **kwargs):
      calls.append(1)
      return real(*args, **kwargs)

    monkeypatch.setattr(rpb, "backward_planes", spy)
    rp._make_fused.cache_clear()
    try:
      jax.grad(lambda pl_: jnp.sum(
          rp.render_mpi_fused(pl_, homs, separable=True)))(planes)
    finally:
      rp._make_fused.cache_clear()
    assert calls

  def test_hom_grads_still_match_reference(self, rng):
    p, h, w = 3, 32, 256
    planes = _mpi(rng, p, h, w)
    homs = _homs(h, w, p, **TRANSLATION)
    wmat = jnp.asarray(rng.normal(size=(3, h, w)).astype(np.float32))

    got = jax.grad(lambda hh: jnp.sum(
        rp.render_mpi_fused(planes, hh, separable=True, check=False)
        * wmat))(homs)
    want = jax.grad(lambda hh: jnp.sum(
        rp.reference_render(planes, hh) * wmat))(homs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-3, atol=1e-3)

  def test_jit_constant_pose_grad_uses_pallas_backward(self, rng,
                                                       monkeypatch):
    """Poses that are jit CONSTANTS (closed over, concrete at trace time)
    still get the Pallas backward: the adjoint is planned eagerly from the
    captured host copy, not lazily from (traced) residuals."""
    p, h, w = 3, 32, 256
    planes = _mpi(rng, p, h, w)
    homs = _homs(h, w, p, **ROTATION)
    calls = []
    real = rpb.backward_planes

    def spy(*args, **kwargs):
      calls.append(kwargs.get("adj_plan") or args[5])
      return real(*args, **kwargs)

    monkeypatch.setattr(rpb, "backward_planes", spy)
    rp._make_shared.cache_clear()
    try:
      got = jax.jit(jax.grad(lambda pl_: jnp.sum(
          rp.render_mpi_fused(pl_, homs, separable=False) ** 2)))(planes)
    finally:
      rp._make_shared.cache_clear()
    assert calls, "jit-constant-pose gradient fell back to the XLA VJP"
    want = jax.grad(lambda pl_: jnp.sum(
        rp.reference_render(pl_, homs) ** 2))(planes)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)


class TestPlanFormatCompat:
  """Pin the plan-format contract between render_pallas and
  render_pallas_bwd: whatever ``_plan_shared`` returns must feed
  ``warp_planes_fused``/``backward_planes`` verbatim (the round-4 banded
  commit widened the tuple and crashed this path)."""

  def test_plan_shared_tuple_feeds_backward_verbatim(self, rng):
    p, h, w = 3, 32, 256
    homs = _homs(h, w, p, **ROTATION)
    plan = rp._plan_shared(homs, h, w)
    assert plan is not None and len(plan) == 4
    planes = _mpi(rng, p, h, w, batch=1)
    warped = rpb.warp_planes_fused(planes, homs[None], False, plan)
    assert warped.shape == (1, p, 4, h, w)

  def test_legacy_two_tuple_still_accepted(self, rng):
    p, h, w = 3, 32, 256
    homs = _homs(h, w, p, **ROTATION)
    plan = rp._plan_shared(homs, h, w)
    assert plan is not None
    planes = _mpi(rng, p, h, w, batch=1)
    got4 = rpb.warp_planes_fused(planes, homs[None], False, plan)
    got2 = rpb.warp_planes_fused(planes, homs[None], False, plan[:2])
    # At the base ladder level the two spellings run identical geometry.
    if (plan[2], plan[3]) == (rp.G_SHARED, rp.G_BAND):
      np.testing.assert_allclose(np.asarray(got4), np.asarray(got2),
                                 atol=1e-6)

  @pytest.mark.parametrize("deg,level", [(3.0, (32, 48)), (6.0, (40, 64))])
  def test_wide_slice_plan_runs_planned_geometry(self, rng, deg, level):
    """A pose whose plan sits ABOVE the base slice level re-warps through
    the planned wide-slice geometry and matches the XLA warp (this is the
    pose class render_pallas.py used to silently demote to the XLA
    backward). Roll drives v-drift across a tile, escalating the ladder."""
    p, h, w = 3, 64, 384
    homs = _roll_homs(h, w, p, deg)
    plan = rp._plan_shared(homs, h, w)
    assert plan is not None, "probe pose fell out of the shared envelope"
    assert (plan[2], plan[3]) == level, (
        f"roll {deg} deg planned {plan}; expected ladder level {level}")
    planes = _mpi(rng, p, h, w, batch=1)
    got = rpb.warp_planes_fused(planes, homs[None], False, plan)[0]
    want = _reference_warp(planes[0], homs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)

  def test_wide_slice_backward_planes_matches_xla_vjp(self, rng):
    """backward_planes with a wide-slice forward plan + independently
    planned adjoint matches the XLA VJP — the restored Pallas backward
    for above-base poses."""
    p, h, w = 3, 64, 384
    homs = _roll_homs(h, w, p, 3.0)
    fwd_plan = rp._plan_shared(homs, h, w)
    assert fwd_plan is not None and (fwd_plan[2], fwd_plan[3]) != (
        rp.G_SHARED, rp.G_BAND)
    adj_plan = rpb.plan_adjoint_shr(homs, h, w)
    if adj_plan is None:
      pytest.skip("adjoint planner rejected the roll pose")
    planes = _mpi(rng, p, h, w, batch=1)
    g = jnp.asarray(rng.normal(size=(1, 3, h, w)).astype(np.float32))
    got = rpb.backward_planes(planes, homs[None], g, False, fwd_plan,
                              adj_plan)
    _, vjp = jax.vjp(rp._reference_render_batch, planes, homs[None])
    want, _ = vjp(g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)
