"""Observability hooks: checkify NaN guards, named scopes, profiler trace
(SURVEY.md §5.1-5.2 — absent upstream, supplied idiomatically)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_vision_tpu import debug
from mpi_vision_tpu.core import render
from mpi_vision_tpu.core.camera import inv_depths


def _args(rng, b=1, hw=24, p=3, poison=False):
  mpi = rng.uniform(0, 1, (b, hw, hw, p, 4)).astype(np.float32)
  if poison:
    mpi[0, hw // 2, hw // 2, 1, 0] = np.nan
  pose = np.eye(4, dtype=np.float32)
  pose[0, 3] = 0.05
  k = np.array([[hw / 2, 0, hw / 2], [0, hw / 2, hw / 2], [0, 0, 1]],
               np.float32)
  return (jnp.asarray(mpi), jnp.asarray(pose)[None],
          inv_depths(1.0, 100.0, p), jnp.asarray(k)[None])


class TestCheckify:

  def test_clean_input_passes_and_matches(self, rng):
    args = _args(rng)
    checked = debug.checked(render.render_mpi)
    np.testing.assert_allclose(
        np.asarray(checked(*args)), np.asarray(render.render_mpi(*args)),
        atol=1e-6)

  def test_nan_injection_raises(self, rng):
    args = _args(rng, poison=True)
    checked = debug.checked(render.render_mpi)
    with pytest.raises(Exception, match="nan"):
      checked(*args)

  def test_nan_in_loss_raises(self, rng):
    from mpi_vision_tpu.train import loss as tloss

    mpi_pred = jnp.asarray(
        rng.uniform(-1, 1, (1, 24, 24, 9)).astype(np.float32))
    batch = {
        "ref_img": jnp.full((1, 24, 24, 3), jnp.nan),   # poisoned input
        "tgt_img": jnp.zeros((1, 24, 24, 3)),
        "tgt_img_cfw": jnp.eye(4)[None],
        "ref_img_wfc": jnp.eye(4)[None],
        "intrinsics": jnp.asarray(
            np.array([[[12., 0, 12], [0, 12., 12], [0, 0, 1]]], np.float32)),
        "mpi_planes": inv_depths(1.0, 100.0, 3),
    }
    checked = debug.checked(tloss.l2_render_loss)
    with pytest.raises(Exception, match="nan"):
      checked(mpi_pred, batch)


class TestScopesAndTrace:

  def test_named_scopes_in_lowered_hlo(self, rng):
    args = _args(rng)
    txt = debug.lowered_text(jax.jit(render.render_mpi).lower(*args))
    assert "render/homographies" in txt
    assert "render/warp_composite_scan" in txt

  def test_profiler_trace_writes(self, rng, tmp_path):
    logdir = str(tmp_path / "trace")
    with debug.trace(logdir):
      out = jax.jit(jnp.sin)(jnp.arange(8.0))
      jax.block_until_ready(out)
    found = [os.path.join(r, f) for r, _, fs in os.walk(logdir) for f in fs]
    assert found, "profiler trace produced no files"
