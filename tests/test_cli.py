"""CLI tests: the notebook workflow as commands (train / export-viewer)."""

import json
import os

import numpy as np
import pytest

from mpi_vision_tpu import cli


def test_train_synthetic_l2(tmp_path, capsys):
  rc = cli.main([
      "train", "--synthetic", "--synthetic-scenes", "3",
      "--img-size", "32", "--num-planes", "4", "--epochs", "2",
      "--no-vgg-loss", "--ckpt", str(tmp_path / "ckpt"),
      "--export-html", str(tmp_path / "v.html"),
  ])
  assert rc == 0
  out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
  assert out["command"] == "train" and out["steps"] == 6
  assert np.isfinite(out["final_loss"])
  assert os.path.isdir(tmp_path / "ckpt")
  html = open(tmp_path / "v.html").read()
  assert html.count("data:image/png;base64,") == 4


def test_train_synthetic_vgg_loss(capsys):
  rc = cli.main([
      "train", "--synthetic", "--synthetic-scenes", "2",
      "--img-size", "32", "--num-planes", "4", "--epochs", "1",
      "--vgg-resize", "0",
  ])
  assert rc == 0
  out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
  assert out["steps"] == 2 and np.isfinite(out["final_loss"])


def test_train_lr_find(capsys):
  rc = cli.main([
      "train", "--synthetic", "--synthetic-scenes", "2",
      "--img-size", "32", "--num-planes", "4", "--epochs", "1",
      "--no-vgg-loss", "--lr-find", "--lr-find-steps", "12",
  ])
  assert rc == 0
  captured = capsys.readouterr()
  out = json.loads(captured.out.strip().splitlines()[-1])
  assert out["steps"] == 2 and np.isfinite(out["final_loss"])
  assert 0 < out["lr_found"] <= 10.0
  assert "lr_find: suggestion" in captured.err


def test_export_viewer_fixture(tmp_path, capsys):
  fixtures = os.path.join(os.path.dirname(__file__), "fixtures", "scene_009")
  rc = cli.main([
      "export-viewer", "--mpi-dir", fixtures,
      "--out", str(tmp_path / "scene.html"),
  ])
  assert rc == 0
  out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
  assert out["layers"] == 10 and out["size"] == [400, 640]
  assert os.path.exists(tmp_path / "scene.html")


def test_unknown_command_exits():
  with pytest.raises(SystemExit):
    cli.main(["frobnicate"])


def test_train_ckpt_overwrite(tmp_path, capsys):
  """Re-running with the same --ckpt path must not crash (resume='never'
  clears the previous run's published checkpoints via store.clear())."""
  argv = ["train", "--synthetic", "--synthetic-scenes", "2",
          "--img-size", "32", "--num-planes", "4", "--epochs", "1",
          "--no-vgg-loss", "--ckpt", str(tmp_path / "ckpt")]
  assert cli.main(argv) == 0
  assert cli.main(argv) == 0
  capsys.readouterr()


@pytest.mark.parametrize("argv", [
    ["train", "--synthetic", "--resume"],
    ["train", "--synthetic", "--save-every", "5", "--keep", "2"],
    ["train", "--synthetic", "--no-nan-guard"],
    ["train", "--synthetic", "--metrics-port", "0"],
    ["train", "--synthetic", "--metrics-log", "/tmp/m.jsonl"],
    ["train", "--synthetic", "--event-log", "/tmp/e.jsonl"],
    ["train", "--synthetic", "--inject-fault", "crash@step=1"],
    ["serve", "--ckpt-scenes", "3"],
    ["serve", "--ckpt-dataset", "/data/re10k"],
    ["serve", "--reload-ckpt-s", "5"],
])
def test_ckpt_flags_without_ckpt_are_rejected(argv):
  """Dangling checkpoint flags must fail loudly, not silently take the
  non-checkpoint path (train: no crash safety; serve: synthetic scenes
  instead of the trained MPIs)."""
  with pytest.raises(SystemExit, match=r"require\(s\) --ckpt"):
    cli.main(argv)


def test_profile_hook_without_profile_dir_rejected():
  """A hook with no captures to hand it is a silently-dead knob."""
  with pytest.raises(SystemExit, match="--profile-hook requires"):
    cli.main(["serve", "--profile-hook", "echo", "--duration", "0.1"])


def test_metrics_port_file_without_metrics_port_rejected(tmp_path):
  """The port file is only written by the metrics listener; dangling it
  would hang a supervisor waiting on the file."""
  with pytest.raises(SystemExit, match="--metrics-port-file requires"):
    cli.main(["train", "--synthetic", "--ckpt", str(tmp_path),
              "--metrics-port-file", str(tmp_path / "p")])


@pytest.mark.parametrize("argv", [
    ["cluster"],                                     # neither
    ["cluster", "--backends", "2", "--join", "h:1"],  # both
])
def test_cluster_needs_exactly_one_backend_source(argv):
  with pytest.raises(SystemExit, match="exactly one of"):
    cli.main(argv)


def test_cluster_join_empty_address_list_rejected():
  with pytest.raises(SystemExit, match="parsed no addresses"):
    cli.main(["cluster", "--join", " , ,"])


def test_alert_hook_without_slo_rejected():
  """Alert edges only exist with SLO tracking; a dangling hook would
  silently never page."""
  with pytest.raises(SystemExit, match="--alert-hook requires"):
    cli.main(["serve", "--no-slo", "--alert-hook", "echo",
              "--duration", "0.1"])


def test_slo_quantile_without_slo_rejected():
  """The quantile objective only acts through the SLO tracker."""
  with pytest.raises(SystemExit, match=r"require\(s\) SLO tracking"):
    cli.main(["serve", "--no-slo", "--slo-quantile", "0.99",
              "--duration", "0.1"])


def test_slo_per_scene_without_quantile_rejected():
  """The per-scene objective IS the quantile one; dangling it would
  silently judge nothing."""
  with pytest.raises(SystemExit, match="--slo-per-scene requires"):
    cli.main(["serve", "--slo-per-scene", "--duration", "0.1"])


def test_tsdb_knobs_without_interval_rejected():
  """Ring knobs only act with sampling on."""
  with pytest.raises(SystemExit, match=r"require\(s\) --tsdb-interval-s"):
    cli.main(["serve", "--tsdb-points", "64", "--duration", "0.1"])
  with pytest.raises(SystemExit, match="--tsdb-points requires"):
    cli.main(["cluster", "--backends", "1", "--tsdb-points", "64"])


def test_ship_knobs_without_url_rejected():
  """Shipper knobs only act with a sink configured."""
  with pytest.raises(SystemExit, match=r"require\(s\) --ship-url"):
    cli.main(["serve", "--ship-spool-dir", "/tmp/spool",
              "--duration", "0.1"])
  with pytest.raises(SystemExit, match=r"require\(s\) --ship-url"):
    cli.main(["serve", "--ship-interval-s", "5", "--duration", "0.1"])


def test_attrib_knobs_without_attrib_rejected():
  """The scene cap only shapes a ledger that exists."""
  with pytest.raises(SystemExit, match="--attrib-scenes requires --attrib"):
    cli.main(["serve", "--attrib-scenes", "16", "--duration", "0.1"])
  with pytest.raises(SystemExit, match="--attrib-scenes must be >= 1"):
    cli.main(["serve", "--attrib", "--attrib-scenes", "0",
              "--duration", "0.1"])


def test_incident_knobs_without_dir_rejected():
  """Recorder knobs only act with a bundle directory; dangling they'd
  silently record nothing."""
  for flag, value in (("--incident-keep", "4"),
                      ("--incident-window-s", "60"),
                      ("--incident-top-cells", "4"),
                      ("--incident-profile", "0.5")):
    with pytest.raises(SystemExit,
                       match=r"require\(s\) --incident-dir"):
      cli.main(["serve", flag, value, "--duration", "0.1"])


def test_incident_dir_without_slo_rejected(tmp_path):
  """Captures trigger off SLO alert edges; without the tracker the
  black box would never write a bundle."""
  with pytest.raises(SystemExit, match="--incident-dir requires SLO"):
    cli.main(["serve", "--no-slo", "--incident-dir", str(tmp_path),
              "--duration", "0.1"])


def test_incident_profile_without_profile_dir_rejected(tmp_path):
  """The in-bundle profiler capture reuses the serve profiler; it needs
  somewhere to write traces."""
  with pytest.raises(SystemExit,
                     match="--incident-profile requires --profile-dir"):
    cli.main(["serve", "--incident-dir", str(tmp_path),
              "--incident-profile", "0.5", "--duration", "0.1"])


@pytest.mark.parametrize("flag,value", [
    ("--session-max", "4"),
    ("--session-idle-s", "10"),
    ("--session-fuse", "2"),
    ("--session-prefetch", "3"),
])
def test_session_knobs_without_session_rejected(flag, value):
  """Session knobs only shape a tier that exists; dangling they'd
  silently leave POST /session a 503."""
  with pytest.raises(SystemExit, match=r"require\(s\) --session"):
    cli.main(["serve", flag, value, "--duration", "0.1"])


def test_bad_session_config_rejected():
  """SessionConfig validation surfaces as a CLI error, not a traceback."""
  with pytest.raises(SystemExit, match="bad session config"):
    cli.main(["serve", "--session", "--session-max", "0",
              "--duration", "0.1"])


def test_cluster_rolling_restart_requires_a_local_pool():
  """--join fronts backends some OTHER supervisor owns; a rolling
  restart needs process control. (--supervise on --join is legal now:
  it degrades to remote health watching + an optional restart hook.)"""
  with pytest.raises(SystemExit, match="require --backends"):
    cli.main(["cluster", "--join", "h:1", "--rolling-restart"])


@pytest.mark.parametrize("argv,msg", [
    # The restart hook only fires from the supervisor's restart path,
    # and only for fleets this process cannot respawn itself.
    (["cluster", "--join", "h:1", "--restart-hook", "echo"],
     "--restart-hook requires --supervise"),
    (["cluster", "--backends", "1", "--supervise",
      "--restart-hook", "echo"], "--restart-hook requires --join"),
    (["cluster", "--join", "h:1", "--supervise",
      "--restart-hook-timeout-s", "5"],
     "--restart-hook-timeout-s requires --restart-hook"),
    (["cluster", "--join", "h:1", "--supervise", "--restart-hook",
      "echo", "--restart-hook-timeout-s", "0"],
     "--restart-hook-timeout-s must be"),
    # Lease knobs elect a supervisor; dangling they'd guard nothing.
    (["cluster", "--join", "h:1", "--lease-dir", "/tmp/l"],
     "--lease-dir requires --supervise"),
    (["cluster", "--join", "h:1", "--lease-ttl-s", "5"],
     "--lease-ttl-s requires --supervise"),
    (["cluster", "--backends", "1", "--supervise",
      "--lease-ttl-s", "0"], "--lease-ttl-s must be"),
    # Gossip knobs only act with peers to gossip with.
    (["cluster", "--backends", "1", "--peers", " , "],
     "--peers parsed no addresses"),
    (["cluster", "--backends", "1", "--gossip-interval-s", "1"],
     "--gossip-interval-s requires --peers"),
    (["cluster", "--backends", "1", "--peers", "h:2",
      "--gossip-interval-s", "0"], "--gossip-interval-s must be"),
    (["cluster", "--backends", "1", "--node-id", "r0"],
     "--node-id requires --peers or --supervise"),
])
def test_cluster_router_ha_knobs_guarded(argv, msg):
  """Router-HA knobs (gossip, lease, remote restart hook) are validated
  at the door — the monitor loop swallows tick exceptions by design, so
  a lazily-raised ValueError would leave supervision silently dead."""
  with pytest.raises(SystemExit, match=msg):
    cli.main(argv)


@pytest.mark.parametrize("argv,msg", [
    # Every autoscale knob only acts through the armed autoscaler;
    # dangling any of them would silently leave elasticity off.
    (["cluster", "--backends", "1", "--supervise",
      "--autoscale-min", "1"], r"require\(s\) --autoscale"),
    (["cluster", "--backends", "1", "--supervise",
      "--autoscale-max", "4"], r"require\(s\) --autoscale"),
    (["cluster", "--backends", "1", "--supervise",
      "--autoscale-up-sustain-s", "2"], r"require\(s\) --autoscale"),
    (["cluster", "--backends", "1", "--supervise",
      "--autoscale-down-sustain-s", "20"], r"require\(s\) --autoscale"),
    (["cluster", "--backends", "1", "--supervise",
      "--autoscale-up-cooldown-s", "10"], r"require\(s\) --autoscale"),
    (["cluster", "--backends", "1", "--supervise",
      "--autoscale-down-cooldown-s", "30"], r"require\(s\) --autoscale"),
    (["cluster", "--backends", "1", "--supervise",
      "--autoscale-queue-high", "8"], r"require\(s\) --autoscale"),
    (["cluster", "--backends", "1", "--supervise",
      "--autoscale-burn-high", "2"], r"require\(s\) --autoscale"),
    (["cluster", "--backends", "1", "--supervise",
      "--autoscale-util-low", "0.1"], r"require\(s\) --autoscale"),
    (["cluster", "--backends", "1", "--supervise",
      "--autoscale-budget", "4"], r"require\(s\) --autoscale"),
    (["cluster", "--backends", "1", "--supervise",
      "--autoscale-budget-window-s", "300"], r"require\(s\) --autoscale"),
    (["cluster", "--backends", "1", "--supervise",
      "--autoscale-drain-s", "0.5"], r"require\(s\) --autoscale"),
    (["cluster", "--backends", "1", "--supervise",
      "--autoscale-interval-s", "1"], r"require\(s\) --autoscale"),
    # Scaling is the leaseholder's act alone.
    (["cluster", "--backends", "1", "--autoscale"],
     "--autoscale requires --supervise"),
    # The hook is the --join fleet's spawn path, nothing else's.
    (["cluster", "--backends", "1", "--supervise", "--autoscale",
      "--provision-hook", "echo"], "--provision-hook requires --join"),
    (["cluster", "--join", "h:1", "--supervise",
      "--provision-hook", "echo"],
     "--provision-hook requires --autoscale"),
    # A --join autoscaler without a hook cannot create capacity.
    (["cluster", "--join", "h:1", "--supervise", "--autoscale"],
     "--autoscale with --join requires --provision-hook"),
    # Value floors are validated at the door, not in the tick loop.
    (["cluster", "--backends", "1", "--supervise", "--autoscale",
      "--autoscale-interval-s", "0"], "--autoscale-interval-s must be"),
    (["cluster", "--backends", "1", "--supervise", "--autoscale",
      "--autoscale-drain-s", "-1"], "--autoscale-drain-s must be"),
    # AutoscaleConfig's own validation surfaces as a door-time exit.
    (["cluster", "--backends", "1", "--supervise", "--autoscale",
      "--autoscale-min", "3", "--autoscale-max", "2"],
     "bad autoscale config"),
])
def test_cluster_autoscale_knobs_guarded(argv, msg):
  """Elastic-fleet knobs are validated at the door — the supervisor
  tick swallows autoscaler exceptions by design (a scaling bug must
  not kill supervision), so a lazily-raised ValueError would leave
  autoscaling silently dead."""
  with pytest.raises(SystemExit, match=msg):
    cli.main(argv)


def test_serve_edge_negative_ttl_guarded():
  """Negative caching only acts through the edge cache; dangling the
  TTL would silently drop the shed behaviour the operator asked for."""
  with pytest.raises(SystemExit, match=r"require\(s\) --edge-cache"):
    cli.main(["serve", "--edge-negative-ttl-s", "30", "--duration", "0.1"])


@pytest.mark.parametrize("flag,value", [
    ("--brownout-burn-high", "3.0"),
    ("--brownout-queue-high", "0.7"),
    ("--brownout-recover-burn", "0.5"),
    ("--brownout-recover-queue", "0.1"),
    ("--brownout-step-dwell-s", "1.0"),
    ("--brownout-recover-dwell-s", "10.0"),
    ("--brownout-plane-keep", "0.25"),
    ("--brownout-warp-scale", "2.0"),
    ("--brownout-max-level", "3"),
])
def test_serve_brownout_knobs_guarded(flag, value):
  """Every ladder knob only acts through the controller; dangling any
  of them would silently leave the operator's degradation policy off."""
  with pytest.raises(SystemExit, match=r"require\(s\) --brownout"):
    cli.main(["serve", flag, value, "--duration", "0.1"])


def test_serve_brownout_requires_slo_and_validates_at_the_door():
  """The ladder is DRIVEN by the SLO burn rate — armed without the
  tracker it would never descend; and a closed hysteresis band must
  fail at startup, not flap in production."""
  with pytest.raises(SystemExit, match="--brownout requires SLO"):
    cli.main(["serve", "--brownout", "--no-slo", "--duration", "0.1"])
  with pytest.raises(SystemExit, match="bad brownout config"):
    cli.main(["serve", "--brownout", "--brownout-recover-burn", "2.0",
              "--brownout-burn-high", "2.0", "--duration", "0.1"])
  with pytest.raises(SystemExit, match="bad brownout config"):
    cli.main(["serve", "--brownout", "--brownout-plane-keep", "0",
              "--duration", "0.1"])
  with pytest.raises(SystemExit, match="bad brownout config"):
    cli.main(["serve", "--brownout", "--brownout-max-level", "5",
              "--duration", "0.1"])


def test_cluster_bad_supervision_knobs_rejected():
  """Invalid supervision knobs must fail at the door: the monitor loop
  swallows tick exceptions by design, so a lazily-raised ValueError
  would leave supervision silently dead."""
  with pytest.raises(SystemExit, match="--restart-budget must be"):
    cli.main(["cluster", "--backends", "1", "--supervise",
              "--restart-budget", "0"])
  with pytest.raises(SystemExit, match="--probe-s must be"):
    cli.main(["cluster", "--backends", "1", "--supervise",
              "--probe-s", "0"])
  with pytest.raises(SystemExit, match="--wedge-after must be"):
    cli.main(["cluster", "--backends", "1", "--supervise",
              "--wedge-after", "0"])


def test_bad_fault_spec_rejected_at_the_door(tmp_path):
  """A typo'd --inject-fault must fail the invocation, not silently arm
  nothing (the chaos drill would then 'pass' by testing nothing)."""
  with pytest.raises(SystemExit, match="fault spec"):
    cli.main(["train", "--synthetic", "--ckpt", str(tmp_path / "c"),
              "--inject-fault", "boom@step=1"])


def test_tsdb_compaction_knobs_guarded():
  """Compaction knobs only act through the ring (and the stride only
  past the age threshold)."""
  with pytest.raises(SystemExit, match=r"require\(s\) --tsdb-interval-s"):
    cli.main(["serve", "--tsdb-compact-after-s", "60", "--duration", "0.1"])
  with pytest.raises(SystemExit,
                     match="--tsdb-compact-stride requires"):
    cli.main(["serve", "--tsdb-interval-s", "1", "--tsdb-compact-stride",
              "4", "--duration", "0.1"])


@pytest.mark.parametrize("argv,msg", [
    (["train-queue", "--root", "/tmp/q", "--concurrency", "0"],
     "--concurrency must be"),
    (["train-queue", "--root", "/tmp/q", "--probe-s", "0"],
     "--probe-s must be"),
    (["train-queue", "--root", "/tmp/q", "--probe-timeout-s", "0"],
     "--probe-timeout-s must be"),
    (["train-queue", "--root", "/tmp/q", "--wedge-after", "0"],
     "--wedge-after must be"),
    (["train-queue", "--root", "/tmp/q", "--restart-budget", "0"],
     "--restart-budget must be"),
    (["train-queue", "--root", "/tmp/q", "--budget-window-s", "0"],
     "--budget-window-s must be"),
    (["train-queue", "--root", "/tmp/q", "--lease-s", "0"],
     "--lease-s must be"),
    (["train-queue", "--root", "/tmp/q", "--startup-grace-s", "-1"],
     "--startup-grace-s must be"),
    (["train-queue", "--root", "/tmp/q", "--publish-keep", "0"],
     "--publish-keep must be"),
    (["train-queue", "--root", "/tmp/q", "--no-slo",
      "--slo-step-latency-ms", "500"], r"require\(s\) SLO tracking"),
    (["train-queue", "--root", "/tmp/q", "--no-slo",
      "--slo-availability", "0.9"], r"require\(s\) SLO tracking"),
    (["train-queue", "--root", "/tmp/q", "--submit", "not json"],
     "--submit is not valid JSON"),
    (["train-queue", "--root", "/tmp/q", "--submit", "[1, 2]"],
     "--submit must be a JSON object"),
])
def test_train_queue_bad_knobs_rejected(argv, msg):
  """Queue knobs are validated at the door: the supervisor's monitor
  loop swallows tick exceptions by design, so a lazily-raised
  ValueError would leave supervision silently dead (the cluster rule)."""
  with pytest.raises(SystemExit, match=msg):
    cli.main(argv)


def test_serve_tile_knobs_guarded():
  """Tile knobs only act through the tiled registry (serve/tiles.py);
  silently serving monolithic scenes would drop the frustum culling
  the operator asked for."""
  with pytest.raises(SystemExit, match=r"require\(s\) --tiled"):
    cli.main(["serve", "--tile-size", "64", "--duration", "0.1"])
  with pytest.raises(SystemExit, match=r"require\(s\) --tiled"):
    cli.main(["serve", "--tile-size", "auto", "--duration", "0.1"])
  with pytest.raises(SystemExit, match="--tile-size must be >= 8"):
    cli.main(["serve", "--tiled", "--tile-size", "4", "--duration", "0.1"])
  with pytest.raises(SystemExit,
                     match="--tile-size must be an integer or 'auto'"):
    cli.main(["serve", "--tiled", "--tile-size", "big",
              "--duration", "0.1"])


def test_serve_asset_knobs_guarded():
  """Asset knobs only act through the tiled registry's digest index
  (serve/assets); dangling any of them would silently serve no
  manifests, cache nothing, or never sync."""
  with pytest.raises(SystemExit, match=r"require\(s\) --tiled"):
    cli.main(["serve", "--asset-cache-mb", "64", "--duration", "0.1"])
  with pytest.raises(SystemExit, match=r"require\(s\) --tiled"):
    cli.main(["serve", "--asset-sync-from", "http://primary:8080",
              "--duration", "0.1"])
  with pytest.raises(SystemExit, match="--asset-cache-mb must be >= 1"):
    cli.main(["serve", "--tiled", "--asset-cache-mb", "0",
              "--duration", "0.1"])
  with pytest.raises(SystemExit,
                     match="--asset-sync-interval-s requires "
                           "--asset-sync-from"):
    cli.main(["serve", "--tiled", "--asset-sync-interval-s", "2",
              "--duration", "0.1"])
  with pytest.raises(SystemExit,
                     match="--asset-sync-interval-s must be > 0"):
    cli.main(["serve", "--tiled", "--asset-sync-from", "http://p:8080",
              "--asset-sync-interval-s", "0", "--duration", "0.1"])


def test_cluster_route_cell_knobs_guarded():
  """The rotation bucket only acts through cell routing; dangling it
  would silently keep scene-level placement."""
  with pytest.raises(SystemExit, match="--route-rot-bucket-deg requires"):
    cli.main(["cluster", "--backends", "1", "--route-rot-bucket-deg", "5"])
  with pytest.raises(SystemExit, match="--route-cell must be"):
    cli.main(["cluster", "--backends", "1", "--route-cell", "-1"])
  with pytest.raises(SystemExit, match="--route-rot-bucket-deg must be"):
    cli.main(["cluster", "--backends", "1", "--route-cell", "0.1",
              "--route-rot-bucket-deg", "0"])


def test_train_queue_metrics_port_knobs_guarded(tmp_path):
  """Same contract as train's: the port file is only written by the
  listener, so dangling it would hang whatever waits on the file."""
  with pytest.raises(SystemExit, match="--metrics-port-file requires"):
    cli.main(["train-queue", "--root", str(tmp_path / "q"),
              "--metrics-port-file", str(tmp_path / "p")])
  with pytest.raises(SystemExit, match="--metrics-port must be"):
    cli.main(["train-queue", "--root", str(tmp_path / "q"),
              "--metrics-port", "-1"])


def test_ship_sink_knobs_guarded(tmp_path):
  with pytest.raises(SystemExit):  # --dir is required
    cli.main(["ship-sink"])
  with pytest.raises(SystemExit, match="--port must be"):
    cli.main(["ship-sink", "--dir", str(tmp_path / "b"), "--port", "-1"])


def test_train_queue_bad_job_id_rejected(tmp_path):
  """Bad or duplicate job ids fail the same validate-at-the-door way as
  every other knob — a clean SystemExit, not a traceback."""
  root = str(tmp_path / "q")
  with pytest.raises(SystemExit, match="--submit rejected"):
    cli.main(["train-queue", "--root", root, "--submit", '{"id": 5}'])
  with pytest.raises(SystemExit, match="--submit rejected"):
    cli.main(["train-queue", "--root", root,
              "--submit", '{"id": "has space"}'])


def test_negative_save_every_rejected(tmp_path):
  with pytest.raises(SystemExit, match="--save-every must be >= 0"):
    cli.main(["train", "--synthetic", "--save-every", "-3",
              "--ckpt", str(tmp_path / "ckpt")])


def test_ckpt_scenes_below_one_rejected(tmp_path):
  with pytest.raises(SystemExit, match="--ckpt-scenes must be >= 1"):
    cli.main(["serve", "--ckpt", str(tmp_path), "--ckpt-scenes", "0"])


def test_keep_below_one_rejected(tmp_path):
  with pytest.raises(SystemExit, match="--keep must be >= 1"):
    cli.main(["train", "--synthetic", "--keep", "0",
              "--ckpt", str(tmp_path / "ckpt")])


def test_train_zero_epochs_errors(capsys):
  with pytest.raises(SystemExit, match="no training steps"):
    cli.main(["train", "--synthetic", "--synthetic-scenes", "2",
              "--img-size", "32", "--num-planes", "4", "--epochs", "0",
              "--no-vgg-loss"])
  capsys.readouterr()


@pytest.mark.parametrize("bf16", [False, True])
def test_train_synthetic_planned_render(capsys, bf16):
  """--planned-render trains through the fused Pallas loss end to end, in
  both default f32 and --bf16 compute."""
  rc = cli.main([
      "train", "--synthetic", "--synthetic-scenes", "2",
      "--img-size", "32", "--num-planes", "4", "--epochs", "1",
      "--no-vgg-loss", "--planned-render",
  ] + (["--bf16"] if bf16 else []))
  assert rc == 0
  out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
  assert out["steps"] == 2 and np.isfinite(out["final_loss"])


def test_train_reports_valid_loss(capsys):
  """Per-epoch validation on the test split's fixed triplets: the summary
  carries first/final valid loss (the reference reports train AND valid
  loss each epoch — notebook cell 16's table)."""
  rc = cli.main([
      "train", "--synthetic", "--synthetic-scenes", "2",
      "--img-size", "32", "--num-planes", "4", "--epochs", "2",
      "--no-vgg-loss",
  ])
  assert rc == 0
  captured = capsys.readouterr()
  out = json.loads(captured.out.strip().splitlines()[-1])
  assert np.isfinite(out["first_valid_loss"])
  assert np.isfinite(out["final_valid_loss"])
  assert "valid loss" in captured.err


def test_train_no_valid_omits_fields(capsys):
  rc = cli.main([
      "train", "--synthetic", "--synthetic-scenes", "2",
      "--img-size", "32", "--num-planes", "4", "--epochs", "1",
      "--no-vgg-loss", "--no-valid",
  ])
  assert rc == 0
  out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
  assert "final_valid_loss" not in out
