"""Metric-name drift self-check: README's metric reference vs reality.

Every ``mpi_*`` family any of this repo's registries can expose — serve
backend (``obs.prom.serve_registry``), SLO engine
(``obs.slo.SloTracker.registry``), cluster router
(``Router._cluster_registry``), training telemetry
(``train.telemetry.TrainMetrics.registry``) — must appear as a
backticked full name in README.md, and vice versa: a backticked
``mpi_*`` token in the README that no registry exposes is a doc for a
metric that does not exist. Either direction failing means the metric
reference rotted silently — exactly what this tier-1 pin exists to
prevent.

Prefix mentions (backticked tokens ending in ``_``, e.g. ``mpi_serve_``)
and wildcard patterns (``mpi_slo_*`` — the ``*`` breaks the token match)
are deliberately NOT counted as family names.
"""

import pathlib
import re

from mpi_vision_tpu.obs import attrib as attrib_mod
from mpi_vision_tpu.obs import incident as incident_mod
from mpi_vision_tpu.obs import prom
from mpi_vision_tpu.obs import ship as ship_mod
from mpi_vision_tpu.obs import tsdb as tsdb_mod
from mpi_vision_tpu.obs.slo import SloConfig, SloTracker
from mpi_vision_tpu.serve.cluster.router import Router
from mpi_vision_tpu.serve.metrics import ServeMetrics
from mpi_vision_tpu.train.supervisor import queue_registry
from mpi_vision_tpu.train.telemetry import TrainMetrics

README = pathlib.Path(__file__).parent.parent / "README.md"

# A full family name is the ENTIRE backticked token under one of the
# exported prefixes (plain `mpi_*` would also catch API names like
# `mpi_from_net_output`); `mpi_serve_` (prefix mention) ends in '_' and
# is filtered below; `mpi_slo_*` (wildcard) never matches because '*'
# precedes the closing backtick.
_TOKEN = re.compile(r"`(mpi_(?:serve|slo|cluster|train|obs)_[a-z0-9_]+)`")


def _serve_families() -> set[str]:
  m = ServeMetrics()
  stats = m.snapshot(cache_stats={"hits": 0, "misses": 0, "evictions": 0,
                                  "bytes": 0, "scenes": 0})
  stats["breaker"] = {"state": "closed", "consecutive_failures": 0}
  reg = prom.serve_registry(stats, m.latency_histogram())
  return {metric.name for metric in reg._metrics}


def _slo_families() -> set[str]:
  # Quantile + per-scene objectives ON so their families count as
  # exposed (they are conditional, like the breaker families above).
  tracker = SloTracker(SloConfig(quantile=0.99, per_scene=True),
                       clock=lambda: 0.0)
  tracker.record(ok=True, latency_s=0.01, scene_id="s0")
  return {metric.name for metric in tracker.registry()._metrics}


def _cluster_families() -> set[str]:
  router = Router(clock=lambda: 0.0)
  return {metric.name for metric in router._cluster_registry()._metrics}


def _obs_families() -> set[str]:
  # The flight-recorder families are always exposed (zeros while off).
  return ({metric.name for metric in tsdb_mod.registry(None)._metrics}
          | {metric.name for metric in ship_mod.registry(None)._metrics}
          | {metric.name for metric in attrib_mod.registry(None)._metrics}
          | {metric.name for metric in incident_mod.registry(None)._metrics})


def _train_families() -> set[str]:
  tm = TrainMetrics(clock=lambda: 0.0)
  tm.record_step(1, loss=0.1, wall_s=0.01, examples=1, lr=1e-3)
  return {metric.name for metric in tm.registry()._metrics}


def _train_queue_families() -> set[str]:
  # The training-queue supervisor's families off a bare snapshot (the
  # registry is a pure function of it, like tsdb/ship above).
  return {metric.name for metric in queue_registry({})._metrics}


def _exposed_families() -> set[str]:
  return (_serve_families() | _slo_families() | _cluster_families()
          | _train_families() | _train_queue_families()
          | _obs_families())


def _documented_families() -> set[str]:
  text = README.read_text()
  return {tok for tok in _TOKEN.findall(text) if not tok.endswith("_")}


def test_every_exposed_family_is_documented():
  missing = _exposed_families() - _documented_families()
  assert not missing, (
      "families exposed by /metrics but absent from README's metric "
      f"reference: {sorted(missing)}")


def test_every_documented_family_is_exposed():
  phantom = _documented_families() - _exposed_families()
  assert not phantom, (
      "README documents metric families no registry exposes "
      f"(doc rot or a typo): {sorted(phantom)}")


def test_doc_scan_actually_finds_families():
  # The regex must really extract names (an empty set x empty set pass
  # would be meaningless) and really skip prefixes/wildcards.
  docs = _documented_families()
  assert "mpi_serve_requests_total" in docs
  assert "mpi_slo_burn_rate" in docs
  assert "mpi_train_steps_total" in docs
  assert "mpi_train_queue_quarantines_total" in docs
  assert "mpi_cluster_backend_up" in docs
  assert not any(t.endswith("_") for t in docs)
  assert len(_exposed_families()) > 40
