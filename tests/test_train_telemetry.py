"""Training telemetry tests: TrainMetrics accounting, mpi_train_*
exposition, the JSONL sink, fit_resumable threading, and the
``train --metrics-port`` smoke — a live HTTP scrape of a RUNNING
training loop (the acceptance pin: training is scrapeable exactly like
a serve backend)."""

import dataclasses
import json
import threading
import urllib.request

import numpy as np
import pytest

from mpi_vision_tpu.ckpt import CheckpointStore, NanGuard, PreemptionGuard
from mpi_vision_tpu.obs import parse_metrics_text
from mpi_vision_tpu.obs.events import EventLog
from mpi_vision_tpu.train import loop as tloop
from mpi_vision_tpu.train.telemetry import (
    TrainMetrics,
    file_metrics_sink,
    make_train_metrics_server,
)


class FakeClock:
  def __init__(self, t=100.0):
    self.t = t

  def __call__(self):
    return self.t

  def advance(self, dt):
    self.t += dt
    return self.t


# --- a minimal train-state stand-in (no model compile) --------------------


@dataclasses.dataclass(frozen=True)
class MiniState:
  params: dict
  opt_state: tuple
  step: int

  def replace(self, **kw):
    return dataclasses.replace(self, **kw)


def _mini_state():
  return MiniState(params={"w": np.zeros(3, np.float32)}, opt_state=(),
                   step=0)


def _mini_step(state, batch):
  batch = np.asarray(batch, np.float32)
  new = state.replace(
      step=state.step + 1,
      params={"w": state.params["w"] + batch.mean()})
  return new, {"loss": float(batch.mean())}


def _epoch(e):
  return [np.full((2, 3), 0.1 * (e + 1) + 0.01 * i, np.float32)
          for i in range(3)]


# --- TrainMetrics unit ----------------------------------------------------


def test_snapshot_and_registry_agree():
  clock = FakeClock()
  tm = TrainMetrics(clock=clock)
  for i in range(5):
    tm.record_step(i + 1, loss=0.5 - 0.01 * i, wall_s=0.02, examples=4,
                   lr=1e-3)
  tm.record_save(5, seconds=0.3, nbytes=1024, reason="epoch")
  tm.record_rollback(3)
  tm.record_preemption(5)
  tm.record_restore(3)
  tm.record_epoch(1)
  clock.advance(2.0)
  snap = tm.snapshot()
  assert snap["steps"] == 5 and snap["step"] == 5 and snap["epoch"] == 1
  assert snap["examples"] == 20
  assert snap["examples_per_sec"] == pytest.approx(20 / 0.1)
  assert snap["loss"] == pytest.approx(0.46)
  assert snap["learning_rate"] == pytest.approx(1e-3)
  assert snap["ckpt"] == {"saves": 1, "save_seconds": 0.3,
                          "save_bytes": 1024, "last_save_ms": 300.0,
                          "last_save_bytes": 1024}
  assert snap["nan_rollbacks"] == 1 and snap["preemptions"] == 1
  assert snap["restores"] == 1
  # Percentile-true off the native histogram: within one ~19%-wide
  # exponential bucket of the (constant) 20 ms truth.
  assert snap["step_ms"]["p50"] == pytest.approx(20.0, rel=0.1)
  assert snap["step_ms"]["p99"] == pytest.approx(20.0, rel=0.15)
  assert snap["step_latency_hist"]["count"] == 5
  assert snap["save_latency_hist"]["count"] == 1

  families = parse_metrics_text(tm.registry(snap).render())

  def val(name):
    return families[name]["samples"][(name, ())]

  assert val("mpi_train_steps_total") == snap["steps"]
  assert val("mpi_train_step") == snap["step"]
  assert val("mpi_train_epoch") == snap["epoch"]
  assert val("mpi_train_examples_total") == snap["examples"]
  assert val("mpi_train_step_seconds_total") \
      == pytest.approx(snap["step_seconds"])
  assert val("mpi_train_loss") == pytest.approx(snap["loss"])
  assert val("mpi_train_learning_rate") == pytest.approx(1e-3)
  assert val("mpi_train_ckpt_saves_total") == 1
  assert val("mpi_train_ckpt_save_bytes_total") == 1024
  assert val("mpi_train_nan_rollbacks_total") == 1
  assert val("mpi_train_preemptions_total") == 1
  assert val("mpi_train_restores_total") == 1
  assert families["mpi_train_steps_total"]["type"] == "counter"
  assert families["mpi_train_loss"]["type"] == "gauge"


def test_idle_metrics_render_without_nans_breaking_parse():
  tm = TrainMetrics(clock=FakeClock())
  families = parse_metrics_text(tm.metrics_text())
  assert families["mpi_train_steps_total"]["samples"][
      ("mpi_train_steps_total", ())] == 0
  # loss/lr/throughput are NaN while idle — exposition must still parse.
  assert "mpi_train_loss" in families


def test_jsonl_sink_records_steps_and_saves(tmp_path):
  path = str(tmp_path / "metrics.jsonl")
  sink = file_metrics_sink(path)
  tm = TrainMetrics(clock=FakeClock(), sink=sink)
  tm.record_step(1, loss=0.5, wall_s=0.01, examples=2, lr=2e-4)
  tm.record_save(1, seconds=0.1, nbytes=64, reason="epoch")
  sink.close()
  lines = [json.loads(l) for l in open(path).read().splitlines()]
  assert [l["event"] for l in lines] == ["train_step", "ckpt_save"]
  assert lines[0]["step"] == 1 and lines[0]["lr"] == pytest.approx(2e-4)
  assert lines[1]["bytes"] == 64 and lines[1]["reason"] == "epoch"


def test_failing_sink_counted_never_raised():
  def bad(line):
    raise OSError("pipe closed")

  tm = TrainMetrics(clock=FakeClock(), sink=bad)
  tm.record_step(1, loss=0.5, wall_s=0.01)
  assert tm.sink_errors == 1 and tm.steps == 1


# --- fit_resumable threading ----------------------------------------------


def test_fit_resumable_records_steps_saves_and_events(tmp_path):
  tm = TrainMetrics()
  ev = EventLog(clock=FakeClock())
  store = CheckpointStore(str(tmp_path), events=ev)
  state, report = tloop.fit_resumable(
      _mini_state(), 2, _epoch, store, step=_mini_step, resume="never",
      telemetry=tm, events=ev)
  assert report["final_step"] == 6
  snap = tm.snapshot()
  assert snap["steps"] == 6 and snap["step"] == 6
  assert snap["examples"] == 12          # 6 steps x batch of 2
  assert snap["epoch"] == 1              # last finished epoch index
  assert snap["loss"] == pytest.approx(report["losses"][-1])
  # Every save the report counts is in the telemetry, with real cost.
  assert snap["ckpt"]["saves"] == report["saves"]
  assert snap["ckpt"]["save_bytes"] > 0
  # The store emitted its lifecycle into the event log.
  assert ev.count("ckpt_save") == report["saves"]
  save = ev.snapshot(kind="ckpt_save")["events"][0]
  assert save["bytes"] > 0 and save["reason"] == "initial"


def test_fit_resumable_restore_and_rollback_telemetry(tmp_path):
  ev = EventLog(clock=FakeClock())
  store = CheckpointStore(str(tmp_path), events=ev)
  tloop.fit_resumable(_mini_state(), 1, _epoch, store, step=_mini_step,
                      resume="never")

  # Resume: the restore is counted and the event emitted.
  tm = TrainMetrics()
  _, report = tloop.fit_resumable(
      _mini_state(), 2, _epoch, CheckpointStore(str(tmp_path), events=ev),
      step=_mini_step, resume="auto", telemetry=tm, events=ev)
  assert report["resumed_from"] == 3
  assert tm.snapshot()["restores"] == 1
  assert ev.count("ckpt_restore") >= 1

  # NaN rollback: counter + event with the rollback target.
  poisoned = []

  def nan_step(state, batch):
    new, metrics = _mini_step(state, batch)
    if state.step == 4 and not poisoned:  # one TRANSIENT glitch
      poisoned.append(True)
      return new, {"loss": float("nan")}
    return new, metrics

  tm2 = TrainMetrics()
  ev2 = EventLog(clock=FakeClock())
  _, report = tloop.fit_resumable(
      _mini_state(), 2, _epoch, CheckpointStore(str(tmp_path / "nan"),
                                                events=ev2),
      step=nan_step, resume="never", nan_guard=NanGuard(max_rollbacks=3),
      telemetry=tm2, events=ev2)
  assert report["nan_rollbacks"] >= 1
  assert tm2.snapshot()["nan_rollbacks"] == report["nan_rollbacks"]
  roll = ev2.snapshot(kind="nan_rollback")["events"]
  assert roll and roll[0]["to_step"] in report["nan_rollback_steps"]


def test_fit_resumable_preemption_telemetry(tmp_path):
  tm = TrainMetrics()
  ev = EventLog(clock=FakeClock())
  preempt = PreemptionGuard()

  def step(state, batch):
    new, metrics = _mini_step(state, batch)
    if new.step == 2:
      preempt.request()
    return new, metrics

  _, report = tloop.fit_resumable(
      _mini_state(), 2, _epoch, CheckpointStore(str(tmp_path), events=ev),
      step=step, resume="never", preemption=preempt,
      telemetry=tm, events=ev)
  assert report["preempted"] is True
  assert tm.snapshot()["preemptions"] == 1
  assert ev.count("preempt") == 1


# --- the --metrics-port smoke: scrape a RUNNING loop ----------------------


def _scrape(port, path="/metrics"):
  with urllib.request.urlopen(
      f"http://127.0.0.1:{port}{path}", timeout=30) as resp:
    return resp.read().decode()


def test_metrics_server_scrapes_live_training_loop(tmp_path):
  """The acceptance pin: while fit_resumable is mid-run, a stock HTTP
  scrape of /metrics sees live, increasing mpi_train_* step metrics —
  then the post-run scrape shows the completed totals."""
  tm = TrainMetrics()
  ev = EventLog(clock=FakeClock())
  httpd = make_train_metrics_server(tm, events=ev)
  port = httpd.server_address[1]
  threading.Thread(target=httpd.serve_forever, daemon=True).start()

  reached_step_2 = threading.Event()
  release = threading.Event()

  def gated_step(state, batch):
    new, metrics = _mini_step(state, batch)
    if new.step == 2:
      # Park the loop mid-epoch with step 1 already recorded, so the
      # scrape below provably reads a RUNNING training process.
      reached_step_2.set()
      assert release.wait(60), "scraper never released the loop"
    return new, metrics

  result = {}

  def run():
    _, result["report"] = tloop.fit_resumable(
        _mini_state(), 2, _epoch, CheckpointStore(str(tmp_path),
                                                  events=ev),
        step=gated_step, resume="never", telemetry=tm, events=ev)

  worker = threading.Thread(target=run, daemon=True)
  worker.start()
  try:
    assert reached_step_2.wait(60)
    live = parse_metrics_text(_scrape(port))
    steps_live = live["mpi_train_steps_total"]["samples"][
        ("mpi_train_steps_total", ())]
    assert steps_live == 1                 # mid-run, not post-run
    assert live["mpi_train_loss"]["samples"][
        ("mpi_train_loss", ())] == pytest.approx(0.1)
    stats = json.loads(_scrape(port, "/stats"))
    assert stats["steps"] == 1
    health = json.loads(_scrape(port, "/healthz"))
    assert health["status"] == "ok" and health["role"] == "train"
    assert health["steps"] == 1 and health["step"] == 1
    # The queue supervisor reads both off one probe: the step counter
    # for wedge detection, the step wall time for the latency SLO.
    assert health["last_step_ms"] > 0
  finally:
    release.set()
    worker.join(120)
  assert not worker.is_alive()
  done = parse_metrics_text(_scrape(port))
  assert done["mpi_train_steps_total"]["samples"][
      ("mpi_train_steps_total", ())] == 6
  assert done["mpi_train_ckpt_saves_total"]["samples"][
      ("mpi_train_ckpt_saves_total", ())] == result["report"]["saves"]
  events = json.loads(_scrape(port, "/debug/events"))
  assert events["by_kind"].get("ckpt_save", 0) == result["report"]["saves"]
  httpd.shutdown()


def test_native_histogram_families_and_quantile_gauges():
  """PR 12 satellite: step/save latencies ride obs/hist.NativeHistogram —
  percentile-true p50/p99 in the snapshot and `/metrics`, exact-merge
  bucket families next to the classic counters."""
  from mpi_vision_tpu.obs import hist as hist_mod

  tm = TrainMetrics(clock=FakeClock())
  for wall in (0.01, 0.01, 0.01, 0.01, 0.5):  # one slow tail step
    tm.record_step(1, loss=0.1, wall_s=wall)
  tm.record_save(5, seconds=0.2, nbytes=10)
  snap = tm.snapshot()
  assert snap["step_ms"]["p50"] == pytest.approx(10.0, rel=0.1)
  assert snap["step_ms"]["p99"] == pytest.approx(500.0, rel=0.15)
  text = tm.registry(snap).render()
  families = parse_metrics_text(text)
  hist = families["mpi_train_step_latency_nativehist"]
  count = hist["samples"][("mpi_train_step_latency_nativehist_count", ())]
  assert count == 5
  assert ("mpi_train_ckpt_save_latency_nativehist_count", ()) in \
      families["mpi_train_ckpt_save_latency_nativehist"]["samples"]
  q = families["mpi_train_step_quantile_seconds"]["samples"]
  p99 = q[("mpi_train_step_quantile_seconds", (("q", "0.99"),))]
  assert p99 == pytest.approx(0.5, rel=0.15)
  # The gauge agrees with the snapshot's own quantile (one source).
  assert p99 * 1e3 == pytest.approx(snap["step_ms"]["p99"], rel=1e-6)
  # Exposition snapshots merge exactly across trainers (pool semantics).
  merged = hist_mod.merge([snap["step_latency_hist"],
                           snap["step_latency_hist"]])
  assert merged.count == 10
