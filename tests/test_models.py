"""Stereo-magnification U-Net: shapes, gradients, and torch-mirror parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from mpi_vision_tpu.models import stereo_mag
from mpi_vision_tpu.torchref import model as torch_model


def _init(num_planes, h, w, norm="instance"):
  net = stereo_mag.StereoMagnificationModel(num_planes=num_planes, norm=norm)
  x = jnp.zeros((1, h, w, 3 + 3 * num_planes))
  params = net.init(jax.random.key(0), x)
  return net, params


def test_output_shape():
  net, params = _init(3, 32, 32)
  x = jnp.ones((2, 32, 32, 12))
  y = net.apply(params, x)
  assert y.shape == (2, 32, 32, 3 + 2 * 3)
  assert np.all(np.abs(np.asarray(y)) <= 1.0)  # tanh head


@pytest.mark.parametrize("norm", ["instance", None])
def test_parity_with_torch_mirror(rng, norm):
  p, h, w = 2, 16, 16
  torch.manual_seed(0)  # unseeded draws occasionally push f32 divergence past atol
  tnet = torch_model.StereoMagnificationModel(num_planes=p, norm=norm).eval()
  jnet = stereo_mag.StereoMagnificationModel(num_planes=p, norm=norm)
  params = stereo_mag.params_from_torch_state(tnet.state_dict(), norm=norm)

  x = rng.uniform(-1.0, 1.0, size=(1, h, w, 3 + 3 * p)).astype(np.float32)
  with torch.no_grad():
    want = tnet(torch.tensor(np.transpose(x, (0, 3, 1, 2))))
  want = np.transpose(want.numpy(), (0, 2, 3, 1))
  got = np.asarray(jnet.apply(params, jnp.asarray(x)))
  np.testing.assert_allclose(got, want, atol=5e-5)


def test_mpi_from_net_output_parity(rng):
  b, h, w, p = 2, 8, 8, 5
  pred = rng.uniform(-1.0, 1.0, size=(b, h, w, 3 + 2 * p)).astype(np.float32)
  ref = rng.uniform(-1.0, 1.0, size=(b, h, w, 3)).astype(np.float32)
  got = np.asarray(stereo_mag.mpi_from_net_output(jnp.asarray(pred), jnp.asarray(ref)))
  want = torch_model.mpi_from_net_output(
      torch.tensor(np.transpose(pred, (0, 3, 1, 2))), torch.tensor(ref)).numpy()
  assert got.shape == (b, h, w, p, 4)
  np.testing.assert_allclose(got, want, atol=1e-6)


def test_gradients_flow(rng):
  net, params = _init(2, 16, 16)
  x = jnp.asarray(rng.uniform(-1, 1, size=(1, 16, 16, 9)).astype(np.float32))

  @jax.jit
  def loss(p):
    return jnp.sum(net.apply(p, x) ** 2)

  g = jax.grad(loss)(params)
  leaves = jax.tree_util.tree_leaves(g)
  assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
  assert any(float(jnp.abs(l).sum()) > 0 for l in leaves)


def test_mpi_assembly_blend_extremes():
  # w=1 -> plane RGB equals ref image; w=-(-1)=0 -> equals background.
  b, h, w_, p = 1, 4, 4, 2
  pred = np.zeros((b, h, w_, 3 + 2 * p), np.float32)
  pred[..., 0] = 1.0   # plane 0 blend weight -> 1
  pred[..., 1] = -1.0  # plane 1 blend weight -> 0
  pred[..., -3:] = 0.5  # background
  ref = np.full((b, h, w_, 3), -0.25, np.float32)
  rgba = np.asarray(stereo_mag.mpi_from_net_output(jnp.asarray(pred), jnp.asarray(ref)))
  np.testing.assert_allclose(rgba[..., 0, :3], -0.25, atol=1e-6)
  np.testing.assert_allclose(rgba[..., 1, :3], 0.5, atol=1e-6)


class TestTinyPlaneUNet:
  """The DeepView-style direct per-plane RGBA predictor (BASELINE config 5;
  bench/config5_tiny_unet.py is its workload)."""

  def _psv(self, rng, b=1, hw=16, p=4):
    from mpi_vision_tpu.models import tiny_unet
    net_input = rng.uniform(-1, 1, (b, hw, hw, 3 + 3 * p)).astype(np.float32)
    return tiny_unet.psv_from_net_input(jnp.asarray(net_input), p)

  def test_psv_from_net_input_layout(self, rng):
    from mpi_vision_tpu.models import tiny_unet
    b, hw, p = 2, 8, 3
    net_input = rng.uniform(-1, 1, (b, hw, hw, 3 + 3 * p)).astype(np.float32)
    psv = tiny_unet.psv_from_net_input(jnp.asarray(net_input), p)
    assert psv.shape == (b, hw, hw, p, 6)
    # channels 0:3 = the PSV planes, channels 3:6 = broadcast ref image.
    np.testing.assert_array_equal(
        np.asarray(psv[..., 1, :3]), net_input[..., 6:9])
    np.testing.assert_array_equal(
        np.asarray(psv[..., 2, 3:]), net_input[..., :3])

  def test_forward_shape_and_ranges(self, rng):
    from mpi_vision_tpu.models import tiny_unet
    model = tiny_unet.TinyPlaneUNet(width=8, mix=1)
    psv = self._psv(rng)
    params = model.init(jax.random.PRNGKey(0), psv)
    mpi = model.apply(params, psv)
    assert mpi.shape == (1, 16, 16, 4, 4)
    out = np.asarray(mpi)
    assert np.isfinite(out).all()
    assert (out[..., :3] >= -1).all() and (out[..., :3] <= 1).all()  # tanh
    assert (out[..., 3] >= 0).all() and (out[..., 3] <= 1).all()     # sigmoid

  def test_overfits_render_loss(self, rng):
    """A few Adam steps on one pair must reduce the render loss (the
    renderer-in-the-loss design trains end to end)."""
    import optax
    from mpi_vision_tpu.core import render
    from mpi_vision_tpu.core.camera import inv_depths
    from mpi_vision_tpu.models import tiny_unet

    p_n, hw = 4, 16
    model = tiny_unet.TinyPlaneUNet(width=8, mix=1)
    psv = self._psv(rng, hw=hw, p=p_n)
    params = model.init(jax.random.PRNGKey(0), psv)
    tgt = jnp.asarray(rng.uniform(-1, 1, (1, hw, hw, 3)).astype(np.float32))
    pose = np.eye(4, dtype=np.float32)
    pose[0, 3] = 0.03
    pose_j = jnp.asarray(pose)[None]
    depths = inv_depths(1.0, 100.0, p_n)
    k = jnp.asarray(np.array(
        [[hw / 2, 0, hw / 2], [0, hw / 2, hw / 2], [0, 0, 1]],
        np.float32))[None]

    def loss_fn(p):
      mpi = model.apply(p, psv)
      out = render.render_mpi(mpi, pose_j, depths, k)
      return jnp.mean((out - tgt) ** 2)

    tx = optax.adam(3e-3)
    opt = tx.init(params)

    @jax.jit
    def step(p, o):
      l, g = jax.value_and_grad(loss_fn)(p)
      up, o = tx.update(g, o)
      return optax.apply_updates(p, up), o, l

    losses = []
    for _ in range(12):
      params, opt, l = step(params, opt)
      losses.append(float(l))
    assert losses[-1] < losses[0], losses


class TestBF16Compute:
  """dtype=jnp.bfloat16: MXU-precision compute, f32 params and output
  (SURVEY.md par.7's "f32 default with bf16 option")."""

  def _setup(self, rng, norm):
    x = jnp.asarray(rng.uniform(-1, 1, (1, 32, 32, 15)).astype(np.float32))
    m32 = stereo_mag.StereoMagnificationModel(num_planes=4, norm=norm)
    mbf = stereo_mag.StereoMagnificationModel(num_planes=4, norm=norm,
                                              dtype=jnp.bfloat16)
    params = m32.init(jax.random.PRNGKey(0), x)["params"]
    return x, m32, mbf, params

  @pytest.mark.parametrize("norm", [None, "instance"])
  def test_forward_tracks_f32(self, rng, norm):
    x, m32, mbf, params = self._setup(rng, norm)
    y32 = m32.apply({"params": params}, x)
    ybf = mbf.apply({"params": params}, x)
    assert ybf.dtype == jnp.float32          # output cast back
    d = np.abs(np.asarray(y32) - np.asarray(ybf))
    # bf16's 8-bit mantissa compounds through ~20 layers; the tanh output
    # lives in (-1, 1), so a few 1e-2 of drift is the expected precision,
    # not a bug.
    assert d.mean() < 2e-2 and d.max() < 0.2, (d.mean(), d.max())

  def test_params_identical_tree_and_f32(self, rng):
    x, m32, mbf, params = self._setup(rng, "instance")
    pbf = mbf.init(jax.random.PRNGKey(0), x)["params"]
    assert jax.tree.structure(params) == jax.tree.structure(pbf)
    assert all(a.dtype == jnp.float32 for a in jax.tree.leaves(pbf))

  def test_grads_finite_and_nonzero(self, rng):
    x, m32, mbf, params = self._setup(rng, None)
    g = jax.grad(lambda p: jnp.sum(
        mbf.apply({"params": p}, x) ** 2))(params)
    leaves = jax.tree.leaves(g)
    assert all(np.isfinite(np.asarray(a)).all() for a in leaves)
    assert any(float(jnp.abs(a).max()) > 0 for a in leaves)
    assert all(a.dtype == jnp.float32 for a in leaves)

  def test_bf16_compute_tracks_f32(self, rng):
    from mpi_vision_tpu.models import tiny_unet

    psv = jnp.asarray(rng.uniform(-1, 1, (1, 16, 16, 3, 3)).astype(np.float32))
    m32 = tiny_unet.TinyPlaneUNet(width=8)
    mbf = tiny_unet.TinyPlaneUNet(width=8, dtype=jnp.bfloat16)
    params = m32.init(jax.random.PRNGKey(0), psv)["params"]
    y32 = m32.apply({"params": params}, psv)
    ybf = mbf.apply({"params": params}, psv)
    assert ybf.dtype == jnp.float32
    d = np.abs(np.asarray(y32) - np.asarray(ybf))
    assert d.mean() < 2e-2 and d.max() < 0.2, (d.mean(), d.max())
