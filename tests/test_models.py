"""Stereo-magnification U-Net: shapes, gradients, and torch-mirror parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from mpi_vision_tpu.models import stereo_mag
from mpi_vision_tpu.torchref import model as torch_model


def _init(num_planes, h, w, norm="instance"):
  net = stereo_mag.StereoMagnificationModel(num_planes=num_planes, norm=norm)
  x = jnp.zeros((1, h, w, 3 + 3 * num_planes))
  params = net.init(jax.random.key(0), x)
  return net, params


def test_output_shape():
  net, params = _init(3, 32, 32)
  x = jnp.ones((2, 32, 32, 12))
  y = net.apply(params, x)
  assert y.shape == (2, 32, 32, 3 + 2 * 3)
  assert np.all(np.abs(np.asarray(y)) <= 1.0)  # tanh head


@pytest.mark.parametrize("norm", ["instance", None])
def test_parity_with_torch_mirror(rng, norm):
  p, h, w = 2, 16, 16
  torch.manual_seed(0)  # unseeded draws occasionally push f32 divergence past atol
  tnet = torch_model.StereoMagnificationModel(num_planes=p, norm=norm).eval()
  jnet = stereo_mag.StereoMagnificationModel(num_planes=p, norm=norm)
  params = stereo_mag.params_from_torch_state(tnet.state_dict(), norm=norm)

  x = rng.uniform(-1.0, 1.0, size=(1, h, w, 3 + 3 * p)).astype(np.float32)
  with torch.no_grad():
    want = tnet(torch.tensor(np.transpose(x, (0, 3, 1, 2))))
  want = np.transpose(want.numpy(), (0, 2, 3, 1))
  got = np.asarray(jnet.apply(params, jnp.asarray(x)))
  np.testing.assert_allclose(got, want, atol=5e-5)


def test_mpi_from_net_output_parity(rng):
  b, h, w, p = 2, 8, 8, 5
  pred = rng.uniform(-1.0, 1.0, size=(b, h, w, 3 + 2 * p)).astype(np.float32)
  ref = rng.uniform(-1.0, 1.0, size=(b, h, w, 3)).astype(np.float32)
  got = np.asarray(stereo_mag.mpi_from_net_output(jnp.asarray(pred), jnp.asarray(ref)))
  want = torch_model.mpi_from_net_output(
      torch.tensor(np.transpose(pred, (0, 3, 1, 2))), torch.tensor(ref)).numpy()
  assert got.shape == (b, h, w, p, 4)
  np.testing.assert_allclose(got, want, atol=1e-6)


def test_gradients_flow(rng):
  net, params = _init(2, 16, 16)
  x = jnp.asarray(rng.uniform(-1, 1, size=(1, 16, 16, 9)).astype(np.float32))

  @jax.jit
  def loss(p):
    return jnp.sum(net.apply(p, x) ** 2)

  g = jax.grad(loss)(params)
  leaves = jax.tree_util.tree_leaves(g)
  assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
  assert any(float(jnp.abs(l).sum()) > 0 for l in leaves)


def test_mpi_assembly_blend_extremes():
  # w=1 -> plane RGB equals ref image; w=-(-1)=0 -> equals background.
  b, h, w_, p = 1, 4, 4, 2
  pred = np.zeros((b, h, w_, 3 + 2 * p), np.float32)
  pred[..., 0] = 1.0   # plane 0 blend weight -> 1
  pred[..., 1] = -1.0  # plane 1 blend weight -> 0
  pred[..., -3:] = 0.5  # background
  ref = np.full((b, h, w_, 3), -0.25, np.float32)
  rgba = np.asarray(stereo_mag.mpi_from_net_output(jnp.asarray(pred), jnp.asarray(ref)))
  np.testing.assert_allclose(rgba[..., 0, :3], -0.25, atol=1e-6)
  np.testing.assert_allclose(rgba[..., 1, :3], 0.5, atol=1e-6)
