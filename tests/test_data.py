"""Data pipeline tests on a synthesized RealEstate10K-layout dataset."""

import numpy as np
import pytest

from mpi_vision_tpu import data as mvdata
from mpi_vision_tpu.train import loop as tloop


@pytest.fixture(scope="module")
def dataset_root(tmp_path_factory):
  root = tmp_path_factory.mktemp("re10k")
  return mvdata.synthesize_dataset(str(root), num_scenes=2, frames=4,
                                   img_size=32)


class TestParsing:

  def test_parse_camera_lines_roundtrip(self, dataset_root):
    scenes = mvdata.load_scenes(dataset_root, "train")
    assert len(scenes) == 2
    s = scenes[0]
    assert s.youtube_id == "synth000"
    assert s.timestamps == [16000, 32000, 48000, 64000]
    assert s.intrinsics.shape == (4, 4)
    assert s.poses.shape == (4, 4, 4)
    np.testing.assert_array_equal(s.poses[0], np.eye(4))
    assert s.poses[2][0, 3] == pytest.approx(-0.2)

  def test_rejects_radial_distortion(self):
    lines = ["https://www.youtube.com/watch?v=x",
             "100 0.9 0.9 0.5 0.5 0.1 0 " + " ".join(["0"] * 12)]
    with pytest.raises(ValueError, match="k1/k2"):
      mvdata.parse_camera_lines(lines)

  def test_comment_lines_dropped(self, tmp_path):
    p = tmp_path / "f.txt"
    p.write_text("# comment\nkeep\n\n  # also comment\nkeep2\n")
    assert mvdata.read_file_lines(str(p)) == ["keep", "keep2"]


class TestTriplets:

  def test_draw_triplet_respects_window(self, dataset_root):
    scene = mvdata.load_scenes(dataset_root, "train")[0]
    rng = np.random.default_rng(0)
    for _ in range(10):
      ref, src, tgt = mvdata.draw_triplet(scene, rng)
      assert src != tgt
      for j in (src, tgt):
        d = abs(scene.timestamps[ref] - scene.timestamps[j])
        assert 16e3 <= d <= 500e3

  def test_window_too_small_raises(self, dataset_root):
    scene = mvdata.load_scenes(dataset_root, "train")[0]
    rng = np.random.default_rng(0)
    with pytest.raises(ValueError, match="<2 frames"):
      mvdata.draw_triplet(scene, rng, min_dist=1e9, max_dist=2e9)


class TestExamples:

  def test_example_contract(self, dataset_root):
    ds = mvdata.RealEstateDataset(dataset_root, is_valid=True,
                                  img_size=32, num_planes=4)
    ex = ds[0]
    assert ex["net_input"].shape == (32, 32, 3 + 3 * 4)
    assert ex["ref_img"].shape == (32, 32, 3)
    assert ex["tgt_img_cfw"].shape == (4, 4)
    assert ex["mpi_planes"].shape == (4,)
    assert ex["mpi_planes"][0] == pytest.approx(100.0)  # far first
    assert ex["net_input"].min() >= -1.0 and ex["net_input"].max() <= 1.0
    # ref image rides in the first 3 channels of the net input (cell 8:77).
    np.testing.assert_array_equal(ex["net_input"][..., :3], ex["ref_img"])
    # world-from-camera really is the inverse of the ref pose.
    scene = ds.scenes[0]
    np.testing.assert_allclose(
        ex["ref_img_wfc"] @ scene.poses[0], np.eye(4), atol=1e-6)

  def test_batches_feed_training(self, dataset_root):
    ds = mvdata.RealEstateDataset(dataset_root, is_valid=True,
                                  img_size=32, num_planes=4)
    state = tloop.create_train_state(
        __import__("jax").random.PRNGKey(0), num_planes=4,
        image_size=(32, 32), learning_rate=1e-3, norm=None)
    step = tloop.make_train_step(vgg_params=None)
    batches = list(mvdata.iterate_batches(ds, batch_size=1, shuffle=False))
    assert len(batches) == 2
    assert batches[0]["mpi_planes"].shape == (1, 4)
    losses = []
    for batch in batches * 3:
      state, metrics = step(state, batch)
      losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]

  def test_iterate_batches_skip_matches_replay_train_split(
      self, dataset_root):
    """The skip-ahead cursor seek must yield the EXACT stream that
    iterating past the skipped batches yields — including on the train
    split, whose triplets draw from a stateful RNG per access
    (skip_example consumes the draws without the frame IO)."""
    def stream(skip):
      ds = mvdata.RealEstateDataset(dataset_root, is_valid=False,
                                    img_size=32, num_planes=4,
                                    rng=np.random.default_rng(7))
      return list(mvdata.iterate_batches(
          ds, batch_size=1, rng=np.random.default_rng(3), skip=skip))

    full = stream(0)
    tail = stream(1)
    assert len(tail) == len(full) - 1
    for a, b in zip(full[1:], tail):
      for key in a:
        np.testing.assert_array_equal(np.asarray(a[key]),
                                      np.asarray(b[key]))

  def test_iterate_batches_skip_past_end_is_empty(self, dataset_root):
    ds = mvdata.RealEstateDataset(dataset_root, is_valid=True,
                                  img_size=32, num_planes=4)
    assert list(mvdata.iterate_batches(ds, batch_size=1, shuffle=False,
                                       skip=99)) == []
    with pytest.raises(ValueError, match="skip"):
      list(mvdata.iterate_batches(ds, batch_size=1, skip=-1))

  def test_train_split_randomizes(self, dataset_root):
    ds = mvdata.RealEstateDataset(dataset_root, is_valid=False, img_size=32,
                                  num_planes=4,
                                  rng=np.random.default_rng(1))
    exs = [ds[0]["tgt_img_cfw"] for _ in range(6)]
    assert any(not np.array_equal(exs[0], e) for e in exs[1:])


class TestPrefetch:

  def test_prefetch_preserves_order_and_content(self):
    from mpi_vision_tpu.data.realestate import prefetch_batches

    items = [{"x": i} for i in range(7)]
    got = list(prefetch_batches(iter(items), size=3))
    assert got == items

  def test_prefetch_propagates_worker_exception(self):
    from mpi_vision_tpu.data.realestate import prefetch_batches

    def gen():
      yield 1
      raise RuntimeError("decode failed")

    it = prefetch_batches(gen(), size=2)
    assert next(it) == 1
    import pytest as _pytest
    with _pytest.raises(RuntimeError, match="decode failed"):
      list(it)


class TestSynthesizeRotation:

  def test_rot_deg_adds_rotation_default_identity(self, tmp_path):
    """rot_deg > 0 writes genuinely rotated (still orthonormal) poses;
    the default stays pure-truck so legacy fixtures are byte-identical."""
    plain = mvdata.synthesize_dataset(
        str(tmp_path / "plain"), num_scenes=1, frames=4, img_size=32)
    rotated = mvdata.synthesize_dataset(
        str(tmp_path / "rot"), num_scenes=1, frames=4, img_size=32,
        rot_deg=2.0)
    s0 = mvdata.load_scenes(plain, "train")[0]
    s1 = mvdata.load_scenes(rotated, "train")[0]
    for pose in s0.poses:
      np.testing.assert_array_equal(pose[:3, :3], np.eye(3))
    rots = [pose[:3, :3] for pose in s1.poses]
    assert any(not np.allclose(r, np.eye(3), atol=1e-6) for r in rots)
    for r in rots:  # still valid camera rotations
      np.testing.assert_allclose(r @ r.T, np.eye(3), atol=1e-5)
      # jitter stays within the requested bound (2 deg ~ 0.035 rad per
      # axis; allow the 3-axis composition a loose envelope)
      angle = np.arccos(np.clip((np.trace(r) - 1) / 2, -1, 1))
      assert angle <= np.radians(2.0) * 2.0

  def test_rotated_dataset_trains_end_to_end(self, tmp_path):
    """The rotated pose stream flows through the dataset -> PSV -> planned
    train step (the tier-census path)."""
    import jax
    import numpy as np2

    from mpi_vision_tpu import config

    root = mvdata.synthesize_dataset(
        str(tmp_path / "ds"), num_scenes=2, frames=4, img_size=32,
        rot_deg=2.0)
    cfg = config.TrainConfig(
        data=config.DataConfig(dataset_path=root, img_size=32,
                               num_planes=4))
    dataset = cfg.data.make_dataset(rng=np2.random.default_rng(0))
    state = cfg.make_train_state(jax.random.PRNGKey(0))
    step = tloop.make_train_step_planned(None, resize=None)
    batches = list(mvdata.iterate_batches(
        dataset, rng=np2.random.default_rng(1)))[:2]
    for b in batches:
      state, metrics = step(state, b)
      assert np2.isfinite(float(metrics["loss"]))
