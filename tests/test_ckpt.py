"""ckpt/ subsystem tests: atomic store, guards, fault injection, the
crash-safe train loop, and the checkpoint -> serve bridge.

The heavyweight acceptance pin (SIGKILL a real subprocess mid-epoch,
resume, compare digests) lives in tests/test_train_resume.py; these are
the in-process behaviors: store atomicity + quarantine mechanics, guard
state machines on fake clocks, and bit-exact resume through the
SimulatedCrash / NaN / preempt fault paths.
"""

import json
import os
import signal
import time

import jax
import numpy as np
import pytest

from mpi_vision_tpu.ckpt import (
    BackgroundSaver,
    CheckpointStore,
    CorruptCheckpointError,
    NanGuard,
    NonFiniteLossError,
    PreemptionGuard,
    SimulatedCrash,
    StallWatchdog,
    TrainFault,
    TrainFaultSource,
    flatten_arrays,
    unflatten_arrays,
)
from mpi_vision_tpu.train import loop as tloop

HW, PLANES = 16, 2


def _tree(rng):
  return {
      "params": {"w": rng.normal(size=(3, 4)).astype(np.float32),
                 "b": rng.normal(size=(4,)).astype(np.float32)},
      "step": np.int64(7),
  }


class TestStore:

  def test_roundtrip_bit_exact_including_scalars(self, rng, tmp_path):
    store = CheckpointStore(str(tmp_path))
    tree = _tree(rng)
    store.save(7, tree, meta={"cursor": {"epoch": 1, "batch": 2}})
    restored = store.restore(template=tree)
    assert restored.step == 7
    assert restored.meta["cursor"] == {"epoch": 1, "batch": 2}
    out = restored.tree(tree)
    assert np.shape(out["step"]) == ()          # 0-d stays 0-d
    assert out["step"].dtype == np.int64
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), tree, out)

  def test_bfloat16_leaves_roundtrip(self, tmp_path):
    import jax.numpy as jnp

    store = CheckpointStore(str(tmp_path))
    # The 0-d scalar exercises the reshape-before-view raw-bytes path
    # (numpy rejects re-viewing a 0-d array at a different itemsize).
    tree = {"x": jnp.arange(6, dtype=jnp.bfloat16).reshape(2, 3) / 3,
            "s": jnp.asarray(0.25, jnp.bfloat16)}
    store.save(0, tree)
    out = store.restore().tree({"x": tree["x"], "s": tree["s"]})
    assert out["x"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(tree["x"], np.float32),
                                  np.asarray(out["x"], np.float32))
    assert out["s"].shape == () and out["s"].dtype == jnp.bfloat16
    assert float(out["s"]) == 0.25

  def test_partial_template_restores_subtree(self, rng, tmp_path):
    store = CheckpointStore(str(tmp_path))
    tree = _tree(rng)
    store.save(1, tree)
    params = store.restore().tree({"params": tree["params"]})["params"]
    np.testing.assert_array_equal(params["w"], tree["params"]["w"])

  def test_missing_template_key_raises(self, rng, tmp_path):
    store = CheckpointStore(str(tmp_path))
    store.save(1, _tree(rng))
    with pytest.raises(KeyError, match="missing array"):
      store.restore(template={"nope": np.zeros(1)})

  def test_gc_keeps_last_k(self, rng, tmp_path):
    store = CheckpointStore(str(tmp_path), keep=2)
    for s in range(5):
      store.save(s, _tree(rng))
    assert store.steps() == [3, 4]

  def test_overwrite_same_step(self, rng, tmp_path):
    store = CheckpointStore(str(tmp_path))
    store.save(3, _tree(rng))
    tree2 = _tree(np.random.default_rng(99))
    store.save(3, tree2)
    out = store.restore().tree(tree2)
    np.testing.assert_array_equal(out["params"]["w"], tree2["params"]["w"])
    assert store.steps() == [3]

  def test_truncated_arrays_quarantined_with_fallback(self, rng, tmp_path):
    store = CheckpointStore(str(tmp_path))
    store.save(1, _tree(rng))
    good = store.restore().tree(_tree(rng))
    store.save(2, _tree(np.random.default_rng(1)))
    # Truncate the newest checkpoint's arrays file (torn write / bit rot).
    path = os.path.join(store._step_dir(2), "arrays.npz")
    with open(path, "r+b") as fh:
      fh.truncate(os.path.getsize(path) // 2)
    events = []
    restored = store.restore(on_quarantine=lambda s, r: events.append((s, r)))
    assert restored.step == 1                    # fell back to last-good
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), good, restored.tree(_tree(rng)))
    assert store.quarantined == 1 and events and events[0][0] == 2
    qdir = os.path.join(str(tmp_path), "quarantine")
    assert len(os.listdir(qdir)) == 1
    assert store.steps() == [1]                  # the bad dir is gone

  def test_garbled_manifest_quarantined(self, rng, tmp_path):
    store = CheckpointStore(str(tmp_path))
    store.save(1, _tree(rng))
    with open(os.path.join(store._step_dir(1), "manifest.json"), "w") as fh:
      fh.write("{not json")
    assert store.restore() is None               # nothing good left
    assert store.quarantined == 1

  def test_transient_read_error_does_not_quarantine(self, rng, tmp_path,
                                                    monkeypatch):
    # fd exhaustion (EMFILE) while reading a manifest is environmental,
    # not corruption: the error must surface as-is and the healthy
    # checkpoint must stay published for the next attempt.
    import builtins
    import errno

    store = CheckpointStore(str(tmp_path))
    store.save(1, _tree(rng))
    real_open = builtins.open

    def flaky_open(file, *a, **kw):
      if str(file).endswith("manifest.json"):
        raise OSError(errno.EMFILE, "Too many open files", str(file))
      return real_open(file, *a, **kw)

    monkeypatch.setattr(builtins, "open", flaky_open)
    with pytest.raises(OSError) as ei:
      store.restore()
    monkeypatch.undo()
    assert ei.value.errno == errno.EMFILE
    assert store.quarantined == 0 and store.steps() == [1]
    assert store.restore().step == 1             # healthy once fds free up

  def test_mangled_step_field_quarantined_with_fallback(self, rng, tmp_path):
    # JSON-valid manifest whose top-level "step" is gone (bit rot inside
    # the key name): must quarantine-and-fall-back, not KeyError.
    store = CheckpointStore(str(tmp_path))
    store.save(1, _tree(rng))
    store.save(2, _tree(np.random.default_rng(1)))
    mpath = os.path.join(store._step_dir(2), "manifest.json")
    with open(mpath) as fh:
      manifest = json.load(fh)
    manifest["step#"] = manifest.pop("step")
    with open(mpath, "w") as fh:
      json.dump(manifest, fh)
    events = []
    restored = store.restore(on_quarantine=lambda s, r: events.append((s, r)))
    assert restored.step == 1                    # fell back to last-good
    assert store.quarantined == 1 and "step invalid" in events[0][1]

  def test_step_directory_mismatch_quarantined(self, rng, tmp_path):
    # JSON-valid "step" that no longer matches the directory it lives in
    # (single flipped digit survives every per-array hash check): a
    # desynced Restored.step would truncate the wrong loss span on NaN
    # rollback and dodge the newest-is-bad quarantine.
    store = CheckpointStore(str(tmp_path))
    store.save(1, _tree(rng))
    store.save(2, _tree(np.random.default_rng(1)))
    mpath = os.path.join(store._step_dir(2), "manifest.json")
    with open(mpath) as fh:
      manifest = json.load(fh)
    manifest["step"] = 20
    with open(mpath, "w") as fh:
      json.dump(manifest, fh)
    events = []
    restored = store.restore(on_quarantine=lambda s, r: events.append((s, r)))
    assert restored.step == 1                    # fell back to last-good
    assert store.quarantined == 1
    assert "manifest step 20 != directory step 2" in events[0][1]

  def test_hash_mismatch_quarantined(self, rng, tmp_path):
    store = CheckpointStore(str(tmp_path))
    store.save(1, _tree(rng))
    mpath = os.path.join(store._step_dir(1), "manifest.json")
    with open(mpath) as fh:
      manifest = json.load(fh)
    key = next(iter(manifest["arrays"]))
    manifest["arrays"][key]["sha256"] = "0" * 64
    with open(mpath, "w") as fh:
      json.dump(manifest, fh)
    with pytest.raises(CorruptCheckpointError, match="hash mismatch"):
      store.restore(step=1)                      # explicit step: raises
    assert store.quarantined == 1

  def test_empty_store_restores_none(self, tmp_path):
    assert CheckpointStore(str(tmp_path)).restore() is None

  def test_crash_before_rename_leaves_no_checkpoint(self, rng, tmp_path):
    faults = TrainFaultSource().at_save(
        1, TrainFault("crash", stage="pre_rename"))
    store = CheckpointStore(str(tmp_path), fault_hook=faults.store_hook)
    store.save(0, _tree(rng))
    with pytest.raises(SimulatedCrash):
      store.save(1, _tree(rng))
    # The interrupted save must not have published; a NEW store (the
    # restarted process) sweeps any staging leftovers and restores 0.
    fresh = CheckpointStore(str(tmp_path))
    assert fresh.steps() == [0]
    assert not [n for n in os.listdir(str(tmp_path))
                if n.startswith(".tmp-")]
    assert fresh.restore().step == 0

  def test_corrupt_write_fault_quarantines_on_restore(self, rng, tmp_path):
    faults = TrainFaultSource().at_save(1, TrainFault("corrupt"))
    store = CheckpointStore(str(tmp_path), fault_hook=faults.store_hook)
    store.save(0, _tree(rng))
    store.save(1, _tree(rng))                    # published, then corrupted
    assert faults.injected["corrupt"] == 1
    fresh = CheckpointStore(str(tmp_path))
    restored = fresh.restore()
    assert restored.step == 0 and fresh.quarantined == 1

  def test_interrupted_same_step_replace_restores_aside(self, rng, tmp_path):
    """A kill between move-aside and publish during a same-step re-save
    must not lose the checkpoint: the init sweep restores the aside."""
    store = CheckpointStore(str(tmp_path))
    tree = _tree(rng)
    store.save(3, tree)
    # Simulate the mid-replace kill window: the published dir was moved
    # aside and the process died before the replacement's rename.
    # Our own pid: the sweep treats it as dead (a just-constructed store
    # cannot have its own in-flight save), which is the recovery path.
    os.rename(store._step_dir(3),
              os.path.join(str(tmp_path),
                           f".old-step_0000000003-{os.getpid()}-1"))
    fresh = CheckpointStore(str(tmp_path))
    assert fresh.steps() == [3]
    restored = fresh.restore(template=tree)
    assert restored.step == 3
    np.testing.assert_array_equal(
        restored.tree(tree)["params"]["w"], tree["params"]["w"])

  def test_clear_removes_published_keeps_quarantine(self, rng, tmp_path):
    store = CheckpointStore(str(tmp_path))
    store.save(1, _tree(rng))
    store.save(2, _tree(rng))
    store.quarantine(2, "evidence")
    assert store.clear() == [1]
    assert store.steps() == [] and store.restore() is None
    assert os.listdir(os.path.join(str(tmp_path), "quarantine"))

  def test_flatten_unflatten_identity(self, rng):
    tree = _tree(rng)
    out = unflatten_arrays(flatten_arrays(tree), tree)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), tree, out)


class TestGuards:

  def test_nan_guard_budget(self):
    guard = NanGuard(max_rollbacks=2)
    guard.note_rollback(3, float("nan"))
    guard.note_rollback(5, float("inf"))
    with pytest.raises(NonFiniteLossError, match="exhausted"):
      guard.note_rollback(7, float("nan"))
    assert guard.rollbacks == 2

  def test_watchdog_fires_once_per_episode(self):
    now = [0.0]
    fired = []
    dog = StallWatchdog(10.0, clock=lambda: now[0],
                        on_stall=fired.append)
    assert not dog.check()
    now[0] = 5.0
    dog.beat()
    now[0] = 14.0                                # 9 s idle: fine
    assert not dog.check()
    now[0] = 16.0                                # 11 s idle: stall
    assert dog.check() and fired == [11.0]
    now[0] = 30.0
    assert not dog.check()                       # same episode: no re-fire
    dog.beat()                                   # progress re-arms
    now[0] = 50.0
    assert dog.check()
    assert dog.stalls == 2

  def test_watchdog_suspended_holds_fire_past_timeout(self):
    # A checkpoint write longer than the timeout must not page: a beat
    # before the save would not survive it, so saves suspend the monitor.
    now = [0.0]
    fired = []
    dog = StallWatchdog(10.0, clock=lambda: now[0], on_stall=fired.append)
    with dog.suspended():
      now[0] = 40.0                              # 40 s "save": way past
      assert not dog.check() and not fired       # suspended: holds fire
    assert not dog.check()                       # exit re-armed the clock
    now[0] = 51.0                                # 11 s since the re-arm
    assert dog.check() and dog.stalls == 1       # real hangs still fire

  def test_watchdog_thread_start_stop(self):
    dog = StallWatchdog(0.01).start(poll_s=0.005)
    assert dog.running
    dog.stop()
    assert not dog.running

  def test_preemption_guard_signal_roundtrip(self):
    guard = PreemptionGuard(signals=(signal.SIGTERM,))
    before = signal.getsignal(signal.SIGTERM)
    with guard:
      assert not guard.requested.is_set()
      signal.raise_signal(signal.SIGTERM)        # handled, not fatal
      assert guard.requested.is_set()
    assert signal.getsignal(signal.SIGTERM) is before

  def test_poison_batch_only_floats(self):
    batch = {"x": np.ones((2, 2), np.float32), "i": np.arange(3)}
    bad = TrainFaultSource.poison_batch(batch)
    assert np.isnan(bad["x"]).all()
    np.testing.assert_array_equal(bad["i"], batch["i"])


# -- the crash-safe loop, in process --------------------------------------


def _batch(epoch: int, i: int):
  rng = np.random.default_rng([11, epoch, i])
  pose = np.eye(4, dtype=np.float32)
  pose[0, 3] = 0.04
  k = np.array([[8, 0, 8], [0, 8, 8], [0, 0, 1]], np.float32)
  return {
      "net_input": rng.uniform(
          -1, 1, (1, HW, HW, 3 + 3 * PLANES)).astype(np.float32),
      "ref_img": rng.uniform(-1, 1, (1, HW, HW, 3)).astype(np.float32),
      "tgt_img": rng.uniform(-1, 1, (1, HW, HW, 3)).astype(np.float32),
      "tgt_img_cfw": np.stack([pose]),
      "ref_img_wfc": np.stack([np.eye(4, dtype=np.float32)]),
      "intrinsics": np.stack([k]),
      "mpi_planes": np.linspace(1.0, 0.01, PLANES, dtype=np.float32),
  }


def _epoch(e):
  return [_batch(e, i) for i in range(4)]


@pytest.fixture(scope="module")
def tiny():
  """One tiny state + compiled step shared by the loop tests."""
  state = tloop.create_train_state(
      jax.random.PRNGKey(0), num_planes=PLANES, image_size=(HW, HW),
      norm=None, learning_rate=1e-3, mutable_lr=True)
  return state, tloop.make_train_step(vgg_params=None)


def _params_equal(a, b):
  jax.tree.map(lambda x, y: np.testing.assert_array_equal(
      np.asarray(x), np.asarray(y)), a, b)


class TestFitResumable:

  def test_mutable_lr_surgery(self, tiny):
    state, _ = tiny
    assert tloop.current_learning_rate(state) == pytest.approx(1e-3)
    cut = tloop.set_learning_rate(state, 5e-4)
    assert tloop.current_learning_rate(cut) == pytest.approx(5e-4)
    fixed = tloop.create_train_state(
        jax.random.PRNGKey(0), num_planes=PLANES, image_size=(HW, HW),
        norm=None)
    assert tloop.current_learning_rate(fixed) is None
    with pytest.raises(ValueError, match="mutable_lr"):
      tloop.set_learning_rate(fixed, 1e-4)

  def test_soft_crash_then_resume_is_bit_exact(self, tiny, tmp_path):
    state, step = tiny
    clean, r_clean = tloop.fit_resumable(
        state, 3, _epoch, CheckpointStore(str(tmp_path / "clean")),
        step=step, save_every=2, resume="never")
    assert r_clean["final_step"] == 12 and len(r_clean["losses"]) == 12

    faults = TrainFaultSource().at_step(7, TrainFault("crash"))
    store = CheckpointStore(str(tmp_path / "crash"),
                            fault_hook=faults.store_hook)
    with pytest.raises(SimulatedCrash):
      tloop.fit_resumable(state, 3, _epoch, store, step=step,
                          save_every=2, resume="never",
                          fault_source=faults)
    resumed, report = tloop.fit_resumable(
        state, 3, _epoch, CheckpointStore(str(tmp_path / "crash")),
        step=step, save_every=2, resume="auto")
    assert report["resumed_from"] == 6
    assert report["final_step"] == 12
    _params_equal(clean.params, resumed.params)
    _params_equal(clean.opt_state, resumed.opt_state)

  def test_resume_must_raises_on_empty_store(self, tiny, tmp_path):
    state, step = tiny
    with pytest.raises(FileNotFoundError, match="resume='must'"):
      tloop.fit_resumable(state, 1, _epoch,
                          CheckpointStore(str(tmp_path)), step=step,
                          resume="must")

  def test_nan_batch_rolls_back_and_cuts_lr(self, tiny, tmp_path):
    state, step = tiny
    faults = TrainFaultSource().at_step(5, TrainFault("nan"))
    guard = NanGuard(lr_cut=0.5)
    out, report = tloop.fit_resumable(
        state, 3, _epoch, CheckpointStore(str(tmp_path)), step=step,
        save_every=2, resume="never", fault_source=faults,
        nan_guard=guard)
    assert faults.injected["nan"] == 1
    assert report["nan_rollbacks"] == 1
    assert report["final_step"] == 12            # finished despite the NaN
    assert all(np.isfinite(report["losses"]))
    assert tloop.current_learning_rate(out) == pytest.approx(5e-4)

  def test_repeated_nan_compounds_the_lr_cut(self, tiny, tmp_path):
    """A second NaN during the replay must cut from the ALREADY-cut LR
    (the rollback save persists the cut), not retry the same LR."""
    state, step = tiny
    faults = (TrainFaultSource()
              .at_step(5, TrainFault("nan"))
              .at_step(6, TrainFault("nan")))
    guard = NanGuard(lr_cut=0.5, max_rollbacks=3)
    out, report = tloop.fit_resumable(
        state, 3, _epoch, CheckpointStore(str(tmp_path)), step=step,
        save_every=2, resume="never", fault_source=faults, nan_guard=guard)
    assert report["nan_rollbacks"] == 2
    assert report["final_step"] == 12
    assert tloop.current_learning_rate(out) == pytest.approx(2.5e-4)

  def test_nan_without_guard_fails_fast(self, tiny, tmp_path):
    state, step = tiny
    faults = TrainFaultSource().at_step(2, TrainFault("nan"))
    with pytest.raises(NonFiniteLossError):
      tloop.fit_resumable(state, 1, _epoch,
                          CheckpointStore(str(tmp_path)), step=step,
                          resume="never", fault_source=faults)

  def test_preempt_fault_saves_and_resume_completes(self, tiny, tmp_path):
    state, step = tiny
    clean, _ = tloop.fit_resumable(
        state, 3, _epoch, CheckpointStore(str(tmp_path / "clean")),
        step=step, resume="never")
    faults = TrainFaultSource().at_step(6, TrainFault("preempt"))
    store_dir = str(tmp_path / "pre")
    out, report = tloop.fit_resumable(
        state, 3, _epoch, CheckpointStore(store_dir), step=step,
        resume="never", fault_source=faults)
    assert report["preempted"] and report["final_step"] == 6
    resumed, r2 = tloop.fit_resumable(
        state, 3, _epoch, CheckpointStore(store_dir), step=step,
        resume="auto")
    assert r2["resumed_from"] == 6 and not r2["preempted"]
    _params_equal(clean.params, resumed.params)

  def test_corrupted_checkpoint_falls_back_and_stays_bit_exact(
      self, tiny, tmp_path):
    """The acceptance pin, in process: the newest checkpoint is corrupted
    by a scheduled corrupt-write fault; resume quarantines it, falls back
    to the previous good one, and still reaches the bit-identical end
    state (the replayed steps are deterministic)."""
    state, step = tiny
    clean, _ = tloop.fit_resumable(
        state, 3, _epoch, CheckpointStore(str(tmp_path / "clean")),
        step=step, save_every=2, resume="never")
    # Crash at step 7 AND corrupt the step-6 checkpoint (save index 3:
    # initial, step2, step4, step6 — the step-4 epoch-boundary save
    # dedupes into the periodic save on the same step).
    faults = (TrainFaultSource()
              .at_step(7, TrainFault("crash"))
              .at_save(3, TrainFault("corrupt")))
    store_dir = str(tmp_path / "crash")
    with pytest.raises(SimulatedCrash):
      tloop.fit_resumable(
          state, 3, _epoch,
          CheckpointStore(store_dir, fault_hook=faults.store_hook),
          step=step, save_every=2, resume="never", fault_source=faults)
    assert faults.injected["corrupt"] == 1
    store = CheckpointStore(store_dir)
    resumed, report = tloop.fit_resumable(
        state, 3, _epoch, store, step=step, save_every=2, resume="auto")
    assert report["quarantined"] == 1            # step 6 was quarantined
    assert report["resumed_from"] == 4           # previous good one
    assert report["final_step"] == 12
    _params_equal(clean.params, resumed.params)
    _params_equal(clean.opt_state, resumed.opt_state)
    assert os.path.isdir(os.path.join(store_dir, "quarantine"))

  def test_hang_fault_trips_watchdog(self, tiny, tmp_path):
    state, step = tiny
    fired = []
    faults = TrainFaultSource().at_step(2, TrainFault("hang", seconds=0.2))
    dog = StallWatchdog(0.05, on_stall=fired.append).start(poll_s=0.01)
    out, report = tloop.fit_resumable(
        state, 1, _epoch, CheckpointStore(str(tmp_path)), step=step,
        resume="never", fault_source=faults, watchdog=dog)
    assert report["final_step"] == 4             # hang delayed, not killed
    assert dog.stalls >= 1 and fired

  def test_slow_make_batches_does_not_trip_watchdog(self, tiny, tmp_path):
    # The first epoch's make_batches does the scene walk + dataset
    # build eagerly — host work between beats, bracketed like
    # checkpoint I/O rather than paged as a device hang.
    state, step = tiny
    fired = []

    def slow_epoch(e):
      time.sleep(0.2)
      return _epoch(e)

    dog = StallWatchdog(0.05, on_stall=fired.append).start(poll_s=0.01)
    out, report = tloop.fit_resumable(
        state, 1, slow_epoch, CheckpointStore(str(tmp_path)), step=step,
        resume="never", watchdog=dog)
    assert report["final_step"] == 4
    assert dog.stalls == 0 and not fired

  def test_slow_on_epoch_does_not_trip_watchdog(self, tiny, tmp_path):
    # The CLI hangs a validation pass off on_epoch; it runs between
    # beats, so a pass longer than the stall timeout must be bracketed
    # by the same suspension as checkpoint I/O — not paged as a hang.
    state, step = tiny
    fired = []
    dog = StallWatchdog(0.05, on_stall=fired.append).start(poll_s=0.01)
    out, report = tloop.fit_resumable(
        state, 1, _epoch, CheckpointStore(str(tmp_path)), step=step,
        resume="never", watchdog=dog,
        on_epoch=lambda *a: time.sleep(0.2))
    assert report["final_step"] == 4
    assert dog.stalls == 0 and not fired


class TestBackgroundSaver:
  """ckpt/background.py: background-thread serialization that the step
  loop never waits on — byte-identical publishes, surfaced failures,
  flush-first reads, and the bit-exact fit_resumable contract intact."""

  def test_publishes_byte_identical_checkpoint(self, rng, tmp_path):
    tree = _tree(rng)
    sync = CheckpointStore(str(tmp_path / "sync"))
    sync.save(7, tree, meta={"cursor": {"epoch": 1, "batch": 2}})
    bg = BackgroundSaver(CheckpointStore(str(tmp_path / "bg")))
    bg.save(7, tree, meta={"cursor": {"epoch": 1, "batch": 2}})
    bg.flush()
    a = sync.restore(template=tree)
    b = bg.restore(template=tree)
    assert b.step == 7 and b.meta == a.meta
    # Identical content hashes: the background path serializes the same
    # bytes the synchronous path does.
    assert ({k: v["sha256"] for k, v in a.manifest["arrays"].items()}
            == {k: v["sha256"] for k, v in b.manifest["arrays"].items()})
    assert bg.saves == 1

  def test_latest_step_counts_pending_save(self, rng, tmp_path):
    import threading

    store = CheckpointStore(str(tmp_path))
    gate = threading.Event()
    real_save = store.save
    store.save = lambda *a, **kw: (gate.wait(30), real_save(*a, **kw))[1]
    bg = BackgroundSaver(store)
    bg.save(5, _tree(rng))
    # The save is still in flight (gated) but the dedupe check must see
    # it — fit_resumable's epoch boundary would double-save otherwise.
    assert bg.latest_step() == 5
    assert store.latest_step() is None
    gate.set()
    bg.flush()
    assert store.latest_step() == 5

  def test_failed_save_surfaces_at_next_touch(self, rng, tmp_path):
    store = CheckpointStore(str(tmp_path))
    store.save = lambda *a, **kw: (_ for _ in ()).throw(
        RuntimeError("disk full"))
    bg = BackgroundSaver(store)
    bg.save(1, _tree(rng))
    with pytest.raises(RuntimeError, match="disk full"):
      bg.flush()
    # The parked error is consumed: the saver is reusable afterwards.
    bg.flush()

  def test_reads_flush_first(self, rng, tmp_path):
    import threading

    store = CheckpointStore(str(tmp_path))
    gate = threading.Event()
    real_save = store.save
    store.save = lambda *a, **kw: (gate.wait(30), real_save(*a, **kw))[1]
    bg = BackgroundSaver(store)
    tree = _tree(rng)
    bg.save(3, tree)
    threading.Timer(0.1, gate.set).start()
    # restore() must block for the in-flight save — a rollback has to be
    # able to land on the checkpoint that was mid-write.
    restored = bg.restore(template=tree)
    assert restored is not None and restored.step == 3

  def test_fit_resumable_with_background_saver_is_bit_exact(
      self, tiny, tmp_path):
    state, step = tiny
    clean, r_clean = tloop.fit_resumable(
        state, 2, _epoch, CheckpointStore(str(tmp_path / "sync")),
        step=step, save_every=2, resume="never")
    bg = BackgroundSaver(CheckpointStore(str(tmp_path / "bg")))
    out, report = tloop.fit_resumable(
        state, 2, _epoch, bg, step=step, save_every=2, resume="never")
    _params_equal(clean.params, out.params)
    assert report["losses"] == r_clean["losses"]
    assert report["saves"] == r_clean["saves"]
    # The loop's exit flush published everything: both stores hold the
    # same final step.
    assert (CheckpointStore(str(tmp_path / "bg")).latest_step()
            == CheckpointStore(str(tmp_path / "sync")).latest_step())


class TestSkipAheadResume:
  """The skip-ahead data-cursor restore: a make_batches that accepts
  ``skip`` seeks straight to the cursor, bit-exact against both the
  replay path and the uninterrupted run."""

  def test_skip_ahead_resume_matches_replay_and_clean(self, tiny, tmp_path):
    state, step = tiny
    clean, _ = tloop.fit_resumable(
        state, 3, _epoch, CheckpointStore(str(tmp_path / "clean")),
        step=step, save_every=2, resume="never")

    def crash_then_resume(root, make_batches):
      faults = TrainFaultSource().at_step(7, TrainFault("crash"))
      store = CheckpointStore(str(root), fault_hook=faults.store_hook)
      with pytest.raises(SimulatedCrash):
        tloop.fit_resumable(state, 3, make_batches, store, step=step,
                            save_every=2, resume="never",
                            fault_source=faults)
      return tloop.fit_resumable(
          state, 3, make_batches, CheckpointStore(str(root)), step=step,
          save_every=2, resume="auto")

    skip_calls = []

    def epoch_with_skip(e, skip=0):
      skip_calls.append((e, skip))
      return _epoch(e)[skip:]

    replayed, r_replay = crash_then_resume(tmp_path / "replay", _epoch)
    skipped, r_skip = crash_then_resume(tmp_path / "skip", epoch_with_skip)
    assert r_replay["resumed_from"] == r_skip["resumed_from"] == 6
    # The seek really happened: the resumed epoch was requested with a
    # non-zero cursor skip.
    assert any(s > 0 for _, s in skip_calls)
    _params_equal(clean.params, replayed.params)
    _params_equal(clean.params, skipped.params)
    _params_equal(replayed.opt_state, skipped.opt_state)

  def test_kwargs_only_callables_route_to_replay(self, tiny, tmp_path):
    # A bare **kwargs would swallow ``skip`` without seeking — the loop
    # must treat it as skip-incapable and replay instead.
    state, step = tiny
    calls = []

    def sneaky(e, **kwargs):
      calls.append(kwargs)
      return _epoch(e)

    faults = TrainFaultSource().at_step(5, TrainFault("preempt"))
    store = CheckpointStore(str(tmp_path))
    tloop.fit_resumable(state, 2, sneaky, store, step=step,
                        resume="never", fault_source=faults)
    out, report = tloop.fit_resumable(
        state, 2, sneaky, CheckpointStore(str(tmp_path)), step=step,
        resume="auto")
    assert all(kw == {} for kw in calls)  # never called with skip=
    clean, _ = tloop.fit_resumable(
        state, 2, _epoch, CheckpointStore(str(tmp_path / "clean")),
        step=step, resume="never")
    _params_equal(clean.params, out.params)


# -- checkpoint -> serve bridge -------------------------------------------


class TestCkptToServe:

  @pytest.fixture(scope="class")
  def trained_store(self, tmp_path_factory):
    root = str(tmp_path_factory.mktemp("ckpt_serve"))
    state = tloop.create_train_state(
        jax.random.PRNGKey(0), num_planes=PLANES, image_size=(HW, HW),
        norm=None, learning_rate=1e-3, mutable_lr=True)
    step = tloop.make_train_step(vgg_params=None)
    meta = {"model": {"num_planes": PLANES, "img_size": HW, "norm": None,
                      "compute_dtype": None, "depth_near": 1.0,
                      "depth_far": 100.0}}
    state, _ = tloop.fit_resumable(
        state, 1, _epoch, CheckpointStore(root), step=step,
        resume="never", meta=meta)
    return root, state

  def test_scenes_from_checkpoint(self, trained_store):
    from mpi_vision_tpu.ckpt.export import scenes_from_checkpoint

    root, state = trained_store
    scenes, info = scenes_from_checkpoint(root, scenes=2)
    assert len(scenes) == 2 and info["step"] == 4
    ids = set()
    for sid, rgba, depths, k in scenes:
      ids.add(sid)
      assert rgba.shape == (HW, HW, PLANES, 4)
      assert depths.shape == (PLANES,) and k.shape == (3, 3)
      assert np.isfinite(rgba).all()
      assert info["params_digest"][:8] in sid    # version-addressed ids
    assert len(ids) == 2

  def test_scenes_from_checkpoint_stable_ids_for_live_reload(
      self, trained_store):
    from mpi_vision_tpu.ckpt.export import scenes_from_checkpoint

    root, _ = trained_store
    scenes, info = scenes_from_checkpoint(root, scenes=2, stable_ids=True)
    # Live reload swaps scenes IN PLACE: ids must be step-independent so
    # a later checkpoint's bake lands under the ids clients already hold.
    assert [sid for sid, *_ in scenes] == ["ckpt_000", "ckpt_001"]
    assert all(info["params_digest"][:8] not in sid
               for sid, *_ in scenes)

  def test_restored_params_match_trained(self, trained_store):
    from mpi_vision_tpu.ckpt.export import restore_params

    root, state = trained_store
    restored, meta, step = restore_params(root)
    assert step == 4 and meta["num_planes"] == PLANES
    _params_equal(state.params, restored.params)

  def test_render_service_serves_ckpt_scenes(self, trained_store):
    from mpi_vision_tpu.ckpt.export import scenes_from_checkpoint
    from mpi_vision_tpu.serve import RenderService

    root, _ = trained_store
    scenes, _ = scenes_from_checkpoint(root, scenes=1)
    with RenderService(max_batch=2, max_wait_ms=0.5,
                       resilience=None) as svc:
      for sid, rgba, depths, k in scenes:
        svc.add_scene(sid, rgba, depths, k)
      img = svc.render(scenes[0][0], np.eye(4, dtype=np.float32))
      assert img.shape == (HW, HW, 3) and np.isfinite(img).all()
      assert svc.cache.stats()["misses"] == 1

  def test_missing_checkpoint_raises(self, tmp_path):
    from mpi_vision_tpu.ckpt.export import restore_params

    with pytest.raises(FileNotFoundError, match="no restorable"):
      restore_params(str(tmp_path))
