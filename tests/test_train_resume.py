"""The kill-and-resume acceptance pin (tier-1, CPU, real SIGKILL).

``bench/train_resume.py`` trains a tiny model through the ckpt/
lifecycle in a SUBPROCESS and dies by actual SIGKILL (the fault source
kills its own process) mid-epoch — no atexit, no finally, exactly what
a preempted VM does. The pins:

  * resume from the newest manifest reproduces the uninterrupted run's
    final checkpoint BIT-IDENTICALLY (digest over params + optimizer
    state + step, read back from disk);
  * a checkpoint corrupted by the scheduled corrupt-write fault is
    quarantined on resume, the run falls back to the previous good one,
    and STILL lands on the bit-identical digest;
  * the one-process ``--selftest`` (SimulatedCrash variant) agrees.

All victim runs share one env (CPU platform, tunneled backends
neutralized) so their digests are comparable; the independent first
wave runs concurrently to keep the tier-1 bill down.
"""

import json
import os
import signal
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "bench", "train_resume.py")

# Tiny run shape: 3 epochs x 4 batches, save every 2 steps. Step 7 is
# mid-epoch-1 (between the step-6 and step-8 saves); save index 3 is the
# step-6 checkpoint (initial, 2, 4, 6 — the epoch-boundary saves dedupe
# into the periodic saves that land on the same steps).
COMMON = ["--epochs", "3", "--batches", "4", "--save-every", "2",
          "--seed", "0"]
CRASH_STEP = "7"
CORRUPT_SAVE = "3"


def _env():
  env = dict(os.environ)
  env["JAX_PLATFORMS"] = "cpu"
  env.pop("PALLAS_AXON_POOL_IPS", None)
  return env


def _spawn(*args):
  return subprocess.Popen(
      [sys.executable, SCRIPT, *COMMON, *args],
      stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
      env=_env(), cwd=REPO)


def _finish(proc, timeout=600):
  out, err = proc.communicate(timeout=timeout)
  return proc.returncode, out, err


def _json_line(out: str, err: str) -> dict:
  lines = [ln for ln in out.strip().splitlines() if ln.startswith("{")]
  assert lines, f"no JSON line:\nstdout={out!r}\nstderr={err[-2000:]}"
  return json.loads(lines[-1])


def test_sigkill_midepoch_resume_is_bit_exact(tmp_path):
  """SIGKILL at step 7 of 12 (mid-epoch), resume, compare digests —
  plus the corrupted-checkpoint fallback variant, in one pass."""
  base_dir = str(tmp_path / "baseline")
  kill_dir = str(tmp_path / "killed")
  rot_dir = str(tmp_path / "killed_corrupt")

  # Wave 1 — three independent runs, concurrently: the uninterrupted
  # baseline, a SIGKILL victim, and a SIGKILL victim whose newest
  # pre-crash checkpoint (step 6) gets corrupted by the fault injector.
  baseline = _spawn("--dir", base_dir, "--fresh")
  killed = _spawn("--dir", kill_dir, "--fresh", "--crash-at", CRASH_STEP)
  rotted = _spawn("--dir", rot_dir, "--fresh", "--crash-at", CRASH_STEP,
                  "--corrupt-save", CORRUPT_SAVE)
  rc_base, out_base, err_base = _finish(baseline)
  rc_kill, _, err_kill = _finish(killed)
  rc_rot, _, err_rot = _finish(rotted)

  assert rc_base == 0, err_base[-2000:]
  base = _json_line(out_base, err_base)
  assert base["value"] == 12 and base["digest"]

  # A hard kill: the process must have died by SIGKILL, printing nothing.
  assert rc_kill == -signal.SIGKILL, (rc_kill, err_kill[-2000:])
  assert rc_rot == -signal.SIGKILL, (rc_rot, err_rot[-2000:])
  # ... and left a published checkpoint behind (atomic saves survived).
  assert any(n.startswith("step_") for n in os.listdir(kill_dir))

  # Wave 2 — resume both victims.
  res_kill = _spawn("--dir", kill_dir)
  res_rot = _spawn("--dir", rot_dir)
  rc1, out1, err1 = _finish(res_kill)
  rc2, out2, err2 = _finish(res_rot)
  assert rc1 == 0, err1[-2000:]
  assert rc2 == 0, err2[-2000:]
  resumed = _json_line(out1, err1)
  rot = _json_line(out2, err2)

  # Clean kill: resumed from the newest save (step 6), bit-identical end.
  assert resumed["resumed_from"] == 6
  assert resumed["value"] == 12
  assert resumed["digest"] == base["digest"], (
      "SIGKILL-then-resume diverged from the uninterrupted run")

  # Corrupted newest checkpoint: quarantined, fell back to step 4,
  # STILL bit-identical (replayed steps are deterministic).
  assert rot["quarantined"] == 1
  assert rot["resumed_from"] == 4
  assert rot["digest"] == base["digest"], (
      "corrupt-fallback resume diverged from the uninterrupted run")
  assert os.path.isdir(os.path.join(rot_dir, "quarantine"))


def test_train_resume_selftest_smoke(tmp_path):
  """The one-process --selftest (SimulatedCrash + resume) stays green —
  the cheap canary that fails first if the resume contract breaks."""
  proc = _spawn("--selftest")
  rc, out, err = _finish(proc)
  assert rc == 0, err[-2000:]
  res = _json_line(out, err)
  assert res["metric"] == "train_resume_selftest" and res["value"] == 1
  assert res["bit_exact"] is True and res["resumed_from"] == 6
