"""Plane-sweep / projection-path parity vs the torch oracle (BASELINE config #3 analog)."""

import jax.numpy as jnp
import numpy as np
import torch

from mpi_vision_tpu.core import camera, sweep
from mpi_vision_tpu.torchref import oracle


def _setup(rng, b=1, h=20, w=20):
  img = rng.uniform(-1, 1, (b, h, w, 3)).astype(np.float32)
  angle = 0.04
  pose = np.eye(4, dtype=np.float32)
  pose[:3, :3] = np.array([[1, 0, 0],
                           [0, np.cos(angle), -np.sin(angle)],
                           [0, np.sin(angle), np.cos(angle)]], np.float32)
  pose[:3, 3] = [0.02, 0.01, -0.05]
  pose = np.broadcast_to(pose, (b, 4, 4)).copy()
  k = np.array([[0.9 * w, 0, w / 2], [0, 0.9 * h, h / 2], [0, 0, 1]], np.float32)
  k = np.broadcast_to(k, (b, 3, 3)).copy()
  return img, pose, k


def test_pixel2cam_cam2pixel_parity(rng):
  img, pose, k = _setup(rng)
  b, h, w, _ = img.shape
  depth = rng.uniform(1, 10, (b, h, w)).astype(np.float32)
  grid_j = jnp.broadcast_to(
      jnp.moveaxis(jnp.stack(jnp.meshgrid(
          jnp.arange(w, dtype=jnp.float32),
          jnp.arange(h, dtype=jnp.float32), indexing="xy") +
          [jnp.ones((h, w))], 0), 0, 0), (b, 3, h, w))
  cam_j = sweep.pixel2cam(jnp.asarray(depth), grid_j, jnp.asarray(k))
  cam_t = oracle.pixel2cam(torch.tensor(depth),
                           oracle.meshgrid_abs(b, h, w), torch.tensor(k))
  np.testing.assert_allclose(np.asarray(cam_j), cam_t.numpy(), rtol=1e-5, atol=1e-4)

  proj = np.asarray(
      jnp.matmul(jnp.asarray(
          np.concatenate([np.concatenate([k, np.zeros((b, 3, 1), np.float32)], 2),
                          np.tile(np.array([[[0, 0, 0, 1]]], np.float32), (b, 1, 1))], 1)),
          jnp.asarray(pose)))
  pix_j = sweep.cam2pixel(cam_j, jnp.asarray(proj))
  pix_t = oracle.cam2pixel(cam_t, torch.tensor(proj))
  np.testing.assert_allclose(np.asarray(pix_j), pix_t.numpy(), rtol=1e-4, atol=1e-3)


def test_inverse_warp_parity(rng):
  img, pose, k = _setup(rng)
  depth = np.full(img.shape[:3], 3.0, np.float32)
  got = np.asarray(sweep.projective_inverse_warp(
      jnp.asarray(img), jnp.asarray(depth), jnp.asarray(pose), jnp.asarray(k)))
  want = oracle.projective_inverse_warp(
      torch.tensor(img), torch.tensor(depth), torch.tensor(pose),
      torch.tensor(k)).numpy()
  np.testing.assert_allclose(got, want, atol=1e-4, rtol=0)
  assert np.abs(got - want).mean() < 1e-5


def test_identity_warp_exact(rng):
  # Identity pose + EXACT convention: warp reproduces the image bit-near.
  img, _, k = _setup(rng)
  pose = np.broadcast_to(np.eye(4, dtype=np.float32), (1, 4, 4)).copy()
  depth = np.full(img.shape[:3], 5.0, np.float32)
  from mpi_vision_tpu.core.sampling import Convention
  out = np.asarray(sweep.projective_inverse_warp(
      jnp.asarray(img), jnp.asarray(depth), jnp.asarray(pose), jnp.asarray(k),
      convention=Convention.EXACT))
  np.testing.assert_allclose(out, img, atol=1e-4)


def test_plane_sweep_parity(rng):
  img, pose, k = _setup(rng, h=16, w=16)
  depths = np.asarray(camera.inv_depths(1.0, 100.0, 6), np.float32)
  got = np.asarray(sweep.plane_sweep(
      jnp.asarray(img), jnp.asarray(depths), jnp.asarray(pose), jnp.asarray(k)))
  want = oracle.plane_sweep(
      torch.tensor(img), torch.tensor(depths), torch.tensor(pose),
      torch.tensor(k)).numpy()
  assert got.shape == want.shape  # [B, H, W, 3P], plane-major channels
  np.testing.assert_allclose(got, want, atol=1e-4, rtol=0)


def test_plane_sweep_stacked_layout(rng):
  img, pose, k = _setup(rng, h=12, w=12)
  depths = np.asarray(camera.inv_depths(1.0, 50.0, 4), np.float32)
  flat = np.asarray(sweep.plane_sweep(
      jnp.asarray(img), jnp.asarray(depths), jnp.asarray(pose), jnp.asarray(k)))
  stack = np.asarray(sweep.plane_sweep(
      jnp.asarray(img), jnp.asarray(depths), jnp.asarray(pose), jnp.asarray(k),
      stacked=True))
  b, h, w, _ = flat.shape
  np.testing.assert_allclose(
      flat.reshape(b, h, w, 4, 3), np.moveaxis(stack, 0, 3), atol=0)


def test_plane_sweep_one(rng):
  img, pose, k = _setup(rng, h=10, w=10)
  depths = np.asarray(camera.inv_depths(1.0, 20.0, 3), np.float32)
  batched = np.asarray(sweep.plane_sweep(
      jnp.asarray(img), jnp.asarray(depths), jnp.asarray(pose), jnp.asarray(k)))
  one = np.asarray(sweep.plane_sweep_one(
      jnp.asarray(img[0]), jnp.asarray(depths), jnp.asarray(pose[0]),
      jnp.asarray(k[0])))
  np.testing.assert_allclose(one, batched, atol=0)
