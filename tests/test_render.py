"""End-to-end MPI render parity vs the torch oracle (BASELINE config #1 analog)."""

import jax.numpy as jnp
import numpy as np
import torch

from mpi_vision_tpu.core import camera, render
from mpi_vision_tpu.core.sampling import Convention
from mpi_vision_tpu.torchref import oracle

L1_BUDGET = 1e-3  # per-pixel, from BASELINE.json


def _setup(rng, b=1, h=24, w=24, p=8):
  rgba = rng.uniform(0, 1, (b, h, w, p, 4)).astype(np.float32)
  depths = np.asarray(camera.inv_depths(1.0, 100.0, p), np.float32)
  # Mild novel-view pose: small rotation about y + translation.
  angle = 0.05
  rot = np.array([[np.cos(angle), 0, np.sin(angle)],
                  [0, 1, 0],
                  [-np.sin(angle), 0, np.cos(angle)]], np.float32)
  pose = np.eye(4, dtype=np.float32)
  pose[:3, :3] = rot
  pose[:3, 3] = [0.05, -0.02, 0.03]
  pose = np.broadcast_to(pose, (b, 4, 4)).copy()
  k = np.array([[0.8 * w, 0, w / 2], [0, 0.8 * w, h / 2], [0, 0, 1]], np.float32)
  k = np.broadcast_to(k, (b, 3, 3)).copy()
  return rgba, pose, depths, k


def _oracle_render(rgba, pose, depths, k):
  return oracle.render_mpi(
      torch.tensor(rgba), torch.tensor(pose), torch.tensor(depths),
      torch.tensor(k)).numpy()


def test_fused_render_parity(rng):
  rgba, pose, depths, k = _setup(rng)
  got = np.asarray(render.render_mpi(
      jnp.asarray(rgba), jnp.asarray(pose), jnp.asarray(depths), jnp.asarray(k)))
  want = _oracle_render(rgba, pose, depths, k)
  assert np.abs(got - want).mean() < L1_BUDGET
  np.testing.assert_allclose(got, want, atol=1e-4, rtol=0)


def test_methods_agree(rng):
  rgba, pose, depths, k = _setup(rng, h=16, w=16, p=5)
  args = (jnp.asarray(rgba), jnp.asarray(pose), jnp.asarray(depths), jnp.asarray(k))
  outs = [np.asarray(render.render_mpi(*args, method=m))
          for m in ("fused", "scan", "assoc")]
  np.testing.assert_allclose(outs[0], outs[1], atol=1e-5)
  np.testing.assert_allclose(outs[0], outs[2], atol=1e-5)


def test_identity_pose_identity_render(rng):
  # Rendering from the MPI's own camera must reproduce the composite in place.
  rgba, _, depths, k = _setup(rng, h=20, w=20, p=6)
  pose = np.broadcast_to(np.eye(4, dtype=np.float32), (1, 4, 4)).copy()
  got = np.asarray(render.render_mpi(
      jnp.asarray(rgba), jnp.asarray(pose), jnp.asarray(depths), jnp.asarray(k),
      convention=Convention.EXACT))
  from mpi_vision_tpu.core import compose
  want = np.asarray(compose.over_composite(
      jnp.asarray(np.moveaxis(rgba, 3, 0))))
  np.testing.assert_allclose(got, want, atol=1e-4)


def test_planes_leading_layout(rng):
  rgba, pose, depths, k = _setup(rng, h=12, w=12, p=4)
  a = np.asarray(render.render_mpi(
      jnp.asarray(rgba), jnp.asarray(pose), jnp.asarray(depths), jnp.asarray(k)))
  b = np.asarray(render.render_mpi(
      jnp.asarray(np.moveaxis(rgba, 3, 0)), jnp.asarray(pose),
      jnp.asarray(depths), jnp.asarray(k), planes_leading=True))
  np.testing.assert_allclose(a, b, atol=1e-6)


def test_render_jit_and_grad(rng):
  import jax

  rgba, pose, depths, k = _setup(rng, h=10, w=10, p=3)

  @jax.jit
  def loss(x):
    out = render.render_mpi(x, jnp.asarray(pose), jnp.asarray(depths),
                            jnp.asarray(k))
    return jnp.mean(out ** 2)

  g = jax.grad(loss)(jnp.asarray(rgba))
  assert g.shape == rgba.shape
  assert np.isfinite(np.asarray(g)).all()
