"""Camera/intrinsics utilities: parity with reference formulas + round trips."""

import jax.numpy as jnp
import numpy as np

from mpi_vision_tpu.core import camera


def _reference_inv_depths(start, end, num):
  # Literal restatement of the reference algorithm (utils.py:297-318).
  inv_s, inv_e = 1.0 / start, 1.0 / end
  depths = [start, end]
  for i in range(1, num - 1):
    frac = float(i) / float(num - 1)
    depths.append(1.0 / (inv_s + (inv_e - inv_s) * frac))
  return sorted(depths)[::-1]


def test_inv_depths_matches_reference():
  for num in (2, 3, 10, 33):
    got = np.asarray(camera.inv_depths(1.0, 100.0, num))
    want = np.array(_reference_inv_depths(1.0, 100.0, num), np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # Descending (far -> near), endpoints included.
    assert got[0] == 100.0 and got[-1] == 1.0
    assert (np.diff(got) < 0).all()


def test_intrinsics_matrix():
  k = np.asarray(camera.intrinsics_matrix(100.0, 110.0, 32.0, 24.0))
  np.testing.assert_allclose(
      k, [[100, 0, 32], [0, 110, 24], [0, 0, 1]])


def test_intrinsics_matrix_batched():
  k = np.asarray(camera.intrinsics_matrix(
      jnp.array([1.0, 2.0]), jnp.array([3.0, 4.0]),
      jnp.array([5.0, 6.0]), jnp.array([7.0, 8.0])))
  assert k.shape == (2, 3, 3)
  np.testing.assert_allclose(k[1], [[2, 0, 6], [0, 4, 8], [0, 0, 1]])


def test_scale_intrinsics():
  k = camera.intrinsics_matrix(0.5, 0.6, 0.5, 0.5)  # normalized
  scaled = np.asarray(camera.scale_intrinsics(k, 224, 224))
  np.testing.assert_allclose(
      scaled, [[112, 0, 112], [0, 134.4, 112], [0, 0, 1]], rtol=1e-6)


def test_preprocess_roundtrip(rng):
  img01 = rng.uniform(0, 1, (4, 4, 3)).astype(np.float32)
  pre = camera.preprocess_image(jnp.asarray(img01))
  assert np.asarray(pre).min() >= -1 and np.asarray(pre).max() <= 1
  post = np.asarray(camera.deprocess_image(pre))
  assert post.dtype == np.uint8
  np.testing.assert_allclose(post, (img01 * 255).astype(np.uint8), atol=1)


def test_crop_to_bounding_box(rng):
  img = rng.uniform(0, 1, (1, 16, 16, 3)).astype(np.float32)
  crop = np.asarray(camera.crop_to_bounding_box(jnp.asarray(img), 2, 3, 8, 8))
  # Differentiable crop at integer offsets == plain slicing.
  np.testing.assert_allclose(crop, img[:, 2:10, 3:11], atol=1e-5)


def test_crop_adjust_intrinsics(rng):
  img = rng.uniform(0, 1, (1, 16, 16, 3)).astype(np.float32)
  k = camera.intrinsics_matrix(0.5, 0.5, 0.5, 0.5)
  cropped, k2 = camera.crop_image_and_adjust_intrinsics(
      jnp.asarray(img), k, 4, 4, 8, 8)
  assert cropped.shape == (1, 8, 8, 3)
  # Center of crop (pixels 4..11) => cx in pixels = 8*0.5... check principal
  # point shifted: pixel cx was 8, minus offset 4 => 4, normalized /8 => 0.5.
  k2 = np.asarray(k2)
  np.testing.assert_allclose(k2[0, 2], 0.5, rtol=1e-6)
  np.testing.assert_allclose(k2[1, 2], 0.5, rtol=1e-6)
  np.testing.assert_allclose(k2[0, 0], 1.0, rtol=1e-6)  # fx 0.5*16/8
