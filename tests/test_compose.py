"""Over-composite: scan vs associative-scan vs torch oracle + properties."""

import jax.numpy as jnp
import numpy as np
import torch

from mpi_vision_tpu.core import compose
from mpi_vision_tpu.torchref import oracle


def _random_mpi(rng, p=6, b=2, h=5, w=7):
  rgba = rng.uniform(0, 1, (p, b, h, w, 4)).astype(np.float32)
  return rgba


def test_scan_matches_oracle(rng):
  rgba = _random_mpi(rng)
  got = np.asarray(compose.over_composite(jnp.asarray(rgba), method="scan"))
  want = oracle.over_composite(torch.tensor(rgba)).numpy()
  np.testing.assert_allclose(got, want, atol=1e-6)


def test_assoc_matches_scan(rng):
  rgba = _random_mpi(rng, p=9)
  a = np.asarray(compose.over_composite(jnp.asarray(rgba), method="scan"))
  b = np.asarray(compose.over_composite(jnp.asarray(rgba), method="assoc"))
  np.testing.assert_allclose(a, b, atol=1e-5)


def test_first_plane_alpha_ignored(rng):
  rgba = _random_mpi(rng)
  rgba2 = rgba.copy()
  rgba2[0, ..., 3] = 0.123  # must not matter
  a = np.asarray(compose.over_composite(jnp.asarray(rgba)))
  b = np.asarray(compose.over_composite(jnp.asarray(rgba2)))
  np.testing.assert_allclose(a, b)


def test_opaque_front_plane_wins(rng):
  rgba = _random_mpi(rng)
  rgba[-1, ..., 3] = 1.0
  out = np.asarray(compose.over_composite(jnp.asarray(rgba)))
  np.testing.assert_allclose(out, rgba[-1, ..., :3], atol=1e-6)


def test_transparent_planes_passthrough(rng):
  rgba = _random_mpi(rng)
  rgba[1:, ..., 3] = 0.0
  out = np.asarray(compose.over_composite(jnp.asarray(rgba)))
  np.testing.assert_allclose(out, rgba[0, ..., :3], atol=1e-6)


def test_single_plane(rng):
  rgba = _random_mpi(rng, p=1)
  out = np.asarray(compose.over_composite(jnp.asarray(rgba)))
  np.testing.assert_allclose(out, rgba[0, ..., :3])


def test_affine_combine_associative(rng):
  rgba = jnp.asarray(_random_mpi(rng, p=4, b=1, h=2, w=2))
  a, b = compose.plane_affine(rgba)
  e = [(a[i], b[i]) for i in range(4)]
  left = compose.combine_affine(compose.combine_affine(e[0], e[1]),
                                compose.combine_affine(e[2], e[3]))
  right = compose.combine_affine(
      e[0], compose.combine_affine(e[1], compose.combine_affine(e[2], e[3])))
  np.testing.assert_allclose(np.asarray(left[0]), np.asarray(right[0]), atol=1e-6)
  np.testing.assert_allclose(np.asarray(left[1]), np.asarray(right[1]), atol=1e-6)
