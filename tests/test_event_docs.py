"""Event-kind drift self-check: README's event table vs reality.

Every literal kind any `events.emit("...")` call site in the package
can produce must have a row in README's event-kinds table (the block
between the `<!-- event-kinds -->` markers), and vice versa: a kind
documented there that no call site emits is a doc for an event that
does not exist. Either direction failing means the event reference
rotted silently — the same tier-1 pin as `test_metric_docs.py`, for
the other operator-facing vocabulary.

Only the first (kind) column counts: the payload column is full of
backticked FIELD names (`backend`, `old`, `step`) that are not kinds.
Kinds built dynamically (none today) would need a literal mention in
source or an explicit allowlist here — by design, so "grep the repo
for the kind you saw in /debug/events" always lands on the emitter.
"""

import pathlib
import re

ROOT = pathlib.Path(__file__).parent.parent
README = ROOT / "README.md"
PACKAGE = ROOT / "mpi_vision_tpu"

_EMIT = re.compile(r'\.emit\(\s*"([a-z_]+)"')
_SECTION = re.compile(r"<!-- event-kinds -->(.*?)<!-- /event-kinds -->",
                      re.DOTALL)
_KIND = re.compile(r"`([a-z_]+)`")


def _emitted_kinds() -> set[str]:
  kinds: set[str] = set()
  for path in sorted(PACKAGE.rglob("*.py")):
    kinds.update(_EMIT.findall(path.read_text()))
  return kinds


def _documented_kinds() -> set[str]:
  section = _SECTION.search(README.read_text())
  assert section, "README lost its <!-- event-kinds --> table markers"
  kinds: set[str] = set()
  for line in section.group(1).splitlines():
    if not line.startswith("|"):
      continue
    cells = line.split("|")
    first = cells[1] if len(cells) > 1 else ""
    if "---" in first or first.strip() == "kind":
      continue
    kinds.update(_KIND.findall(first))
  return kinds


def test_every_emitted_kind_is_documented():
  missing = _emitted_kinds() - _documented_kinds()
  assert not missing, (
      "event kinds emitted in source but absent from README's "
      f"event-kinds table: {sorted(missing)}")


def test_every_documented_kind_is_emitted():
  phantom = _documented_kinds() - _emitted_kinds()
  assert not phantom, (
      "README documents event kinds no call site emits "
      f"(doc rot or a typo): {sorted(phantom)}")


def test_scans_actually_find_kinds():
  # Both scans must really extract names — an empty-vs-empty pass would
  # be meaningless — and the doc scan must not leak payload fields.
  emitted = _emitted_kinds()
  assert "slo_alert" in emitted and "incident_captured" in emitted
  assert len(emitted) > 25
  documented = _documented_kinds()
  assert "breaker" in documented
  # Payload-column fields must not count as kinds.
  assert "backend" not in documented and "old" not in documented
