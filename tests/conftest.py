"""Test harness config: run JAX on a virtual 8-device CPU mesh.

The hardening itself (env vars + tunnelled-backend neutralization) lives in
the repo-root ``_cpu_mesh`` module, shared with ``__graft_entry__``'s
multichip dryrun so the two cannot drift. Must run before the first device
use, hence the call at conftest import time.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _cpu_mesh import force_cpu_mesh  # noqa: E402

force_cpu_mesh(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
  return np.random.default_rng(0)
