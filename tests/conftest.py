"""Test harness config: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; the standard JAX substitute is
`--xla_force_host_platform_device_count` (SURVEY.md §4d). Must run before the
first `import jax`, hence env mutation at conftest import time.
"""

import os

_FLAG = "--xla_force_host_platform_device_count=8"
_existing = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _existing:
  os.environ["XLA_FLAGS"] = f"{_existing} {_FLAG}".strip()
# Hard override: the ambient environment may point JAX at a tunneled TPU
# (JAX_PLATFORMS=axon); tests must run on the virtual CPU mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

# The axon PJRT plugin may already be registered by sitecustomize before this
# conftest runs, and its (tunnelled) initialization hangs CPU-only test runs
# even under JAX_PLATFORMS=cpu — swap in a quietly-failing factory so the
# platform names stay *known* (Pallas import registers 'tpu' lowerings, which
# requires that) but the tunnelled backend can never initialize.
import jax._src.xla_bridge as _xb  # noqa: E402


def _disabled_backend_factory(*args, **kwargs):
  raise RuntimeError("tpu/axon backends are disabled under the CPU test mesh")


for _plat in ("axon", "tpu"):
  if _plat in _xb._backend_factories:
    _xb.register_backend_factory(
        _plat, _disabled_backend_factory, priority=-1000, fail_quietly=True)

# jax was already imported by sitecustomize with JAX_PLATFORMS=axon baked into
# its config; point the live config back at cpu as well.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
  return np.random.default_rng(0)
