"""Geometry parity vs the torch oracle + algebraic property tests."""

import jax.numpy as jnp
import numpy as np
import torch

from mpi_vision_tpu.core import geometry
from mpi_vision_tpu.torchref import oracle


def _random_pose(rng):
  # Small random rotation via Rodrigues + small translation.
  axis = rng.standard_normal(3)
  axis = axis / np.linalg.norm(axis)
  angle = rng.uniform(-0.3, 0.3)
  k = np.array([[0, -axis[2], axis[1]], [axis[2], 0, -axis[0]],
                [-axis[1], axis[0], 0]])
  rot = np.eye(3) + np.sin(angle) * k + (1 - np.cos(angle)) * (k @ k)
  t = rng.uniform(-0.2, 0.2, (3, 1))
  return rot.astype(np.float32), t.astype(np.float32)


def test_homogeneous_grid():
  grid = np.asarray(geometry.homogeneous_grid(3, 5))
  want = oracle.meshgrid_abs(1, 3, 5)[0].numpy()
  np.testing.assert_allclose(grid, want)


def test_safe_divide():
  num = jnp.array([1.0, 2.0, 3.0])
  den = jnp.array([0.0, 4.0, -2.0])
  got = np.asarray(geometry.safe_divide(num, den))
  want = oracle.safe_divide(torch.tensor([1.0, 2.0, 3.0]),
                            torch.tensor([0.0, 4.0, -2.0])).numpy()
  np.testing.assert_allclose(got, want)


def test_inverse_homography_parity(rng):
  rot, t = _random_pose(rng)
  k = np.array([[100.0, 0, 32], [0, 100.0, 24], [0, 0, 1]], np.float32)
  n_hat = np.array([[0.0, 0.0, 1.0]], np.float32)[None]
  a = np.array([[[-2.5]]], np.float32)
  got = np.asarray(geometry.inverse_homography(
      jnp.asarray(k)[None], jnp.asarray(k)[None], jnp.asarray(rot)[None],
      jnp.asarray(t)[None], jnp.asarray(n_hat), jnp.asarray(a)))
  want = oracle.inverse_homography(
      torch.tensor(k)[None], torch.tensor(k)[None], torch.tensor(rot)[None],
      torch.tensor(t)[None], torch.tensor(n_hat), torch.tensor(a)).numpy()
  np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_identity_homography_is_identity():
  # Identity pose => homography == identity for any plane.
  k = jnp.array([[50.0, 0, 16], [0, 50.0, 16], [0, 0, 1]])[None]
  rot = jnp.eye(3)[None]
  t = jnp.zeros((1, 3, 1))
  n_hat = jnp.array([[[0.0, 0.0, 1.0]]])
  a = jnp.array([[[-3.0]]])
  hom = np.asarray(geometry.inverse_homography(k, k, rot, t, n_hat, a))
  np.testing.assert_allclose(hom[0], np.eye(3), atol=1e-5)


def test_apply_homography_roundtrip(rng):
  rot, t = _random_pose(rng)
  k = np.array([[80.0, 0, 20], [0, 80.0, 20], [0, 0, 1]], np.float32)
  n_hat = np.array([[[0.0, 0.0, 1.0]]], np.float32)
  a = np.array([[[-4.0]]], np.float32)
  hom = geometry.inverse_homography(
      jnp.asarray(k)[None], jnp.asarray(k)[None], jnp.asarray(rot)[None],
      jnp.asarray(t)[None], jnp.asarray(n_hat), jnp.asarray(a))
  inv_hom = jnp.linalg.inv(hom)
  pts = jnp.moveaxis(geometry.homogeneous_grid(6, 6), 0, -1)[None]
  fwd = geometry.apply_homography(pts, hom)
  back = geometry.apply_homography(fwd, inv_hom)
  back = geometry.from_homogeneous(back)
  np.testing.assert_allclose(
      np.asarray(back), np.asarray(geometry.from_homogeneous(pts)),
      atol=1e-3)


def test_relative_pose_composition():
  src = jnp.eye(4).at[:3, 3].set(jnp.array([1.0, 0, 0]))[None]
  tgt = jnp.eye(4).at[:3, 3].set(jnp.array([0.0, 2.0, 0]))[None]
  rel = np.asarray(geometry.relative_pose(src, tgt))
  # rel maps src-cam coords to tgt-cam coords: p_tgt = rel @ p_src.
  p_world = np.array([0.0, 0, 5.0, 1.0])
  p_src = np.asarray(src)[0] @ p_world
  p_tgt = np.asarray(tgt)[0] @ p_world
  np.testing.assert_allclose(rel[0] @ p_src, p_tgt, atol=1e-6)


def test_intrinsics_to_4x4():
  k = jnp.array([[10.0, 0, 2], [0, 11.0, 3], [0, 0, 1]])
  k4 = np.asarray(geometry.intrinsics_to_4x4(k[None]))[0]
  assert k4.shape == (4, 4)
  np.testing.assert_allclose(k4[:3, :3], np.asarray(k))
  np.testing.assert_allclose(k4[3], [0, 0, 0, 1])
  np.testing.assert_allclose(k4[:3, 3], 0)
