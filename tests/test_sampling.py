"""Parity: JAX bilinear sampler vs torch grid_sample (the whole 1e-3 budget).

torch's F.grid_sample with its defaults (bilinear, zeros padding,
align_corners=False) is the spec oracle, exercised through the oracle wrapper
that reproduces the reference's (0,1)->(-1,1) mapping (utils.py:127).
"""

import jax.numpy as jnp
import numpy as np
import torch

from mpi_vision_tpu.core import sampling
from mpi_vision_tpu.core.sampling import Convention
from mpi_vision_tpu.torchref import oracle

TOL = 1e-5


def _compare(img, coords):
  got = np.asarray(sampling.bilinear_sample(jnp.asarray(img), jnp.asarray(coords)))
  want = oracle.grid_sample_01(torch.tensor(img), torch.tensor(coords)).numpy()
  np.testing.assert_allclose(got, want, atol=TOL, rtol=0)


def test_in_range_square(rng):
  img = rng.standard_normal((2, 16, 16, 3), dtype=np.float32)
  coords = rng.uniform(0.1, 0.9, (2, 8, 8, 2)).astype(np.float32)
  _compare(img, coords)


def test_out_of_range_and_edges(rng):
  # Coords spilling outside (0,1) must hit zero padding identically.
  img = rng.standard_normal((1, 12, 12, 4), dtype=np.float32)
  coords = rng.uniform(-0.5, 1.5, (1, 10, 10, 2)).astype(np.float32)
  _compare(img, coords)


def test_non_square(rng):
  img = rng.standard_normal((3, 9, 17, 2), dtype=np.float32)
  coords = rng.uniform(-0.2, 1.2, (3, 5, 7, 2)).astype(np.float32)
  _compare(img, coords)


def test_exact_pixel_centers(rng):
  # Coord (i + 0.5)/size hits pixel i exactly under align_corners=False.
  img = rng.standard_normal((1, 4, 6, 1), dtype=np.float32)
  ys, xs = np.meshgrid(np.arange(4), np.arange(6), indexing="ij")
  coords = np.stack([(xs + 0.5) / 6.0, (ys + 0.5) / 4.0], axis=-1)
  coords = coords[None].astype(np.float32)
  got = np.asarray(sampling.bilinear_sample(jnp.asarray(img), jnp.asarray(coords)))
  np.testing.assert_allclose(got, img, atol=TOL, rtol=0)


def test_leading_dims_broadcast(rng):
  # Planes axis on the images, shared coords.
  img = rng.standard_normal((4, 2, 8, 8, 3), dtype=np.float32)
  coords = rng.uniform(0, 1, (2, 8, 8, 2)).astype(np.float32)
  got = sampling.bilinear_sample(jnp.asarray(img), jnp.asarray(coords))
  assert got.shape == (4, 2, 8, 8, 3)
  want = oracle.grid_sample_01(
      torch.tensor(img), torch.tensor(np.broadcast_to(coords, (4, 2, 8, 8, 2)).copy()))
  np.testing.assert_allclose(np.asarray(got), want.numpy(), atol=TOL, rtol=0)


def test_gradients_match_torch(rng):
  import jax

  img = rng.standard_normal((1, 8, 8, 2), dtype=np.float32)
  coords = rng.uniform(-0.1, 1.1, (1, 6, 6, 2)).astype(np.float32)

  def loss_jax(i, c):
    return jnp.sum(sampling.bilinear_sample(i, c) ** 2)

  gi, gc = jax.grad(loss_jax, argnums=(0, 1))(jnp.asarray(img), jnp.asarray(coords))

  ti = torch.tensor(img, requires_grad=True)
  tc = torch.tensor(coords, requires_grad=True)
  loss = (oracle.grid_sample_01(ti, tc) ** 2).sum()
  loss.backward()

  np.testing.assert_allclose(np.asarray(gi), ti.grad.numpy(), atol=1e-4, rtol=1e-4)
  np.testing.assert_allclose(np.asarray(gc), tc.grad.numpy(), atol=1e-3, rtol=1e-3)


def test_conventions():
  # REF_PROJECTION == EXACT on square sizes, differs on non-square.
  xy = jnp.array([[[3.0, 2.0]]])
  sq_a = sampling.normalize_pixel_coords(xy, 8, 8, Convention.REF_PROJECTION)
  sq_b = sampling.normalize_pixel_coords(xy, 8, 8, Convention.EXACT)
  np.testing.assert_allclose(np.asarray(sq_a), np.asarray(sq_b))
  ns_a = sampling.normalize_pixel_coords(xy, 8, 16, Convention.REF_PROJECTION)
  ns_b = sampling.normalize_pixel_coords(xy, 8, 16, Convention.EXACT)
  assert not np.allclose(np.asarray(ns_a), np.asarray(ns_b))
  # REF_HOMOGRAPHY divides by (dim - 1) with the x/height, y/width swap.
  hom = sampling.normalize_pixel_coords(xy, 5, 9, Convention.REF_HOMOGRAPHY)
  np.testing.assert_allclose(np.asarray(hom)[0, 0], [3.0 / 4.0, 2.0 / 8.0])
