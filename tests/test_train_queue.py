"""Training job queue + supervisor: the state machine on fakes.

Everything here runs on fake clocks, launchers, and transports — no
subprocesses, no JAX — so the whole lease/requeue/quarantine machine is
pinned in milliseconds. The real-subprocess acceptance arc lives in
``tests/test_train_queue_arc.py``; the chaos bench's ``--dry`` decision
path is registered tier-1 here (in-process, fake time).
"""

import json
import signal

import pytest

from mpi_vision_tpu.obs.events import EventLog
from mpi_vision_tpu.train import faultinject as fi
from mpi_vision_tpu.train.queue import (
    JobQueue,
    JobQueueError,
    LeaseLostError,
)
from mpi_vision_tpu.train.supervisor import (
    JobSpecError,
    SubprocessLauncher,
    TrainSupervisor,
)


class FakeClock:
  def __init__(self, t=1000.0):
    self.t = t

  def __call__(self):
    return self.t

  def sleep(self, seconds):
    self.t += max(float(seconds), 0.0)


# --- queue lifecycle ------------------------------------------------------


def test_submit_lease_complete_roundtrip(tmp_path):
  clock = FakeClock()
  events = EventLog(clock=clock)
  q = JobQueue(str(tmp_path), lease_s=30.0, clock=clock, events=events)
  jid = q.submit({"epochs": 1}, job_id="a")
  assert jid == "a" and q.get("a").state == "queued"
  job = q.lease("w1")
  assert job.id == "a" and job.state == "leased"
  assert q.lease("w2") is None  # single job, already claimed
  q.mark_running("a", "w1", attempt=0)
  assert q.get("a").attempts == 1
  q.complete("a", "w1", {"ok": True})
  assert q.get("a").state == "done"
  assert q.drained()
  assert events.count("training_job_done") == 1
  # The record is one atomic JSON file a fresh reader can reload whole.
  reloaded = JobQueue(str(tmp_path), clock=clock)
  assert reloaded.get("a").record["result"] == {"ok": True}


def test_lease_respects_backoff_floor_and_fifo(tmp_path):
  clock = FakeClock()
  q = JobQueue(str(tmp_path), clock=clock)
  q.submit({}, job_id="old")
  clock.t += 1.0
  q.submit({}, job_id="new")
  job = q.lease("w")
  assert job.id == "old"  # FIFO by creation time
  q.mark_running("old", "w", 0)
  q.requeue("old", "w", "crash", not_before_unix_s=clock() + 10.0)
  assert q.lease("w").id == "new"  # old is cooling off
  q.requeue("new", "w", "crash", not_before_unix_s=clock() + 5.0)
  assert q.lease("w") is None
  clock.t += 10.1
  assert q.lease("w").id == "old"


def test_dead_worker_lease_expires_and_requeues(tmp_path):
  clock = FakeClock()
  events = EventLog(clock=clock)
  q = JobQueue(str(tmp_path), lease_s=10.0, clock=clock, events=events)
  q.submit({}, job_id="a")
  q.lease("w1")
  q.mark_running("a", "w1", 0)
  assert q.reap_expired() == []  # heartbeat fresh
  clock.t += 9.0
  q.heartbeat("a", "w1")
  clock.t += 9.0
  assert q.reap_expired() == []  # refreshed in time
  clock.t += 10.1
  assert q.reap_expired() == ["a"]  # the worker died: requeued, not lost
  record = q.get("a").record
  assert record["state"] == "queued" and record["lease"] is None
  assert q.leases_expired == 1
  assert events.count("training_job_lease_expired") == 1
  # The dead worker's late write is refused: its lease is gone.
  with pytest.raises(LeaseLostError):
    q.heartbeat("a", "w1")
  with pytest.raises(LeaseLostError):
    q.complete("a", "w1")
  # A new worker resumes it (attempts carries across workers).
  job = q.lease("w2")
  q.mark_running("a", "w2", job.attempts)
  assert q.get("a").attempts == 2


def test_quarantine_is_terminal_until_readmitted(tmp_path):
  clock = FakeClock()
  events = EventLog(clock=clock)
  q = JobQueue(str(tmp_path), clock=clock, events=events)
  q.submit({}, job_id="p")
  q.lease("w")
  q.mark_running("p", "w", 0)
  q.quarantine("p", "w", "crash-loop")
  assert q.get("p").state == "quarantined"
  assert q.lease("w") is None and q.drained()
  assert events.count("training_job_quarantined") == 1
  q.readmit("p")
  assert q.get("p").state == "queued"
  assert q.lease("w").id == "p"


def test_queue_guards(tmp_path):
  q = JobQueue(str(tmp_path), clock=FakeClock())
  with pytest.raises(ValueError, match="lease_s"):
    JobQueue(str(tmp_path), lease_s=0)
  with pytest.raises(ValueError, match="must be a dict"):
    q.submit("nope")
  with pytest.raises(ValueError, match="job id"):
    q.submit({}, job_id="bad/../id")
  q.submit({}, job_id="dup")
  with pytest.raises(JobQueueError, match="already exists"):
    q.submit({}, job_id="dup")
  with pytest.raises(JobQueueError, match="not quarantined/failed"):
    q.readmit("dup")


# --- fault grammar --------------------------------------------------------


def test_fault_grammar_roundtrip():
  spec = fi.parse_fault("crash@step=7,hard,attempt=0")
  assert spec == {"kind": "crash", "attempt": 0, "step": 7, "hard": True}
  assert fi.format_fault(spec) == "crash@step=7,hard,attempt=0"
  assert fi.parse_fault("corrupt@save=1,mode=garble")["mode"] == "garble"
  assert fi.parse_fault("hang@step=2,seconds=9.5")["seconds"] == 9.5
  for bad in ("crash", "crash@", "boom@step=1", "crash@step=1,save=2",
              "nan@save=1", "corrupt@step=1", "crash@step=x",
              "crash@step=1,zorp=3"):
    with pytest.raises(fi.FaultSpecError):
      fi.parse_fault(bad)


def test_malformed_fault_entries_are_spec_errors_not_loops():
  """JSON job specs can carry dict or garbage fault entries: they must
  raise FaultSpecError (-> terminal spec-reject at the launcher), never
  a bare KeyError/TypeError that would strand the job in a
  lease-reap-respawn loop the restart budget cannot see."""
  for bad in (5, "crash@step=1", {"kind": "crash"},
              [{"kind": "crash"}], [{"step": 1}], [5], [None]):
    with pytest.raises(fi.FaultSpecError):
      fi.applicable(bad, 0)
    with pytest.raises(fi.FaultSpecError):
      fi.build_source(bad)
  # Valid dict entries (the JSON spec form) still work.
  assert fi.applicable([{"kind": "crash", "step": 1, "hard": True}],
                       0) == ["crash@step=1,hard"]
  # A typo'd key must REJECT, not silently vanish in the round-trip —
  # a dropped "atempt" gate turns a one-shot crash into a poison job.
  with pytest.raises(fi.FaultSpecError, match="atempt"):
    fi.applicable([{"kind": "crash", "step": 1, "atempt": 0}], 0)


def test_launcher_rejects_malformed_faults_terminally(tmp_path):
  launcher = SubprocessLauncher(str(tmp_path))
  queue = JobQueue(str(tmp_path / "q"), clock=FakeClock())
  queue.submit({"faults": [{"kind": "crash"}]}, job_id="garbage")
  with pytest.raises(JobSpecError):
    launcher.argv(queue.get("garbage"), 0, False)


def test_fault_attempt_gating():
  faults = ["crash@step=1,hard,attempt=0", "nan@step=2"]
  assert fi.applicable(faults, 0) == ["crash@step=1,hard,attempt=0",
                                      "nan@step=2"]
  assert fi.applicable(faults, 1) == ["nan@step=2"]  # gated crash dropped
  assert fi.build_source(faults, attempt=1).on_step(1) is None
  assert fi.build_source(faults, attempt=0).on_step(1) is not None
  assert fi.build_source(["crash@step=1,attempt=2"], attempt=0) is None


# --- supervisor over fakes ------------------------------------------------


class FakeHandle:
  def __init__(self, port=9):
    self.rc = None
    self.kills = []
    self.ckpt_dir = "<fake>"
    self.port = port
    self.health = {"status": "ok", "steps": 0, "last_step_ms": 25.0}
    self.term_exits_clean = False

  def poll(self):
    return self.rc

  def kill(self, sig):
    self.kills.append(int(sig))
    if sig == signal.SIGTERM and self.term_exits_clean:
      self.rc = 0
    else:
      self.rc = -int(sig)

  def metrics_address(self):
    return f"127.0.0.1:{self.port}"


class FakeLauncher:
  def __init__(self):
    self.spawned = []
    self.handles = {}
    self.reject = set()

  def __call__(self, job, attempt, resume):
    if job.id in self.reject:
      raise JobSpecError("bad spec")
    handle = FakeHandle(port=9000 + len(self.spawned))
    self.spawned.append((job.id, attempt, resume))
    self.handles[(job.id, attempt)] = handle
    return handle


class FakeTransport:
  """Keyed by the probed address (a probe of job A answered with job
  B's counters would reset the wrong stall clock)."""

  def __init__(self, launcher):
    self.launcher = launcher

  def request(self, method, url, body=None, headers=None, timeout=None):
    for handle in self.launcher.handles.values():
      if (handle.rc is None
          and url == f"http://{handle.metrics_address()}/healthz"):
        return 200, {}, json.dumps(handle.health).encode()
    raise ConnectionError("down")


class FakePublish:
  def __init__(self):
    self.calls = []

  def publish_from(self, src_root, meta_extra=None):
    self.calls.append((src_root, meta_extra))
    return len(self.calls) - 1, 0


def _sup(tmp_path, **kwargs):
  clock = kwargs.pop("clock", FakeClock())
  events = EventLog(clock=clock)
  queue = JobQueue(str(tmp_path), lease_s=60.0, clock=clock, events=events)
  launcher = FakeLauncher()
  defaults = dict(restart_budget=2, budget_window_s=600.0,
                  backoff_base_s=1.0, backoff_mult=2.0, backoff_max_s=8.0,
                  wedge_after=3, startup_grace_s=5.0)
  defaults.update(kwargs)
  supervisor = TrainSupervisor(
      queue, launcher=launcher, transport=FakeTransport(launcher),
      events=events, clock=clock, sleep=clock.sleep, **defaults)
  return clock, queue, launcher, supervisor, events


def test_crash_loop_quarantined_at_exactly_the_budget(tmp_path):
  clock, queue, launcher, sup, events = _sup(tmp_path, restart_budget=2)
  queue.submit({}, job_id="poison")
  sup.tick()
  assert launcher.spawned == [("poison", 0, False)]
  for attempt in (0, 1, 2):
    launcher.handles[("poison", attempt)].rc = 1
    sup.tick()          # detect the crash (first retry is immediate,
    clock.t += 10.0     # later ones back off; jump past any backoff)
    sup.tick()
  # 1 first attempt + 2 budgeted retries, then containment.
  assert queue.get("poison").state == "quarantined"
  assert queue.get("poison").attempts == 3
  assert sup.quarantines_total == 1 and sup.failures_total == 3
  assert [s[2] for s in launcher.spawned] == [False, True, True]  # resumes
  assert events.count("training_job_quarantined") == 1
  # Containment, not collapse: a sibling submitted later still drains.
  queue.submit({}, job_id="good")
  sup.tick()
  launcher.handles[("good", 0)].rc = 0
  sup.tick()
  assert queue.get("good").state == "done"


def test_backoff_between_repeat_failures(tmp_path):
  clock, queue, launcher, sup, _ = _sup(tmp_path, restart_budget=3,
                                        backoff_base_s=1.0)
  queue.submit({}, job_id="flappy")
  sup.tick()
  launcher.handles[("flappy", 0)].rc = 1
  sup.tick()  # failure 1: immediate retry (streak 1 -> backoff(0)=0)
  assert ("flappy", 1, True) in launcher.spawned
  launcher.handles[("flappy", 1)].rc = 1
  sup.tick()  # failure 2: 1s backoff — not runnable yet
  assert queue.get("flappy").state == "queued"
  sup.tick()
  assert len(launcher.spawned) == 2  # still cooling
  clock.t += 1.1
  sup.tick()
  assert launcher.spawned[-1] == ("flappy", 2, True)


def test_wedged_trainer_is_sigkilled_and_requeued(tmp_path):
  clock, queue, launcher, sup, events = _sup(tmp_path, wedge_after=2)
  queue.submit({}, job_id="stuck")
  sup.tick()
  handle = launcher.handles[("stuck", 0)]
  handle.health = {"status": "ok", "steps": 4, "last_step_ms": 25.0}
  sup.tick()  # progress observed: stall counter resets
  sup.tick()  # stall 1
  sup.tick()  # stall 2 -> wedged: SIGKILL + requeue (+ immediate respawn)
  assert handle.kills == [signal.SIGKILL]
  assert sup.wedges_total == 1 and sup.failures_total == 1
  assert events.count("training_job_wedged") == 1
  assert launcher.spawned[-1] == ("stuck", 1, True)


def test_startup_grace_tolerates_slow_first_compile(tmp_path):
  clock, queue, launcher, sup, _ = _sup(tmp_path, wedge_after=2,
                                        startup_grace_s=30.0)
  queue.submit({}, job_id="cold")
  sup.tick()
  handle = launcher.handles[("cold", 0)]
  handle.health = {"status": "garbage"}  # listener not answering yet
  for _ in range(10):  # way past wedge_after, inside the grace window
    clock.t += 1.0
    sup.tick()
  assert sup.wedges_total == 0 and handle.kills == []
  clock.t += 30.0  # grace expired, still no health: now it counts
  sup.tick()
  sup.tick()
  assert sup.wedges_total == 1


def test_preempt_requeues_without_spending_budget(tmp_path):
  clock, queue, launcher, sup, events = _sup(tmp_path, restart_budget=1)
  queue.submit({}, job_id="a")
  sup.tick()
  handle = launcher.handles[("a", 0)]
  handle.term_exits_clean = True  # the CLI's preempt save + clean exit
  assert sup.preempt(drain_timeout_s=1.0) == ["a"]
  record = queue.get("a").record
  assert record["state"] == "queued"
  assert record["history"][-1]["counted"] is False  # no budget spent
  assert sup.preemptions_total == 1 and sup.failures_total == 0
  assert events.count("training_job_preempt") == 1
  # The next tick resumes it and it completes.
  sup.tick()
  assert launcher.spawned[-1] == ("a", 1, True)
  launcher.handles[("a", 1)].rc = 0
  sup.tick()
  assert queue.get("a").state == "done"


def test_completed_job_publishes_into_the_watch_store(tmp_path):
  clock, queue, launcher, sup, events = _sup(tmp_path)
  publish = FakePublish()
  sup.publish_store = publish
  queue.submit({}, job_id="a")
  sup.tick()
  launcher.handles[("a", 0)].rc = 0
  sup.tick()
  assert publish.calls == [("<fake>", {"job": "a"})]
  assert queue.get("a").record["result"]["published_step"] == 0
  assert sup.publishes_total == 1
  assert events.count("training_job_published") == 1


def test_bad_spec_fails_terminally_without_stalling_the_queue(tmp_path):
  clock, queue, launcher, sup, _ = _sup(tmp_path)
  queue.submit({}, job_id="bad")
  queue.submit({}, job_id="good")
  launcher.reject.add("bad")
  sup.tick()
  assert queue.get("bad").state == "failed"
  assert sup.spec_rejects_total == 1
  launcher.handles[("good", 0)].rc = 0
  sup.tick()
  assert queue.get("good").state == "done" and queue.drained()


def test_slo_scores_attempts_and_step_latency(tmp_path):
  from mpi_vision_tpu.obs.slo import SloConfig, SloTracker

  clock, queue, launcher, sup, _ = _sup(tmp_path, restart_budget=1)
  slo = SloTracker(SloConfig(latency_threshold_s=0.1), clock=clock)
  sup.slo = slo
  queue.submit({}, job_id="a")
  sup.tick()
  handle = launcher.handles[("a", 0)]
  sup.tick()  # first healthy probe: liveness baseline, no latency sample
  handle.health = {"status": "ok", "steps": 1, "last_step_ms": 250.0}
  sup.tick()  # a real step delta, 250ms > 100ms threshold: latency-bad
  handle.rc = 0
  sup.tick()  # attempt succeeded: availability-good
  snap = slo.snapshot()
  assert snap["objectives"]["latency"]["slow"]["bad"] == 1
  # Step samples score ONLY latency: availability is attempt outcomes
  # alone (one completed attempt == one good event), so a healthy job's
  # steady step stream cannot dilute a sibling's crash-loop out of the
  # availability burn rate.
  assert snap["objectives"]["availability"]["slow"]["requests"] == 1
  assert snap["objectives"]["availability"]["slow"]["bad"] == 0
  assert snap["objectives"]["latency"]["slow"]["requests"] == 1
  # The scrape surface joins the queue + SLO families (Registry.extend).
  text = sup.metrics_text()
  assert "mpi_train_queue_spawns_total" in text
  assert "mpi_slo_attainment" in text


def test_readmitted_job_gets_a_fresh_restart_budget(tmp_path):
  clock, queue, launcher, sup, _ = _sup(tmp_path, restart_budget=1)
  queue.submit({}, job_id="p")
  sup.tick()
  for attempt in (0, 1):
    launcher.handles[("p", attempt)].rc = 1
    sup.tick()
    clock.t += 10.0
    sup.tick()
  assert queue.get("p").state == "quarantined"
  assert queue.get("p").attempts == 2  # 1 + budget
  queue.readmit("p")
  # The operator override promises a FRESH budget: the next failure must
  # retry, not instantly re-quarantine off the exhausted old one.
  sup.tick()
  launcher.handles[("p", 2)].rc = 1
  sup.tick()
  # Fresh budget: the failure RETRIED (the first retry is immediate, so
  # the same tick respawned it as attempt 3) instead of re-quarantining.
  assert launcher.spawned[-1] == ("p", 3, True)
  assert queue.get("p").state == "running"
  launcher.handles[("p", 3)].rc = 1
  sup.tick()
  assert queue.get("p").state == "quarantined"  # fresh budget exhausted
  assert sup.quarantines_total == 2


def test_supervisor_guards():
  with pytest.raises(ValueError, match="concurrency"):
    TrainSupervisor(object(), launcher=lambda *a: None, concurrency=0)
  with pytest.raises(ValueError, match="restart_budget"):
    TrainSupervisor(object(), launcher=lambda *a: None, restart_budget=0)
  with pytest.raises(ValueError, match="wedge_after"):
    TrainSupervisor(object(), launcher=lambda *a: None, wedge_after=0)
  with pytest.raises(ValueError, match="launcher or a work_root"):
    TrainSupervisor(object())


def test_queue_registry_families(tmp_path):
  clock, queue, launcher, sup, _ = _sup(tmp_path)
  queue.submit({}, job_id="a")
  sup.tick()
  text = sup.metrics_text()
  assert 'mpi_train_queue_jobs{state="running"} 1' in text
  assert "mpi_train_queue_spawns_total 1" in text
  assert "mpi_train_queue_quarantines_total 0" in text


def test_queue_metrics_server_scrape_surface(tmp_path):
  """The ``train-queue --metrics-port`` listener: /metrics renders the
  mpi_train_queue_* registry the supervisor already builds, /stats the
  snapshot, /healthz the drain/quarantine headline — over real HTTP."""
  import json as json_mod
  import threading
  import urllib.request

  from mpi_vision_tpu.train.supervisor import make_queue_metrics_server

  clock, queue, launcher, sup, events = _sup(tmp_path)
  queue.submit({}, job_id="a")
  sup.tick()
  server = make_queue_metrics_server(sup, events=events)
  threading.Thread(target=server.serve_forever, daemon=True).start()
  base = f"http://127.0.0.1:{server.server_address[1]}"
  try:
    with urllib.request.urlopen(base + "/metrics", timeout=5) as resp:
      text = resp.read().decode()
    assert "mpi_train_queue_spawns_total 1" in text
    with urllib.request.urlopen(base + "/stats", timeout=5) as resp:
      stats = json_mod.loads(resp.read())
    assert stats["spawns"] == 1 and "queue" in stats
    with urllib.request.urlopen(base + "/healthz", timeout=5) as resp:
      health = json_mod.loads(resp.read())
    assert health["status"] == "ok" and health["role"] == "train-queue"
    assert health["running"] == 1 and health["drained"] is False
    with urllib.request.urlopen(base + "/debug/events?recent=8",
                                timeout=5) as resp:
      ev = json_mod.loads(resp.read())
    assert ev["emitted"] >= 1
  finally:
    server.shutdown()
    server.server_close()


# --- the subprocess launcher's argv (no spawn) ----------------------------


def test_launcher_argv_isolation_and_faults(tmp_path):
  launcher = SubprocessLauncher(str(tmp_path))
  queue = JobQueue(str(tmp_path / "q"), clock=FakeClock())
  queue.submit({"epochs": 2, "img_size": 32, "num_planes": 4, "seed": 7,
                "faults": ["crash@step=1,hard,attempt=0"]}, job_id="j1")
  job = queue.get("j1")
  argv0 = launcher.argv(job, attempt=0, resume=False)
  assert "--ckpt" in argv0 and str(tmp_path / "j1" / "ckpt") in argv0
  assert "--resume" not in argv0
  assert "--inject-fault" in argv0  # attempt 0 carries its gated fault
  assert "--no-vgg-loss" in argv0 and "--no-valid" in argv0
  argv1 = launcher.argv(job, attempt=1, resume=True)
  assert "--resume" in argv1
  assert "--inject-fault" not in argv1  # the gate filtered it out
  queue.submit({"epochs": "two"}, job_id="j2")
  with pytest.raises(JobSpecError, match="epochs"):
    launcher.argv(queue.get("j2"), 0, False)


# --- chaos bench, dry decision path (tier-1 registration) -----------------


def test_chaos_bench_dry_smoke():
  """The full chaos drill — poison quarantined at exactly its budget,
  wedge killed and retried, crash-once resumed, everything else drained
  and published — on the scripted fakes, in fake time."""
  import importlib.util
  import os

  path = os.path.join(os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))), "bench", "train_queue.py")
  spec = importlib.util.spec_from_file_location("bench_train_queue", path)
  mod = importlib.util.module_from_spec(spec)
  spec.loader.exec_module(mod)
  out = mod.run_dry(budget=1)
  assert out["metric"] == "train_queue_chaos" and out["dry"] is True
  assert out["drained"] is True and out["value"] == 3
  assert out["jobs"]["quarantined"] == 1
  assert out["poison_attempts"] == 1 + out["restart_budget"]
  assert out["wedges"] == 1 and out["publishes"] == 3
  assert out["slo"]["objectives"]["availability"]["requests"] > 0


# --- review-round pins ----------------------------------------------------


def test_orphaned_claim_ages_out_and_job_stays_leasable(tmp_path):
  """A claimer killed between creating its claim file and leasing must
  not make the job permanently unleasable: the claim ages out after
  lease_s (requeued-never-lost applies to the claim protocol too)."""
  clock = FakeClock()
  q = JobQueue(str(tmp_path), lease_s=10.0, clock=clock)
  q.submit({}, job_id="a")
  # Forge a crashed peer's orphan claim.
  with open(q._claim_path("a"), "w") as fh:
    json.dump({"owner": "dead", "ts_unix_s": clock()}, fh)
  assert q.lease("w") is None  # fresh claim: a live peer, back off
  clock.t += 10.1
  job = q.lease("w")  # stale claim removed, job claimed normally
  assert job is not None and job.id == "a"


def test_completion_after_lease_reaped_is_skipped_not_crashed(tmp_path):
  """A tick that outlived lease_s may find its finished job already
  reaped: completion (and publish) must be skipped for the new owner,
  never crash the tick or double-publish."""
  clock = FakeClock()
  events = EventLog(clock=clock)
  queue = JobQueue(str(tmp_path), lease_s=5.0, clock=clock, events=events)
  launcher = FakeLauncher()
  sup = TrainSupervisor(queue, launcher=launcher,
                        transport=FakeTransport(launcher), events=events,
                        clock=clock, sleep=clock.sleep)
  publish = FakePublish()
  sup.publish_store = publish
  queue.submit({}, job_id="a")
  sup.tick()
  clock.t += 6.0  # the supervisor stalled past lease_s
  launcher.handles[("a", 0)].rc = 0
  sup.tick()  # reap_expired requeues "a" first, then the exit lands
  # The reaper's requeue stands: the stale attempt neither completed
  # the job nor published its checkpoint (the same tick may have
  # legitimately re-leased it as a fresh attempt — that is recovery,
  # not completion).
  assert queue.get("a").state != "done"
  assert publish.calls == []               # no publish for a lost lease
  assert sup.completes_total == 0 and sup.tick_errors == 0


def test_run_until_drained_is_interruptible(tmp_path):
  clock, queue, launcher, sup, _ = _sup(tmp_path)
  queue.submit({}, job_id="never-finishes")
  stops = iter([False, True])
  assert sup.run_until_drained(timeout_s=100.0,
                               should_stop=lambda: next(stops)) is False


def test_stale_claim_takeover_is_single_winner(tmp_path, monkeypatch):
  """Two workers judging the same orphan claim stale must not both win
  it: the takeover is an atomic rename, so the loser backs off instead
  of unlinking the winner's fresh claim (double-lease guard)."""
  clock = FakeClock()
  q = JobQueue(str(tmp_path), lease_s=10.0, clock=clock)
  q.submit({}, job_id="a")
  with open(q._claim_path("a"), "w") as fh:
    json.dump({"owner": "dead", "ts_unix_s": clock()}, fh)
  clock.t += 10.1
  # Simulate the loser: the orphan vanished under us (peer renamed it).
  import mpi_vision_tpu.train.queue as qmod
  def rename_lost(src, dst):
    raise OSError("vanished: a peer won the takeover")
  monkeypatch.setattr(qmod.os, "rename", rename_lost)
  assert q.lease("slow-worker") is None  # backs off, no double lease
  monkeypatch.undo()
  assert q.lease("fast-worker").id == "a"  # recovery still works


def test_stale_takeover_restores_a_freshly_relinked_claim(tmp_path,
                                                          monkeypatch):
  """The takeover rename must verify what it moved: a peer may complete
  its own takeover and link a FRESH claim between our staleness read and
  the rename — stealing that claim would double-lease the job."""
  import os

  clock = FakeClock()
  q = JobQueue(str(tmp_path), lease_s=10.0, clock=clock)
  q.submit({}, job_id="a")
  with open(q._claim_path("a"), "w") as fh:
    json.dump({"owner": "dead", "ts_unix_s": clock()}, fh)
  clock.t += 10.1
  import mpi_vision_tpu.train.queue as qmod
  real_rename = qmod.os.rename
  raced = {"done": False}
  def racing_rename(src, dst):
    if src == q._claim_path("a") and not raced["done"]:
      raced["done"] = True
      # The peer finished its takeover and linked a FRESH claim here.
      with open(src, "w") as fh:
        json.dump({"owner": "peer", "ts_unix_s": clock()}, fh)
    real_rename(src, dst)
  monkeypatch.setattr(qmod.os, "rename", racing_rename)
  assert q.lease("slow") is None  # backed off, nothing stolen
  # The peer's fresh claim is back in place, still guarding the job.
  with open(q._claim_path("a")) as fh:
    assert json.load(fh)["owner"] == "peer"


def test_sweep_spares_a_live_peers_inflight_write(tmp_path):
  clock = FakeClock()
  q = JobQueue(str(tmp_path), clock=clock)
  import os
  live = str(tmp_path / f".tmp-job-x-{os.getpid()+0}-deadbeef")
  # Our own pid counts as dead (fresh construction), so fake a LIVE
  # peer with pid 1 (init: always alive) and a dead one with an
  # implausible pid.
  peer = str(tmp_path / ".tmp-job-y-1-deadbeef")
  dead = str(tmp_path / ".tmp-job-z-999999999-deadbeef")
  for p in (peer, dead):
    open(p, "w").close()
  JobQueue(str(tmp_path), clock=clock)  # construction sweeps
  assert os.path.exists(peer)      # live peer's write untouched
  assert not os.path.exists(dead)  # crashed writer's junk removed
  os.unlink(peer)


def test_mark_running_lease_loss_kills_the_spawn(tmp_path):
  """A spawn slower than lease_s whose job was reaped mid-launch must
  kill the fresh process, not leak it unsupervised."""
  clock, queue, launcher, sup, _ = _sup(tmp_path)
  queue.submit({}, job_id="a")
  real_mark = queue.mark_running
  orphans = []
  def slow_mark(job_id, owner, attempt, detail=None):
    queue.mark_running = real_mark  # only the FIRST spawn is slow
    orphans.append(launcher.handles[(job_id, attempt)])
    clock.t += 120.0           # the spawn outlived lease_s (60)
    queue.reap_expired()       # another worker's reaper took the job
    return real_mark(job_id, owner, attempt, detail=detail)
  queue.mark_running = slow_mark
  sup.tick()
  assert signal.SIGKILL in orphans[0].kills  # the orphan was killed
  # The reaper's requeue stood at the instant of loss; the same tick
  # then re-leased the job as a fresh, properly-owned attempt.
  assert sup.running() == ["a"]
  assert queue.get("a").state == "running"
  fresh = launcher.handles[("a", 0)]
  assert fresh is not orphans[0] and fresh.kills == []


def test_run_until_drained_contains_tick_errors(tmp_path):
  clock, queue, launcher, sup, _ = _sup(tmp_path)
  queue.submit({}, job_id="a")
  boom = {"n": 0}
  real_tick = sup.tick
  def flaky_tick():
    if boom["n"] == 0:
      boom["n"] += 1
      raise OSError("transient NFS sadness")
    real_tick()
  # After the one flaky tick, real ticks run the job to completion.
  def finish_soon():
    real_tick()
    for handle in launcher.handles.values():
      handle.rc = 0
  sup.tick = lambda: (flaky_tick() if boom["n"] == 0 else finish_soon())
  assert sup.run_until_drained(timeout_s=50.0) is True
  assert sup.tick_errors == 1


# --- budget persistence + multi-worker drill (ISSUE 15) -------------------


def _worker(tmp_path, owner, clock, **kwargs):
  """One worker's worth of machinery over the SHARED queue directory:
  its own JobQueue instance (the queue is the disk), launcher, and
  transport — only the clock is shared, like real co-located workers."""
  events = EventLog(clock=clock)
  queue = JobQueue(str(tmp_path), lease_s=60.0, clock=clock,
                   events=events)
  launcher = FakeLauncher()
  defaults = dict(restart_budget=2, budget_window_s=600.0,
                  backoff_base_s=1.0, backoff_mult=2.0, backoff_max_s=8.0,
                  wedge_after=3, startup_grace_s=5.0)
  defaults.update(kwargs)
  supervisor = TrainSupervisor(
      queue, launcher=launcher, transport=FakeTransport(launcher),
      events=events, clock=clock, sleep=clock.sleep, owner=owner,
      **defaults)
  return queue, launcher, supervisor


def test_budget_spends_persist_across_supervisor_restarts(tmp_path):
  """THE no-fresh-budget pin: a supervisor restart mid-crash-loop must
  resume the quarantine countdown from the spends persisted on the job
  record, not hand the poison job a whole new budget."""
  clock = FakeClock()
  queue1, launcher1, sup1 = _worker(tmp_path, "w1", clock,
                                    restart_budget=2)
  queue1.submit({}, job_id="loopy")
  sup1.tick()
  for attempt in (0, 1):  # two failures: the whole budget, spent
    launcher1.handles[("loopy", attempt)].rc = 1
    sup1.tick()
    clock.t += 10.0
    sup1.tick()
  # The spend window rode the requeue onto the record as wall times.
  spends = queue1.get("loopy").budget_spend_unix_s
  assert len(spends) == 2 and all(t <= clock() for t in spends)
  # The supervisor dies; its replacement reads the same queue dir.
  queue2, launcher2, sup2 = _worker(tmp_path, "w2", clock,
                                    restart_budget=2)
  # w1's in-flight attempt is still leased to w1 until the lease
  # expires; the replacement reaps it on its first tick.
  clock.t += 60.1
  sup2.tick()  # reap + lease + spawn attempt 3
  assert [s[0] for s in launcher2.spawned] == ["loopy"]
  launcher2.handles[("loopy", queue2.get("loopy").attempts - 1)].rc = 1
  sup2.tick()
  # Adopted budget: 2 in-window spends + this failure = immediate
  # quarantine. A fresh budget would have granted 2 more respawns.
  assert queue2.get("loopy").state == "quarantined"
  assert sup2.quarantines_total == 1
  assert len(launcher2.spawned) == 1  # zero extra respawns granted
  # readmit() clears the persisted window with the quarantine: the
  # operator's fresh-budget promise holds across restarts too.
  queue2.readmit("loopy")
  assert queue2.get("loopy").budget_spend_unix_s == []


def test_preempt_requeue_leaves_persisted_spends_untouched(tmp_path):
  """Preemption is planned downtime: it must neither spend budget NOR
  erase the crash-loop history a previous failure persisted."""
  clock = FakeClock()
  queue, launcher, sup = _worker(tmp_path, "w1", clock, restart_budget=3)
  queue.submit({}, job_id="a")
  sup.tick()
  launcher.handles[("a", 0)].rc = 1  # one real failure: one spend
  sup.tick()
  spends = queue.get("a").budget_spend_unix_s
  assert len(spends) == 1
  clock.t += 2.0
  sup.tick()  # respawn (attempt 1)
  assert sup.running() == ["a"]
  sup.preempt()
  assert queue.get("a").state == "queued"
  # No spend added, none erased: the window is exactly as it was.
  assert queue.get("a").budget_spend_unix_s == spends


def test_two_workers_one_queue_no_double_lease_no_lost_job(tmp_path):
  """The multi-worker drill on fakes: two supervisors drain one shared
  queue directory — every job runs under exactly one owner, a dead
  worker's jobs are reaped and finished by the survivor, and the dead
  worker's zombie attempts are fenced off (killed on lease loss)."""
  clock = FakeClock()
  queue_a, launcher_a, sup_a = _worker(tmp_path, "workerA", clock,
                                       concurrency=2)
  queue_b, launcher_b, sup_b = _worker(tmp_path, "workerB", clock,
                                       concurrency=2)
  for i in range(4):
    queue_a.submit({}, job_id=f"j{i}")
    clock.t += 0.01  # distinct create stamps keep FIFO deterministic
  sup_a.tick()  # A fills its 2 slots first...
  sup_b.tick()  # ...B gets the remaining 2
  ran_a, ran_b = set(sup_a.running()), set(sup_b.running())
  assert ran_a == {"j0", "j1"} and ran_b == {"j2", "j3"}
  assert ran_a.isdisjoint(ran_b)  # no job double-leased, none skipped
  # B's jobs complete; A then DIES (stops ticking, processes linger).
  for job_id in ran_b:
    launcher_b.handles[(job_id, 0)].rc = 0
  sup_b.tick()
  assert queue_b.get("j2").state == "done"
  assert queue_b.get("j3").state == "done"
  # Past A's lease TTL the survivor reaps and re-runs A's jobs — the
  # queue loses nothing to a dead worker.
  clock.t += 60.1
  sup_b.tick()
  assert queue_b.leases_expired == 2
  assert set(sup_b.running()) == {"j0", "j1"}
  assert [s for s in launcher_b.spawned if s[0] in ("j0", "j1")] == [
      ("j0", 1, True), ("j1", 1, True)]  # attempts carried, resumed
  # The dead worker lurching back must NOT fight the survivor: its
  # heartbeats fail (lease lost) and it fences its own zombies.
  sup_a.tick()
  assert sup_a.running() == []
  for job_id in ("j0", "j1"):
    assert signal.SIGKILL in launcher_a.handles[(job_id, 0)].kills
  # The survivor drains the re-run jobs to done: nothing lost, nothing
  # run twice concurrently.
  for job_id in ("j0", "j1"):
    launcher_b.handles[(job_id, 1)].rc = 0
  sup_b.tick()
  assert all(queue_b.get(f"j{i}").state == "done" for i in range(4))
  assert queue_b.drained()
  total_spawns = len(launcher_a.spawned) + len(launcher_b.spawned)
  assert total_spawns == 6  # 4 first attempts + 2 takeover re-runs
