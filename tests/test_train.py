"""Train subpackage tests: VGG parity, losses, optimization, ckpt, sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from mpi_vision_tpu.core.camera import inv_depths
from mpi_vision_tpu.models.stereo_mag import StereoMagnificationModel
from mpi_vision_tpu.parallel import mesh as pmesh
from mpi_vision_tpu.torchref import vgg as tvgg
from mpi_vision_tpu.train import loop as tloop
from mpi_vision_tpu.train import loss as tloss
from mpi_vision_tpu.train import vgg as jvgg


def _batch(rng, b=1, hw=32, p=4):
  """A synthetic batch with the reference dataset contract."""
  ref = rng.uniform(-1, 1, (b, hw, hw, 3)).astype(np.float32)
  tgt = rng.uniform(-1, 1, (b, hw, hw, 3)).astype(np.float32)
  pose = np.eye(4, dtype=np.float32)
  pose[0, 3] = 0.04
  k = np.array([[hw / 2, 0, hw / 2], [0, hw / 2, hw / 2], [0, 0, 1]],
               np.float32)
  net_input = rng.uniform(-1, 1, (b, hw, hw, 3 + 3 * p)).astype(np.float32)
  return {
      "net_input": jnp.asarray(net_input),
      "ref_img": jnp.asarray(ref),
      "tgt_img": jnp.asarray(tgt),
      "tgt_img_cfw": jnp.asarray(np.stack([pose] * b)),
      "ref_img_wfc": jnp.asarray(np.stack([np.eye(4, dtype=np.float32)] * b)),
      "intrinsics": jnp.asarray(np.stack([k] * b)),
      "mpi_planes": jnp.asarray(np.asarray(inv_depths(1.0, 100.0, p))),
  }


class TestVGGParity:

  def test_feature_parity_with_torch_mirror(self, rng):
    torch.manual_seed(0)
    features = tvgg.build_features()
    params = jvgg.params_from_torch_state(features.state_dict())
    x = rng.uniform(-1, 1, (2, 32, 32, 3)).astype(np.float32)
    jax_taps = jvgg.VGG16Features().apply(params, jnp.asarray(x))
    torch_taps = tvgg.extract_features(
        features, torch.from_numpy(x).permute(0, 3, 1, 2))
    assert len(jax_taps) == len(torch_taps) == 4
    for jt, tt in zip(jax_taps, torch_taps):
      np.testing.assert_allclose(
          np.asarray(jt), tt.permute(0, 2, 3, 1).numpy(), atol=2e-4, rtol=0)

  def test_imagenet_normalize_matches_reference_quirk(self):
    # The reference applies mean/std DIRECTLY to [-1,1] images (cell 12,
    # no [0,1] rescale); the published loss values depend on that.
    x = jnp.zeros((1, 2, 2, 3))
    got = np.asarray(jvgg.imagenet_normalize(x))
    want = (0.0 - jvgg.IMAGENET_MEAN) / jvgg.IMAGENET_STD
    np.testing.assert_allclose(got[0, 0, 0], want, atol=1e-6)

  def test_state_dict_roundtrip(self):
    """flax -> torch state dict -> flax must be the identity."""
    params = jvgg.init_params(3)
    back = jvgg.params_from_torch_state(jvgg.state_dict_from_params(params))
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), params, back)

  def test_save_load_default_params(self, tmp_path, monkeypatch):
    """Orbax persistence + the MPI_VISION_VGG16_CKPT default resolution."""
    params = jvgg.init_params(1)
    path = str(tmp_path / "vgg16")
    jvgg.save_params(path, params)
    monkeypatch.setenv("MPI_VISION_VGG16_CKPT", path)
    loaded = jvgg.default_params()
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), params, loaded)
    monkeypatch.delenv("MPI_VISION_VGG16_CKPT")
    fallback = jvgg.default_params()
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), jvgg.init_params(0), fallback)

  def test_scalar_perceptual_loss_parity_with_torch(self, rng):
    """End-to-end loss VALUE parity with shared weights (VERDICT r2 item 3):
    net output -> MPI -> render -> normalize -> VGG taps -> weighted L1s,
    |jax - torch| <= 1e-4."""
    from mpi_vision_tpu.torchref import loss as torch_loss_lib

    torch.manual_seed(0)
    features = tvgg.build_features()
    vgg_params = jvgg.params_from_torch_state(features.state_dict())
    batch = _batch(rng)
    p = 4
    mpi_pred = rng.uniform(-1, 1, (1, 32, 32, 2 * p + 3)).astype(np.float32)

    jax_loss = float(tloss.vgg_perceptual_loss(
        jnp.asarray(mpi_pred), batch, vgg_params, resize=None))
    tbatch = {k: torch.as_tensor(np.asarray(v)) for k, v in batch.items()}
    torch_val = float(torch_loss_lib.vgg_perceptual_loss(
        torch.from_numpy(mpi_pred).permute(0, 3, 1, 2), tbatch, features,
        resize=None))
    assert abs(jax_loss - torch_val) <= 1e-4, (jax_loss, torch_val)

  def test_scalar_perceptual_loss_parity_resize_path(self, rng):
    """Same, through the bilinear-resize branch (cell 12:48-52 semantics)."""
    from mpi_vision_tpu.torchref import loss as torch_loss_lib

    torch.manual_seed(1)
    features = tvgg.build_features()
    vgg_params = jvgg.params_from_torch_state(features.state_dict())
    batch = _batch(rng)
    mpi_pred = rng.uniform(-1, 1, (1, 32, 32, 11)).astype(np.float32)

    jax_loss = float(tloss.vgg_perceptual_loss(
        jnp.asarray(mpi_pred), batch, vgg_params, resize=24))
    tbatch = {k: torch.as_tensor(np.asarray(v)) for k, v in batch.items()}
    torch_val = float(torch_loss_lib.vgg_perceptual_loss(
        torch.from_numpy(mpi_pred).permute(0, 3, 1, 2), tbatch, features,
        resize=24))
    assert abs(jax_loss - torch_val) <= 1e-4, (jax_loss, torch_val)


class TestLosses:

  def test_l2_loss_zero_when_render_matches_target(self, rng):
    batch = _batch(rng)
    p = 4
    # An MPI prediction whose render IS the reference image: identity pose,
    # fully-opaque planes, blend weight 1 -> every plane == ref image.
    batch["tgt_img_cfw"] = jnp.asarray(np.eye(4, dtype=np.float32)[None])
    mpi_pred = jnp.concatenate([
        jnp.ones((1, 32, 32, p)),          # blend -> 1 (tanh space)
        jnp.ones((1, 32, 32, p)),          # alpha -> 1
        jnp.zeros((1, 32, 32, 3)),
    ], axis=-1)
    batch["tgt_img"] = batch["ref_img"]
    # EXACT convention: identity pose == identity resampling. (The reference
    # REF_HOMOGRAPHY convention slightly resamples even at identity — its
    # dim-1 normalization quirk — so it is not exactly zero here.)
    from mpi_vision_tpu.core.sampling import Convention
    loss = tloss.l2_render_loss(mpi_pred, batch, convention=Convention.EXACT)
    assert float(loss) < 1e-10

  def test_vgg_loss_positive_and_finite(self, rng):
    batch = _batch(rng)
    mpi_pred = jnp.asarray(
        rng.uniform(-1, 1, (1, 32, 32, 11)).astype(np.float32))
    params = jvgg.init_params(0)
    loss = tloss.vgg_perceptual_loss(mpi_pred, batch, params, resize=None)
    assert np.isfinite(float(loss)) and float(loss) > 0

  def test_batched_mpi_planes_uses_row_zero(self, rng):
    """Collated [B, P] mpi_planes must behave like the reference's [0]."""
    batch = _batch(rng)
    mpi_pred = jnp.asarray(
        rng.uniform(-1, 1, (1, 32, 32, 11)).astype(np.float32))
    l_unbatched = tloss.l2_render_loss(mpi_pred, batch)
    batch["mpi_planes"] = jnp.stack([batch["mpi_planes"]])
    l_batched = tloss.l2_render_loss(mpi_pred, batch)
    np.testing.assert_allclose(float(l_unbatched), float(l_batched))

  def test_vgg_loss_resize_path(self, rng):
    batch = _batch(rng)
    mpi_pred = jnp.asarray(
        rng.uniform(-1, 1, (1, 32, 32, 11)).astype(np.float32))
    params = jvgg.init_params(0)
    loss = tloss.vgg_perceptual_loss(mpi_pred, batch, params, resize=64)
    assert np.isfinite(float(loss))


class TestTrainLoop:

  def test_train_step_reduces_l2_loss(self, rng):
    state = tloop.create_train_state(
        jax.random.PRNGKey(0), num_planes=4, image_size=(32, 32),
        learning_rate=1e-3, norm=None)
    step = tloop.make_train_step(vgg_params=None)
    batch = _batch(rng)
    losses = []
    for _ in range(8):
      state, metrics = step(state, batch)
      losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses

  def test_lr_find_sweeps_and_suggests(self, rng):
    state = tloop.create_train_state(
        jax.random.PRNGKey(0), num_planes=4, image_size=(32, 32),
        learning_rate=1e-3, norm=None)
    found = tloop.lr_find(state, [_batch(rng)], num_steps=40,
                          lr_start=1e-6, lr_end=10.0)
    assert len(found["lrs"]) == len(found["losses"]) == len(found["smoothed"])
    assert len(found["lrs"]) >= 2
    # Geometric schedule, monotone increasing lrs within [start, end].
    lrs = np.asarray(found["lrs"])
    assert np.all(np.diff(lrs) > 0) and lrs[0] >= 1e-6 and lrs[-1] <= 10.0
    # The suggestion is one of the swept lrs, away from the divergent tail.
    assert found["suggestion"] in found["lrs"]
    assert found["suggestion"] < lrs[-1]
    # The sweep must not mutate the input state.
    assert int(state.step) == 0

  def test_checkpoint_roundtrip(self, rng, tmp_path):
    state = tloop.create_train_state(
        jax.random.PRNGKey(0), num_planes=4, image_size=(32, 32), norm=None)
    step = tloop.make_train_step(vgg_params=None)
    state, _ = step(state, _batch(rng))
    path = str(tmp_path / "ckpt")
    tloop.save_checkpoint(path, state)

    fresh = tloop.create_train_state(
        jax.random.PRNGKey(1), num_planes=4, image_size=(32, 32), norm=None)
    restored = tloop.restore_checkpoint(path, fresh)
    assert int(restored.step) == 1
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        state.params, restored.params)

  def test_sharded_step_matches_single_device(self, rng):
    m = pmesh.make_mesh()
    state = tloop.create_train_state(
        jax.random.PRNGKey(0), num_planes=4, image_size=(32, 32), norm=None)
    batch = _batch(rng, b=8)

    single = tloop.make_train_step(vgg_params=None)
    s1, m1 = single(state, batch)

    sharded = tloop.shard_train_step(m, vgg_params=None)
    s2, m2 = sharded(pmesh.replicate(state, m), pmesh.shard_batch(batch, m))
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), atol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5),
        s1.params, s2.params)


def _batch_pose(rng, pose, b=1, hw=32, p=4):
  """_batch with an explicit target pose."""
  batch = _batch(rng, b=b, hw=hw, p=p)
  batch["tgt_img_cfw"] = jnp.asarray(np.stack([pose] * b))
  return batch


def _rot_pose(ry=0.006, tx=0.03):
  pose = np.eye(4, dtype=np.float32)
  c, s = np.cos(ry), np.sin(ry)
  pose[:3, :3] = np.array([[c, 0, s], [0, 1, 0], [-s, 0, c]], np.float32)
  pose[0, 3] = tx
  return pose


class TestPlannedTrainStep:
  """make_train_step_planned: fused Pallas render in the loss, forward and
  backward, planned per batch on the host."""

  def test_gradients_match_xla_loss(self, rng):
    """The planned loss's gradients match the XLA 'fused' loss's."""
    state = tloop.create_train_state(
        jax.random.PRNGKey(0), num_planes=4, image_size=(32, 32), norm=None)
    for pose in (np.eye(4, dtype=np.float32), _rot_pose()):
      pose = pose.copy()
      pose[0, 3] = 0.04
      batch = _batch_pose(rng, pose)
      bundle = tloop.plan_batch_render(batch)
      assert bundle is not None
      rk = dict(separable=bundle["separable"], check=False,
                plan=bundle["plan"], adj_plan=bundle["adj_plan"])
      loss_planned = tloop.make_loss_fn(None, method="fused_pallas",
                                        render_kwargs=rk)
      loss_xla = tloop.make_loss_fn(None)
      gp = jax.grad(loss_planned)(state.params, state.apply_fn, batch)
      gx = jax.grad(loss_xla)(state.params, state.apply_fn, batch)
      jax.tree.map(
          lambda a, b: np.testing.assert_allclose(
              np.asarray(a), np.asarray(b), atol=2e-3), gp, gx)

  def test_planned_step_trains_and_caches_one_signature(self, rng):
    state = tloop.create_train_state(
        jax.random.PRNGKey(0), num_planes=4, image_size=(32, 32),
        learning_rate=1e-3, norm=None)
    step = tloop.make_train_step_planned(vgg_params=None)
    batch = _batch(rng)
    losses = []
    for _ in range(6):
      state, metrics = step(state, batch)
      losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
    assert len(step.cache) == 1
    (key,) = step.cache
    assert key != "xla"

  def test_rotation_batch_uses_general_plan(self, rng):
    state = tloop.create_train_state(
        jax.random.PRNGKey(0), num_planes=4, image_size=(32, 32), norm=None)
    step = tloop.make_train_step_planned(vgg_params=None)
    state, metrics = step(state, _batch_pose(rng, _rot_pose()))
    assert np.isfinite(float(metrics["loss"]))
    (key,) = step.cache
    assert key != "xla" and key[0] is False  # general (non-separable) plan
    assert key[2] is not None                # Pallas backward engaged

  def test_large_rotation_batch_uses_banded_tier(self, rng):
    """A pose past the shared envelope trains through the banded Pallas
    forward (plan tagged 'banded') with the XLA backward (adj_plan None)."""
    state = tloop.create_train_state(
        jax.random.PRNGKey(0), num_planes=4, image_size=(32, 32), norm=None)
    step = tloop.make_train_step_planned(vgg_params=None)
    roll = np.eye(4, dtype=np.float32)
    c, s = np.cos(0.35), np.sin(0.35)            # ~20 degrees in-plane
    roll[:3, :3] = np.array([[c, -s, 0], [s, c, 0], [0, 0, 1]], np.float32)
    roll[0, 3] = 0.03
    state, metrics = step(state, _batch_pose(rng, roll))
    assert np.isfinite(float(metrics["loss"]))
    (key,) = step.cache
    assert key != "xla" and key[0] is False
    assert key[1][0] == "banded"
    assert key[2] is None                        # XLA backward (middle tier)

  def test_out_of_envelope_batch_falls_back_to_xla(self, rng):
    state = tloop.create_train_state(
        jax.random.PRNGKey(0), num_planes=4, image_size=(32, 32), norm=None)
    step = tloop.make_train_step_planned(vgg_params=None)
    wild = _rot_pose(ry=0.8)  # ~46 degrees: far outside the envelope
    state, metrics = step(state, _batch_pose(rng, wild))
    assert np.isfinite(float(metrics["loss"]))
    assert "xla" in step.cache


class TestShardedPlannedTrainStep:
  """shard_train_step_planned: fused Pallas loss per shard under shard_map."""

  def test_matches_single_device_planned(self, rng):
    m = pmesh.make_mesh()
    state = tloop.create_train_state(
        jax.random.PRNGKey(0), num_planes=4, image_size=(32, 32), norm=None)
    batch = _batch_pose(rng, _rot_pose(), b=8)

    single = tloop.make_train_step_planned(vgg_params=None)
    s1, m1 = single(state, batch)

    sharded = tloop.shard_train_step_planned(m, vgg_params=None)
    s2, m2 = sharded(pmesh.replicate(state, m), pmesh.shard_batch(batch, m))
    (key,) = sharded.cache
    assert key != "xla" and key[0] is False and key[2] is not None
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), atol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4),
        s1.params, s2.params)

  def test_out_of_envelope_falls_back_to_sharded_xla(self, rng):
    m = pmesh.make_mesh()
    state = tloop.create_train_state(
        jax.random.PRNGKey(0), num_planes=4, image_size=(32, 32), norm=None)
    batch = _batch_pose(rng, _rot_pose(ry=0.8), b=8)
    sharded = tloop.shard_train_step_planned(m, vgg_params=None)
    _, metrics = sharded(pmesh.replicate(state, m),
                         pmesh.shard_batch(batch, m))
    assert np.isfinite(float(metrics["loss"]))
    assert "xla" in sharded.cache

  def test_rejects_indivisible_batch(self, rng):
    m = pmesh.make_mesh()
    if m.shape["data"] == 1:
      pytest.skip("every batch divides a 1-device mesh")
    state = tloop.create_train_state(
        jax.random.PRNGKey(0), num_planes=4, image_size=(32, 32), norm=None)
    with pytest.raises(ValueError, match="not divisible"):
      tloop.shard_train_step_planned(m, vgg_params=None)(
          state, _batch(rng, b=3))


def test_vgg_bf16_loss_tracks_f32(rng):
  """vgg_dtype=bf16 perceptual loss stays close to the f32 loss."""
  from mpi_vision_tpu.train import loss as loss_lib
  from mpi_vision_tpu.train import vgg

  batch = _batch(rng, hw=32)
  params = vgg.init_params(0)
  state = tloop.create_train_state(
      jax.random.PRNGKey(0), num_planes=4, image_size=(32, 32), norm=None)
  pred = state.apply_fn({"params": state.params}, batch["net_input"])
  l32 = float(loss_lib.vgg_perceptual_loss(pred, batch, params, resize=None))
  lbf = float(loss_lib.vgg_perceptual_loss(pred, batch, params, resize=None,
                                           vgg_dtype=jnp.bfloat16))
  assert np.isfinite(lbf)
  assert abs(l32 - lbf) / max(abs(l32), 1e-6) < 0.05, (l32, lbf)
