"""Mesh-parallel rendering/compositing tests on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_vision_tpu.core import compose, render
from mpi_vision_tpu.core.camera import inv_depths
from mpi_vision_tpu.parallel import mesh as pmesh


def _pose(tx):
  pose = np.eye(4, dtype=np.float32)
  pose[0, 3] = tx
  return pose


@pytest.fixture
def scene(rng):
  h, w, p = 32, 32, 8
  mpi = jnp.asarray(rng.uniform(0, 1, (h, w, p, 4)).astype(np.float32))
  depths = inv_depths(1.0, 100.0, p)
  k = jnp.asarray(
      np.array([[0.5 * w, 0, w / 2], [0, 0.5 * w, h / 2], [0, 0, 1]],
               np.float32))
  return mpi, depths, k


def test_make_mesh_shapes():
  m = pmesh.make_mesh()
  assert m.shape["data"] == len(jax.devices())
  m2 = pmesh.make_mesh(("data", "planes"), shape=(2, 4))
  assert m2.shape == {"data": 2, "planes": 4}


def test_render_views_sharded_matches_single_device(rng, scene):
  mpi, depths, k = scene
  m = pmesh.make_mesh()
  poses = jnp.asarray(
      np.stack([_pose(0.01 * i) for i in range(16)]))
  got = pmesh.render_views_sharded(mpi, poses, depths, k, m)
  b = poses.shape[0]
  want = render.render_mpi(
      jnp.broadcast_to(mpi[None], (b,) + mpi.shape), poses, depths,
      jnp.broadcast_to(k[None], (b, 3, 3)))
  np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_render_views_sharded_rejects_indivisible(scene):
  mpi, depths, k = scene
  m = pmesh.make_mesh()
  poses = jnp.asarray(np.stack([_pose(0.01)] * 3))
  with pytest.raises(ValueError, match="not divisible"):
    pmesh.render_views_sharded(mpi, poses, depths, k, m)


@pytest.mark.parametrize("batch_dims", [(), (2,)])
def test_plane_sharded_composite_matches_scan(rng, batch_dims):
  p, h, w = 16, 16, 24
  rgba = jnp.asarray(
      rng.uniform(0, 1, (p,) + batch_dims + (h, w, 4)).astype(np.float32))
  m = pmesh.make_mesh(("planes",))
  got = pmesh.over_composite_planes_sharded(rgba, m)
  want = compose.over_composite(rgba)
  np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_plane_sharded_composite_single_opaque_plane(rng):
  """First (farthest) plane's alpha must be ignored regardless of sharding."""
  p, h, w = 8, 16, 24
  rgba = jnp.asarray(rng.uniform(0, 1, (p, h, w, 4)).astype(np.float32))
  rgba = rgba.at[1:, ..., 3].set(0.0)  # only the farthest plane visible
  m = pmesh.make_mesh(("planes",))
  got = pmesh.over_composite_planes_sharded(rgba, m)
  np.testing.assert_allclose(
      np.asarray(got), np.asarray(rgba[0, ..., :3]), atol=1e-6)


def test_sharded_render_under_jit(rng, scene):
  mpi, depths, k = scene
  m = pmesh.make_mesh()
  poses = jnp.asarray(np.stack([_pose(0.01 * i) for i in range(8)]))
  fn = jax.jit(lambda a, b: pmesh.render_views_sharded(a, b, depths, k, m))
  got = fn(mpi, poses)
  want = pmesh.render_views_sharded(mpi, poses, depths, k, m)
  np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


class TestViewsPlanesSharded:
  """2-D mesh: views DP-sharded x planes sequence-parallel-sharded."""

  def test_matches_single_device(self, rng, scene):
    mpi, depths, k = scene
    m = pmesh.make_mesh(("data", "planes"), shape=(2, 4))
    poses = jnp.asarray(np.stack([_pose(0.01 * i) for i in range(4)]))
    got = pmesh.render_views_planes_sharded(mpi, poses, depths, k, m)
    b = poses.shape[0]
    want = render.render_mpi(
        jnp.broadcast_to(mpi[None], (b,) + mpi.shape), poses, depths,
        jnp.broadcast_to(k[None], (b, 3, 3)))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

  def test_swapped_mesh_shape(self, rng, scene):
    mpi, depths, k = scene
    m = pmesh.make_mesh(("data", "planes"), shape=(4, 2))
    poses = jnp.asarray(np.stack([_pose(0.02 * i) for i in range(8)]))
    got = pmesh.render_views_planes_sharded(mpi, poses, depths, k, m)
    want = render.render_mpi(
        jnp.broadcast_to(mpi[None], (8,) + mpi.shape), poses, depths,
        jnp.broadcast_to(k[None], (8, 3, 3)))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

  def test_rejects_indivisible(self, scene):
    mpi, depths, k = scene
    m = pmesh.make_mesh(("data", "planes"), shape=(2, 4))
    with pytest.raises(ValueError, match="not divisible"):
      pmesh.render_views_planes_sharded(
          mpi, jnp.zeros((3, 4, 4)), depths, k, m)


class TestSharedFusedAutoPlan:
  """render_views_sharded(method='fused_pallas') plans concrete pose sets
  itself — no caller-side plan boilerplate."""

  def test_auto_planned_fused_matches_xla(self, rng, scene):
    mpi, depths, k = scene
    m = pmesh.make_mesh()
    # Mixed separable + small-pan poses: forces the general kernel plan.
    poses = []
    for i in range(8):
      pose = np.eye(4, dtype=np.float32)
      ang = np.radians(0.3) * np.sin(2 * np.pi * i / 8)
      c, s = np.cos(ang), np.sin(ang)
      pose[:3, :3] = [[c, 0, s], [0, 1, 0], [-s, 0, c]]
      pose[0, 3] = 0.02 * i
      poses.append(pose)
    poses = jnp.asarray(np.stack(poses))
    got = pmesh.render_views_sharded(mpi, poses, depths, k, m,
                                     method="fused_pallas")
    want = pmesh.render_views_sharded(mpi, poses, depths, k, m)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-3)

  def test_out_of_envelope_raises(self, rng, scene):
    mpi, depths, k = scene
    m = pmesh.make_mesh()
    # 90-degree YAW: the homography denominator changes sign over the
    # image, which every Pallas tier (shared, banded) rejects at any size.
    # (A 90-degree roll no longer works here: at this tiny image the
    # banded middle tier legitimately covers it — the whole source fits
    # one band.)
    wild = np.eye(4, dtype=np.float32)
    wild[:3, :3] = np.array([[0, 0, 1], [0, 1, 0], [-1, 0, 0]], np.float32)
    poses = jnp.asarray(np.stack([wild] * 8))
    with pytest.raises(ValueError, match="outside the fused-kernel"):
      pmesh.render_views_sharded(mpi, poses, depths, k, m,
                                 method="fused_pallas")
