"""Reference-API tail: compat shim, pixel-shuffle ops, multi-source input,
public IO helpers (VERDICT r2 item 8 — rows 11/15/16/36)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from mpi_vision_tpu import compat
from mpi_vision_tpu.core import camera, sweep
from mpi_vision_tpu.core.camera import inv_depths
from mpi_vision_tpu.data import realestate
from mpi_vision_tpu.torchref import oracle


class TestSpaceToDepth:

  def test_roundtrip_identity(self, rng):
    x = rng.uniform(size=(2, 8, 12, 3)).astype(np.float32)
    y = camera.space_to_depth(jnp.asarray(x), 2)
    assert y.shape == (2, 4, 6, 12)
    back = camera.depth_to_space(y, 2)
    np.testing.assert_array_equal(np.asarray(back), x)

  def test_matches_torch_unfold_reference(self, rng):
    """The reference SpaceToDepth is F.unfold-based (utils.py:803-817):
    channel-major (c*b*b + dy*b + dx) output ordering."""
    import torch.nn.functional as F

    b = 2
    x = rng.uniform(size=(1, 4, 6, 3)).astype(np.float32)
    nchw = torch.from_numpy(x).permute(0, 3, 1, 2)
    want = F.unfold(nchw, b, stride=b).reshape(
        1, 3 * b * b, 4 // b, 6 // b)
    got = camera.space_to_depth(jnp.asarray(x), b)      # NHWC
    np.testing.assert_allclose(
        np.moveaxis(np.asarray(got), -1, 1), want.numpy(), atol=0)

  def test_depth_to_space_matches_pixel_shuffle(self, rng):
    """DepthToSpace == torch.nn.PixelShuffle (utils.py:820)."""
    b = 2
    x = rng.uniform(size=(1, 3, 4, 5 * b * b)).astype(np.float32)
    want = torch.nn.PixelShuffle(b)(
        torch.from_numpy(x).permute(0, 3, 1, 2))
    got = camera.depth_to_space(jnp.asarray(x), b)
    np.testing.assert_allclose(
        np.moveaxis(np.asarray(got), -1, 1), want.numpy(), atol=0)

  def test_compat_modules_nchw(self, rng):
    x = rng.uniform(size=(1, 3, 4, 8)).astype(np.float32)  # NCHW
    s2d = compat.SpaceToDepth(2)
    d2s = compat.DepthToSpace(2)
    y = s2d(jnp.asarray(x))
    assert y.shape == (1, 12, 2, 4)
    np.testing.assert_array_equal(np.asarray(d2s(y)), x)
    # torch tensors in -> torch tensors out, same values
    yt = s2d(torch.from_numpy(x))
    np.testing.assert_array_equal(yt.numpy(), np.asarray(y))


class TestFormatNetworkInput:

  def test_matches_oracle_multi_source(self, rng):
    n, b, hw, p = 2, 1, 24, 3
    ref = rng.uniform(-1, 1, (b, hw, hw, 3)).astype(np.float32)
    srcs = rng.uniform(-1, 1, (n, b, hw, hw, 3)).astype(np.float32)
    ref_pose = np.eye(4, dtype=np.float32)[None].repeat(b, 0)
    src_poses = np.stack([np.eye(4, dtype=np.float32)[None].repeat(b, 0)
                          for _ in range(n)])
    src_poses[0, :, 0, 3] = 0.05
    src_poses[1, :, 1, 3] = -0.04
    planes = np.asarray(inv_depths(1.0, 100.0, p), np.float32)
    k = np.array([[hw / 2, 0, hw / 2], [0, hw / 2, hw / 2], [0, 0, 1]],
                 np.float32)[None].repeat(b, 0)

    got = sweep.format_network_input(
        jnp.asarray(ref), jnp.asarray(srcs), jnp.asarray(ref_pose),
        jnp.asarray(src_poses), jnp.asarray(planes), jnp.asarray(k))
    assert got.shape == (b, hw, hw, 3 + 3 * p * n)

    vols = [torch.from_numpy(ref)]
    for i in range(n):
      rel = torch.from_numpy(src_poses[i]) @ torch.inverse(
          torch.from_numpy(ref_pose))
      vols.append(oracle.plane_sweep(
          torch.from_numpy(srcs[i]), torch.from_numpy(planes), rel,
          torch.from_numpy(k)))
    want = torch.cat(vols, dim=-1).numpy()
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-3, rtol=0)

  def test_compat_shim_both_backends_agree(self, rng):
    n, b, hw, p = 2, 1, 24, 3
    ref = rng.uniform(-1, 1, (b, hw, hw, 3)).astype(np.float32)
    srcs = rng.uniform(-1, 1, (n, b, hw, hw, 3)).astype(np.float32)
    ref_pose = np.eye(4, dtype=np.float32)[None]
    src_poses = np.stack([np.eye(4, dtype=np.float32)[None]] * n)
    src_poses[0, :, 0, 3] = 0.06
    planes = np.asarray(inv_depths(1.0, 100.0, p), np.float32)
    k = np.array([[hw / 2, 0, hw / 2], [0, hw / 2, hw / 2], [0, 0, 1]],
                 np.float32)[None]
    got_j = compat.format_network_input_torch(
        ref, srcs, ref_pose, src_poses, planes, k)
    got_t = compat.format_network_input_torch(
        torch.from_numpy(ref), torch.from_numpy(srcs),
        torch.from_numpy(ref_pose), torch.from_numpy(src_poses),
        torch.from_numpy(planes), torch.from_numpy(k), backend="torch")
    np.testing.assert_allclose(
        np.asarray(got_j), got_t.numpy(), atol=1e-3, rtol=0)


class TestCompatShim:

  def _mpi_args(self, rng, b=1, hw=24, p=3):
    mpi = rng.uniform(0, 1, (b, hw, hw, p, 4)).astype(np.float32)
    pose = np.eye(4, dtype=np.float32)
    pose[0, 3] = 0.05
    planes = np.asarray(inv_depths(1.0, 100.0, p), np.float32)
    k = np.array([[hw / 2, 0, hw / 2], [0, hw / 2, hw / 2], [0, 0, 1]],
                 np.float32)
    return mpi, pose[None].repeat(b, 0), planes, k[None].repeat(b, 0)

  def test_mpi_render_view_backends_agree(self, rng):
    mpi, pose, planes, k = self._mpi_args(rng)
    got_j = compat.mpi_render_view_torch(mpi, pose, planes, k)
    got_t = compat.mpi_render_view_torch(
        torch.from_numpy(mpi), torch.from_numpy(pose),
        torch.from_numpy(planes), torch.from_numpy(k), backend="torch")
    np.testing.assert_allclose(
        np.asarray(got_j), got_t.numpy(), atol=1e-3, rtol=0)

  def test_plane_sweep_backends_agree(self, rng):
    img = rng.uniform(-1, 1, (1, 24, 24, 3)).astype(np.float32)
    pose = np.eye(4, dtype=np.float32)
    pose[0, 3] = 0.07
    planes = np.asarray(inv_depths(1.0, 100.0, 4), np.float32)
    k = np.array([[12., 0, 12], [0, 12., 12], [0, 0, 1]], np.float32)
    got_j = compat.plane_sweep_torch(img, planes, pose[None], k[None])
    got_t = compat.plane_sweep_torch(
        torch.from_numpy(img), torch.from_numpy(planes),
        torch.from_numpy(pose)[None], torch.from_numpy(k)[None],
        backend="torch")
    np.testing.assert_allclose(
        np.asarray(got_j), got_t.numpy(), atol=1e-3, rtol=0)

  def test_projective_forward_homography_backends_agree(self, rng):
    mpi, pose, planes, k = self._mpi_args(rng)
    stack = np.moveaxis(mpi, 3, 0)                    # [P, B, H, W, 4]
    got_j = compat.projective_forward_homography_torch(stack, k, pose, planes)
    got_t = compat.projective_forward_homography_torch(
        torch.from_numpy(stack), torch.from_numpy(k),
        torch.from_numpy(pose), torch.from_numpy(planes), backend="torch")
    np.testing.assert_allclose(
        np.asarray(got_j), got_t.numpy(), atol=1e-3, rtol=0)

  def test_over_composite_accepts_list(self, rng):
    planes = [rng.uniform(0, 1, (1, 8, 8, 4)).astype(np.float32)
              for _ in range(3)]
    got_j = compat.over_composite(planes)
    got_t = compat.over_composite(
        [torch.from_numpy(p) for p in planes], backend="torch")
    np.testing.assert_allclose(
        np.asarray(got_j), got_t.numpy(), atol=1e-5, rtol=0)

  def test_small_helpers_backends_agree(self, rng):
    d_j = np.asarray(compat.inv_depths(1.0, 100.0, 6))
    d_t = compat.inv_depths(1.0, 100.0, 6, backend="torch").numpy()
    np.testing.assert_allclose(d_j, d_t)
    k_j = np.asarray(compat.make_intrinsics_matrix(2.0, 3.0, 4.0, 5.0))
    k_t = compat.make_intrinsics_matrix(
        2.0, 3.0, 4.0, 5.0, backend="torch").numpy()
    np.testing.assert_allclose(k_j, k_t)
    x = rng.uniform(size=(2, 3)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(compat.preprocess_image_torch(x)), x * 2 - 1)

  def test_unknown_backend_raises(self):
    with pytest.raises(ValueError, match="backend"):
      compat.inv_depths(1.0, 100.0, 4, backend="tf")


class TestPublicIOHelpers:

  def test_open_image_and_resize_with_intrinsics(self, rng, tmp_path):
    from PIL import Image

    arr = (rng.uniform(size=(20, 30, 3)) * 255).astype(np.uint8)
    path = os.path.join(tmp_path, "img.png")
    Image.fromarray(arr).save(path)

    img = realestate.open_image(path)
    assert img.shape == (20, 30, 3) and img.max() <= 1.0
    img2 = realestate.open_image(path, size=(15, 10), scale=False)
    assert img2.shape == (10, 15, 3) and img2.max() > 1.0

    k = np.array([[30., 0, 15], [0, 20., 10], [0, 0, 1]], np.float32)
    image, k2 = realestate.resize_with_intrinsics(path, k, 10, 15)
    assert image.shape == (10, 15, 3)
    assert image.min() >= -1.0 and image.max() <= 1.0
    # fx scales by width ratio (15/30), fy by height ratio (10/20).
    np.testing.assert_allclose(k2[0, 0], 15.0)
    np.testing.assert_allclose(k2[1, 1], 10.0)


class TestCompatTail:
  """The remaining star-import names (utils.py:7-16, 41-101, 160-233,
  601-687, 725-799): jax and torch backends agree."""

  def test_fs_helpers(self, tmp_path):
    (tmp_path / "b").mkdir()
    (tmp_path / "a").mkdir()
    (tmp_path / "f.txt").write_text("x")
    assert compat.list_folders(tmp_path) == [str(tmp_path / "a"),
                                             str(tmp_path / "b")]
    assert compat.list_files(tmp_path) == [str(tmp_path / "f.txt")]
    assert compat.flatten([[1, 2], [3]]) == [1, 2, 3]

  def test_transpose_and_points_and_normalize(self, rng):
    pts = rng.normal(size=(2, 4, 4, 3)).astype(np.float32)
    hom = np.eye(3, dtype=np.float32) + 0.01 * rng.normal(
        size=(2, 3, 3)).astype(np.float32)
    tj = compat.transpose_torch(hom)
    tt = compat.transpose_torch(torch.from_numpy(hom), backend="torch")
    np.testing.assert_array_equal(np.asarray(tj), tt.numpy())
    pj = compat.transform_points_torch(pts, hom)
    pt = compat.transform_points_torch(torch.from_numpy(pts),
                                       torch.from_numpy(hom),
                                       backend="torch")
    np.testing.assert_allclose(np.asarray(pj), pt.numpy(), atol=1e-5)
    nj = compat.normalize_homogeneous_torch(pj)
    nt = compat.normalize_homogeneous_torch(pt, backend="torch")
    np.testing.assert_allclose(np.asarray(nj), nt.numpy(), atol=1e-5)

  def _plane_args(self, rng, h=16, w=16, b=1):
    imgs = rng.uniform(size=(b, h, w, 3)).astype(np.float32)
    grid = np.asarray(oracle.meshgrid_abs(b, h, w))        # [B, 3, H, W]
    pix = np.moveaxis(grid, 1, -1).astype(np.float32)      # [B, H, W, 3]
    k = np.array([[0.5 * w, 0, w / 2], [0, 0.5 * w, h / 2], [0, 0, 1]],
                 np.float32)[None].repeat(b, 0)
    rot = np.eye(3, dtype=np.float32)[None].repeat(b, 0)
    t = np.array([[0.05], [0.0], [-0.02]], np.float32)[None].repeat(b, 0)
    n_hat = np.array([[[0.0, 0.0, 1.0]]], np.float32).repeat(b, 0)
    a = np.array([[[-2.0]]], np.float32).repeat(b, 0)[..., None]
    return imgs, pix, k, rot, t, n_hat, a.reshape(b, 1, 1)

  def test_transform_plane_imgs_backends_agree(self, rng):
    imgs, pix, k, rot, t, n_hat, a = self._plane_args(rng)
    got_j = compat.transform_plane_imgs_torch(imgs, pix, k, k, rot, t,
                                              n_hat, a)
    got_t = compat.transform_plane_imgs_torch(
        *(torch.from_numpy(x) for x in (imgs, pix, k, k, rot, t, n_hat, a)),
        backend="torch")
    np.testing.assert_allclose(np.asarray(got_j), got_t.numpy(), atol=1e-4)

  def test_planar_transform_backends_agree(self, rng):
    imgs, pix, k, rot, t, n_hat, a = self._plane_args(rng)
    L = 3
    imgs_l = np.stack([imgs] * L)
    n_l = np.stack([n_hat] * L)
    a_l = np.stack([a * (i + 1) for i in range(L)])
    got_j = compat.planar_transform_torch(imgs_l, pix, k, k, rot, t, n_l,
                                          a_l)
    got_t = compat.planar_transform_torch(
        *(torch.from_numpy(x)
          for x in (imgs_l, pix, k, k, rot, t, n_l, a_l)),
        backend="torch")
    assert got_j.shape == (L, 1, 16, 16, 3)
    np.testing.assert_allclose(np.asarray(got_j), got_t.numpy(), atol=1e-4)

  def test_crop_backends_agree(self, rng):
    img = rng.uniform(size=(1, 12, 14, 3)).astype(np.float32)
    got_j = compat.crop_to_bounding_box_torch(img, 2, 3, 6, 8)
    got_t = compat.crop_to_bounding_box_torch(torch.from_numpy(img), 2, 3,
                                              6, 8, backend="torch")
    np.testing.assert_allclose(np.asarray(got_j), got_t.numpy(), atol=1e-5)
    k = np.array([[0.9, 0, 0.5], [0, 1.1, 0.5], [0, 0, 1]],
                 np.float32)[None]
    cj, kj = compat.crop_image_and_adjust_intrinsics_torch(img, k, 2, 3, 6,
                                                           8)
    ct, kt = compat.crop_image_and_adjust_intrinsics_torch(
        torch.from_numpy(img), torch.from_numpy(k), 2, 3, 6, 8,
        backend="torch")
    np.testing.assert_allclose(np.asarray(cj), ct.numpy(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(kj), kt.numpy(), atol=1e-5)

  def test_projective_pixel_transform_backends_agree(self, rng):
    b, h, w = 1, 10, 12
    depth = np.full((b, h, w), 2.5, np.float32)
    grid = np.asarray(oracle.meshgrid_abs(b, h, w)).astype(np.float32)
    k = np.array([[0.5 * w, 0, w / 2], [0, 0.5 * w, h / 2], [0, 0, 1]],
                 np.float32)[None]
    src_pose = np.eye(4, dtype=np.float32)[None]
    tgt_pose = np.eye(4, dtype=np.float32)[None]
    tgt_pose[:, 0, 3] = 0.1
    got_j = compat.projective_pixel_transform(depth, grid, src_pose,
                                              tgt_pose, k, k)
    got_t = compat.projective_pixel_transform(
        *(torch.from_numpy(x)
          for x in (depth, grid, src_pose, tgt_pose, k, k)),
        backend="torch")
    np.testing.assert_allclose(np.asarray(got_j), got_t.numpy(), atol=1e-4)

  def test_warp2_and_sweep_one2_backends_agree(self, rng):
    hs, ws, ht, wt = 12, 16, 10, 14
    img = rng.uniform(size=(1, hs, ws, 3)).astype(np.float32)
    depth = np.full((1, ht, wt), 3.0, np.float32)
    pose = np.eye(4, dtype=np.float32)[None]
    pose[:, 0, 3] = 0.05
    ks = np.array([[0.5 * ws, 0, ws / 2], [0, 0.5 * ws, hs / 2],
                   [0, 0, 1]], np.float32)[None]
    kt = np.array([[0.5 * wt, 0, wt / 2], [0, 0.5 * wt, ht / 2],
                   [0, 0, 1]], np.float32)[None]
    got_j = compat.projective_inverse_warp_torch2(img, depth, pose, ks, kt,
                                                  ht, wt)
    got_t = compat.projective_inverse_warp_torch2(
        torch.from_numpy(img), torch.from_numpy(depth),
        torch.from_numpy(pose), torch.from_numpy(ks), torch.from_numpy(kt),
        ht, wt, backend="torch")
    assert got_j.shape == (1, ht, wt, 3)
    np.testing.assert_allclose(np.asarray(got_j), got_t.numpy(), atol=1e-4)

    # ret_flows: both backends must return RAW source-pixel (x, y) flows.
    wj, fj = compat.projective_inverse_warp_torch2(
        img, depth, pose, ks, kt, ht, wt, ret_flows=True)
    wt_, ft = compat.projective_inverse_warp_torch2(
        torch.from_numpy(img), torch.from_numpy(depth),
        torch.from_numpy(pose), torch.from_numpy(ks), torch.from_numpy(kt),
        ht, wt, ret_flows=True, backend="torch")
    np.testing.assert_allclose(np.asarray(wj), wt_.numpy(), atol=1e-4)
    np.testing.assert_allclose(np.asarray(fj), ft.numpy(), atol=1e-3)
    assert float(np.abs(np.asarray(fj)).max()) > 1.5  # raw pixels, not (0,1)

    planes = np.asarray(inv_depths(1.0, 20.0, 4))
    sj = compat.plane_sweep_torch_one2(img[0], planes, pose[0], ks[0],
                                       kt[0], ht, wt)
    st = compat.plane_sweep_torch_one2(
        torch.from_numpy(img[0]), torch.from_numpy(planes),
        torch.from_numpy(pose[0]), torch.from_numpy(ks[0]),
        torch.from_numpy(kt[0]), ht, wt, backend="torch")
    assert sj.shape == (1, ht, wt, 12)
    np.testing.assert_allclose(np.asarray(sj), st.numpy(), atol=1e-4)

  def test_surface_is_complete(self):
    """Every public name of the reference module exists on the shim."""
    names = [
        "list_folders", "list_files", "flatten", "meshgrid_abs_torch",
        "divide_safe_torch", "transpose_torch", "inv_homography_torch",
        "transform_points_torch", "normalize_homogeneous_torch",
        "bilinear_wrapper_torch", "over_composite",
        "transform_plane_imgs_torch", "planar_transform_torch",
        "projective_forward_homography_torch", "mpi_render_view_torch",
        "inv_depths", "open_image", "preprocess_image_torch",
        "deprocess_image_torch", "pixel2cam_torch", "cam2pixel_torch",
        "resampler_wrapper_torch", "projective_inverse_warp_torch",
        "plane_sweep_torch", "format_network_input_torch",
        "show_torch_image", "plane_sweep_torch_one", "scale_intrinsics",
        "resize_with_intrinsics_torch", "make_intrinsics_matrix",
        "read_file_lines", "crop_to_bounding_box_torch",
        "crop_image_and_adjust_intrinsics_torch",
        "projective_pixel_transform", "parse_camera_lines",
        "projective_inverse_warp_torch2", "plane_sweep_torch_one2",
        "SpaceToDepth", "DepthToSpace",
    ]
    missing = [n for n in names if not hasattr(compat, n)]
    assert not missing, missing
